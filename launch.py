#!/usr/bin/env python
"""Repo-root launcher shim: ``python launch.py --config=... [overrides]``."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from frl_distributed_ml_scaffold_tpu.launcher.launch import main

if __name__ == "__main__":
    raise SystemExit(main())
