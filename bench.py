#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline metric (BASELINE.md): ImageNet samples/sec/chip on ResNet-50
training (fwd+bwd+update, bf16 mixed precision, synthetic data so the loader
can't be the bottleneck). Falls back down the model ladder if a family isn't
built yet.

``vs_baseline``: BASELINE.json's ``published`` is empty (reference repo
absent — see BASELINE.md); the comparison constant below is the documented
*assumed* A100-DDP ResNet-50 figure (2500 samples/sec/chip, bf16) so the
ratio is meaningful the day real numbers surface. Target from the north
star: >= 0.9 * A100 -> vs_baseline >= 0.9.
"""

from __future__ import annotations

import json
import sys

# Assumed reference numbers (documented stand-ins; see module docstring).
ASSUMED_BASELINE = {
    "rn50_imagenet_samples_per_sec_per_chip": 2500.0,
    "mnist_mlp_samples_per_sec_per_chip": 100000.0,
}


def bench_config(name: str, overrides: list[str], *, steps: int, warmup: int):
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.utils.timing import StepTimer

    cfg = apply_overrides(get_config(name), overrides)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    # One device-resident batch, reused (global_batch returns sharded
    # jax.Arrays): the benchmark measures the chip (fwd+bwd+update), not the
    # host loader (BASELINE.md protocol).
    batch = trainer.pipeline.global_batch(0)
    # Windowed timing: sync on the loss once per window, steps inside a
    # window pipeline as in a real training loop (per-step syncs would
    # charge the host<->device round-trip latency to every step).
    # ``warmup`` counts windows (the first ones contain compile + ramp).
    window = 5
    n_windows = max(1, -(-steps // window))  # ceil; at least one measured
    timer = StepTimer(warmup=warmup)
    for _ in range(n_windows + warmup + 1):
        for _ in range(window):
            state, metrics = trainer.train_step(state, batch)
        timer.tick_window(metrics["loss"], window)
    perf = timer.summary(cfg.data.global_batch_size)
    if "samples_per_sec_per_chip" not in perf:
        raise RuntimeError(f"benchmark produced no timed windows: {perf}")
    return perf


def main() -> int:
    candidates = [
        (
            "rn50_imagenet_samples_per_sec_per_chip",
            "imagenet_rn50_ddp",
            # bs=512 is the measured single-chip throughput knee (256: 1905,
            # 512: 2025, 1024: 1842 samples/sec/chip on v5e).
            ["data.global_batch_size=512", "trainer.log_every=1000000"],
            20,
        ),
        (
            "mnist_mlp_samples_per_sec_per_chip",
            "mnist_mlp",
            ["data.global_batch_size=1024", "trainer.log_every=1000000"],
            50,
        ),
    ]
    last_err = None
    for metric, cfg_name, overrides, steps in candidates:
        try:
            perf = bench_config(cfg_name, overrides, steps=steps, warmup=3)
            value = perf["samples_per_sec_per_chip"]
            base = ASSUMED_BASELINE[metric]
            print(
                json.dumps(
                    {
                        "metric": metric,
                        "value": round(value, 2),
                        "unit": "samples/sec/chip",
                        "vs_baseline": round(value / base, 4),
                    }
                )
            )
            return 0
        except Exception as e:  # fall down the ladder, report at the end
            last_err = e
            continue
    print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0,
                      "error": str(last_err)}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
