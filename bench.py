#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline metric (BASELINE.md): ImageNet samples/sec/chip on ResNet-50
training (fwd+bwd+update, bf16 mixed precision, synthetic data so the loader
can't be the bottleneck). Falls back down the model ladder if a family isn't
built yet.

``vs_baseline``: BASELINE.json's ``published`` is empty (reference repo
absent — see BASELINE.md); the comparison constant below is the documented
*assumed* A100-DDP ResNet-50 figure (2500 samples/sec/chip, bf16) so the
ratio is meaningful the day real numbers surface. Target from the north
star: >= 0.9 * A100 -> vs_baseline >= 0.9.

Watchdog design (round-2, after BENCH_r01 rc=124): the experimental axon
TPU relay can hang in backend bring-up indefinitely. Every stage that can
touch a device runs in a BOUNDED subprocess:

  1. probe: ``jax.devices()`` under a hard timeout — if the relay is down
     we find out in ``PROBE_TIMEOUT_S``, not 25 silent minutes;
  2. each candidate benchmark: its own subprocess + timeout, result handed
     back as a ``RESULT {json}`` line.

Whatever happens — TPU up, TPU down, compile hang — the parent ALWAYS
prints exactly one final JSON line to stdout; progress/diagnostics go to
stderr, flushed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Assumed reference numbers (documented stand-ins; see module docstring).
ASSUMED_BASELINE = {
    "rn50_imagenet_samples_per_sec_per_chip": 2500.0,
    "mnist_mlp_samples_per_sec_per_chip": 100000.0,
}

# Dense bf16 peak FLOP/s per chip, by jax device_kind (for MFU). CPU and
# unknown chips report no MFU rather than a made-up one.
CHIP_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
}

PROBE_TIMEOUT_S = int(os.environ.get("FRL_BENCH_PROBE_TIMEOUT_S", "240"))
CANDIDATE_TIMEOUT_S = int(os.environ.get("FRL_BENCH_CANDIDATE_TIMEOUT_S", "720"))

#: Last successfully-captured headline result (committed evidence). Written
#: on every green headline run; re-emitted marked ``"stale": true`` when the
#: relay is down at bench time, so an outage degrades the record to "most
#: recent real measurement + its capture timestamp" instead of an error
#: object that carries no performance information at all.
#:
#: Env-overridable (FRL_BENCH_LAST_GOOD_PATH) so tests that drive main()'s
#: save path write a sandbox file instead of poisoning the committed
#: evidence cache with fixture values — which is exactly what happened
#: through round 5: every pytest run stamped value=123.0 into the repo
#: copy, so the tier-1 stale fallback could never fire with real data.
LAST_GOOD_PATH = os.environ.get("FRL_BENCH_LAST_GOOD_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_last_good.json"
)


def _save_last_good(result: dict) -> None:
    try:
        rec = dict(result)
        rec["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open(LAST_GOOD_PATH + ".tmp", "w") as fh:
            json.dump(rec, fh, indent=2)
        os.replace(LAST_GOOD_PATH + ".tmp", LAST_GOOD_PATH)
    except OSError as e:  # evidence cache is best-effort, never fatal
        _progress(f"could not save last-good record: {e}")


def _corroborated(rec: dict) -> bool:
    """A cached record may only be re-emitted as a stale measurement if
    the evidence trail actually contains it: the metric's config family
    must have a BENCH_TABLE.jsonl protocol row whose samples/sec agrees
    within 25%. A hand-edited or corrupted cache must degrade to the
    honest error object, not get republished wearing a 'measured' label.
    """
    # A corrupted cache/table must yield False, never a traceback — the
    # caller's contract is "exactly one final JSON line, whatever
    # happens", and the garbage inputs this guard exists for are exactly
    # the ones that make float()/dict access raise.
    try:
        metric = str(rec.get("metric", ""))
        value = float(rec["value"])
        config_by_metric = {
            "rn50_imagenet_samples_per_sec_per_chip": "imagenet_rn50_ddp",
            "mnist_mlp_samples_per_sec_per_chip": "mnist_mlp",
        }
        config = config_by_metric.get(metric)
        if config is None:
            return False
        for row in _table_rows(config):
            measured = float(row["samples_per_sec_per_chip"])
            # Generous band: the table (rewritten only by a fully
            # green --all) can legitimately lag the headline by a
            # round's optimization jump (+38% happened in round 4) —
            # the guard exists to catch FABRICATIONS (123 vs 289688,
            # three orders of magnitude), not real progress.
            if measured > 0 and 0.4 * measured <= value <= 2.5 * measured:
                return True
        return False
    except Exception:
        return False


def _table_rows(config: str):
    """BENCH_TABLE.jsonl rows for one config, per-line tolerant (one
    malformed row must not poison the rest), chronological order. The
    single implementation both the corroboration guard and the table
    fallback iterate."""
    table = os.path.join(os.path.dirname(LAST_GOOD_PATH), "BENCH_TABLE.jsonl")
    try:
        with open(table) as fh:
            lines = fh.readlines()
    except OSError:
        return
    for line in lines:
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if (
            isinstance(row, dict)
            and row.get("config") == config
            and "samples_per_sec_per_chip" in row
        ):
            yield row


def _row_captured_at(row: dict) -> str | None:
    """Capture-time provenance of a protocol row, best evidence first:
    the explicit ``captured_at`` stamp (written by protocol_record since
    round 6), else a date parsed out of the row's ``source``/``note``
    free text (the round-4/5 rows record e.g. "captured 2026-07-30
    ~21:26 UTC" there). None only for rows with no provenance at all —
    the case the tier-2 "unknown time" log line is reserved for."""
    ts = row.get("captured_at")
    if isinstance(ts, str) and ts:
        return ts
    import re

    text = f"{row.get('source', '')} {row.get('note', '')}"
    m = re.search(r"(\d{4}-\d{2}-\d{2})(?:\s*~?(\d{1,2}):(\d{2}))?", text)
    if m is None:
        return None
    if m.group(2):
        return f"{m.group(1)}T{int(m.group(2)):02d}:{m.group(3)}:00Z"
    return f"{m.group(1)}T00:00:00Z"


def _table_fallback_record() -> dict | None:
    """Second-tier stale source: reconstruct the headline record from
    BENCH_TABLE.jsonl's own protocol row (committed evidence, written
    only by a fully green ``--all``). Used when the last-good cache is
    absent or fails corroboration — the protocol table cannot be beaten
    for trustworthiness by a single-value cache file."""
    try:
        # LAST matching row: the table accumulates rows per config over
        # rounds in chronological order, and the fallback's contract is
        # "most recent real measurement".
        row = None
        for row in _table_rows("imagenet_rn50_ddp"):
            pass
        if row is None:
            return None
        value = float(row["samples_per_sec_per_chip"])
        metric = "rn50_imagenet_samples_per_sec_per_chip"
        rec = {
            "metric": metric,
            "value": round(value, 2),
            "unit": "samples/sec/chip",
            "vs_baseline": round(value / ASSUMED_BASELINE[metric], 4),
            "source": "BENCH_TABLE.jsonl protocol row "
                      f"(chip={row.get('chip', '?')})",
        }
        if "mfu" in row:
            rec["mfu"] = row["mfu"]
        ts = _row_captured_at(row)
        if ts:
            rec["captured_at"] = ts
        return rec
    except Exception:
        return None


def _emit_stale_or_error(error: str) -> int:
    """Final-line fallback: most recent real measurement marked stale, or —
    only if none was ever captured — the bare error object.

    Always returns rc=1: the benchmark did NOT run, and anything keying on
    the exit code must see that. The final line still carries the last real
    numbers (with ``stale``/``stale_reason``/``captured_at``) so the record
    of a relay outage is "most recent measurement + when + why stale"
    rather than an error object with no performance information.
    """
    try:
        with open(LAST_GOOD_PATH) as fh:
            rec = json.load(fh)
    except (OSError, ValueError):
        rec = None
    if rec and "value" in rec and not _corroborated(rec):
        _progress(
            "last-good record is NOT corroborated by BENCH_TABLE.jsonl "
            "(hand-edited or corrupted cache?); falling back to the "
            "protocol table's own row"
        )
        rec = None
    if rec is None or "value" not in rec:
        rec = _table_fallback_record()
    if rec and "value" in rec:
        rec["stale"] = True
        # The typed status stamp (the BENCH_TABLE vocabulary: "queued"
        # placeholders, "stale" re-emissions): anything consuming the
        # final line — or a table this record gets appended to — can
        # filter on status without parsing the boolean + reason pair,
        # and the schema test refuses a stale row wearing a fresh face
        # (no captured_at) or a measured one wearing "stale".
        rec["status"] = "stale"
        rec["stale_reason"] = error[:300]
        _progress(
            f"relay down ({error[:120]}); re-emitting last good capture "
            f"from {rec.get('captured_at', 'unknown time')}"
        )
        print(json.dumps(rec), flush=True)
    else:
        print(json.dumps({"metric": "error", "value": 0, "unit": "",
                          "vs_baseline": 0, "error": error[:500]}),
              flush=True)
    return 1


def _progress(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def bench_config(name: str, overrides: list[str], *, steps: int, warmup: int):
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.launcher.launch import enable_compile_cache
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.utils.timing import StepTimer

    # Repeat bench runs of the same config hit the persistent compile cache
    # instead of paying the 20-40s TPU compile inside the watchdog budget.
    enable_compile_cache()

    # prefetch=0: the benchmark reuses one device-resident batch; background
    # prefetch would only add host/device contention inside timed windows.
    cfg = apply_overrides(get_config(name), ["data.prefetch=0"] + overrides)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    # One device-resident batch, reused (global_batch returns sharded
    # jax.Arrays): the benchmark measures the chip (fwd+bwd+update), not the
    # host loader (BASELINE.md protocol).
    batch = trainer.pipeline.global_batch(0)
    # FLOPs of one compiled step, from XLA's own cost model (counts every op
    # the step actually runs: fwd+bwd+optimizer, all grad-accum microbatches).
    cost = trainer.step_cost_analysis(state, batch)
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0
    # Windowed timing: sync on the loss once per window, steps inside a
    # window pipeline as in a real training loop (per-step syncs would
    # charge the host<->device round-trip latency to every step).
    # ``warmup`` counts windows (the first ones contain compile + ramp).
    # 30 steps/window: the relay's sync RTT is ~20 ms, which a 5-step
    # window charged as ~4 ms/step (-10% on RN50) and a 20-step window as
    # ~1 ms/step; real training loops sync once per log_every (100s of
    # steps), so 30 still over-charges relative to production.
    window = int(os.environ.get("FRL_BENCH_WINDOW", "30"))
    n_windows = max(1, -(-steps // window))  # ceil; at least one measured
    timer = StepTimer(warmup=warmup)
    for _ in range(n_windows + warmup + 1):
        for _ in range(window):
            state, metrics = trainer.train_step(state, batch)
        timer.tick_window(metrics["loss"], window)
    perf = timer.summary(cfg.data.global_batch_size)
    if "samples_per_sec_per_chip" not in perf:
        raise RuntimeError(f"benchmark produced no timed windows: {perf}")
    perf["_record"] = protocol_record(cfg, trainer, perf, step_flops=step_flops)
    # The protocol line must say exactly what ran — config name + the
    # non-default knobs (stem, remat, chunking, ...) that produced it.
    perf["_record"]["overrides"] = list(overrides)
    return perf


def protocol_record(cfg, trainer, perf, *, step_flops: float = 0.0) -> dict:
    """The BASELINE.md measurement-protocol record (one JSONL line/run)."""
    import jax

    n_chips = jax.device_count()
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev))
    rec = {
        "config": cfg.name,
        "model": getattr(cfg.model, "family", type(cfg.model).__name__),
        "global_batch_size": cfg.data.global_batch_size,
        "per_chip_batch_size": cfg.data.global_batch_size // n_chips,
        "mesh": dict(trainer.env.mesh.shape),
        "param_sharding": cfg.parallel.param_sharding,
        "precision": cfg.precision.policy,
        "grad_accum": cfg.trainer.grad_accum,
        "remat": cfg.trainer.remat,
        "n_chips": n_chips,
        "chip": kind,
        "steps_per_sec": round(perf["steps_per_sec"], 4),
        "samples_per_sec_per_chip": round(perf["samples_per_sec_per_chip"], 2),
        "step_time_median_s": round(perf["step_time_median_s"], 6),
        "step_time_p90_s": round(perf["step_time_p90_s"], 6),
        # Capture-time provenance travels WITH the row: the stale-fallback
        # tiers re-emit it so an outage record always says when its
        # numbers were real (satellite of the round-6 provenance fix).
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    from frl_distributed_ml_scaffold_tpu.utils.profiling import device_memory_stats

    rec.update(device_memory_stats())
    if step_flops > 0:
        rec["model_flops_per_sample"] = round(
            step_flops / cfg.data.global_batch_size
        )
        peak = CHIP_PEAK_FLOPS.get(kind)
        if peak:
            # MFU: achieved FLOP/s over peak, per chip (flops here is the
            # whole-step XLA count, so this is end-to-end training MFU).
            rec["mfu"] = round(
                step_flops * perf["steps_per_sec"] / (n_chips * peak), 4
            )
    return rec


# The five BASELINE configs, sized for one v5e chip (shrunk only where the
# full model cannot fit / compile on a single chip; recorded in overrides so
# the emitted protocol line says exactly what ran).
ALL_CONFIGS = [
    ("mnist_mlp", ["data.global_batch_size=1024"], 50),
    # Same operating point as the headline candidate below (s2d stem) so
    # regenerating the table reproduces the row BASELINE.md documents.
    ("imagenet_rn50_ddp",
     ["data.global_batch_size=512", "model.stem=s2d"], 20),
    # remat=none: config 3 prescribes activation checkpointing for fitting
    # FSDP shards at scale, but on one chip bs=256 fits without it and the
    # recompute is pure overhead (measured: 865.6 samples/sec/chip remat
    # none vs 616.7 full vs 778.6 dots, 2026-07-30). The protocol line
    # records the remat mode so the tradeoff stays visible.
    ("imagenet_vitb_fsdp",
     ["data.global_batch_size=256", "trainer.remat=none"], 20),
    (
        # Round-4 operating point: per-block remat (model.block_remat)
        # caps backward residency at one block's internals, unlocking
        # microbatch 8 — measured 33.6 samples/sec/chip vs 24.25 at the
        # old mb4/remat=dots knee (+39%, MFU 0.337 → 0.467). See
        # docs/perf_playbook.md "Per-block remat on the flagship" and
        # tools/perf_sweep.py gpt2_block_remat (mb16/32 measure the same
        # within noise; mb8 recompiles fastest).
        # lm_loss_chunk: chunked-vocab head+CE — skips the [B,T,50257]
        # logits materialization; measured +9% at microbatch 4 (19.78 vs
        # 18.15 samples/sec/chip) on top of the memory saved.
        "gpt2_medium_zero1",
        ["data.global_batch_size=8", "trainer.grad_accum=1",
         "model.attention=flash", "model.lm_loss_chunk=128",
         "trainer.remat=none", "model.block_remat=full"],
        10,
    ),
    (
        # The recorded optimizer decision (VERDICT r4 #1): adafactor beat
        # adamw +4.6% at mb4 remat=none on-chip (31.7 vs 30.3,
        # evidence_r4/perf_sweep2.log) with convergence within tolerance
        # (tools/opt_convergence.py); this row carries the variant at the
        # flagship operating point so regenerating the table keeps the
        # A/B visible next to gpt2_medium_zero1's adamw line.
        "gpt2_medium_adafactor",
        ["data.global_batch_size=8", "trainer.grad_accum=1",
         "model.attention=flash", "model.lm_loss_chunk=128",
         "trainer.remat=none", "model.block_remat=full"],
        10,
    ),
    (
        # On-chip MoE protocol line (SURVEY C9): single chip has no expert
        # axis to shard (mesh.expert=1 — EP itself is sim-verified), but
        # the grouped GSEC dispatch, capacity routing, z-loss, and the
        # stacked-expert FFN einsums all run at real shapes here.
        # 908M params: AdamW's fp32 mu/nu alone (10.9G) blow the 15.75G
        # chip (first on-chip attempt 2026-07-30 died in relay compile),
        # so the single-chip line runs Adafactor (factored second moment —
        # the standard MoE-scale choice) + per-block remat.
        "gpt2_moe",
        ["data.global_batch_size=8", "trainer.grad_accum=1",
         "model.attention=flash", "model.lm_loss_chunk=128",
         "mesh.expert=1", "optimizer.name=adafactor",
         "trainer.remat=none", "model.block_remat=full"],
        10,
    ),
    ("ego4d_video_elastic", ["data.global_batch_size=32",
                             "checkpoint.enabled=false"], 10),
]


def _ensure_bench_shards(dir_: str, n_shards: int = 4, per: int = 256,
                         size: int = 224) -> str:
    """Generate (once, then reuse) uint8 decoded-image shards at RN50/ViT
    shapes — the exact on-disk format tools/decode_imagenet.py produces.
    Contents are random: the loader bench measures gather+augment+feed
    throughput, which is content-independent."""
    import numpy as np

    os.makedirs(dir_, exist_ok=True)
    for s in range(n_shards):
        ip = os.path.join(dir_, f"train_images_{s:03d}.npy")
        lp = os.path.join(dir_, f"train_labels_{s:03d}.npy")
        if not (os.path.exists(ip) and os.path.exists(lp)):
            rng = np.random.default_rng(s)
            np.save(ip, rng.integers(
                0, 256, size=(per, size, size, 3), dtype=np.uint8))
            np.save(lp, rng.integers(0, 1000, size=per))
    return dir_


def run_real_data() -> int:
    """SURVEY §7 hard part 5: does samples/sec/chip measure the chip or the
    loader? Streams a FRESH batch through the full input tier every step —
    disk shards → memmap gather → native augment → device feed — and
    compares against the identical streaming loop on the synthetic source.
    One JSONL row per mode plus a verdict row. (The protocol benchmark
    deliberately reuses one device-resident batch; this mode exists to
    check that choice against reality.)

    Honesty note: on the axon relay, host→device feeding crosses the
    tunnel, which is NOT representative of production pod infeed
    bandwidth — the verdict row carries the feed path so the comparison
    reads as what it is.
    """
    _respect_platform_env()
    kind, probe_err = probe_backend()
    if probe_err is not None:
        print(json.dumps({"mode": "_probe", "error": probe_err}), flush=True)
        return 1
    import time as _time

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.launcher.launch import enable_compile_cache
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    enable_compile_cache()
    shard_dir = _ensure_bench_shards(
        os.environ.get("FRL_BENCH_DATA_DIR", "/tmp/frl_bench_shards")
    )
    bs, warm, steps = 256, 3, 12
    rows = {}
    for mode, extra in (
        ("synthetic_stream", []),
        ("real_stream", [f"data.data_dir={shard_dir}"]),
    ):
        cfg = apply_overrides(
            get_config("imagenet_rn50_ddp"),
            [f"data.global_batch_size={bs}", "model.stem=s2d",
             "trainer.log_every=1000000", "data.prefetch=2"] + extra,
        )
        trainer = Trainer(cfg)
        # prefetch>0 wraps the pipeline; the source lives on the inner one.
        inner = getattr(trainer.pipeline, "_p", trainer.pipeline)
        if mode == "real_stream" and inner.source.is_synthetic:
            raise RuntimeError("real-data shards not picked up")
        state = trainer.init_state()
        for step in range(warm):
            state, m = trainer.train_step(
                state, trainer.pipeline.global_batch(step)
            )
        import jax

        jax.device_get(m["loss"])
        t0 = _time.perf_counter()
        for step in range(warm, warm + steps):
            state, m = trainer.train_step(
                state, trainer.pipeline.global_batch(step)
            )
        jax.device_get(m["loss"])
        dt = (_time.perf_counter() - t0) / steps
        rows[mode] = bs / dt
        print(json.dumps({
            "mode": mode, "global_batch_size": bs,
            "step_time_ms": round(dt * 1e3, 2),
            "samples_per_sec_per_chip": round(bs / dt, 1),
        }), flush=True)
        del trainer, state, m, inner
        # Release the first mode's params/opt-state/executables (and the
        # pipeline's prefetch buffers held via `inner`) before the second
        # allocates (same settle tools/perf_sweep.py build() uses) —
        # two live Trainers can RESOURCE_EXHAUSTED an HBM-constrained chip.
        import gc

        gc.collect()
        jax.clear_caches()
        gc.collect()
    ratio = rows["real_stream"] / rows["synthetic_stream"]
    print(json.dumps({
        "mode": "verdict",
        "real_over_synthetic": round(ratio, 4),
        "loader_bound": bool(ratio < 0.9),
        "feed_path": "host->relay tunnel (not production infeed)",
    }), flush=True)
    return 0


def run_all(out_path: str = "BENCH_TABLE.jsonl") -> int:
    """Benchmark every BASELINE config; emit protocol JSONL + a table."""
    _respect_platform_env()
    kind, probe_err = probe_backend()
    if probe_err is not None:
        # Do NOT touch out_path: a dead relay must never clobber the
        # last good capture with a one-line error record.
        rec = {"config": "_probe", "error": probe_err,
               "note": f"existing {out_path} left untouched"}
        print(json.dumps(rec), flush=True)
        return 1
    rows = []
    # Stage into a temp file; the live table is replaced ALL-OR-NOTHING:
    # it is the evidence artifact, and a partial table would silently
    # drop the last good rows of whichever configs failed this run.
    # Every row (success or error) still streams to stdout regardless.
    tmp_path = out_path + ".tmp"
    try:
        with open(tmp_path, "w") as fh:
            for name, overrides, steps in ALL_CONFIGS:
                _progress(f"benchmarking {name} ...")
                try:
                    perf = bench_config(
                        name, overrides + ["trainer.log_every=1000000"],
                        steps=steps, warmup=2,
                    )
                    rec = perf["_record"]
                except Exception as e:  # record the failure, keep benching
                    rec = {"config": name, "error": str(e)[:300]}
                rows.append(rec)
                fh.write(json.dumps(rec) + "\n")
                fh.flush()
                print(json.dumps(rec))
        ok = [r for r in rows if "error" not in r]
        if len(ok) == len(rows):
            os.replace(tmp_path, out_path)
        else:
            _progress(
                f"{len(rows) - len(ok)} config(s) failed; existing "
                f"{out_path} left untouched"
            )
    finally:
        if os.path.exists(tmp_path):  # error/partial run or interrupt
            os.remove(tmp_path)
    print(f"\n{'config':28s} {'samples/s/chip':>14s} {'step_ms':>9s} {'mfu':>6s}  mesh")
    for r in ok:
        mfu = f"{r['mfu']:.3f}" if "mfu" in r else "-"
        print(
            f"{r['config']:28s} {r['samples_per_sec_per_chip']:14.1f} "
            f"{r['step_time_median_s']*1e3:9.2f} {mfu:>6s}  {r['mesh']}"
        )
    return 0 if len(ok) == len(rows) else 1


# Headline candidates, best first (the ladder the parent walks).
CANDIDATES = [
    (
        "rn50_imagenet_samples_per_sec_per_chip",
        "imagenet_rn50_ddp",
        # bs=512 is the measured single-chip throughput knee (256: 1905,
        # 512: 2025, 1024: 1842 samples/sec/chip on v5e). s2d stem: the
        # mathematically exact space-to-depth rewrite of the 7x7/s2 stem
        # (models/resnet.py), measured +1.5% over conv7.
        ["data.global_batch_size=512", "model.stem=s2d",
         "trainer.log_every=1000000"],
        90,  # 3 measured 30-step windows (median taken across windows)
    ),
    (
        "mnist_mlp_samples_per_sec_per_chip",
        "mnist_mlp",
        ["data.global_batch_size=1024", "trainer.log_every=1000000"],
        50,
    ),
]


def _candidate_result(metric: str, cfg_name: str, overrides: list[str],
                      steps: int) -> dict:
    perf = bench_config(cfg_name, overrides, steps=steps, warmup=3)
    value = perf["samples_per_sec_per_chip"]
    base = ASSUMED_BASELINE[metric]
    rec = perf["_record"]
    out = {
        "metric": metric,
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(value / base, 4),
    }
    if "mfu" in rec:
        out["mfu"] = rec["mfu"]
    return out


def _respect_platform_env() -> None:
    """Make the JAX_PLATFORMS env var authoritative again.

    The axon sitecustomize (on PYTHONPATH) pins jax_platforms at the
    jax.config level, which beats env vars — so a subprocess launched with
    JAX_PLATFORMS=cpu would still try TPU bring-up. Re-assert the env var
    at the config level before any backend initializes.
    """
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


def child_main(spec_json: str) -> int:
    """Run ONE candidate in this (sacrificial) process; emit RESULT line."""
    _respect_platform_env()
    spec = json.loads(spec_json)
    result = _candidate_result(
        spec["metric"], spec["config"], spec["overrides"], spec["steps"]
    )
    print("RESULT " + json.dumps(result), flush=True)
    return 0


def _run_bounded(argv: list[str], timeout_s: int) -> tuple[int | None, str, str]:
    """Run argv with a hard timeout; returns (rc, stdout, stderr).

    rc=None means timeout (distinct from any real exit/signal code). The
    child is killed (not just waited on) so a hung TPU bring-up can't
    outlive the budget.
    """
    try:
        r = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s
        )
        return r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        def _txt(x):
            return x.decode(errors="replace") if isinstance(x, bytes) else (x or "")

        return None, _txt(e.stdout), _txt(e.stderr)


def probe_backend() -> tuple[str | None, str | None]:
    """Bounded backend bring-up check. Returns (device_kind, error)."""
    code = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "d = jax.devices()\n"
        "print('PROBE_OK', len(d), '|', getattr(d[0], 'device_kind', str(d[0])))"
    )
    t0 = time.perf_counter()
    rc, out, err = _run_bounded([sys.executable, "-c", code], PROBE_TIMEOUT_S)
    dt = time.perf_counter() - t0
    if rc is None:
        return None, (
            f"backend init timeout after {PROBE_TIMEOUT_S}s "
            f"(platform={os.environ.get('JAX_PLATFORMS', 'default')})"
        )
    if rc != 0:
        return None, f"backend init failed rc={rc}: {err.strip()[-300:]}"
    for line in out.splitlines():
        if line.startswith("PROBE_OK"):
            _progress(f"backend up in {dt:.1f}s: {line}")
            return line.split("|", 1)[1].strip(), None
    return None, f"probe produced no PROBE_OK line: {out[-200:]!r}"


def main() -> int:
    if "--all" in sys.argv:
        return run_all()
    if "--real-data" in sys.argv:
        return run_real_data()
    if "--child" in sys.argv:
        return child_main(sys.argv[sys.argv.index("--child") + 1])

    _progress(
        f"start platform={os.environ.get('JAX_PLATFORMS', 'default')} "
        f"probe_timeout={PROBE_TIMEOUT_S}s candidate_timeout={CANDIDATE_TIMEOUT_S}s"
    )
    kind, probe_err = probe_backend()
    if probe_err is not None:
        return _emit_stale_or_error(probe_err)

    last_err: str = "no candidates ran"
    for metric, cfg_name, overrides, steps in CANDIDATES:
        spec = json.dumps({"metric": metric, "config": cfg_name,
                           "overrides": overrides, "steps": steps})
        _progress(f"candidate {cfg_name} ({metric}) ...")
        t0 = time.perf_counter()
        rc, out, err = _run_bounded(
            [sys.executable, os.path.abspath(__file__), "--child", spec],
            CANDIDATE_TIMEOUT_S,
        )
        dt = time.perf_counter() - t0
        if rc is None:
            last_err = f"{cfg_name}: timeout after {CANDIDATE_TIMEOUT_S}s"
            _progress(last_err)
            continue
        result = None
        for line in out.splitlines():
            if line.startswith("RESULT "):
                result = json.loads(line[len("RESULT "):])
        if rc == 0 and result is not None:
            _progress(f"candidate {cfg_name} done in {dt:.1f}s")
            _save_last_good(result)
            print(json.dumps(result), flush=True)
            return 0
        last_err = f"{cfg_name}: rc={rc}: {err.strip()[-300:]}"
        _progress(last_err)
    return _emit_stale_or_error(last_err)


if __name__ == "__main__":
    sys.exit(main())
