#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline metric (BASELINE.md): ImageNet samples/sec/chip on ResNet-50
training (fwd+bwd+update, bf16 mixed precision, synthetic data so the loader
can't be the bottleneck). Falls back down the model ladder if a family isn't
built yet.

``vs_baseline``: BASELINE.json's ``published`` is empty (reference repo
absent — see BASELINE.md); the comparison constant below is the documented
*assumed* A100-DDP ResNet-50 figure (2500 samples/sec/chip, bf16) so the
ratio is meaningful the day real numbers surface. Target from the north
star: >= 0.9 * A100 -> vs_baseline >= 0.9.
"""

from __future__ import annotations

import json
import sys

# Assumed reference numbers (documented stand-ins; see module docstring).
ASSUMED_BASELINE = {
    "rn50_imagenet_samples_per_sec_per_chip": 2500.0,
    "mnist_mlp_samples_per_sec_per_chip": 100000.0,
}


def bench_config(name: str, overrides: list[str], *, steps: int, warmup: int):
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.utils.timing import StepTimer

    # prefetch=0: the benchmark reuses one device-resident batch; background
    # prefetch would only add host/device contention inside timed windows.
    cfg = apply_overrides(get_config(name), ["data.prefetch=0"] + overrides)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    # One device-resident batch, reused (global_batch returns sharded
    # jax.Arrays): the benchmark measures the chip (fwd+bwd+update), not the
    # host loader (BASELINE.md protocol).
    batch = trainer.pipeline.global_batch(0)
    # Windowed timing: sync on the loss once per window, steps inside a
    # window pipeline as in a real training loop (per-step syncs would
    # charge the host<->device round-trip latency to every step).
    # ``warmup`` counts windows (the first ones contain compile + ramp).
    window = 5
    n_windows = max(1, -(-steps // window))  # ceil; at least one measured
    timer = StepTimer(warmup=warmup)
    for _ in range(n_windows + warmup + 1):
        for _ in range(window):
            state, metrics = trainer.train_step(state, batch)
        timer.tick_window(metrics["loss"], window)
    perf = timer.summary(cfg.data.global_batch_size)
    if "samples_per_sec_per_chip" not in perf:
        raise RuntimeError(f"benchmark produced no timed windows: {perf}")
    perf["_record"] = protocol_record(cfg, trainer, perf)
    return perf


def protocol_record(cfg, trainer, perf) -> dict:
    """The BASELINE.md measurement-protocol record (one JSONL line/run)."""
    import jax

    n_chips = jax.device_count()
    dev = jax.devices()[0]
    return {
        "config": cfg.name,
        "model": getattr(cfg.model, "family", type(cfg.model).__name__),
        "global_batch_size": cfg.data.global_batch_size,
        "per_chip_batch_size": cfg.data.global_batch_size // n_chips,
        "mesh": dict(trainer.env.mesh.shape),
        "param_sharding": cfg.parallel.param_sharding,
        "precision": cfg.precision.policy,
        "grad_accum": cfg.trainer.grad_accum,
        "remat": cfg.trainer.remat,
        "n_chips": n_chips,
        "chip": getattr(dev, "device_kind", str(dev)),
        "steps_per_sec": round(perf["steps_per_sec"], 4),
        "samples_per_sec_per_chip": round(perf["samples_per_sec_per_chip"], 2),
        "step_time_median_s": round(perf["step_time_median_s"], 6),
        "step_time_p90_s": round(perf["step_time_p90_s"], 6),
    }


# The five BASELINE configs, sized for one v5e chip (shrunk only where the
# full model cannot fit / compile on a single chip; recorded in overrides so
# the emitted protocol line says exactly what ran).
ALL_CONFIGS = [
    ("mnist_mlp", ["data.global_batch_size=1024"], 50),
    ("imagenet_rn50_ddp", ["data.global_batch_size=512"], 20),
    ("imagenet_vitb_fsdp", ["data.global_batch_size=256"], 20),
    (
        "gpt2_medium_zero1",
        ["data.global_batch_size=8", "trainer.grad_accum=1",
         "model.attention=flash"],
        10,
    ),
    ("ego4d_video_elastic", ["data.global_batch_size=32",
                             "checkpoint.enabled=false"], 10),
]


def run_all(out_path: str = "BENCH_TABLE.jsonl") -> int:
    """Benchmark every BASELINE config; emit protocol JSONL + a table."""
    rows = []
    with open(out_path, "w") as fh:
        for name, overrides, steps in ALL_CONFIGS:
            try:
                perf = bench_config(
                    name, overrides + ["trainer.log_every=1000000"],
                    steps=steps, warmup=2,
                )
                rec = perf["_record"]
            except Exception as e:  # record the failure, keep benching
                rec = {"config": name, "error": str(e)[:300]}
            rows.append(rec)
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            print(json.dumps(rec))
    ok = [r for r in rows if "error" not in r]
    print(f"\n{'config':28s} {'samples/s/chip':>14s} {'step_ms':>9s}  mesh")
    for r in ok:
        print(
            f"{r['config']:28s} {r['samples_per_sec_per_chip']:14.1f} "
            f"{r['step_time_median_s']*1e3:9.2f}  {r['mesh']}"
        )
    return 0 if len(ok) == len(rows) else 1


def main() -> int:
    if "--all" in sys.argv:
        return run_all()
    candidates = [
        (
            "rn50_imagenet_samples_per_sec_per_chip",
            "imagenet_rn50_ddp",
            # bs=512 is the measured single-chip throughput knee (256: 1905,
            # 512: 2025, 1024: 1842 samples/sec/chip on v5e).
            ["data.global_batch_size=512", "trainer.log_every=1000000"],
            20,
        ),
        (
            "mnist_mlp_samples_per_sec_per_chip",
            "mnist_mlp",
            ["data.global_batch_size=1024", "trainer.log_every=1000000"],
            50,
        ),
    ]
    last_err = None
    for metric, cfg_name, overrides, steps in candidates:
        try:
            perf = bench_config(cfg_name, overrides, steps=steps, warmup=3)
            value = perf["samples_per_sec_per_chip"]
            base = ASSUMED_BASELINE[metric]
            print(
                json.dumps(
                    {
                        "metric": metric,
                        "value": round(value, 2),
                        "unit": "samples/sec/chip",
                        "vs_baseline": round(value / base, 4),
                    }
                )
            )
            return 0
        except Exception as e:  # fall down the ladder, report at the end
            last_err = e
            continue
    print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0,
                      "error": str(last_err)}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
