"""Flash-decode kernel gates (ops/decode_attention.py).

The same contract every kernel in the repo is held to (fused_bn, flash
attention): interpreter-mode equivalence against the identical-numerics
dense reference — here across cache OCCUPANCY (the dimension the split-KV
kernel is built around: occupancy 1, chunk boundaries, full bucket) and
dtypes — plus the decode-path integration gates: the model's decode step
must read only the active cache bucket (jaxpr-pinned), and the
flash-routed model must reproduce the dense decode path token-for-token.
"""

from __future__ import annotations

import pytest as _pytest_mark

# Whole module is `serving`; the op-level kernel gates (sub-second,
# interpreter-mode) additionally ride `fast` per-test — the model-level
# integration gates compile multi-second decode programs and stay tier-1.
pytestmark = _pytest_mark.mark.serving

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_init

from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    PrecisionConfig,
)
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
# The submodule via importlib: the ops package re-exports the
# decode_attention FUNCTION, which shadows the submodule attribute on
# every `import ... as` form (the flash_attention naming pattern).
import importlib

da = importlib.import_module(
    "frl_distributed_ml_scaffold_tpu.ops.decode_attention"
)
from frl_distributed_ml_scaffold_tpu.precision import get_policy

FP32 = get_policy(PrecisionConfig(policy="fp32"))


def _make(b, s, h, d, dtype, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, h, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    return q, k, v


#: Occupancy classes per bucket S: a single row (the first decode step of
#: a fresh request), straddling the first KV-chunk boundary, a mid-bucket
#: interior point, and the full bucket — plus per-ROW variation inside
#: each case (the engine's slots never share an occupancy).
def _occupancies(s):
    return sorted({1, 2, min(8, s), min(9, s), s // 2, s - 1, s})


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
@pytest.mark.fast
@pytest.mark.parametrize("s", [8, 64, 512], ids=lambda s: f"S{s}")
def test_flash_decode_matches_dense_across_occupancies(dtype, s):
    """Interpreter-mode kernel == dense reference at every occupancy
    class of every bucket size, fp32 to fp32 tolerance and bf16 to one-ulp
    class tolerance (the repo's standard kernel gate)."""
    b, h, d = 3, 4, 64
    for occ in _occupancies(s):
        q, k, v = _make(b, s, h, d, dtype, seed=occ)
        lens = jnp.asarray(
            [occ, max(1, occ // 2), min(s, occ + 3)], jnp.int32
        )
        ref = da.dense_decode_attention(q, k, v, lens)
        out = da._local_decode(q, k, v, lens, impl="flash", interpret=True)
        ref32 = np.asarray(ref, np.float32)
        out32 = np.asarray(out, np.float32)
        if dtype == jnp.float32:
            np.testing.assert_allclose(ref32, out32, atol=2e-6, rtol=2e-6)
        else:
            atol = 2 * float(jnp.finfo(jnp.bfloat16).eps) * max(
                1.0, float(np.abs(ref32).max())
            )
            np.testing.assert_allclose(ref32, out32, atol=atol, rtol=0.05)


@pytest.mark.fast
def test_flash_decode_occupied_prefix_only():
    """Length masking is real: cache rows at positions >= kv_len must not
    influence the output (fill them with garbage and compare against a
    clean cache)."""
    b, s, h, d = 2, 64, 4, 64
    q, k, v = _make(b, s, h, d, jnp.float32)
    lens = jnp.asarray([5, 23], jnp.int32)
    occ = np.arange(s)[None, :, None, None] < np.asarray(lens)[:, None, None, None]
    k_dirty = jnp.where(occ, k, 1e6)
    v_dirty = jnp.where(occ, v, -1e6)
    clean = da._local_decode(q, k, v, lens, impl="flash", interpret=True)
    dirty = da._local_decode(
        q, k_dirty, v_dirty, lens, impl="flash", interpret=True
    )
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(dirty))


@pytest.mark.fast
def test_flash_decode_untileable_falls_back_to_dense():
    """Shapes outside the kernel contract (head_dim not sublane-aligned,
    S with no power-of-two divisor) must take the identical-numerics dense
    path, not miscompute."""
    b, h = 2, 2
    for s, d in ((48, 16), (7, 64)):
        q, k, v = _make(b, s, h, d, jnp.float32)
        lens = jnp.asarray([3, s], jnp.int32)
        out = da._local_decode(q, k, v, lens, impl="flash", interpret=True)
        ref = da.dense_decode_attention(q, k, v, lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.fast
def test_decode_attention_rejects_unknown_impl():
    q, k, v = _make(2, 8, 2, 32, jnp.float32)
    with pytest.raises(KeyError, match="decode_attention"):
        da._local_decode(
            q, k, v, jnp.asarray([1, 2], jnp.int32), impl="bogus",
            interpret=True,
        )


# ------------------------------------------------------- quantized cache


def _make_quant(b, s, h, d, fmt="int8", seed=0):
    from frl_distributed_ml_scaffold_tpu.ops.quantization import quantize

    q, k, v = _make(b, s, h, d, jnp.float32, seed=seed)
    kq, ks = quantize(k, fmt, channel_axes=(0, 1, 2))
    vq, vs = quantize(v, fmt, channel_axes=(0, 1, 2))
    return q, (k, v), (kq, ks[..., 0]), (vq, vs[..., 0])


@pytest.mark.fast
@pytest.mark.parametrize("s", [8, 64, 512], ids=lambda s: f"S{s}")
def test_quant_flash_decode_matches_quant_dense_across_occupancies(s):
    """The quantized-cache column of the kernel grid: interpreter-mode
    quantized kernel == the chunked quantized dense reference == the
    full-dequantize oracle, at every occupancy class (all three consume
    the SAME once-quantized values, so agreement is kernel-tolerance,
    not quantization-tolerance)."""
    from frl_distributed_ml_scaffold_tpu.ops.quantization import dequantize

    b, h, d = 3, 4, 64
    for occ in _occupancies(s):
        q, (k, v), (kq, ks), (vq, vs) = _make_quant(b, s, h, d, seed=occ)
        lens = jnp.asarray(
            [occ, max(1, occ // 2), min(s, occ + 3)], jnp.int32
        )
        ref = da.dense_decode_attention_quant(q, kq, vq, lens, ks, vs)
        out = da._local_decode(
            q, kq, vq, lens, impl="flash", interpret=True,
            k_scale=ks, v_scale=vs,
        )
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=3e-6, rtol=3e-6
        )
        # Oracle: dequantize everything, run the unquantized reference —
        # the chunked online-softmax path must agree to fp32 merge
        # tolerance (this is what makes "chunked" a pure memory property).
        kf = dequantize(kq, ks[..., None], jnp.float32)
        vf = dequantize(vq, vs[..., None], jnp.float32)
        oracle = da.dense_decode_attention(q, kf, vf, lens)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(oracle), atol=2e-6, rtol=2e-6
        )


@pytest.mark.fast
def test_quant_decode_tracks_unquantized_within_tolerance():
    """int8-cache decode vs the full-precision cache on the same values:
    the documented quantization band (per-position-per-head scales keep
    the relative error at the scaled-int grid's ~0.4%, amplified through
    the softmax to a few percent worst-case)."""
    b, s, h, d = 2, 64, 4, 64
    q, (k, v), (kq, ks), (vq, vs) = _make_quant(b, s, h, d)
    lens = jnp.asarray([17, 64], jnp.int32)
    ref = da.dense_decode_attention(q, k, v, lens)
    out = da.dense_decode_attention_quant(q, kq, vq, lens, ks, vs)
    rel = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
    assert rel < 0.05, rel


def _quant_cache_grid(gpt, fmt, buckets, atol_factor, steps):
    """Shared quantized-cache harness: (i) quantized-KV generation is
    token-IDENTICAL across cache buckets (each written token quantizes
    once over its own head vector — the values a position contributes
    are bucket-independent by construction); (ii) teacher-forced decode
    logits stay within ``atol_factor`` of the full-precision cache's.
    Token equality across FORMATS is not the gate — argmax on a random
    tiny model can sit on near-ties."""
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.models.generation import (
        _decode_step,
        _prefill,
        generate,
    )

    model, params, tokens = gpt
    mq = GPT(dataclasses.replace(model.config, kv_cache_quant=fmt), FP32)
    outs = [
        generate(mq, params, tokens, max_new_tokens=5, temperature=0.0,
                 cache_len=cl)
        for cl in buckets
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(o))

    md, mqb = model.clone(cache_len=32), mq.clone(cache_len=32)
    log_d, cache_d = _prefill(md, params, tokens, None)
    log_q, cache_q = _prefill(mqb, params, tokens, None)
    scale = max(1.0, float(jnp.abs(log_d).max()))
    for _ in range(steps):
        np.testing.assert_allclose(
            np.asarray(log_d), np.asarray(log_q), atol=atol_factor * scale,
        )
        tok = jnp.argmax(log_d, -1).astype(jnp.int32)
        log_d, cache_d = _decode_step(md, params, cache_d, tok)
        log_q, cache_q = _decode_step(mqb, params, cache_q, tok)


def test_fp8_cache_generates_and_tracks(gpt):
    """The fp8_e4m3 cache flavor rides the same knob end-to-end at the
    fp8 band (looser: 3-bit mantissa; the tight grid rides the int8
    column, test_quantized_cache_bucket_invariant_and_tracks_bf16)."""
    _quant_cache_grid(gpt, "fp8_e4m3", (None, 64), 0.12, steps=4)


@pytest.mark.fast
def test_quant_dense_chunk_is_strictly_smaller_than_bucket():
    """The bounded-dequantize contract the materialization pin relies
    on: the chunked reference never widens a full-bucket cache tensor,
    at any bucket size including the smallest."""
    for s in (8, 16, 64, 512):
        q, (k, v), (kq, ks), (vq, vs) = _make_quant(2, s, 2, 32)
        lens = jnp.asarray([1, s], jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda *a: da.dense_decode_attention_quant(*a)
        )(q, kq, vq, lens, ks, vs)
        pins.assert_no_wide_dims_materialized(
            jaxpr, (s, 2, 32),
            msg=f"quant dense fallback widened the full S={s} bucket",
        )


# ------------------------------------------------------------ paged cache


def _paged_from_contiguous(k, v, bs, n_blocks, seed=0, scales=None):
    """Scatter a contiguous [B, S, H, D] cache into pool blocks through a
    random (non-trivial) block table — the layout serving/engine.py
    grafts into, built here by hand so the op gates do not depend on the
    engine."""
    rng = np.random.default_rng(seed)
    b, s, h, d = k.shape
    m_tbl = s // bs
    assert b * m_tbl <= n_blocks - 1, "pool too small for the fixture"
    perm = rng.permutation(np.arange(1, n_blocks))[: b * m_tbl]
    tables = jnp.asarray(perm.reshape(b, m_tbl), jnp.int32)
    k_pool = jnp.zeros((n_blocks, bs, h, d), k.dtype)
    v_pool = jnp.zeros((n_blocks, bs, h, d), v.dtype)
    sc_pools = None
    if scales is not None:
        ks, vs = scales
        ksp = jnp.zeros((n_blocks, bs, h), ks.dtype)
        vsp = jnp.zeros((n_blocks, bs, h), vs.dtype)
    for bb in range(b):
        for j in range(m_tbl):
            pid = int(tables[bb, j])
            k_pool = k_pool.at[pid].set(k[bb, j * bs : (j + 1) * bs])
            v_pool = v_pool.at[pid].set(v[bb, j * bs : (j + 1) * bs])
            if scales is not None:
                ksp = ksp.at[pid].set(ks[bb, j * bs : (j + 1) * bs])
                vsp = vsp.at[pid].set(vs[bb, j * bs : (j + 1) * bs])
    if scales is not None:
        sc_pools = (ksp, vsp)
    return k_pool, v_pool, tables, sc_pools


@pytest.mark.fast
@pytest.mark.parametrize("s", [16, 64, 512], ids=lambda s: f"S{s}")
def test_paged_decode_matches_contiguous_across_occupancies(s):
    """The paged column of the kernel grid (ISSUE 10): the streamed
    paged dense reference tracks the contiguous dense reference to fp32
    merge tolerance at every occupancy class, and the interpreter-mode
    paged kernel (block table on the scalar-prefetch channel) matches
    the streamed reference — same physical blocks, same order, same
    chunking — to kernel tolerance."""
    b, h, d, bs = 3, 4, 64, 8
    for occ in _occupancies(s):
        q, k, v = _make(b, s, h, d, jnp.float32, seed=occ)
        lens = jnp.asarray(
            [occ, max(1, occ // 2), min(s, occ + 3)], jnp.int32
        )
        k_pool, v_pool, tables, _ = _paged_from_contiguous(
            k, v, bs, b * (s // bs) + 7, seed=occ
        )
        ref = da.dense_decode_attention(q, k, v, lens)
        out = da.dense_paged_decode_attention(
            q, k_pool, v_pool, lens, tables
        )
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=2e-6, rtol=2e-6
        )
        kern = da._local_paged_decode(
            q, k_pool, v_pool, lens, tables, impl="flash", interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(kern), np.asarray(out), atol=2e-6, rtol=2e-6
        )


@pytest.mark.fast
def test_paged_quant_decode_matches_quant_dense():
    """Quantized pools: the paged streamed reference == the contiguous
    chunked quantized reference (same once-quantized values), and the
    interpreter-mode quantized paged kernel tracks it."""
    b, s, h, d, bs = 3, 64, 4, 64, 8
    for occ in (1, 9, 32, 64):
        q, (k, v), (kq, ks), (vq, vs) = _make_quant(b, s, h, d, seed=occ)
        lens = jnp.asarray(
            [occ, max(1, occ // 2), min(s, occ + 3)], jnp.int32
        )
        kqp, vqp, tables, (ksp, vsp) = _paged_from_contiguous(
            kq, vq, bs, b * (s // bs) + 5, seed=occ,
            scales=(ks.astype(jnp.float32), vs.astype(jnp.float32)),
        )
        ref = da.dense_decode_attention_quant(q, kq, vq, lens, ks, vs)
        out = da.dense_paged_decode_attention(
            q, kqp, vqp, lens, tables, ksp, vsp
        )
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=3e-6, rtol=3e-6
        )
        kern = da._local_paged_decode(
            q, kqp, vqp, lens, tables, impl="flash", interpret=True,
            k_scale=ksp, v_scale=vsp,
        )
        np.testing.assert_allclose(
            np.asarray(kern), np.asarray(out), atol=3e-6, rtol=3e-6
        )


@pytest.mark.fast
def test_paged_decode_ignores_unreferenced_and_dead_blocks():
    """Isolation, the property block sharing rests on: pool blocks not
    referenced by a row's table — and referenced blocks past the row's
    occupancy — must not influence its output (fill both with garbage
    and compare against the clean pool)."""
    b, s, h, d, bs = 2, 64, 4, 64, 8
    q, k, v = _make(b, s, h, d, jnp.float32)
    lens = jnp.asarray([5, 23], jnp.int32)
    k_pool, v_pool, tables, _ = _paged_from_contiguous(k, v, bs, 32)
    clean = da.dense_paged_decode_attention(q, k_pool, v_pool, lens, tables)
    # Garbage in every block a row's OCCUPIED prefix does not reach:
    # row 0 occupies 5 tokens (block 0 of its table), row 1 occupies 23
    # (blocks 0..2) — everything else in the pool is fair game.
    live = set()
    for bb in range(b):
        for j in range((int(lens[bb]) - 1) // bs + 1):
            live.add(int(tables[bb, j]))
    dirty_k, dirty_v = k_pool, v_pool
    for pid in range(32):
        if pid not in live:
            dirty_k = dirty_k.at[pid].set(1e6)
            dirty_v = dirty_v.at[pid].set(-1e6)
    # Positions past occupancy INSIDE the last live block too.
    dirty = da.dense_paged_decode_attention(q, dirty_k, dirty_v, lens, tables)
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(dirty))
    kern_clean = da._local_paged_decode(
        q, k_pool, v_pool, lens, tables, impl="flash", interpret=True
    )
    kern_dirty = da._local_paged_decode(
        q, dirty_k, dirty_v, lens, tables, impl="flash", interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(kern_clean), np.asarray(kern_dirty)
    )


@pytest.mark.fast
def test_paged_untileable_block_falls_back_to_dense():
    """Block geometries outside the kernel contract (block < 8, head_dim
    not sublane-aligned) must take the identical-numerics streamed dense
    path, not miscompute — the ``_local_decode`` fallback contract."""
    b, h = 2, 2
    for bs, d in ((4, 64), (8, 16)):
        s = 8 * bs
        q, k, v = _make(b, s, h, d, jnp.float32)
        lens = jnp.asarray([3, s], jnp.int32)
        k_pool, v_pool, tables, _ = _paged_from_contiguous(k, v, bs, 32)
        out = da._local_paged_decode(
            q, k_pool, v_pool, lens, tables, impl="flash", interpret=True
        )
        ref = da.dense_paged_decode_attention(q, k_pool, v_pool, lens, tables)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.fast
def test_paged_dense_fallback_streams_bounded_chunks():
    """The paged no-cache-clone contract at the op level: the streamed
    reference never materializes the logical cache view (no intermediate
    carries the M*bs logical-context dim) at any block size — the same
    bounded-chunk discipline as the quantized fallback, which is what
    the graft-lint paged program pin relies on."""
    b, h, d = 2, 2, 32
    for bs, m_tbl in ((8, 8), (16, 32)):
        s = bs * m_tbl
        n_blocks = 2 * b * m_tbl + 1
        q = jnp.zeros((b, h, d), jnp.float32)
        k_pool = jnp.zeros((n_blocks, bs, h, d), jnp.float32)
        tables = jnp.zeros((b, m_tbl), jnp.int32)
        lens = jnp.asarray([1, s], jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda *a: da.dense_paged_decode_attention(*a)
        )(q, k_pool, k_pool, lens, tables)
        pins.assert_no_dim_materialized(
            jaxpr, s,
            f"paged dense fallback materialized the M*bs={s} logical view",
        )


# --------------------------------------------- speculative verify tile


@pytest.mark.fast
@pytest.mark.parametrize("t", [2, 4], ids=lambda t: f"T{t}")
def test_paged_verify_matches_per_position_decode(t):
    """ISSUE 11 op gate: the verify tile's causal contract — query j of
    a row whose TOTAL occupancy (tile included) is L scores exactly
    like a single-token decode step at occupancy L - T + 1 + j, for
    every position, at mixed occupancies — and the interpreter-mode
    verify kernel matches the streamed reference to kernel tolerance.
    This per-position equality is what makes greedy acceptance exact
    (the engine's token-identity pin rides on it)."""
    b, s, h, d, bs = 3, 64, 4, 64, 8
    rng = np.random.default_rng(7 + t)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    k_pool, v_pool, tables, _ = _paged_from_contiguous(
        k, v, bs, b * (s // bs) + 5, seed=t
    )
    lens = jnp.asarray([t + 1, 29, s], jnp.int32)  # total incl. tile
    out = da.dense_paged_verify_attention(q, k_pool, v_pool, lens, tables)
    for j in range(t):
        ref = da.dense_decode_attention(
            q[:, j], k, v, lens - (t - 1) + j
        )
        np.testing.assert_allclose(
            np.asarray(out[:, j]), np.asarray(ref), atol=2e-6, rtol=2e-6,
            err_msg=f"verify position {j} diverged from its decode step",
        )
    kern = da._local_paged_verify(
        q, k_pool, v_pool, lens, tables, impl="flash", interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(kern), np.asarray(out), atol=2e-6, rtol=2e-6
    )


@pytest.mark.fast
def test_paged_verify_quant_matches_quant_reference():
    """Quantized pools under the verify tile: the streamed reference's
    per-position slices track the contiguous quantized decode reference
    (same once-quantized values), and the interpreter-mode quantized
    verify kernel matches the streamed reference."""
    b, s, h, d, bs, t = 3, 64, 4, 64, 8, 3
    rng = np.random.default_rng(3)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    from frl_distributed_ml_scaffold_tpu.ops.quantization import quantize

    kq, ks = quantize(k, "int8", channel_axes=(0, 1, 2))
    vq, vs = quantize(v, "int8", channel_axes=(0, 1, 2))
    ks, vs = ks[..., 0], vs[..., 0]
    kqp, vqp, tables, sc = _paged_from_contiguous(
        kq, vq, bs, b * (s // bs) + 5, seed=5, scales=(ks, vs)
    )
    ksp, vsp = sc
    lens = jnp.asarray([t, 21, s], jnp.int32)
    out = da.dense_paged_verify_attention(
        q, kqp, vqp, lens, tables, ksp, vsp
    )
    for j in range(t):
        ref = da.dense_decode_attention_quant(
            q[:, j], kq, vq, lens - (t - 1) + j, ks, vs
        )
        np.testing.assert_allclose(
            np.asarray(out[:, j]), np.asarray(ref), atol=1e-5, rtol=1e-5,
        )
    kern = da._local_paged_verify(
        q, kqp, vqp, lens, tables, impl="flash", interpret=True,
        k_scale=ksp, v_scale=vsp,
    )
    np.testing.assert_allclose(
        np.asarray(kern), np.asarray(out), atol=1e-5, rtol=1e-5
    )


@pytest.mark.fast
def test_paged_verify_dense_fallback_streams_bounded_chunks():
    """The no-logical-view contract holds at tile width: k+1 query
    positions make the gather temptation bigger, not smaller — the
    verify fallback still streams one bounded block per table column
    (no intermediate carries the M*bs logical-context dim), which is
    what the graft-lint serving:verify_step_paged pin relies on."""
    b, h, d, t = 2, 2, 32, 3
    for bs, m_tbl in ((8, 8), (16, 32)):
        s = bs * m_tbl
        n_blocks = 2 * b * m_tbl + 1
        q = jnp.zeros((b, t, h, d), jnp.float32)
        k_pool = jnp.zeros((n_blocks, bs, h, d), jnp.float32)
        tables = jnp.zeros((b, m_tbl), jnp.int32)
        lens = jnp.asarray([t, s], jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda *a: da.dense_paged_verify_attention(*a)
        )(q, k_pool, k_pool, lens, tables)
        pins.assert_no_dim_materialized(
            jaxpr, s,
            f"verify fallback materialized the M*bs={s} logical view",
        )


# --------------------------------------------------------- model decode


TINY = dict(
    vocab_size=64, num_layers=2, num_heads=2, hidden_dim=64, seq_len=96,
    dropout=0.0,
)


@pytest.fixture(scope="module")
def gpt():
    model = GPT(GPTConfig(**TINY), FP32)
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params, tokens


@pytest.mark.parametrize("policy", ["fp32", "bf16_mixed"])
def test_model_flash_decode_matches_dense_decode(policy):
    """The integration gate, across a bucket boundary (prompt in bucket
    16, generation crossing into 32): under fp32, generate() with
    decode_attention=flash (kernel forced through the interpreter) must
    reproduce the dense decode path's greedy tokens at every step. Under
    bf16 the online-softmax merge legitimately rounds once where the
    dense softmax rounds per op, so the gate is per-step LOGITS within
    the bf16 ulp class on the teacher-forced dense trajectory (greedy
    argmax on a random tiny model sits on bf16-scale ties)."""
    import dataclasses

    pol = get_policy(PrecisionConfig(policy=policy))
    cfg = GPTConfig(**TINY)
    tokens = jax.random.randint(jax.random.key(3), (2, 10), 0, 64)
    model_d = GPT(dataclasses.replace(cfg, decode_attention="dense"), pol)
    params = jit_init(model_d, tokens, train=False)["params"]
    from frl_distributed_ml_scaffold_tpu.models.generation import generate

    ref = generate(model_d, params, tokens, max_new_tokens=12,
                   temperature=0.0)
    model_f = GPT(dataclasses.replace(cfg, decode_attention="flash"), pol)
    da.FORCE_INTERPRET = True
    try:
        if policy == "fp32":
            out = generate(model_f, params, tokens, max_new_tokens=12,
                           temperature=0.0)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
            return
        # bf16: teacher-force the dense trajectory through both paths and
        # compare the logits stepwise.
        from frl_distributed_ml_scaffold_tpu.models.generation import (
            _decode_step,
            _prefill,
        )

        ref_np = np.asarray(ref)
        md, mf = (m.clone(cache_len=32) for m in (model_d, model_f))
        log_d, cache_d = _prefill(md, params, tokens, None)
        log_f, cache_f = _prefill(mf, params, tokens, None)
        atol = 8 * float(jnp.finfo(jnp.bfloat16).eps) * max(
            1.0, float(np.abs(np.asarray(log_d, np.float32)).max())
        )
        for i in range(10, ref_np.shape[1]):
            np.testing.assert_allclose(
                np.asarray(log_d, np.float32), np.asarray(log_f, np.float32),
                atol=atol, rtol=0.05,
            )
            tok = jnp.asarray(ref_np[:, i], jnp.int32)
            log_d, cache_d = _decode_step(md, params, cache_d, tok)
            log_f, cache_f = _decode_step(mf, params, cache_f, tok)
    finally:
        da.FORCE_INTERPRET = None


def test_bucketed_cache_matches_full_cache(gpt):
    """Numerics across cache buckets: the same generation run in the
    smallest covering bucket, an oversized bucket, and the legacy
    full-seq_len cache must agree token-for-token."""
    from frl_distributed_ml_scaffold_tpu.models.generation import generate

    model, params, tokens = gpt
    outs = [
        generate(model, params, tokens, max_new_tokens=6, temperature=0.0,
                 cache_len=cl)
        for cl in (None, 32, model.config.seq_len)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(o))


def test_quantized_cache_bucket_invariant_and_tracks_bf16(gpt):
    """The int8 column of the bucket/dtype grid at the documented
    quantization band (~0.4% per-tensor noise through the softmax),
    including the legacy full-seq_len bucket."""
    model, _, _ = gpt
    _quant_cache_grid(
        gpt, "int8", (None, 32, model.config.seq_len), 0.03, steps=6
    )


def _decode_step_jaxpr(model, params, cache_len):
    """Jaxpr of one single-token decode step at the given cache bucket."""
    m = model.clone(cache_len=cache_len)
    tokens = jnp.zeros((2, 1), jnp.int32)
    # Build a cache of the right structure via a 1-token prefill.
    _, vars_out = m.apply(
        {"params": params}, tokens, decode=True, mutable=["cache"]
    )
    cache = vars_out["cache"]

    def step(params, cache, tok):
        logits, vo = m.apply(
            {"params": params, "cache": cache}, tok, decode=True,
            mutable=["cache"],
        )
        return logits, vo["cache"]

    return jax.make_jaxpr(step)(params, cache, tokens)


# The eqn-shape walker this file used to carry lives in
# analysis/jaxpr_utils.py; the pin itself rides analysis.pins.
from frl_distributed_ml_scaffold_tpu.analysis import pins


@pytest.mark.fast
def test_quantized_decode_step_never_dequantizes_whole_cache(gpt):
    """ISSUE 6's decode pin: the int8-KV decode step at a 16-bucket
    carries (i) no full-seq_len intermediate (the PR 4 pin still holds)
    and (ii) no WIDE-float intermediate with the cache's (S, H, hd)
    geometry — the cache dequantizes per chunk, never wholesale. The
    deliberately-broken wholesale variant is the graft-lint mutation
    gate (tests/test_graft_lint.py)."""
    import dataclasses

    model, params, _ = gpt
    mq = GPT(
        dataclasses.replace(model.config, kv_cache_quant="int8"), FP32
    )
    seq_len, bucket = model.config.seq_len, 16
    jaxpr = _decode_step_jaxpr(mq, params, bucket)
    pins.assert_no_dim_materialized(
        jaxpr, seq_len,
        "quantized decode step materializes full-context arrays",
    )
    h = model.config.num_heads
    hd = model.config.hidden_dim // h
    pins.assert_no_wide_dims_materialized(
        jaxpr, (bucket, h, hd),
        msg="quantized decode step dequantized the whole cache",
    )
    # The 1-byte cache updates ARE there (the pin isn't passing vacuously).
    shapes = pins.eqn_output_shapes(jaxpr)
    assert any(s[-3:] == (bucket, h, hd) for s in shapes), (
        "no bucket-sized cache arrays found — is decode even caching?"
    )


@pytest.mark.fast
def test_decode_step_reads_only_active_bucket(gpt):
    """The jaxpr pin of the acceptance gate: with the cache bucketed to 16
    of a seq_len=96 model, the decode step must carry NO intermediate
    sized to the full context — every cache-derived array (the cache
    update, the [B, H, 1, S] score strip, the attention output chain) is
    bucket-sized. seq_len appears only in the wpe PARAM (an invar, never
    materialized per step: the position embedding is gathered per row)."""
    model, params, _ = gpt
    seq_len, bucket = model.config.seq_len, 16
    jaxpr = _decode_step_jaxpr(model, params, bucket)
    pins.assert_no_dim_materialized(
        jaxpr, seq_len,
        f"decode step materializes full-context ({seq_len}) arrays with a "
        f"{bucket}-bucket cache",
    )
    shapes = pins.eqn_output_shapes(jaxpr)
    h, hd = model.config.num_heads, model.config.hidden_dim // model.config.num_heads
    assert any(
        s[-3:] == (bucket, h, hd) or (bucket in s and h in s)
        for s in shapes
    ), "no bucket-sized cache arrays found — is decode even caching?"
