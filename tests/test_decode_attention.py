"""Flash-decode kernel gates (ops/decode_attention.py).

The same contract every kernel in the repo is held to (fused_bn, flash
attention): interpreter-mode equivalence against the identical-numerics
dense reference — here across cache OCCUPANCY (the dimension the split-KV
kernel is built around: occupancy 1, chunk boundaries, full bucket) and
dtypes — plus the decode-path integration gates: the model's decode step
must read only the active cache bucket (jaxpr-pinned), and the
flash-routed model must reproduce the dense decode path token-for-token.
"""

from __future__ import annotations

import pytest as _pytest_mark

# Whole module is `serving`; the op-level kernel gates (sub-second,
# interpreter-mode) additionally ride `fast` per-test — the model-level
# integration gates compile multi-second decode programs and stay tier-1.
pytestmark = _pytest_mark.mark.serving

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_init

from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    PrecisionConfig,
)
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
# The submodule via importlib: the ops package re-exports the
# decode_attention FUNCTION, which shadows the submodule attribute on
# every `import ... as` form (the flash_attention naming pattern).
import importlib

da = importlib.import_module(
    "frl_distributed_ml_scaffold_tpu.ops.decode_attention"
)
from frl_distributed_ml_scaffold_tpu.precision import get_policy

FP32 = get_policy(PrecisionConfig(policy="fp32"))


def _make(b, s, h, d, dtype, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, h, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    return q, k, v


#: Occupancy classes per bucket S: a single row (the first decode step of
#: a fresh request), straddling the first KV-chunk boundary, a mid-bucket
#: interior point, and the full bucket — plus per-ROW variation inside
#: each case (the engine's slots never share an occupancy).
def _occupancies(s):
    return sorted({1, 2, min(8, s), min(9, s), s // 2, s - 1, s})


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
@pytest.mark.fast
@pytest.mark.parametrize("s", [8, 64, 512], ids=lambda s: f"S{s}")
def test_flash_decode_matches_dense_across_occupancies(dtype, s):
    """Interpreter-mode kernel == dense reference at every occupancy
    class of every bucket size, fp32 to fp32 tolerance and bf16 to one-ulp
    class tolerance (the repo's standard kernel gate)."""
    b, h, d = 3, 4, 64
    for occ in _occupancies(s):
        q, k, v = _make(b, s, h, d, dtype, seed=occ)
        lens = jnp.asarray(
            [occ, max(1, occ // 2), min(s, occ + 3)], jnp.int32
        )
        ref = da.dense_decode_attention(q, k, v, lens)
        out = da._local_decode(q, k, v, lens, impl="flash", interpret=True)
        ref32 = np.asarray(ref, np.float32)
        out32 = np.asarray(out, np.float32)
        if dtype == jnp.float32:
            np.testing.assert_allclose(ref32, out32, atol=2e-6, rtol=2e-6)
        else:
            atol = 2 * float(jnp.finfo(jnp.bfloat16).eps) * max(
                1.0, float(np.abs(ref32).max())
            )
            np.testing.assert_allclose(ref32, out32, atol=atol, rtol=0.05)


@pytest.mark.fast
def test_flash_decode_occupied_prefix_only():
    """Length masking is real: cache rows at positions >= kv_len must not
    influence the output (fill them with garbage and compare against a
    clean cache)."""
    b, s, h, d = 2, 64, 4, 64
    q, k, v = _make(b, s, h, d, jnp.float32)
    lens = jnp.asarray([5, 23], jnp.int32)
    occ = np.arange(s)[None, :, None, None] < np.asarray(lens)[:, None, None, None]
    k_dirty = jnp.where(occ, k, 1e6)
    v_dirty = jnp.where(occ, v, -1e6)
    clean = da._local_decode(q, k, v, lens, impl="flash", interpret=True)
    dirty = da._local_decode(
        q, k_dirty, v_dirty, lens, impl="flash", interpret=True
    )
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(dirty))


@pytest.mark.fast
def test_flash_decode_untileable_falls_back_to_dense():
    """Shapes outside the kernel contract (head_dim not sublane-aligned,
    S with no power-of-two divisor) must take the identical-numerics dense
    path, not miscompute."""
    b, h = 2, 2
    for s, d in ((48, 16), (7, 64)):
        q, k, v = _make(b, s, h, d, jnp.float32)
        lens = jnp.asarray([3, s], jnp.int32)
        out = da._local_decode(q, k, v, lens, impl="flash", interpret=True)
        ref = da.dense_decode_attention(q, k, v, lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.fast
def test_decode_attention_rejects_unknown_impl():
    q, k, v = _make(2, 8, 2, 32, jnp.float32)
    with pytest.raises(KeyError, match="decode_attention"):
        da._local_decode(
            q, k, v, jnp.asarray([1, 2], jnp.int32), impl="bogus",
            interpret=True,
        )


# --------------------------------------------------------- model decode


TINY = dict(
    vocab_size=64, num_layers=2, num_heads=2, hidden_dim=64, seq_len=96,
    dropout=0.0,
)


@pytest.fixture(scope="module")
def gpt():
    model = GPT(GPTConfig(**TINY), FP32)
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params, tokens


@pytest.mark.parametrize("policy", ["fp32", "bf16_mixed"])
def test_model_flash_decode_matches_dense_decode(policy):
    """The integration gate, across a bucket boundary (prompt in bucket
    16, generation crossing into 32): under fp32, generate() with
    decode_attention=flash (kernel forced through the interpreter) must
    reproduce the dense decode path's greedy tokens at every step. Under
    bf16 the online-softmax merge legitimately rounds once where the
    dense softmax rounds per op, so the gate is per-step LOGITS within
    the bf16 ulp class on the teacher-forced dense trajectory (greedy
    argmax on a random tiny model sits on bf16-scale ties)."""
    import dataclasses

    pol = get_policy(PrecisionConfig(policy=policy))
    cfg = GPTConfig(**TINY)
    tokens = jax.random.randint(jax.random.key(3), (2, 10), 0, 64)
    model_d = GPT(dataclasses.replace(cfg, decode_attention="dense"), pol)
    params = jit_init(model_d, tokens, train=False)["params"]
    from frl_distributed_ml_scaffold_tpu.models.generation import generate

    ref = generate(model_d, params, tokens, max_new_tokens=12,
                   temperature=0.0)
    model_f = GPT(dataclasses.replace(cfg, decode_attention="flash"), pol)
    da.FORCE_INTERPRET = True
    try:
        if policy == "fp32":
            out = generate(model_f, params, tokens, max_new_tokens=12,
                           temperature=0.0)
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
            return
        # bf16: teacher-force the dense trajectory through both paths and
        # compare the logits stepwise.
        from frl_distributed_ml_scaffold_tpu.models.generation import (
            _decode_step,
            _prefill,
        )

        ref_np = np.asarray(ref)
        md, mf = (m.clone(cache_len=32) for m in (model_d, model_f))
        log_d, cache_d = _prefill(md, params, tokens, None)
        log_f, cache_f = _prefill(mf, params, tokens, None)
        atol = 8 * float(jnp.finfo(jnp.bfloat16).eps) * max(
            1.0, float(np.abs(np.asarray(log_d, np.float32)).max())
        )
        for i in range(10, ref_np.shape[1]):
            np.testing.assert_allclose(
                np.asarray(log_d, np.float32), np.asarray(log_f, np.float32),
                atol=atol, rtol=0.05,
            )
            tok = jnp.asarray(ref_np[:, i], jnp.int32)
            log_d, cache_d = _decode_step(md, params, cache_d, tok)
            log_f, cache_f = _decode_step(mf, params, cache_f, tok)
    finally:
        da.FORCE_INTERPRET = None


def test_bucketed_cache_matches_full_cache(gpt):
    """Numerics across cache buckets: the same generation run in the
    smallest covering bucket, an oversized bucket, and the legacy
    full-seq_len cache must agree token-for-token."""
    from frl_distributed_ml_scaffold_tpu.models.generation import generate

    model, params, tokens = gpt
    outs = [
        generate(model, params, tokens, max_new_tokens=6, temperature=0.0,
                 cache_len=cl)
        for cl in (None, 32, model.config.seq_len)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(o))


def _decode_step_jaxpr(model, params, cache_len):
    """Jaxpr of one single-token decode step at the given cache bucket."""
    m = model.clone(cache_len=cache_len)
    tokens = jnp.zeros((2, 1), jnp.int32)
    # Build a cache of the right structure via a 1-token prefill.
    _, vars_out = m.apply(
        {"params": params}, tokens, decode=True, mutable=["cache"]
    )
    cache = vars_out["cache"]

    def step(params, cache, tok):
        logits, vo = m.apply(
            {"params": params, "cache": cache}, tok, decode=True,
            mutable=["cache"],
        )
        return logits, vo["cache"]

    return jax.make_jaxpr(step)(params, cache, tokens)


# The eqn-shape walker this file used to carry lives in
# analysis/jaxpr_utils.py; the pin itself rides analysis.pins.
from frl_distributed_ml_scaffold_tpu.analysis import pins


@pytest.mark.fast
def test_decode_step_reads_only_active_bucket(gpt):
    """The jaxpr pin of the acceptance gate: with the cache bucketed to 16
    of a seq_len=96 model, the decode step must carry NO intermediate
    sized to the full context — every cache-derived array (the cache
    update, the [B, H, 1, S] score strip, the attention output chain) is
    bucket-sized. seq_len appears only in the wpe PARAM (an invar, never
    materialized per step: the position embedding is gathered per row)."""
    model, params, _ = gpt
    seq_len, bucket = model.config.seq_len, 16
    jaxpr = _decode_step_jaxpr(model, params, bucket)
    pins.assert_no_dim_materialized(
        jaxpr, seq_len,
        f"decode step materializes full-context ({seq_len}) arrays with a "
        f"{bucket}-bucket cache",
    )
    shapes = pins.eqn_output_shapes(jaxpr)
    h, hd = model.config.num_heads, model.config.hidden_dim // model.config.num_heads
    assert any(
        s[-3:] == (bucket, h, hd) or (bucket in s and h in s)
        for s in shapes
    ), "no bucket-sized cache arrays found — is decode even caching?"
