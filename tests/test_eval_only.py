"""--eval-only entrypoint (reference call stack (e): restore → eval)."""

from __future__ import annotations
import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast


import pytest

from launch import main


def test_eval_only_roundtrip(tmp_path):
    common = [
        "--config=mnist_mlp",
        "--device=cpu",
        "data.global_batch_size=64",
        "checkpoint.enabled=true",
        f"workdir={tmp_path}",
    ]
    assert (
        main(common + ["trainer.total_steps=8", "checkpoint.save_every=8",
                       "trainer.log_every=4"])
        == 0
    )
    assert main(common + ["--eval-only"]) == 0


def test_eval_only_without_checkpoint_errors(tmp_path):
    with pytest.raises(RuntimeError, match="eval-only"):
        main([
            "--config=mnist_mlp", "--device=cpu", "--eval-only",
            "data.global_batch_size=64", "checkpoint.enabled=true",
            f"workdir={tmp_path}",
        ])
