"""Online-ingestion tier (SURVEY C16, VERDICT r4 missing #5): the loader
widens its sampling window while producers keep sealing new shards —
reference parity with torch's streaming DataLoader, expressed as an
append-only shard watermark (data/streaming.py)."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import os

import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.imagenet import ImageNet
from frl_distributed_ml_scaffold_tpu.data.shards import sealed_save
from frl_distributed_ml_scaffold_tpu.data.streaming import (
    StreamingShardCorpus,
    _sealed_pair_count,
)


def _write_shard(dir_, idx, *, n=8, size=8, label_base=0, labels=True):
    rng = np.random.default_rng(idx)
    sealed_save(
        os.path.join(dir_, f"train_images_{idx:03d}.npy"),
        rng.random((n, size, size, 3), np.float32).astype(np.float32),
    )
    if labels:
        sealed_save(
            os.path.join(dir_, f"train_labels_{idx:03d}.npy"),
            np.full(n, label_base + idx, np.int32),
        )


def test_sealed_pair_count_prefix_rule(tmp_path):
    d = str(tmp_path)
    assert _sealed_pair_count(d, "train", "images") == 0
    _write_shard(d, 0)
    _write_shard(d, 1, labels=False)  # labels half still in flight
    _write_shard(d, 2)  # sealed, but AFTER the incomplete pair
    # Prefix rule: the window stops at the first incomplete pair — shard 2
    # stays invisible until shard 1's labels land (index order is the
    # producers' append order).
    assert _sealed_pair_count(d, "train", "images") == 1


def test_streaming_refuses_empty_corpus(tmp_path):
    """Zero sealed pairs must REFUSE, not fall back: an uncapped view can
    crash on a half-sealed pair, and the loader's synthetic fallback is
    decided once at construction — it would silently train on fake data
    forever while real shards land seconds later."""
    d = str(tmp_path)
    with pytest.raises(ValueError, match="no sealed"):
        StreamingShardCorpus(d, "train", "images", refresh_every=4)
    # Half-sealed (labels in flight) is still "no pair".
    _write_shard(d, 0, labels=False)
    with pytest.raises(ValueError, match="no sealed"):
        StreamingShardCorpus(d, "train", "images", refresh_every=4)


def test_streaming_corpus_widens_and_freezes_between_refreshes(tmp_path):
    d = str(tmp_path)
    _write_shard(d, 0)
    corpus = StreamingShardCorpus(d, "train", "images", refresh_every=10)
    assert corpus.found and corpus.n == 8
    assert corpus.state() == {"shards": 1, "items": 8, "skew_deferrals": 0}

    _write_shard(d, 1)
    # Before the refresh step the view is FROZEN (determinism contract).
    corpus.maybe_refresh(5)
    assert corpus.n == 8
    # At/after the refresh boundary the window widens to the new shard.
    corpus.maybe_refresh(10)
    assert corpus.n == 16
    assert corpus.state() == {"shards": 2, "items": 16, "skew_deferrals": 0}
    # New items are actually reachable, with their own labels.
    x, y = corpus.gather(np.arange(8, 16))
    assert x.shape == (8, 8, 8, 3)
    np.testing.assert_array_equal(y, np.full(8, 1))


def test_streaming_multihost_window_protocol(tmp_path, monkeypatch):
    """Leader-published window with deferred activation: hosts adopt the
    same shard SET at the same refresh bucket — never a count-only,
    moment-of-read-dependent min (the divergence mode a symmetric
    protocol has). Two hosts simulated in one process by patching
    process_count/index."""
    import json

    import jax

    d = str(tmp_path)
    _write_shard(d, 0)
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    # Pre-seed host 1's publish (what its construction would write),
    # then construct the leader — it proposes the initial window — and
    # the follower, which adopts it. Sequential: the two "hosts" share
    # one process here, so concurrency would also share the monkeypatch.
    os.makedirs(os.path.join(d, ".stream_sync"), exist_ok=True)
    with open(
        os.path.join(d, ".stream_sync", "train_images_host_1.json"), "w"
    ) as fh:
        json.dump({"count": 1, "anchor": 0}, fh)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    leader = StreamingShardCorpus(d, "train", "images", refresh_every=10)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    follower = StreamingShardCorpus(d, "train", "images", refresh_every=10)
    assert leader.n == follower.n == 8

    # Producer seals shard 1. At bucket 1 both hosts publish their new
    # counts; the leader (refreshing after the follower's publish is
    # visible) PROPOSES with activation deferred to bucket 2 — neither
    # adopts yet. Both adopt at their bucket-2 refresh; the window file
    # carries anchor + count (a shard SET, not a bare count).
    _write_shard(d, 1)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    follower.maybe_refresh(10)  # publishes count=2; window still old
    assert follower.n == 8
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    leader.maybe_refresh(10)  # bucket 1: proposes, must not adopt
    assert leader.n == 8
    win = json.load(
        open(os.path.join(d, ".stream_sync", "train_images_window.json"))
    )
    assert win == {"count": 2, "anchor": 0, "activate_at_bucket": 2}
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    follower.maybe_refresh(20)  # bucket 2: adopt
    assert follower.n == 16
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    leader.maybe_refresh(20)
    assert leader.n == 16


def test_streaming_retry_within_bucket_and_skew_counter(tmp_path):
    """An agreed window this host can't serve yet must RETRY on the next
    batch (not defer a whole refresh bucket — the window is already active
    on peers, so every deferred step skews the DP data distribution), and
    the lag must be observable via the ``skew_deferrals`` watermark."""
    d = str(tmp_path)
    _write_shard(d, 0)
    corpus = StreamingShardCorpus(d, "train", "images", refresh_every=10)

    # Simulate the leader having activated a 2-shard window that this
    # host's filesystem view does not serve yet (NFS attribute-cache lag).
    real_agree = corpus._proto.agree
    corpus._proto.agree = lambda bucket: (2, 0)
    corpus.maybe_refresh(10)
    assert corpus.n == 8  # not adopted
    assert corpus.state()["skew_deferrals"] == 1
    # The retry happens on the NEXT batch, within the same bucket.
    assert corpus._next_refresh == 11
    corpus.maybe_refresh(11)
    assert corpus.state()["skew_deferrals"] == 2

    # The lagging shard lands: the very next batch adopts — no waiting
    # for bucket 2 — and the schedule returns to the bucket boundary.
    _write_shard(d, 1)
    corpus.maybe_refresh(12)
    assert corpus.n == 16
    assert corpus.state()["skew_deferrals"] == 2
    assert corpus._next_refresh == 20
    corpus._proto.agree = real_agree


def test_streaming_initial_rejects_stale_anchor_window(tmp_path, monkeypatch):
    """A ``.stream_sync`` window file left by an EARLIER corpus in the same
    directory (different anchor) must not be adopted at construction —
    its counts index a different shard SET. The protocol keeps waiting for
    a window matching the local anchor and fails loudly at the deadline."""
    import json

    import jax

    d = str(tmp_path)
    # Current corpus anchors at shard 3 (earlier shards were rotated out).
    _write_shard(d, 3)
    _write_shard(d, 4)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)  # follower: no
    # leader process exists to replace the stale window in this test.
    os.makedirs(os.path.join(d, ".stream_sync"), exist_ok=True)
    with open(
        os.path.join(d, ".stream_sync", "train_images_window.json"), "w"
    ) as fh:
        json.dump({"count": 9, "anchor": 0, "activate_at_bucket": 0}, fh)
    with pytest.raises(ValueError, match="no agreed initial window"):
        corpus = StreamingShardCorpus.__new__(StreamingShardCorpus)
        corpus.data_dir, corpus.split, corpus.kind = d, "train", "images"
        corpus.refresh_every = 4
        from frl_distributed_ml_scaffold_tpu.data.streaming import (
            _WindowProtocol,
        )

        corpus._proto = _WindowProtocol(
            d, "train_images", corpus._local_scan
        )
        corpus._proto.initial(deadline_s=2.5)


def test_streaming_retry_budget_caps_per_batch_scans(tmp_path):
    """A PERMANENTLY unservable window (rotated corpus mid-run) must not
    pay a directory scan + sync publish + warning on every batch forever:
    after the per-bucket retry budget, adoption defers to the next bucket
    boundary."""
    from frl_distributed_ml_scaffold_tpu.data import streaming

    d = str(tmp_path)
    _write_shard(d, 0)
    corpus = StreamingShardCorpus(d, "train", "images", refresh_every=100)
    corpus._proto.agree = lambda bucket: (5, 0)  # never servable locally
    step = 100
    for _ in range(streaming.RETRY_BUDGET_PER_BUCKET):
        corpus.maybe_refresh(step)
        assert corpus._next_refresh == step + 1  # retrying next batch
        step = corpus._next_refresh
    corpus.maybe_refresh(step)
    assert corpus._next_refresh == 200, "budget spent: defer to boundary"
    assert corpus.state()["skew_deferrals"] == (
        streaming.RETRY_BUDGET_PER_BUCKET + 1
    )
    # Fresh bucket, fresh budget.
    corpus.maybe_refresh(200)
    assert corpus._next_refresh == 201


def test_streaming_leader_repairs_stale_anchor_window(tmp_path, monkeypatch):
    """The LEADER must overwrite a leftover different-anchor window (its
    count is incomparable with the current corpus) once every live host
    has published the new anchor — otherwise the followers' anchor guard
    would spin to the deadline on a state the leader could repair."""
    import json

    import jax

    d = str(tmp_path)
    _write_shard(d, 3)  # current corpus anchors at 3
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    os.makedirs(os.path.join(d, ".stream_sync"), exist_ok=True)
    with open(
        os.path.join(d, ".stream_sync", "train_images_window.json"), "w"
    ) as fh:
        json.dump({"count": 9, "anchor": 0, "activate_at_bucket": 0}, fh)
    with open(
        os.path.join(d, ".stream_sync", "train_images_host_1.json"), "w"
    ) as fh:
        json.dump({"count": 1, "anchor": 3}, fh)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    leader = StreamingShardCorpus(d, "train", "images", refresh_every=4)
    assert leader.n == 8
    win = json.load(
        open(os.path.join(d, ".stream_sync", "train_images_window.json"))
    )
    assert win["anchor"] == 3 and win["count"] == 1


def test_streaming_initial_accepts_matching_anchor_window(tmp_path,
                                                          monkeypatch):
    """Control for the stale-anchor guard: a same-anchor window from an
    earlier run IS servable (append-only corpus) and must still be adopted
    without waiting for a live leader."""
    import json

    import jax

    d = str(tmp_path)
    _write_shard(d, 0)
    _write_shard(d, 1)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    os.makedirs(os.path.join(d, ".stream_sync"), exist_ok=True)
    with open(
        os.path.join(d, ".stream_sync", "train_images_window.json"), "w"
    ) as fh:
        json.dump({"count": 1, "anchor": 0, "activate_at_bucket": 0}, fh)
    corpus = StreamingShardCorpus(d, "train", "images", refresh_every=4)
    assert corpus.n == 8  # the 1-shard window from the previous run


def test_streaming_token_bin_grows(tmp_path):
    """The LM tier's online ingestion: a tokenizer keeps APPENDING to
    {split}.bin; the loader's visible window widens (rounded down to
    TOKEN_BLOCK so a half-flushed tail is never sampled) and freezes
    between refreshes."""
    from frl_distributed_ml_scaffold_tpu.data.lm import (
        append_token_bin,
        write_token_bin,
    )
    from frl_distributed_ml_scaffold_tpu.data.streaming import (
        TOKEN_BLOCK,
        StreamingTokenBin,
    )

    path = os.path.join(str(tmp_path), "train.bin")
    rng = np.random.default_rng(0)
    write_token_bin(path, rng.integers(0, 100, TOKEN_BLOCK + 100),
                    vocab_size=100)
    tb = StreamingTokenBin(path, np.uint16, refresh_every=10)
    assert len(tb) == TOKEN_BLOCK  # tail below a block stays invisible

    append_token_bin(path, rng.integers(0, 100, 2 * TOKEN_BLOCK))
    tb.maybe_refresh(5)
    assert len(tb) == TOKEN_BLOCK  # frozen between refreshes
    tb.maybe_refresh(10)
    assert len(tb) == 3 * TOKEN_BLOCK
    assert tb.state() == {"tokens": 3 * TOKEN_BLOCK, "skew_deferrals": 0}

    # The appender must refuse ids that don't fit the pinned dtype/vocab.
    with pytest.raises(ValueError, match="vocab_size"):
        append_token_bin(path, np.array([101]))


def test_token_bin_dtype_sized_from_vocab_not_first_chunk(tmp_path):
    """A declared 100k vocab must pin uint32 even when the first chunk's
    ids happen to fit uint16 — else a later legal append wedges the
    stream on an accidental dtype choice."""
    from frl_distributed_ml_scaffold_tpu.data.lm import (
        append_token_bin,
        write_token_bin,
    )

    path = os.path.join(str(tmp_path), "train.bin")
    write_token_bin(path, np.arange(100), vocab_size=100_000)
    append_token_bin(path, np.array([70_000]))  # legal id, needs uint32
    mm = np.memmap(path, dtype=np.uint32, mode="r")
    assert int(mm[-1]) == 70_000


def test_streaming_lm_loader_end_to_end(tmp_path):
    """TokenBinLM with data.streaming=true samples only the visible
    window and widens to appended tokens at the refresh boundary."""
    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
    from frl_distributed_ml_scaffold_tpu.data.lm import (
        TokenBinLM,
        append_token_bin,
        write_token_bin,
    )
    from frl_distributed_ml_scaffold_tpu.data.streaming import TOKEN_BLOCK

    path = os.path.join(str(tmp_path), "train.bin")
    # First block all-zeros, appended block all-ones: batch contents
    # reveal which window a sample came from.
    write_token_bin(path, np.zeros(TOKEN_BLOCK, np.int64), vocab_size=4)
    cfg = DataConfig(
        name="lm", global_batch_size=4, seq_len=64, vocab_size=4,
        data_dir=str(tmp_path), streaming=True, streaming_refresh_every=4,
        prefetch=0,
    )
    loader = TokenBinLM(cfg, split="train")
    assert not loader.is_synthetic
    assert int(loader.batch(0, 4)["tokens"].max()) == 0

    append_token_bin(path, np.ones(TOKEN_BLOCK, np.int64))
    for step in range(1, 4):
        assert int(loader.batch(step, 4)["tokens"].max()) == 0
    seen_one = any(
        int(loader.batch(step, 4)["tokens"].max()) == 1
        for step in range(4, 40)
    )
    assert seen_one  # widened window reaches the appended tokens


def test_streaming_loader_end_to_end(tmp_path):
    d = str(tmp_path)
    _write_shard(d, 0, n=16, size=8)
    cfg = DataConfig(
        name="imagenet", global_batch_size=4, image_size=8, channels=3,
        num_classes=16, data_dir=d, streaming=True,
        streaming_refresh_every=4, prefetch=0,
    )
    loader = ImageNet(cfg, split="train")
    assert not loader.is_synthetic
    b0 = loader.batch(0, 4)
    assert b0["image"].shape == (4, 8, 8, 3)
    assert set(np.unique(b0["label"])) <= {0}

    _write_shard(d, 1, n=16, size=8)
    # Steps before the refresh boundary still sample the old window...
    for step in range(1, 4):
        assert set(np.unique(loader.batch(step, 4)["label"])) <= {0}
    # ...and from the boundary on, shard 1's labels appear (sample enough
    # batches that missing them is a ~1e-10 event, not a flake).
    seen = set()
    for step in range(4, 40):
        seen |= set(np.unique(loader.batch(step, 4)["label"]))
    assert seen == {0, 1}, seen
