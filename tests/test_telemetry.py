"""Telemetry tier (ISSUE 7): the metrics/tracing layer across train,
serve, and elastic.

Four layers, mirroring the subsystem:

- **Registry**: counter/gauge/histogram semantics, log2-bucket quantile
  estimation, enabled=False no-ops, reset; the Prometheus text format is
  golden-tested byte-for-byte (tests/golden/telemetry_snapshot.prom).
- **Watchdog**: a silent loop fires exactly once per silence (counter +
  faulthandler dump + metric snapshot in the dump file); a beating loop
  never fires.
- **Serving**: the engine exports TTFT/TPOT histograms, occupancy/HBM/
  bytes-per-slot gauges and grow/graft counters through BOTH exporters;
  completions carry ttft/tpot SLO columns; telemetry-on decode is
  token-identical to telemetry-off with step time within noise (the
  overhead pin).
- **Trainer/elastic**: fit() writes telemetry.jsonl + metrics.prom with
  the data-wait/compute split and MFU; tools/telemetry_report.py renders
  them; the membership heartbeat-age gauge tracks stale peers.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.obs

from frl_distributed_ml_scaffold_tpu.telemetry import (
    LOG2_LATENCY_BUCKETS_S,
    MetricsRegistry,
    StallWatchdog,
    Timeline,
    jsonl_record,
    prometheus_text,
    write_prometheus_file,
)

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


# ---------------------------------------------------------------- registry


@pytest.mark.fast
def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("x_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="< 0"):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(7)
    g.set(3)
    assert g.value == 3.0
    h = reg.histogram("lat")
    assert h.buckets == LOG2_LATENCY_BUCKETS_S
    h.observe(0.001)
    h.observe(100.0)  # past the last bound -> +Inf bucket
    assert h.count == 2 and h.sum == pytest.approx(100.001)
    # Same name returns the same object; a type conflict refuses.
    assert reg.counter("x_total") is c
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x_total")


@pytest.mark.fast
def test_histogram_quantiles_within_bucket_resolution():
    """The log2 estimator must bracket the true quantile within its
    containing bucket (the 2x-granularity contract) and clamp the +Inf
    bucket to the last finite bound."""
    reg = MetricsRegistry()
    h = reg.histogram("lat")
    vals = [0.001] * 50 + [0.1] * 50
    for v in vals:
        h.observe(v)
    for q, true in ((0.25, 0.001), (0.75, 0.1)):
        est = h.quantile(q)
        # true value's bucket: (lo, hi] with hi = smallest bound >= true
        hi = min(b for b in h.buckets if b >= true)
        lo = max([b for b in h.buckets if b < hi], default=0.0)
        assert lo <= est <= hi, (q, est, lo, hi)
    h2 = reg.histogram("inf_heavy")
    h2.observe(1e9)
    assert h2.quantile(0.99) == h2.buckets[-1]
    assert reg.histogram("empty").quantile(0.5) == 0.0


@pytest.mark.fast
def test_disabled_registry_noops_and_reset():
    off = MetricsRegistry(enabled=False)
    off.counter("c").inc()
    off.gauge("g").set(5)
    off.histogram("h").observe(1.0)
    assert off.counter("c").value == 0.0
    assert off.histogram("h").count == 0
    reg = MetricsRegistry()
    reg.counter("c").inc(4)
    reg.histogram("h").observe(0.5)
    reg.reset()
    assert reg.counter("c").value == 0.0
    assert reg.histogram("h").count == 0
    assert reg.histogram("h").quantile(0.5) == 0.0


@pytest.mark.fast
def test_prometheus_text_matches_golden():
    """The acceptance golden: the text exposition format byte-for-byte
    (cumulative buckets, _sum/_count, HELP/TYPE headers, sorted names).
    Regenerate deliberately if the format changes — this is the contract
    scrape configs parse."""
    reg = MetricsRegistry()
    c = reg.counter("serve_completed_total", help="requests finished")
    c.inc()
    c.inc(4)
    g = reg.gauge("serve_slot_occupancy", help="active slots / num_slots")
    g.set(0.75)
    h = reg.histogram(
        "serve_tpot_seconds",
        help="per-output-token latency over live slots (decode steps)",
        buckets=(0.001, 0.004, 0.016, 0.064, 0.256),
    )
    for v in (0.0005, 0.002, 0.002, 0.01, 0.05, 1.5):
        h.observe(v)
    golden = open(os.path.join(GOLDEN, "telemetry_snapshot.prom")).read()
    assert prometheus_text(reg) == golden


@pytest.mark.fast
def test_prometheus_text_never_tears_under_concurrent_observes():
    """Regression for the graft-lint concurrency audit of
    telemetry/metrics.py: ``prometheus_text`` renders ENTIRELY under the
    registry lock. The previous shape copied the metrics dict under the
    lock but read ``_counts``/``count``/``sum`` outside it, so a scrape
    racing ``observe()`` could publish a histogram whose bucket rows
    disagree with ``_count``/``_sum``. Every observation here adds
    exactly 1.0, so any torn render shows ``sum != count`` or an +Inf
    cumulative != count."""
    import threading

    reg = MetricsRegistry()
    h = reg.histogram("tear_check_seconds", buckets=(0.5, 2.0))
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            h.observe(1.0)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 1.0
        renders = 0
        while time.monotonic() < deadline:
            rows = dict(
                line.rsplit(" ", 1)
                for line in prometheus_text(reg).strip().splitlines()
                if not line.startswith("#")
            )
            count = int(rows["tear_check_seconds_count"])
            assert float(rows["tear_check_seconds_sum"]) == float(count)
            assert int(rows['tear_check_seconds_bucket{le="+Inf"}']) == count
            renders += 1
    finally:
        stop.set()
        t.join(5)
    assert renders > 50 and h.count > 0  # the race was actually exercised


@pytest.mark.fast
def test_snapshot_jsonl_roundtrip_and_prom_file(tmp_path):
    """snapshot() survives a JSONL round trip with the raw bucket counts
    intact (the telemetry_report merge contract), and the .prom sidecar
    is written atomically."""
    from frl_distributed_ml_scaffold_tpu.utils.logging import JsonlWriter

    reg = MetricsRegistry()
    reg.counter("n_total").inc(3)
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    path = tmp_path / "t.jsonl"
    w = JsonlWriter(str(path))
    w.write(jsonl_record(reg, step=7))
    w.close()
    rec = json.loads(path.read_text())
    assert rec["event"] == "telemetry" and rec["step"] == 7
    m = rec["metrics"]
    assert m["n_total"] == 3.0
    assert m["lat"]["count"] == 3
    assert m["lat"]["buckets"] == {"0.1": 1, "1": 2, "+Inf": 3}
    prom = tmp_path / "m.prom"
    write_prometheus_file(reg, str(prom))
    assert 'lat_bucket{le="+Inf"} 3' in prom.read_text()
    assert not (tmp_path / "m.prom.tmp").exists()


@pytest.mark.fast
def test_timeline_ring_buffer_and_drain():
    tl = Timeline(capacity=4)
    for i in range(6):
        tl.event("phase", dur_s=0.1, step=i)
    assert len(tl) == 4 and tl.dropped == 2
    assert [r["step"] for r in tl.tail(2)] == [4, 5]
    recs = tl.drain()
    assert [r["step"] for r in recs] == [2, 3, 4, 5]
    assert all(r["event"] == "timeline" for r in recs)
    assert len(tl) == 0
    off = Timeline(enabled=False)
    off.event("x")
    assert len(off) == 0


@pytest.mark.fast
def test_timeline_double_drain_and_post_wraparound_refill():
    """Satellite: drain() is idempotent on empty (the crash-path finally
    block re-drains after the log-boundary drain — must yield [] not
    duplicates), and the ring keeps accepting/counting after wrapping."""
    tl = Timeline(capacity=3)
    for i in range(5):
        tl.event("p", step=i)
    first = tl.drain()
    assert [r["step"] for r in first] == [2, 3, 4]
    assert tl.drain() == [] and tl.drain() == []  # double (and triple)
    assert tl.dropped == 2  # dropped survives drains: it is a counter
    # Refill after wraparound+drain behaves like a fresh ring.
    for i in range(4):
        tl.event("q", step=10 + i)
    assert tl.dropped == 3
    assert [r["step"] for r in tl.drain()] == [11, 12, 13]
    assert tl.tail() == []


@pytest.mark.fast
def test_jsonl_writer_truncates_partial_line_on_reopen(tmp_path):
    """Satellite: a run killed mid-write leaves a torn final line; the
    next JsonlWriter open repairs the file (truncate to the last
    newline) so every line stays parseable across the crash."""
    from frl_distributed_ml_scaffold_tpu.utils.logging import JsonlWriter

    path = tmp_path / "t.jsonl"
    w = JsonlWriter(str(path))
    w.write({"step": 1})
    w.write({"step": 2})
    w.close()
    with open(path, "a") as fh:  # the torn write (no trailing newline)
        fh.write('{"step": 3, "partial')
    w2 = JsonlWriter(str(path))
    w2.write({"step": 4})
    w2.close()
    recs = [json.loads(l) for l in open(path)]  # every line parses
    assert [r["step"] for r in recs] == [1, 2, 4]
    # A torn FIRST line (no complete record at all) truncates to empty.
    p2 = tmp_path / "torn.jsonl"
    p2.write_text('{"never finished')
    w3 = JsonlWriter(str(p2))
    w3.write({"ok": 1})
    w3.close()
    assert [json.loads(l)["ok"] for l in open(p2)] == [1]
    # A cleanly-closed file reopens untouched.
    w4 = JsonlWriter(str(path))
    w4.close()
    assert [r["step"] for r in (json.loads(l) for l in open(path))] == [1, 2, 4]


# ---------------------------------------------------------------- watchdog


@pytest.mark.fast
def test_watchdog_fires_once_per_stall_with_dump(tmp_path):
    """A silent loop: exactly one stalls_total increment per silence
    window, and the dump carries the faulthandler traceback + the live
    metric snapshot + the timeline tail."""
    reg = MetricsRegistry()
    reg.counter("serve_decode_steps_total").inc(5)
    tl = Timeline()
    tl.event("decode", dur_s=0.01, step=41)
    dump = tmp_path / "stall.txt"
    wd = StallWatchdog(
        0.1, name="t", registry=reg, timeline=tl,
        dump_path=str(dump), poll_s=0.02,
    )
    try:
        wd.beat()
        time.sleep(0.5)  # several polls past the deadline: still ONE fire
        assert wd.fired == 1
        assert reg.counter("stalls_total").value == 1
        text = dump.read_text()
        assert "watchdog[t] stall" in text
        assert "Current thread" in text  # faulthandler traceback
        assert "serve_decode_steps_total" in text  # metric snapshot
        assert '"name": "decode"' in text  # timeline tail
        wd.beat()  # re-arm; a second silence fires again
        time.sleep(0.3)
        assert wd.fired == 2
    finally:
        wd.stop()


@pytest.mark.fast
def test_watchdog_healthy_loop_never_fires():
    reg = MetricsRegistry()
    wd = StallWatchdog(0.5, registry=reg, poll_s=0.02)
    try:
        for _ in range(25):
            wd.beat()
            time.sleep(0.01)
    finally:
        wd.stop()
    assert wd.fired == 0
    assert reg.counter("stalls_total").value == 0


@pytest.mark.fast
def test_watchdog_disabled_spawns_no_thread():
    wd = StallWatchdog(0.0)
    assert not wd.enabled
    wd.beat()
    wd.stop()  # no-op, no thread to join


@pytest.mark.fast
def test_watchdog_first_beat_grace_absorbs_compile():
    """Satellite: before the FIRST beat the deadline is scaled by
    first_beat_scale (the step-0 compile window) — a slow first beat
    does not fire; a LATER silence of the same length does."""
    reg = MetricsRegistry()
    wd = StallWatchdog(
        0.2, registry=reg, poll_s=0.02, first_beat_scale=10.0
    )
    try:
        # 3x the deadline, but 1.4 s under the 10x first-beat grace —
        # wide enough that a loaded CI host cannot false-fire it.
        time.sleep(0.6)
        assert wd.fired == 0
        wd.beat()  # "compile finished, step 0 dispatched"
        time.sleep(0.6)  # the SAME silence after a beat: normal deadline
        assert wd.fired == 1
    finally:
        wd.stop()


@pytest.mark.fast
def test_watchdog_unbeaten_still_fires_at_scaled_deadline():
    """The grace is a multiplier, not a disable: a child that never
    beats at all (hung before step 0) fires once the scaled deadline
    passes."""
    reg = MetricsRegistry()
    wd = StallWatchdog(
        0.1, registry=reg, poll_s=0.02, first_beat_scale=3.0
    )
    try:
        time.sleep(0.6)  # past 3 * 0.1
        assert wd.fired == 1
        assert reg.counter("stalls_total").value == 1
    finally:
        wd.stop()


# ----------------------------------------------------------------- serving


@pytest.fixture(scope="module")
def gpt():
    import jax

    from _jit import jit_init
    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=4, hidden_dim=64,
            seq_len=64, dropout=0.0,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params


def _serve(model, params, workload, **kw):
    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    eng = ServingEngine(model, params, num_slots=3, temperature=0.0, **kw)
    for prompt, n_new in workload:
        eng.submit(prompt, n_new)
    done = {c.id: c for c in eng.run()}
    return eng, done


def _workload(n=6, seed=3):
    rng = np.random.default_rng(seed)
    return [
        (
            rng.integers(0, 64, size=int(rng.integers(2, 12))).astype(np.int32),
            int(rng.integers(2, 8)),
        )
        for _ in range(n)
    ]


def test_engine_exports_serving_catalog_via_both_exporters(gpt):
    """The acceptance gate: TTFT/TPOT histograms, slot-occupancy /
    bytes-per-slot / HBM gauges and grow/graft counters present in BOTH
    the JSONL snapshot and the Prometheus text, with counts that agree
    with the completions."""
    model, params = gpt
    work = _workload()
    eng, done = _serve(model, params, work)
    try:
        assert len(done) == len(work)
        snap = eng.telemetry.snapshot()
        # Histogram counts tie out: one TTFT per admitted request, one
        # TPOT observation per generated-token-after-the-first.
        n_decode_tokens = sum(
            len(c.tokens) - c.prompt_len - 1 for c in done.values()
        )
        assert snap["serve_ttft_seconds"]["count"] == len(work)
        assert snap["serve_tpot_seconds"]["count"] == n_decode_tokens
        assert snap["serve_completed_total"] == len(work)
        assert snap["serve_prefill_total"] == len(work)
        assert snap["serve_cache_graft_total"] == len(work)
        assert snap["serve_bytes_per_slot"] == eng.bytes_per_slot() > 0
        assert 0.0 <= snap["serve_slot_occupancy"] <= 1.0
        for k in ("serve_hbm_in_use_gib", "serve_hbm_peak_gib",
                  "serve_queue_depth", "stalls_total"):
            assert k in snap  # registered up front, 0 on CPU sim
        txt = prometheus_text(eng.telemetry)
        for name in (
            "serve_ttft_seconds_bucket", "serve_tpot_seconds_sum",
            "serve_slot_occupancy", "serve_bytes_per_slot",
            "serve_hbm_in_use_gib", "serve_bucket_grow_total",
            "serve_cache_graft_total", "stalls_total",
        ):
            assert name in txt, name
        # The per-step timeline recorded the serving phases.
        names = {r["name"] for r in eng.timeline.tail(10**6)}
        assert {"prefill", "decode", "retire"} <= names
    finally:
        eng.close()


def test_completion_slo_columns_consistent_with_latencies(gpt):
    """ttft_s is the prefill latency; tpot p50/p99 bracket the true
    decode-step percentiles within their log2 bucket (the estimator's
    documented resolution)."""
    from frl_distributed_ml_scaffold_tpu.telemetry import (
        LOG2_LATENCY_BUCKETS_S as B,
    )

    model, params = gpt
    eng, done = _serve(model, params, _workload())
    try:
        for c in done.values():
            lat = c.token_latencies_s
            assert c.ttft_s == lat[0]
            decode = lat[1:]
            if not decode:
                assert c.tpot_p50_s == 0.0 and c.tpot_p99_s == 0.0
                continue
            assert c.tpot_p99_s >= c.tpot_p50_s > 0.0
            for est, q in ((c.tpot_p50_s, 50), (c.tpot_p99_s, 99)):
                # inverted_cdf matches the estimator's semantics (smallest
                # observation whose cumulative count reaches q*n); default
                # linear interpolation invents midpoints between distant
                # observations that no bucket estimator can reproduce.
                true = float(
                    np.percentile(decode, q, method="inverted_cdf")
                )
                hi = min(b for b in B if b >= min(true, B[-1]))
                lo = max([b for b in B if b < hi], default=0.0)
                # estimate lives in [true's bucket lo, bucket hi] modulo
                # interpolation across equal-count neighbors; assert the
                # 2x-granularity contract loosely: within one bucket.
                assert lo / 2 <= est <= hi * 2, (est, true, lo, hi)
    finally:
        eng.close()


def test_engine_telemetry_overhead_pin(gpt):
    """The overhead pin: telemetry-on vs telemetry-off serve the same
    workload TOKEN-IDENTICALLY (telemetry must never touch the jitted
    programs), and the measured-pass per-token latency stays within
    noise (generous 3x bound on medians — what it catches is a metric
    accidentally forcing a device sync or landing inside a trace)."""
    model, params = gpt
    work = _workload(n=6, seed=11)
    runs = {}
    for label, reg in (
        ("on", None),  # engine default: enabled registry
        ("off", MetricsRegistry(enabled=False)),
    ):
        eng, _ = _serve(model, params, work, telemetry=reg)  # warm pass
        eng.reset_cache()
        for prompt, n_new in work:
            eng.submit(prompt, n_new)
        done = {c.id: c for c in eng.run()}
        runs[label] = (
            {rid: c.tokens for rid, c in done.items()},
            [dt for c in done.values() for dt in c.token_latencies_s[1:]],
        )
        eng.close()
    tokens_on, lat_on = runs["on"]
    tokens_off, lat_off = runs["off"]
    assert sorted(tokens_on) == sorted(tokens_off)
    for rid in tokens_on:
        np.testing.assert_array_equal(
            tokens_on[rid], tokens_off[rid],
            err_msg=f"telemetry changed request {rid}'s tokens",
        )
    med_on = float(np.median(lat_on))
    med_off = float(np.median(lat_off))
    assert med_on <= 3.0 * max(med_off, 1e-9), (med_on, med_off)


def test_engine_watchdog_fires_on_decode_silence(gpt, tmp_path):
    """Engine wiring: a stalled engine (no step() calls) trips the
    watchdog — stalls_total increments and the dump lands; an engine
    that keeps stepping does not fire."""
    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    model, params = gpt
    dump = tmp_path / "serve_stall.txt"
    eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0,
        stall_timeout_s=0.15, stall_dump_path=str(dump),
    )
    try:
        eng.submit(np.arange(4, dtype=np.int32), 30)
        eng.step()  # beats
        time.sleep(0.6)  # silence: the "decode loop wedged" scenario
        assert eng.telemetry.counter("stalls_total").value >= 1
        assert "watchdog[serve] stall" in dump.read_text()
        # Recovery: serving still completes after the stall report.
        done = eng.run()
        assert len(done) == 1
    finally:
        eng.close()


# ---------------------------------------------------------- trainer tier


@pytest.mark.fast
def test_step_timer_summary_reports_tail_percentiles():
    """Satellite 2: p50/p95/p99 in StepTimer.summary(), ordered and
    consistent with the recorded times."""
    from frl_distributed_ml_scaffold_tpu.utils.timing import StepTimer

    t = StepTimer(warmup=0)
    t._times = [0.01] * 96 + [0.5] * 4  # 4% straggler steps
    s = t.summary(samples_per_step=8)
    assert s["step_time_p50_s"] == s["step_time_median_s"] == 0.01
    assert s["step_time_p95_s"] <= s["step_time_p99_s"]
    assert s["step_time_p99_s"] > 0.4  # the tail the mean hides
    assert s["step_time_mean_s"] < 0.05
    assert s["samples_per_sec_per_chip"] > 0


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    """One telemetry-enabled trainer run shared by the trainer-tier tests
    (>= 2 post-warmup log windows so MFU and the step histogram fill)."""
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    workdir = tmp_path_factory.mktemp("telemetry_run")
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=12",
            "trainer.log_every=3",
            "trainer.stall_timeout_s=120",
            "data.global_batch_size=32",
            "checkpoint.enabled=false",
            f"workdir={workdir}",
        ],
    )
    _, last = Trainer(cfg).fit()
    return os.path.join(workdir, cfg.name), last


def test_trainer_fit_exports_telemetry(telemetry_run):
    """The trainer tier end-to-end: metrics.jsonl carries the p50/p95/p99
    + data-wait/compute split + MFU extras; telemetry.jsonl carries
    timeline phases and cumulative snapshots; metrics.prom scrapes."""
    run_dir, last = telemetry_run
    for k in ("step_time_p50_s", "step_time_p95_s", "step_time_p99_s",
              "data_wait_s", "compute_s", "mfu"):
        assert k in last, (k, last)
    assert last["mfu"] > 0
    assert last["compute_s"] >= 0 and last["data_wait_s"] >= 0
    recs = [
        json.loads(l)
        for l in open(os.path.join(run_dir, "telemetry.jsonl"))
    ]
    kinds = {r["event"] for r in recs}
    assert kinds == {"timeline", "telemetry"}
    phases = {r["name"] for r in recs if r["event"] == "timeline"}
    assert {"load_batch", "dispatch"} <= phases
    final = [r for r in recs if r["event"] == "telemetry"][-1]["metrics"]
    assert final["train_steps_total"] == 12
    assert final["train_step_seconds"]["count"] >= 2  # post-warmup windows
    assert final["train_data_wait_seconds"]["count"] == 12
    assert final["stalls_total"] == 0  # healthy run: watchdog never fired
    assert final["train_mfu"] > 0
    prom = open(os.path.join(run_dir, "metrics.prom")).read()
    for name in ("train_step_seconds_bucket", "train_data_wait_seconds_sum",
                 "train_mfu", "train_hbm_peak_gib", "stalls_total"):
        assert name in prom, name


def test_telemetry_report_renders_run(telemetry_run, tmp_path, capsys):
    """tools/telemetry_report.py over the run's JSONL: percentile table
    + --json machine output whose quantiles come from the raw buckets."""
    import sys as _sys

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools not in _sys.path:
        _sys.path.insert(0, tools)
    import telemetry_report

    run_dir, _ = telemetry_run
    out = tmp_path / "rep.json"
    rc = telemetry_report.main(
        [os.path.join(run_dir, "telemetry.jsonl"), "--json", str(out)]
    )
    assert rc == 0
    text = capsys.readouterr().out
    assert "train_step_seconds" in text and "p99_s" in text
    rep = json.loads(out.read_text())
    names = {h["name"] for h in rep["histograms"]}
    assert {"train_step_seconds", "train_data_wait_seconds"} <= names
    for h in rep["histograms"]:
        assert h["p50_s"] <= h["p90_s"] <= h["p95_s"] <= h["p99_s"]
        if h["count"]:
            assert h["p99_s"] > 0
    assert rep["timeline"]["dispatch"]["count"] == 12
    assert rep["scalars"]["train_steps_total"] == 12


@pytest.mark.fast
def test_telemetry_report_bucket_quantile_math():
    """The report's from-serialized-buckets estimator agrees with the
    live Histogram estimator it reconstructs."""
    import sys as _sys

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools not in _sys.path:
        _sys.path.insert(0, tools)
    from telemetry_report import bucket_quantile

    reg = MetricsRegistry()
    h = reg.histogram("x")
    rng = np.random.default_rng(0)
    for v in rng.lognormal(mean=-6, sigma=1.5, size=500):
        h.observe(float(v))
    snap = reg.snapshot()["x"]
    for q in (0.5, 0.9, 0.99):
        assert bucket_quantile(
            snap["buckets"], snap["count"], q
        ) == pytest.approx(h.quantile(q))


# --------------------------------------------------------------- elastic


@pytest.mark.fast
def test_membership_heartbeat_age_gauge(tmp_path):
    """The elastic tier's scrape signal: after a liveness read the gauge
    carries the oldest LIVE member heartbeat age. An evicted (stale) peer
    must NOT feed the gauge: a hard-crashed host's file is never unlinked
    (only clean retire() removes it), so folding its ever-growing age in
    would saturate the gauge forever and mask live-member lag — evictions
    show up in the shrink/reform counters, not here."""
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import _Membership

    reg = MetricsRegistry()
    m = _Membership(str(tmp_path), uid=0, endpoint="h:1", registry=reg)
    m.beat()
    surv = m.survivors(peer_timeout_s=60.0)
    assert [r["uid"] for r in surv] == [0]
    age_fresh = reg.gauge("elastic_heartbeat_age_s").value
    assert 0.0 <= age_fresh < 5.0
    # A peer whose heartbeat is 120 s old: evicted from the survivor set,
    # and the gauge keeps tracking the live members only.
    peer = os.path.join(str(tmp_path), "members", "host_1.json")
    with open(peer, "w") as fh:
        json.dump({"uid": 1, "endpoint": "h:2", "ts": 0.0}, fh)
    old = time.time() - 120.0
    os.utime(peer, (old, old))
    surv = m.survivors(peer_timeout_s=60.0)
    assert [r["uid"] for r in surv] == [0]
    assert reg.gauge("elastic_heartbeat_age_s").value < 5.0
    # A LIVE-but-lagging peer (30 s < timeout) is what the gauge warns
    # about: stays in the survivor set, age shows up.
    lag = time.time() - 30.0
    os.utime(peer, (lag, lag))
    surv = m.survivors(peer_timeout_s=60.0)
    assert [r["uid"] for r in surv] == [0, 1]
    assert 20.0 < reg.gauge("elastic_heartbeat_age_s").value <= 60.0
    m.retire()


# ---------------------------------------------------------- trace_analyze


@pytest.mark.fast
def test_trace_analyze_lane_report_matches_golden():
    """Satellite 3's golden: the --json lane structure on fixed synthetic
    spans is byte-stable across PRs, so overlap classifications diff."""
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from tools.trace_analyze import lane_report

    ms = int(1e9)
    events = [
        ("fusion.loop_multiply.9", 0 * ms, 6 * ms),
        ("collective-permute-start.1", 1 * ms, 3 * ms),
        ("collective-permute-done.2", 8 * ms, 10 * ms),
        ("all-gather-fusion.3", 5 * ms, 7 * ms),
        ("custom-call.decode_kernel.1", 10 * ms, 12 * ms),
        ("scatter.9", 12 * ms, 13 * ms),
    ]
    golden = json.load(
        open(os.path.join(GOLDEN, "trace_analyze_lane.json"))
    )
    assert lane_report(events, top_n=4) == golden


@pytest.mark.fast
def test_trace_analyze_lane_report_no_decode_lane():
    """A training lane (no decode kernel) reports decode: null — the
    field is present (schema-stable) but unclassified."""
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from tools.trace_analyze import lane_report

    rep = lane_report([("fusion.matmul.1", 0, int(1e9))])
    assert rep["decode"] is None
    assert rep["overlap"] == {}
    assert rep["top_ops"][0]["op"] == "fusion.matmul.1"
