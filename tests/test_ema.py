"""EMA of params (trainer.ema_decay): updated inside the compiled step,
sharded like the params, used by evaluation, checkpointed with the state."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import jax
import jax.numpy as jnp
import numpy as np

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


def mnist_trainer(tmp_path, extra=()):
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=6",
            "trainer.log_every=100",
            "data.global_batch_size=64",
            "model.hidden_sizes=32",
            "precision.policy=fp32",
            "trainer.ema_decay=0.5",
            f"workdir={tmp_path}",
        ]
        + list(extra),
    )
    return Trainer(cfg)


def test_ema_recursion_matches_manual(tmp_path):
    trainer = mnist_trainer(tmp_path)
    state = trainer.init_state()
    expected = jax.device_get(state.params)  # ema starts as params
    for step in range(3):
        batch = trainer.pipeline.global_batch(step)
        state, _ = trainer.train_step(state, batch)
        p = jax.device_get(state.params)
        expected = jax.tree.map(lambda e, q: 0.5 * e + 0.5 * q, expected, p)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6),
        expected,
        jax.device_get(state.ema_params),
    )
    # EMA trails the live params (it still remembers the init).
    diffs = jax.tree.leaves(
        jax.tree.map(
            lambda e, q: float(jnp.max(jnp.abs(e - q))),
            state.ema_params,
            state.params,
        )
    )
    assert max(diffs) > 0


def test_ema_shards_like_params(tmp_path):
    trainer = mnist_trainer(
        tmp_path,
        ["mesh.data=4", "mesh.fsdp=2", "parallel.param_sharding=fsdp",
         "parallel.fsdp_min_size=1"],
    )
    state = trainer.init_state()
    p_leaves = jax.tree.leaves(state.params)
    e_leaves = jax.tree.leaves(state.ema_params)
    assert len(p_leaves) == len(e_leaves)
    for p, e in zip(p_leaves, e_leaves):
        assert p.sharding == e.sharding, (p.sharding, e.sharding)


def test_eval_uses_ema_weights(tmp_path):
    trainer = mnist_trainer(tmp_path)
    state = trainer.init_state()
    for step in range(4):
        batch = trainer.pipeline.global_batch(step)
        state, _ = trainer.train_step(state, batch)
    with_ema = trainer.evaluate(state, num_steps=2)
    assert with_ema == trainer.evaluate(state, num_steps=2)  # deterministic
    # Evaluating with the EMA slot holding the LIVE weights must differ —
    # i.e. evaluate() really reads ema_params, not params (the pytree
    # structure stays fixed so the compiled eval step is reused).
    live = trainer.evaluate(
        state.replace(ema_params=state.params), num_steps=2
    )
    assert with_ema != live


def test_ema_checkpoint_roundtrip(tmp_path):
    trainer = mnist_trainer(
        tmp_path,
        ["checkpoint.enabled=true", "checkpoint.save_every=2",
         "checkpoint.async_save=false"],
    )
    state = trainer.init_state()
    for step in range(2):
        batch = trainer.pipeline.global_batch(step)
        state, _ = trainer.train_step(state, batch)
    trainer.checkpointer.save(2, state, force=True)
    trainer.checkpointer.wait()

    fresh = mnist_trainer(
        tmp_path,
        ["checkpoint.enabled=true", "checkpoint.async_save=false"],
    )
    restored = fresh.checkpointer.restore_or_init(fresh)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=0, rtol=0
        ),
        jax.device_get(state.ema_params),
        jax.device_get(restored.ema_params),
    )


def test_ema_toggle_across_resume(tmp_path):
    """Flipping trainer.ema_decay across a resume must bridge, not abort:
    off->on seeds EMA from the restored params; on->off discards it."""
    ck = ["checkpoint.enabled=true", "checkpoint.save_every=100",
          "checkpoint.async_save=false"]

    # --- off -> on -----------------------------------------------------
    t_off = mnist_trainer(tmp_path / "a", ck + ["trainer.ema_decay=0.0"])
    s = t_off.init_state()
    for step in range(2):
        s, _ = t_off.train_step(s, t_off.pipeline.global_batch(step))
    t_off.checkpointer.save(2, s, force=True)
    t_off.checkpointer.wait()

    t_on = mnist_trainer(tmp_path / "a", ck)  # ema_decay=0.5 via helper
    restored = t_on.checkpointer.restore_or_init(t_on)
    assert restored.ema_params is not None
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(restored.ema_params),
        jax.device_get(restored.params),
    )
    # And training continues from the bridged state.
    restored, _ = t_on.train_step(restored, t_on.pipeline.global_batch(2))
    assert int(jax.device_get(restored.step)) == 3

    # --- on -> off -----------------------------------------------------
    t_on2 = mnist_trainer(tmp_path / "b", ck)
    s = t_on2.init_state()
    for step in range(2):
        s, _ = t_on2.train_step(s, t_on2.pipeline.global_batch(step))
    t_on2.checkpointer.save(2, s, force=True)
    t_on2.checkpointer.wait()

    t_off2 = mnist_trainer(tmp_path / "b", ck + ["trainer.ema_decay=0.0"])
    restored2 = t_off2.checkpointer.restore_or_init(t_off2)
    assert restored2.ema_params is None
    restored2, _ = t_off2.train_step(restored2, t_off2.pipeline.global_batch(2))
    assert int(jax.device_get(restored2.step)) == 3
