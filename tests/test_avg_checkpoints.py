"""tools/avg_checkpoints.py: the config.json -> rebuild -> params-only
restore -> average chain, end to end against a hand-computed mean."""

import json
import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "tools")
)

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


def test_avg_checkpoints_end_to_end(tmp_path, monkeypatch):
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["trainer.total_steps=6", "trainer.log_every=100",
         "checkpoint.enabled=true", "checkpoint.save_every=2",
         "data.global_batch_size=16", "model.hidden_sizes=16",
         f"workdir={tmp_path}"],
    )
    trainer = Trainer(cfg)
    trainer.fit()
    trainer.checkpointer.close()

    # Hand-computed mean of the last 2 checkpoints via full restores.
    fresh = Trainer(cfg)
    steps = fresh.checkpointer.all_steps()[-2:]
    trees = [
        jax.device_get(
            fresh.checkpointer.restore(
                fresh.state_shapes, fresh.state_shardings, s
            ).params
        )
        for s in steps
    ]
    expected = jax.tree.map(
        lambda a, b: (np.asarray(a, np.float64) + np.asarray(b, np.float64))
        / 2.0,
        *trees,
    )
    fresh.checkpointer.close()

    import avg_checkpoints

    out = str(tmp_path / "avg.msgpack")
    monkeypatch.setattr(
        sys, "argv",
        ["avg_checkpoints.py", "--workdir", str(tmp_path / "mnist_mlp"),
         "--last", "2", "--out", out],
    )
    assert avg_checkpoints.main() == 0

    from import_hf_gpt2 import load_params

    got = load_params(out)
    jax.tree.map(
        lambda g, e: np.testing.assert_allclose(
            np.asarray(g), np.asarray(e, np.float32), atol=1e-7
        ),
        got,
        expected,
    )
