"""Chunked-vocab LM loss (model.lm_loss_chunk): identical loss and grads to
the dense head, without ever materializing [B, T, vocab] logits."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig, PrecisionConfig
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.trainer.tasks import make_lm_loss

FP32 = get_policy(PrecisionConfig(policy="fp32"))
TINY = dict(
    vocab_size=96, num_layers=2, num_heads=2, hidden_dim=32, seq_len=16, dropout=0.0
)


def loss_and_grads(cfg, tokens, params):
    model = GPT(cfg, FP32)
    lf = make_lm_loss(model)
    batch = {"tokens": tokens}

    def scalar(p):
        return lf(p, {}, batch, jax.random.key(0), False)[0]

    # jit: one compiled (and persistently cached) program per chunk size
    # instead of eager op-by-op dispatch of the whole fwd+bwd.
    (loss, (metrics, _)) = jax.jit(
        lambda p: lf(p, {}, batch, jax.random.key(0), False)
    )(params)
    return loss, metrics, jax.jit(jax.grad(scalar))(params)


def test_chunked_loss_matches_dense_head():
    base = GPTConfig(**TINY)
    tokens = jax.random.randint(jax.random.key(3), (4, 17), 0, 96)
    params = GPT(base, FP32).init(
        {"params": jax.random.key(0)}, tokens[:, :-1], train=False
    )["params"]
    loss_d, met_d, g_d = loss_and_grads(base, tokens, params)
    for chunk in (4, 8, 16):
        cc = dataclasses.replace(base, lm_loss_chunk=chunk)
        loss_c, met_c, g_c = loss_and_grads(cc, tokens, params)
        np.testing.assert_allclose(loss_c, loss_d, atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(
            met_c["ce_loss"], met_d["ce_loss"], atol=1e-6, rtol=1e-6
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4),
            g_c,
            g_d,
        )


def test_chunked_loss_moe_keeps_aux():
    from frl_distributed_ml_scaffold_tpu.config.schema import MoEConfig

    cfg = dataclasses.replace(
        GPTConfig(**TINY, moe=MoEConfig(num_experts=4, top_k=2)),
        lm_loss_chunk=8,
    )
    dense = dataclasses.replace(cfg, lm_loss_chunk=0)
    tokens = jax.random.randint(jax.random.key(5), (4, 17), 0, 96)
    params = GPT(dense, FP32).init(
        {"params": jax.random.key(0)}, tokens[:, :-1], train=False
    )["params"]
    loss_c, met_c, _ = loss_and_grads(cfg, tokens, params)
    loss_d, met_d, _ = loss_and_grads(dense, tokens, params)
    np.testing.assert_allclose(loss_c, loss_d, atol=1e-6, rtol=1e-6)
    assert met_c["aux_loss"] > 0
    np.testing.assert_allclose(met_c["aux_loss"], met_d["aux_loss"], rtol=1e-6)


def test_indivisible_seq_falls_back_to_dense():
    """seq not divisible by the chunk: silently use the dense head (the
    config is a memory knob, not a correctness switch)."""
    cc = dataclasses.replace(GPTConfig(**TINY), lm_loss_chunk=5)  # 16 % 5 != 0
    tokens = jax.random.randint(jax.random.key(7), (2, 17), 0, 96)
    params = GPT(cc, FP32).init(
        {"params": jax.random.key(0)}, tokens[:, :-1], train=False
    )["params"]
    loss_c, _, _ = loss_and_grads(cc, tokens, params)
    loss_d, _, _ = loss_and_grads(GPTConfig(**TINY), tokens, params)
    np.testing.assert_allclose(loss_c, loss_d, atol=1e-7)
