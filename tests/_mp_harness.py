"""Shared harness for the real-subprocess rendezvous tests.

Both multi-process tiers (test_multiprocess.py: plain 2-process training;
test_elastic_multiprocess.py: supervised kill-and-resume) spawn worker
scripts that must rendezvous over a TCP port with identical env plumbing.
The subtleties live here once: the XLA device-count flag must be SET (not
inherited — pytest's conftest already exported device_count=8, and the
workers' own launcher only appends the flag when absent), PYTHONPATH must
keep the axon sitecustomize entries while adding the repo root, and worker
pipes must be drained concurrently with a kill-on-failure guarantee (a
blocked pipe on one worker deadlocks its peers through the collectives).
"""

import os
import socket
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def rendezvous_env(tmp_path, port, *, device_count, num_processes=2):
    """Base env for one worker process (add FRL_TPU_PROCESS_ID per worker)."""
    return {
        **os.environ,
        "FRL_TPU_COORDINATOR": f"127.0.0.1:{port}",
        "FRL_TPU_NUM_PROCESSES": str(num_processes),
        "FRL_TEST_WORKDIR": str(tmp_path),
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={device_count}",
        # Script-by-path puts tests/ on sys.path, not the repo root; keep any
        # existing entries (the axon sitecustomize lives on PYTHONPATH).
        "PYTHONPATH": REPO_ROOT
        + (os.pathsep + os.environ["PYTHONPATH"] if os.environ.get("PYTHONPATH") else ""),
    }


def run_workers(script, envs, *, timeout):
    """Spawn one worker per env, drain all pipes concurrently, return
    (returncodes, outputs). Any failure path kills the whole set — leaked
    workers would hold the rendezvous port and retry initialization for
    minutes."""
    name = os.path.join(os.path.dirname(os.path.abspath(__file__)), script)
    procs = [
        subprocess.Popen(
            [sys.executable, name],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO_ROOT,
        )
        for env in envs
    ]
    try:
        with ThreadPoolExecutor(max_workers=len(procs)) as pool:
            futures = [
                pool.submit(p.communicate, timeout=timeout) for p in procs
            ]
            outputs = [f.result(timeout=timeout + 30)[0] for f in futures]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return [p.returncode for p in procs], outputs
