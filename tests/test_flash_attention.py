"""Pallas flash attention vs. the dense reference (SURVEY §4 unit tier).

Runs the real kernel code path in Pallas interpreter mode on CPU (same
kernels the TPU compiles) and asserts forward and gradient equivalence with
``dense_attention`` — the numerics contract shared by every attention mode.
"""

from __future__ import annotations
import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.ops.flash_attention import flash_attention
from frl_distributed_ml_scaffold_tpu.ops.ring_attention import dense_attention


def _qkv(b=2, t=256, h=2, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_uneven_blocks():
    # block_q != block_k and blocks that don't divide evenly into each other
    q, k, v = _qkv(t=512)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64,
                          interpret=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_dense(causal):
    q, k, v = _qkv(t=128)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                            interpret=True)
        return (o * jnp.sin(jnp.arange(o.size).reshape(o.shape))).sum()

    def loss_dense(q, k, v):
        o = dense_attention(q, k, v, causal=causal)
        return (o * jnp.sin(jnp.arange(o.size).reshape(o.shape))).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            gf, gd, atol=5e-5, rtol=5e-4,
            err_msg=f"grad mismatch for {name}",
        )


def test_bf16_forward_close():
    q, k, v = _qkv(dtype=jnp.bfloat16, t=128)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_fallback_on_untileable_shapes():
    # T=100 has no power-of-two block divisor; the fallback must actually be
    # taken (a 100-row tile would fail Mosaic's sublane alignment on TPU).
    import importlib

    fa_mod = importlib.import_module(
        "frl_distributed_ml_scaffold_tpu.ops.flash_attention"
    )

    assert fa_mod._pick_block(100, 100) is None  # 100 = 4·25: no p2 divisor
    assert fa_mod._pick_block(24, 24) == 8  # sublane-aligned 3×8 tiling
    assert fa_mod._pick_block(1024, 256) == 256
    assert fa_mod._pick_block(96, 256) == 32

    q, k, v = _qkv(t=100, d=32)
    out = flash_attention(q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_length_adaptive_block_ladder():
    """Pin the auto block selection the on-chip sweep tuned
    (evidence_r4/flash_sweep.log → BASELINE.md long-context table):
    512 below 16k, 1024 from 16k up — at 16k/32k/64k the 1024×1024
    blocks measured +21%/+37%/+39% over 512×512 on v5e. A regression
    here silently costs a third of long-context throughput."""
    import importlib

    fa_mod = importlib.import_module(
        "frl_distributed_ml_scaffold_tpu.ops.flash_attention"
    )
    for t, want in [
        (1024, 512), (8192, 512),
        (16384, 1024), (32768, 1024), (65536, 1024),
    ]:
        assert fa_mod._auto_block(t) == want, (t, fa_mod._auto_block(t))
        # And the tileability snap keeps the preferred size whole at
        # power-of-two T (these lengths never fall down the ladder).
        assert fa_mod._pick_block(t, want) == want


def test_sharded_flash_matches_dense():
    """Under a live mesh the wrapper runs the kernel inside shard_map over
    the batch + TP-head axes — per-(b,h) local, no gather (the review-flagged
    multi-device cliff). Verified against dense on the 8-device CPU mesh."""
    import jax

    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context

    env = build_mesh(MeshConfig(data=4, model=2))
    q, k, v = _qkv(b=4, t=128, h=2, d=32)
    ref = dense_attention(q, k, v, causal=True)
    with mesh_context(env):
        out = jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64, interpret=True
            )
        )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_sharded_flash_delegates_to_ring_on_seq_axis():
    """attention='flash' under a sequence-sharded mesh routes through ring
    attention (whose hops ARE the flash kernel) instead of raising — the
    round-1 flash/SP exclusion, lifted. Must match dense numerics."""
    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context

    env = build_mesh(MeshConfig(data=2, seq=4))
    q, k, v = _qkv(b=4, t=128, h=2, d=32)
    ref = dense_attention(q, k, v, causal=True)
    with mesh_context(env):
        out = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=True)
        )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_gpt_model_flash_attention_path(tmp_path):
    """attention='flash' trains end-to-end (tiny GPT).

    On the CPU test backend this exercises the config wiring plus the
    documented non-TPU dense fallback; the kernel numerics themselves are
    covered by the interpret=True tests above.
    """
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        [
            "model.num_layers=2",
            "model.hidden_dim=64",
            "model.num_heads=2",
            "model.vocab_size=256",
            "model.seq_len=64",
            "model.attention=flash",
            "data.seq_len=64",
            "data.vocab_size=256",
            "data.global_batch_size=8",
            "trainer.grad_accum=1",
            "trainer.log_every=10",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = trainer.pipeline.global_batch(0)
    losses = []
    for step in range(8):
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
