"""Pipeline parallelism (SURVEY C7): GPipe-in-GSPMD must (i) match the plain
layer-stacked model exactly, (ii) actually shard stages over ``pipe``, and
(iii) train end-to-end composed with DP."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_apply, jit_init

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig, PrecisionConfig
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

FP32 = get_policy(PrecisionConfig(policy="fp32"))

TINY = dict(
    vocab_size=128, num_layers=4, num_heads=2, hidden_dim=32, seq_len=16, dropout=0.0
)


def plain_to_pipelined(params, num_stages):
    """Map plain GPT params -> pipelined structure: the ``blocks`` leaves
    reshape [L, ...] -> [S, L/S, ...] and move under pipeline/ticks/blocks."""
    blocks = jax.tree.map(
        lambda x: x.reshape((num_stages, x.shape[0] // num_stages) + x.shape[1:]),
        params["blocks"],
    )
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["pipeline"] = {"ticks": {"blocks": blocks}}
    return out


def plain_to_circular(params, num_stages, repeat):
    """Plain GPT params -> circular structure: ``blocks`` leaves reshape
    [L, ...] -> [repeat, S, L/(S*repeat), ...] (virtual stage r*S+j holds
    layer group r*S+j) and move under pipeline/blocks."""
    blocks = jax.tree.map(
        lambda x: x.reshape(
            (repeat, num_stages, x.shape[0] // (repeat * num_stages)) + x.shape[1:]
        ),
        params["blocks"],
    )
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["pipeline"] = {"blocks": blocks}
    return out


@pytest.mark.parametrize(
    "stages,repeat,micro",
    [(2, 2, 2), (2, 2, 4)],  # M == S (no parking) and M > S (parking FIFO)
)
def test_circular_pp_matches_plain(stages, repeat, micro):
    """The circular (interleaved) schedule — dynamic per-tick virtual-stage
    param selection + parking FIFO — must match the plain stack exactly,
    forward and backward."""
    base = GPTConfig(**TINY)
    cc = dataclasses.replace(
        base,
        pipeline_stages=stages,
        pipeline_microbatches=micro,
        pipeline_circular_repeat=repeat,
    )
    tokens = jax.random.randint(jax.random.key(8), (8, 16), 0, 128)
    m_plain, m_c = GPT(base, FP32), GPT(cc, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]
    cp = plain_to_circular(params, stages, repeat)
    out_plain = jit_apply(m_plain, train=False)({"params": params}, tokens)
    out_c = jit_apply(m_c, train=False)({"params": cp}, tokens)
    np.testing.assert_allclose(out_plain, out_c, atol=1e-5, rtol=1e-5)

    def loss_plain(p):
        return jnp.mean(m_plain.apply({"params": p}, tokens, train=False) ** 2)

    def loss_c(p):
        return jnp.mean(m_c.apply({"params": p}, tokens, train=False) ** 2)

    g_plain = plain_to_circular(
        jax.jit(jax.grad(loss_plain))(params), stages, repeat
    )
    g_c = jax.jit(jax.grad(loss_c))(cp)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4),
        g_plain,
        g_c,
    )


def test_circular_pp_requires_enough_microbatches():
    """M < S would make a re-entering microbatch collide with a fresh
    injection — the model must refuse, not silently corrupt the schedule."""
    cc = dataclasses.replace(
        GPTConfig(**TINY),
        pipeline_stages=2,
        pipeline_microbatches=1,
        pipeline_circular_repeat=2,
    )
    tokens = jax.random.randint(jax.random.key(9), (8, 16), 0, 128)
    with pytest.raises(ValueError, match="microbatches >= stages"):
        GPT(cc, FP32).init({"params": jax.random.key(0)}, tokens, train=False)


def test_circular_pp_e2e_trains_and_shards(tmp_path):
    """Circular PP=2 x repeat=2 trains end-to-end on the mesh, block params
    carry [repeat, stage, ...] with the stage dim actually sharded over
    ``pipe``, and the logged bubble fraction reflects the v* amortization."""
    from frl_distributed_ml_scaffold_tpu.parallel.pipeline import pipeline_summary

    trainer = make_gpt_trainer(
        tmp_path,
        [
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=4",
            "model.pipeline_circular_repeat=2",
            "mesh.pipe=2",
            "mesh.data=4",
        ],
    )
    summary = pipeline_summary(trainer.cfg.model)
    assert "circular(x2)" in summary and "0.111" in summary  # 1/(2*4+1)
    state = trainer.init_state()
    leaf = state.params["pipeline"]["blocks"]["attn"]["query"]["kernel"]
    assert leaf.shape[:2] == (2, 2)  # [repeat, stage, ...]
    spec = leaf.sharding.spec
    assert spec[1] == "pipe" and spec[0] is None, spec
    state, metrics = run_steps(trainer, state, steps=3)
    assert np.isfinite(float(metrics["loss"]))


def test_pp_forward_matches_plain():
    base = GPTConfig(**TINY)
    pp = dataclasses.replace(base, pipeline_stages=2, pipeline_microbatches=2)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 128)
    m_plain, m_pp = GPT(base, FP32), GPT(pp, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]
    out_plain = jit_apply(m_plain, train=False)({"params": params}, tokens)
    out_pp = jit_apply(m_pp, train=False)(
        {"params": plain_to_pipelined(params, 2)}, tokens
    )
    np.testing.assert_allclose(out_plain, out_pp, atol=1e-5, rtol=1e-5)


def test_pp_grads_match_plain():
    """Autodiff through the rolling-buffer schedule == plain backprop."""
    base = GPTConfig(**TINY)
    pp = dataclasses.replace(base, pipeline_stages=2, pipeline_microbatches=2)
    tokens = jax.random.randint(jax.random.key(2), (4, 16), 0, 128)
    m_plain, m_pp = GPT(base, FP32), GPT(pp, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]

    def loss_plain(p):
        return jnp.mean(m_plain.apply({"params": p}, tokens, train=False) ** 2)

    def loss_pp(p):
        return jnp.mean(m_pp.apply({"params": p}, tokens, train=False) ** 2)

    g_plain = jax.jit(jax.grad(loss_plain))(params)
    g_pp = jax.jit(jax.grad(loss_pp))(plain_to_pipelined(params, 2))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4),
        plain_to_pipelined(g_plain, 2),
        g_pp,
    )


def test_pp_moe_aux_loss_batch_invariant():
    """The MoE router aux loss must not scale with num_microbatches."""
    from frl_distributed_ml_scaffold_tpu.config.schema import MoEConfig

    base = GPTConfig(**TINY, moe=MoEConfig(num_experts=4, top_k=2))
    pp = dataclasses.replace(base, pipeline_stages=2, pipeline_microbatches=4)
    tokens = jax.random.randint(jax.random.key(3), (8, 16), 0, 128)
    m_plain, m_pp = GPT(base, FP32), GPT(pp, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]
    _, aux_plain = jit_apply(m_plain, train=False)({"params": params}, tokens)
    _, aux_pp = jit_apply(m_pp, train=False)(
        {"params": plain_to_pipelined(params, 2)}, tokens
    )
    # Microbatch router stats are means over different token subsets, so
    # the two aux values agree only in expectation — assert same scale.
    assert float(aux_plain) > 0
    ratio = float(aux_pp) / float(aux_plain)
    assert 0.5 < ratio < 2.0, f"aux scales with microbatch count: {ratio}"


@pytest.mark.xfail(
    strict=True,
    reason="pipeline(stage-vmap spmd_axis_name='pipe') x sequence-parallel "
    "shard_map produces a DETERMINISTIC wrong forward in this jaxlib build: "
    "identical ~0.18-0.21 max diff across meshes (pipe2xdata2xseq2, 4-dev), "
    "microbatch counts (2/4), single-CPU taskset, and Pallas-interpreter "
    "local attention, while pp x dense/flash and plain ring/ulysses are all "
    "exact — NOT a tolerance class (do not re-tolerance; see CHANGES.md "
    "PR 3 / memory repo-test-flakiness). Tracked in BACKLOG R8-2; "
    "strict=True so a fixed jaxlib un-xfails this loudly. RESOLVED on the "
    "MPMD backend (ISSUE 14): test_pp_composes_with_ring_attention_mpmd "
    "passes the same composition through per-stage programs with no "
    "stage vmap — pp x SP users should run model.pipeline_impl=mpmd.",
)
def test_pp_composes_with_ring_attention():
    """Round-1 exclusion, lifted: ring attention's shard_map (ppermute over
    ``seq``) nests inside the pipeline's stage vmap via spmd_axis_name.
    PP=2 x SP=2 forward must match the plain dense-attention model."""
    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context

    base = GPTConfig(**TINY)
    pp_ring = dataclasses.replace(
        base, pipeline_stages=2, pipeline_microbatches=2, attention="ring"
    )
    tokens = jax.random.randint(jax.random.key(4), (4, 16), 0, 128)
    m_plain, m_pp = GPT(base, FP32), GPT(pp_ring, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]
    out_plain = jit_apply(m_plain, train=False)({"params": params}, tokens)

    env = build_mesh(MeshConfig(pipe=2, data=2, seq=2))
    with mesh_context(env):
        out_pp = jax.jit(
            lambda p, t: m_pp.apply({"params": p}, t, train=False)
        )(plain_to_pipelined(params, 2), tokens)
    np.testing.assert_allclose(out_plain, out_pp, atol=2e-5, rtol=1e-5)


def test_pp_composes_with_ring_attention_grads(tmp_path):
    """The same composition must hold through the backward (custom-VJP ring
    inside the vmapped/scanned pipeline): train a PP=2 x SP=2 x DP=2 GPT
    end-to-end and check the loss moves."""
    trainer = make_gpt_trainer(
        tmp_path,
        [
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=2",
            "model.attention=ring",
            "mesh.pipe=2",
            "mesh.data=2",
            "mesh.seq=2",
        ],
    )
    state = trainer.init_state()
    _, metrics = run_steps(trainer, state, steps=4)
    assert np.isfinite(float(metrics["loss"]))


def test_pp_composes_with_remat(tmp_path):
    """PP x activation checkpointing: rematerializing through the rolling-
    buffer schedule must not change the math (it is the lever that keeps
    GPipe's saved-per-tick activations from bounding pipeline depth)."""
    ref = make_gpt_trainer(
        tmp_path / "ref",
        ["model.pipeline_stages=2", "model.pipeline_microbatches=2",
         "mesh.pipe=2", "mesh.data=4", "trainer.remat=none"],
    )
    ref_state, _ = run_steps(ref, ref.init_state(), steps=3)
    for mode in ("full", "dots"):
        tr = make_gpt_trainer(
            tmp_path / mode,
            ["model.pipeline_stages=2", "model.pipeline_microbatches=2",
             "mesh.pipe=2", "mesh.data=4", f"trainer.remat={mode}"],
        )
        state, _ = run_steps(tr, tr.init_state(), steps=3)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
            ),
            jax.device_get(ref_state.params),
            jax.device_get(state.params),
        )


@pytest.mark.xfail(
    strict=True,
    reason="same deterministic pipeline x sequence-parallel divergence as "
    "test_pp_composes_with_ring_attention (the composition, not the "
    "attention impl, is what breaks — Ulysses' all_to_all shows the "
    "identical diff). Tracked in BACKLOG R8-2; strict=True so a fixed "
    "jaxlib un-xfails this loudly. RESOLVED on the MPMD backend (ISSUE "
    "14): test_pp_composes_with_ulysses_attention_mpmd passes the same "
    "composition through per-stage programs with no stage vmap.",
)
def test_pp_composes_with_ulysses_attention():
    """Ulysses' all_to_all shard_map also batches over the stage vmap."""
    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context

    base = GPTConfig(**TINY)
    pp_uly = dataclasses.replace(
        base, pipeline_stages=2, pipeline_microbatches=2, attention="ulysses"
    )
    tokens = jax.random.randint(jax.random.key(5), (4, 16), 0, 128)
    m_plain, m_pp = GPT(base, FP32), GPT(pp_uly, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]
    out_plain = jit_apply(m_plain, train=False)({"params": params}, tokens)

    env = build_mesh(MeshConfig(pipe=2, data=2, seq=2))
    with mesh_context(env):
        out_pp = jax.jit(
            lambda p, t: m_pp.apply({"params": p}, t, train=False)
        )(plain_to_pipelined(params, 2), tokens)
    np.testing.assert_allclose(out_plain, out_pp, atol=2e-5, rtol=1e-5)


def test_pp_composes_with_ring_attention_mpmd(tmp_path):
    """BACKLOG R8-2, resolved on the MPMD path (ISSUE 14): the per-stage
    programs have no vmap(spmd_axis_name), so ring attention's shard_map
    (ppermute over ``seq``) opens directly inside each stage program —
    the pipe2 x data2 x seq2 composition that deterministically diverges
    under the SPMD stage vmap (the strict-xfail twin above) must PASS
    here, forward AND through two finite training steps."""
    import dataclasses as _dc

    trainer = make_gpt_trainer(
        tmp_path,
        [
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=2",
            "model.pipeline_impl=mpmd",
            "model.attention=ring",
            "mesh.pipe=2",
            "mesh.data=2",
            "mesh.seq=2",
        ],
    )
    plain = GPT(
        _dc.replace(
            trainer.cfg.model, pipeline_stages=1, attention="dense"
        ),
        trainer.policy,
    )
    tokens = jax.random.randint(jax.random.key(4), (8, 32), 0, 128)
    params = jit_init(plain, tokens, train=False)["params"]
    out_plain = jit_apply(plain, train=False)({"params": params}, tokens)
    mp_params = trainer._mpmd.place_plain_params(jax.device_get(params))
    out_mpmd = trainer._mpmd.apply_logits(mp_params, tokens)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out_mpmd)),
        np.asarray(jax.device_get(out_plain)),
        atol=2e-5, rtol=1e-5,
    )
    state = trainer.init_state().replace(params=mp_params)
    state, metrics = run_steps(trainer, state, steps=2)
    assert np.isfinite(float(metrics["loss"]))


def test_pp_composes_with_ulysses_attention_mpmd(tmp_path):
    """Ulysses' all_to_all shard_map through the MPMD per-stage programs:
    the second half of the R8-2 pair, passing where the stage-vmap twin
    strict-xfails."""
    import dataclasses as _dc

    trainer = make_gpt_trainer(
        tmp_path,
        [
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=2",
            "model.pipeline_impl=mpmd",
            "model.attention=ulysses",
            "mesh.pipe=2",
            "mesh.data=2",
            "mesh.seq=2",
        ],
    )
    plain = GPT(
        _dc.replace(
            trainer.cfg.model, pipeline_stages=1, attention="dense"
        ),
        trainer.policy,
    )
    tokens = jax.random.randint(jax.random.key(5), (8, 32), 0, 128)
    params = jit_init(plain, tokens, train=False)["params"]
    out_plain = jit_apply(plain, train=False)({"params": params}, tokens)
    mp_params = trainer._mpmd.place_plain_params(jax.device_get(params))
    out_mpmd = trainer._mpmd.apply_logits(mp_params, tokens)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out_mpmd)),
        np.asarray(jax.device_get(out_plain)),
        atol=2e-5, rtol=1e-5,
    )


def test_pp_composes_with_flash_attention_pallas(monkeypatch):
    """flash's pallas_call-in-shard_map also nests under the stage vmap.
    On CPU flash normally falls back to dense before reaching its shard_map,
    so force interpreter mode through the model's call site to exercise the
    real composition the TPU path uses."""
    import functools

    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context
    import importlib

    # The ops package re-exports the flash_attention FUNCTION under the same
    # name, shadowing the submodule on attribute import.
    fa_mod = importlib.import_module(
        "frl_distributed_ml_scaffold_tpu.ops.flash_attention"
    )

    monkeypatch.setattr(
        fa_mod,
        "flash_attention",
        functools.partial(fa_mod.flash_attention, interpret=True),
    )
    base = GPTConfig(**TINY)
    pp_flash = dataclasses.replace(
        base, pipeline_stages=2, pipeline_microbatches=2, attention="flash"
    )
    tokens = jax.random.randint(jax.random.key(6), (4, 16), 0, 128)
    m_plain, m_pp = GPT(base, FP32), GPT(pp_flash, FP32)
    params = jit_init(m_plain, tokens, train=False)["params"]
    out_plain = jit_apply(m_plain, train=False)({"params": params}, tokens)

    env = build_mesh(MeshConfig(pipe=2, data=2, model=2))
    with mesh_context(env):
        out_pp = jax.jit(
            lambda p, t: m_pp.apply({"params": p}, t, train=False)
        )(plain_to_pipelined(params, 2), tokens)
    np.testing.assert_allclose(out_plain, out_pp, atol=2e-5, rtol=1e-5)


GPT_TINY_OVERRIDES = [
    "model.vocab_size=128",
    "model.num_layers=4",
    "model.num_heads=2",
    "model.hidden_dim=32",
    "model.seq_len=32",
    "data.vocab_size=128",
    "data.seq_len=32",
    "data.global_batch_size=16",
    "trainer.grad_accum=1",
    "optimizer.warmup_steps=0",
    "precision.policy=fp32",
    "trainer.log_every=1000",
]


def make_gpt_trainer(tmp_path, overrides):
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        GPT_TINY_OVERRIDES + [f"workdir={tmp_path}"] + overrides,
    )
    return Trainer(cfg)


def run_steps(trainer, state, steps=6):
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    return state, metrics


def test_pp_e2e_matches_dp(tmp_path):
    """PP=2 x DP=4 training == pure DP=8 training, step for step.

    The two init RNG layouts differ (vmap-over-stages splits differently
    than the plain layer scan), so the PP run starts from the DP run's
    init mapped into the stage-stacked structure.
    """
    dp = make_gpt_trainer(tmp_path / "dp", ["mesh.data=8"])
    pp = make_gpt_trainer(
        tmp_path / "pp",
        [
            "mesh.data=4",
            "mesh.pipe=2",
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=4",
        ],
    )
    dp_state = dp.init_state()
    shared = plain_to_pipelined(jax.device_get(dp_state.params), 2)
    pp_state = pp.init_state().replace(params=shared)

    dp_state, _ = run_steps(dp, dp_state)
    pp_state, pp_metrics = run_steps(pp, pp_state)
    assert np.isfinite(float(pp_metrics["loss"]))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-4),
        plain_to_pipelined(jax.device_get(dp_state.params), 2),
        jax.device_get(pp_state.params),
    )


def test_pp_actually_shards_stages(tmp_path):
    """Stage dim of every block param must shard over ``pipe``; training
    must reduce the loss."""
    cfg = apply_overrides(
        get_config("gpt2_pp"),
        GPT_TINY_OVERRIDES
        + [
            f"workdir={tmp_path}",
            "mesh.data=4",
            "mesh.pipe=2",
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=4",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    blocks = state.params["pipeline"]["ticks"]["blocks"]
    for leaf in jax.tree.leaves(blocks):
        assert tuple(leaf.sharding.spec)[:1] == ("pipe",), leaf.sharding.spec
    losses = []
    for step in range(8):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_pp_composes_with_tp(tmp_path):
    """PP x TP: stage dim on ``pipe`` AND kernel dim on ``model`` at once."""
    cfg = apply_overrides(
        get_config("gpt2_pp"),
        GPT_TINY_OVERRIDES
        + [
            f"workdir={tmp_path}",
            "mesh.data=2",
            "mesh.pipe=2",
            "mesh.model=2",
            "model.pipeline_stages=2",
            "model.pipeline_microbatches=2",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    qk = state.params["pipeline"]["ticks"]["blocks"]["attn"]["query"]["kernel"]
    spec = tuple(qk.sharding.spec)
    assert spec[0] == "pipe" and "model" in spec, spec
    batch = trainer.pipeline.global_batch(0)
    state, metrics = trainer.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.parametrize("circular", [False, True], ids=["gpipe", "circular"])
def test_pp_stage_remat_grads_match(circular):
    """pipeline_stage_remat is pure rematerialization: gradients must be
    identical (fp32, same contractions) to the non-remat schedule while the
    backward saves only stage-boundary activations per tick (residency
    measured by tools/pp_memory_audit.py)."""
    base = GPTConfig(**TINY)
    kw = dict(pipeline_stages=2, pipeline_microbatches=2)
    if circular:
        kw["pipeline_circular_repeat"] = 2
        base = dataclasses.replace(base, num_layers=4)
        to_pp = lambda p: plain_to_circular(p, 2, 2)
    else:
        to_pp = lambda p: plain_to_pipelined(p, 2)
    pp = dataclasses.replace(base, **kw)
    pp_sr = dataclasses.replace(pp, pipeline_stage_remat=True)
    tokens = jax.random.randint(jax.random.key(3), (4, 16), 0, 128)
    params = jit_init(GPT(base, FP32), tokens, train=False)["params"]

    def grads(model):
        def loss(p):
            return jnp.mean(
                model.apply({"params": p}, tokens, train=False) ** 2
            )

        return jax.jit(jax.grad(loss))(to_pp(params))

    g, g_sr = grads(GPT(pp, FP32)), grads(GPT(pp_sr, FP32))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6),
        g,
        g_sr,
    )

    # Composes with the trainer-level remat wrap (nested jax.checkpoint —
    # trainer.remat=full around a stage-remat pipeline).
    m_sr = GPT(pp_sr, FP32)

    def loss_sr(p):
        return jnp.mean(m_sr.apply({"params": p}, tokens, train=False) ** 2)

    g_nested = jax.jit(jax.grad(jax.checkpoint(loss_sr)))(to_pp(params))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6),
        g,
        g_nested,
    )


def _residual_bytes_of(loss, params):
    from jax._src.ad_checkpoint import saved_residuals

    total = 0
    for aval, _ in saved_residuals(loss, params):
        if hasattr(aval, "shape"):
            total += int(aval.size) * aval.dtype.itemsize
    return total


def test_pp_residual_ordering_pinned():
    """CI-light version of the tools/pp_memory_audit.py conclusion (VERDICT
    r3 next-round #8), pinned so the docs' qualitative ordering can't rot:
    saved fwd→bwd residuals must satisfy stage_remat < plain < gpipe
    (the raw scan-autodiff pipeline saves every tick's stage activations —
    MORE than plain DP — and stage remat collapses it to boundaries)."""
    base = GPTConfig(**TINY)
    pp = dataclasses.replace(
        base, pipeline_stages=2, pipeline_microbatches=4
    )
    pp_sr = dataclasses.replace(pp, pipeline_stage_remat=True)
    tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, 128)
    params = jit_init(GPT(base, FP32), tokens, train=False)["params"]

    def bytes_for(model, to_params):
        def loss(p):
            return jnp.mean(
                model.apply({"params": p}, tokens, train=False) ** 2
            )

        return _residual_bytes_of(loss, to_params(params))

    plain = bytes_for(GPT(base, FP32), lambda p: p)
    gpipe = bytes_for(GPT(pp, FP32), lambda p: plain_to_pipelined(p, 2))
    sr = bytes_for(GPT(pp_sr, FP32), lambda p: plain_to_pipelined(p, 2))
    assert sr < plain < gpipe, (sr, plain, gpipe)
