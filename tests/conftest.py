"""Test harness: simulated 8-device CPU mesh (SURVEY §4, C20).

Must run before jax is imported anywhere: forces the host platform and 8
virtual CPU devices so every parallelism mode (DP/FSDP/TP/PP/SP/EP) runs real
meshes and real collectives in pytest without TPU hardware — the TPU-native
replacement for the reference's Gloo/fake-process-group test tier.
"""

import os
import sys

# Overwrite (not setdefault): the environment pins JAX_PLATFORMS=axon (the
# real TPU plugin); tests must run on the simulated CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Repo root on sys.path so the package imports without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin (injected via sitecustomize on PYTHONPATH) overrides
# jax_platforms at the jax.config level, which beats the env var — override
# it back before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The HF-interop tests load host torch into this (shared) pytest process;
# the launcher's *runtime* no-CUDA tier would then trip on every later
# launch-path test. That tier is for real launch processes — waive it
# suite-wide and exercise its semantics explicitly in test_train_mnist.
os.environ.setdefault("FRL_ALLOW_HOST_TORCH", "1")

# Persistent compilation cache (repo-local, gitignored): the suite's wall
# time is dominated by XLA compiles of the same tiny models on the same
# 8-device mesh; caching them across runs cuts repeat `pytest` runs by
# minutes on this 1-core box. One shared helper with the launcher/bench;
# tests lower the thresholds because their compiles are tiny but numerous.
from frl_distributed_ml_scaffold_tpu.launcher.launch import (  # noqa: E402
    enable_compile_cache,
)

enable_compile_cache()
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# Measured and rejected (2026-07-30): jax_disable_most_optimizations=True
# cuts per-test XLA compile by ~1/3 but makes the *runtime* of the conv- and
# step-heavy tests 1.7-2x slower — net suite time went 703s -> 767s. The
# suite's budget is better served by keeping shapes tiny per-test.
#
# Measured and adopted (2026-07-30): tests must jax.jit their flax
# init/apply/grad calls instead of running them eagerly — eager dispatch
# walks hundreds of tiny ops one by one on this 1-core box (11.8s for an
# eager RN50 init vs <1s as one cached program). Jitting the hot test
# bodies cut the warm suite 394s -> 255s at identical coverage.

# Evidence-cache sandbox, SESSION-WIDE (round-6 hardening of the round-5
# self-poisoning fix): bench.py's last-good cache path is env-overridable,
# and test_bench.py monkeypatches its own module object — but any OTHER
# test that imports bench (or launches a subprocess that does) would still
# write the COMMITTED bench_last_good.json. Exporting the override here,
# before any test imports bench, covers every reacher in one place;
# setdefault keeps an operator's explicit override authoritative.
import tempfile  # noqa: E402

os.environ.setdefault(
    "FRL_BENCH_LAST_GOOD_PATH",
    os.path.join(
        tempfile.gettempdir(), f"frl_bench_last_good_sandbox_{os.getpid()}.json"
    ),
)

import contextlib  # noqa: E402
import logging  # noqa: E402
import pytest  # noqa: E402

_REPO_BENCH_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bench_last_good.json",
)


@pytest.fixture(scope="session", autouse=True)
def committed_bench_cache_stays_byte_identical():
    """The committed evidence cache must survive a FULL suite run
    byte-identical (ISSUE r6 satellite; the round-5 bug was every pytest
    run stamping fixture value 123.0 into it). Asserting at session
    teardown catches any write path the env sandbox above misses."""
    before = (
        open(_REPO_BENCH_CACHE, "rb").read()
        if os.path.exists(_REPO_BENCH_CACHE)
        else None
    )
    yield
    after = (
        open(_REPO_BENCH_CACHE, "rb").read()
        if os.path.exists(_REPO_BENCH_CACHE)
        else None
    )
    assert before == after, (
        "the test suite modified the committed bench_last_good.json — "
        "some _save_last_good/_emit_stale_or_error reacher is not covered "
        "by the FRL_BENCH_LAST_GOOD_PATH sandbox"
    )


@contextlib.contextmanager
def capture_frl_logs():
    """Collect framework log messages. The framework logger sets
    ``propagate=False`` (process-0 stdout gating), so pytest's ``caplog``
    never sees its records — tests attach a handler directly instead."""
    from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

    records: list[str] = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = get_logger()
    handler = _Capture()
    logger.addHandler(handler)
    try:
        yield records
    finally:
        logger.removeHandler(handler)
