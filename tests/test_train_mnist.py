"""End-to-end slice (SURVEY §7 stage 2 / §4 integration): MLP on (synthetic)
MNIST through the full launcher→config→data→step→metrics path."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import sys

import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


def small_mnist_cfg(tmp_path, **kw):
    cfg = get_config("mnist_mlp")
    cfg = apply_overrides(
        cfg,
        [
            "trainer.total_steps=30",
            "trainer.log_every=20",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "model.hidden_sizes=128,64",
            f"workdir={tmp_path}",
        ]
        + [f"{k}={v}" for k, v in kw.items()],
    )
    return cfg


def test_mnist_mlp_learns(tmp_path):
    trainer = Trainer(small_mnist_cfg(tmp_path))
    state = trainer.init_state()

    losses = []
    for step in range(30):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))

    assert losses[-1] < losses[0] * 0.5, f"loss did not halve: {losses[0]} -> {losses[-1]}"
    assert float(metrics["accuracy"]) > 0.8
    # Top-5 (10 classes here) must dominate top-1 by construction.
    assert float(metrics["accuracy_top5"]) >= float(metrics["accuracy"])


def test_mnist_fit_loop_and_eval(tmp_path):
    cfg = small_mnist_cfg(tmp_path)
    trainer = Trainer(cfg)
    state, last = trainer.fit()
    assert int(np.asarray(state.step)) == 30
    assert "loss" in last and last["loss"] < 2.0
    ev = trainer.evaluate(state, num_steps=3)
    assert ev["eval_accuracy"] > 0.5
    # fit() records the resolved config — the experiment's reproducibility
    # artifact (offline tools rebuild the exact model from it).
    import json

    with open(tmp_path / "mnist_mlp" / "config.json") as fh:
        dumped = json.load(fh)
    assert dumped["model"]["family"] == "mlp"
    assert dumped["trainer"]["total_steps"] == 30


def test_launcher_cli_runs(tmp_path, capsys):
    from frl_distributed_ml_scaffold_tpu.launcher.launch import main

    rc = main(
        [
            "--config=mnist_mlp",
            "--device=cpu",
            "trainer.total_steps=5",
            "trainer.log_every=5",
            "trainer.eval_every=0",
            "data.global_batch_size=32",
            "model.hidden_sizes=32",
            f"workdir={tmp_path}",
        ]
    )
    assert rc == 0


def test_launcher_describe_dry_run(tmp_path, capsys):
    """--describe prints mesh + per-param shardings + FLOPs and trains
    nothing (no metrics.jsonl is written)."""
    from frl_distributed_ml_scaffold_tpu.launcher.launch import main

    rc = main(
        [
            "--config=mnist_mlp",
            "--device=cpu",
            "--describe",
            "data.global_batch_size=32",
            "model.hidden_sizes=32",
            f"workdir={tmp_path}",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "mesh:" in out
    assert "PartitionSpec" in out
    assert "train-step FLOPs" in out and "G/sample" in out
    assert not (tmp_path / "mnist_mlp" / "metrics.jsonl").exists()


def test_cuda_import_scan_semantics():
    """The static no-CUDA scan must catch every import form (multi-module,
    from-import, importlib/__import__ literals) and must NOT false-positive
    on docstring text — and the real package tree must be clean."""
    import ast

    from frl_distributed_ml_scaffold_tpu.launcher.launch import (
        _assert_no_cuda_imports,
        _imported_names,
    )

    _assert_no_cuda_imports()  # the shipped sources pass

    bad = ast.parse(
        "import os, torch\n"
        "from torch.cuda import nccl\n"
        "import importlib\n"
        "importlib.import_module('cupy')\n"
        "x = __import__('torch')\n"
    )
    names = set(_imported_names(bad))
    assert {"torch", "torch.cuda", "cupy"} <= names

    ok = ast.parse('"""example:\n    import torch\n"""\nimport numpy\n')
    assert "torch" not in set(_imported_names(ok))


def test_cuda_runtime_check_semantics(monkeypatch):
    """The runtime tier catches banned modules loaded in the launch process
    (e.g. pulled transitively, invisible to the static scan) and is waived
    only by the explicit FRL_ALLOW_HOST_TORCH escape hatch."""
    import types

    from frl_distributed_ml_scaffold_tpu.launcher.launch import (
        _assert_no_cuda_imports,
    )

    monkeypatch.delenv("FRL_ALLOW_HOST_TORCH", raising=False)
    monkeypatch.delitem(sys.modules, "torch", raising=False)
    monkeypatch.setitem(sys.modules, "cupy", types.ModuleType("cupy"))
    with pytest.raises(RuntimeError, match="cupy"):
        _assert_no_cuda_imports()

    monkeypatch.setenv("FRL_ALLOW_HOST_TORCH", "1")
    _assert_no_cuda_imports()  # waived: only the static scan runs
