"""Integration tier (SURVEY §4): each BASELINE config as a shrunken smoke
run asserting the loss decreases, plus TP/SP/EP recipe variants."""

import jax
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, set_current_mesh
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


@pytest.fixture(autouse=True)
def clear_mesh_context():
    yield
    set_current_mesh(None)


def smoke_run(name, overrides, tmp_path, steps=8):
    cfg = apply_overrides(
        get_config(name),
        [
            "precision.policy=fp32",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ]
        + overrides,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], f"no learning: {losses}"
    return losses


def test_config2_rn50_ddp(tmp_path):
    smoke_run(
        "imagenet_rn50_ddp",
        [
            "model.depth=10",
            "data.image_size=32",
            "data.num_classes=8",
            "model.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.learning_rate=0.05",
            "optimizer.warmup_steps=0",
            "mesh.data=8",
        ],
        tmp_path,
    )


def test_config3_vitb_fsdp(tmp_path):
    smoke_run(
        "imagenet_vitb_fsdp",
        [
            "model.image_size=32",
            "model.patch_size=8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.num_classes=8",
            "data.image_size=32",
            "data.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.warmup_steps=0",
            "optimizer.learning_rate=1e-3",
            "mesh.fsdp=8",
            "parallel.fsdp_min_size=64",
        ],
        tmp_path,
    )


def test_config4_gpt2_zero1(tmp_path):
    smoke_run(
        "gpt2_medium_zero1",
        [
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=32",
            "data.vocab_size=128",
            "data.seq_len=32",
            "data.global_batch_size=16",
            "trainer.grad_accum=2",
            "optimizer.warmup_steps=0",
            "mesh.fsdp=8",
        ],
        tmp_path,
    )


def test_config5_video(tmp_path):
    smoke_run(
        "ego4d_video_elastic",
        [
            "model.image_size=16",
            "model.num_frames=4",
            "model.tubelet_size=2,8,8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.num_classes=8",
            "data.image_size=16",
            "data.num_frames=4",
            "data.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.warmup_steps=0",
            "mesh.fsdp=8",
            "parallel.fsdp_min_size=64",
        ],
        tmp_path,
    )


GPT_TINY = [
    "model.vocab_size=128",
    "model.num_layers=2",
    "model.num_heads=4",
    "model.hidden_dim=64",
    "model.seq_len=32",
    "data.vocab_size=128",
    "data.seq_len=32",
    "data.global_batch_size=16",
    "trainer.grad_accum=1",
    "optimizer.warmup_steps=0",
]


def run_gpt(tmp_path, mesh_overrides, steps=6):
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["precision.policy=fp32", "trainer.log_every=1000", f"workdir={tmp_path}"]
        + GPT_TINY
        + mesh_overrides,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    return jax.device_get(state), metrics


def test_tp_matches_dp(tmp_path):
    """Tensor parallelism (SURVEY C6): TP=2 numerics == pure DP.

    Param tolerance is ~steps x lr (6 x 3e-4, with adamw's transient
    overshoot headroom): TP's per-layer allreduces reorder the reductions
    of numerically-zero grads (softmax is key-bias invariant) that adamw
    amplifies to lr-scale sign updates — the test_fsdp_overlap.py
    tolerance class. The loss comparison is the tight equivalence gate."""
    ref_state, ref_m = run_gpt(tmp_path / "dp", ["mesh.data=8", "mesh.fsdp=1"])
    tp_state, tp_m = run_gpt(
        tmp_path / "tp", ["mesh.data=4", "mesh.fsdp=1", "mesh.model=2"]
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-3, rtol=1e-4),
        ref_state.params,
        tp_state.params,
    )
    np.testing.assert_allclose(
        float(tp_m["loss"]), float(ref_m["loss"]), atol=1e-3
    )


def test_tp_actually_shards_params(tmp_path):
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["precision.policy=fp32", f"workdir={tmp_path}"]
        + GPT_TINY
        + ["mesh.data=4", "mesh.model=2"],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    qk = state.params["blocks"]["attn"]["query"]["kernel"]
    assert "model" in tuple(qk.sharding.spec), qk.sharding.spec


VIT_TINY = [
    "model.image_size=32",
    "model.patch_size=8",
    "model.hidden_dim=64",
    "model.num_layers=2",
    "model.num_heads=4",
    "model.num_classes=8",
    "data.image_size=32",
    "data.num_classes=8",
    "data.global_batch_size=16",
    "optimizer.warmup_steps=0",
    "trainer.log_every=1000",
    "precision.policy=fp32",
    "checkpoint.enabled=false",
]


def run_vit(tmp_path, mesh_overrides, steps=3):
    cfg = apply_overrides(
        get_config("imagenet_vitb_fsdp"),
        VIT_TINY + [f"workdir={tmp_path}"] + mesh_overrides,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    return jax.device_get(state), metrics, trainer


def test_vit_tp_matches_dp(tmp_path):
    """TP rules for the ViT encoder (VERDICT r1 #7): TP=2 == pure DP, and
    TP composes with the recipe's FSDP overlay.

    Param tolerance is ~2x steps x lr (3 x 3e-3 at the ViT recipe's LR,
    doubled for adamw's early bias-correction overshoot): the adam-noise
    amplification class (see test_tp_matches_dp); the zero-grad params it
    flips barely move the loss, which is compared tightly."""
    ref_state, ref_m, _ = run_vit(
        tmp_path / "dp", ["mesh.data=8", "parallel.param_sharding=replicated"]
    )
    tp_state, tp_m, _ = run_vit(
        tmp_path / "tp",
        ["mesh.data=4", "mesh.model=2", "parallel.param_sharding=replicated"],
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-2, rtol=1e-4),
        ref_state.params,
        tp_state.params,
    )
    np.testing.assert_allclose(
        float(tp_m["loss"]), float(ref_m["loss"]), atol=2e-3
    )


def test_vit_tp_actually_shards_params_with_fsdp(tmp_path):
    _, _, trainer = run_vit(
        tmp_path,
        ["mesh.data=2", "mesh.model=2", "mesh.fsdp=2",
         "parallel.fsdp_min_size=64"],
        steps=1,
    )
    state = trainer.init_state()
    attn = state.params["EncoderBlock_0"]["MultiHeadDotProductAttention_0"]
    q_spec = tuple(attn["query"]["kernel"].sharding.spec)
    assert "model" in q_spec, q_spec
    assert "fsdp" in q_spec, q_spec  # TP x FSDP overlay both live
    out_spec = tuple(attn["out"]["kernel"].sharding.spec)
    assert out_spec and out_spec[0] == "model", out_spec  # row-split


def test_video_tp_runs_and_shards(tmp_path):
    cfg = apply_overrides(
        get_config("ego4d_video_elastic"),
        [
            "model.image_size=32",
            "model.num_frames=4",
            "model.tubelet_size=2,8,8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.num_classes=8",
            "data.image_size=32",
            "data.num_frames=4",
            "data.num_classes=8",
            "data.global_batch_size=8",
            "precision.policy=fp32",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            "mesh.data=4",
            "mesh.model=2",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    blk = state.params["EncoderBlock_0"]["MlpBlock_0"]
    assert "model" in tuple(blk["Dense_0"]["kernel"].sharding.spec)
    for step in range(2):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_resnet_refuses_model_axis(tmp_path):
    """ResNet has no TP rules; a model>1 mesh must refuse loudly instead of
    silently replicating (VERDICT r1 missing #6)."""
    cfg = apply_overrides(
        get_config("imagenet_rn50_ddp"),
        ["model.depth=18", "data.image_size=32", "mesh.data=4",
         "mesh.model=2", f"workdir={tmp_path}"],
    )
    with pytest.raises(ValueError, match="no tensor-parallel"):
        Trainer(cfg)


def test_adafactor_weight_decay_is_adamw_semantics():
    """weight_decay must mean the same thing for every optimizer: per-step
    decay = lr * wd (decoupled), NOT optax.adafactor's raw multiplier
    (which would decay ~1/lr times harder for adamw-tuned configs)."""
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        OptimizerConfig,
        TrainerConfig,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.optimizers import make_optimizer

    lr, wd = 1e-2, 0.1
    params = {"w": jnp.full((4,), 2.0)}
    grads = {"w": jnp.zeros((4,))}  # zero grads isolate the decay term

    tx, _ = make_optimizer(
        OptimizerConfig(
            name="adafactor", learning_rate=lr, weight_decay=wd,
            schedule="constant", grad_clip_norm=None,
        ),
        TrainerConfig(total_steps=10),
    )
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    # update == -lr * wd * param exactly (zero gradient contribution).
    np.testing.assert_allclose(
        np.asarray(updates["w"]), -lr * wd * np.asarray(params["w"]),
        rtol=1e-6,
    )


def test_gpt_adafactor_trains_and_zero1_warns(tmp_path):
    """Adafactor (sublinear-memory LM optimizer) trains; under zero1 its
    factored v_row/v_col leaves can't mirror param specs and the partition
    layer's replication warning fires — the guard working on a real
    optimizer, not just a synthetic state tree."""
    from conftest import capture_frl_logs

    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        [
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            # >= optax's min_dim_size_to_factor (128) on two dims, so the
            # second moment actually factors into v_row/v_col.
            "model.hidden_dim=128",
            "model.seq_len=32",
            "data.vocab_size=128",
            "data.seq_len=32",
            "data.global_batch_size=16",
            "optimizer.name=adafactor",
            "optimizer.learning_rate=0.01",
            "optimizer.warmup_steps=0",
            "trainer.grad_accum=1",
            "trainer.log_every=1000",
            "precision.policy=fp32",
            "checkpoint.enabled=false",
            "mesh.fsdp=8",
            "parallel.fsdp_min_size=64",
            f"workdir={tmp_path}",
        ],
    )
    with capture_frl_logs() as records:
        trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(6):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    assert any("REPLICATED" in m for m in records), records


def test_ring_recipe_runs(tmp_path):
    """SP ring recipe (SURVEY C8) trains on a seq=4 mesh."""
    cfg = apply_overrides(
        get_config("gpt2_ring"),
        [
            "precision.policy=fp32",
            "trainer.log_every=1000",
            f"workdir={tmp_path}",
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=64",
            "data.vocab_size=128",
            "data.seq_len=64",
            "data.global_batch_size=8",
            "mesh.data=2",
            "mesh.seq=4",
            "optimizer.warmup_steps=0",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(6):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_recipe_runs(tmp_path):
    """EP recipe (SURVEY C9) trains on an expert=4 mesh."""
    cfg = apply_overrides(
        get_config("gpt2_moe"),
        [
            "precision.policy=fp32",
            "trainer.log_every=1000",
            f"workdir={tmp_path}",
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=32",
            "model.moe.num_experts=4",
            "data.vocab_size=128",
            "data.seq_len=32",
            "data.global_batch_size=16",
            "mesh.data=2",
            "mesh.expert=4",
            "optimizer.warmup_steps=0",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    # EP must actually be active (SURVEY C9): expert weights sharded over
    # the expert axis even with model=1 — a replicated-expert run would
    # still "learn" and pass the loss check below.
    wi = state.params["blocks"]["moe"]["wi"]
    assert "expert" in tuple(wi.sharding.spec), wi.sharding.spec
    losses = []
    for step in range(6):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_sort_dispatch_under_ep_mesh(tmp_path):
    """`moe.dispatch=sort` must COMPILE and train under a real expert-
    sharded mesh, and its first-step loss must match the einsum path on
    the same mesh — the scatter/gather exchange has no hand placed
    collectives, so this is the GSPMD-lowering coverage the equivalence
    unit test (single-logical-device) cannot give."""
    def run(dispatch):
        cfg = apply_overrides(
            get_config("gpt2_moe"),
            [
                "precision.policy=fp32",
                "trainer.log_every=1000",
                f"workdir={tmp_path}/{dispatch}",
                "model.vocab_size=128", "model.num_layers=2",
                "model.num_heads=4", "model.hidden_dim=64",
                "model.seq_len=32", "model.moe.num_experts=4",
                f"model.moe.dispatch={dispatch}",
                "data.vocab_size=128", "data.seq_len=32",
                "data.global_batch_size=16",
                "mesh.data=2", "mesh.expert=4",
                "optimizer.warmup_steps=0",
            ],
        )
        trainer = Trainer(cfg)
        state = trainer.init_state()
        state, metrics = trainer.train_step(
            state, trainer.pipeline.global_batch(0)
        )
        return float(metrics["loss"])

    # rtol is 1e-3, not 1e-5: the recipe runs bf16_mixed, and the two
    # dispatch formulations associate the bf16 exchange matmuls
    # differently (multi-core XLA reorders further) — a routing/seating
    # bug would show up at 1e-1 scale, not 1e-4. Exact fp32 equivalence
    # of outputs+grads is pinned by test_moe_sorted_matches_einsum.
    np.testing.assert_allclose(run("sort"), run("einsum"), rtol=1e-3)


def test_long_context_recipe_runs(tmp_path):
    """Single-chip long-context recipe (gpt2_long): flash + chunked-vocab
    loss + full remat, shrunk to CI size (flash falls back to dense off-TPU
    with identical numerics)."""
    smoke_run(
        "gpt2_long",
        [
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=256",
            "model.lm_loss_chunk=64",
            "data.vocab_size=128",
            "data.seq_len=256",
            "data.global_batch_size=8",
            "trainer.grad_accum=2",
            "mesh.data=8",
            "optimizer.warmup_steps=0",
        ],
        tmp_path,
        steps=6,
    )


def test_circular_pp_recipe_runs(tmp_path):
    """gpt2_pp_circular: the interleaved schedule end-to-end on a pipe=4
    mesh, with the bubble improvement visible in the summary."""
    from frl_distributed_ml_scaffold_tpu.parallel.pipeline import pipeline_summary

    overrides = [
        "model.vocab_size=128",
        "model.num_layers=8",
        "model.num_heads=2",
        "model.hidden_dim=32",
        "model.seq_len=32",
        "model.pipeline_microbatches=4",
        "data.vocab_size=128",
        "data.seq_len=32",
        "data.global_batch_size=8",
        "mesh.pipe=4",
        "mesh.data=2",
        "optimizer.warmup_steps=0",
        "optimizer.learning_rate=0.01",
        "trainer.grad_accum=1",
    ]
    cfg = apply_overrides(get_config("gpt2_pp_circular"), overrides)
    assert "circular(x2)" in pipeline_summary(cfg.model)
    smoke_run("gpt2_pp_circular", overrides, tmp_path, steps=5)


def test_rn101_recipe_runs(tmp_path):
    """Scale-up recipe: assert the registry default IS depth-101 (the
    (3,4,23,3) bottleneck stack), then train the recipe plumbing at
    depth=10 — a full depth-101 run costs ~70s of CPU-sim runtime and
    proves nothing the depth assertion plus the shared ResNet code paths
    don't already cover."""
    from frl_distributed_ml_scaffold_tpu.models.resnet import (
        BOTTLENECK,
        STAGE_SIZES,
    )

    cfg = get_config("imagenet_rn101_ddp")
    assert cfg.model.depth == 101
    assert STAGE_SIZES[101] == (3, 4, 23, 3) and BOTTLENECK[101]
    smoke_run(
        "imagenet_rn101_ddp",
        [
            "model.depth=10",
            "data.image_size=32",
            "data.num_classes=8",
            "model.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.learning_rate=0.05",
            "optimizer.warmup_steps=0",
            "mesh.data=8",
        ],
        tmp_path,
        steps=4,
    )


def test_vitl_recipe_runs(tmp_path):
    """ViT-L registration smoke at tiny shapes (hidden shrunk; the recipe
    default 307M params would swamp the CPU sim)."""
    smoke_run(
        "imagenet_vitl_fsdp",
        [
            "model.image_size=32",
            "model.patch_size=8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "data.image_size=32",
            "data.num_classes=8",
            "model.num_classes=8",
            "data.global_batch_size=16",
            "trainer.remat=none",
            "optimizer.warmup_steps=0",
            "mesh.fsdp=8",
        ],
        tmp_path,
        steps=4,
    )
