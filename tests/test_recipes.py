"""Integration tier (SURVEY §4): each BASELINE config as a shrunken smoke
run asserting the loss decreases, plus TP/SP/EP recipe variants."""

import jax
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, set_current_mesh
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


@pytest.fixture(autouse=True)
def clear_mesh_context():
    yield
    set_current_mesh(None)


def smoke_run(name, overrides, tmp_path, steps=8):
    cfg = apply_overrides(
        get_config(name),
        [
            "precision.policy=fp32",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ]
        + overrides,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], f"no learning: {losses}"
    return losses


def test_config2_rn50_ddp(tmp_path):
    smoke_run(
        "imagenet_rn50_ddp",
        [
            "model.depth=18",
            "data.image_size=32",
            "data.num_classes=8",
            "model.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.learning_rate=0.05",
            "optimizer.warmup_steps=0",
            "mesh.data=8",
        ],
        tmp_path,
    )


def test_config3_vitb_fsdp(tmp_path):
    smoke_run(
        "imagenet_vitb_fsdp",
        [
            "model.image_size=32",
            "model.patch_size=8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.num_classes=8",
            "data.image_size=32",
            "data.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.warmup_steps=0",
            "optimizer.learning_rate=1e-3",
            "mesh.fsdp=8",
            "parallel.fsdp_min_size=64",
        ],
        tmp_path,
    )


def test_config4_gpt2_zero1(tmp_path):
    smoke_run(
        "gpt2_medium_zero1",
        [
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=32",
            "data.vocab_size=128",
            "data.seq_len=32",
            "data.global_batch_size=16",
            "trainer.grad_accum=2",
            "optimizer.warmup_steps=0",
            "mesh.fsdp=8",
        ],
        tmp_path,
    )


def test_config5_video(tmp_path):
    smoke_run(
        "ego4d_video_elastic",
        [
            "model.image_size=16",
            "model.num_frames=4",
            "model.tubelet_size=2,8,8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.num_classes=8",
            "data.image_size=16",
            "data.num_frames=4",
            "data.num_classes=8",
            "data.global_batch_size=16",
            "optimizer.warmup_steps=0",
            "mesh.fsdp=8",
            "parallel.fsdp_min_size=64",
        ],
        tmp_path,
    )


GPT_TINY = [
    "model.vocab_size=128",
    "model.num_layers=2",
    "model.num_heads=4",
    "model.hidden_dim=64",
    "model.seq_len=32",
    "data.vocab_size=128",
    "data.seq_len=32",
    "data.global_batch_size=16",
    "trainer.grad_accum=1",
    "optimizer.warmup_steps=0",
]


def run_gpt(tmp_path, mesh_overrides, steps=6):
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["precision.policy=fp32", "trainer.log_every=1000", f"workdir={tmp_path}"]
        + GPT_TINY
        + mesh_overrides,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    return jax.device_get(state), metrics


def test_tp_matches_dp(tmp_path):
    """Tensor parallelism (SURVEY C6): TP=2 numerics == pure DP."""
    ref_state, _ = run_gpt(tmp_path / "dp", ["mesh.data=8", "mesh.fsdp=1"])
    tp_state, _ = run_gpt(
        tmp_path / "tp", ["mesh.data=4", "mesh.fsdp=1", "mesh.model=2"]
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-4),
        ref_state.params,
        tp_state.params,
    )


def test_tp_actually_shards_params(tmp_path):
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["precision.policy=fp32", f"workdir={tmp_path}"]
        + GPT_TINY
        + ["mesh.data=4", "mesh.model=2"],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    qk = state.params["blocks"]["attn"]["query"]["kernel"]
    assert "model" in tuple(qk.sharding.spec), qk.sharding.spec


def test_ring_recipe_runs(tmp_path):
    """SP ring recipe (SURVEY C8) trains on a seq=4 mesh."""
    cfg = apply_overrides(
        get_config("gpt2_ring"),
        [
            "precision.policy=fp32",
            "trainer.log_every=1000",
            f"workdir={tmp_path}",
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=64",
            "data.vocab_size=128",
            "data.seq_len=64",
            "data.global_batch_size=8",
            "mesh.data=2",
            "mesh.seq=4",
            "optimizer.warmup_steps=0",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(6):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_recipe_runs(tmp_path):
    """EP recipe (SURVEY C9) trains on an expert=4 mesh."""
    cfg = apply_overrides(
        get_config("gpt2_moe"),
        [
            "precision.policy=fp32",
            "trainer.log_every=1000",
            f"workdir={tmp_path}",
            "model.vocab_size=128",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.hidden_dim=64",
            "model.seq_len=32",
            "model.moe.num_experts=4",
            "data.vocab_size=128",
            "data.seq_len=32",
            "data.global_batch_size=16",
            "mesh.data=2",
            "mesh.expert=4",
            "optimizer.warmup_steps=0",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    # EP must actually be active (SURVEY C9): expert weights sharded over
    # the expert axis even with model=1 — a replicated-expert run would
    # still "learn" and pass the loss check below.
    wi = state.params["blocks"]["moe"]["wi"]
    assert "expert" in tuple(wi.sharding.spec), wi.sharding.spec
    losses = []
    for step in range(6):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]