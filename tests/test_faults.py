"""Chaos suite (ISSUE 9): the fault matrix, pinned.

Every injected fault class must prove BOTH detection and recovery
(docs/operations.md "Failure semantics" is the human-readable matrix
this file enforces):

- torn / corrupt checkpoint  → commit markers skip it, restore falls
  back down the committed chain, the directory is reported, not deleted;
- loader exception           → unified retry policy rebuilds the batch
  (pure function of step), permanent faults still kill the run loudly;
- hung step                  → the stall watchdog fires;
- SIGTERM preemption         → synchronized checkpoint + clean exit +
  exact resume;
- serving queue overflow /
  deadline overrun /
  poison request /
  cache-grow failure         → typed completions, slot freed for refill,
  and — the acceptance headline — every NON-faulted request stays
  token-identical to its solo ``generate()`` run under chaos;
- heartbeat-write failures   → counted, membership record retired after
  N consecutive so peers evict deterministically.

Injection is the seeded ``FaultPlan`` (faults/plan.py): deterministic,
no-op unarmed, telemetry-counted.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.chaos

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_init

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    PrecisionConfig,
    ServingConfig,
)
from frl_distributed_ml_scaffold_tpu.faults import (
    FaultPlan,
    FaultSpec,
    RetryPolicy,
)
from frl_distributed_ml_scaffold_tpu.models.generation import generate
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.serving import ServingEngine
from frl_distributed_ml_scaffold_tpu.telemetry import MetricsRegistry
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


# ----------------------------------------------------------- plan + retry


@pytest.mark.fast
def test_fault_plan_fires_on_exact_occurrence_window():
    """at/times index MATCHING consultations 1-based and deterministically;
    unarmed sites cost one dict lookup and never fire."""
    plan = FaultPlan([FaultSpec(site="serve.grow", at=3, times=2)])
    fired = [plan.fire("serve.grow") is not None for _ in range(6)]
    assert fired == [False, False, True, True, False, False]
    assert plan.injected == {"serve.grow": 2}
    assert plan.fire("checkpoint.torn_write") is None  # unarmed site
    # times=0: every consultation from `at` on.
    forever = FaultPlan([dict(site="data.loader", at=2, times=0)])
    assert [forever.fire("data.loader") is not None for _ in range(4)] == [
        False, True, True, True,
    ]
    # Two specs stacked on ONE site count consultations independently:
    # at=1 and at=2 fire on consultations 1 and 2 (an early return after
    # the first spec would make the second window fire late).
    stacked = FaultPlan(
        [dict(site="serve.grow", at=1), dict(site="serve.grow", at=2)]
    )
    assert [stacked.fire("serve.grow") is not None for _ in range(3)] == [
        True, True, False,
    ]
    assert stacked.injected == {"serve.grow": 2}


@pytest.mark.fast
def test_fault_plan_keyed_matching_and_seeded_probability():
    """A keyed spec counts only matching consultations; p<1 draws ride
    the plan's seed, so the same seed replays the same chaos."""
    plan = FaultPlan([dict(site="serve.prefill", key="7", at=2)])
    seq = [
        plan.fire("serve.prefill", k) is not None
        for k in ("5", "7", "5", "7", "7")
    ]
    # Consultations with key "7" are #1, #2, #3 of the spec: fires on #2.
    assert seq == [False, False, False, True, False]

    def draws(seed):
        p = FaultPlan([dict(site="data.loader", times=0, p=0.5)], seed=seed)
        return [p.fire("data.loader") is not None for _ in range(32)]

    assert draws(3) == draws(3)
    assert draws(3) != draws(4)  # astronomically unlikely to collide
    assert 0 < sum(draws(3)) < 32


@pytest.mark.fast
def test_fault_plan_env_roundtrip_and_refusals():
    plan = FaultPlan.from_env(
        '{"seed": 5, "specs": [{"site": "trainer.hung_step", "arg": 0.25}]}'
    )
    assert plan.seed == 5 and plan.sites == ["trainer.hung_step"]
    assert FaultPlan.from_env('[{"site": "serve.grow"}]').sites == ["serve.grow"]
    with pytest.raises(ValueError, match="not valid JSON"):
        FaultPlan.from_env("serve.grow@3")
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan([dict(site="serve.typo")])
    with pytest.raises(ValueError, match="at="):
        FaultPlan([dict(site="serve.grow", at=0)])


@pytest.mark.fast
def test_fault_plan_counts_injections_in_telemetry():
    reg = MetricsRegistry()
    plan = FaultPlan(
        [dict(site="serve.grow", times=2)], registry=reg
    )
    # Armed-site counters exist at 0 before any firing (catalog contract).
    assert reg.counter("fault_injected_serve_grow_total").value == 0
    for _ in range(5):
        plan.fire("serve.grow")
    assert reg.counter("fault_injected_total").value == 2
    assert reg.counter("fault_injected_serve_grow_total").value == 2


@pytest.mark.fast
def test_ambient_plan_scoping():
    assert faults.fire("serve.grow") is None
    with faults.active(FaultPlan([dict(site="serve.grow", times=0)])) as p:
        assert faults.fire("serve.grow") is p._by_site["serve.grow"][0]
        with pytest.raises(OSError):
            faults.maybe_raise("serve.grow", OSError)
    assert faults.fire("serve.grow") is None  # restored on exit


@pytest.mark.fast
def test_retry_policy_delays_and_budget():
    rp = RetryPolicy(max_retries=4, backoff_s=0.5, max_backoff_s=3.0)
    assert [rp.delay(i) for i in (1, 2, 3, 4)] == [0.5, 1.0, 2.0, 3.0]
    jit = RetryPolicy(max_retries=6, backoff_s=1.0, jitter=0.5, seed=9)
    a, b = list(jit.delays()), list(jit.delays())
    assert a == b  # seeded jitter replays
    assert all(0.0 < d for d in a) and any(d != jit.delay(i + 1) or True for i, d in enumerate(a))
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    slept: list[float] = []
    assert rp.call(flaky, sleep=slept.append) == "ok"
    assert calls["n"] == 3 and slept == [0.5, 1.0]

    def always():
        raise OSError("permanent")

    counter = MetricsRegistry().counter("retries")
    with pytest.raises(OSError, match="permanent"):
        rp.call(always, sleep=lambda d: None, counter=counter)
    # Only PERFORMED retries count — the budget-exhausting failure
    # propagates, it is not a retry (no phantom attempt in the ledger).
    assert counter.value == rp.max_retries

    # Exceptions outside retry_on propagate immediately (no absorption).
    def wrong():
        raise KeyError("bug")

    with pytest.raises(KeyError):
        rp.call(wrong, retry_on=(OSError,), sleep=lambda d: None)


# ----------------------------------------------------------------- serving


FP32 = get_policy(PrecisionConfig(policy="fp32"))
TINY = dict(
    vocab_size=64, num_layers=2, num_heads=4, hidden_dim=64, seq_len=64,
    dropout=0.0,
)


@pytest.fixture(scope="module")
def gpt():
    model = GPT(GPTConfig(**TINY), FP32)
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params


def _solo(model, params, prompt, n_new):
    ref = generate(
        model, params, jnp.asarray(prompt)[None], max_new_tokens=n_new,
        temperature=0.0,
    )
    return np.asarray(ref)[0]


@pytest.mark.fast
@pytest.mark.serving
def test_queue_overflow_sheds_typed_completions(gpt):
    """Submits beyond max_queue_depth resolve IMMEDIATELY as typed
    "shed" completions (prompt back, zero tokens), counted, while
    admitted requests serve normally — run() never hangs on a shed id."""
    model, params = gpt
    eng = ServingEngine(
        model, params, num_slots=1, temperature=0.0, max_queue_depth=2
    )
    rids = [eng.submit(np.arange(4, dtype=np.int32) + i, 3) for i in range(5)]
    done = {c.id: c for c in eng.run()}
    assert sorted(done) == sorted(rids), "an id never resolved"
    reasons = [done[r].finish_reason for r in rids]
    # No step ran between submits, so the queue only fills: r0, r1 make
    # depth 2 and every later submit sheds — exactly 3 typed sheds.
    assert reasons == ["length", "length", "shed", "shed", "shed"]
    for rid in rids[:2]:
        assert done[rid].ok
        np.testing.assert_array_equal(
            done[rid].tokens,
            _solo(model, params, np.asarray(done[rid].tokens[:done[rid].prompt_len]), 3),
        )
    for rid in rids[3:]:
        c = done[rid]
        assert c.finish_reason == "shed" and len(c.tokens) == c.prompt_len
        assert c.token_latencies_s == []
    assert eng.telemetry.counter("serve_shed_total").value == 3
    assert eng.stats["finish_shed"] == 3
    eng.close()


@pytest.mark.fast
@pytest.mark.serving
def test_deadline_expired_in_queue_sheds_before_prefill(gpt):
    """A request whose deadline passes while QUEUED is shed at admission
    (no prefill work wasted on an abandoned answer) with a typed
    "deadline" completion; the slot admits the next request instead."""
    model, params = gpt
    eng = ServingEngine(model, params, num_slots=1, temperature=0.0)
    ra = eng.submit(np.arange(5, dtype=np.int32), 6, deadline_s=1e-6)
    rb = eng.submit(np.arange(5, dtype=np.int32) + 2, 3)
    time.sleep(0.01)  # let ra's deadline lapse before any admission
    done = {c.id: c for c in eng.run()}
    assert done[ra].finish_reason == "deadline"
    assert len(done[ra].tokens) == done[ra].prompt_len  # nothing generated
    assert done[rb].ok
    np.testing.assert_array_equal(
        done[rb].tokens, _solo(model, params, np.arange(5, dtype=np.int32) + 2, 3)
    )
    assert eng.telemetry.counter("serve_deadline_miss_total").value == 1
    assert eng.stats["prefill_8"] == 1  # only rb was prefilled
    eng.close()


@pytest.mark.fast
@pytest.mark.serving
def test_deadline_mid_decode_cancels_and_frees_slot(gpt):
    """Mid-decode cancellation: an in-flight request past its deadline
    retires with the tokens generated SO FAR (typed "deadline"), the
    slot refills, and the refilled request completes token-identically."""
    model, params = gpt
    eng = ServingEngine(model, params, num_slots=1, temperature=0.0)
    ra = eng.submit(np.arange(5, dtype=np.int32), 30, deadline_s=60.0)
    rb = eng.submit(np.arange(6, dtype=np.int32), 3)
    first = eng.step()  # prefill + first decode tick for ra
    assert not first and eng._active[0]
    # Deterministic expiry: collapse ra's deadline after real decode work
    # has happened (wall-clock thresholds would flake on a loaded box).
    eng._req[0].deadline_s = 1e-6
    done = {c.id: c for c in first + eng.run()}
    assert done[ra].finish_reason == "deadline"
    n_partial = len(done[ra].tokens) - done[ra].prompt_len
    assert n_partial >= 1, "cancellation should carry the partial answer"
    assert len(done[ra].token_latencies_s) == n_partial
    # The freed slot served rb to completion, token-identical.
    assert done[rb].ok
    np.testing.assert_array_equal(
        done[rb].tokens, _solo(model, params, np.arange(6, dtype=np.int32), 3)
    )
    assert eng.telemetry.counter("serve_deadline_miss_total").value == 1
    eng.close()


@pytest.mark.fast
@pytest.mark.serving
def test_poison_request_quarantined_batch_survives(gpt):
    """One failing request cannot wedge the batch: the poisoned prefill
    yields a typed "error" completion + quarantine counter, concurrent
    requests stay token-identical, and the engine keeps admitting new
    work afterwards."""
    model, params = gpt
    eng = ServingEngine(model, params, num_slots=2, temperature=0.0)
    ra = eng.submit(np.arange(5, dtype=np.int32), 4)
    rb = eng.submit(np.arange(6, dtype=np.int32), 4)
    with faults.active(FaultPlan([dict(site="serve.prefill", key=str(ra))])):
        done = {c.id: c for c in eng.run()}
    assert done[ra].finish_reason == "error"
    assert done[rb].ok
    np.testing.assert_array_equal(
        done[rb].tokens, _solo(model, params, np.arange(6, dtype=np.int32), 4)
    )
    assert eng.telemetry.counter("serve_quarantined_total").value == 1
    # Plan disarmed: the same prompt now serves fine (nothing latched).
    rc = eng.submit(np.arange(5, dtype=np.int32), 4)
    done2 = {c.id: c for c in eng.run()}
    assert done2[rc].ok
    np.testing.assert_array_equal(
        done2[rc].tokens, _solo(model, params, np.arange(5, dtype=np.int32), 4)
    )
    eng.close()


@pytest.mark.fast
@pytest.mark.serving
def test_quarantine_is_rng_neutral_for_sampled_decode(gpt):
    """A quarantined admission rolls the engine RNG back, so chaos
    token-identity holds for SAMPLED (temperature>0) decode too: the
    healthy request sees exactly the splits a fault-free engine would
    have handed it, poison or no poison."""
    model, params = gpt
    prompt = np.arange(5, dtype=np.int32)

    ref_eng = ServingEngine(model, params, num_slots=2, temperature=0.7)
    rid = ref_eng.submit(prompt, 6)
    ref = {c.id: c for c in ref_eng.run()}[rid].tokens
    ref_eng.close()

    eng = ServingEngine(model, params, num_slots=2, temperature=0.7)
    pid = eng.submit(np.arange(3, dtype=np.int32), 4)  # poisoned first
    hid = eng.submit(prompt, 6)
    with faults.active(
        FaultPlan([dict(site="serve.prefill", key=str(pid), times=0)])
    ):
        done = {c.id: c for c in eng.run()}
    assert done[pid].finish_reason == "error"
    np.testing.assert_array_equal(done[hid].tokens, ref)
    eng.close()


@pytest.mark.fast
@pytest.mark.serving
def test_grow_failure_degrades_not_dies(gpt):
    """A cache-grow allocation failure retires only the rows that NEED
    the larger bucket (typed "error", partial tokens carried); rows
    inside the current bucket — INCLUDING one sitting exactly at
    ``_len == bucket``, which needs capacity exactly ``_len`` and so
    still fits — finish token-identically and the engine grows fine once
    the fault clears."""
    model, params = gpt
    eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0, min_bucket=8
    )
    ra = eng.submit(np.arange(4, dtype=np.int32), 30)  # needs bucket 16+
    # Admitted the same step as ra (prompt 3 -> _len 4 after prefill), so
    # when ra forces the grow (its _len hits 9) rb sits at _len == 8: the
    # bucket-boundary row the victim cut must NOT retire.
    rb = eng.submit(np.arange(3, dtype=np.int32) + 1, 10)
    with faults.active(FaultPlan([dict(site="serve.grow")])):  # fires once
        done = {c.id: c for c in eng.run()}
    assert done[ra].finish_reason == "error"
    assert len(done[ra].tokens) > done[ra].prompt_len  # partial answer
    # rb survived the failed grow at the boundary, then grew for real
    # once the one-shot fault was exhausted (its own _len passes 8).
    assert done[rb].ok
    np.testing.assert_array_equal(
        done[rb].tokens,
        _solo(model, params, np.arange(3, dtype=np.int32) + 1, 10),
    )
    assert eng.telemetry.counter("serve_grow_failures_total").value >= 1
    assert eng.stats["grow_failures"] >= 1
    # Fault cleared: the same big request now grows and completes.
    rc = eng.submit(np.arange(4, dtype=np.int32), 30)
    done2 = {c.id: c for c in eng.run()}
    assert done2[rc].ok
    np.testing.assert_array_equal(
        done2[rc].tokens, _solo(model, params, np.arange(4, dtype=np.int32), 30)
    )
    eng.close()


@pytest.mark.fast
@pytest.mark.serving
def test_draft_failure_degrades_slot_to_plain_decode(gpt):
    """ISSUE 11 fault-matrix row: the ``serve.draft`` site fails the
    speculative draft proposer mid-decode — the hit slot degrades to
    plain single-token decode for the REST of its request (sticky,
    counted in serve_spec_draft_failures_total), the batch never sheds
    or hangs (every id resolves), tokens stay identical to generate()
    (drafting is advisory — acceptance was exact anyway), and a NEW
    request admitted after the fault clears speculates again."""
    model, params = gpt
    rep = np.tile(np.asarray([7, 11, 13, 5], np.int32), 5)
    rand = np.arange(9, dtype=np.int32) * 5 % 64
    eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0,
        kv_block_size=8, speculate="ngram", speculate_k=4,
    )
    # at=2: the first propose round works (verify steps happen), the
    # second consultation kills ONE slot's drafting.
    with faults.active(FaultPlan([dict(site="serve.draft", at=2, times=1)])):
        ra = eng.submit(rep, 10)
        rb = eng.submit(rand, 6)
        done = {c.id: c for c in eng.run()}
    assert sorted(done) == [ra, rb], "a faulted slot hung or shed"
    for rid, (p, n) in {ra: (rep, 10), rb: (rand, 6)}.items():
        assert done[rid].ok
        np.testing.assert_array_equal(
            done[rid].tokens, _solo(model, params, p, n),
            err_msg=f"request {rid} diverged under draft failure",
        )
    assert eng.stats["spec_draft_failures"] == 1
    assert (
        eng.telemetry.counter("serve_spec_draft_failures_total").value == 1
    )
    # Fault cleared + slot re-admitted: speculation resumes (the
    # degradation is per-request, not per-engine) — drafts are proposed
    # and verify steps run again.
    before = eng.stats["decode_verify"]
    before_prop = eng.stats["spec_proposed"]
    rc = eng.submit(rep, 8)
    done2 = {c.id: c for c in eng.run()}
    assert done2[rc].ok
    assert eng.stats["decode_verify"] > before
    assert eng.stats["spec_proposed"] > before_prop
    eng.close()


@pytest.mark.serving
def test_chaos_non_faulted_requests_token_identical(gpt):
    """The acceptance headline: queue bound + deadlines + poison at once,
    and every NON-faulted request still equals its solo generate() run
    token-for-token, while every faulted one gets a typed completion.
    ServingConfig knobs drive the engine the way a production config
    would."""
    from frl_distributed_ml_scaffold_tpu.analysis import pins

    model, params = gpt
    scfg = ServingConfig(max_queue_depth=4, default_deadline_s=0.0)
    # The lock-order sentinel (ISSUE 20) rides the chaos headline: every
    # package lock the engine creates under fault injection is recorded,
    # and the acquisition order must stay acyclic.
    with faults.instrumented_locks() as locks_rec:
        eng = ServingEngine(
            model, params, num_slots=2, temperature=0.0, serving=scfg,
        )
        rng = np.random.default_rng(0)
        reqs = {}
        poison_rid = 1  # ids are sequential on a fresh engine
        with faults.active(
            FaultPlan(
                [dict(site="serve.prefill", key=str(poison_rid), times=0)]
            )
        ):
            for i in range(6):
                prompt = rng.integers(
                    0, 64, size=int(rng.integers(2, 10))
                ).astype(np.int32)
                n_new = int(rng.integers(2, 6))
                dl = 1e-6 if i == 2 else 0.0  # request 2: instant deadline
                rid = eng.submit(prompt, n_new, deadline_s=dl)
                reqs[rid] = (prompt, n_new)
            done = {c.id: c for c in eng.run()}
    pins.assert_lock_order_acyclic(locks_rec)
    assert sorted(done) == sorted(reqs), "every id resolves exactly once"
    reasons = {rid: done[rid].finish_reason for rid in sorted(done)}
    assert reasons[poison_rid] == "error"
    assert reasons[2] == "deadline"
    assert list(reasons.values()).count("shed") == 2  # submits 4, 5 overflowed
    ok = [rid for rid, c in done.items() if c.ok]
    assert ok, reasons
    for rid in ok:
        prompt, n_new = reqs[rid]
        np.testing.assert_array_equal(
            done[rid].tokens, _solo(model, params, prompt, n_new),
            err_msg=f"request {rid} diverged under chaos",
        )
    t = eng.telemetry
    assert t.counter("serve_shed_total").value == 2
    assert t.counter("serve_quarantined_total").value == 1
    assert t.counter("serve_deadline_miss_total").value == 1
    eng.close()


# -------------------------------------------------------------- checkpoint


def _trainer_cfg(tmp_path, extra=()):
    return apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=6",
            "trainer.log_every=3",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "model.hidden_sizes=32",
            "precision.policy=fp32",
            f"workdir={tmp_path}",
        ]
        + list(extra),
    )


CKPT = [
    "checkpoint.enabled=true",
    "checkpoint.save_every=2",
    "checkpoint.async_save=false",
]


def test_torn_checkpoint_write_skipped_and_resumed_from_last_good(tmp_path):
    """Satellite 3 + tentpole (c): a torn write at step 6 (third save) is
    invisible to latest_step(), restore_or_init resumes from step 4 (last
    committed), training completes, and the torn directory is REPORTED
    and left on disk."""
    cfg = _trainer_cfg(tmp_path, CKPT)
    with faults.active(FaultPlan([dict(site="checkpoint.torn_write", at=3)])):
        t = Trainer(cfg)
        t.fit()
        t.checkpointer.close()

    fresh = Trainer(cfg)
    ck = fresh.checkpointer
    assert ck.all_steps(include_uncommitted=True) == [2, 4, 6]
    assert ck.all_steps() == [2, 4]
    assert ck.latest_step() == 4
    assert ck.uncommitted_steps() == [6]
    # The torn directory is reported, never silently deleted.
    assert os.path.isdir(os.path.join(str(tmp_path), cfg.name, "ckpt", "6"))

    restored = ck.restore_or_init(fresh)
    assert int(jax.device_get(restored.step)) == 4
    state, _ = fresh.fit(restored)
    assert int(jax.device_get(state.step)) == 6
    fresh.checkpointer.close()


def test_corrupt_committed_step_falls_back_down_chain(tmp_path):
    """Bit rot a marker cannot see: a COMMITTED step whose payload is
    truncated fails restore, is recorded in corrupt_steps (dir kept), and
    restore_or_init lands on the previous committed step."""
    import glob

    cfg = _trainer_cfg(tmp_path, CKPT)
    t = Trainer(cfg)
    t.fit()
    t.checkpointer.close()

    files = [
        p
        for p in glob.glob(
            os.path.join(str(tmp_path), cfg.name, "ckpt", "6", "**", "*"),
            recursive=True,
        )
        if os.path.isfile(p)
    ]
    victim = max(files, key=os.path.getsize)
    with open(victim, "r+b") as fh:
        fh.truncate(3)

    fresh = Trainer(cfg)
    restored = fresh.checkpointer.restore_or_init(fresh)
    assert int(jax.device_get(restored.step)) == 4
    assert fresh.checkpointer.corrupt_steps == [6]
    assert os.path.isdir(os.path.join(str(tmp_path), cfg.name, "ckpt", "6"))
    fresh.checkpointer.close()


def test_legacy_checkpoint_dir_without_markers_still_restores(tmp_path):
    """Directories written before the commit-marker protocol (no
    commits/ dir) are honored wholesale — the marker protocol must not
    orphan existing checkpoints."""
    cfg = _trainer_cfg(tmp_path, CKPT)
    t = Trainer(cfg)
    t.fit()
    t.checkpointer.close()
    shutil.rmtree(os.path.join(str(tmp_path), cfg.name, "ckpt", "commits"))

    fresh = Trainer(cfg)
    assert fresh.checkpointer.latest_step() == 6
    assert fresh.checkpointer.uncommitted_steps() == []
    restored = fresh.checkpointer.restore_or_init(fresh)
    assert int(jax.device_get(restored.step)) == 6
    fresh.checkpointer.close()


def test_first_commit_backfills_legacy_markers(tmp_path):
    """The FIRST new-protocol save in a pre-marker directory backfills
    markers for the legacy steps atomically — they were committed
    wholesale and must STAY committed once commits/ exists (otherwise
    one new save would flip the entire pre-existing history to
    "uncommitted" and a crash mid-transition could orphan it)."""
    cfg = _trainer_cfg(tmp_path, CKPT)
    t = Trainer(cfg)
    t.fit()
    t.checkpointer.close()
    shutil.rmtree(os.path.join(str(tmp_path), cfg.name, "ckpt", "commits"))

    cfg2 = _trainer_cfg(tmp_path, CKPT + ["trainer.total_steps=8"])
    fresh = Trainer(cfg2)
    restored = fresh.checkpointer.restore_or_init(fresh)
    assert int(jax.device_get(restored.step)) == 6  # wholesale honor
    fresh.fit(restored)  # saves step 8 -> first _commit backfills
    fresh.checkpointer.close()

    ck = Trainer(cfg2).checkpointer
    # max_to_keep=3 garbage-collected step 2 when 8 landed; the legacy
    # steps that remain on disk (4, 6) stayed committed through the
    # transition instead of flipping to "uncommitted".
    assert ck.all_steps() == [4, 6, 8]
    assert ck.uncommitted_steps() == []
    assert ck.latest_step() == 8
    ck.close()


def test_async_saves_commit_at_wait(tmp_path):
    """Async saves stay uncommitted until wait()/close() proves the bytes
    (fit() waits in its final block, so a normal run commits everything)."""
    cfg = _trainer_cfg(
        tmp_path,
        ["checkpoint.enabled=true", "checkpoint.save_every=2",
         "checkpoint.async_save=true"],
    )
    t = Trainer(cfg)
    t.fit()
    t.checkpointer.close()
    fresh = Trainer(cfg)
    assert fresh.checkpointer.latest_step() == 6
    assert fresh.checkpointer.uncommitted_steps() == []
    fresh.checkpointer.close()


# ----------------------------------------------------------------- trainer


def test_loader_fault_retried_and_run_completes(tmp_path):
    """A transient loader exception is retried under the unified policy
    (the batch is a pure function of step — the rebuild is exact) and
    the run completes; retries are observable."""
    cfg = _trainer_cfg(tmp_path)
    with faults.active(FaultPlan([dict(site="data.loader", key="2")])):
        t = Trainer(cfg)
        state, _ = t.fit()
    assert int(jax.device_get(state.step)) == 6
    assert t.pipeline.loader_retries >= 1


def test_loader_permanent_fault_raises_after_budget(tmp_path):
    """A permanently failing loader exhausts the budget and propagates —
    loud death, not an infinite retry spin."""
    cfg = _trainer_cfg(tmp_path, ["data.loader_retry_backoff_s=0.001"])
    with faults.active(FaultPlan([dict(site="data.loader", key="2", times=0)])):
        t = Trainer(cfg)
        with pytest.raises(RuntimeError, match="injected fault: data.loader"):
            t.fit()


@pytest.mark.obs
def test_hung_step_fires_stall_watchdog(tmp_path):
    """A hung step (injected 0.5 s silence against a 0.06 s deadline) is
    DETECTED: stalls_total fires and the dump lands, while the run still
    completes once the hang clears (recovery = the loop was only slow,
    not dead — the watchdog's job is the report)."""
    cfg = _trainer_cfg(
        tmp_path,
        ["trainer.stall_timeout_s=0.06",
         "trainer.stall_timeout_first_beat_scale=200"],
    )
    with faults.active(
        FaultPlan([dict(site="trainer.hung_step", key="3", arg=0.5)])
    ):
        t = Trainer(cfg)
        state, _ = t.fit()
    assert int(jax.device_get(state.step)) == 6
    run_dir = os.path.join(str(tmp_path), cfg.name)
    prom = open(os.path.join(run_dir, "metrics.prom")).read()
    stalls = [
        l for l in prom.splitlines()
        if l.startswith("stalls_total ")
    ]
    assert stalls and float(stalls[0].split()[-1]) >= 1, prom
    assert os.path.exists(os.path.join(run_dir, "stall_dump.txt"))


def test_preempt_fault_checkpoints_and_resumes_exactly(tmp_path):
    """The trainer.preempt site delivers our own SIGTERM: the in-flight
    step finishes, a synchronized checkpoint lands (the elastic
    supervisor reads the clean rc 0 as completion — the budget-free
    path), and a fresh run resumes with no step lost or duplicated."""
    cfg = _trainer_cfg(
        tmp_path,
        ["trainer.total_steps=10", "trainer.log_every=2",
         "checkpoint.enabled=true", "checkpoint.save_every=100",
         "checkpoint.async_save=false"],
    )
    with faults.active(FaultPlan([dict(site="trainer.preempt", key="4")])):
        t = Trainer(cfg)
        state, last = t.fit()
    assert last.get("event") == "preempted"
    assert int(jax.device_get(state.step)) == 5
    assert t.checkpointer.latest_step() == 5
    t.checkpointer.close()

    resumed = Trainer(cfg)
    state2, _ = resumed.fit()
    assert int(jax.device_get(state2.step)) == 10
    with open(os.path.join(str(tmp_path), cfg.name, "metrics.jsonl")) as fh:
        steps = [json.loads(l)["step"] for l in fh]
    assert steps == [2, 4, 5, 6, 8, 10], steps
    resumed.checkpointer.close()


def test_preempt_save_knob_off_skips_forced_save(tmp_path):
    """trainer.preempt_save=false: the preemption still exits cleanly
    (finish step, clean return) but writes no forced checkpoint — the
    externally-managed-checkpoints escape hatch."""
    cfg = _trainer_cfg(
        tmp_path,
        ["trainer.total_steps=10", "trainer.preempt_save=false",
         "checkpoint.enabled=true", "checkpoint.save_every=100",
         "checkpoint.async_save=false"],
    )
    with faults.active(FaultPlan([dict(site="trainer.preempt", key="4")])):
        t = Trainer(cfg)
        state, last = t.fit()
    assert last.get("event") == "preempted"
    assert int(jax.device_get(state.step)) == 5
    assert t.checkpointer.latest_step() is None  # nothing ever saved
    t.checkpointer.close()


# ----------------------------------------------------------------- elastic


@pytest.mark.fast
def test_heartbeat_failures_counted_then_record_retired(tmp_path):
    """Satellite 1: heartbeat-write failures are counted
    (heartbeat_write_failures_total) and after N consecutive failures the
    membership record is RETIRED (unlinked, thread stopped) so peers
    evict deterministically instead of racing the staleness window."""
    from frl_distributed_ml_scaffold_tpu.analysis import pins
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import _Membership

    # Sentinel (ISSUE 20): the heartbeat thread's _beat_lock nests over
    # FaultPlan._lock (maybe_raise) over MetricsRegistry._lock (inc) —
    # a real three-deep chain that must record acyclic.
    with faults.instrumented_locks() as locks_rec:
        reg = MetricsRegistry()
        m = _Membership(str(tmp_path), uid=1, endpoint="h:1", registry=reg)
        # First beat succeeds (the record exists), then the FS "dies".
        with faults.active(
            FaultPlan([dict(site="elastic.heartbeat_write", at=2, times=0)])
        ):
            m.start(interval_s=0.02, retire_after=3)
            assert os.path.exists(m.path)
            deadline = time.monotonic() + 5
            while m._thread.is_alive() and time.monotonic() < deadline:
                time.sleep(0.02)
        assert not m._thread.is_alive(), "thread should have self-retired"
        assert not os.path.exists(m.path), "record should be unlinked"
        assert reg.counter("heartbeat_write_failures_total").value >= 3
        m.stop()
    pins.assert_lock_order_acyclic(locks_rec)
    pins.assert_no_blocking_under_lock(locks_rec)


@pytest.mark.fast
def test_heartbeat_transient_failures_recover_without_retirement(tmp_path):
    """Consecutive-failure accounting resets on success: a 2-failure blip
    under retire_after=3 keeps the membership record alive."""
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import _Membership

    reg = MetricsRegistry()
    m = _Membership(str(tmp_path), uid=2, endpoint="h:2", registry=reg)
    with faults.active(
        FaultPlan([dict(site="elastic.heartbeat_write", at=2, times=2)])
    ):
        m.start(interval_s=0.02, retire_after=3)
        deadline = time.monotonic() + 2
        while (
            reg.counter("heartbeat_write_failures_total").value < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        time.sleep(0.1)  # several healthy beats after the blip
    assert m._thread.is_alive(), "a 2-failure blip must not retire"
    assert os.path.exists(m.path)
    assert reg.counter("heartbeat_write_failures_total").value == 2
    m.stop()


def test_sigterm_fault_under_supervision_exits_clean(tmp_path):
    """FRL_FAULT_SIGNAL=TERM: the supervised child preempts itself
    gracefully at the fault step — checkpoint, rc 0 — and the supervisor
    reads the clean exit as completion (the budget-free preemption
    path), with the checkpoint ready for the next scheduled launch."""
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import supervise
    from frl_distributed_ml_scaffold_tpu.launcher.launch import _parse_args

    overrides = [
        "trainer.total_steps=12",
        "trainer.log_every=4",
        "trainer.eval_every=0",
        "data.global_batch_size=64",
        "model.hidden_sizes=32",
        "precision.policy=fp32",
        "checkpoint.save_every=100",
        "checkpoint.async_save=false",
        "elastic.backoff_s=0.1",
        f"workdir={tmp_path}",
    ]
    args = _parse_args(
        ["--config", "mnist_mlp", "--device", "cpu", "--sim-devices", "8",
         "--elastic"] + overrides
    )
    cfg = apply_overrides(get_config("mnist_mlp"), overrides)
    os.environ["FRL_FAULT_AT_STEP"] = "5"
    os.environ["FRL_FAULT_SIGNAL"] = "TERM"
    try:
        rc = supervise(args, cfg)
    finally:
        del os.environ["FRL_FAULT_AT_STEP"]
        del os.environ["FRL_FAULT_SIGNAL"]
    assert rc == 0
    run_dir = os.path.join(str(tmp_path), cfg.name)
    assert os.path.exists(os.path.join(run_dir, "fault_injected"))
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        recs = [json.loads(l) for l in fh]
    # The child preempted at step 5 (graceful path logs the event)...
    assert any(r.get("event") == "preempted" and r["step"] == 5 for r in recs)
    # ...and the synchronized checkpoint is committed at that step.
    from frl_distributed_ml_scaffold_tpu.checkpoint.manager import Checkpointer

    ck = Checkpointer(os.path.join(run_dir, "ckpt"), cfg.checkpoint)
    assert ck.latest_step() == 5
    ck.close()


# -------------------------------------------------------------- serve_bench


@pytest.mark.serving
def test_serve_bench_chaos_arm_reports_rates(capsys):
    """Satellite 5: the --chaos arm reports shed rate, deadline-miss
    rate, quarantine count, and non-faulted p99 — and the base row's
    measured pass is unaffected (completed == requests)."""
    import sys as _sys

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools not in _sys.path:
        _sys.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            "--preset", "tiny", "--requests", "6", "--slots", "2",
            "--max-new", "4", "--sim-devices", "0",
            "--arms", "dense_replicated", "--chaos",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines() if l.startswith("{")
    ]
    assert len(lines) == 1
    s = json.loads(lines[0])["serving"]
    assert s["engine_stats"]["completed"] == 6  # measured pass untouched
    c = s["chaos"]
    assert c["requests"] == 6
    assert c["shed_rate"] > 0 and c["deadline_miss_rate"] > 0
    assert c["quarantined"] == 1 and c["injected"] == {"serve.prefill": 1}
    assert c["completed_ok"] >= 1 and c["nonfaulted_p99_ms"] > 0
    total = (
        c["by_reason"].get("shed", 0)
        + c["by_reason"].get("deadline", 0)
        + c["by_reason"].get("error", 0)
        + c["completed_ok"]
    )
    assert total == c["requests"], c  # every request resolved, typed


@pytest.mark.serving
def test_prefill_worker_death_requeues_and_completes_identically(gpt):
    """ISSUE 12 fault-matrix row, ``serve.prefill_worker``: the prefill
    worker dying mid-request re-queues it at the head of its tenant
    queue (typed, counted — worker failures + requeue stat) and the
    retry completes TOKEN-IDENTICALLY; the decode worker's running slots
    never notice. The never-hangs contract extends across the worker
    boundary: every submitted id resolves exactly once."""
    from frl_distributed_ml_scaffold_tpu.serving import DisaggServingEngine

    model, params = gpt
    eng = DisaggServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    pa, pb = np.arange(5, dtype=np.int32), np.arange(6, dtype=np.int32)
    with faults.active(
        FaultPlan([dict(site="serve.prefill_worker", at=1, times=1)])
    ) as plan:
        ra = eng.submit(pa, 5)
        rb = eng.submit(pb, 4)
        done = {c.id: c for c in eng.run()}
    assert plan.injected == {"serve.prefill_worker": 1}
    assert done[ra].ok and done[rb].ok
    np.testing.assert_array_equal(done[ra].tokens, _solo(model, params, pa, 5))
    np.testing.assert_array_equal(done[rb].tokens, _solo(model, params, pb, 4))
    t = eng.telemetry
    assert t.counter("serve_prefill_worker_failures_total").value == 1
    assert eng.stats["prefill_worker_requeued"] == 1
    assert eng.stats["handoff_requeued"] == 0
    eng.close()


@pytest.mark.serving
def test_handoff_failure_retries_then_resolves_typed_error(gpt):
    """ISSUE 12 fault-matrix row, ``serve.handoff``: a single splice
    failure re-queues and recovers token-identically; a PERSISTENT
    failure exhausts ``handoff_retries`` and resolves as a typed
    "error" completion — counted at every attempt, never a hang, and
    the pool reservation is released each time (no block leak: a
    healthy request admits afterwards)."""
    from frl_distributed_ml_scaffold_tpu.serving import DisaggServingEngine

    model, params = gpt
    p = np.arange(5, dtype=np.int32)

    eng = DisaggServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    with faults.active(
        FaultPlan([dict(site="serve.handoff", at=1, times=1)])
    ):
        rid = eng.submit(p, 5)
        done = {c.id: c for c in eng.run()}
    assert done[rid].ok
    np.testing.assert_array_equal(done[rid].tokens, _solo(model, params, p, 5))
    assert eng.telemetry.counter("serve_handoff_failures_total").value == 1

    with faults.active(FaultPlan([dict(site="serve.handoff", times=0)])):
        rid2 = eng.submit((p + 1) % 64, 4)
        done2 = {c.id: c for c in eng.run()}
    assert done2[rid2].finish_reason == "error"
    # 1 (recovered above) + initial + handoff_retries retries.
    assert (
        eng.telemetry.counter("serve_handoff_failures_total").value
        == 1 + 1 + eng.handoff_retries
    )
    # No block leak: the released reservations admit a healthy request.
    rid3 = eng.submit(p, 4)
    done3 = {c.id: c for c in eng.run()}
    assert done3[rid3].ok
    np.testing.assert_array_equal(done3[rid3].tokens, _solo(model, params, p, 4))
    eng.close()


@pytest.mark.serving
def test_serve_bench_disagg_chaos_reports_requeues(capsys):
    """serve_bench --chaos on the ``*_disagg`` arm: the worker-boundary
    injections (one prefill-worker death, one handoff failure) are
    reported next to the recovery proof — both re-queued, every burst
    request resolved."""
    import json as _json

    sys_path_mod = __import__("sys")
    import os as _os

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in sys_path_mod.path:
        sys_path_mod.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            "--preset", "tiny", "--requests", "4", "--slots", "2",
            "--max-new", "4", "--sim-devices", "0",
            "--arms", "flash_replicated_paged_disagg", "--chaos",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines() if l.startswith("{")
    ]
    assert len(lines) == 1
    d = _json.loads(lines[0])["serving"]["disagg"]
    c = d["chaos"]
    assert c["injected"] == {
        "serve.prefill_worker": 1, "serve.handoff": 1
    }
    assert c["prefill_worker_failures"] == 1
    assert c["handoff_failures"] == 1
    assert c["requeued"] == 2
    assert c["completed"] == d["decode_requests"] + d["burst_requests"]
    assert c["completed_ok"] == c["completed"]


@pytest.mark.serving
def test_worker_failure_is_rng_neutral_for_sampled_decode(gpt):
    """The disaggregated analog of quarantine rng-neutrality: a
    prefill-worker failure re-queues the request and the RETRY reuses
    the failed attempt's RNG split, so sampled (temperature>0) output —
    the faulted request's AND every later request's — is identical to a
    fault-free run of the same engine."""
    from frl_distributed_ml_scaffold_tpu.serving import DisaggServingEngine

    model, params = gpt
    pa, pb = np.arange(5, dtype=np.int32), np.arange(6, dtype=np.int32)

    def serve(plan):
        eng = DisaggServingEngine(
            model, params, num_slots=2, temperature=0.7, kv_block_size=8
        )
        ctx = faults.active(plan) if plan else None
        if ctx:
            with ctx:
                ra = eng.submit(pa, 6)
                rb = eng.submit(pb, 4)
                done = {c.id: c for c in eng.run()}
        else:
            ra = eng.submit(pa, 6)
            rb = eng.submit(pb, 4)
            done = {c.id: c for c in eng.run()}
        eng.close()
        return done[ra].tokens, done[rb].tokens

    ref_a, ref_b = serve(None)
    got_a, got_b = serve(
        FaultPlan([dict(site="serve.prefill_worker", at=1, times=1)])
    )
    np.testing.assert_array_equal(got_a, ref_a)
    np.testing.assert_array_equal(got_b, ref_b)


# ----------------------------------------------- lock-order sentinel


@pytest.mark.fast
def test_instrumented_locks_record_edges_and_raise_on_inversion():
    """ISSUE 20 runtime sentinel: within ``faults.instrumented_locks``
    every patched-factory lock records per-thread acquisition order; a
    clean nesting leaves an acyclic edge set, and acquiring the same two
    locks in OPPOSITE orders raises AssertionError at scope exit with
    the cycle named."""
    import threading

    with faults.instrumented_locks(wrap_all=True) as rec:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
    edges = rec.order_edges()
    assert len(edges) == 1 and next(iter(edges.values())) == 1
    assert rec.find_cycle() is None

    with pytest.raises(AssertionError, match="lock-order-inversion"):
        with faults.instrumented_locks(wrap_all=True):
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass


@pytest.mark.fast
def test_instrumented_locks_do_not_mask_body_failures():
    """A drill's own exception propagates even when the recorder also
    saw a cycle — the sentinel must never shadow the real failure."""
    import threading

    with pytest.raises(ValueError, match="the real failure"):
        with faults.instrumented_locks(wrap_all=True):
            a, b = threading.Lock(), threading.Lock()
            with a, b:
                pass
            with b, a:
                pass
            raise ValueError("the real failure")
    from frl_distributed_ml_scaffold_tpu.faults import locks as _locks

    assert threading.Lock is _locks._REAL_LOCK  # factories restored


@pytest.mark.fast
def test_instrumented_rlock_reentrancy_and_condition_roundtrip():
    """RLock reentrancy records ONE acquisition per outermost hold;
    Condition wait/notify works across threads under instrumentation
    (wait's release/reacquire is recorded, not deadlocked)."""
    import threading

    with faults.instrumented_locks(wrap_all=True) as rec:
        r = threading.RLock()
        with r:
            with r:  # re-entry: no second acquisition recorded
                pass
        cond = threading.Condition()
        seen = []

        def consumer():
            with cond:
                while not seen:
                    cond.wait(timeout=5)

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        with cond:
            seen.append(1)
            cond.notify()
        t.join(5)
        assert not t.is_alive()
    total = sum(rec.order_edges().values(), 0)
    acq = {s for s in rec.max_holds()}
    assert any("#" in s or ":" in s for s in acq)  # per-instance site ids
    assert rec.find_cycle() is None
    assert total >= 0  # edge map well-formed after cross-thread waits


@pytest.mark.fast
def test_instrumented_locks_publish_telemetry_and_pins():
    """publish(registry) emits the four series; the analysis pins accept
    a clean recording and reject a held-too-long lock."""
    import threading

    from frl_distributed_ml_scaffold_tpu.analysis import pins

    reg = MetricsRegistry()
    with faults.instrumented_locks(registry=reg, wrap_all=True) as rec:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                time.sleep(0.05)
    assert reg.counter("lock_acquisitions_total").value >= 2
    assert reg.gauge("lock_sites").value >= 2
    assert reg.gauge("lock_order_edges").value >= 1
    assert reg.gauge("lock_hold_max_seconds").value >= 0.05
    pins.assert_lock_order_acyclic(rec)
    pins.assert_no_blocking_under_lock(rec, max_hold_s=2.0)
    with pytest.raises(AssertionError, match="held"):
        pins.assert_no_blocking_under_lock(rec, max_hold_s=0.01)
