"""Multi-process elastic kill-and-resume (SURVEY C14, call stack (d)).

Composes the two tiers that were previously only proven separately
(test_multiprocess.py: 2-process rendezvous/training; test_elastic.py:
single-host crash→restart→resume): TWO supervised processes rendezvous over
``jax.distributed``; the coordinator's child hard-dies mid-run (fault
injection — the moral equivalent of SIGKILL); the surviving process's child
detects the peer loss through the coordination service and exits; each
host's supervisor restarts its child; the 2-process group re-forms and
training resumes from the last sharded checkpoint with no step duplicated
or lost. This is BASELINE config 5's "multi-node elastic" capability on
real process boundaries.
"""

import json
import os

from _mp_harness import free_port, rendezvous_env, run_workers


def test_multiprocess_kill_and_resume(tmp_path):
    env_base = rendezvous_env(tmp_path, free_port(), device_count=2)
    envs = []
    for pid in range(2):
        env = {**env_base, "FRL_TPU_PROCESS_ID": str(pid)}
        if pid == 0:
            # Kill the COORDINATOR's child: the harder failure mode — the
            # peer loses the coordination service itself, not just a member.
            env["FRL_FAULT_AT_STEP"] = "9"
        envs.append(env)
    rcs, outputs = run_workers("_elastic_worker.py", envs, timeout=280)
    for rc, out in zip(rcs, outputs):
        assert rc == 0, f"supervisor failed:\n{out[-3000:]}"

    # Each host's supervisor went through exactly one restart cycle: the
    # faulted child on host 0, the peer-loss exit on host 1.
    for out in outputs:
        assert "elastic: run completed after 1 restart(s)" in out, out[-3000:]
    assert "fault injection: hard-exit" in outputs[0]
    # The survivor died to the coordination service noticing the dead peer,
    # not to the fault hook (it was never armed there).
    assert "fault injection" not in outputs[1]

    run_dir = os.path.join(str(tmp_path), "mnist_mlp")
    assert os.path.exists(os.path.join(run_dir, "fault_injected"))
    # Proof of resume-not-restart: metrics.jsonl (process-0-gated, append-
    # only across child generations) — run 1 logs steps 4 and 8, dies after
    # 9; run 2 restores the step-8 checkpoint and logs only 12.
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        steps = [json.loads(line)["step"] for line in fh]
    assert steps == [4, 8, 12], steps
    ckpt_steps = sorted(
        int(d) for d in os.listdir(os.path.join(run_dir, "ckpt")) if d.isdigit()
    )
    assert 12 in ckpt_steps
