"""Multi-process elastic kill-and-resume (SURVEY C14, call stack (d)).

Composes the two tiers that were previously only proven separately
(test_multiprocess.py: 2-process rendezvous/training; test_elastic.py:
single-host crash→restart→resume): TWO supervised processes rendezvous over
``jax.distributed``; the coordinator's child hard-dies mid-run (fault
injection — the moral equivalent of SIGKILL); the surviving process's child
detects the peer loss through the coordination service and exits; each
host's supervisor restarts its child; the 2-process group re-forms and
training resumes from the last sharded checkpoint with no step duplicated
or lost. This is BASELINE config 5's "multi-node elastic" capability on
real process boundaries.
"""

import json
import os

from _mp_harness import free_port, rendezvous_env, run_workers


def test_multiprocess_kill_and_resume(tmp_path):
    env_base = rendezvous_env(tmp_path, free_port(), device_count=2)
    envs = []
    for pid in range(2):
        env = {**env_base, "FRL_TPU_PROCESS_ID": str(pid)}
        if pid == 0:
            # Kill the COORDINATOR's child: the harder failure mode — the
            # peer loses the coordination service itself, not just a member.
            env["FRL_FAULT_AT_STEP"] = "9"
        envs.append(env)
    rcs, outputs = run_workers("_elastic_worker.py", envs, timeout=280)
    for rc, out in zip(rcs, outputs):
        assert rc == 0, f"supervisor failed:\n{out[-3000:]}"

    # Each host's supervisor went through exactly one restart cycle: the
    # faulted child on host 0, the peer-loss exit on host 1.
    for out in outputs:
        assert "elastic: run completed after 1 restart(s)" in out, out[-3000:]
    assert "fault injection: hard-exit" in outputs[0]
    # The survivor died to the coordination service noticing the dead peer,
    # not to the fault hook (it was never armed there).
    assert "fault injection" not in outputs[1]

    run_dir = os.path.join(str(tmp_path), "mnist_mlp")
    assert os.path.exists(os.path.join(run_dir, "fault_injected"))
    # Proof of resume-not-restart: metrics.jsonl (process-0-gated, append-
    # only across child generations) — run 1 logs steps 4 and 8, dies after
    # 9; run 2 restores the step-8 checkpoint and logs only 12.
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        steps = [json.loads(line)["step"] for line in fh]
    assert steps == [4, 8, 12], steps
    ckpt_steps = sorted(
        int(d) for d in os.listdir(os.path.join(run_dir, "ckpt")) if d.isdigit()
    )
    assert 12 in ckpt_steps


def test_multiprocess_shrink_to_survivors(tmp_path):
    """Smaller-slice continuation (SURVEY C14 "re-initialize (possibly
    smaller slice)", call stack (d) "re-rendezvous with surviving nodes"):
    the COORDINATOR host dies permanently (fault + zero restart budget);
    the surviving host's supervisor fails one full-size restart against the
    dead coordinator, reads the membership heartbeats, shrinks to a
    1-process world with itself as rank 0, and finishes the run from the
    last sharded checkpoint — no step duplicated or lost."""
    env_base = rendezvous_env(tmp_path, free_port(), device_count=2)
    envs = []
    for pid in range(2):
        env = {
            **env_base,
            "FRL_TPU_PROCESS_ID": str(pid),
            # Bound the dead-coordinator rendezvous: the shrink decision
            # happens after this timeout fails the full-size restart.
            "FRL_TPU_INIT_TIMEOUT_S": "15",
            "FRL_TPU_HOST_ADDRESS": "127.0.0.1",
        }
        if pid == 0:
            env["FRL_FAULT_AT_STEP"] = "9"
        envs.append(env)
    rcs, outputs = run_workers("_elastic_shrink_worker.py", envs, timeout=280)

    # Host 0: the fault's exit code surfaces (budget 0, never restarted).
    assert rcs[0] == 43, f"coordinator supervisor:\n{outputs[0][-3000:]}"
    assert "fault injection: hard-exit" in outputs[0]
    # Host 1: survived, shrank, completed.
    assert rcs[1] == 0, f"survivor supervisor:\n{outputs[1][-3000:]}"
    assert "elastic: shrinking from 2 to 1" in outputs[1], outputs[1][-3000:]
    assert "elastic: run completed" in outputs[1]
    assert "fault injection" not in outputs[1]

    run_dir = os.path.join(str(tmp_path), "mnist_mlp")
    # Proof of resume-not-restart across the topology change: run 1
    # (2 hosts, host 0 was rank 0) logs steps 4 and 8 then dies after 9;
    # the shrunk run (host 1 as the new rank 0) restores the step-8
    # checkpoint and logs only 12 — same append-only metrics.jsonl.
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        steps = [json.loads(line)["step"] for line in fh]
    assert steps == [4, 8, 12], steps
    ckpt_steps = sorted(
        int(d) for d in os.listdir(os.path.join(run_dir, "ckpt")) if d.isdigit()
    )
    assert 12 in ckpt_steps
    # The dead host retired its heartbeat; the survivor's is the only one
    # left (it retires on clean exit too — directory may also be empty).
    members = os.listdir(os.path.join(run_dir, "members"))
    assert "host_0.json" not in members, members


def test_multiprocess_grow_back_after_shrink(tmp_path):
    """Re-admission after a shrink (VERDICT r4 #7): the coordinator host
    dies, the survivor shrinks to a 1-process world and keeps training;
    the dead host then comes back (repaired / false-positive eviction).
    The survivor's grow watcher must preempt its child (SIGTERM →
    checkpoint → clean exit) and re-form the 2-process world — ranks
    remapped back, Orbax resharding restore — and BOTH hosts finish the
    run, no step lost or duplicated, no operator action."""
    env_base = rendezvous_env(tmp_path, free_port(), device_count=2)
    envs = []
    for pid in range(2):
        env = {
            **env_base,
            "FRL_TPU_PROCESS_ID": str(pid),
            "FRL_TPU_INIT_TIMEOUT_S": "15",
            "FRL_TPU_HOST_ADDRESS": "127.0.0.1",
            # Stretch steps so the revival lands while the shrunken world
            # is still mid-run (synthetic steps are sub-ms otherwise).
            "FRL_STEP_DELAY_S": "0.25",
        }
        if pid == 0:
            env["FRL_FAULT_AT_STEP"] = "9"
        envs.append(env)
    rcs, outputs = run_workers("_elastic_grow_worker.py", envs, timeout=420)

    # Host 0 revived and its second supervisor completed the run.
    assert rcs[0] == 0, f"revived coordinator:\n{outputs[0][-3000:]}"
    # Host 1 shrank, then grew back, then completed.
    assert rcs[1] == 0, f"survivor supervisor:\n{outputs[1][-3000:]}"
    assert "elastic: shrinking from 2 to 1" in outputs[1], outputs[1][-3000:]
    assert "preempting child to re-form" in outputs[1], outputs[1][-3000:]
    assert "elastic: growing from 1 to 2" in outputs[1], outputs[1][-3000:]
    assert "elastic: run completed" in outputs[1]

    run_dir = os.path.join(str(tmp_path), "mnist_mlp")
    # No step lost or duplicated across BOTH topology changes: the
    # append-only metrics.jsonl (written by whichever host is rank 0 at
    # the time) must be non-decreasing and end exactly at total_steps.
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        steps = [json.loads(line)["step"] for line in fh]
    assert steps == sorted(steps), steps
    assert steps[-1] == 120 and steps.count(120) == 1, steps
    ckpt_steps = sorted(
        int(d) for d in os.listdir(os.path.join(run_dir, "ckpt")) if d.isdigit()
    )
    assert 120 in ckpt_steps
