"""Model-family coverage (SURVEY C15) + advanced-parallelism numerics
(C6 TP, C8 SP ring/Ulysses, C9 EP) on the simulated 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_apply, jit_init

from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    MoEConfig,
    ResNetConfig,
    VideoConfig,
    ViTConfig,
)
from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    build_mesh,
    set_current_mesh,
)
from frl_distributed_ml_scaffold_tpu.models import create_model
from frl_distributed_ml_scaffold_tpu.precision import get_policy

FP32 = get_policy("fp32")


@pytest.fixture(autouse=True)
def clear_mesh_context():
    yield
    set_current_mesh(None)


def init_and_forward(model, x, train=False):
    variables = jit_init(model, x, train=False)
    rngs = {"dropout": jax.random.key(1)} if train else None
    out = jit_apply(model, train=train, rngs=rngs)(variables, x)
    return variables, out


def test_resnet50_forward_and_batchstats():
    model = create_model(ResNetConfig(depth=50, num_classes=10), FP32)
    x = jnp.ones((2, 64, 64, 3))
    variables, logits = init_and_forward(model, x)
    assert logits.shape == (2, 10)
    assert "batch_stats" in variables
    # train mode mutates batch_stats
    out, updated = jit_apply(
        model, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(1)},
    )(variables, x)
    leaves_before = jax.tree.leaves(variables["batch_stats"])
    leaves_after = jax.tree.leaves(updated["batch_stats"])
    assert any(
        not np.allclose(a, b) for a, b in zip(leaves_before, leaves_after)
    )


def test_resnet18_basic_block():
    model = create_model(ResNetConfig(depth=18, num_classes=7), FP32)
    x = jnp.ones((2, 32, 32, 3))
    _, logits = init_and_forward(model, x)
    assert logits.shape == (2, 7)


def test_vit_forward():
    cfg = ViTConfig(
        image_size=32, patch_size=8, hidden_dim=64, num_layers=2,
        num_heads=4, num_classes=10,
    )
    model = create_model(cfg, FP32)
    x = jnp.ones((2, 32, 32, 3))
    _, logits = init_and_forward(model, x)
    assert logits.shape == (2, 10)


def test_video_forward():
    cfg = VideoConfig(
        image_size=32, num_frames=4, tubelet_size=(2, 8, 8), hidden_dim=64,
        num_layers=2, num_heads=4, num_classes=11,
    )
    model = create_model(cfg, FP32)
    x = jnp.ones((2, 4, 32, 32, 3))
    _, logits = init_and_forward(model, x)
    assert logits.shape == (2, 11)


def test_s2d_stem_is_exact_rewrite_of_conv7():
    """The space-to-depth stem must compute the SAME function as the 7x7/s2
    SAME-padded stem under the documented weight relabeling — it is a perf
    knob, not an architecture change."""
    from frl_distributed_ml_scaffold_tpu.models.resnet import (
        s2d_stem_weights,
        space_to_depth,
    )

    key = jax.random.key(0)
    x = jax.random.normal(key, (2, 32, 32, 3))
    w7 = jax.random.normal(jax.random.key(1), (7, 7, 3, 16))

    ref = jax.lax.conv_general_dilated(
        x, w7, window_strides=(2, 2), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    out = jax.lax.conv_general_dilated(
        space_to_depth(x, 2), s2d_stem_weights(w7), window_strides=(1, 1),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    # fp32 accumulation-order noise only — a wrong tap relabeling would be
    # O(1) wrong everywhere, not 1e-5 on isolated elements.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_maxpool_mask_grad_matches_scatter():
    """pool_grad='mask' must be the identical function forward and, on
    tie-free inputs, produce the identical gradient as the autodiff
    select_and_scatter path (it is a perf knob, not an architecture
    change). Continuous fp32 random inputs make ties measure-zero."""
    from frl_distributed_ml_scaffold_tpu.models.resnet import (
        _max_pool_mask_grad,
        _stem_max_pool,
    )

    x = jax.random.normal(jax.random.key(0), (2, 8, 8, 4))
    w = jax.random.normal(jax.random.key(1), (2, 4, 4, 4))

    def loss(pool, x):
        return jnp.sum(pool(x) * w)

    np.testing.assert_array_equal(
        np.asarray(_max_pool_mask_grad(x)), np.asarray(_stem_max_pool(x))
    )
    g_ref = jax.grad(lambda x: loss(_stem_max_pool, x))(x)
    g_mask = jax.grad(lambda x: loss(_max_pool_mask_grad, x))(x)
    np.testing.assert_allclose(
        np.asarray(g_mask), np.asarray(g_ref), rtol=1e-6
    )


def test_maxpool_mask_grad_ties_preserve_mass():
    """On tied maxima the mask path splits gradient equally across the tied
    entries (select_and_scatter routes all of it to the first); both must
    conserve total gradient mass per window."""
    from frl_distributed_ml_scaffold_tpu.models.resnet import (
        _max_pool_mask_grad,
    )

    x = jnp.ones((1, 4, 4, 1))  # every window fully tied
    dy_total = 4.0  # 2x2 output of ones
    g = jax.grad(lambda x: jnp.sum(_max_pool_mask_grad(x)))(x)
    np.testing.assert_allclose(float(jnp.sum(g)), dy_total, rtol=1e-6)
    assert float(jnp.max(g)) < 1.0  # actually split, not first-takes-all


def test_resnet_pool_grad_mask_trains():
    model = create_model(
        ResNetConfig(depth=10, num_classes=7, pool_grad="mask"), FP32
    )
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3))
    variables, logits = init_and_forward(model, x)
    assert logits.shape == (2, 7)
    g = jax.jit(
        jax.grad(
            lambda p: model.apply(
                {**variables, "params": p}, x, train=False
            ).sum()
        )
    )(variables["params"])
    assert all(np.isfinite(l).all() for l in jax.tree.leaves(g))


def test_resnet_s2d_stem_trains():
    model = create_model(
        ResNetConfig(depth=18, num_classes=7, stem="s2d"), FP32
    )
    x = jnp.ones((2, 32, 32, 3))
    _, logits = init_and_forward(model, x)
    assert logits.shape == (2, 7)


def tiny_gpt(**kw):
    defaults = dict(
        vocab_size=64, num_layers=2, num_heads=4, hidden_dim=32, seq_len=16
    )
    defaults.update(kw)
    return GPTConfig(**defaults)


def test_gpt_forward():
    model = create_model(tiny_gpt(), FP32)
    tokens = jnp.zeros((2, 16), jnp.int32)
    _, logits = init_and_forward(model, tokens)
    assert logits.shape == (2, 16, 64)


def test_gpt_causality():
    """Changing a future token must not change past logits."""
    model = create_model(tiny_gpt(), FP32)
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(5)
    variables = jit_init(model, t1, train=False)
    fwd = jit_apply(model, train=False)
    l1 = fwd(variables, t1)
    l2 = fwd(variables, t2)
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
    assert not np.allclose(l1[0, 10:], l2[0, 10:])


def test_gpt_block_remat_grads_match():
    """Per-block remat (model.block_remat, trainer C11's selective tier) is
    pure rematerialization: loss and grads must match block_remat=none
    exactly for both policies. The memory claim it exists for is audited
    by tools/pp_memory_audit.py --flagship (mb8: 24.5G with remat=dots →
    6.8G with block_remat=full, 7.2G save_attn)."""
    tokens = (jnp.arange(32, dtype=jnp.int32).reshape(2, 16)) % 64

    def loss_and_grads(br):
        model = create_model(tiny_gpt(block_remat=br), FP32)
        params = jit_init(model, tokens, train=False)

        def loss(p):
            logits = model.apply(
                p, tokens, train=True, rngs={"dropout": jax.random.key(1)}
            )
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        return jax.jit(jax.value_and_grad(loss))(params)

    l0, g0 = loss_and_grads("none")
    for br in ("full", "save_attn"):
        l1, g1 = loss_and_grads(br)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6), g0, g1
        )


def test_gpt_block_remat_reduces_saved_residuals():
    """The qualitative ordering the flagship audit documents, pinned at
    tiny shapes so it can't rot: saved fwd→bwd residuals must satisfy
    block_remat full < save_attn < none."""
    from jax._src.ad_checkpoint import saved_residuals

    tokens = (jnp.arange(32, dtype=jnp.int32).reshape(2, 16)) % 64

    def residual_bytes(br):
        model = create_model(
            tiny_gpt(num_layers=4, block_remat=br), FP32
        )
        params = jit_init(model, tokens, train=False)

        def loss(p):
            logits = model.apply(p, tokens, train=True)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        total = 0
        for aval, _ in saved_residuals(loss, params):
            if hasattr(aval, "shape"):
                total += int(aval.size) * aval.dtype.itemsize
        return total

    full, attn, none = (
        residual_bytes("full"),
        residual_bytes("save_attn"),
        residual_bytes("none"),
    )
    assert full < attn < none, (full, attn, none)


def test_gpt_block_remat_unknown_mode_raises():
    model = create_model(tiny_gpt(block_remat="bogus"), FP32)
    tokens = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(KeyError, match="block_remat"):
        jit_init(model, tokens, train=False)


def test_gpt_moe_forward_and_aux():
    model = create_model(
        tiny_gpt(moe=MoEConfig(num_experts=4, top_k=2)), FP32
    )
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = jit_init(model, tokens, train=False)
    logits, aux = jit_apply(model, train=False)(variables, tokens)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(float(aux)) and float(aux) >= 0


# ---------------------- attention-op equivalence (C8) ----------------------


def _rand_qkv(key, b=2, t=32, h=4, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d), jnp.float32),
        jax.random.normal(kk, (b, t, h, d), jnp.float32),
        jax.random.normal(kv, (b, t, h, d), jnp.float32),
    )


def test_ring_attention_matches_dense():
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
        ring_attention,
    )

    env = build_mesh(MeshConfig(data=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(0))
    ref = _single_shard_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads_match_dense():
    """Backward through the ppermute ring (autodiff of the fori_loop online
    softmax) must match dense gradients — the training path, not just eval."""
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
        ring_attention,
    )

    env = build_mesh(MeshConfig(data=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(3))

    def loss(att):
        def f(q, k, v):
            o = att(q, k, v)
            return (o * jnp.cos(jnp.arange(o.size).reshape(o.shape))).sum()

        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

    g_ring = loss(lambda q, k, v: ring_attention(q, k, v))(q, k, v)
    g_dense = loss(
        lambda q, k, v: _single_shard_attention(q, k, v, causal=True)
    )(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=5e-5,
            err_msg=f"ring grad mismatch for d{name}",
        )


def test_ring_attention_pallas_hops_match_dense():
    """Ring with the per-hop Pallas flash kernels (interpreter mode on CPU):
    the fused path must match dense exactly like the fallback path does.
    Shapes chosen so each hop tiles (T_local=16 ≥ min block 8, d%32==0)."""
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
        ring_attention,
    )

    env = build_mesh(MeshConfig(data=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(5), b=2, t=64, h=2, d=32)
    ref = _single_shard_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, interpret=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_pallas_hops_grads_match_dense():
    """Custom-VJP ring backward with the Pallas per-hop backward kernels:
    traveling dK/dV accumulators + global-lse probabilities must reproduce
    the dense gradients."""
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
        ring_attention,
    )

    env = build_mesh(MeshConfig(data=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(7), b=2, t=64, h=2, d=32)

    def loss(att):
        def f(q, k, v):
            o = att(q, k, v)
            return (o * jnp.cos(jnp.arange(o.size).reshape(o.shape))).sum()

        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

    g_ring = loss(
        lambda q, k, v: ring_attention(q, k, v, interpret=True)
    )(q, k, v)
    g_dense = loss(
        lambda q, k, v: _single_shard_attention(q, k, v, causal=True)
    )(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=5e-5,
            err_msg=f"pallas ring grad mismatch for d{name}",
        )


@pytest.mark.parametrize("seq_n,t,causal", [
    (2, 32, True), (8, 64, True), (2, 32, False), (8, 64, False),
])
def test_ring_attention_sweep_matches_dense(seq_n, t, causal):
    """Property sweep over ring widths/lengths/masking for the custom-VJP
    ring: fwd AND grads must match dense for every combination (one shape
    per path is not enough for code this math-heavy)."""
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
        ring_attention,
    )

    env = build_mesh(MeshConfig(data=8 // seq_n, seq=seq_n))
    set_current_mesh(env)
    # The data-axis size (8//seq_n) must divide the batch.
    q, k, v = _rand_qkv(
        jax.random.key(seq_n * t + causal), b=max(2, 8 // seq_n), t=t
    )

    ref = _single_shard_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal=causal))(
        q, k, v
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def g(att):
        return jax.jit(
            jax.grad(lambda q, k, v: (att(q, k, v) ** 2).sum(), argnums=(0, 1, 2))
        )

    g_ring = g(lambda q, k, v: ring_attention(q, k, v, causal=causal))(q, k, v)
    g_dense = g(
        lambda q, k, v: _single_shard_attention(q, k, v, causal=causal)
    )(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=1e-4,
            err_msg=f"ring sweep grad mismatch d{name} "
                    f"(seq={seq_n}, t={t}, causal={causal})",
        )


def test_ring_attention_long_context_32k():
    """SURVEY §5 long-context: 32k tokens over an 8-shard ring runs without
    materializing any [T, T] buffer — per-shard transient memory is the
    4k-local block only (the round-1 implementation would have needed
    8 × [4k, 4k] fp32 per head here). Forward-only, bf16, sanity-checked
    against row-stochasticity (output of attention over bf16-normal V has
    bounded magnitude)."""
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import ring_attention

    env = build_mesh(MeshConfig(data=1, seq=8))
    set_current_mesh(env)
    t = 32768
    kq, kk, kv = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(kq, (1, t, 1, 32), jnp.bfloat16)
    k = jax.random.normal(kk, (1, t, 1, 32), jnp.bfloat16)
    v = jax.random.normal(kv, (1, t, 1, 32), jnp.bfloat16)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v))(q, k, v)
    out = np.asarray(out, np.float32)
    assert out.shape == (1, t, 1, 32)
    assert np.isfinite(out).all()
    # Attention outputs are convex combinations of V rows — magnitudes stay
    # O(1); a softmax/merge bug (double-normalization, lse drift) blows this.
    assert np.abs(out).max() < 6.0


def test_ring_attention_noncausal():
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
        ring_attention,
    )

    env = build_mesh(MeshConfig(data=1, seq=8))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(1), b=1, t=64)
    ref = _single_shard_attention(q, k, v, causal=False)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_matches_dense():
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
    )
    from frl_distributed_ml_scaffold_tpu.ops.ulysses import ulysses_attention

    env = build_mesh(MeshConfig(data=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(2))  # h=4 divisible by seq=4
    ref = _single_shard_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_pallas_local_attention_matches_dense():
    """Ulysses with the fused flash kernel for its local full-T attention
    (interpreter mode on CPU): forward and grads must match dense."""
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
        _single_shard_attention,
    )
    from frl_distributed_ml_scaffold_tpu.ops.ulysses import ulysses_attention

    env = build_mesh(MeshConfig(data=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(9), b=2, t=64, h=4, d=32)
    ref = _single_shard_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, interpret=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def loss(att):
        def f(q, k, v):
            o = att(q, k, v)
            return (o * jnp.cos(jnp.arange(o.size).reshape(o.shape))).sum()

        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

    g_u = loss(lambda q, k, v: ulysses_attention(q, k, v, interpret=True))(q, k, v)
    g_d = loss(lambda q, k, v: _single_shard_attention(q, k, v, causal=True))(
        q, k, v
    )
    for gu, gd, name in zip(g_u, g_d, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gu), np.asarray(gd), atol=5e-5,
            err_msg=f"ulysses-pallas grad mismatch for d{name}",
        )


def test_ulysses_head_divisibility_error():
    from frl_distributed_ml_scaffold_tpu.ops.ulysses import ulysses_attention

    env = build_mesh(MeshConfig(data=1, seq=8))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(3), h=4)  # 4 heads, seq=8 -> error
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v)


def test_ulysses_tp_local_head_divisibility_error():
    """TP shards heads too: 4 heads / model=2 = 2 local heads, seq=4 -> the
    *local* count is what must divide (global 4 % 4 == 0 would pass)."""
    from frl_distributed_ml_scaffold_tpu.ops.ulysses import ulysses_attention

    env = build_mesh(MeshConfig(data=1, model=2, seq=4))
    set_current_mesh(env)
    q, k, v = _rand_qkv(jax.random.key(4), h=4)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v)


def test_moe_grouped_dispatch_matches_ungrouped():
    """GShard grouped routing (the G× dispatch-memory saver) must be a pure
    re-bucketing: with capacity ample enough that no group drops a token,
    G=1 and G=4 route every token to the same experts with the same gates,
    so the block output is identical. Aux differs only through per-group
    bookkeeping (it must not), so it is asserted equal too."""
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    base = tiny_gpt(
        moe=MoEConfig(
            num_experts=4, top_k=2, capacity_factor=8.0, num_groups=1
        )
    )
    x = jax.random.normal(jax.random.key(0), (2, 16, 32), jnp.float32)

    def run(cfg):
        m = MoEMlp(cfg, jnp.float32)
        variables = jax.jit(
            lambda v: m.init(jax.random.key(1), v, train=False)
        )(x)
        return jax.jit(
            lambda v, xx: m.apply(v, xx, train=False)
        )(variables, x)

    y1, aux1 = run(base)
    cfg4 = dataclasses.replace(
        base, moe=dataclasses.replace(base.moe, num_groups=4)
    )
    y4, aux4 = run(cfg4)
    np.testing.assert_allclose(y1, y4, atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(float(aux1), float(aux4), rtol=1e-6)


def test_moe_sorted_matches_einsum():
    """moe.dispatch=sort (scatter/gather ragged exchange) must be a pure
    reformulation of the einsum-GSEC path: the seating cumsum is shared,
    so outputs, aux, and drop behavior are identical — including under
    tight capacity (real drops) and grouped routing. Gradients too: the
    gather/scatter VJP must agree with the one-hot einsum VJP."""
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    x = jax.random.normal(jax.random.key(0), (2, 16, 32), jnp.float32)

    def run(cfg, with_grad=False):
        m = MoEMlp(cfg, jnp.float32)
        variables = jax.jit(
            lambda v: m.init(jax.random.key(1), v, train=True)
        )(x)

        def loss_fn(v, xx):
            y, aux = m.apply(v, xx, train=True)
            return jnp.sum(y * y) + aux, (y, aux)

        if with_grad:
            (loss, (y, aux)), grads = jax.jit(
                jax.value_and_grad(loss_fn, has_aux=True)
            )(variables, x)
            return y, aux, grads
        (_, (y, aux)) = jax.jit(loss_fn)(variables, x)
        return y, aux, None

    for label, moe_cfg in [
        ("ample", MoEConfig(num_experts=4, top_k=2, capacity_factor=8.0)),
        ("drops", MoEConfig(num_experts=4, top_k=2, capacity_factor=0.5)),
        (
            "grouped",
            MoEConfig(
                num_experts=4, top_k=2, capacity_factor=1.25, num_groups=2
            ),
        ),
    ]:
        cfg_e = tiny_gpt(moe=moe_cfg)
        cfg_s = dataclasses.replace(
            cfg_e, moe=dataclasses.replace(moe_cfg, dispatch="sort")
        )
        with_grad = label == "drops"
        y_e, aux_e, g_e = run(cfg_e, with_grad)
        y_s, aux_s, g_s = run(cfg_s, with_grad)
        np.testing.assert_allclose(
            y_e, y_s, atol=1e-5, rtol=1e-5, err_msg=label
        )
        np.testing.assert_allclose(
            float(aux_e), float(aux_s), rtol=1e-6, err_msg=label
        )
        if with_grad:
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, atol=1e-4, rtol=1e-4, err_msg=label
                ),
                g_e,
                g_s,
            )


def test_moe_sort_dispatch_rejects_unknown():
    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    cfg = tiny_gpt(
        moe=MoEConfig(num_experts=4, top_k=2, dispatch="ragged")
    )
    m = MoEMlp(cfg, jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 16, 32), jnp.float32)
    with pytest.raises(ValueError, match="dispatch"):
        m.init(jax.random.key(1), x, train=False)


def test_moe_router_z_loss_penalizes_large_logits():
    """The z-loss term must grow with router-logit magnitude (its whole
    point) and vanish when disabled."""
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    cfg = tiny_gpt(
        moe=MoEConfig(num_experts=4, top_k=2, router_z_loss=1e-3)
    )
    m = MoEMlp(cfg, jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 16, 32), jnp.float32)
    variables = jax.jit(lambda v: m.init(jax.random.key(1), v, train=False))(x)
    apply = jax.jit(lambda v, xx: m.apply(v, xx, train=False))
    _, aux = apply(variables, x)

    # Scale the router kernel: logits grow, z² grows, aux must grow.
    big = jax.tree.map(lambda l: l, variables)
    big = {"params": dict(big["params"])}
    router = dict(big["params"]["router"])
    router["kernel"] = router["kernel"] * 50.0
    big["params"]["router"] = router
    _, aux_big = apply(big, x)
    assert float(aux_big) > float(aux)

    cfg0 = dataclasses.replace(
        cfg, moe=dataclasses.replace(cfg.moe, router_z_loss=0.0)
    )
    m0 = MoEMlp(cfg0, jnp.float32)
    _, aux0 = jax.jit(lambda v, xx: m0.apply(v, xx, train=False))(variables, x)
    assert float(aux) > float(aux0)  # the z term is there and positive


def test_moe_explicit_groups_must_divide_in_training():
    """A silent gcd snap of an explicit num_groups in the TRAINING path
    would change per-group capacity/drop semantics with no signal
    (round-3 advisor finding): num_groups=6 with n=32 must raise, not
    quietly train with G=2. The decode path keeps the gcd fallback
    (covered in test_generation's grouped-MoE decode case)."""
    import pytest

    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    cfg = tiny_gpt(
        moe=MoEConfig(num_experts=4, top_k=2, num_groups=6)
    )
    m = MoEMlp(cfg, jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 16, 32), jnp.float32)
    with pytest.raises(ValueError, match="num_groups=6 does not divide"):
        m.init(jax.random.key(1), x, train=True)
    # num_groups=16 divides n=32 but not b=2: groups would cut sequences
    # and break batch alignment — refused on the same grounds.
    cfg16 = tiny_gpt(moe=MoEConfig(num_experts=4, top_k=2, num_groups=16))
    with pytest.raises(ValueError, match="num_groups=16 does not divide"):
        MoEMlp(cfg16, jnp.float32).init(jax.random.key(1), x, train=True)
    # train=False (decode) still snaps: init succeeds.
    variables = m.init(jax.random.key(1), x, train=False)
    y, _ = m.apply(variables, x, train=False)
    assert y.shape == x.shape


def test_moe_auto_groups_align_with_batch_dim():
    """Auto group count must divide the BATCH dim (not merely n=b*t) so
    the (b,t,d)->(g,s,d) reshape never cuts a group mid-sequence and the
    group dim stays batch-sharded (round-3 advisor finding)."""
    from frl_distributed_ml_scaffold_tpu.models.moe import _num_groups

    moe = MoEConfig(num_experts=4, top_k=2, num_groups=0)
    # No mesh env in this test process scope -> auto is 1.
    assert _num_groups(moe, 32, 2, True) == 1

    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        build_mesh,
        mesh_context,
    )

    env = build_mesh(MeshConfig(data=8))
    with mesh_context(env):
        # b=2, 8 batch shards: shards does not divide b -> snap to
        # gcd(2, 8) = 2, never 8 (which divides n=32 but cuts sequences).
        assert _num_groups(moe, 32, 2, True) == 2
        assert _num_groups(moe, 64, 8, True) == 8


def test_moe_grouped_dispatch_residual_ordering():
    """CI-light pin of the tools/pp_memory_audit.py --moe conclusion: the
    grouped (GSEC) dispatch saves strictly fewer fwd→bwd residual bytes
    than ungrouped (per-group capacity shrinks the [G,S,E,C] one-hots G×),
    and per-block remat collapses the dispatch residual class entirely —
    which is why a sort-based dispatch is NOT shipped (measured at real
    shapes: 6.04 GB → 1.51 GB → 0.05 GB, docs/perf_playbook.md)."""
    from jax._src.ad_checkpoint import saved_residuals

    tokens = (jnp.arange(64, dtype=jnp.int32).reshape(4, 16)) % 64

    def residual_bytes(groups, block_remat):
        model = create_model(
            tiny_gpt(
                moe=MoEConfig(num_experts=4, top_k=2, num_groups=groups),
                block_remat=block_remat,
            ),
            FP32,
        )
        params = jit_init(model, tokens, train=False)

        def loss(p):
            logits, aux = model.apply(p, tokens, train=True)
            return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

        total = 0
        for aval, _ in saved_residuals(loss, params):
            if hasattr(aval, "shape"):
                total += int(aval.size) * aval.dtype.itemsize
        return total

    ungrouped = residual_bytes(1, "none")
    grouped = residual_bytes(4, "none")
    remat = residual_bytes(4, "full")
    assert remat < grouped < ungrouped, (remat, grouped, ungrouped)
