"""Optimizer/schedule factory extras (SURVEY C3): lion and the WSD
schedule behave as specified."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import os

import jax
import numpy as np

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.config.schema import OptimizerConfig, TrainerConfig
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
from frl_distributed_ml_scaffold_tpu.trainer.optimizers import (
    make_optimizer,
    make_schedule,
)
from frl_distributed_ml_scaffold_tpu.utils.trees import tree_param_count


def test_wsd_schedule_shape():
    cfg = OptimizerConfig(
        learning_rate=1.0, schedule="wsd", warmup_steps=10, wsd_decay_fraction=0.5
    )
    sched = make_schedule(cfg, total_steps=110)  # 10 warmup + 50 stable + 50 decay
    assert float(sched(0)) == 0.0  # warmup starts at zero
    np.testing.assert_allclose(float(sched(10)), 1.0, atol=1e-6)  # peak
    np.testing.assert_allclose(float(sched(59)), 1.0, atol=1e-6)  # stable hold
    assert 0.0 < float(sched(85)) < 1.0  # inside the decay ramp
    np.testing.assert_allclose(float(sched(110)), 0.0, atol=1e-6)  # decayed out


def test_lion_trains_and_halves_moment_state():
    def trainer_for(name):
        cfg = apply_overrides(
            get_config("mnist_mlp"),
            [
                "trainer.total_steps=6",
                "trainer.log_every=100",
                "data.global_batch_size=64",
                "model.hidden_sizes=32",
                "precision.policy=fp32",
                f"optimizer.name={name}",
                # Lion's canonical LR is ~a decade under AdamW's.
                "optimizer.learning_rate=0.0003",
                "workdir=/tmp/frl_lion_test",
            ],
        )
        return Trainer(cfg)

    t = trainer_for("lion")
    state = t.init_state()
    losses = []
    for step in range(12):
        state, m = t.train_step(state, t.pipeline.global_batch(step))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    # Windowed trend, not last < first: lion's sign updates make single
    # steps noisy at this scale (per-batch loss can tick up within 6
    # steps on some XLA reduction orders); the 12-step window average is
    # the robust "it trains" signal.
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses

    # One moment vs AdamW's two: the optimizer state is ~half the memory.
    lion_state_n = tree_param_count(state.opt_state)
    adamw = trainer_for("adamw")
    adamw_state_n = tree_param_count(adamw.init_state().opt_state)
    assert lion_state_n < 0.6 * adamw_state_n, (lion_state_n, adamw_state_n)


def test_adafactor_recipe_lr_actually_learns():
    """Pin the round-5 optimizer decision's convergence side: adafactor's
    update is RELATIVE (scaled by RMS(param)), so inheriting adamw's
    3e-4 silently un-trains the model (measured: loss 6.26 -> 6.20 in
    300 steps vs 4.07 for adamw — evidence_r5/opt_convergence.log). The
    gpt2_medium_adafactor recipe must carry an adafactor-scale LR, and
    at that LR a short run must actually learn."""
    recipe = get_config("gpt2_medium_adafactor")
    assert recipe.optimizer.name == "adafactor"
    assert recipe.optimizer.learning_rate >= 3e-3, (
        "adafactor recipe inherited an adam-scale LR"
    )

    cfg = apply_overrides(
        get_config("gpt2_medium_adafactor"),
        [
            "model.num_layers=2", "model.num_heads=4",
            "model.hidden_dim=128", "model.seq_len=128",
            "model.vocab_size=512",
            "data.seq_len=128", "data.vocab_size=512",
            "data.global_batch_size=8",
            "trainer.total_steps=40", "trainer.grad_accum=1",
            "trainer.remat=none", "trainer.log_every=100",
            "optimizer.warmup_steps=5",
            "mesh.fsdp=1", "mesh.data=-1",
            "precision.policy=fp32",
            "checkpoint.enabled=false",
            "workdir=/tmp/frl_adafactor_test",
        ],
    )
    t = Trainer(cfg)
    state = t.init_state()
    losses = []
    for step in range(40):
        state, m = t.train_step(state, t.pipeline.global_batch(step))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    # The inherited-LR failure mode drops loss ~0.01 absolute in this
    # window (it needed 300 steps to move 0.06); the correct LR drops
    # ~0.36 in 40 steps (measured 2026-07-30). 0.2 separates cleanly on
    # both sides.
    assert losses[-1] < losses[0] - 0.2, (losses[0], losses[-1])


def test_adafactor_recipe_lr_at_10m_proxy():
    """Round-6 de-risk of the recipe LR AT SCALE (ISSUE r6 satellite): the
    committed ≥1k-step evidence run at the 10.34M-param proxy
    (`tools/opt_convergence.py --scale 10m --steps 1000`,
    evidence_r6/opt_convergence_10m.log) must back the pinned 1e-2 —
    bracketed from below (3e-3 clearly under-trains: 2.68 vs 0.73) and
    from above (3e-2 measured), with 1e-2 no worse than adamw's final
    loss × the tool's 1.10 tolerance (measured: it WINS outright,
    0.7274 vs 0.8519). The recipe must carry that LR and cite the log.
    The 40-step early marker in the same rows shows why this pin reads
    evidence instead of re-training: at 10M params the optimizers have
    not separated by step 40 (all ≈9.03 from 9.06)."""
    import json

    log = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "evidence_r6", "opt_convergence_10m.log",
    )
    rows = [
        json.loads(l) for l in open(log) if l.lstrip().startswith("{")
    ]
    by = {
        (r["optimizer"], r["lr"]): r
        for r in rows
        if r.get("scale") == "10m" and "optimizer" in r
    }
    adamw = by[("adamw", 3e-4)]
    lo, mid, hi = (by[("adafactor", lr)] for lr in (3e-3, 1e-2, 3e-2))
    for r in (adamw, lo, mid, hi):
        assert r["steps"] >= 1000, r  # the >=1k-step requirement
    # The decision: 1e-2 converges at least as well as adamw at scale.
    assert mid["loss_final_mean"] <= 1.10 * adamw["loss_final_mean"], (
        mid, adamw,
    )
    # Bracketing from below is informative: a decade down under-trains.
    assert lo["loss_final_mean"] > 1.5 * mid["loss_final_mean"], (lo, mid)
    # And the registered recipe carries exactly the evidenced LR + cite.
    recipe = get_config("gpt2_medium_adafactor")
    assert recipe.optimizer.learning_rate == 1e-2
    from frl_distributed_ml_scaffold_tpu.config import recipes

    assert "opt_convergence_10m" in recipes.gpt2_medium_adafactor.__doc__


def test_lion_composes_with_zero1_sharding():
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=2",
            "data.global_batch_size=64",
            "model.hidden_sizes=64,64",
            "precision.policy=fp32",
            "optimizer.name=lion",
            "mesh.data=4",
            "mesh.fsdp=2",
            "parallel.opt_sharding=zero1",
            "parallel.fsdp_min_size=1",
            "workdir=/tmp/frl_lion_zero1",
        ],
    )
    t = Trainer(cfg)
    state = t.init_state()
    # Lion's momentum is param-shaped, so ZeRO-1 must shard it like params.
    sharded = [
        s for s in jax.tree.leaves(
            jax.tree.map(lambda x: x.sharding.spec, state.opt_state)
        )
        if any(ax is not None for ax in s)
    ]
    assert sharded, "zero1 left every lion moment leaf replicated"
    state, m = t.train_step(state, t.pipeline.global_batch(0))
    assert np.isfinite(float(m["loss"]))


def test_offload_opt_state_refuses_backend_without_pinned_host(tmp_path):
    """trainer.offload_opt_state is a TPU capacity feature; on the CPU sim
    (no pinned_host memory) the Trainer must refuse with a clear error
    instead of the partitioner's opaque RET_CHECK failure."""
    import pytest

    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["trainer.offload_opt_state=true", f"workdir={tmp_path}"],
    )
    with pytest.raises(ValueError, match="pinned_host"):
        Trainer(cfg)


def test_fused_adamw_matches_optax_adamw():
    """The fused kernel's math must be bit-compatible with optax.adamw
    (same bias correction, decoupled decay, LR scaling) over several
    steps — on the non-TPU fallback path AND through the Pallas kernel in
    interpret mode (padding/unpadding included via odd-sized leaves)."""
    import jax.numpy as jnp
    import optax

    from frl_distributed_ml_scaffold_tpu.ops.fused_adamw import fused_adamw

    params = {
        "w": jax.random.normal(jax.random.key(0), (37, 5)),  # odd size
        "b": jax.random.normal(jax.random.key(1), (3,)),
    }
    sched = optax.cosine_decay_schedule(1e-2, 20)
    kw = dict(b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.1)
    ref_tx = optax.adamw(sched, **kw)

    for interpret in (None, True):  # None -> jnp fallback on CPU; True -> kernel
        tx = fused_adamw(sched, interpret=interpret, **kw)
        p_ref, s_ref = dict(params), ref_tx.init(params)
        p_f, s_f = dict(params), tx.init(params)
        for step in range(3):
            grads = jax.tree.map(
                lambda p: jnp.cos(p + step).astype(p.dtype), p_ref
            )
            u, s_ref = ref_tx.update(grads, s_ref, p_ref)
            p_ref = optax.apply_updates(p_ref, u)
            p_f, s_f = tx.fused_apply(grads, s_f, p_f)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, atol=2e-6, rtol=2e-6
                ),
                p_ref,
                p_f,
            )
        # The generic optax contract (deltas) agrees with fused_apply too.
        tx2 = fused_adamw(sched, interpret=interpret, **kw)
        p2, s2 = dict(params), tx2.init(params)
        for step in range(2):
            grads = jax.tree.map(
                lambda p: jnp.cos(p + step).astype(p.dtype), p2
            )
            u2, s2 = tx2.update(grads, s2, p2)
            p2 = optax.apply_updates(p2, u2)
        # p2 after 2 steps == p_ref after... re-run ref for 2 steps
        p_r, s_r = dict(params), ref_tx.init(params)
        for step in range(2):
            grads = jax.tree.map(
                lambda p: jnp.cos(p + step).astype(p.dtype), p_r
            )
            u, s_r = ref_tx.update(grads, s_r, p_r)
            p_r = optax.apply_updates(p_r, u)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=2e-6, rtol=2e-6),
            p_r,
            p2,
        )


def test_fused_adamw_trains_end_to_end(tmp_path):
    """optimizer.name=fused_adamw through the full trainer (fallback path
    on the CPU sim): loss decreases, moment state is param-shaped."""
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "optimizer.name=fused_adamw",
            "optimizer.learning_rate=0.003",
            "trainer.total_steps=12",
            "trainer.log_every=1000",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "model.hidden_sizes=32",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(8):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    assert int(jax.device_get(state.opt_state.count)) == 8


def test_fused_adamw_refuses_grad_clip():
    import pytest

    with pytest.raises(ValueError, match="grad_clip_norm"):
        make_optimizer(
            OptimizerConfig(name="fused_adamw", grad_clip_norm=1.0),
            TrainerConfig(total_steps=10),
        )


def test_fused_adamw_refuses_sharded_state(tmp_path):
    """GSPMD cannot partition the opaque kernel: ZeRO/FSDP configs must be
    refused, not silently all-gathered every step."""
    import pytest

    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["optimizer.name=fused_adamw", f"workdir={tmp_path}"],
    )
    with pytest.raises(ValueError, match="fused_adamw requires replicated"):
        Trainer(cfg)


def test_fused_adamw_refuses_tp_mesh(tmp_path):
    """mesh.model>1 shards params via partition rules even under
    param_sharding=replicated — the opaque kernel would silently
    all-gather them each step (round-3 advisor finding), so the trainer
    must refuse TP/EP meshes just like ZeRO/FSDP."""
    import pytest

    cfg = apply_overrides(
        get_config("gpt2_tp"),
        ["optimizer.name=fused_adamw", f"workdir={tmp_path}"],
    )
    assert cfg.mesh.model > 1 and cfg.parallel.param_sharding == "replicated"
    with pytest.raises(ValueError, match="fused_adamw requires replicated"):
        Trainer(cfg)
