"""Optimizer/schedule factory extras (SURVEY C3): lion and the WSD
schedule behave as specified."""

import jax
import numpy as np

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.config.schema import OptimizerConfig, TrainerConfig
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
from frl_distributed_ml_scaffold_tpu.trainer.optimizers import (
    make_optimizer,
    make_schedule,
)
from frl_distributed_ml_scaffold_tpu.utils.trees import tree_param_count


def test_wsd_schedule_shape():
    cfg = OptimizerConfig(
        learning_rate=1.0, schedule="wsd", warmup_steps=10, wsd_decay_fraction=0.5
    )
    sched = make_schedule(cfg, total_steps=110)  # 10 warmup + 50 stable + 50 decay
    assert float(sched(0)) == 0.0  # warmup starts at zero
    np.testing.assert_allclose(float(sched(10)), 1.0, atol=1e-6)  # peak
    np.testing.assert_allclose(float(sched(59)), 1.0, atol=1e-6)  # stable hold
    assert 0.0 < float(sched(85)) < 1.0  # inside the decay ramp
    np.testing.assert_allclose(float(sched(110)), 0.0, atol=1e-6)  # decayed out


def test_lion_trains_and_halves_moment_state():
    def trainer_for(name):
        cfg = apply_overrides(
            get_config("mnist_mlp"),
            [
                "trainer.total_steps=6",
                "trainer.log_every=100",
                "data.global_batch_size=64",
                "model.hidden_sizes=32",
                "precision.policy=fp32",
                f"optimizer.name={name}",
                # Lion's canonical LR is ~a decade under AdamW's.
                "optimizer.learning_rate=0.0003",
                "workdir=/tmp/frl_lion_test",
            ],
        )
        return Trainer(cfg)

    t = trainer_for("lion")
    state = t.init_state()
    losses = []
    for step in range(6):
        state, m = t.train_step(state, t.pipeline.global_batch(step))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses

    # One moment vs AdamW's two: the optimizer state is ~half the memory.
    lion_state_n = tree_param_count(state.opt_state)
    adamw = trainer_for("adamw")
    adamw_state_n = tree_param_count(adamw.init_state().opt_state)
    assert lion_state_n < 0.6 * adamw_state_n, (lion_state_n, adamw_state_n)


def test_lion_composes_with_zero1_sharding():
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=2",
            "data.global_batch_size=64",
            "model.hidden_sizes=64,64",
            "precision.policy=fp32",
            "optimizer.name=lion",
            "mesh.data=4",
            "mesh.fsdp=2",
            "parallel.opt_sharding=zero1",
            "parallel.fsdp_min_size=1",
            "workdir=/tmp/frl_lion_zero1",
        ],
    )
    t = Trainer(cfg)
    state = t.init_state()
    # Lion's momentum is param-shaped, so ZeRO-1 must shard it like params.
    sharded = [
        s for s in jax.tree.leaves(
            jax.tree.map(lambda x: x.sharding.spec, state.opt_state)
        )
        if any(ax is not None for ax in s)
    ]
    assert sharded, "zero1 left every lion moment leaf replicated"
    state, m = t.train_step(state, t.pipeline.global_batch(0))
    assert np.isfinite(float(m["loss"]))


def test_offload_opt_state_refuses_backend_without_pinned_host(tmp_path):
    """trainer.offload_opt_state is a TPU capacity feature; on the CPU sim
    (no pinned_host memory) the Trainer must refuse with a clear error
    instead of the partitioner's opaque RET_CHECK failure."""
    import pytest

    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["trainer.offload_opt_state=true", f"workdir={tmp_path}"],
    )
    with pytest.raises(ValueError, match="pinned_host"):
        Trainer(cfg)
