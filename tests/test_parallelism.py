"""Simulated-distributed tier (SURVEY §4): every strategy must (i) match the
single-device run numerically and (ii) produce the expected shardings."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
from frl_distributed_ml_scaffold_tpu.utils.trees import named_tree_map


def make_trainer(tmp_path, mesh_overrides, extra=(), devices=None):
    cfg = get_config("mnist_mlp")
    cfg = apply_overrides(
        cfg,
        [
            "trainer.total_steps=5",
            "data.global_batch_size=64",
            "model.hidden_sizes=64,32",
            "precision.policy=fp32",
            f"workdir={tmp_path}",
        ]
        + list(mesh_overrides)
        + list(extra),
    )
    env = build_mesh(cfg.mesh, devices=devices)
    return Trainer(cfg, mesh_env=env)


def run_steps(trainer, n=5):
    state = trainer.init_state()
    for step in range(n):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    return jax.device_get(state), jax.device_get(metrics)


def assert_trees_close(a, b, atol=1e-5):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=atol, rtol=1e-5), a, b
    )


@pytest.fixture(scope="module")
def single_device_result(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("single")
    trainer = make_trainer(tmp, ["mesh.data=1"], devices=jax.devices()[:1])
    return run_steps(trainer)


def test_dp_matches_single_device(tmp_path, single_device_result):
    """DDP equivalence (SURVEY C4): 8-way DP == 1 device, same global batch."""
    trainer = make_trainer(tmp_path, ["mesh.data=8"])
    state, metrics = run_steps(trainer)
    ref_state, ref_metrics = single_device_result
    assert_trees_close(state.params, ref_state.params)
    np.testing.assert_allclose(metrics["loss"], ref_metrics["loss"], atol=1e-5)


def test_fsdp_matches_and_shards(tmp_path, single_device_result):
    """FSDP (SURVEY C5): full-shard equivalence + params actually sharded."""
    trainer = make_trainer(
        tmp_path,
        ["mesh.data=1", "mesh.fsdp=8"],
        extra=["parallel.param_sharding=fsdp", "parallel.fsdp_min_size=64"],
    )
    state_dev = trainer.init_state()

    def check(name, leaf):
        if leaf.size >= 64:
            assert any(
                "fsdp" in (e or ()) if isinstance(e, tuple) else e == "fsdp"
                for e in leaf.sharding.spec
            ), f"{name} not fsdp-sharded: {leaf.sharding.spec}"
        return leaf

    named_tree_map(check, state_dev.params)

    for step in range(5):
        batch = trainer.pipeline.global_batch(step)
        state_dev, metrics = trainer.train_step(state_dev, batch)
    state = jax.device_get(state_dev)
    ref_state, _ = single_device_result
    assert_trees_close(state.params, ref_state.params)


def test_dp_x_fsdp_hybrid(tmp_path, single_device_result):
    """2-way DP x 4-way FSDP hybrid matches single device.

    Param tolerance is steps x lr (5 x 1e-3), not 1e-5: adamw amplifies
    numerically-zero grads into lr-scale sign updates from float noise,
    and the hybrid mesh reorders those reductions (multi-core XLA
    reassociation; see tests/test_fsdp_overlap.py for the class). The
    loss stays tight — that is the real equivalence signal."""
    trainer = make_trainer(
        tmp_path,
        ["mesh.data=2", "mesh.fsdp=4"],
        extra=["parallel.param_sharding=fsdp", "parallel.fsdp_min_size=64"],
    )
    state, metrics = run_steps(trainer)
    ref_state, ref_metrics = single_device_result
    assert_trees_close(state.params, ref_state.params, atol=5e-3)
    np.testing.assert_allclose(
        metrics["loss"], ref_metrics["loss"], atol=1e-3
    )


def test_zero1_shards_opt_state_only(tmp_path, single_device_result):
    """ZeRO-1 (SURVEY C5): params replicated, adam mu/nu sharded, math equal."""
    trainer = make_trainer(
        tmp_path,
        ["mesh.data=1", "mesh.fsdp=8"],
        extra=["parallel.opt_sharding=zero1", "parallel.fsdp_min_size=64"],
    )
    state_dev = trainer.init_state()

    # Params replicated:
    for leaf in jax.tree.leaves(state_dev.params):
        assert leaf.sharding.spec == P(), f"param unexpectedly sharded: {leaf.sharding.spec}"
    # Large optimizer-state mirrors sharded:
    sharded = [
        leaf
        for leaf in jax.tree.leaves(state_dev.opt_state)
        if hasattr(leaf, "sharding") and leaf.ndim > 0 and leaf.size >= 64
        and leaf.sharding.spec != P()
    ]
    assert sharded, "no optimizer-state leaf is sharded under zero1"

    for step in range(5):
        batch = trainer.pipeline.global_batch(step)
        state_dev, _ = trainer.train_step(state_dev, batch)
    state = jax.device_get(state_dev)
    ref_state, _ = single_device_result
    assert_trees_close(state.params, ref_state.params)


def test_opt_state_unmatched_leaf_warns_and_replicates():
    """ZeRO sharding silently no-ops for optimizer states that don't embed
    param-suffixed subtrees (e.g. factored states) — that must warn, not
    pass quietly (VERDICT r1 weak #7)."""
    from frl_distributed_ml_scaffold_tpu.config.schema import (
        MeshConfig,
        ParallelConfig,
    )
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        opt_state_specs,
        param_specs,
    )
    env = build_mesh(MeshConfig(fsdp=8))
    parallel = ParallelConfig(
        param_sharding="replicated", opt_sharding="zero1", fsdp_min_size=1024
    )
    params = {"dense": {"kernel": jnp.zeros((64, 64))}}
    p_specs = param_specs(params, parallel, env.mesh)
    # A factored-style state: big leaves under paths that do NOT end with
    # any param path.
    opt_state = {
        "factored_v_row": jnp.zeros((64, 64)),
        "tiny": jnp.zeros((4,)),  # below fsdp_min_size: no warning for this
    }

    from conftest import capture_frl_logs

    with capture_frl_logs() as records:
        specs = opt_state_specs(opt_state, params, p_specs, parallel, env.mesh)
    assert specs["factored_v_row"] == P()
    warnings = [m for m in records if "REPLICATED" in m]
    assert len(warnings) == 1, records
    assert "factored_v_row" in warnings[0] and "tiny" not in warnings[0]


def test_fsdp_overlap_refuses_unhooked_family(tmp_path):
    """Overlap-scheduled FSDP (parallel/fsdp_overlap.py) exists only for
    model families with blockwise apply hooks (gpt, resnet); an MLP config
    must refuse loudly — a silent fallback to the GSPMD schedule would
    invalidate any A/B built on the flag."""
    with pytest.raises(ValueError, match="blockwise apply hooks"):
        make_trainer(
            tmp_path,
            ["mesh.data=1", "mesh.fsdp=8"],
            extra=["parallel.param_sharding=fsdp", "parallel.fsdp_overlap=true"],
        )


def test_grad_accum_matches(tmp_path, single_device_result):
    """Grad accumulation (SURVEY C12): 4 microbatches == 1 full batch."""
    trainer = make_trainer(
        tmp_path, ["mesh.data=8"], extra=["trainer.grad_accum=4"]
    )
    state, _ = run_steps(trainer)
    ref_state, _ = single_device_result
    assert_trees_close(state.params, ref_state.params)


def test_remat_matches(tmp_path, single_device_result):
    """Activation checkpointing (SURVEY C11) must not change the math."""
    for mode in ("full", "dots"):
        trainer = make_trainer(
            tmp_path, ["mesh.data=8"], extra=[f"trainer.remat={mode}"]
        )
        state, _ = run_steps(trainer)
        ref_state, _ = single_device_result
        assert_trees_close(state.params, ref_state.params)


def test_bf16_mixed_policy_runs_and_learns(tmp_path):
    """bf16 AMP smoke (SURVEY C10): runs, loss finite and decreasing."""
    trainer = make_trainer(
        tmp_path, ["mesh.data=8"], extra=["precision.policy=bf16_mixed"]
    )
    state = trainer.init_state()
    first = None
    for step in range(10):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert np.isfinite(last) and last < first
    # Params stay fp32 (master copy), per the bf16_mixed policy.
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(state.params))