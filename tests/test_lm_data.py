"""Token-bin LM corpus loader (SURVEY C16): producer/consumer round-trip,
deterministic step-indexed sampling, synthetic fallback, trainer wiring."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import json
import os

import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.lm import TokenBinLM, write_token_bin


def make_corpus(tmp_path, n=4096, vocab=512, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, size=n)
    write_token_bin(str(tmp_path / "train.bin"), tokens, vocab_size=vocab)
    return tokens


def test_round_trip_windows_match_source(tmp_path):
    tokens = make_corpus(tmp_path)
    cfg = DataConfig(
        name="lm", data_dir=str(tmp_path), seq_len=64, vocab_size=512
    )
    src = TokenBinLM(cfg, split="train")
    assert not src.is_synthetic
    batch = src.batch(3, batch_size=8)
    assert batch["tokens"].shape == (8, 65)  # seq_len + 1 (shifted target)
    assert batch["tokens"].dtype == np.int32
    # Every row must be a contiguous window of the source stream.
    for row in batch["tokens"]:
        starts = np.where(tokens == row[0])[0]
        assert any(
            np.array_equal(tokens[s : s + 65], row)
            for s in starts
            if s + 65 <= len(tokens)
        )


def test_sampling_is_pure_function_of_step(tmp_path):
    make_corpus(tmp_path)
    cfg = DataConfig(
        name="lm", data_dir=str(tmp_path), seq_len=32, vocab_size=512
    )
    a = TokenBinLM(cfg, split="train").batch(5, 4)
    b = TokenBinLM(cfg, split="train").batch(5, 4)  # fresh instance
    np.testing.assert_array_equal(a["tokens"], b["tokens"])
    c = TokenBinLM(cfg, split="train").batch(6, 4)
    assert not np.array_equal(a["tokens"], c["tokens"])
    # Validation split reuses train.bin but salts the stream.
    d = TokenBinLM(cfg, split="validation").batch(5, 4)
    assert not np.array_equal(a["tokens"], d["tokens"])


def test_uint16_dtype_chosen_and_read_back(tmp_path):
    make_corpus(tmp_path, vocab=500)
    with open(tmp_path / "train.bin.json") as fh:
        assert json.load(fh)["dtype"] == "uint16"
    big = np.array([0, 70000, 3], dtype=np.int64)
    write_token_bin(str(tmp_path / "big" / "train.bin"), big)
    cfg = DataConfig(
        name="lm", data_dir=str(tmp_path / "big"), seq_len=1, vocab_size=100000
    )
    src = TokenBinLM(cfg, split="train")
    assert src._mm.dtype == np.uint32
    assert 70000 in np.asarray(src.batch(0, 4)["tokens"])


def test_synthetic_fallback_without_dir():
    cfg = DataConfig(name="lm", data_dir=None, seq_len=16, vocab_size=64)
    src = TokenBinLM(cfg, split="train")
    assert src.is_synthetic
    assert src.batch(0, 4)["tokens"].shape == (4, 17)


def test_vocab_mismatch_raises(tmp_path):
    make_corpus(tmp_path, vocab=512)
    cfg = DataConfig(
        name="lm", data_dir=str(tmp_path), seq_len=16, vocab_size=256
    )
    with pytest.raises(ValueError, match="vocab_size"):
        TokenBinLM(cfg, split="train")


def test_gpt_trains_on_token_bin_corpus(tmp_path):
    """BASELINE config 4 accepts data.name=lm + data_dir (VERDICT r1 #6)."""
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    corpus_dir = tmp_path / "corpus"
    corpus_dir.mkdir()
    make_corpus(corpus_dir, n=8192, vocab=256)
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        [
            "model.num_layers=2",
            "model.hidden_dim=64",
            "model.num_heads=2",
            "model.vocab_size=256",
            "model.seq_len=32",
            "data.name=lm",
            f"data.data_dir={corpus_dir}",
            "data.seq_len=32",
            "data.vocab_size=256",
            "data.global_batch_size=8",
            "data.prefetch=0",
            "trainer.grad_accum=1",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    assert not trainer.pipeline.source.is_synthetic
    state = trainer.init_state()
    losses = []
    for step in range(4):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()


def test_encode_corpus_byte_level_round_trip(tmp_path):
    """tools/encode_corpus.py --byte-level: raw text -> train.bin the LM
    loader consumes — the producer CLI half of the token-bin contract."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    (tmp_path / "a.txt").write_text("hello world")
    (tmp_path / "b.txt").write_text("second doc")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "encode_corpus.py"),
         str(tmp_path / "corpus"), str(tmp_path / "a.txt"),
         str(tmp_path / "b.txt"), "--byte-level"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    meta = json.loads(out.stdout.strip().splitlines()[-1])
    # 11 + separator + 10 + separator
    assert meta["tokens"] == 23 and meta["vocab_size"] == 256

    cfg = DataConfig(
        name="lm", data_dir=str(tmp_path / "corpus"), seq_len=8,
        vocab_size=256, global_batch_size=4,
    )
    ds = TokenBinLM(cfg, split="train")
    assert not ds.is_synthetic
    batch = ds.batch(0, batch_size=4)
    x = batch["tokens"]
    assert x.shape == (4, 9) and x.dtype == np.int32  # seq_len + 1
    # Byte-level: every sampled window is a verbatim slice of the corpus
    # byte stream (documents joined by the 0 separator).
    corpus = b"hello world\x00second doc\x00"
    for row in x:
        assert bytes(row.astype(np.uint8)) in corpus, row
