"""Low-precision fast path gates (parallel.low_precision +
ops/collective_matmul.py ``lowp``): the quantized collective-matmul rings
must (i) track the full-precision rings numerically at the DOCUMENTED
tolerances (int8 per-tensor quantization is ~0.4% relative noise per
tensor; after 3 adamw steps on the tiny grid the observed param drift is
~1e-3, loss drift ~3e-5 — gated at 1e-2 / 5e-3 with margin, see
docs/perf_playbook.md "Low-precision fast path"), (ii) actually shrink
the wire — every chunk-sized ppermute payload is 1-byte, pinned through
the per-dtype collective census at >= 3x lower collective-permute bytes
than the full-precision schedule — and (iii) refuse configs where the
knob would silently change nothing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.analysis import pins
from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    census_by_dtype,
    census_diff,
    collective_census,
)
from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    build_mesh,
    mesh_context,
    shard_map_compat,
)
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

GPT_TINY = [
    "model.num_layers=2", "model.num_heads=4", "model.hidden_dim=64",
    "model.seq_len=64", "model.vocab_size=256",
    "data.seq_len=64", "data.vocab_size=256",
    "data.global_batch_size=16",
    "trainer.grad_accum=1", "trainer.remat=none",
    "trainer.log_every=1000000",
    "precision.policy=fp32",
    "checkpoint.enabled=false",
    "optimizer.warmup_steps=0",
]


def make_trainer(name, overrides, tmp_path):
    cfg = apply_overrides(
        get_config(name), GPT_TINY + [f"workdir={tmp_path}"] + list(overrides)
    )
    return Trainer(cfg, mesh_env=build_mesh(cfg.mesh))


def run_steps(trainer, n=3):
    state = trainer.init_state()
    for step in range(n):
        state, metrics = trainer.train_step(
            state, trainer.pipeline.global_batch(step)
        )
    return jax.device_get(state), jax.device_get(metrics)


def assert_close_at_lowp_tolerance(ref, lp, ref_m=None, lp_m=None):
    """THE documented int8-vs-full-precision band: params within 1e-2
    absolute (quantization noise x adamw's lr-scale amplification of
    sign flips, ~8x margin over the observed ~1.2e-3), losses within
    5e-3 relative."""
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-2, rtol=1e-3),
        ref.params,
        lp.params,
    )
    if ref_m is not None:
        l_ref, l_lp = float(ref_m["loss"]), float(lp_m["loss"])
        assert abs(l_ref - l_lp) <= 5e-3 * max(1.0, abs(l_ref)), (
            l_ref, l_lp,
        )


# ------------------------------------------------------------- ring level


def _ring_pair(lowp, grad=False):
    """agm -> mrs on a data=2 x model=4 mesh, JITTED (eager shard_map
    dispatch of the unrolled rings costs minutes of per-op compiles on
    the sim; one jitted program is sub-second)."""
    from functools import partial

    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.ops.collective_matmul import (
        all_gather_matmul,
        matmul_reduce_scatter,
    )

    env = build_mesh(MeshConfig(data=2, model=4))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 32, 16)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(16, 24)), jnp.float32) * 0.2
    w2 = jnp.asarray(rng.normal(size=(24, 16)), jnp.float32) * 0.2

    def fwd(x, w1, w2):
        agm = shard_map_compat(
            partial(all_gather_matmul, axis_name="model", chunk_axis=1,
                    return_full=False, precision=None, lowp=lowp),
            mesh=env.mesh,
            in_specs=(P(None, "model", None), P(None, "model")),
            out_specs=P(None, None, "model"),
        )
        mrs = shard_map_compat(
            partial(matmul_reduce_scatter, axis_name="model", chunk_axis=1,
                    precision=None, lowp=lowp),
            mesh=env.mesh,
            in_specs=(P(None, None, "model"), P("model", None)),
            out_specs=P(None, "model", None),
        )
        return mrs(agm(x, w1), w2)

    with mesh_context(env):
        if grad:
            return jax.jit(
                jax.grad(lambda *a: (fwd(*a) ** 2).sum(), argnums=(0, 1, 2))
            )(x, w1, w2)
        return jax.jit(fwd)(x, w1, w2)


@pytest.mark.fast
@pytest.mark.parametrize("lowp", ["int8", "fp8_e4m3"])
def test_ring_pair_forward_tracks_full_precision(lowp):
    """agm -> mrs (the Megatron column->row pair) quantized vs full
    precision, per-shard: the op-level tolerance band (int8 ~1%, fp8_e4m3
    ~4% — one fewer mantissa bit than the scaled-int grid)."""
    ref = _ring_pair(None)
    out = _ring_pair(lowp)
    rel = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
    assert rel < (0.03 if lowp == "int8" else 0.10), (lowp, rel)


@pytest.mark.fast
def test_ring_grads_track_full_precision_straight_through():
    """The backward rings quantize their own transfers but differentiate
    straight-through — gradients stay within the same relative band."""
    ref = _ring_pair(None, grad=True)
    out = _ring_pair("int8", grad=True)
    for a, b in zip(ref, out):
        rel = float(jnp.abs(a - b).max() / jnp.abs(a).max())
        assert rel < 0.05, rel


# ---------------------------------------------------------- trainer grids


def int8_pair(tmp_path, mesh, extra=()):
    """(full-precision tp_overlap, int8 tp_overlap) after 3 steps on the
    same mesh — the quantization-noise-only A/B (both sides run the ring
    schedule, so the delta IS the low-precision path)."""
    ref = make_trainer(
        "gpt2_medium_tp_overlap", mesh + list(extra), tmp_path / "ref"
    )
    lp = make_trainer(
        "gpt2_medium_tp_overlap_int8", mesh + list(extra), tmp_path / "lp"
    )
    return run_steps(ref), run_steps(lp)


def test_int8_rings_match_model_only_mesh(tmp_path):
    """model=8: the pure-TP mesh of the acceptance grid, plus the
    sharding sanity check (a silently replicated run would also
    'match')."""
    (ref, ref_m), (lp, lp_m) = int8_pair(
        tmp_path, ["mesh.data=1", "mesh.model=8"]
    )
    assert_close_at_lowp_tolerance(ref, lp, ref_m, lp_m)
    t = make_trainer(
        "gpt2_medium_tp_overlap_int8", ["mesh.data=1", "mesh.model=8"],
        tmp_path / "shard",
    )
    state = t.init_state()
    qk = state.params["blocks"]["attn"]["query"]["kernel"]
    assert any(
        e == "model" or (isinstance(e, tuple) and "model" in e)
        for e in qk.sharding.spec
    ), qk.sharding.spec


@pytest.mark.slow
def test_int8_rings_match_fsdp_x_model(tmp_path):
    """data=2 x fsdp=2 x model=2 with fsdp-sharded params: the quantized
    rings must compose with GSPMD's fsdp gathers of the weight shards.
    (slow tier: each trainer pair costs ~60 s of XLA compiles — the
    model-only pair plus the op-level band tests carry tier-1.)"""
    extra = [
        "parallel.param_sharding=fsdp", "parallel.opt_sharding=like_params",
        "parallel.fsdp_min_size=16",
    ]
    (ref, _), (lp, _) = int8_pair(
        tmp_path, ["mesh.data=2", "mesh.fsdp=2", "mesh.model=2"], extra
    )
    assert_close_at_lowp_tolerance(ref, lp)


@pytest.mark.slow
def test_int8_rings_grad_accum_matches(tmp_path):
    """grad_accum=4: the quantized rings run inside the microbatch scan
    body (the acceptance grid's accumulation cell; slow tier — see
    test_int8_rings_match_fsdp_x_model)."""
    (ref, _), (lp, _) = int8_pair(
        tmp_path, ["mesh.data=2", "mesh.model=4"],
        extra=["trainer.grad_accum=4"],
    )
    assert_close_at_lowp_tolerance(ref, lp)


@pytest.mark.slow
@pytest.mark.parametrize("block_remat", ["full", "save_attn"])
def test_int8_rings_block_remat_interaction(tmp_path, block_remat):
    """Remat cells: the quantized rings sit inside the remat region, so
    the backward re-runs them (re-quantizing the SAME values — the
    deterministic quantizer makes recompute reproduce the forward)."""
    (ref, _), (lp, _) = int8_pair(
        tmp_path, ["mesh.data=2", "mesh.model=4"],
        extra=[f"model.block_remat={block_remat}"],
    )
    assert_close_at_lowp_tolerance(ref, lp)


# ----------------------------------------------------------- bytes pins


def _step_census(t):
    state = t.init_state()
    batch = t.pipeline.global_batch(0)
    with mesh_context(t.env):
        jaxpr = jax.make_jaxpr(t._train_step_fn)(state, batch)
    return collective_census(jaxpr)


@pytest.mark.fast
def test_int8_ring_collective_bytes_pinned_3x_lower(tmp_path):
    """THE comm pin of the acceptance gate (ISSUE 6): on the same mesh,
    the int8 recipe's collective-permute bytes are >= 3x lower than the
    full-precision rings' (4x at the fp32 sim policy minus scale
    traffic), every chunk-sized ppermute payload is 1-byte
    (assert_collective_bytes_within on the wide dtypes: only scalar
    scales remain), and census_diff against the full-precision census
    shows the f32 chunk traffic REMOVED and int8 traffic ADDED — the
    promoted, diffable form of 'the rings actually shrank'."""
    mesh = ["mesh.data=1", "mesh.model=8"]
    ref = make_trainer("gpt2_medium_tp_overlap", mesh, tmp_path / "ref")
    lp = make_trainer("gpt2_medium_tp_overlap_int8", mesh, tmp_path / "lp")
    c_ref = _step_census(ref)
    c_lp = _step_census(lp)

    ref_bytes = pins.collective_bytes(c_ref, "ppermute", axes=("model",))
    lp_bytes = pins.collective_bytes(c_lp, "ppermute", axes=("model",))
    assert ref_bytes > 0 and lp_bytes > 0
    assert ref_bytes >= 3 * lp_bytes, (ref_bytes, lp_bytes)

    # Wide dtypes may carry only the scalar scales: budget = the scale
    # traffic itself (one f32 per chunk transfer) with 2x headroom.
    by_dtype = census_by_dtype(c_lp)
    scale_bytes = by_dtype.get(("ppermute", "float32"), {}).get(
        "total_bytes", 0
    )
    pins.assert_collective_bytes_within(
        c_lp, "ppermute", max(2 * scale_bytes, 1),
        dtypes=("float32", "bfloat16", "float16"),
        msg="int8 recipe moves chunk-sized wide-float ppermute traffic",
    )
    assert by_dtype[("ppermute", "int8")]["total_bytes"] > 0

    # The diffable artifact: f32 chunk records removed, int8 added.
    diff = census_diff(c_ref, c_lp)
    assert any(d["dtype"] == "int8" for d in diff["added"]), diff["added"]
    assert any(
        d["dtype"] == "float32" and d["primitive"] == "ppermute"
        for d in diff["removed"]
    ), diff["removed"]


@pytest.mark.fast
def test_fp8_knob_traces_fp8_rings(tmp_path):
    """The fp8 flavors ride the same knob: parallel.low_precision=
    fp8_e4m3 produces float8 ppermute payloads (smoke — the deep numerics
    grid rides int8, the serving default)."""
    t = make_trainer(
        "gpt2_medium_tp_overlap",
        ["mesh.data=1", "mesh.model=8", "parallel.low_precision=fp8_e4m3"],
        tmp_path,
    )
    by_dtype = census_by_dtype(_step_census(t))
    assert by_dtype.get(("ppermute", "float8_e4m3fn"), {}).get(
        "total_bytes", 0
    ) > 0, sorted(by_dtype)


# ------------------------------------------------------------- validation


@pytest.mark.fast
def test_low_precision_requires_tp_overlap(tmp_path):
    """The knob quantizes the rings; without them it must refuse, not
    silently change nothing (the no-silent-fallback contract)."""
    with pytest.raises(ValueError, match="tp_overlap"):
        make_trainer(
            "gpt2_medium_zero1",
            ["mesh.fsdp=8", "parallel.low_precision=int8"],
            tmp_path,
        )


@pytest.mark.fast
def test_low_precision_unknown_format_refuses(tmp_path):
    with pytest.raises(KeyError, match="fp8_e4m3"):
        make_trainer(
            "gpt2_medium_tp_overlap",
            ["mesh.data=1", "mesh.model=8", "parallel.low_precision=int4"],
            tmp_path,
        )
