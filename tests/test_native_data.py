"""Native data core (SURVEY C16): C++ path vs numpy fallback parity, and
the prefetching pipeline's exact-resume contract."""

from __future__ import annotations
import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast


import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.data import native as nv


requires_native = pytest.mark.skipif(
    not nv.native_available(), reason="native core unavailable (no g++?)"
)


def test_gather_rows_matches_fancy_index():
    src = np.random.default_rng(0).random((64, 3, 5), np.float32)
    idx = np.array([0, 63, 7, 7, 12], np.int64)
    out = nv.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_noncontiguous_falls_back():
    src = np.random.default_rng(0).random((64, 8), np.float32)[:, ::2]
    idx = np.array([0, 5], np.int64)
    np.testing.assert_array_equal(nv.gather_rows(src, idx), src[idx])


def test_gather_rows_uint8_scales():
    src = np.random.default_rng(0).integers(0, 256, (32, 4, 4, 3)).astype(np.uint8)
    idx = np.array([3, 0, 31], np.int64)
    out = nv.gather_rows(src, idx)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, src[idx].astype(np.float32) / 255.0,
                               rtol=1e-6)


@pytest.mark.parametrize("dtype", [np.uint16, np.uint32])
def test_gather_windows_matches_slices(dtype):
    src = np.random.default_rng(1).integers(0, 60000, size=4096).astype(dtype)
    starts = np.array([0, 17, 4096 - 33, 1000, 17], np.int64)
    out = nv.gather_windows(src, starts, 33)
    assert out.dtype == np.int32
    for row, s in zip(out, starts):
        np.testing.assert_array_equal(row, src[s : s + 33].astype(np.int32))


def test_gather_windows_bounds_checked():
    src = np.zeros(100, np.uint16)
    with pytest.raises(IndexError):
        nv.gather_windows(src, np.array([90], np.int64), 11)
    with pytest.raises(IndexError):
        nv.gather_windows(src, np.array([-1], np.int64), 5)


@requires_native
def test_gather_windows_native_matches_fallback(monkeypatch):
    src = np.random.default_rng(2).integers(0, 2**16, size=8192).astype(np.uint16)
    starts = np.random.default_rng(3).integers(0, 8192 - 65, size=64)
    native_out = nv.gather_windows(src, starts, 65)
    monkeypatch.setattr(nv, "_load", lambda: None)
    fallback_out = nv.gather_windows(src, starts, 65)
    np.testing.assert_array_equal(native_out, fallback_out)


def test_pool_stress_back_to_back_calls():
    """Race regression: rapid back-to-back parallel_for calls (the
    gather-then-augment pattern) must neither corrupt results nor hang."""
    rng = np.random.default_rng(7)
    src = rng.random((256, 64), np.float32)
    for trial in range(50):
        idx = rng.integers(0, 256, 64).astype(np.int64)
        out1 = nv.gather_rows(src, idx)
        out2 = nv.gather_rows(src, idx[::-1].copy())
        np.testing.assert_array_equal(out1, src[idx])
        np.testing.assert_array_equal(out2, src[idx[::-1]])


def test_augment_eval_is_center_crop_normalize():
    x = np.random.default_rng(1).random((4, 36, 36, 3), np.float32)
    out = nv.augment_batch(x, 32, seed=9, train=False)
    ref = (x[:, 2:34, 2:34] - nv._IMAGENET_MEAN) / nv._IMAGENET_STD
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_augment_train_outputs_are_crops():
    """Every train output must equal some (crop, flip) of its input."""
    x = np.random.default_rng(2).random((2, 20, 20, 1), np.float32)
    out = nv.augment_batch(
        x, 16, seed=3, train=True,
        mean=np.zeros(1, np.float32), std=np.ones(1, np.float32),
    )
    for i in range(2):
        candidates = []
        for y0 in range(5):
            for x0 in range(5):
                patch = x[i, y0:y0 + 16, x0:x0 + 16]
                candidates += [patch, patch[:, ::-1]]
        assert any(np.allclose(out[i], c, atol=1e-6) for c in candidates)


def test_augment_deterministic_in_seed():
    x = np.random.default_rng(4).random((8, 40, 40, 3), np.float32)
    a = nv.augment_batch(x, 32, seed=11, train=True)
    b = nv.augment_batch(x, 32, seed=11, train=True)
    c = nv.augment_batch(x, 32, seed=12, train=True)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_gather_rows_rejects_out_of_bounds():
    src = np.random.default_rng(0).random((16, 4), np.float32)
    for bad in ([-1, 0], [0, 16], [99]):
        with pytest.raises(IndexError, match="out of bounds"):
            nv.gather_rows(src, np.array(bad, np.int64))


@requires_native
def test_augment_native_matches_numpy_bitwise():
    """Native and numpy augmentation must share ONE RNG stream: resuming in
    an environment whose native availability differs must not change the
    training stream (batches are pure functions of (seed, step))."""
    rng = np.random.default_rng(5)
    # (40, 36): both crop dims free; (32, 36) / (40, 32) / (32, 32): the
    # draw-SKIPPING branches (a dim with no crop freedom consumes no RNG
    # draw, in C++ and numpy alike — the subtlest part of the contract).
    for h, w in ((40, 36), (32, 36), (40, 32), (32, 32)):
        x = rng.random((16, h, w, 3), np.float32)
        for seed, train in ((0, True), (123456789, True), (7, False)):
            a = nv.augment_batch(x, 32, seed=seed, train=train)
            b = nv._augment_numpy(
                x, 32, seed=seed, train=train,
                mean=nv._IMAGENET_MEAN, std=nv._IMAGENET_STD,
            )
            np.testing.assert_array_equal(a, b)


def test_augment_rejects_oversized_crop():
    x = np.zeros((2, 16, 16, 3), np.float32)
    with pytest.raises(ValueError, match="crop"):
        nv.augment_batch(x, 32, seed=0, train=True)


def test_imagenet_real_shards_gather_and_augment(tmp_path):
    """Sharded .npy store -> mmap, native gather, crop-augment to model size."""
    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
    from frl_distributed_ml_scaffold_tpu.data.imagenet import ImageNet

    rng = np.random.default_rng(0)
    n_per, stored, target = 10, 40, 32
    for shard in range(2):
        np.save(
            tmp_path / f"train_images_{shard:03d}.npy",
            rng.random((n_per, stored, stored, 3), np.float32),
        )
        np.save(
            tmp_path / f"train_labels_{shard:03d}.npy",
            rng.integers(0, 5, n_per).astype(np.int32),
        )
    cfg = DataConfig(
        name="imagenet", image_size=target, num_classes=5, channels=3,
        data_dir=str(tmp_path),
    )
    src = ImageNet(cfg, split="train")
    assert not src.is_synthetic
    b = src.batch(0, 8)
    assert b["image"].shape == (8, target, target, 3)
    assert b["label"].shape == (8,)
    # step-determinism (exact resume contract)
    b2 = src.batch(0, 8)
    np.testing.assert_array_equal(b["image"], b2["image"])
    assert not np.array_equal(b["image"], src.batch(1, 8)["image"])


def test_prefetching_pipeline_matches_synchronous():
    import jax

    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig, MeshConfig
    from frl_distributed_ml_scaffold_tpu.data.pipeline import (
        DataPipeline,
        PrefetchingPipeline,
    )
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh

    env = build_mesh(MeshConfig(data=8))
    cfg = DataConfig(name="synthetic_mnist", global_batch_size=32)
    sync = DataPipeline(cfg, env)
    pre = PrefetchingPipeline(DataPipeline(cfg, env), depth=3)
    # Arbitrary access order incl. a resume-style jump backwards.
    for step in (0, 1, 2, 5, 6, 1, 2):
        a = sync.global_batch(step)
        b = pre.global_batch(step)
        for k in a:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a[k])), np.asarray(jax.device_get(b[k]))
            )


def test_trainer_uses_prefetching_pipeline(tmp_path):
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.data.pipeline import PrefetchingPipeline
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=6",
            "trainer.log_every=3",
            "data.global_batch_size=32",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    assert isinstance(trainer.pipeline, PrefetchingPipeline)
    _, last = trainer.fit()
    assert last["loss"] < 3.0
    # fit() closed the prefetcher: no leaked worker, no in-flight futures.
    assert trainer.pipeline._ex is None and not trainer.pipeline._futures
    # ...and the pipeline transparently re-opens for a second fit.
    _, last2 = trainer.fit(num_steps=8)
    assert last2["loss"] <= last["loss"] + 1e-3


def test_prefetch_transfers_on_worker_thread():
    """A consumed prefetched batch must already be COMMITTED to its
    devices: the worker runs the full host->device path (shardings_for +
    placement + the readiness wait), so the consumer thread never pays
    H2D. Pinned by recording which thread ran the build."""
    import threading

    import jax

    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig, MeshConfig
    from frl_distributed_ml_scaffold_tpu.data.pipeline import (
        DataPipeline,
        PrefetchingPipeline,
    )
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh

    env = build_mesh(MeshConfig(data=8))
    cfg = DataConfig(name="synthetic_mnist", global_batch_size=32)
    inner = DataPipeline(cfg, env)
    build_threads: list[str] = []
    orig = inner.global_batch

    def recording(step):
        build_threads.append(threading.current_thread().name)
        return orig(step)

    inner.global_batch = recording
    pre = PrefetchingPipeline(inner, depth=2)
    try:
        pre.global_batch(0)  # primes the prefetch window
        for fut in list(pre._futures.values()):
            fut.result()  # let the workers finish before consuming
        build_threads.clear()
        batch = pre.global_batch(1)  # prefetched: no consumer-thread build
        assert build_threads == [] or all(
            t.startswith("frl-data-prefetch") for t in build_threads
        ), build_threads
        for key, arr in batch.items():
            assert isinstance(arr, jax.Array), key
            assert arr.committed, f"{key} not committed to devices"
            assert arr.sharding == inner.shardings_for(
                {key: np.asarray(jax.device_get(arr))}
            )[key], key
    finally:
        pre.close()


def test_native_load_builds_lock_free_and_racers_park_on_done(monkeypatch):
    """Regression for graft-lint concurrency finding blocking-under-lock
    (data/native.py _load -> _build -> subprocess.run): the module lock
    only claims/publishes — the slow build runs LOCK-FREE, so mid-build
    the lock is immediately available and a racing caller parks on
    ``_done`` (returning the published lib) instead of queueing behind a
    120 s compile."""
    import threading

    monkeypatch.setattr(nv, "_lib", None)
    monkeypatch.setattr(nv, "_tried", False)
    monkeypatch.setattr(nv, "_done", threading.Event())

    in_build = threading.Event()
    release = threading.Event()
    sentinel = object()  # stands in for the CDLL

    def fake_uncached():
        assert nv._lock.acquire(blocking=False), (
            "_load holds native._lock across the build again"
        )
        nv._lock.release()
        in_build.set()
        assert release.wait(5)
        return sentinel

    monkeypatch.setattr(nv, "_load_uncached", fake_uncached)

    got = {}
    t1 = threading.Thread(target=lambda: got.__setitem__("a", nv._load()))
    t1.start()
    assert in_build.wait(5)
    t2 = threading.Thread(target=lambda: got.__setitem__("b", nv._load()))
    t2.start()
    t2.join(0.2)
    assert t2.is_alive(), "racer should park on _done, not claim a build"
    release.set()
    t1.join(5)
    t2.join(5)
    assert got["a"] is sentinel and got["b"] is sentinel


def test_native_module_carries_no_concurrency_findings():
    """The static side of the same regression: the concurrency pass on
    data/native.py stays empty (no blocking-under-lock on the build
    path, no unguarded writes to the _lib/_tried publication state)."""
    from frl_distributed_ml_scaffold_tpu.analysis.concurrency import (
        lint_concurrency_paths,
    )

    findings = lint_concurrency_paths([nv.__file__])
    assert findings == [], [f.message for f in findings]
