"""MPMD pipeline parallelism (ISSUE 14): per-stage programs + host-side
1F1B driver (parallel/mpmd_pipeline.py) must (i) be loss/token-parity
with the plain stack, pure DP, and the SPMD pipeline at equal
(stages, microbatches), (ii) hold only min(S, M) in-flight microbatch
activations (the 1F1B memory model, pinned against the driver's
measured counters), (iii) move inter-stage data ONLY as explicit
transfers (census-pinned in test_graft_lint.py), and (iv) surface
per-stage telemetry + watchdog beats from the driver loop."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_apply, jit_init

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    PrecisionConfig,
)
from frl_distributed_ml_scaffold_tpu.models.gpt import (
    GPT,
    mpmd_merge_params,
    mpmd_stage_params,
    unstack_pipeline_params,
)
from frl_distributed_ml_scaffold_tpu.parallel.mpmd_pipeline import (
    bubble_fraction,
    peak_live_activations,
    stage_peak_live,
)
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

FP32 = get_policy(PrecisionConfig(policy="fp32"))

TINY = dict(
    vocab_size=128, num_layers=4, num_heads=2, hidden_dim=32, seq_len=16,
    dropout=0.0,
)

GPT_TINY_OVERRIDES = [
    "model.vocab_size=128",
    "model.num_layers=4",
    "model.num_heads=2",
    "model.hidden_dim=32",
    "model.seq_len=32",
    "data.vocab_size=128",
    "data.seq_len=32",
    "data.global_batch_size=16",
    "trainer.grad_accum=1",
    "optimizer.warmup_steps=0",
    "precision.policy=fp32",
    "trainer.log_every=1000",
]

MPMD = [
    "model.pipeline_stages=2",
    "model.pipeline_microbatches=4",
    "model.pipeline_impl=mpmd",
    "mesh.pipe=2",
    "mesh.data=4",
]


def make_trainer(tmp_path, overrides):
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        GPT_TINY_OVERRIDES + [f"workdir={tmp_path}"] + overrides,
    )
    return Trainer(cfg)


def run_steps(trainer, state, steps=4):
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    return state, metrics


def max_diff(a, b):
    return max(
        jax.tree.leaves(
            jax.tree.map(
                lambda x, y: float(
                    np.max(np.abs(np.asarray(x) - np.asarray(y)))
                ),
                a,
                b,
            )
        )
    )


# ------------------------------------------------------- analytic model


@pytest.mark.fast
def test_bubble_and_peak_live_model():
    """The analytic schedule model (satellite pin): GPipe and 1F1B share
    the (S-1)/(M+S-1) bubble fraction; 1F1B's win is peak live
    activations — min(S, M), == S and < M whenever M > S, vs GPipe's M."""
    for s, m in [(2, 4), (4, 8), (4, 4), (2, 2), (3, 12)]:
        assert bubble_fraction("1f1b", s, m) == pytest.approx(
            (s - 1) / (m + s - 1)
        )
        assert bubble_fraction("gpipe", s, m) == bubble_fraction("1f1b", s, m)
        assert peak_live_activations("gpipe", s, m) == m
        assert peak_live_activations("1f1b", s, m) == min(s, m)
        if m > s:
            assert peak_live_activations("1f1b", s, m) == s
            assert peak_live_activations("1f1b", s, m) < m
        # Per-stage profile: stage j warms up S-1-j forwards then holds
        # one in flight — monotone down the pipe.
        assert [stage_peak_live(j, s, m) for j in range(s)] == [
            min(s - j, m) for j in range(s)
        ]
    with pytest.raises(KeyError, match="schedule"):
        bubble_fraction("interleaved", 2, 4)


@pytest.mark.fast
def test_stage_params_roundtrip_and_unstack():
    """mpmd_stage_params slices the plain stack losslessly (stage 0 owns
    wte/wpe, the last stage ln_f) and both mpmd_merge_params and
    unstack_pipeline_params invert it exactly."""
    cfg = GPTConfig(**TINY)
    tokens = jax.random.randint(jax.random.key(0), (4, 16), 0, 128)
    params = jit_init(GPT(cfg, FP32), tokens, train=False)["params"]
    staged = mpmd_stage_params(cfg, params, 2)
    assert set(staged) == {"stage_0", "stage_1"}
    assert "wte" in staged["stage_0"] and "wpe" in staged["stage_0"]
    assert "ln_f" in staged["stage_1"] and "wte" not in staged["stage_1"]
    for j in range(2):
        lead = jax.tree.leaves(staged[f"stage_{j}"]["blocks"])[0].shape[0]
        assert lead == 2  # L/S
    assert max_diff(params, mpmd_merge_params(cfg, staged)) == 0.0
    assert max_diff(params, unstack_pipeline_params(cfg, staged)) == 0.0
    with pytest.raises(ValueError, match="PLAIN"):
        mpmd_stage_params(cfg, staged, 2)


# ------------------------------------------------------------- parity


def test_mpmd_forward_and_eval_match_plain(tmp_path):
    """The per-stage forward chain + tied head == the plain GPT apply,
    and the runner's eval step reproduces the plain CE exactly."""
    import optax

    trainer = make_trainer(tmp_path, MPMD)
    cfg = trainer.cfg
    plain = GPT(
        dataclasses.replace(cfg.model, pipeline_stages=1), trainer.policy
    )
    batch = trainer.pipeline.global_batch(0)
    tokens = jnp.asarray(batch["tokens"])
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    params = jit_init(plain, inputs, train=False)["params"]
    logits_plain = jit_apply(plain, train=False)({"params": params}, inputs)
    ce_plain = float(
        optax.softmax_cross_entropy_with_integer_labels(
            np.asarray(logits_plain, np.float32), np.asarray(targets)
        ).mean()
    )
    mp_params = trainer._mpmd.place_plain_params(jax.device_get(params))
    logits_mp = trainer._mpmd.apply_logits(mp_params, inputs)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(logits_mp)),
        np.asarray(jax.device_get(logits_plain)),
        atol=2e-5, rtol=1e-5,
    )
    state = trainer._mpmd.init_state().replace(params=mp_params)
    ev = trainer.eval_step(state, batch)
    assert float(ev["loss"]) == pytest.approx(ce_plain, abs=2e-5)


def test_mpmd_e2e_matches_dp(tmp_path):
    """MPMD PP=2 x DP=4 training == pure DP=8 training, step for step —
    through the 1F1B driver, explicit transfers, the tied-embedding
    gradient reduction, and the host-coordinated global grad clip (the
    recipe's grad_clip_norm=1.0 stays ON)."""
    dp = make_trainer(tmp_path / "dp", ["mesh.data=8"])
    mp = make_trainer(tmp_path / "mp", MPMD)
    dp_state = dp.init_state()
    plain = jax.device_get(dp_state.params)
    mp_state = mp.init_state().replace(
        params=mp._mpmd.place_plain_params(plain)
    )
    dp_state, dm = run_steps(dp, dp_state)
    mp_state, mm = run_steps(mp, mp_state)
    assert float(mm["loss"]) == pytest.approx(float(dm["loss"]), abs=1e-5)
    assert float(mm["grad_norm"]) == pytest.approx(
        float(dm["grad_norm"]), abs=1e-4
    )
    merged = mpmd_merge_params(mp.cfg.model, jax.device_get(mp_state.params))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-4),
        jax.device_get(dp_state.params),
        merged,
    )


def test_mpmd_matches_spmd_pipeline(tmp_path):
    """The acceptance pin: the MPMD backend is loss/param-parity with the
    SPMD stage-vmap pipeline at equal (pipeline_stages,
    pipeline_microbatches) on the same pipe-mesh grid."""
    spmd = make_trainer(
        tmp_path / "spmd",
        ["model.pipeline_stages=2", "model.pipeline_microbatches=4",
         "mesh.pipe=2", "mesh.data=4"],
    )
    mp = make_trainer(tmp_path / "mpmd", MPMD)
    spmd_state = spmd.init_state()
    # The SPMD init is stage-stacked; route both backends through ONE
    # plain tree so they start identical.
    plain = unstack_pipeline_params(
        spmd.cfg.model, jax.device_get(spmd_state.params)
    )
    mp_state = mp.init_state().replace(
        params=mp._mpmd.place_plain_params(plain)
    )
    spmd_state, sm = run_steps(spmd, spmd_state)
    mp_state, mm = run_steps(mp, mp_state)
    assert float(mm["loss"]) == pytest.approx(float(sm["loss"]), abs=2e-5)
    merged = mpmd_merge_params(mp.cfg.model, jax.device_get(mp_state.params))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-4),
        unstack_pipeline_params(
            spmd.cfg.model, jax.device_get(spmd_state.params)
        ),
        merged,
    )


def test_mpmd_grad_accum_and_remat_match_dp(tmp_path):
    """Grad accumulation folds into the 1F1B run as extra microbatches
    and trainer.remat checkpoints the stage recompute — both must stay
    numerics-identical to the DP reference with the same knobs."""
    dp = make_trainer(
        tmp_path / "dp",
        ["mesh.data=8", "trainer.grad_accum=2", "trainer.remat=full"],
    )
    mp = make_trainer(
        tmp_path / "mp",
        ["model.pipeline_stages=2", "model.pipeline_microbatches=2",
         "model.pipeline_impl=mpmd", "mesh.pipe=2", "mesh.data=4",
         "trainer.grad_accum=2", "trainer.remat=full"],
    )
    assert mp._mpmd.total_micro == 4  # 2 microbatches x 2 accum chunks
    dp_state = dp.init_state()
    plain = jax.device_get(dp_state.params)
    mp_state = mp.init_state().replace(
        params=mp._mpmd.place_plain_params(plain)
    )
    dp_state, _ = run_steps(dp, dp_state, steps=3)
    mp_state, _ = run_steps(mp, mp_state, steps=3)
    merged = mpmd_merge_params(mp.cfg.model, jax.device_get(mp_state.params))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-4),
        jax.device_get(dp_state.params),
        merged,
    )


def test_mpmd_composes_with_overlap_schedules(tmp_path):
    """The PR 13 declarations lower PER STAGE PROGRAM: blockwise fsdp
    gathers and collective-matmul TP rings inside a stage must match
    their GSPMD twins exactly — and the stage programs must actually
    carry the declared collectives (census pin)."""
    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        collective_census,
    )

    # fsdp blockwise gathers inside the stage scan body.
    fs = ["model.pipeline_stages=2", "model.pipeline_microbatches=2",
          "model.pipeline_impl=mpmd", "mesh.pipe=2", "mesh.fsdp=4",
          "mesh.data=1", "parallel.param_sharding=fsdp",
          "parallel.fsdp_min_size=16"]
    ref = make_trainer(tmp_path / "fs_gspmd", fs)
    ovl = make_trainer(tmp_path / "fs_ovl", fs + ["parallel.fsdp_overlap=true"])
    ref_state = ref.init_state()
    plain = mpmd_merge_params(
        ref.cfg.model, jax.device_get(ref_state.params)
    )
    ovl_state = ovl.init_state().replace(
        params=ovl._mpmd.place_plain_params(plain)
    )
    ref_state, _ = run_steps(ref, ref_state, steps=2)
    ovl_state, _ = run_steps(ovl, ovl_state, steps=2)
    assert max_diff(
        jax.device_get(ref_state.params), jax.device_get(ovl_state.params)
    ) < 5e-4
    arts = ovl._mpmd.lint_artifacts()
    for art in arts:
        prims = {
            r.primitive
            for r in collective_census(art["fwd_bwd_jaxpr"])
            if "fsdp" in r.axes
        }
        assert "all_gather" in prims, (art["stage"], prims)

    # TP rings inside the stage blocks.
    tp = ["model.pipeline_stages=2", "model.pipeline_microbatches=2",
          "model.pipeline_impl=mpmd", "mesh.pipe=2", "mesh.data=2",
          "mesh.model=2"]
    tref = make_trainer(tmp_path / "tp_gspmd", tp)
    tovl = make_trainer(tmp_path / "tp_ovl", tp + ["parallel.tp_overlap=true"])
    tref_state = tref.init_state()
    tplain = mpmd_merge_params(
        tref.cfg.model, jax.device_get(tref_state.params)
    )
    tovl_state = tovl.init_state().replace(
        params=tovl._mpmd.place_plain_params(tplain)
    )
    tref_state, _ = run_steps(tref, tref_state, steps=2)
    tovl_state, _ = run_steps(tovl, tovl_state, steps=2)
    assert max_diff(
        jax.device_get(tref_state.params), jax.device_get(tovl_state.params)
    ) < 5e-4
    for art in tovl._mpmd.lint_artifacts():
        fwd_census = collective_census(art["fwd_jaxpr"])
        assert any(
            r.primitive == "ppermute" and "model" in r.axes
            for r in fwd_census
        ), art["stage"]
        # The rings replace the monolithic gathers on the model axis.
        assert not any(
            r.primitive == "all_gather" and "model" in r.axes
            for r in fwd_census
        ), art["stage"]


# ------------------------------------------- schedule memory + transfers


def test_mpmd_peak_live_and_transfer_accounting(tmp_path):
    """THE 1F1B memory pin: the driver's measured in-flight activation
    counters equal the analytic per-stage model (min(S-j, M); max over
    stages min(S, M) == S < M = GPipe), and the explicit boundary
    transfers account for exactly the bytes the schedule moves."""
    trainer = make_trainer(tmp_path, MPMD)
    runner = trainer._mpmd
    s, m = runner.num_stages, runner.total_micro
    assert m > s  # the regime where 1F1B beats GPipe's memory
    state = trainer.init_state()
    batch = trainer.pipeline.global_batch(0)
    state, _ = trainer.train_step(state, batch)
    # Stage j saves boundary inputs for its pending backwards; the last
    # stage runs fused fwd+bwd and holds none.
    assert runner.last_peak_live[:-1] == [
        stage_peak_live(j, s, m) for j in range(s - 1)
    ]
    assert max(runner.last_peak_live) == peak_live_activations("1f1b", s, m)
    assert max(runner.last_peak_live) == s
    assert max(runner.last_peak_live) < peak_live_activations("gpipe", s, m)

    mcfg = trainer.cfg.model
    mb = runner.micro_batch
    t, d, v = mcfg.seq_len, mcfg.hidden_dim, mcfg.vocab_size
    acts = (s - 1) * m * mb * t * d * 4  # fwd activations, fp32
    grads = (s - 1) * m * mb * t * d * 4  # bwd cotangents
    toks = m * mb * t * 4 * 2  # stage-0 inputs + last-stage targets
    emb = v * d * 4 * 2  # tied-embedding mirror out + head grad back
    assert runner.last_boundary_bytes == acts + grads + toks + emb


def test_mpmd_telemetry_gauges_and_watchdog_beats(tmp_path):
    """Satellite 4 wiring, unit level: per-stage idle gauges + the
    analytic bubble gauge + the boundary-transfer counter land in the
    attached registry, and the 1F1B driver beats the watchdog from
    INSIDE its dispatch loop (so a wedged transfer fires the stall
    dump)."""
    from frl_distributed_ml_scaffold_tpu.telemetry import MetricsRegistry

    class BeatStub:
        beats = 0

        def beat(self):
            self.beats += 1

    trainer = make_trainer(tmp_path, MPMD)
    runner = trainer._mpmd
    reg = MetricsRegistry()
    stub = BeatStub()
    runner.attach_telemetry(registry=reg, watchdog=stub)
    state = trainer.init_state()
    state, _ = trainer.train_step(state, trainer.pipeline.global_batch(0))
    snap = reg.snapshot()
    s, m = runner.num_stages, runner.total_micro
    assert snap["pipeline_bubble_fraction"] == pytest.approx(
        bubble_fraction("1f1b", s, m)
    )
    for j in range(s):
        assert f"pipeline_stage{j}_idle_s" in snap
        assert snap[f"pipeline_stage{j}_idle_s"] >= 0.0
    assert (
        snap["pipeline_boundary_transfer_bytes_total"]
        == runner.last_boundary_bytes
    )
    # One beat per dispatched stage op + one per stage update: stages
    # 0..S-2 run 2M ops (F+B), the last stage M fused ops.
    assert stub.beats == (s - 1) * 2 * m + m + s


@pytest.mark.obs
def test_mpmd_fit_exports_stage_telemetry(tmp_path):
    """End-to-end: a 2-step mpmd fit() exports the stage gauges through
    the standard telemetry.jsonl, and tools/telemetry_report.py renders
    them (the satellite's visibility requirement)."""
    trainer = make_trainer(
        tmp_path, MPMD + ["trainer.log_every=1", "trainer.total_steps=2"]
    )
    trainer.fit(num_steps=2)
    run_dir = os.path.join(str(tmp_path), trainer.cfg.name)
    telem_path = os.path.join(run_dir, "telemetry.jsonl")
    assert os.path.exists(telem_path)
    import tools.telemetry_report as treport

    rep = treport.report(treport.load(telem_path))
    scalars = rep["scalars"]
    assert "pipeline_bubble_fraction" in scalars
    assert scalars["pipeline_bubble_fraction"] == pytest.approx(
        bubble_fraction("1f1b", 2, 4)
    )
    for j in range(2):
        assert f"pipeline_stage{j}_idle_s" in scalars
    assert scalars["pipeline_boundary_transfer_bytes_total"] > 0


# --------------------------------------------------- generate + refusals


def test_mpmd_params_generate_like_plain(tmp_path):
    """Decode runs on the plain stack: generation._plain_stack restacks
    MPMD per-stage params automatically (unstack_pipeline_params'
    stage_0 branch), so an mpmd-trained checkpoint generates without
    config surgery."""
    from frl_distributed_ml_scaffold_tpu.models.generation import generate

    cfg = GPTConfig(**TINY)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    plain = GPT(cfg, FP32)
    params = jit_init(plain, tokens, train=False)["params"]
    pp_cfg = dataclasses.replace(
        cfg, pipeline_stages=2, pipeline_impl="mpmd"
    )
    staged = mpmd_stage_params(cfg, params, 2)
    prompt = np.asarray(tokens[:, :5])
    out_plain = generate(plain, params, prompt, max_new_tokens=4)
    out_mpmd = generate(GPT(pp_cfg, FP32), staged, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(
        np.asarray(out_plain), np.asarray(out_mpmd)
    )


@pytest.mark.fast
def test_mpmd_refusals(tmp_path):
    """Config combinations the MPMD backend cannot honor must refuse at
    Trainer construction with actionable messages, not mis-train."""
    with pytest.raises(ValueError, match="MoE"):
        make_trainer(
            tmp_path / "moe",
            MPMD + ["model.moe.num_experts=4", "mesh.data=1",
                    "mesh.expert=4"],
        )
    with pytest.raises(ValueError, match="circular"):
        make_trainer(
            tmp_path / "circ", MPMD + ["model.pipeline_circular_repeat=2"]
        )
    with pytest.raises(ValueError, match="pipe"):
        make_trainer(
            tmp_path / "mesh",
            ["model.pipeline_stages=4", "model.pipeline_impl=mpmd",
             "model.pipeline_microbatches=4", "mesh.pipe=2", "mesh.data=4"],
        )
    with pytest.raises(KeyError, match="pipeline_impl"):
        make_trainer(
            tmp_path / "impl",
            ["model.pipeline_stages=2", "model.pipeline_impl=banana",
             "mesh.pipe=2", "mesh.data=4"],
        )
