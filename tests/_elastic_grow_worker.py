"""Per-process supervisor half of the grow-back (re-admission) test.

Launched (once per simulated host) by tests/test_elastic_multiprocess.py::
test_multiprocess_grow_back_after_shrink. Host 0 (the COORDINATOR) dies
(fault + zero restart budget), host 1 shrinks to a 1-process world and
keeps training — then host 0 COMES BACK (the repaired-host scenario the
round-4 supervisor left to operator action): its script waits until the
shrunken world has visibly progressed (a checkpoint ≥ GATE_STEP), then
starts a fresh supervisor with the ORIGINAL topology. Host 1's grow
watcher must notice the revived heartbeat, preempt its child (SIGTERM →
checkpoint → clean exit), and re-form the 2-process world; both finish
the run together with no step lost or duplicated.

Env contract: FRL_TPU_COORDINATOR, FRL_TPU_NUM_PROCESSES,
FRL_TPU_PROCESS_ID, FRL_TEST_WORKDIR; FRL_FAULT_AT_STEP on host 0 only;
FRL_STEP_DELAY_S stretches step wall-clock so the revival lands mid-run;
FRL_TPU_INIT_TIMEOUT_S bounds rendezvous waits; FRL_TPU_HOST_ADDRESS
pins published endpoints to loopback.
"""

import os
import sys
import time

#: The shrunken world must have saved a checkpoint at/after this step
#: before host 0 revives (proves the 1-process continuation made real
#: progress first — and leaves plenty of run for the grown world).
GATE_STEP = 15


def _launch(extra):
    from frl_distributed_ml_scaffold_tpu.launcher.launch import main as launch_main

    return launch_main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--sim-devices", "2",
            "--coordinator", os.environ["FRL_TPU_COORDINATOR"],
            "--num-processes", os.environ["FRL_TPU_NUM_PROCESSES"],
            "--process-id", os.environ["FRL_TPU_PROCESS_ID"],
            "--elastic",
            "trainer.total_steps=120",
            "trainer.log_every=10",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "data.prefetch=0",
            "model.hidden_sizes=32",
            "precision.policy=fp32",
            "checkpoint.save_every=5",
            "checkpoint.async_save=false",
            "elastic.backoff_s=0.1",
            "elastic.shrink_after=2",
            "elastic.peer_timeout_s=6",
            "workdir=" + os.environ["FRL_TEST_WORKDIR"],
        ]
        + extra
    )


def main() -> int:
    pid = os.environ["FRL_TPU_PROCESS_ID"]
    if pid != "0":
        return _launch([])

    # Host 0, act 1: the doomed coordinator (fault at step 9, no budget).
    rc = _launch(["elastic.max_restarts=0"])
    assert rc == 43, f"expected the injected fault's rc, got {rc}"

    # Act 2: wait for the survivor to shrink and progress past the gate...
    ckpt_dir = os.path.join(
        os.environ["FRL_TEST_WORKDIR"], "mnist_mlp", "ckpt"
    )
    deadline = time.monotonic() + 150
    while time.monotonic() < deadline:
        steps = [
            int(d) for d in (
                os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []
            ) if d.isdigit()
        ]
        if steps and max(steps) >= GATE_STEP:
            break
        time.sleep(0.5)
    else:
        print("grow worker: survivor never progressed past the gate")
        return 7

    # ...then come back from repair: fresh supervisor, ORIGINAL topology.
    # (The fault marker already exists, so the fault hook stays disarmed.)
    return _launch(["elastic.max_restarts=8"])


if __name__ == "__main__":
    sys.exit(main())
