"""graft-lint (frl_distributed_ml_scaffold_tpu/analysis/): each analyzer
pass on small synthetic programs — one positive and one negative case per
pass — plus the mutation gates the ISSUE names: re-enable plain GSPMD TP
and the exposed-collective detector fires; drop a donation and the audit
fires; oversize a decode intermediate and the materialization budget
fires.  The CLI itself runs over every registered recipe as the `lint`
tier's integration gate."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.analysis import pins
from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    census_diff,
    collective_census,
    hlo_collective_census,
)
from frl_distributed_ml_scaffold_tpu.analysis.donation import (
    args_info_donations,
    compiled_aliases,
    lowered_donations,
)
from frl_distributed_ml_scaffold_tpu.analysis.hygiene import lint_source
from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
    max_materialized_bytes,
    oversized_intermediates,
)
from frl_distributed_ml_scaffold_tpu.analysis.reshard import (
    exposed_collectives,
    monolithic_gathers,
)
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    build_mesh,
    mesh_context,
    shard_map_compat,
)
from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig

pytestmark = pytest.mark.lint


# ------------------------------------------------------ collective census


@pytest.mark.fast
def test_census_counts_collectives_with_axes_and_scan_trips():
    """Positive: a psum + ppermute inside a 3-trip scan is recorded with
    its axis name and a trip_count of 3; negative: a collective-free
    program yields an empty census."""
    env = build_mesh(MeshConfig(data=8))

    def inner(x):
        def body(c, _):
            c = jax.lax.psum(c, "data")
            c = jax.lax.ppermute(
                c, "data", [(i, (i + 1) % 8) for i in range(8)]
            )
            return c, ()

        y, _ = jax.lax.scan(body, x, None, length=3)
        return y

    f = shard_map_compat(
        inner, mesh=env.mesh, in_specs=P("data"), out_specs=P("data")
    )
    with mesh_context(env):
        jaxpr = jax.make_jaxpr(f)(jnp.ones((8, 4)))
    census = collective_census(jaxpr)
    by_prim = {r.primitive: r for r in census}
    assert set(by_prim) == {"psum", "ppermute"}, census
    assert by_prim["psum"].axes == ("data",)
    assert by_prim["psum"].trip_count == 3
    assert by_prim["ppermute"].trip_count == 3
    # bytes: per-shard [1, 4] fp32 = 16 bytes per call (8-way split of 8).
    assert by_prim["psum"].bytes_per_call == 1 * 4 * 4
    assert by_prim["psum"].total_bytes == 3 * 1 * 4 * 4

    empty = collective_census(jax.make_jaxpr(lambda x: x * 2)(jnp.ones(3)))
    assert empty == []


@pytest.mark.fast
def test_census_diff_reports_added_and_removed():
    env = build_mesh(MeshConfig(data=8))

    def with_psum(x):
        return jax.lax.psum(x, "data")

    def with_two(x):
        return jax.lax.psum(jax.lax.psum(x, "data"), "data")

    def mk(fn):
        f = shard_map_compat(
            fn, mesh=env.mesh, in_specs=P("data"), out_specs=P()
        )
        with mesh_context(env):
            return collective_census(jax.make_jaxpr(f)(jnp.ones((8,))))

    one, two = mk(with_psum), mk(with_two)
    d = census_diff(one, two)
    assert len(d["added"]) == 1 and d["added"][0]["count"] == 1
    assert d["removed"] == []
    d_rev = census_diff(two, one)
    assert len(d_rev["removed"]) == 1 and d_rev["added"] == []
    assert census_diff(one, one) == {"added": [], "removed": []}


@pytest.mark.fast
def test_census_diff_sees_scan_trip_count_drift():
    """Same eqn, longer scan (12x the wire bytes) must register as drift
    — trip_count is part of the record identity."""
    env = build_mesh(MeshConfig(data=8))

    def mk(length):
        def inner(x):
            def body(c, _):
                return jax.lax.psum(c, "data"), ()

            return jax.lax.scan(body, x, None, length=length)[0]

        f = shard_map_compat(
            inner, mesh=env.mesh, in_specs=P("data"), out_specs=P("data")
        )
        with mesh_context(env):
            return collective_census(jax.make_jaxpr(f)(jnp.ones((8, 4))))

    d = census_diff(mk(2), mk(24))
    assert d["added"] and d["removed"], d
    assert d["added"][0]["trip_count"] == 24
    assert d["removed"][0]["trip_count"] == 2


# --------------------------------------- exposed collectives / reshard


def _tp_matmul_compiled(constrain_out: bool):
    """A Megatron-ish sharded matmul pair; GSPMD must insert an all-reduce
    (row-split contraction) when the output is pinned replicated-on-model."""
    env = build_mesh(MeshConfig(data=2, model=4))
    mesh = env.mesh
    x = jax.ShapeDtypeStruct(
        (16, 32), jnp.float32, sharding=NamedSharding(mesh, P("data", None))
    )
    w1 = jax.ShapeDtypeStruct(
        (32, 32), jnp.float32, sharding=NamedSharding(mesh, P(None, "model"))
    )
    w2 = jax.ShapeDtypeStruct(
        (32, 32), jnp.float32, sharding=NamedSharding(mesh, P("model", None))
    )

    def f(x, w1, w2):
        y = (x @ w1) @ w2
        if constrain_out:
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh, P("data", None))
            )
        return y

    with mesh_context(env):
        return jax.jit(f).lower(x, w1, w2).compile()


@pytest.mark.fast
def test_mutation_gspmd_tp_trips_exposed_collective_detector():
    """THE mutation gate: on plain GSPMD TP the partitioner inserts an
    all-reduce for the row-split contraction — the detector must fire on
    the compiled HLO (it cannot fire on the jaxpr: GSPMD collectives
    don't exist there, which is why the detector reads HLO)."""
    compiled = _tp_matmul_compiled(constrain_out=True)
    assert collective_census(
        jax.make_jaxpr(lambda x: x + 1)(jnp.ones(3))
    ) == []  # jaxpr level blind to GSPMD, as documented
    hits = exposed_collectives(
        compiled.as_text(), ops=("all-reduce", "all-gather")
    )
    assert hits, "GSPMD TP produced no exposed collective?!"
    with pytest.raises(AssertionError, match="all-reduce"):
        pins.assert_no_collective_hlo(compiled, "all-reduce")


@pytest.mark.fast
def test_negative_unsharded_program_has_no_exposed_collectives():
    compiled = jax.jit(lambda x: x @ x).lower(jnp.ones((8, 8))).compile()
    assert exposed_collectives(compiled.as_text()) == []
    pins.assert_no_collective_hlo(compiled, "all-reduce")
    pins.assert_no_collective_hlo(compiled, "all-gather")


@pytest.mark.fast
def test_monolithic_gather_detector_on_synthetic_gathers():
    """Positive/negative for the jaxpr-level reshard pass: a gather of an
    allowed per-block slice passes; a gather of a full stacked tensor is
    flagged."""
    env = build_mesh(MeshConfig(fsdp=8))

    def gather(x):
        return jax.lax.all_gather(x, "fsdp", tiled=True)

    f = shard_map_compat(
        gather, mesh=env.mesh, in_specs=P("fsdp"), out_specs=P()
    )
    with mesh_context(env):
        jaxpr = jax.make_jaxpr(f)(jnp.ones((8, 16)))
    assert monolithic_gathers(jaxpr, allowed_shapes={(8, 16)}) == []
    bad = monolithic_gathers(jaxpr, allowed_shapes={(2, 16)})
    assert bad == [(8, 16)]
    pins.assert_all_gather_outputs_within(jaxpr, {(8, 16)})
    with pytest.raises(AssertionError, match="monolithic"):
        pins.assert_all_gather_outputs_within(jaxpr, {(2, 16)})


@pytest.mark.fast
def test_reshard_pin_matches_shape_signatures_in_hlo():
    """assert_reshard_free flags only collectives carrying the pinned
    signatures (the serving handoff pin's contract)."""
    compiled = _tp_matmul_compiled(constrain_out=True)
    txt = compiled.as_text()
    hits = hlo_collective_census(txt)
    assert hits
    shapes = {tuple(s) for r in hits for s in r.shapes}
    some_shape = next(iter(shapes))
    with pytest.raises(AssertionError, match="reshard"):
        pins.assert_reshard_free(
            txt, [some_shape],
            ops=("all-reduce", "all-gather", "all-to-all"),
        )
    # A signature that matches nothing passes.
    pins.assert_reshard_free(txt, [(99, 99, 99)])


# ------------------------------------------------------- materialization


@pytest.mark.fast
def test_materialization_budget_positive_and_negative():
    def f(x):
        big = jnp.einsum("i,j->ij", x, x)  # [256, 256] fp32 = 256 KiB
        return big.sum()

    jaxpr = jax.make_jaxpr(f)(jnp.ones((256,)))
    assert max_materialized_bytes(jaxpr) == 256 * 256 * 4
    assert oversized_intermediates(jaxpr, 300 * 1024) == []
    over = oversized_intermediates(jaxpr, 100 * 1024)
    assert [tuple(i.shape) for i in over] == [(256, 256)]
    pins.assert_max_materialized_bytes(jaxpr, 300 * 1024)
    with pytest.raises(AssertionError, match="budget"):
        pins.assert_max_materialized_bytes(jaxpr, 100 * 1024)


@pytest.mark.fast
def test_mutation_oversized_decode_intermediate_is_caught(gpt_tiny):
    """THE decode mutation gate: the bucketed decode step passes the
    no-full-seq_len pin; the legacy full-context cache (the 'oversized
    intermediate' mutation — cache_len=seq_len) trips the same analyzer."""
    model, params = gpt_tiny
    seq_len = model.config.seq_len

    def step_jaxpr(cache_len):
        m = model.clone(cache_len=cache_len)
        tokens = jnp.zeros((2, 1), jnp.int32)
        _, vo = jax.eval_shape(
            lambda p, t: m.apply(
                {"params": p}, t, decode=True, mutable=["cache"]
            ),
            params, tokens,
        )
        return jax.make_jaxpr(
            lambda p, c, t: m.apply(
                {"params": p, "cache": c}, t, decode=True,
                mutable=["cache"],
            )
        )(params, vo["cache"], tokens)

    pins.assert_no_dim_materialized(step_jaxpr(16), seq_len)
    with pytest.raises(AssertionError, match=str(seq_len)):
        pins.assert_no_dim_materialized(step_jaxpr(seq_len), seq_len)


@pytest.fixture(scope="module")
def gpt_tiny():
    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=2, hidden_dim=32,
            seq_len=96, dropout=0.0,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0)},
            jnp.zeros((2, 4), jnp.int32),
            train=False,
        )["params"]
    )
    return model, params


# --------------------------------------------------------------- donation


# -------------------------------------------------- low-precision pins


@pytest.mark.fast
def test_collective_bytes_pin_positive_and_negative():
    """assert_collective_bytes_within sums (dtype-/axis-filtered) wire
    bytes: a budget above the measured traffic passes, below fires with
    the measured total; dtype filtering separates payload from scale
    traffic."""
    env = build_mesh(MeshConfig(data=8))
    perm = [(i, (i + 1) % 8) for i in range(8)]

    def inner(x):
        q = x.astype(jnp.int8)
        s = jnp.max(jnp.abs(x))[None]
        q = jax.lax.ppermute(q, "data", perm)
        s = jax.lax.ppermute(s, "data", perm)
        return q.astype(jnp.float32) * s

    f = shard_map_compat(
        inner, mesh=env.mesh, in_specs=P("data"), out_specs=P("data")
    )
    with mesh_context(env):
        jaxpr = jax.make_jaxpr(f)(jnp.ones((8, 64)))
    # Payload: [1, 64] int8 = 64 bytes; scale: [1] f32 = 4 bytes.
    assert pins.collective_bytes(jaxpr, "ppermute") == 68
    assert pins.collective_bytes(jaxpr, "ppermute", dtypes=("int8",)) == 64
    pins.assert_collective_bytes_within(
        jaxpr, "ppermute", 8, dtypes=("float32",)
    )
    with pytest.raises(AssertionError, match="bytes"):
        pins.assert_collective_bytes_within(
            jaxpr, "ppermute", 32, dtypes=("int8",)
        )


@pytest.mark.fast
def test_mutation_bf16_ring_under_int8_recipe_trips_bytes_pin(monkeypatch):
    """THE low-precision mutation gate (ISSUE 6): strip the quantization
    off the rings while the recipe says low_precision=int8 — the runner's
    per-dtype census check must flag the wide ppermute payloads (and the
    missing int8 traffic) as errors. At HEAD the same recipe lints
    clean (test_lint_train_step_overlap_recipes_enforce_their_pins
    covers the tp_overlap family positive)."""
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_train_step,
    )
    from frl_distributed_ml_scaffold_tpu.parallel import tp_overlap as tpo

    # (The positive — the int8 recipe linting clean at HEAD — rides
    # test_cli_all_recipes_runs_clean_and_emits_json, which lints every
    # registered recipe; no need to pay a second trainer build here.)
    real = tpo.make_tp_hooks

    def sabotaged(cfg, env):
        return dataclasses.replace(real(cfg, env), lowp=None)

    monkeypatch.setattr(tpo, "make_tp_hooks", sabotaged)
    rep = lint_train_step(
        "gpt2_medium_tp_overlap_int8", workdir="/tmp/graft_lint_test"
    )
    codes = {f.code for f in rep.errors()}
    assert "wide-ppermute" in codes and "missing-lowp-rings" in codes, (
        codes, [f.message for f in rep.errors()][:3],
    )


@pytest.mark.fast
def test_mutation_wholesale_cache_dequantize_trips_materialization(gpt_tiny):
    """THE quantized-decode mutation gate: the shipped int8-KV decode
    step passes the no-wide-cache-geometry pin (it dequantizes per
    chunk); a deliberately-broken step that dequantizes the WHOLE cache
    before attending trips the same analyzer."""
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.models.gpt import (
        GPT,
        _masked_dense_attention,
    )
    from frl_distributed_ml_scaffold_tpu.ops.quantization import (
        dequantize,
        quantize,
    )

    model, _ = gpt_tiny
    bucket, h = 16, model.config.num_heads
    hd = model.config.hidden_dim // h
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 1, h, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, bucket, h, hd)), jnp.float32)
    kq, ks = quantize(k, "int8", channel_axes=(0, 1, 2))

    def broken(q, kq, ks):
        # The mutation: wholesale dequantize, then dense-attend.
        kf = dequantize(kq, ks, jnp.float32)  # [B, S, H, hd] fp32
        mask = jnp.ones((2, 1, bucket), bool)
        return _masked_dense_attention(q, kf, kf, mask)

    jaxpr = jax.make_jaxpr(broken)(q, kq, ks)
    with pytest.raises(AssertionError, match="geometry"):
        pins.assert_no_wide_dims_materialized(jaxpr, (bucket, h, hd))

    def broken_transposed(q, kq, ks):
        # Same mutation behind a layout transpose ([B, S, H, hd] ->
        # [B, H, S, hd], the kernel layout): the pin matches the cache
        # geometry as a dim multiset, so reordering can't dodge it.
        kf = dequantize(
            jnp.transpose(kq, (0, 2, 1, 3)),
            jnp.transpose(ks, (0, 2, 1))[..., None],
            jnp.float32,
        )
        return (q[:, 0, :, None, :] * kf).sum()

    jaxpr_t = jax.make_jaxpr(broken_transposed)(
        q, kq, jnp.squeeze(ks, -1) if ks.ndim == 4 else ks
    )
    with pytest.raises(AssertionError, match="geometry"):
        pins.assert_no_wide_dims_materialized(jaxpr_t, (bucket, h, hd))

    # The shipped quantized decode step passes (positive gate, runner-
    # level: same analyzer the CLI arms for serving:decode_step_int8kv).
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_decode_step,
    )

    rep = lint_decode_step(kv_cache_quant="int8")
    assert rep.program == "serving:decode_step_int8kv"
    assert rep.ok, [f.message for f in rep.errors()]


@pytest.mark.fast
def test_paged_decode_step_lint_clean_and_mutations_trip():
    """ISSUE 10's no-cache-clone gates on the block-table serving
    program: the shipped paged decode step passes both teeth (no
    full-seq_len materialization, nothing bigger than one pool leaf —
    the donated in-place update); the two canonical regressions trip —
    (a) clone-per-grow: padding the pool one block wider is a
    bigger-than-pool copy, exactly the bucketed ``_grow_fn`` clone the
    paged engine exists to delete; (b) gather-the-logical-view:
    ``pool[tables]`` reshaped contiguous materializes the full logical
    context the table indirection exists to avoid."""
    from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
        oversized_intermediates,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        _max_pool_leaf_bytes,
        build_paged_decode_step_program,
        lint_paged_decode_step,
    )

    # Positive gates, runner-level: the same analyzers the CLI arms for
    # serving:decode_step_paged[_int8kv].
    for quant in ("none", "int8"):
        rep = lint_paged_decode_step(kv_cache_quant=quant)
        assert rep.ok, [f.message for f in rep.errors()]
        assert rep.meta["pool_leaf_bytes"] > 0

    model, params, cache, tok, jaxpr = build_paged_decode_step_program()
    seq_len = model.config.seq_len
    budget = _max_pool_leaf_bytes(cache)
    pins.assert_no_dim_materialized(jaxpr, seq_len)
    pins.assert_max_materialized_bytes(jaxpr, budget)

    # Mutation (a): clone-per-grow — pad the pool one block wider.
    def clone_per_grow(c):
        kp = c["blocks"]["attn"]["key_pool"]  # [L, N, bs, H, hd]
        pad = [(0, 0)] * kp.ndim
        pad[1] = (0, 1)
        return jnp.pad(kp, pad)

    grow_jaxpr = jax.make_jaxpr(clone_per_grow)(cache)
    assert oversized_intermediates(grow_jaxpr, budget), (
        "a padded-pool clone fits under the pool-leaf budget — the "
        "no-cache-clone pin has no teeth"
    )
    with pytest.raises(AssertionError, match="budget"):
        pins.assert_max_materialized_bytes(grow_jaxpr, budget)

    # Mutation (b): gather the logical cache view out of the pool.
    def gather_logical(c):
        kp = c["blocks"]["attn"]["key_pool"]  # [L, N, bs, H, hd]
        tbl = c["block_tables"]  # [B, M]
        g = jnp.take(kp, tbl, axis=1)  # [L, B, M, bs, H, hd]
        l, _, _, h, hd = kp.shape
        b, m = tbl.shape
        return g.reshape(l, b, m * kp.shape[2], h, hd)  # full context

    gather_jaxpr = jax.make_jaxpr(gather_logical)(cache)
    with pytest.raises(AssertionError, match=str(seq_len)):
        pins.assert_no_dim_materialized(gather_jaxpr, seq_len)


def test_verify_step_lint_clean_and_mutations_trip():
    """ISSUE 11's gates on the speculative verify program: the shipped
    [B, k+1] verify step passes the paged pins at tile width (no
    full-seq_len materialization, nothing bigger than one pool leaf,
    every cache leaf donated on the engine's ONE compiled verify
    program); the canonical regressions trip — (a) scoring the tile
    against a GATHERED logical cache view (the k+1 queries make the
    gather temptation bigger, and it materializes the full context the
    table indirection exists to avoid), and (b) dropping the verify
    program's cache donation (two pools live per verify)."""
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        _max_pool_leaf_bytes,
        build_verify_step_program,
        lint_verify_step,
    )
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        _verify_step,
    )

    # Positive gates: both pool flavors, same analyzers the CLI arms
    # for serving:verify_step_paged.
    for quant in ("none", "int8"):
        rep = lint_verify_step(kv_cache_quant=quant)
        assert rep.ok, [f.message for f in rep.errors()]
        assert rep.meta["verify_positions"] == 3
        assert rep.meta["pool_leaf_bytes"] > 0

    model, params, cache, tile, jaxpr = build_verify_step_program()
    seq_len = model.config.seq_len
    budget = _max_pool_leaf_bytes(cache)
    pins.assert_no_dim_materialized(jaxpr, seq_len)
    pins.assert_max_materialized_bytes(jaxpr, budget)

    # Mutation (a): verify the tile against the gathered logical view —
    # a [B, T, M*bs]-scored step materializes the full context.
    def gathered_scores(c, t):
        kp = c["blocks"]["attn"]["key_pool"]  # [L, N, bs, H, hd]
        tbl = c["block_tables"]  # [B, M]
        g = jnp.take(kp[0], tbl, axis=0)  # [B, M, bs, H, hd]
        b, m = tbl.shape
        logical = g.reshape(b, m * kp.shape[2], -1)  # full context
        q = jnp.zeros((b, t.shape[1], logical.shape[-1]), jnp.float32)
        return jnp.einsum("btd,bsd->bts", q, logical.astype(jnp.float32))

    mut_jaxpr = jax.make_jaxpr(gathered_scores)(cache, tile)
    with pytest.raises(AssertionError, match=str(seq_len)):
        pins.assert_no_dim_materialized(mut_jaxpr, seq_len)

    # Mutation (b): dropped donation on the verify program — the audit
    # fires at the args_info level exactly like the decode programs.
    m = model.clone(kv_block_size=16, kv_pool_blocks=9)

    def fn(p, c, t):
        logits, c = _verify_step(m, p, c, t)
        return jnp.argmax(logits, -1), c

    donated = jax.jit(fn, donate_argnums=(1,)).lower(params, cache, tile)
    dropped = jax.jit(fn).lower(params, cache, tile)
    n_cache = len(jax.tree.leaves(cache))
    pins.assert_donated(donated, min_donated=n_cache)
    with pytest.raises(AssertionError, match="donated"):
        pins.assert_donated(dropped, min_donated=1)
    d_pairs = args_info_donations(dropped)
    assert not any(d for _, d in d_pairs), "dropped donation still marked"


def test_handoff_lint_clean_and_gather_mutation_trips():
    """ISSUE 12's gates on the prefill→decode handoff splice: the
    shipped splice (``generation.splice_pool_blocks`` — the exact
    function the engine jits for both colocated grafts and
    disaggregated handoffs) passes all three teeth (ZERO collectives,
    no full-seq_len materialization, nothing bigger than one pool leaf,
    pool donated), and the canonical regression trips — a GATHER-BASED
    handoff that materializes the logical cache view (``pool[tables]``
    contiguous) and rewrites the pool is exactly the cache copy the
    block-table splice exists to delete."""
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
        oversized_intermediates,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        _max_pool_leaf_bytes,
        build_handoff_program,
        lint_handoff,
    )

    rep = lint_handoff()
    assert rep.ok, [f.message for f in rep.errors()]
    assert rep.meta["collective_census"] == [], "splice grew a collective"
    assert rep.meta["pool_leaf_bytes"] > 0
    # The ledger's table-bytes claim: splice ownership cost is the int32
    # table row, orders of magnitude under the pool.
    assert rep.meta["splice_table_bytes"] * 100 < rep.meta["pool_leaf_bytes"]

    model, pool_cache, slot_cache, blk_ids, jaxpr = build_handoff_program()
    seq_len = model.config.seq_len
    budget = _max_pool_leaf_bytes(pool_cache)
    pins.assert_no_dim_materialized(jaxpr, seq_len)
    pins.assert_max_materialized_bytes(jaxpr, budget)

    # Mutation: the gather-based handoff — materialize the logical view,
    # splice the slot cache into it, scatter the WHOLE pool back.
    def gather_handoff(c, sc):
        kp = c["blocks"]["attn"]["key_pool"]  # [L, N, bs, H, hd]
        tbl = c["block_tables"]  # [B, M]
        g = jnp.take(kp, tbl, axis=1)  # [L, B, M, bs, H, hd]
        l, _, bs, h, hd = kp.shape
        b, m = tbl.shape
        logical = g.reshape(l, b, m * bs, h, hd)  # the full-context copy
        sk = sc["blocks"]["attn"]["cached_key"]  # [L, 1, s_c, H, hd]
        logical = logical.at[:, 0, : sk.shape[2]].set(sk[:, 0])
        return logical

    mut_jaxpr = jax.make_jaxpr(gather_handoff)(pool_cache, slot_cache)
    assert oversized_intermediates(mut_jaxpr, budget), (
        "a gather-based handoff fits under the pool-leaf budget — the "
        "cache-copy pin has no teeth"
    )
    with pytest.raises(AssertionError, match=str(seq_len)):
        pins.assert_no_dim_materialized(mut_jaxpr, seq_len)


def test_reshard_lint_clean_and_naive_mutation_trips(monkeypatch):
    """ISSUE 15's gates on the redistribution executor's same-mesh
    program classes: at HEAD every ``reshard:*`` program passes (every
    per-device intermediate inside the plan's scratch budget, the pure
    axis-move all_gather-free, source donated), and the canonical
    regression — the NAIVE gather-then-scatter executor, which stages
    the full logical array on every device before re-slicing — trips
    the replicated-staging materialization pin on every program (plus
    the gather-on-move pin on the pure all_to_all class)."""
    import frl_distributed_ml_scaffold_tpu.redistribute.executor as rd_exec
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        RESHARD_PROGRAMS,
        build_reshard_program,
        lint_reshard,
        lint_reshard_programs,
    )

    reports = lint_reshard_programs()
    assert {r.program for r in reports} == set(RESHARD_PROGRAMS)
    for rep in reports:
        assert rep.ok, (rep.program, [f.message for f in rep.errors()])
        assert rep.meta["plan"]["bytes_moved"] == (
            rep.meta["plan"]["bytes_lower_bound"]
        ), rep.program
    # The pure-move program really is ONE all_to_all on the wire.
    plan, jaxpr, _ = build_reshard_program("reshard:tp_row_to_col")
    pins.assert_collective_present(jaxpr, "all_to_all")
    pins.assert_no_collective(jaxpr, "all_gather")

    monkeypatch.setattr(rd_exec, "_NAIVE_GATHER_SCATTER", True)
    for name in RESHARD_PROGRAMS:
        rep = lint_reshard(name)
        codes = {f.code for f in rep.errors()}
        assert "replicated-staging" in codes, (name, codes)
        if RESHARD_PROGRAMS[name].get("no_gather"):
            assert "gather-on-move" in codes, (name, codes)


@pytest.mark.fast
def test_mutation_dropped_donation_is_caught():
    """THE donation mutation gate: the same program jitted with and
    without donate_argnums — the audit passes the donated one and fires
    on the dropped one, at both the lowered and args_info levels."""
    s = {"mu": jnp.ones((64, 64)), "nu": jnp.ones((64, 64))}
    g = jnp.ones((64, 64))

    def update(s, g):
        return {"mu": s["mu"] * 0.9 + g, "nu": s["nu"] * 0.99 + g * g}

    donated = jax.jit(update, donate_argnums=(0,)).lower(s, g)
    dropped = jax.jit(update).lower(s, g)

    pins.assert_donated(donated, min_donated=2)
    with pytest.raises(AssertionError, match="donated"):
        pins.assert_donated(dropped, min_donated=1)

    d_pairs = dict(args_info_donations(donated))
    assert all(d for p, d in d_pairs.items() if "mu" in p or "nu" in p)
    assert not any(d for p, d in dict(args_info_donations(dropped)).items())

    # Lowered-marker level agrees.
    assert sum(1 for d in lowered_donations(donated.as_text()) if d.donated) == 2
    assert sum(1 for d in lowered_donations(dropped.as_text()) if d.donated) == 0


@pytest.mark.fast
def test_compiled_alias_table_positive_and_negative():
    """Compiled-executable ground truth: donation shows up in
    input_output_alias; without donation the table is empty."""
    f = lambda x: x + 1.0
    x = jnp.ones((32, 32))
    comp_d = jax.jit(f, donate_argnums=(0,)).lower(x).compile()
    comp_n = jax.jit(f).lower(x).compile()
    aliases = pins.assert_aliased(comp_d, min_aliases=1)
    assert aliases[0]["param"] == 0
    assert compiled_aliases(comp_n) == []
    with pytest.raises(AssertionError, match="alias"):
        pins.assert_aliased(comp_n)


# ---------------------------------------------------------------- hygiene


@pytest.mark.fast
def test_hygiene_flags_host_sync_rng_and_axis_typo():
    src = '''
import random
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

def traced_bad(x):
    noise = random.random()
    y = jnp.sum(x) * noise
    z = float(np.median(y))
    zz = float(jnp.mean(y))
    v = y.item()
    w = lax.psum(y, "modle")
    i = lax.axis_index("daat")
    return jax.device_get(w) + i

def host_ok(batch):
    import numpy as np
    return np.asarray(batch["x"]).mean()
'''
    findings = lint_source(src, "synthetic.py")
    codes = sorted({(f.code, f.severity) for f in findings})
    assert ("python-rng", "error") in codes, codes
    assert ("host-sync", "error") in codes, codes
    assert ("axis-typo", "error") in codes, codes
    # Both positions: psum's arg 1 ("modle") AND axis_index's arg 0
    # ("daat") — the typo detector knows each collective's axis slot.
    typos = {f.context["axis"] for f in findings if f.code == "axis-typo"}
    assert typos == {"modle", "daat"}, typos
    assert ("host-sync-cast", "warning") in codes, codes  # float(np.median)
    assert ("numpy-in-traced", "warning") in codes, codes
    # The host-side function (no jnp/lax in body) is exempt.
    assert not any(
        f.context.get("function") == "host_ok" for f in findings
    ), findings


@pytest.mark.fast
def test_hygiene_clean_traced_source_passes():
    src = '''
import jax
import jax.numpy as jnp
from jax import lax

def traced_ok(x):
    y = jnp.sum(x)
    return lax.psum(y, "model")
'''
    assert lint_source(src, "clean.py") == []


@pytest.mark.fast
@pytest.mark.obs
def test_hygiene_metrics_in_traced_mutation_gate():
    """ISSUE 7 mutation gate: a telemetry mutation inside traced code is
    an ERROR (trace-time freeze or per-step host sync), while the legal
    look-alikes — jnp's functional ``x.at[i].set(v)`` in traced code and
    metric writes on the host side of the jitted call — stay clean."""
    bad = '''
import jax.numpy as jnp

def traced_decode(x, m_tpot, engine, reg):
    y = jnp.sum(x)
    m_tpot.observe(0.001)
    engine.telemetry.counter("decode_steps_total").inc()
    reg.gauge("occupancy").set(0.5)
    return y
'''
    findings = [
        f for f in lint_source(bad, "bad.py") if f.code == "metrics-in-traced"
    ]
    # Every metric statement flagged (chained factory+mutator may each
    # report, so pin the flagged LINES): observe / telemetry chain / set.
    assert {f.context["line"] for f in findings} == {6, 7, 8}, findings
    assert all(f.severity == "error" for f in findings)
    assert {f.context["function"] for f in findings} == {"traced_decode"}
    calls = {f.context["call"] for f in findings}
    assert "m_tpot.observe" in calls and "reg.gauge" in calls, calls

    clean = '''
import jax.numpy as jnp
import numpy as np

def traced_update(cache, idx, v, done):
    out = cache.at[idx].set(v)      # functional update, not a gauge
    done.set()                      # threading.Event.set(): zero args
    counts, edges = jnp.histogram(out, bins=8)   # array op, not a factory
    np.histogram(np.ones(4), bins=2)             # ditto at shape time
    return out * jnp.ones(())

def host_step(engine, fn, x):
    t0 = perf_counter()
    y = fn(x)                       # the jitted call
    engine.m_step.observe(perf_counter() - t0)
    engine.telemetry.counter("steps_total").inc()
    return y
'''
    assert [
        f for f in lint_source(clean, "clean.py")
        if f.code == "metrics-in-traced"
    ] == []


@pytest.mark.fast
def test_hygiene_span_tracing_in_traced_mutation_gate():
    """ISSUE 8 mutation gate: the hygiene ERROR extends to the span API —
    ``.span(...)`` starts and any ``tracing``/``tracer`` attribute chain
    inside traced code are flagged (a span inside a trace freezes at
    trace time or drags a per-step clock read + sync in); the host-side
    loop around the jitted call stays clean."""
    bad = '''
import jax.numpy as jnp

def traced_block(x, engine, tracer):
    with engine.tracing.span("block"):
        y = jnp.sum(x)
    tracer.emit("phase", t0=0.0, dur_s=0.1)
    sp = tracer.begin("p")
    return y
'''
    findings = [
        f for f in lint_source(bad, "bad.py") if f.code == "metrics-in-traced"
    ]
    assert {f.context["line"] for f in findings} == {5, 7, 8}, findings
    assert all(f.severity == "error" for f in findings)

    clean = '''
import jax.numpy as jnp

def traced_fn(x):
    return jnp.sum(x) * 2

def host_loop(tracer, fn, x, trace):
    with tracer.span("dispatch", trace=trace):
        y = fn(x)                      # the jitted call
    return y
'''
    assert [
        f for f in lint_source(clean, "clean.py")
        if f.code == "metrics-in-traced"
    ] == []


@pytest.mark.fast
def test_hygiene_repo_traced_modules_are_clean():
    """The repo's own traced modules carry no hygiene errors (warnings
    allowed: shape-time numpy is legal)."""
    from frl_distributed_ml_scaffold_tpu.analysis.runner import lint_hygiene

    report = lint_hygiene()
    assert report.errors() == [], [f.message for f in report.errors()]


# ------------------------------------------------------------- robustness


@pytest.mark.fast
@pytest.mark.chaos
def test_robustness_flags_swallowed_exceptions_and_unbounded_retry():
    """ISSUE 9 mutation gate: a pass-only wide except is an ERROR (the
    fault vanishes — no log, no counter, no typed resolution) in HOST
    code too, and a while-True retry loop with no backoff call and a
    never-escalating handler is a WARNING."""
    from frl_distributed_ml_scaffold_tpu.analysis.hygiene import (
        lint_robustness_source,
    )

    bad = '''
import os, time

def swallow_everything(path):
    try:
        os.remove(path)
    except Exception:
        pass

def swallow_bare(path):
    try:
        os.remove(path)
    except:
        ...

def swallow_in_tuple(path):
    try:
        os.remove(path)
    except (OSError, Exception):
        pass

def spin_forever(fn):
    while True:
        try:
            return fn()
        except OSError:
            continue

def spin_with_str_join(fn, log):
    while True:
        try:
            return fn()
        except OSError as e:
            log(", ".join([str(e)]))  # join != backoff: still a busy-spin
            continue
'''
    findings = lint_robustness_source(bad, "bad.py")
    swallowed = [f for f in findings if f.code == "swallowed-exception"]
    assert len(swallowed) == 3, findings
    assert all(f.severity == "error" for f in swallowed)
    spins = [f for f in findings if f.code == "unbounded-retry"]
    assert len(spins) == 2 and all(
        f.severity == "warning" for f in spins
    ), findings

    clean = '''
import os, time

def narrow_swallow(path):
    try:
        os.remove(path)
    except OSError:
        pass  # best-effort unlink: narrow type is legal

def logged_swallow(path, logger):
    try:
        os.remove(path)
    except Exception as e:
        logger.warning("cleanup failed: %s", e)

def retry_with_backoff(fn, policy):
    while True:
        try:
            return fn()
        except OSError:
            time.sleep(policy.backoff_s)

def retry_that_escalates(fn):
    while True:
        try:
            return fn()
        except OSError:
            raise
'''
    assert lint_robustness_source(clean, "clean.py") == [], (
        lint_robustness_source(clean, "clean.py")
    )


@pytest.mark.fast
@pytest.mark.chaos
def test_robustness_repo_package_is_clean():
    """The whole package (host orchestration included — engine,
    supervisor, checkpointer) carries no robustness errors: every wide
    except either handles, logs, or narrows."""
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_robustness,
    )

    report = lint_robustness()
    assert report.meta["files"] > 50  # the glob really covers the package
    assert report.errors() == [], [f.message for f in report.errors()]


# ----------------------------------------------------------- concurrency


@pytest.mark.fast
@pytest.mark.chaos
def test_concurrency_unguarded_shared_write_mutation_gate():
    """ISSUE 20 mutation gate (a): an attribute written under
    ``self._lock`` in one method is GUARDED; a read-modify-write of it
    outside that lock, on a class that spawns threads, is an ERROR
    (lost-update race). The properly-locked twin lints clean."""
    from frl_distributed_ml_scaffold_tpu.analysis.concurrency import (
        lint_concurrency_source,
    )

    bad = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def add(self, n):
        with self._lock:
            self._count += n

    def _run(self):
        self._count += 1  # RMW of a guarded attr, no lock held
'''
    findings = lint_concurrency_source(bad, "bad.py")
    races = [f for f in findings if f.code == "unguarded-shared-write"]
    assert len(races) == 1, findings
    assert races[0].severity == "error"
    assert "_count" in races[0].message
    assert "Pool._lock" in races[0].message

    clean = bad.replace(
        "        self._count += 1  # RMW of a guarded attr, no lock held",
        "        with self._lock:\n            self._count += 1",
    )
    assert lint_concurrency_source(clean, "clean.py") == [], (
        lint_concurrency_source(clean, "clean.py")
    )


@pytest.mark.fast
@pytest.mark.chaos
def test_concurrency_lock_order_inversion_mutation_gate():
    """ISSUE 20 mutation gate (b): both inversion shapes are caught —
    a direct nested-``with`` A→B/B→A in one module, and the
    interprocedural form where each class takes its own lock then calls
    into the other (edges discovered through annotated constructor
    params). The one-direction variant lints clean."""
    from frl_distributed_ml_scaffold_tpu.analysis.concurrency import (
        lint_concurrency_source,
    )

    direct = '''
import threading

a = threading.Lock()
b = threading.Lock()

def fwd():
    with a:
        with b:
            pass

def rev():
    with b:
        with a:
            pass
'''
    findings = lint_concurrency_source(direct, "direct.py")
    cycles = [f for f in findings if f.code == "lock-order-inversion"]
    assert len(cycles) == 1, findings
    assert cycles[0].severity == "error"
    assert "direct.py" in cycles[0].message  # edge sites are named

    interproc = '''
import threading

class Right:
    def __init__(self, left: "Left"):
        self._lock = threading.Lock()
        self._left = left

    def bump(self):
        with self._lock:
            pass

    def rev(self):
        with self._lock:
            self._left.poke()

class Left:
    def __init__(self, right: "Right"):
        self._lock = threading.Lock()
        self._right = right

    def poke(self):
        with self._lock:
            pass

    def fwd(self):
        with self._lock:
            self._right.bump()
'''
    findings = lint_concurrency_source(interproc, "interproc.py")
    cycles = [f for f in findings if f.code == "lock-order-inversion"]
    assert len(cycles) == 1, findings
    assert "Left._lock" in cycles[0].message
    assert "Right._lock" in cycles[0].message

    # Drop one direction and the cycle disappears.
    one_way = interproc.replace(
        "    def rev(self):\n        with self._lock:\n"
        "            self._left.poke()\n",
        "",
    )
    assert one_way != interproc
    assert lint_concurrency_source(one_way, "one_way.py") == [], (
        lint_concurrency_source(one_way, "one_way.py")
    )


@pytest.mark.fast
@pytest.mark.chaos
def test_concurrency_blocking_under_lock_mutation_gate():
    """ISSUE 20 mutation gate (c): text-surgery on the REAL
    ``telemetry/metrics.py`` source — inserting ``jax.block_until_ready``
    inside ``Counter.inc``'s locked region — trips ``blocking-under-lock``
    (error), while the committed source stays clean.  Also the
    interprocedural shape: a helper that sleeps, called under a lock."""
    from frl_distributed_ml_scaffold_tpu.analysis.concurrency import (
        lint_concurrency_source,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = open(
        os.path.join(
            repo, "frl_distributed_ml_scaffold_tpu", "telemetry",
            "metrics.py",
        )
    ).read()
    assert lint_concurrency_source(src, "metrics.py") == [], (
        lint_concurrency_source(src, "metrics.py")
    )
    anchor = "        with self._reg._lock:\n            self._value += n"
    assert anchor in src
    mutated = src.replace(
        anchor,
        "        with self._reg._lock:\n"
        "            jax.block_until_ready(n)\n"
        "            self._value += n",
    )
    findings = lint_concurrency_source(mutated, "metrics.py")
    blocked = [f for f in findings if f.code == "blocking-under-lock"]
    assert len(blocked) == 1, findings
    assert blocked[0].severity == "error"
    assert "block_until_ready" in blocked[0].message

    indirect = '''
import time
import threading

class Poller:
    def __init__(self):
        self._lock = threading.Lock()

    def _backoff(self):
        time.sleep(0.5)

    def step(self):
        with self._lock:
            self._backoff()
'''
    findings = lint_concurrency_source(indirect, "indirect.py")
    blocked = [f for f in findings if f.code == "blocking-under-lock"]
    assert blocked and all(f.severity == "error" for f in blocked), findings
    assert any("time.sleep" in f.message for f in blocked)


@pytest.mark.fast
@pytest.mark.chaos
def test_concurrency_repo_package_is_clean():
    """The whole package (serving engine, elastic launcher, telemetry,
    native loader) carries no lock-discipline errors: every guarded
    attribute is written under its lock, the acquisition-order graph is
    acyclic, and nothing blocks while holding a lock."""
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_concurrency,
    )

    report = lint_concurrency()
    assert report.meta["files"] > 50  # the glob really covers the package
    assert report.errors() == [], [f.message for f in report.errors()]


# ------------------------------------------------------------ runner/CLI


@pytest.mark.fast
def test_lint_train_step_overlap_recipes_enforce_their_pins():
    """The runner applies the right invariant per recipe class: both
    overlap recipes lint clean at HEAD (their schedules intact)."""
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_train_step,
    )

    for name in ("gpt2_medium_tp_overlap", "gpt2_medium_fsdp_overlap"):
        rep = lint_train_step(name, workdir="/tmp/graft_lint_test")
        assert rep.ok, [f.message for f in rep.errors()]
        census = rep.meta["collective_census"]
        assert census, "overlap recipe census is empty?!"
        prims = {r["primitive"] for r in census}
        if name == "gpt2_medium_tp_overlap":
            assert "ppermute" in prims, prims
            assert "all_gather" not in prims, prims
        else:
            assert "all_gather" in prims and "reduce_scatter" in prims, prims


@pytest.mark.fast
def test_stage_program_lint_clean_and_mutations_trip(monkeypatch):
    """THE pipeline:stage_program family gates (ISSUE 14). Positive: the
    MPMD recipe's per-stage programs lint clean at HEAD (free of
    cross-stage collectives, stage state donated). Mutations: (a) drop
    the stage update donation — the audit fires `stage-not-donated`;
    (b) sneak a pipe-axis psum into a stage program — the census check
    fires `cross-stage-collective` (boundary traffic must be the
    driver's explicit transfers only)."""
    from frl_distributed_ml_scaffold_tpu import parallel
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_stage_programs,
    )
    from frl_distributed_ml_scaffold_tpu.parallel import (
        mpmd_pipeline as mpp,
    )

    rep = lint_stage_programs(workdir="/tmp/graft_lint_test")
    assert rep.ok, [f.message for f in rep.errors()]
    assert rep.meta["pipeline"]["impl"] == "mpmd"
    assert rep.meta["stages"] == rep.meta["pipeline"]["stages"]

    # (a) dropped stage-state donation.
    monkeypatch.setattr(mpp, "_DONATE_STAGE_STATE", False)
    rep_d = lint_stage_programs(workdir="/tmp/graft_lint_test")
    codes = {f.code for f in rep_d.errors()}
    assert "stage-not-donated" in codes, codes
    monkeypatch.setattr(mpp, "_DONATE_STAGE_STATE", True)

    # (b) a collective over the pipe axis inside a stage program.
    real = mpp._stage_forward

    def sabotaged(module, policy, params_c, x, rng, train):
        from frl_distributed_ml_scaffold_tpu.dist.mesh import (
            current_mesh_env,
        )

        y = real(module, policy, params_c, x, rng, train)
        env = current_mesh_env()
        return shard_map_compat(
            lambda t: jax.lax.psum(t, "pipe"),
            mesh=env.mesh, in_specs=P(), out_specs=P(),
        )(y)

    monkeypatch.setattr(mpp, "_stage_forward", sabotaged)
    rep_c = lint_stage_programs(workdir="/tmp/graft_lint_test")
    codes = {f.code for f in rep_c.errors()}
    assert "cross-stage-collective" in codes, codes


@pytest.mark.fast
def test_lint_runner_unknown_recipe_refuses():
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_train_step,
    )

    with pytest.raises(KeyError, match="RECIPE_OVERRIDES"):
        lint_train_step("no_such_recipe", workdir="/tmp/graft_lint_test")


@pytest.mark.fast
def test_cli_all_recipes_runs_clean_and_emits_json(tmp_path):
    """The acceptance gate: `python tools/graft_lint.py --all-recipes`
    exits 0 on HEAD under JAX_PLATFORMS=cpu and the JSON report covers
    every registered recipe + the serving decode step + hygiene."""
    from frl_distributed_ml_scaffold_tpu.config import list_configs

    out = tmp_path / "report.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
         "--all-recipes", "--json", str(out), "-q",
         "--workdir", str(tmp_path / "wd")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    reports = json.loads(out.read_text())
    programs = {r["program"] for r in reports}
    for name in list_configs():
        assert f"recipe:{name}" in programs, programs
    assert "serving:decode_step" in programs
    assert "serving:decode_step_int8kv" in programs
    assert "serving:handoff" in programs
    assert "pipeline:stage_program" in programs
    assert "reshard:fsdp_to_tp" in programs
    assert "reshard:tp_row_to_col" in programs
    assert "reshard:restore_even_to_fsdp" in programs
    assert "hygiene:traced-modules" in programs
    assert "robustness:package" in programs
    assert "concurrency:package" in programs
    assert all(r["ok"] for r in reports), [
        r["program"] for r in reports if not r["ok"]
    ]


@pytest.mark.fast
def test_cli_only_selects_named_pass_families(tmp_path):
    """ISSUE 20 satellite: ``--only concurrency`` runs exactly that pass
    (no recipe tracing — fast), exits 0 on HEAD, and stacking ``--only``
    flags unions the families."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = tmp_path / "only.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
         "--only", "concurrency", "--json", str(out), "-q"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    reports = json.loads(out.read_text())
    assert {r["program"] for r in reports} == {"concurrency:package"}

    out3 = tmp_path / "only3.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
         "--only", "concurrency", "--only", "robustness",
         "--only", "hygiene", "--json", str(out3), "-q"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    programs = {r["program"] for r in json.loads(out3.read_text())}
    assert programs == {
        "concurrency:package", "robustness:package",
        "hygiene:traced-modules",
    }


@pytest.mark.fast
def test_cli_only_unknown_pass_refused(tmp_path):
    """A typo'd pass name must fail loudly (argparse choices), not lint
    nothing and exit 0; --only also refuses to combine with --no-*."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
         "--only", "concurency", "-q"],  # sic: typo'd
        capture_output=True, text=True, env=env, cwd=repo, timeout=120,
    )
    assert proc.returncode != 0
    assert "invalid choice" in proc.stderr, proc.stderr

    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
         "--only", "hygiene", "--no-serving", "-q"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=120,
    )
    assert proc.returncode != 0
    assert "--no-" in proc.stderr, proc.stderr


@pytest.mark.fast
def test_cli_exits_nonzero_on_error_finding(tmp_path, monkeypatch):
    """severity:error ⇒ non-zero exit: lint a recipe subset with an
    absurd materialization budget (1 byte) — every recipe trips it."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
         "--recipe", "mnist_mlp", "--no-serving", "--no-hygiene",
         "--budget-mb", "0.000001", "-q",
         "--workdir", str(tmp_path / "wd")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "over-budget" in proc.stdout


def test_cli_census_baseline_roundtrip_and_diff(tmp_path):
    """--save-census then --against: identical program ⇒ no census
    warnings; a doctored baseline (one ring removed) ⇒ census-added."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    census_path = tmp_path / "census.json"
    base_cmd = [
        sys.executable, os.path.join(repo, "tools", "graft_lint.py"),
        "--recipe", "gpt2_medium_tp_overlap", "--no-serving",
        "--no-hygiene", "-q", "--workdir", str(tmp_path / "wd"),
    ]
    proc = subprocess.run(
        base_cmd + ["--save-census", str(census_path)],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    baseline = json.loads(census_path.read_text())
    assert baseline["recipe:gpt2_medium_tp_overlap"]

    proc2 = subprocess.run(
        base_cmd + ["--against", str(census_path), "--json",
                    str(tmp_path / "r.json")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc2.returncode == 0
    reports = json.loads((tmp_path / "r.json").read_text())
    assert not any(
        f["code"].startswith("census-")
        for r in reports for f in r["findings"]
    )

    # Doctor the baseline: drop one record — the diff must flag it added.
    key = "recipe:gpt2_medium_tp_overlap"
    baseline[key] = baseline[key][1:]
    census_path.write_text(json.dumps(baseline))
    proc3 = subprocess.run(
        base_cmd + ["--against", str(census_path), "--json",
                    str(tmp_path / "r3.json")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc3.returncode == 0  # census drift is a warning, not an error
    reports3 = json.loads((tmp_path / "r3.json").read_text())
    assert any(
        f["code"] == "census-added"
        for r in reports3 for f in r["findings"]
    ), reports3


# ------------------------------------------------------------ perf ledger


def test_perf_ledger_check_matches_committed_baseline(tmp_path):
    """ISSUE 8 acceptance gate: `python tools/perf_ledger.py --check`
    round-trips green against the committed PERF_LEDGER.json — the
    analytic census/FLOPs of the baseline recipes are bit-deterministic
    on the CPU sim, so this is the census-vs-measured regression gate
    that substitutes for the dead bench relay."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "perf_ledger.py"),
         "--check", "--workdir", str(tmp_path / "wd")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rows match" in proc.stdout
    # The committed baseline carries both sides of the join: analytic
    # census/flops AND the measured provenance columns.
    baseline = json.loads(
        open(os.path.join(repo, "PERF_LEDGER.json")).read()
    )
    rows = baseline["rows"]
    assert "serving:decode_step" in rows
    tp = rows["recipe:gpt2_medium_tp_overlap"]
    assert tp["collectives"]["ppermute"]["total_bytes"] > 0  # the rings
    assert tp["flops_per_step"] > 0
    assert tp["measured"]["step_time_p50_s"] > 0
    assert tp["attribution"]["mfu"] > 0
    assert rows["serving:decode_step"]["measured"]["tpot_p50_s"] > 0


def test_perf_ledger_check_exits_nonzero_on_mutation(tmp_path):
    """The mutation gate: doctor the committed baseline (census bytes and
    FLOPs) — --check must report the drift per field and exit 1."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    baseline = json.loads(
        open(os.path.join(repo, "PERF_LEDGER.json")).read()
    )
    tp = baseline["rows"]["recipe:gpt2_medium_tp_overlap"]
    tp["flops_per_step"] += 1
    tp["collectives"]["ppermute"]["total_bytes"] //= 2
    doctored = tmp_path / "doctored.json"
    doctored.write_text(json.dumps(baseline))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "perf_ledger.py"),
         "--check", "--baseline", str(doctored),
         "--workdir", str(tmp_path / "wd")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "flops_per_step drifted" in proc.stdout
    assert "collectives drifted" in proc.stdout
