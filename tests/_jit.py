"""Compiled flax init/apply helpers for tests.

Eager flax ``init``/``apply`` dispatches hundreds of tiny ops one by one on
the 1-core CPU sim box (measured: 11.8 s for an eager RN50 init vs <1 s as
one jitted, persistently cached program); jitting the hot test bodies cut
the warm suite 394 s -> 255 s. Use these instead of calling models eagerly.
"""

from __future__ import annotations

import jax


def jit_init(model, *args, rng=None, **kw):
    """``model.init`` as one compiled program; returns the variables dict."""
    key = jax.random.key(0) if rng is None else rng
    return jax.jit(lambda k: model.init({"params": k}, *args, **kw))(key)


def jit_apply(model, **kw):
    """A compiled ``(variables, *args) -> model.apply(variables, *args)``.

    Static knobs (``train=``, ``mutable=``, ``decode=``, ``rngs=``) go in
    ``**kw``; reuse the returned callable to share one compilation across
    repeated calls with the same shapes.
    """
    return jax.jit(lambda v, *a: model.apply(v, *a, **kw))
