"""Worker half of the 2-process jax.distributed integration test.

Launched (twice) by tests/test_multiprocess.py with FRL_TPU_* rendezvous env
vars. Exercises the real multi-process branches that single-process CI can
never reach: ``jax.distributed.initialize``, ``process_count() > 1`` host
collectives, per-process data sharding, and two global train steps.
Prints ``CHECK <json>`` lines the parent asserts on.
"""

import json
import os
import sys


def main() -> int:
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    import jax

    # The axon sitecustomize pins jax_platforms at the config level, which
    # beats env vars — force CPU the same way tests/conftest.py does.
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from frl_distributed_ml_scaffold_tpu.dist import collectives
    from frl_distributed_ml_scaffold_tpu.dist.initialize import (
        initialize_distributed,
        process_count,
        process_index,
        shutdown_distributed,
    )

    initialize_distributed()  # resolves from FRL_TPU_* env vars
    pid = process_index()
    out = {"process_count": process_count(), "pid": pid}
    out["local_devices"] = jax.local_device_count()
    out["global_devices"] = jax.device_count()

    # Host-tier collectives (SURVEY C2): the branches with process_count>1.
    got = collectives.host_broadcast(np.array([41.0 + pid], np.float32))
    out["broadcast"] = float(got[0])  # both must see process 0's 41.0
    gathered = collectives.host_all_gather(np.array([pid], np.int32))
    out["all_gather"] = np.asarray(gathered).ravel().tolist()
    collectives.barrier("twoproc-test")

    # Global-batch assembly + two real train steps over a 2-process mesh.
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "data.global_batch_size=16",
            "data.prefetch=0",
            "model.hidden_sizes=32",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            "workdir=" + os.environ["FRL_TEST_WORKDIR"],
        ],
    )
    trainer = Trainer(cfg)
    out["local_batch"] = trainer.pipeline.local_batch_size
    state = trainer.init_state()
    for step in range(2):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    # The loss is a global reduction — every process must report the same.
    out["loss"] = round(float(jax.device_get(metrics["loss"])), 6)

    # Hybrid ICI x DCN mesh across REAL process boundaries: with 2
    # processes x 4 local devices, dcn_data=2 puts the slice boundary
    # exactly at the process boundary — the closest a test can get to a
    # multi-slice pod without pod hardware.
    cfg_dcn = apply_overrides(
        cfg, ["mesh.dcn_data=2", "workdir=" + os.environ["FRL_TEST_WORKDIR"] + "/dcn"]
    )
    t2 = Trainer(cfg_dcn)
    out["dcn_mesh"] = dict(t2.env.mesh.shape)
    s2 = t2.init_state()
    for step in range(2):
        b2 = t2.pipeline.global_batch(step)
        s2, m2 = t2.train_step(s2, b2)
    out["dcn_loss"] = round(float(jax.device_get(m2["loss"])), 6)

    print("CHECK " + json.dumps(out), flush=True)
    shutdown_distributed()
    return 0


if __name__ == "__main__":
    sys.exit(main())
