"""Worker half of the 2-process jax.distributed integration test.

Launched (twice) by tests/test_multiprocess.py with FRL_TPU_* rendezvous env
vars. Exercises the real multi-process branches that single-process CI can
never reach: ``jax.distributed.initialize``, ``process_count() > 1`` host
collectives, per-process data sharding, and two global train steps.
Prints ``CHECK <json>`` lines the parent asserts on.
"""

import json
import os
import sys


def main() -> int:
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    import jax

    # The axon sitecustomize pins jax_platforms at the config level, which
    # beats env vars — force CPU the same way tests/conftest.py does.
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from frl_distributed_ml_scaffold_tpu.dist import collectives
    from frl_distributed_ml_scaffold_tpu.dist.initialize import (
        initialize_distributed,
        process_count,
        process_index,
        shutdown_distributed,
    )

    initialize_distributed()  # resolves from FRL_TPU_* env vars
    pid = process_index()
    out = {"process_count": process_count(), "pid": pid}
    out["local_devices"] = jax.local_device_count()
    out["global_devices"] = jax.device_count()

    # Host-tier collectives (SURVEY C2): the branches with process_count>1.
    got = collectives.host_broadcast(np.array([41.0 + pid], np.float32))
    out["broadcast"] = float(got[0])  # both must see process 0's 41.0
    gathered = collectives.host_all_gather(np.array([pid], np.int32))
    out["all_gather"] = np.asarray(gathered).ravel().tolist()
    collectives.barrier("twoproc-test")

    # Global-batch assembly + two real train steps over a 2-process mesh.
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "data.global_batch_size=16",
            "data.prefetch=0",
            "model.hidden_sizes=32",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            "workdir=" + os.environ["FRL_TEST_WORKDIR"],
        ],
    )
    trainer = Trainer(cfg)
    out["local_batch"] = trainer.pipeline.local_batch_size
    state = trainer.init_state()
    for step in range(2):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    # The loss is a global reduction — every process must report the same.
    out["loss"] = round(float(jax.device_get(metrics["loss"])), 6)

    # Hybrid ICI x DCN mesh across REAL process boundaries: with 2
    # processes x 4 local devices, dcn_data=2 puts the slice boundary
    # exactly at the process boundary — the closest a test can get to a
    # multi-slice pod without pod hardware.
    cfg_dcn = apply_overrides(
        cfg, ["mesh.dcn_data=2", "workdir=" + os.environ["FRL_TEST_WORKDIR"] + "/dcn"]
    )
    t2 = Trainer(cfg_dcn)
    out["dcn_mesh"] = dict(t2.env.mesh.shape)
    s2 = t2.init_state()
    for step in range(2):
        b2 = t2.pipeline.global_batch(step)
        s2, m2 = t2.train_step(s2, b2)
    out["dcn_loss"] = round(float(jax.device_get(m2["loss"])), 6)

    # Per-host distinct-batch contract over a REAL on-disk corpus (SURVEY
    # C16 "sharded per-host input"): each process draws its own sample
    # indices (host_offset folds into the sampling rng) and the global
    # batch assembles every host's local slice into the right global
    # shards (jax.make_array_from_process_local_data path). The corpus is
    # written by the parent test: constant-valued images whose pixel value
    # encodes the sample index, labels = index — so pairing survives
    # gather + augment (flip/crop of a constant image is the identity;
    # normalization is invertible).
    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
    from frl_distributed_ml_scaffold_tpu.data.native import (
        _IMAGENET_MEAN,
        _IMAGENET_STD,
    )
    from frl_distributed_ml_scaffold_tpu.data.pipeline import build_pipeline

    corpus_dir = os.path.join(os.environ["FRL_TEST_WORKDIR"], "corpus")
    dcfg = DataConfig(
        name="imagenet", data_dir=corpus_dir, global_batch_size=16,
        image_size=8, channels=3, num_classes=256, prefetch=0,
    )
    pipe = build_pipeline(dcfg, trainer.env, split="train")
    inner = getattr(pipe, "_p", pipe)
    assert not inner.source.is_synthetic, "corpus not picked up"
    local = pipe.local_batch(0)
    out["rd_local_labels"] = np.asarray(local["label"]).astype(int).tolist()
    # Pixel value decodes back to the sample index: pairing preserved
    # through the native gather + augment path.
    decoded = (
        np.asarray(local["image"])[:, 0, 0, 0] * _IMAGENET_STD[0]
        + _IMAGENET_MEAN[0]
    ) * 255.0
    out["rd_pixel_decode_ok"] = bool(
        np.allclose(decoded, np.asarray(local["label"]), atol=1.0)
    )
    gb = pipe.global_batch(0)
    shards = sorted(
        gb["label"].addressable_shards, key=lambda s: s.index[0].start or 0
    )
    mine = np.concatenate([np.asarray(s.data) for s in shards]).astype(int)
    # This process's addressable slice of the GLOBAL batch must be exactly
    # the local draw, in order.
    out["rd_global_matches_local"] = bool(
        np.array_equal(mine, np.asarray(local["label"]).astype(int))
    )

    print("CHECK " + json.dumps(out), flush=True)
    shutdown_distributed()
    return 0


if __name__ == "__main__":
    sys.exit(main())
