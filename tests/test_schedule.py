"""Unified overlap-schedule layer (parallel/schedule.py, ISSUE 13): the
declarative per-axis gather/scatter schedule must (i) be EXACTLY the
program the legacy fsdp_overlap/tp_overlap knobs build (the adapters'
equivalence contract), (ii) match the all-GSPMD path numerically on the
composed meshes, (iii) refuse contradictory declarations with a typed
``ScheduleError`` naming the attribute, and (iv) be verifiable
declaratively — ``analysis.pins.assert_schedule`` derives the expected
collective classes/counts/bytes from the declaration itself, including
the composed recipe's zero-monolithic-all_gather pin and the int8
variant's >= 3.5x ppermute-bytes reduction."""

import re

import jax
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context
from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
    OverlapSchedule,
    ScheduleError,
    gather,
    parse_schedule,
    scatter,
    schedule_from_config,
    validate_schedule_config,
)
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

GPT_TINY = [
    "model.num_layers=2", "model.num_heads=4", "model.hidden_dim=64",
    "model.seq_len=64", "model.vocab_size=256",
    "data.seq_len=64", "data.vocab_size=256",
    "data.global_batch_size=16",
    "trainer.grad_accum=1", "trainer.remat=none",
    "trainer.log_every=1000000",
    "precision.policy=fp32",
    "checkpoint.enabled=false",
    "optimizer.warmup_steps=0",
    "parallel.fsdp_min_size=16",
]

FSDP = ["parallel.param_sharding=fsdp", "parallel.opt_sharding=like_params"]

COMPOSED_MESH = ["mesh.data=1", "mesh.fsdp=4", "mesh.model=2"]

#: The composed declaration, spelled as the explicit string form.
COMPOSED_DECL = (
    "gather(fsdp,block,prefetch=1)+scatter(fsdp)"
    "+gather(model,ring_chunk)+scatter(model)"
)


def make_trainer(name, base, overrides, tmp_path):
    cfg = apply_overrides(
        get_config(name), base + [f"workdir={tmp_path}"] + list(overrides)
    )
    return Trainer(cfg, mesh_env=build_mesh(cfg.mesh))


def run_steps(trainer, n=3):
    state = trainer.init_state()
    for step in range(n):
        state, metrics = trainer.train_step(
            state, trainer.pipeline.global_batch(step)
        )
    return jax.device_get(state), jax.device_get(metrics)


def assert_params_close(a, b, atol=2e-3):
    """steps x lr tolerance (the test_fsdp_overlap.py discipline; see its
    docstring for why adamw noise forbids 1e-5-tight param compares)."""
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=atol, rtol=1e-4),
        a.params,
        b.params,
    )


def _step_jaxpr(t):
    batch = {
        k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype)
        for k, v in t.pipeline.global_batch(0).items()
    }
    with mesh_context(t.env):
        return jax.make_jaxpr(t._train_step_fn)(t.state_shapes, batch)


def _normalized(jaxpr) -> str:
    # Function-object reprs (remat policies) embed addresses; the
    # PROGRAM identity is everything else.
    return re.sub(r"0x[0-9a-f]+", "0x", str(jaxpr))


# ------------------------------------------------------- declaration API


@pytest.mark.fast
def test_parse_render_roundtrip_and_knob_derivation():
    s = parse_schedule(
        "gather(fsdp, block, prefetch=2) + scatter(fsdp) "
        "+ gather(model, ring_chunk, lowp=int8) + scatter(model, lowp=int8)"
    )
    assert parse_schedule(s.render()) == s
    assert s.block_gather().prefetch == 2
    assert s.ring_gather().lowp == "int8"
    assert s.short() == "fsdp:block(p2)+model:ring(int8)"
    # The legacy knobs derive the same declaration the composed int8
    # recipe documents (prefetch=1 there).
    derived = schedule_from_config(
        get_config("gpt2_medium_fsdp_tp_overlap_int8")
    )
    assert derived == parse_schedule(
        "gather(fsdp,block,prefetch=1)+scatter(fsdp)"
        "+gather(model,ring_chunk,lowp=int8)+scatter(model,lowp=int8)"
    )
    assert derived.describe()["declared"] == derived.render()
    # No overlap knobs -> no schedule.
    assert schedule_from_config(get_config("gpt2_medium_zero1")) is None


@pytest.mark.fast
def test_schedule_errors_are_typed_and_name_the_attribute():
    """Contradictory knob compositions refuse loudly at BUILD time with
    the offending schedule attribute on the exception — the satellite
    bugfix: these used to surface as shape errors deep in the scan
    body (or silently change nothing)."""
    with pytest.raises(ScheduleError, match="granularity") as e:
        gather("fsdp", granularity="rings")
    assert e.value.attribute == "granularity"
    with pytest.raises(ScheduleError, match="fsdp_prefetch") as e:
        gather("fsdp", prefetch=-1)
    assert e.value.attribute == "prefetch"
    with pytest.raises(ScheduleError) as e:
        gather("fsdp", granularity="block", lowp="int8")
    assert e.value.attribute == "lowp"  # lowp is a ring-transfer attr
    with pytest.raises(ScheduleError) as e:
        OverlapSchedule.build(gather("model", granularity="block"),
                              scatter("model"))
    assert e.value.attribute == "axis"  # no block lowering on model
    with pytest.raises(ScheduleError) as e:
        OverlapSchedule.build(scatter("fsdp"))
    assert e.value.attribute == "axis"  # scatter without its gather
    with pytest.raises(ScheduleError) as e:
        OverlapSchedule.build(
            gather("model", granularity="ring_chunk", lowp="int8"),
            scatter("model"),
        )
    assert e.value.attribute == "lowp"  # fwd/bwd wire quantize together
    # lowp without ANY ring axis (the legacy low_precision contract).
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"), ["parallel.low_precision=int8"]
    )
    with pytest.raises(ScheduleError, match="tp_overlap") as e:
        schedule_from_config(cfg)
    assert e.value.attribute == "lowp"
    # Unknown formats keep the lowp_dtype KeyError + vocabulary.
    with pytest.raises(KeyError, match="fp8_e4m3"):
        parse_schedule("gather(model,ring_chunk,lowp=int4)+scatter(model)")


@pytest.mark.fast
def test_prefetch_window_beyond_block_count_refuses():
    """A prefetch window larger than the block count used to be a silent
    no-op structurally indistinguishable from a schedule bug — now a
    typed build-time refusal."""
    cfg = apply_overrides(
        get_config("gpt2_medium_fsdp_overlap"),
        GPT_TINY + ["parallel.fsdp_prefetch=3"],  # num_layers=2
    )
    sched = schedule_from_config(cfg)
    with pytest.raises(ScheduleError, match="block count") as e:
        validate_schedule_config(sched, cfg)
    assert e.value.attribute == "prefetch"


@pytest.mark.fast
def test_explicit_string_contradicting_knobs_refuses():
    cfg = apply_overrides(
        get_config("gpt2_medium_tp_overlap"),
        ["parallel.schedule=gather(fsdp,block)+scatter(fsdp)"],
    )
    with pytest.raises(ScheduleError, match="contradicts") as e:
        schedule_from_config(cfg)
    assert e.value.attribute == "schedule"
    # lowp knob vs a string declaring a DIFFERENT ring format.
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["parallel.low_precision=int8",
         "parallel.schedule=gather(model,ring_chunk,lowp=fp8_e4m3)"
         "+scatter(model,lowp=fp8_e4m3)"],
    )
    with pytest.raises(ScheduleError, match="contradicts") as e:
        schedule_from_config(cfg)
    assert e.value.attribute == "lowp"


@pytest.mark.fast
def test_explicit_string_agreeing_with_knobs_is_accepted():
    """Per-knob agreement, not whole-declaration equality: a lowp ring
    declared via the string satisfies low_precision=int8 even with
    tp_overlap left false (the string replaces the derivation), and
    prefetch is refused as a ring attribute rather than silently
    dropped from render()."""
    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        ["parallel.low_precision=int8",
         "parallel.schedule=gather(model,ring_chunk,lowp=int8)"
         "+scatter(model,lowp=int8)"],
    )
    sched = schedule_from_config(cfg)
    assert sched.ring_gather().lowp == "int8"
    with pytest.raises(ScheduleError, match="block-granularity") as e:
        parse_schedule("gather(model,ring_chunk,prefetch=2)+scatter(model)")
    assert e.value.attribute == "prefetch"


# ------------------------------------------- adapters: program identity


@pytest.mark.parametrize(
    "name,mesh,extra,decl",
    [
        (
            "gpt2_medium_fsdp_overlap",
            ["mesh.data=1", "mesh.fsdp=8"],
            FSDP,
            "gather(fsdp,block,prefetch=1)+scatter(fsdp)",
        ),
        (
            "gpt2_medium_tp_overlap",
            ["mesh.data=1", "mesh.model=8"],
            [],
            "gather(model,ring_chunk)+scatter(model)",
        ),
        (
            "gpt2_medium_fsdp_tp_overlap",
            COMPOSED_MESH,
            [],
            COMPOSED_DECL,
        ),
    ],
    ids=["fsdp-block", "model-ring", "composed"],
)
def test_string_declaration_is_program_identical_to_legacy_knobs(
    tmp_path, name, mesh, extra, decl
):
    """The adapters' equivalence contract, pinned at PROGRAM level: the
    legacy knob spelling and the explicit ``parallel.schedule`` string
    trace to the identical train-step jaxpr — same gathers, same rings,
    same remat policies, eqn for eqn. (Numerics-vs-GSPMD for the legacy
    knobs stays where it always lived: tests/test_{fsdp,tp}_overlap.py,
    which this identity extends to the string form for free.)"""
    legacy = make_trainer(name, GPT_TINY, mesh + extra, tmp_path / "legacy")
    knobs = legacy.cfg.parallel
    string = make_trainer(
        "gpt2_medium_zero1",
        GPT_TINY,
        mesh
        + [
            f"parallel.param_sharding={knobs.param_sharding}",
            f"parallel.opt_sharding={knobs.opt_sharding}",
            f"parallel.schedule={decl}",
        ],
        tmp_path / "string",
    )
    assert _normalized(_step_jaxpr(legacy)) == _normalized(
        _step_jaxpr(string)
    )


# ------------------------------------------------- equivalence grid
# schedule-vs-GSPMD numerics. fsdp-only and model-only cells ride the
# program-identity pin above plus the legacy grids
# (tests/test_{fsdp,tp}_overlap.py); the cells here are the ones the
# satellite adds: the composed recipe, data x fsdp via the string form,
# grad accumulation, and (slow) the remat x mesh matrix.


def composed_pair(tmp_path, extra=()):
    """(all-GSPMD fsdp x model state+metrics, composed-schedule
    state+metrics) after 3 identical steps."""
    ref = make_trainer(
        "gpt2_tp", GPT_TINY, COMPOSED_MESH + FSDP + list(extra),
        tmp_path / "ref",
    )
    ovl = make_trainer(
        "gpt2_medium_fsdp_tp_overlap", GPT_TINY,
        COMPOSED_MESH + list(extra), tmp_path / "ovl",
    )
    return run_steps(ref), run_steps(ovl)


def test_composed_schedule_matches_gspmd_fsdp_x_model(tmp_path):
    """THE acceptance cell: the registered composed recipe (blockwise
    fsdp gathers + model rings in one scan body) vs the all-GSPMD path
    on the same mesh — params inside the documented steps x lr band,
    losses identical to the documented 1e-5."""
    (ref, ref_m), (ovl, ovl_m) = composed_pair(tmp_path)
    assert_params_close(ref, ovl)
    np.testing.assert_allclose(ovl_m["loss"], ref_m["loss"], atol=1e-5)


def test_composed_schedule_grad_accum_matches(tmp_path):
    """grad_accum=4: both explicit schedules inside the microbatch scan."""
    (ref, _), (ovl, ovl_m) = composed_pair(
        tmp_path, extra=["trainer.grad_accum=4"]
    )
    assert_params_close(ref, ovl)
    assert np.isfinite(ovl_m["loss"])


def test_block_schedule_via_string_matches_data_x_fsdp(tmp_path):
    """data=2 x fsdp=4 through the explicit declaration string — the
    schedule-vs-GSPMD face of the data x fsdp cell (the legacy-knob face
    lives in test_fsdp_overlap.py)."""
    mesh = ["mesh.data=2", "mesh.fsdp=4"]
    ref = make_trainer(
        "gpt2_medium_zero1", GPT_TINY, mesh + FSDP, tmp_path / "ref"
    )
    ovl = make_trainer(
        "gpt2_medium_zero1", GPT_TINY,
        mesh + FSDP
        + ["parallel.schedule=gather(fsdp,block,prefetch=1)+scatter(fsdp)"],
        tmp_path / "ovl",
    )
    (ref_s, ref_m), (ovl_s, ovl_m) = run_steps(ref), run_steps(ovl)
    assert_params_close(ref_s, ovl_s)
    np.testing.assert_allclose(ovl_m["loss"], ref_m["loss"], atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("block_remat", ["full", "save_attn"])
def test_composed_schedule_block_remat_matrix(tmp_path, block_remat):
    """remat x composed mesh: the hooks sit inside the per-block remat
    region, so the backward re-gathers AND re-runs the rings."""
    (ref, _), (ovl, _) = composed_pair(
        tmp_path, extra=[f"model.block_remat={block_remat}"]
    )
    assert_params_close(ref, ovl)


@pytest.mark.slow
def test_composed_schedule_trainer_remat_matrix(tmp_path):
    """Whole-loss checkpointing around the composed hooked model."""
    (ref, _), (ovl, _) = composed_pair(
        tmp_path, extra=["trainer.remat=full"]
    )
    assert_params_close(ref, ovl)


# ------------------------------------------------- declarative pins
# assert_schedule derives the expectation from the declaration; these are
# the acceptance pins plus the mutation gates the satellite requires.

from frl_distributed_ml_scaffold_tpu.analysis import pins
from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    collective_census,
)
from frl_distributed_ml_scaffold_tpu.analysis.schedule import (
    ring_ppermute_bytes,
)
from frl_distributed_ml_scaffold_tpu.parallel.partition import (
    block_param_slice_shapes,
)


def _composed_artifacts(tmp_path, name="gpt2_medium_fsdp_tp_overlap"):
    t = make_trainer(name, GPT_TINY, COMPOSED_MESH, tmp_path / name)
    jaxpr = _step_jaxpr(t)
    sched = t.overlap_schedule
    axis_sizes = {a: t.env.axis_size(a) for a in ("data", "fsdp", "model")}
    slices = block_param_slice_shapes(
        t.state_shapes.params, t.env.axis_size("model")
    )
    return t, jaxpr, sched, axis_sizes, slices


@pytest.mark.fast
def test_assert_schedule_pins_composed_recipe(tmp_path):
    """The composed recipe is jaxpr-pinned FREE of monolithic
    all_gathers: every all_gather is a per-block param slice inside the
    layer scans, the TP rings are whole ppermute chains, and the
    explicit reduce_scatter exists — all derived from the declaration
    alone."""
    _, jaxpr, sched, axis_sizes, slices = _composed_artifacts(tmp_path)
    pins.assert_schedule(
        jaxpr, sched, axis_sizes=axis_sizes, param_slices=slices
    )
    # Belt-and-braces on the headline claim: gathers live IN the scans.
    scan_gathers = pins.scan_collective_counts(jaxpr, "all_gather")
    assert any(n > 0 for n in scan_gathers), scan_gathers
    pins.assert_collective_present(jaxpr, "ppermute")
    pins.assert_collective_present(jaxpr, "reduce_scatter")


def test_assert_schedule_pins_int8_wire_ratio(tmp_path):
    """The composed _int8 variant is census-pinned >= 3.5x lower
    model-axis ppermute bytes than the fp32 composed path (4x element
    width minus the scale traffic) — the lowp-as-schedule-attribute
    acceptance pin, measured via the declaration."""
    _, jaxpr32, _, _, _ = _composed_artifacts(tmp_path)
    _, jaxpr8, sched8, axis_sizes, slices = _composed_artifacts(
        tmp_path, name="gpt2_medium_fsdp_tp_overlap_int8"
    )
    base_census = collective_census(jaxpr32)
    pins.assert_schedule(
        jaxpr8, sched8, axis_sizes=axis_sizes, param_slices=slices,
        baseline_census=base_census, min_wire_ratio=3.5,
    )
    ratio = ring_ppermute_bytes(base_census, "model") / ring_ppermute_bytes(
        collective_census(jaxpr8), "model"
    )
    assert ratio >= 3.5, ratio


@pytest.mark.fast
def test_assert_schedule_mutation_gspmd_fallback_trips(tmp_path):
    """Mutation gate 1: a GSPMD fallback (the same config WITHOUT the
    hooks — no explicit gathers, no rings) must trip the declared
    schedule's pins."""
    ref = make_trainer(
        "gpt2_tp", GPT_TINY, COMPOSED_MESH + FSDP, tmp_path / "gspmd"
    )
    jaxpr = _step_jaxpr(ref)
    sched = parse_schedule(COMPOSED_DECL)
    axis_sizes = {a: ref.env.axis_size(a) for a in ("data", "fsdp", "model")}
    slices = block_param_slice_shapes(
        ref.state_shapes.params, ref.env.axis_size("model")
    )
    with pytest.raises(AssertionError, match="missing-"):
        pins.assert_schedule(
            jaxpr, sched, axis_sizes=axis_sizes, param_slices=slices,
            msg="missing-rings/missing-block-gathers",
        )


def test_assert_schedule_mutation_wide_ring_under_lowp_trips(tmp_path):
    """Mutation gate 2: a wide fp32 ring under a ``lowp`` schedule must
    trip — the fp32 composed program checked against the int8
    declaration reports wide-ppermute payloads and the missing int8
    traffic."""
    _, jaxpr32, _, axis_sizes, slices = _composed_artifacts(tmp_path)
    sched8 = parse_schedule(
        "gather(fsdp,block,prefetch=1)+scatter(fsdp)"
        "+gather(model,ring_chunk,lowp=int8)+scatter(model,lowp=int8)"
    )
    with pytest.raises(AssertionError, match="wide floats|lowp"):
        pins.assert_schedule(
            jaxpr32, sched8, axis_sizes=axis_sizes, param_slices=slices
        )


@pytest.mark.fast
@pytest.mark.lint
def test_schedule_program_family_lints_composed_recipes():
    """The ``schedule:`` graft-lint program family (satellite: CI
    covers the composed recipe): the declaration-first reports lint
    clean at HEAD and carry the declared schedule in meta."""
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        lint_schedule_program,
    )

    rep = lint_schedule_program(
        "gpt2_medium_fsdp_tp_overlap", workdir="/tmp/graft_lint_test"
    )
    assert rep.program == "schedule:gpt2_medium_fsdp_tp_overlap"
    assert rep.ok, [f.message for f in rep.errors()]
    assert rep.meta["schedule"]["short"] == "fsdp:block(p1)+model:ring"
