"""Compressed video → clip-shard producer (tools/decode_video.py) + loader
round-trip (SURVEY C16, the Ego4D-analogue ingestion path).

The encode/decode halves run in a subprocess (TensorFlow is IO-only
tooling and must never load into the training/test process); the loader
and training assertions run here on the produced shards — the same
contract real extracted-frame footage would exercise.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRODUCER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import tensorflow as tf

    raw, out = sys.argv[1], sys.argv[2]
    rng = np.random.default_rng(0)
    # Two classes; class 0 holds frame-JPEG video dirs, class 1 holds an
    # animated GIF — both supported layouts in one tree. Distinct constant
    # intensity per class makes labels checkable post-decode.
    for ci, cls in enumerate(["walking", "cooking"]):
        cdir = os.path.join(raw, "train", cls)
        os.makedirs(cdir, exist_ok=True)
        if ci == 0:
            for v in range(2):
                vdir = os.path.join(cdir, f"vid_{v}")
                os.makedirs(vdir, exist_ok=True)
                for f in range(20):  # 20 frames -> 2 non-overlap windows
                    img = np.full((48, 40, 3), 30, np.uint8)
                    img += rng.integers(0, 15, img.shape, dtype=np.uint8)
                    tf.io.write_file(
                        os.path.join(vdir, f"frame_{f:04d}.jpg"),
                        tf.io.encode_jpeg(tf.constant(img)),
                    )
        else:
            from PIL import Image

            frames = [
                Image.fromarray(
                    np.full((48, 40, 3), 200, np.uint8)
                    + rng.integers(0, 15, (48, 40, 3), dtype=np.uint8)
                )
                for _ in range(12)
            ]
            frames[0].save(
                os.path.join(cdir, "clip.gif"), save_all=True,
                append_images=frames[1:], duration=40, loop=0,
            )
    sys.argv = [
        "decode_video.py", raw, out, "--split", "train",
        "--frames", "8", "--size", "32", "--shard-items", "3",
        "--dtype", "uint8",
    ]
    sys.path.insert(0, os.path.join(%r, "tools"))
    import decode_video
    raise SystemExit(decode_video.main())
    """
) % (REPO_ROOT,)


@pytest.fixture(scope="module")
def clip_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("video_raw")
    raw, out = str(tmp / "raw"), str(tmp / "shards")
    env = {**os.environ, "CUDA_VISIBLE_DEVICES": "-1",
           "TF_CPP_MIN_LOG_LEVEL": "2"}
    env.pop("XLA_FLAGS", None)  # keep TF from parsing jax's sim-device flag
    proc = subprocess.run(
        [sys.executable, "-c", _PRODUCER, raw, out],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    return out


def test_producer_emits_paired_clip_shards(clip_dir):
    xs = sorted(f for f in os.listdir(clip_dir) if "clips" in f)
    ys = sorted(f for f in os.listdir(clip_dir) if "labels" in f)
    # 2 frame-dirs x 2 windows + 1 gif x 1 window = 5 clips / 3 per shard.
    assert len(xs) == len(ys) == 2
    x0 = np.load(os.path.join(clip_dir, xs[0]))
    assert x0.shape == (3, 8, 32, 32, 3) and x0.dtype == np.uint8
    meta = json.load(open(os.path.join(clip_dir, "train_meta.json")))
    assert meta["clips"] == 5 and meta["videos"] == 3
    assert meta["class_names"] == ["cooking", "walking"]


def test_loader_reads_decoded_clips_with_correct_pairing(clip_dir):
    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
    from frl_distributed_ml_scaffold_tpu.data.video import VideoClips

    cfg = DataConfig(
        name="video", data_dir=clip_dir, num_frames=8, image_size=32,
        channels=3, num_classes=2,
    )
    src = VideoClips(cfg, split="train")
    assert not src.is_synthetic
    batch = src.batch(0, 16)
    assert batch["video"].shape == (16, 8, 32, 32, 3)
    assert batch["video"].dtype == np.float32
    # uint8 shards rescale to [0,1] in the shared gather; class identity
    # survives: walking≈30/255 dark, cooking≈200/255 bright (sorted class
    # order puts cooking=0, walking=1).
    means = batch["video"].mean(axis=(1, 2, 3, 4))
    for m, y in zip(means, batch["label"]):
        assert (m > 0.5) == (y == 0), (m, y)


def test_video_recipe_trains_from_decoded_shards(clip_dir, tmp_path):
    """tree → shards → video-recipe training e2e, like the ImageNet path."""
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("ego4d_video_elastic"),
        [
            "data.name=video",  # the recipe defaults to video_synthetic
            f"data.data_dir={clip_dir}",
            "data.global_batch_size=8",
            "data.num_frames=8",
            "data.image_size=32",
            "data.num_classes=2",
            "data.prefetch=0",
            "model.num_frames=8",
            "model.image_size=32",
            "model.num_classes=2",
            "model.tubelet_size=(2,8,8)",
            "model.hidden_dim=32",
            "model.num_layers=2",
            "model.num_heads=2",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    inner_pipe = getattr(trainer.pipeline, "_p", trainer.pipeline)
    assert not inner_pipe.source.is_synthetic
    state = trainer.init_state()
    for step in range(2):
        state, metrics = trainer.train_step(
            state, trainer.pipeline.global_batch(step)
        )
    assert np.isfinite(float(metrics["loss"]))
