"""Serving subsystem gates (serving/engine.py + the TP-sharded decode
path + tools/serve_bench.py).

Three layers, mirroring the subsystem:

- **Engine**: continuous batching over the fixed slot array — retired
  slots are refilled and the refilled request completes correctly (the
  acceptance gate), bucket growth, eos retirement, engine == generate()
  on the same request.
- **Parallel**: the sharded decode path matches the replicated path on
  model-only and data×model sim meshes, the prefill emits the cache
  model-sharded, and the prefill→decode handoff carries NO monolithic
  cache reshard (jaxpr/HLO pin, the tp_overlap pin style).
- **Bench**: tools/serve_bench.py runs end-to-end on CPU sim and emits a
  BENCH_TABLE-schema-valid row.
"""

from __future__ import annotations

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.serving

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_init

from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    MeshConfig,
    PrecisionConfig,
)
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    build_mesh,
    mesh_context,
)
from frl_distributed_ml_scaffold_tpu.models.generation import generate
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT, gpt_tp_rules
from frl_distributed_ml_scaffold_tpu.parallel.partition import (
    shard_params_for_serving,
)
from frl_distributed_ml_scaffold_tpu.analysis import pins
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

FP32 = get_policy(PrecisionConfig(policy="fp32"))
TINY = dict(
    vocab_size=64, num_layers=2, num_heads=4, hidden_dim=64, seq_len=64,
    dropout=0.0,
)


@pytest.fixture(scope="module")
def gpt():
    model = GPT(GPTConfig(**TINY), FP32)
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params, tokens


def _shard(params, env):
    return shard_params_for_serving(params, env, gpt_tp_rules())


# ------------------------------------------------------------------ engine


@pytest.mark.fast
def test_engine_matches_generate_greedy(gpt):
    """A single request through the slot machinery must equal generate()
    token-for-token (same shared decode entry point underneath)."""
    model, params, _ = gpt
    p = np.arange(5, dtype=np.int32) % 64
    eng = ServingEngine(model, params, num_slots=2, temperature=0.0)
    rid = eng.submit(p, max_new_tokens=6)
    done = {c.id: c for c in eng.run()}
    ref = generate(
        model, params, jnp.asarray(p)[None], max_new_tokens=6,
        temperature=0.0,
    )
    np.testing.assert_array_equal(done[rid].tokens, np.asarray(ref)[0])


@pytest.mark.fast
def test_engine_continuous_batching_refills_slots(gpt):
    """The acceptance gate: more requests than slots — retired slots must
    be refilled while other slots keep decoding, every refilled request
    must complete, and each completion must equal its own single-request
    generate() run (slot reuse cannot leak cache state)."""
    model, params, _ = gpt
    rng = np.random.default_rng(7)
    reqs = {}
    eng = ServingEngine(model, params, num_slots=3, temperature=0.0)
    for _ in range(8):
        l = int(rng.integers(2, 12))
        prompt = rng.integers(0, 64, size=l).astype(np.int32)
        n_new = int(rng.integers(2, 9))
        rid = eng.submit(prompt, n_new)
        reqs[rid] = (prompt, n_new)
    done = {c.id: c for c in eng.run()}
    assert sorted(done) == sorted(reqs), "not every request completed"
    # 8 requests through 3 slots: at least one slot was reused, and at
    # least one decode step ran with a mid-stream admission behind it.
    assert eng.stats["completed"] == 8
    assert eng.stats["decode_steps"] > 0
    for rid, (prompt, n_new) in reqs.items():
        ref = generate(
            model, params, jnp.asarray(prompt)[None], max_new_tokens=n_new,
            temperature=0.0,
        )
        np.testing.assert_array_equal(
            done[rid].tokens, np.asarray(ref)[0],
            err_msg=f"request {rid} diverged from its solo generate()",
        )


@pytest.mark.fast
def test_engine_eos_retirement_frees_slot(gpt):
    """A request hitting eos must retire early (finish_reason='eos',
    fewer tokens than budget) and hand its slot to the next queued
    request, which then completes."""
    model, params, _ = gpt
    p = np.arange(6, dtype=np.int32)
    # Find the greedy continuation's second token and use it as eos.
    ref = np.asarray(
        generate(model, params, jnp.asarray(p)[None], max_new_tokens=3,
                 temperature=0.0)
    )[0]
    eos = int(ref[7])
    eng = ServingEngine(
        model, params, num_slots=1, temperature=0.0, eos_id=eos
    )
    rid_a = eng.submit(p, max_new_tokens=10)
    rid_b = eng.submit((p + 1) % 64, max_new_tokens=2)
    done = {c.id: c for c in eng.run()}
    assert done[rid_a].finish_reason == "eos"
    assert len(done[rid_a].tokens) == 6 + 2  # retired at eos, not budget
    assert rid_b in done, "freed slot was not refilled"
    assert len(done[rid_b].tokens) == 6 + 2


@pytest.mark.fast
def test_engine_rejects_invalid_requests(gpt):
    """Guard rails: empty prompts, non-positive budgets (prefill always
    samples one token, and a seq_len prompt with budget 0 would push the
    bucket past seq_len), and context overflow all fail at submit() —
    never mid-loop."""
    model, params, _ = gpt
    with pytest.raises(ValueError, match="num_slots"):
        ServingEngine(model, params, num_slots=0)
    eng = ServingEngine(model, params, num_slots=1, temperature=0.0)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(np.zeros(0, np.int32), 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.zeros(model.config.seq_len, np.int32), 0)
    with pytest.raises(ValueError, match="exceeds the model context"):
        eng.submit(np.zeros(model.config.seq_len, np.int32), 1)


@pytest.mark.fast
def test_engine_bucket_growth_and_latency_accounting(gpt):
    """Cache buckets grow monotonically (powers of two) only when an
    active slot needs the room, and every completion carries per-token
    latencies."""
    model, params, _ = gpt
    eng = ServingEngine(model, params, num_slots=2, temperature=0.0,
                        min_bucket=8)
    eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=30)
    eng.submit(np.arange(7, dtype=np.int32), max_new_tokens=5)
    done = eng.run()
    grows = [k for k in eng.stats if k.startswith("grow_")]
    assert grows, f"34-token request in min_bucket=8 never grew: {dict(eng.stats)}"
    assert len(done) == 2
    for c in done:  # one latency per GENERATED token, every completion
        assert len(c.token_latencies_s) == len(c.tokens) - c.prompt_len, c
        assert all(dt > 0 for dt in c.token_latencies_s)


# ---------------------------------------------------------------- parallel


@pytest.mark.parametrize(
    "mesh_kw",
    [dict(data=1, model=8), dict(data=4, model=2)],
    ids=["model_only", "data_x_model"],
)
def test_sharded_decode_matches_replicated(gpt, mesh_kw):
    """Head-sharded serving == replicated serving, generate() AND the
    engine, on the two acceptance meshes."""
    model, params, tokens = gpt
    ref = generate(model, params, tokens, max_new_tokens=5, temperature=0.0)
    prompt = np.asarray(tokens[0], np.int32)
    eng_ref = ServingEngine(model, params, num_slots=2, temperature=0.0)
    rid = eng_ref.submit(prompt, 4)
    solo_ref = {c.id: c for c in eng_ref.run()}[rid]

    env = build_mesh(MeshConfig(**mesh_kw))
    with mesh_context(env):
        sharded = _shard(params, env)
        out = generate(
            model, sharded, tokens, max_new_tokens=5, temperature=0.0
        )
        eng = ServingEngine(model, sharded, num_slots=2, temperature=0.0)
        rid2 = eng.submit(prompt, 4)
        solo = {c.id: c for c in eng.run()}[rid2]
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    np.testing.assert_array_equal(solo_ref.tokens, solo.tokens)


def test_prefill_emits_model_sharded_cache_no_reshard_pin(gpt):
    """The handoff pin (tp_overlap pin style): under a model-axis mesh,
    (i) prefill EMITS the KV cache head-sharded over ``model`` — no
    post-hoc resharding; (ii) the compiled decode step contains no
    all-gather of a cache-shaped array (the only gathers legal in the
    step are logit-sized); (iii) the decode step's cache output shardings
    equal its inputs' — the layout is a fixed point of the step."""
    model, params, _ = gpt
    # model=4 so the 4 heads split exactly (h % model == 0 is the
    # shard_map head-sharding contract; an indivisible mesh legally falls
    # back to GSPMD's own split).
    env = build_mesh(MeshConfig(data=2, model=4))
    tp_m = 4
    bucket = 16
    m = model.clone(cache_len=bucket)
    tokens = jax.random.randint(jax.random.key(5), (2, 8), 0, 64)

    with mesh_context(env):
        sharded = _shard(params, env)

        @jax.jit
        def prefill(params, toks):
            logits, vo = m.apply(
                {"params": params}, toks, decode=True, mutable=["cache"]
            )
            return logits, vo["cache"]

        _, cache = prefill(sharded, tokens)
        kv = cache["blocks"]["attn"]["cached_key"]  # [L, B, S, H, hd]
        # The jit output sharding may surface as GSPMDSharding (no .spec);
        # the per-device shard geometry is the layout fact that matters:
        # the heads axis must be SPLIT over the 8-way model axis.
        shard = kv.sharding.shard_shape(kv.shape)
        h = model.config.num_heads
        assert shard[3] == h // tp_m, (
            f"prefill cache not head-sharded: global {kv.shape}, "
            f"per-device {shard}"
        )

        @jax.jit
        def step(params, cache, tok):
            logits, vo = m.apply(
                {"params": params, "cache": cache}, tok, decode=True,
                mutable=["cache"],
            )
            return logits, vo["cache"]

        tok = jnp.zeros((2, 1), jnp.int32)
        compiled = step.lower(sharded, cache, tok).compile()
        _, cache2 = step(sharded, cache, tok)
        kv2 = cache2["blocks"]["attn"]["cached_key"]
        assert kv2.sharding.shard_shape(kv2.shape) == shard, (
            "decode step changed the cache layout: "
            f"{shard} -> {kv2.sharding.shard_shape(kv2.shape)}"
        )

    # HLO pin (analysis.pins.assert_reshard_free): no all-gather whose
    # result carries the cache's [S, H] (or sharded-H) geometry — a
    # monolithic reshard of the cache would have to materialize one.
    cache_sigs = set()
    l, b = model.config.num_layers, tokens.shape[0]
    h, hd = model.config.num_heads, TINY["hidden_dim"] // model.config.num_heads
    for hh in {h, h // tp_m or 1}:
        for bb in {b, b // 2 or 1}:
            cache_sigs.add((l, bb, bucket, hh, hd))
            cache_sigs.add((bb, bucket, hh, hd))
    pins.assert_reshard_free(compiled, cache_sigs, ops=("all-gather",))


@pytest.mark.fast
def test_decode_step_donates_and_aliases_cache(gpt):
    """The PR 5 donation-audit fix, pinned: the engine's compiled decode
    step donates its KV cache input and the executable actually aliases
    the cache buffers in/out — without it every decode step transiently
    holds TWO caches live (the allocation spike slot counts are sized
    against).  Checked at both levels graft-lint audits: donation markers
    in the lowered module, alias table in the compiled executable."""
    model, params, _ = gpt
    eng = ServingEngine(model, params, num_slots=2, temperature=0.0)
    rid = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=4)
    completed = list(eng.step())  # builds cache + decode program
    bucket = eng.bucket
    cache = eng.cache
    tok = jnp.zeros((eng.num_slots,), jnp.int32)
    rng = jax.random.key(0)
    lowered = eng._decode_fn(bucket).lower(params, cache, tok, rng)

    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
    )

    n_cache = len(jax.tree.leaves(cache))
    pairs = args_info_donations(lowered)
    assert sum(1 for _, d in pairs if d) >= n_cache, pairs
    # Every cache leaf (arg 1) is donated; params (arg 0) are NOT.
    # (args_info paths are rooted at the (args, kwargs) pair: "[0][k]...")
    for p, d in pairs:
        if p.startswith("[0][1]"):
            assert d, f"cache leaf {p} not donated"
        if p.startswith("[0][0]"):
            assert not d, f"param leaf {p} unexpectedly donated"

    # Compiled ground truth: the alias table carries >= n_cache entries.
    pins.assert_aliased(lowered.compile(), min_aliases=n_cache)

    # The graft program donates the engine cache (arg 0) the same way.
    g_lowered = eng._graft_fn(bucket, bucket).lower(
        cache, jax.tree.map(
            lambda x: jnp.zeros((x.shape[0], 1) + x.shape[2:], x.dtype)
            if x.ndim >= 2 else jnp.zeros((1,), x.dtype),
            cache,
        ),
        jnp.int32(0),
    )
    g_pairs = args_info_donations(g_lowered)
    for p, d in g_pairs:
        if p.startswith("[0][0]"):
            assert d, f"graft engine-cache leaf {p} not donated"
    # Engine still serves correctly with donation on (end-to-end).
    done = {c.id: c for c in completed + eng.run()}
    assert rid in done


# --------------------------------------------------------- quantized cache


@pytest.fixture(scope="module")
def gpt_int8(gpt):
    model, params, tokens = gpt
    mq = GPT(
        dataclasses.replace(model.config, kv_cache_quant="int8"), FP32
    )
    return mq, params, tokens


@pytest.mark.fast
def test_engine_int8_cache_matches_quantized_generate(gpt_int8):
    """Continuous batching over the int8 cache: every request through
    slot reuse must equal its own quantized-generate() run token-for-
    token (the engine and generate share the decode entry, and the
    scale leaves ride the same graft/grow taxonomy as the K/V stacks —
    a scale leaf left behind by a graft would diverge here)."""
    model, params, _ = gpt_int8
    rng = np.random.default_rng(11)
    eng = ServingEngine(model, params, num_slots=3, temperature=0.0)
    reqs = {}
    for _ in range(7):
        l = int(rng.integers(2, 12))
        prompt = rng.integers(0, 64, size=l).astype(np.int32)
        n_new = int(rng.integers(2, 9))
        reqs[eng.submit(prompt, n_new)] = (prompt, n_new)
    done = {c.id: c for c in eng.run()}
    assert sorted(done) == sorted(reqs)
    for rid, (prompt, n_new) in reqs.items():
        ref = generate(
            model, params, jnp.asarray(prompt)[None],
            max_new_tokens=n_new, temperature=0.0,
        )
        np.testing.assert_array_equal(
            done[rid].tokens, np.asarray(ref)[0],
            err_msg=f"request {rid} diverged from its solo generate()",
        )


@pytest.mark.fast
def test_engine_bytes_per_slot_accounts_for_scales(gpt, gpt_int8):
    """The satellite-6 regression: bucket HBM accounting must include
    the scale tensors. The engine's measured bytes-per-slot equals the
    analytic estimate EXACTLY for both cache flavors (a model growing a
    cache leaf the estimate doesn't know fails here), the int8 estimate
    is strictly larger than payload-only accounting (scales are not
    free), and the bf16-reference ratio clears the >= 1.8x concurrent-
    slots acceptance bar at this geometry."""
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        estimate_cache_bytes_per_slot,
    )

    results = {}
    for name, (model, params, _) in (("none", gpt), ("int8", gpt_int8)):
        eng = ServingEngine(model, params, num_slots=2, temperature=0.0)
        eng.submit(np.arange(5, dtype=np.int32), 3)
        eng.run()
        est = estimate_cache_bytes_per_slot(
            model.config, eng.bucket, kv_dtype_bytes=4  # fp32 sim cache
        )
        assert eng.bytes_per_slot() == est, (name, eng.bytes_per_slot(), est)
        results[name] = (model.config, eng.bucket)

    cfg_q, bucket = results["int8"]
    h, hd = cfg_q.num_heads, cfg_q.hidden_dim // cfg_q.num_heads
    payload_only = cfg_q.num_layers * (2 * bucket * h * hd + 4) + 4
    est_q = estimate_cache_bytes_per_slot(cfg_q, bucket)
    assert est_q > payload_only, "scale bytes missing from the estimate"
    # The >= 1.8x acceptance ratio holds at REAL serving geometry (the
    # scale overhead is 2/head_dim of the payload: head_dim 64 gives
    # 128/(64+2) ≈ 1.94x; the deliberately tiny head_dim-16 fixture
    # above sits at 1.78x — which is exactly why the accounting must
    # include scales instead of advertising a flat 2x).
    flagship = GPTConfig(kv_cache_quant="int8")  # gpt2-medium defaults
    est_q_med = estimate_cache_bytes_per_slot(flagship, 1024)
    est_bf16_med = estimate_cache_bytes_per_slot(
        GPTConfig(), 1024, kv_dtype_bytes=2
    )
    assert est_bf16_med >= 1.8 * est_q_med, (est_bf16_med, est_q_med)


@pytest.mark.parametrize(
    "mesh_kw",
    [dict(data=1, model=8), dict(data=4, model=2)],
    ids=["model_only", "data_x_model"],
)
def test_sharded_int8_decode_matches_replicated(gpt_int8, mesh_kw):
    """Head-sharded int8-KV serving == replicated int8-KV serving on the
    acceptance meshes: the scale arrays shard like the cache (heads over
    ``model``) and the handoff stays layout-stable."""
    model, params, tokens = gpt_int8
    ref = generate(model, params, tokens, max_new_tokens=5, temperature=0.0)
    env = build_mesh(MeshConfig(**mesh_kw))
    with mesh_context(env):
        sharded = _shard(params, env)
        out = generate(
            model, sharded, tokens, max_new_tokens=5, temperature=0.0
        )
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


# ----------------------------------------------------- paged (block) cache


def _paged_vs_generate(model, params, bs, reqs, num_slots=2, **eng_kw):
    """Serve ``reqs`` [(prompt, n_new)] through a paged engine and assert
    every completion equals its own solo generate() run."""
    eng = ServingEngine(
        model, params, num_slots=num_slots, temperature=0.0,
        kv_block_size=bs, **eng_kw,
    )
    ids = {eng.submit(p, n): (p, n) for p, n in reqs}
    done = {c.id: c for c in eng.run()}
    assert sorted(done) == sorted(ids), "not every request completed"
    for rid, (prompt, n_new) in ids.items():
        ref = generate(
            model, params, jnp.asarray(prompt)[None], max_new_tokens=n_new,
            temperature=0.0,
        )
        np.testing.assert_array_equal(
            done[rid].tokens, np.asarray(ref)[0],
            err_msg=f"request {rid} diverged from its solo generate()",
        )
    return eng, done


def test_paged_engine_matches_generate_with_block_append(gpt):
    """The paged acceptance core: continuous batching over the block
    pool is token-identical to generate(), INCLUDING a mid-decode block
    append (growth = one table write, never a cache clone — the stats
    prove an append actually happened and that no bucket grow ran)."""
    model, params, _ = gpt
    rng = np.random.default_rng(3)
    reqs = [
        # 3-token prompt + 14 new tokens crosses the first 8-block
        # boundary mid-decode (alloc covers position 3; appends follow).
        (np.arange(3, dtype=np.int32), 14),
        (rng.integers(0, 64, size=9).astype(np.int32), 5),
        (rng.integers(0, 64, size=2).astype(np.int32), 8),
    ]
    eng, done = _paged_vs_generate(model, params, 8, reqs)
    assert eng.stats["block_append"] > 0, dict(eng.stats)
    assert eng.stats["decode_paged"] > 0
    assert not any(k.startswith("grow_") for k in eng.stats), (
        "paged engine ran a bucket grow — growth must append blocks"
    )
    # Every block released at retirement except prefix-cache-held ones;
    # reservations fully unwound.
    assert eng._reserved_future == 0
    assert all(not b for b in eng._slot_blocks)
    eng.close()


@pytest.mark.parametrize("fmt", ["int8", "fp8_e4m3"])
def test_paged_engine_token_identical_across_block_sizes_and_formats(
    gpt, fmt
):
    """The satellite grid: paged engine == quantized generate() per
    request across block sizes, for each quantized KV format (the scale
    pools ride the same block taxonomy as the K/V pools — a scale block
    left behind by a graft or append diverges here)."""
    model, params, _ = gpt
    mq = GPT(dataclasses.replace(model.config, kv_cache_quant=fmt), FP32)
    rng = np.random.default_rng(13)
    for bs in (4, 16):
        reqs = [
            (rng.integers(0, 64, size=int(rng.integers(2, 12))).astype(np.int32),
             int(rng.integers(2, 9)))
            for _ in range(4)
        ]
        # One request always crosses a block boundary mid-decode.
        reqs.append((np.arange(2, dtype=np.int32), bs + 4))
        eng, _ = _paged_vs_generate(mq, params, bs, reqs, num_slots=3)
        assert eng.stats["block_append"] > 0, (fmt, bs, dict(eng.stats))
        eng.close()


def test_paged_prefix_sharing_cow_and_retire_orders(gpt):
    """Shared-prefix caching end-to-end: requests sharing a system
    prompt prefill once per UNIQUE prefix (full-block granularity, the
    divergent partial block re-derived privately = copy-on-write), stay
    token-identical to generate(), survive retiring in a different
    order than they were admitted, and keep serving hits after every
    original holder retired (the refcounted cache outlives the slots)."""
    model, params, _ = gpt
    rng = np.random.default_rng(17)
    bs = 8
    # 20-token prefix = 2 full blocks + a 4-token partial (the COW
    # block: B re-derives it privately, so A's copy is never written).
    pre = rng.integers(0, 64, size=20).astype(np.int32)
    # Tails sized so every prompt spans 3 FULL blocks (l in 24..26): each
    # request then registers its own divergent 3-block chain on top of
    # the shared 2-block one — the COW assertion below needs them.
    tails = [rng.integers(0, 64, size=n).astype(np.int32) for n in (4, 5, 6)]
    # Different budgets force retirement in a different order than
    # admission (A longest, C shortest).
    reqs = [
        (np.concatenate([pre, tails[0]]), 12),
        (np.concatenate([pre, tails[1]]), 3),
        (np.concatenate([pre, tails[2]]), 7),
    ]
    eng, done = _paged_vs_generate(model, params, bs, reqs, num_slots=3)
    comps = [done[i] for i in sorted(done)]
    # First admission misses; both followers hit the 2-block chain.
    assert [c.prefix_cache_hit for c in comps] == [False, True, True]
    assert [c.prefill_tokens_saved for c in comps] == [0, 16, 16]
    assert eng.stats["prefix_hits"] == 2
    assert eng.stats["prefill_tokens_saved"] == 32
    # Retirement order differed from admission order (budgets 12/3/7).
    assert eng.stats["block_append"] >= 0  # appends allowed, not required
    # After every holder retired, the chain still serves: a fourth
    # request with the same prefix hits without any live slot holding it.
    p4 = np.concatenate([pre, rng.integers(0, 64, size=4).astype(np.int32)])
    rid4 = eng.submit(p4, 4)
    done4 = {c.id: c for c in eng.run()}[rid4]
    assert done4.prefix_cache_hit and done4.prefill_tokens_saved == 16
    ref = generate(
        model, params, jnp.asarray(p4)[None], max_new_tokens=4,
        temperature=0.0,
    )
    np.testing.assert_array_equal(done4.tokens, np.asarray(ref)[0])
    # COW invariant at the allocator level: the SHARED chain (keyed by
    # the common 2-full-block prefix) is exactly 2 blocks — the partial
    # third block was never shared; each request's own longer chains
    # diverge at the key (they embed the private COW block's tokens),
    # so no other prompt can ever match into a divergent block.
    shared_chain = eng._prefix_cache[pre[:16].tobytes()]
    assert len(shared_chain) == 2, shared_chain
    third_blocks = {
        ids[2]
        for key, ids in eng._prefix_cache.items()
        if len(ids) >= 3 and key.startswith(pre[:16].tobytes())
    }
    assert len(third_blocks) >= 2, (
        "divergent requests share a third block — COW violated"
    )
    eng.close()


def test_paged_pool_exhaustion_defers_then_sheds(gpt):
    """Admission is priced in pool headroom: with a pool sized for ~one
    request, later submits WAIT at the queue head (admission_deferred)
    and — with bounded admission — the overflow sheds typed. Every id
    still resolves exactly once, and the tiny pool serves the whole
    backlog correctly as slots retire and release blocks."""
    model, params, _ = gpt
    # 4 usable blocks of 8 = two 9-token+6-new requests (2 blocks each):
    # with 3 slots, the third admission finds a free SLOT but no pool
    # headroom — the deferral under test.
    eng = ServingEngine(
        model, params, num_slots=3, temperature=0.0,
        kv_block_size=8, kv_pool_blocks=5, max_queue_depth=3,
    )
    with pytest.raises(ValueError, match="KV blocks"):
        eng.submit(np.arange(40, dtype=np.int32), 10)  # can never fit
    reqs = {}
    shed = []
    for i in range(5):
        prompt = ((np.arange(9) + 3 * i) % 64).astype(np.int32)
        rid = eng.submit(prompt, 6)
        reqs[rid] = (prompt, 6)
    done = {c.id: c for c in eng.run()}
    assert sorted(done) == sorted(reqs)
    by_reason = {}
    for c in done.values():
        by_reason[c.finish_reason] = by_reason.get(c.finish_reason, 0) + 1
    assert by_reason.get("shed", 0) >= 1, by_reason
    assert eng.stats["admission_deferred"] > 0, dict(eng.stats)
    for rid, c in done.items():
        if not c.ok:
            continue
        prompt, n_new = reqs[rid]
        ref = generate(
            model, params, jnp.asarray(prompt)[None],
            max_new_tokens=n_new, temperature=0.0,
        )
        np.testing.assert_array_equal(c.tokens, np.asarray(ref)[0])
    eng.close()


def test_paged_pool_bytes_accounting(gpt, gpt_int8):
    """Paged capacity math honesty: the measured per-block bytes of the
    LIVE pool equal the analytic estimate exactly for both cache
    flavors (scale pools included — a pool leaf the estimate doesn't
    know fails here), mirroring the bucketed bytes-per-slot pin."""
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        estimate_pool_block_bytes,
    )

    for name, (model, params, _) in (("none", gpt), ("int8", gpt_int8)):
        eng = ServingEngine(
            model, params, num_slots=2, temperature=0.0, kv_block_size=8
        )
        # 9-token prompt: one FULL block registers in the prefix cache,
        # so utilization stays > 0 after retirement (cache-held block).
        eng.submit(np.arange(9, dtype=np.int32), 3)
        eng.run()
        est = estimate_pool_block_bytes(
            model.config, 8, kv_dtype_bytes=4  # fp32 sim cache
        )
        assert eng.block_bytes() == est, (name, eng.block_bytes(), est)
        assert eng.bytes_per_slot() > 0
        assert 0.0 < eng.pool_utilization() <= 1.0
        assert eng.stats["pool_peak_blocks"] >= 2
        eng.close()


@pytest.mark.parametrize(
    "mesh_kw",
    [dict(data=1, model=8), dict(data=4, model=2)],
    ids=["model_only", "data_x_model"],
)
def test_paged_sharded_matches_replicated(gpt, mesh_kw):
    """Head-sharded paged serving == replicated paged serving on the
    acceptance meshes: the pools shard over heads only (never batch —
    blocks are shared across rows), tables/lengths ride the batch axes."""
    model, params, tokens = gpt
    prompt = np.asarray(tokens[0], np.int32)
    eng_ref = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    rid = eng_ref.submit(prompt, 4)
    ref = {c.id: c for c in eng_ref.run()}[rid]
    eng_ref.close()
    env = build_mesh(MeshConfig(**mesh_kw))
    with mesh_context(env):
        sharded = _shard(params, env)
        eng = ServingEngine(
            model, sharded, num_slots=2, temperature=0.0, kv_block_size=8
        )
        rid2 = eng.submit(prompt, 4)
        out = {c.id: c for c in eng.run()}[rid2]
        eng.close()
    np.testing.assert_array_equal(ref.tokens, out.tokens)


def test_paged_prefix_hit_with_overflowing_suffix_bucket(gpt):
    """Regression (review find): a prefix hit whose seeded write window
    overruns the slot-cache capacity — prefix m*bs + suffix bucket s_p >
    cache bucket s_c (e.g. 16-token prefix + 48-token suffix in a
    64-bucket) — must still be token-identical to the cold path. The
    suffix prefill's trailing wrapped-pad garbage columns land past the
    capacity and must be DROPPED; clipping them piled every one onto
    position s_c - 1, clobbering the last real prompt token's K/V."""
    model, _, _ = gpt
    # seq_len=64 can't host l=64 + new tokens; build a 128-context twin
    # (its wpe is context-sized, so it needs its own params).
    big_model = GPT(
        dataclasses.replace(model.config, seq_len=128), FP32
    )
    params = jit_init(
        big_model, jax.random.randint(jax.random.key(2), (2, 8), 0, 64),
        train=False,
    )["params"]
    rng = np.random.default_rng(23)
    bs = 16
    pre = rng.integers(0, 64, size=bs).astype(np.int32)
    warm = np.concatenate([pre, rng.integers(0, 64, size=4).astype(np.int32)])
    # l = 64: l_suf = 48 -> s_p = 64 while s_c = bucket(64) = 64, so the
    # seeded writes span positions 16..79 — 16 columns past capacity.
    big = np.concatenate([pre, rng.integers(0, 64, size=48).astype(np.int32)])
    eng = ServingEngine(
        big_model, params, num_slots=2, temperature=0.0, kv_block_size=bs
    )
    eng.submit(warm, 4)
    eng.run()
    rid = eng.submit(big, 5)
    done = {c.id: c for c in eng.run()}[rid]
    assert done.prefix_cache_hit and done.prefill_tokens_saved == bs
    ref = generate(
        big_model, params, jnp.asarray(big)[None], max_new_tokens=5,
        temperature=0.0,
    )
    np.testing.assert_array_equal(done.tokens, np.asarray(ref)[0])
    eng.close()


@pytest.mark.fast
def test_paged_decode_step_donates_pool(gpt):
    """The donation pin at POOL scale: the paged engine's one compiled
    decode program donates every cache leaf (pool included) and the
    executable aliases the buffers — without it each step holds two
    POOLS live, a far bigger spike than the bucketed double-cache."""
    model, params, _ = gpt
    eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    eng.submit(np.arange(5, dtype=np.int32), 3)
    completed = list(eng.step())
    cache = eng.cache
    tok = jnp.zeros((eng.num_slots,), jnp.int32)
    lowered = eng._paged_decode_fn().lower(
        params, cache, tok, jax.random.key(0)
    )

    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
    )

    n_cache = len(jax.tree.leaves(cache))
    pairs = args_info_donations(lowered)
    for p, d in pairs:
        if p.startswith("[0][1]"):
            assert d, f"paged cache leaf {p} not donated"
        if p.startswith("[0][0]"):
            assert not d, f"param leaf {p} unexpectedly donated"
    pins.assert_aliased(lowered.compile(), min_aliases=n_cache)
    done = {c.id: c for c in completed + eng.run()}
    assert done
    eng.close()


# ------------------------------------------------- speculative decoding


@pytest.fixture(scope="module")
def gpt_draft(gpt):
    """A 1-layer draft GPT sharing the target's tokenizer (tier B)."""
    model, _, _ = gpt
    dcfg = dataclasses.replace(
        model.config, num_layers=1, num_heads=2, hidden_dim=32
    )
    draft = GPT(dcfg, FP32)
    tokens = jax.random.randint(jax.random.key(9), (2, 8), 0, 64)
    dparams = jit_init(draft, tokens, train=False)["params"]
    return draft, dparams


_ACCEPTING_CACHE: dict[tuple, np.ndarray] = {}


def _accepting_prompt(model, params, k: int = 4) -> np.ndarray:
    """A prompt whose greedy continuation ACCEPTS n-gram drafts: probe a
    few seeds of the model's own greedy text and keep the one whose
    simulated tier-A acceptance scores highest. Derived at runtime
    because the fixture's params — and hence the model's greedy cycles
    — depend on the ambient ``jax_threefry_partitionable`` state, which
    earlier mesh-building tests flip; a hardcoded "repetitive" pattern
    is only repetitive under one variant. Deterministic for whichever
    variant is active (greedy decode + fixed probe seeds)."""
    key = (id(params), getattr(model.config, "kv_cache_quant", "none"))
    if key in _ACCEPTING_CACHE:
        return _ACCEPTING_CACHE[key]
    import os as _os
    import sys as _sys

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in _sys.path:
        _sys.path.insert(0, tools)
    from serve_bench import _simulate_ngram_serving

    rng = np.random.default_rng(0)
    best = None
    for _ in range(8):
        s = rng.integers(0, 64, size=6).astype(np.int32)
        full = np.asarray(
            generate(
                model, params, jnp.asarray(s)[None], max_new_tokens=30,
                temperature=0.0,
            )
        )[0].astype(np.int32)
        prompt, cont = full[:20], full[20:]
        toks, ver = _simulate_ngram_serving(prompt, cont, k)
        score = toks / max(ver, 1)
        if best is None or score > best[0]:
            best = (score, prompt)
        if score >= 2.5:
            break
    assert best[0] > 1.0, (
        f"no probed continuation accepts any drafts (best {best[0]})"
    )
    _ACCEPTING_CACHE[key] = best[1]
    return best[1]


def _spec_reqs(rng, bs, model, params):
    """Mixed speculative workload: a draft-accepting prompt (the model's
    own repetitive text — high acceptance), a random prompt (high
    rejection -> rollback), and a short prompt whose budget crosses a
    block boundary MID-DECODE."""
    return [
        (_accepting_prompt(model, params), bs + 6),
        (rng.integers(0, 64, size=9).astype(np.int32), 6),
        (np.arange(2, dtype=np.int32), bs + 4),
    ]


@pytest.mark.fast
def test_ngram_propose_unit():
    """Tier-A proposer semantics: a periodic history proposes its own
    continuation (full k even when the most recent overlapping match
    truncates), a fresh history proposes nothing, and the continuation
    never exceeds k."""
    from frl_distributed_ml_scaffold_tpu.serving.engine import ngram_propose

    cyc = np.asarray([3, 9, 4, 3, 9, 4, 3, 9, 4], np.int64)
    d = ngram_propose(cyc, 4)
    np.testing.assert_array_equal(d, [3, 9, 4, 3])  # the periodic draft
    const = np.full(8, 5, np.int64)
    np.testing.assert_array_equal(ngram_propose(const, 3), [5, 5, 5])
    fresh = np.arange(10)  # no repeated n-gram anywhere
    assert ngram_propose(fresh, 4).size == 0
    assert ngram_propose(cyc, 2).size == 2
    assert ngram_propose(np.asarray([1]), 4).size == 0


def test_spec_ngram_token_identical_grid(gpt):
    """THE speculative acceptance core (ISSUE 11): greedy speculative
    decode == generate() token-for-token across block sizes, on a mixed
    batch where some slots speculate (repetitive prompt, high accept)
    and some effectively single-step (random prompts, rejected drafts
    -> rollback, including across a block boundary). Verify steps and
    block rollbacks must actually have happened, and every reservation
    unwinds."""
    model, params, _ = gpt
    rng = np.random.default_rng(31)
    for bs in (4, 16):
        eng, done = _paged_vs_generate(
            model, params, bs, _spec_reqs(rng, bs, model, params),
            num_slots=3, speculate="ngram", speculate_k=4,
        )
        assert eng.stats["decode_verify"] > 0, dict(eng.stats)
        assert eng.stats["spec_proposed"] > 0
        # Acceptance happened (the accepting prompt) — the deterministic
        # every-draft-rejected rollback-ACROSS-a-boundary case lives in
        # the draft test below.
        assert 0 < eng.stats["spec_accepted"] <= eng.stats["spec_proposed"]
        assert eng.stats["spec_emitted"] >= eng.stats["spec_slot_verifies"]
        assert eng._reserved_future == 0
        assert all(not b for b in eng._slot_blocks)
        eng.close()


@pytest.mark.parametrize("fmt", ["int8", "fp8_e4m3"])
def test_spec_token_identical_quantized_pools(gpt, fmt):
    """The acceptance grid's quantized column: speculative decode over
    int8/fp8 pools (verify tile quantizes once per written position,
    scale pools ride the same table indirection) stays token-identical
    to the quantized generate()."""
    model, params, _ = gpt
    mq = GPT(dataclasses.replace(model.config, kv_cache_quant=fmt), FP32)
    rng = np.random.default_rng(37)
    eng, _ = _paged_vs_generate(
        mq, params, 8, _spec_reqs(rng, 8, mq, params), num_slots=3,
        speculate="ngram", speculate_k=4,
    )
    assert eng.stats["decode_verify"] > 0, (fmt, dict(eng.stats))
    eng.close()


def test_spec_draft_token_identical_and_windowed(gpt, gpt_draft):
    """Tier B: a (random, hence mostly-rejected) draft model proposes
    through the windowed batched propose program; output is still
    token-identical — acceptance is exact, drafting is advisory — and
    the constant full-k rejections force the rollback-ACROSS-a-block-
    boundary acceptance case: draft positions straddling a boundary
    append a block before the verify, rejection pops it back to the
    free list (block_rollback > 0), and every reservation unwinds."""
    model, params, _ = gpt
    draft, dparams = gpt_draft
    rng = np.random.default_rng(41)
    eng, done = _paged_vs_generate(
        model, params, 8, _spec_reqs(rng, 8, model, params), num_slots=3,
        speculate="draft", speculate_k=3,
        draft_model=draft, draft_params=dparams,
    )
    assert eng.stats["decode_verify"] > 0
    assert eng.stats["spec_proposed"] > 0
    assert eng.stats["block_rollback"] > 0, dict(eng.stats)
    assert eng._reserved_future == 0
    assert all(not b for b in eng._slot_blocks)
    # Per-request SLO column: rates are well-formed fractions.
    for c in done.values():
        assert 0.0 <= c.spec_accept_rate <= 1.0
    eng.close()


def test_spec_rollback_returns_blocks_to_pool(gpt):
    """The rollback acceptance pin: after every request retires, pool
    utilization returns to baseline — EXACTLY zero with the prefix
    cache off (every block the verify steps ever appended, including
    rejected-draft tails, is back on the free list) — and the
    utilization gauge agrees."""
    model, params, _ = gpt
    rng = np.random.default_rng(43)
    eng = ServingEngine(
        model, params, num_slots=3, temperature=0.0, kv_block_size=4,
        prefix_cache=False, speculate="ngram", speculate_k=4,
    )
    for p, n in _spec_reqs(rng, 4, model, params):
        eng.submit(p, n)
    done = eng.run()
    assert len(done) == 3
    assert eng.stats["decode_verify"] > 0
    assert eng.pool_utilization() == 0.0, dict(eng.stats)
    assert len(eng._free) == eng.pool_blocks - 1
    assert eng._reserved_future == 0
    assert (eng._ref == 0).all()
    snap = eng.telemetry.snapshot()
    assert snap["serve_pool_utilization"] == 0.0
    eng.close()


@pytest.mark.fast
def test_spec_verify_compiles_once_and_donates_pool(gpt):
    """No per-k ladder: the verify program object is constructed once
    and reused for every verify step regardless of how many drafts each
    slot carries; and it donates every cache leaf (pool included) with
    the executable aliasing the buffers — the decode-program audit at
    tile width."""
    model, params, _ = gpt
    eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8,
        speculate="ngram", speculate_k=3,
    )
    fn_a = eng._verify_fn()
    assert eng._verify_fn() is fn_a, "verify program rebuilt per call"
    eng.submit(np.tile(np.asarray([3, 9], np.int32), 6), 10)
    eng.submit(np.arange(5, dtype=np.int32), 4)
    done = eng.run()
    assert len(done) == 2 and eng.stats["decode_verify"] > 0
    assert eng._verify_fn() is fn_a, "verify program rebuilt mid-serve"

    cache = eng.cache
    tile = jnp.zeros((eng.num_slots, eng.spec_k + 1), jnp.int32)
    lowered = fn_a.lower(params, cache, tile)
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
    )

    n_cache = len(jax.tree.leaves(cache))
    for p, d in args_info_donations(lowered):
        if p.startswith("[0][1]"):
            assert d, f"verify cache leaf {p} not donated"
        if p.startswith("[0][0]"):
            assert not d, f"param leaf {p} unexpectedly donated"
    pins.assert_aliased(lowered.compile(), min_aliases=n_cache)
    eng.close()


@pytest.mark.fast
def test_spec_eos_mid_group_truncates(gpt):
    """A group whose accepted drafts contain eos retires AT the eos
    (tokens after it are discarded — speculation must not overshoot the
    engine's eos-retirement contract)."""
    model, params, _ = gpt
    p = np.tile(np.asarray([7, 11, 13, 5], np.int32), 5)
    ref = np.asarray(
        generate(model, params, jnp.asarray(p)[None], max_new_tokens=12,
                 temperature=0.0)
    )[0]
    # Choose eos = a token greedy emits mid-stream (position 4 of 12).
    eos = int(ref[p.size + 4])
    first = int(np.flatnonzero(ref[p.size:] == eos)[0])
    eng = ServingEngine(
        model, params, num_slots=1, temperature=0.0, eos_id=eos,
        kv_block_size=8, speculate="ngram", speculate_k=4,
    )
    rid = eng.submit(p, 12)
    done = {c.id: c for c in eng.run()}[rid]
    assert done.finish_reason == "eos"
    assert len(done.tokens) == p.size + first + 1, (
        len(done.tokens), p.size, first
    )
    np.testing.assert_array_equal(
        done.tokens, ref[: p.size + first + 1]
    )
    eng.close()


@pytest.mark.fast
def test_spec_knob_refusals(gpt, gpt_draft):
    """Guard rails: speculate needs the paged cache and greedy decode;
    draft tier needs a draft model with the same tokenizer; k >= 1;
    config-and-scalars double-specification refused."""
    from frl_distributed_ml_scaffold_tpu.config.schema import ServingConfig

    model, params, _ = gpt
    draft, dparams = gpt_draft
    with pytest.raises(ValueError, match="PAGED"):
        ServingEngine(model, params, num_slots=1, speculate="ngram")
    with pytest.raises(ValueError, match="greedy"):
        ServingEngine(
            model, params, num_slots=1, kv_block_size=8,
            speculate="ngram", speculate_k=2, temperature=0.5,
        )
    with pytest.raises(ValueError, match="draft_model"):
        ServingEngine(
            model, params, num_slots=1, kv_block_size=8,
            speculate="draft", speculate_k=2,
        )
    with pytest.raises(ValueError, match="speculate_k"):
        ServingEngine(
            model, params, num_slots=1, kv_block_size=8,
            speculate="ngram", speculate_k=0,
        )
    with pytest.raises(ValueError, match="unknown"):
        ServingEngine(
            model, params, num_slots=1, kv_block_size=8,
            speculate="medusa", speculate_k=2,
        )
    bad_draft = GPT(
        dataclasses.replace(draft.config, vocab_size=32), FP32
    )
    with pytest.raises(ValueError, match="tokenizer"):
        ServingEngine(
            model, params, num_slots=1, kv_block_size=8,
            speculate="draft", speculate_k=2,
            draft_model=bad_draft, draft_params=dparams,
        )
    with pytest.raises(ValueError, match="not both"):
        ServingEngine(
            model, params, num_slots=1,
            serving=ServingConfig(kv_block_size=8, speculate="ngram"),
            speculate_k=3,
        )


def test_spec_telemetry_counters_and_slo_columns(gpt):
    """The telemetry satellite: spec counters live in the catalog (and
    move), the accepted-per-verify histogram counts exactly the
    speculating slot-verifies on the shared log2 ladder, and the
    aggregate counters reconcile with the engine stats and with the
    per-request Completion.spec_accept_rate columns."""
    model, params, _ = gpt
    eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8,
        speculate="ngram", speculate_k=4,
    )
    rid_rep = eng.submit(_accepting_prompt(model, params), 10)
    rid_rand = eng.submit(
        np.random.default_rng(3).integers(0, 64, size=7).astype(np.int32), 5
    )
    done = {c.id: c for c in eng.run()}
    snap = eng.telemetry.snapshot()
    assert snap["serve_spec_proposed_total"] == eng.stats["spec_proposed"] > 0
    assert snap["serve_spec_accepted_total"] == eng.stats["spec_accepted"]
    assert snap["serve_spec_verify_total"] == eng.stats["decode_verify"] > 0
    h = snap["serve_spec_accepted_per_verify"]
    assert h["count"] == eng.stats["spec_slot_verifies"] > 0
    # The histogram's total mass equals emitted tokens (sum over
    # observations of tokens-per-verify) — log2 buckets, exact values
    # 1/2/4 land on bucket bounds, so check via the stats ledger.
    assert eng.stats["spec_emitted"] >= eng.stats["spec_slot_verifies"]
    # Per-request SLO columns: the accepting prompt actually accepted.
    assert done[rid_rep].spec_accept_rate > 0.0
    assert 0.0 <= done[rid_rand].spec_accept_rate <= 1.0
    eng.close()


# ------------------------------------------------------------------- bench


def test_serve_bench_runs_and_emits_schema_valid_row(capsys):
    """tools/serve_bench.py end-to-end on CPU sim: continuous batching
    completes every request (more requests than slots, so retired slots
    are refilled and the refilled requests finish) and the emitted row
    meets the BENCH_TABLE measured-row schema (the test_bench.py
    contract: config + mesh + per-sample FLOPs + MFU + provenance)."""
    import json

    sys_path_mod = __import__("sys")
    import os as _os

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in sys_path_mod.path:
        sys_path_mod.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            "--preset", "tiny", "--requests", "5", "--slots", "2",
            "--max-new", "4", "--sim-devices", "0",
            "--arms", "dense_replicated,flash_sharded",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines()
        if l.startswith("{")
    ]
    assert len(lines) == 2, lines
    for line in lines:
        row = json.loads(line)
        for key in ("config", "samples_per_sec_per_chip", "mesh",
                    "model_flops_per_sample", "mfu"):
            assert key in row, f"row missing {key}"
        assert isinstance(row["mesh"], dict) and row["mesh"]
        assert row["model_flops_per_sample"] > 0
        assert 0 < row["mfu"] < 1.0
        assert re.match(r"\d{4}-\d{2}-\d{2}T", row["captured_at"])
        s = row["serving"]
        assert s["engine_stats"]["completed"] == 5
        assert s["tokens_per_sec"] > 0
        assert s["latency_p99_ms"] >= s["latency_p50_ms"] > 0
    arms = {json.loads(l)["serving"]["arm"] for l in lines}
    assert arms == {"dense_replicated", "flash_sharded"}


def test_serve_bench_int8_arm_reports_capacity_win(capsys):
    """The int8-KV arm: completes the same workload, reports the
    capacity columns (bytes/slot from the ACTUAL cache, bf16 reference
    at the same bucket, slots at the HBM budget), and clears the >= 1.8x
    concurrent-slots acceptance bar against the bf16 reference."""
    import json

    sys_path_mod = __import__("sys")
    import os as _os

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in sys_path_mod.path:
        sys_path_mod.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            "--preset", "tiny", "--requests", "4", "--slots", "2",
            "--max-new", "4", "--sim-devices", "0",
            "--arms", "flash_replicated_int8",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines()
        if l.startswith("{")
    ]
    assert len(lines) == 1, lines
    s = json.loads(lines[0])["serving"]
    assert s["kv_cache_quant"] == "int8"
    assert s["engine_stats"]["completed"] == 4
    assert s["hbm_bytes_per_slot"] > 0
    assert s["cache_bucket"] > 0
    # >= 1.8x the concurrent slots of a bf16 cache at equal HBM.
    assert s["bytes_per_slot_bf16_ref"] >= 1.8 * s["hbm_bytes_per_slot"], s
    assert s["max_slots_at_hbm"] >= 1.8 * s["max_slots_at_hbm_bf16_ref"], s


def test_serve_bench_paged_arm_capacity_and_prefix_scaling(capsys):
    """The ISSUE 10 acceptance pin: on a mixed-length workload the paged
    arm fits >= 1.5x the concurrent slots of the bucketed bf16 baseline
    at equal HBM (the pinned lower bound; the int8-pool arm compounds
    further), and the shared-prefix workload's prefill work scales with
    UNIQUE prefixes — every repeat request saves exactly its full shared
    blocks, corroborated per request by the Completion SLO fields."""
    import json

    sys_path_mod = __import__("sys")
    import os as _os

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in sys_path_mod.path:
        sys_path_mod.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            # The mixed-length operating point the ratio is pinned at:
            # the longest request pushes the bucketed engine's shared
            # bucket to 128 while the paged engine pays each row's
            # actual blocks (~45-token average need), so the headroom is
            # structural, not a boundary accident.
            "--preset", "tiny", "--requests", "8", "--slots", "3",
            "--max-new", "16", "--sim-devices", "0",
            "--arms", "flash_replicated,flash_replicated_paged",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines()
        if l.startswith("{")
    ]
    rows = {json.loads(l)["serving"]["arm"]: json.loads(l)["serving"]
            for l in lines}
    bucketed = rows["flash_replicated"]
    paged = rows["flash_replicated_paged"]
    assert paged["engine_stats"]["completed"] == 8
    p = paged["paged"]
    assert p["block_bytes"] > 0 and p["pool_peak_blocks"] > 0
    # THE capacity acceptance: >= 1.5x concurrent slots at equal HBM vs
    # the bucketed baseline ARM on the same workload — arm-to-arm, same
    # cache dtype on both sides (fp32 on the sim, bf16 on chip: the
    # paged win is structural, so the ratio carries over), with 1.5x as
    # the pinned lower bound. The measured point here sits at ~1.8x,
    # and the int8-pool arm compounds it further.
    assert paged["max_slots_at_hbm"] >= 1.5 * bucketed["max_slots_at_hbm"], (
        paged["max_slots_at_hbm"], bucketed["max_slots_at_hbm"]
    )
    # The paged arm's own dtype-consistent bucketed reference agrees.
    assert paged["max_slots_at_hbm"] >= 1.5 * paged["max_slots_at_hbm_bf16_ref"], paged
    # Shared-prefix workload: prefill scales with unique prefixes.
    x = paged["prefix"]
    repeats = x["requests"] - x["unique_prefixes"]
    shared_tokens = x["prefix_blocks"] * p["block_size"]
    assert x["prefill_tokens_saved"] == repeats * shared_tokens, x
    assert x["prefill_tokens"] == x["prompt_tokens_total"] - x["prefill_tokens_saved"], x
    assert x["prefix_hits"] == repeats, x
    # Per-request corroboration: the aggregate is the sum of what each
    # completion reports (the SLO-column satellite).
    assert x["per_request_hits"] == repeats, x
    assert x["per_request_tokens_saved"] == x["prefill_tokens_saved"], x
    # The bucketed arm carries zeroed prefix SLO columns, not absent ones.
    assert bucketed["prefix_hit_rate"] == 0.0
    assert bucketed["prefill_tokens_saved"] == 0
    # ... and zeroed/neutral speculative SLO columns (ISSUE 11).
    assert bucketed["speculate"] == "off"
    assert bucketed["spec_accept_rate"] == 0.0
    assert bucketed["decode_invocations_per_token"] == 1.0


def test_serve_bench_spec_arm_acceptance_pin(capsys):
    """THE ISSUE 11 acceptance pin, measured: on the repetitive-text
    workload the n-gram speculative arm retires >= 2.0 tokens per
    verify step and cuts target-model decode invocations per emitted
    token >= 1.8x vs speculate=off on the same workload (the analytic
    twin is the perf ledger's serving:verify_step_paged row — k+1
    positions amortize one pool read). The measured point here sits at
    ~2.9x on both columns."""
    import json

    sys_path_mod = __import__("sys")
    import os as _os

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in sys_path_mod.path:
        sys_path_mod.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            "--preset", "tiny", "--requests", "4", "--slots", "2",
            "--max-new", "8", "--sim-devices", "0",
            "--arms", "flash_replicated_paged_spec_ngram",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines()
        if l.startswith("{")
    ]
    assert len(lines) == 1, lines
    s = json.loads(lines[0])["serving"]
    assert s["speculate"] == "ngram"
    assert s["engine_stats"]["completed"] == 4
    assert s["engine_stats"]["decode_verify"] > 0
    sp = s["spec_repetitive"]
    # Acceptance bar 1: mean accepted tokens per verify step >= 2.0.
    assert sp["mean_accepted_per_verify"] >= 2.0, sp
    # Acceptance bar 2: >= 1.8x fewer decode invocations per token.
    assert sp["invocations_reduction_x"] >= 1.8, sp
    assert sp["off_decode_invocations_per_token"] == 1.0
    assert sp["decode_invocations_per_token"] <= 1.0 / 1.8 + 1e-9, sp
    # Reconciliation: accepted drafts + one bonus per verify = emitted.
    assert sp["accepted"] <= sp["proposed"]
    assert 0.0 < sp["acceptance_rate"] <= 1.0
    # The mixed-length MAIN workload also ran speculatively (its
    # acceptance is workload-dependent; the columns just have to be
    # well-formed and the engine invocation ledger consistent).
    assert 0.0 <= s["spec_accept_rate"] <= 1.0
    assert 0.0 < s["decode_invocations_per_token"] <= 1.0


# ----------------------------------------- disaggregated serving (ISSUE 12)


from frl_distributed_ml_scaffold_tpu.serving import (  # noqa: E402
    DisaggServingEngine,
    TenantSpec,
)


def _disagg_vs_generate(model, params, bs, reqs, num_slots=2, **eng_kw):
    """Serve ``reqs`` [(prompt, n_new, tenant)] through the
    disaggregated scheduler and assert every completion equals its own
    solo generate() run — the prefill-worker → splice → decode-worker
    path cannot drift from the monolithic one."""
    eng = DisaggServingEngine(
        model, params, num_slots=num_slots, temperature=0.0,
        kv_block_size=bs, **eng_kw,
    )
    ids = {}
    for p, n, tenant in reqs:
        ids[eng.submit(p, n, tenant=tenant)] = (p, n, tenant)
    done = {c.id: c for c in eng.run()}
    assert sorted(done) == sorted(ids), "not every request completed"
    for rid, (prompt, n_new, tenant) in ids.items():
        assert done[rid].tenant == tenant
        ref = generate(
            model, params, jnp.asarray(prompt)[None], max_new_tokens=n_new,
            temperature=0.0,
        )
        np.testing.assert_array_equal(
            done[rid].tokens, np.asarray(ref)[0],
            err_msg=f"request {rid} diverged from its solo generate()",
        )
    return eng, done


def _mixed_tenant_reqs(rng, n=6):
    tenants = ["fg", "bg"]
    return [
        (rng.integers(0, 64, size=int(rng.integers(2, 12))).astype(np.int32),
         int(rng.integers(2, 9)), tenants[i % 2])
        for i in range(n)
    ]


@pytest.mark.parametrize("bs", [8, 16])
def test_disagg_token_identical_bf16(gpt, bs):
    """ISSUE 12 acceptance core, bf16/fp32 column: continuous batching
    through the disaggregated prefill/decode split — every handoff a
    block-table splice — is token-identical to generate() across block
    sizes, under two tenants of different SLO classes. Handoffs (not
    colocated admissions) must actually have carried every request."""
    model, params, _ = gpt
    rng = np.random.default_rng(41)
    eng, done = _disagg_vs_generate(
        model, params, bs, _mixed_tenant_reqs(rng), num_slots=3,
        tenants=[TenantSpec("fg", "latency"),
                 TenantSpec("bg", "best_effort")],
    )
    assert eng.stats["handoffs"] == len(done)
    assert eng.stats["handoff_splices"] == len(done)
    assert eng.stats["handoff_transfer_bytes"] == 0  # shared pool: re-own
    assert eng.decode._reserved_future == 0
    assert all(not b for b in eng.decode._slot_blocks)
    eng.close()


@pytest.mark.parametrize("bs", [8, 16])
def test_disagg_token_identical_int8(gpt_int8, bs):
    """The quantized column: the splice moves int8 pool blocks AND
    their scale blocks (the PR 6 format vocabulary rides the same
    name-keyed taxonomy), token-identical to the quantized generate()."""
    model, params, _ = gpt_int8
    rng = np.random.default_rng(43)
    eng, done = _disagg_vs_generate(
        model, params, bs, _mixed_tenant_reqs(rng), num_slots=3,
    )
    assert eng.stats["handoffs"] == len(done)
    eng.close()


def test_disagg_spec_rides_decode_worker(gpt):
    """Speculation rides the DECODE worker unchanged: an accepting
    prompt speculates (verify steps, accepted drafts) while admissions
    arrive via handoff, and output stays token-identical."""
    model, params, _ = gpt
    rng = np.random.default_rng(47)
    reqs = [
        (_accepting_prompt(model, params), 14, "fg"),
        (rng.integers(0, 64, size=9).astype(np.int32), 6, "bg"),
        (np.arange(2, dtype=np.int32), 12, "bg"),
    ]
    eng, done = _disagg_vs_generate(
        model, params, 8, reqs, num_slots=3,
        speculate="ngram", speculate_k=4,
        tenants=[TenantSpec("fg", "latency"),
                 TenantSpec("bg", "best_effort")],
    )
    assert eng.stats["decode_verify"] > 0, dict(eng.stats)
    assert 0 < eng.stats["spec_accepted"] <= eng.stats["spec_proposed"]
    assert eng.stats["handoffs"] == len(done)
    eng.close()


def test_disagg_prefix_reuse_through_prefill_worker(gpt):
    """Shared-prefix admissions cross the worker boundary: the seed
    gathers from the decode worker's POOL, the prefill worker prefills
    only the suffix, and the splice writes only the private blocks —
    prefill work still scales with unique prefixes, token-identically."""
    model, params, _ = gpt
    bs = 8
    pre = np.arange(2 * bs, dtype=np.int32) % 64  # two exact blocks
    reqs = [
        (np.concatenate([pre, np.asarray([7, 9], np.int32)]), 4, "fg"),
        (np.concatenate([pre, np.asarray([11, 3, 5], np.int32)]), 4, "fg"),
    ]
    eng, done = _disagg_vs_generate(model, params, bs, reqs, num_slots=2)
    assert eng.stats["prefix_hits"] == 1, dict(eng.stats)
    assert eng.stats["prefill_tokens_saved"] == 2 * bs
    hits = [c for c in done.values() if c.prefix_cache_hit]
    assert len(hits) == 1 and hits[0].prefill_tokens_saved == 2 * bs
    eng.close()


def test_disagg_preemption_park_resume_token_identity(gpt):
    """The SLO scheduler's preemption contract: a latency-class arrival
    with no free slot PARKS the best-effort slot (blocks stay owned —
    zero device work), decodes to completion, and the parked request
    RESUMES (table re-own + one cursor pointer-move) and finishes
    TOKEN-IDENTICALLY — nothing about its K/V ever moved."""
    from frl_distributed_ml_scaffold_tpu import faults

    model, params, _ = gpt
    # Lock-order sentinel (ISSUE 20): the disagg engine's worker queues
    # and telemetry locks record under instrumentation — park/resume
    # must not introduce a cyclic acquisition order.
    with faults.instrumented_locks() as locks_rec:
        eng = DisaggServingEngine(
            model, params, num_slots=1, temperature=0.0, kv_block_size=8,
            tenants=[TenantSpec("fg", "latency"),
                     TenantSpec("bg", "best_effort")],
        )
        pb = np.arange(4, dtype=np.int32)
        pf = (np.arange(5, dtype=np.int32) + 7) % 64
        rb = eng.submit(pb, 14, tenant="bg")
        out = []
        for _ in range(4):  # bg decoding mid-stream when fg arrives
            out += eng.step()
        rf = eng.submit(pf, 4, tenant="fg")
        done = {c.id: c for c in out + eng.run()}
    pins.assert_lock_order_acyclic(locks_rec)
    assert eng.stats["preemptions"] == 1
    assert eng.stats["parked"] == 1 and eng.stats["resumed"] == 1
    assert eng.telemetry.counter("serve_preemption_total").value == 1
    assert eng.telemetry.counter("serve_resume_total").value == 1
    for rid, (p, n) in ((rb, (pb, 14)), (rf, (pf, 4))):
        ref = generate(
            model, params, jnp.asarray(p)[None], max_new_tokens=n,
            temperature=0.0,
        )
        np.testing.assert_array_equal(done[rid].tokens, np.asarray(ref)[0])
    # The preempted tenant's completion is attributed correctly and the
    # fg request finished FIRST (that is what the preemption bought).
    assert done[rb].tenant == "bg" and done[rf].tenant == "fg"
    eng.close()


@pytest.mark.fast
def test_disagg_per_tenant_shed_ordering(gpt):
    """SLO-ordered shedding: with the GLOBAL queue bound hit, a
    latency-class arrival sheds the newest queued best-effort request
    instead of itself — overload lands on the class the SLO says eats
    it, counted per tenant."""
    model, params, _ = gpt
    eng = DisaggServingEngine(
        model, params, num_slots=1, temperature=0.0, kv_block_size=8,
        max_queue_depth=2,
        tenants=[TenantSpec("fg", "latency"),
                 TenantSpec("bg", "best_effort")],
    )
    p = np.arange(4, dtype=np.int32)
    bg_ids = [eng.submit((p + i) % 64, 2, tenant="bg") for i in range(2)]
    fg_id = eng.submit((p + 9) % 64, 2, tenant="fg")  # bound hit: bg pays
    bg_shed_after = eng.submit((p + 3) % 64, 2, tenant="bg")  # self-sheds
    done = {c.id: c for c in eng.run()}
    assert done[fg_id].ok, "latency arrival must not shed itself"
    assert done[bg_ids[0]].ok, "older bg request survives"
    assert done[bg_ids[1]].finish_reason == "shed", "newest bg pays"
    assert done[bg_shed_after].finish_reason == "shed"
    t = eng.telemetry
    assert t.counter("serve_shed_total_tenant_bg").value == 2
    assert t.counter("serve_shed_total_tenant_fg").value == 0
    assert done[bg_ids[1]].tenant == "bg"
    eng.close()


def test_disagg_separate_partition_transfers_only_blocks(gpt):
    """The two-submesh instantiation (the tentpole's CPU-sim shape): the
    prefill worker runs on its OWN 1-device submesh with its own params
    replica, dispatches async, and the handoff moves ONLY the suffix
    slot-cache blocks across partitions (counted) — output stays
    token-identical."""
    model, params, _ = gpt
    penv = build_mesh(MeshConfig(data=1), devices=[jax.devices()[1]])
    rng = np.random.default_rng(53)
    eng, done = _disagg_vs_generate(
        model, params, 8,
        [(rng.integers(0, 64, size=int(rng.integers(2, 10)))
          .astype(np.int32), int(rng.integers(2, 7)), "default")
         for _ in range(4)],
        num_slots=2, prefill_env=penv,
    )
    assert eng.stats["handoffs"] == len(done)
    moved = eng.stats["handoff_transfer_bytes"]
    assert moved > 0
    assert (
        eng.telemetry.counter("serve_handoff_transfer_bytes_total").value
        == moved
    )
    # Far less than the logical caches: only prompt-bucket slot caches
    # ever cross, never the pool.
    pool_bytes = sum(
        int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(eng.decode.cache)
    )
    assert moved < pool_bytes
    eng.close()


def test_handoff_splice_reshard_free_compiled_hlo(gpt):
    """ISSUE 12 acceptance pin: under a live model mesh the compiled
    handoff splice is RESHARD-FREE — no all-gather producing an array
    with the pool's (or the logical cache's) geometry. The head-sharded
    pool takes the prefilled blocks in place; a gather-based handoff
    would have to materialize one of these signatures."""
    model, params, _ = gpt
    env = build_mesh(MeshConfig(data=2, model=4))
    bs, tp_m = 8, 4
    with mesh_context(env):
        sharded = _shard(params, env)
        eng = ServingEngine(
            model, sharded, num_slots=2, temperature=0.0, kv_block_size=bs,
        )
        rid = eng.submit(np.arange(5, dtype=np.int32), 3)
        done = {c.id: c for c in eng.run()}
        assert done[rid].ok
        s_c = 8
        mc = model.clone(cache_len=s_c)
        tok = jnp.zeros((1, 1), jnp.int32)
        _, vars_out = jax.jit(
            lambda p, t: mc.apply(
                {"params": p}, t, decode=True, mutable=["cache"]
            ),
        )(sharded, tok)
        slot_cache = vars_out["cache"]
        n_priv = 1
        compiled = eng._paged_graft_fn(s_c, n_priv).lower(
            eng.cache, slot_cache,
            jnp.zeros((n_priv,), jnp.int32), jnp.int32(0), jnp.int32(0),
        ).compile()
    l = model.config.num_layers
    h = model.config.num_heads
    hd = model.config.hidden_dim // h
    n_pool = eng.pool_blocks
    sigs = set()
    for hh in {h, h // tp_m}:
        sigs.add((l, n_pool, bs, hh, hd))  # a regathered pool
        sigs.add((n_pool, bs, hh, hd))
        for b in (1, 2):
            sigs.add((l, b, model.config.seq_len, hh, hd))  # logical view
    pins.assert_reshard_free(compiled, sigs, ops=("all-gather",))
    eng.close()


def test_serve_bench_disagg_arm_tail_isolation_pin(capsys):
    """THE ISSUE 12 acceptance pin: the serve_bench ``*_disagg`` arm's
    burst A/B holds decode TPOT p99 under a prefill burst at <= 0.5x
    the colocated arm's (>= 2x tail isolation — structurally ~(P+d) vs
    ~(k·P+d) with k free slots churning budget-1 prefills, so the
    margin is architectural, not a timing accident), with the handoff
    a zero-copy re-own (0 transfer bytes) and the burst genuinely
    deferred."""
    import json

    sys_path_mod = __import__("sys")
    import os as _os

    tools = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools",
    )
    if tools not in sys_path_mod.path:
        sys_path_mod.path.insert(0, tools)
    import serve_bench

    rc = serve_bench.main(
        [
            "--preset", "tiny", "--requests", "4", "--slots", "4",
            "--max-new", "6", "--sim-devices", "0",
            "--arms", "flash_replicated_paged_disagg",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines()
        if l.startswith("{")
    ]
    assert len(lines) == 1
    s = json.loads(lines[0])["serving"]
    assert s["disaggregated"] is True
    assert s["engine_stats"]["handoffs"] == s["requests"]
    d = s["disagg"]
    # Tail isolation: disagg p99 <= 0.5x colocated p99.
    assert d["tail_isolation_x"] >= 2.0, d
    assert (
        d["disagg_decode_tpot_p99_ms"]
        <= 0.5 * d["colocated_decode_tpot_p99_ms"]
    ), d
    # The handoff is a block-table splice: zero cache-copy bytes moved
    # (shared pool: ownership re-owns; the census/HLO pins live in
    # test_graft_lint.py and test_handoff_splice_reshard_free above).
    assert d["handoff_transfer_bytes"] == 0
    assert d["handoffs"] == d["decode_requests"] + d["burst_requests"]
    assert d["prefill_deferred"] > 0, "the burst was never deferred"
    assert d["handoff_p50_ms"] > 0


def test_disagg_expired_parked_request_retires_typed_and_frees_blocks(gpt):
    """A parked request past its deadline must not hold its pool blocks
    hostage: the scheduler's parked sweep retires it typed "deadline"
    IN PLACE (no slot, no device work), carrying the tokens generated
    before the park, and its blocks/reservation return to the pool."""
    model, params, _ = gpt
    eng = DisaggServingEngine(
        model, params, num_slots=1, temperature=0.0, kv_block_size=8,
        tenants=[TenantSpec("fg", "latency"),
                 TenantSpec("bg", "best_effort")],
    )
    pb = np.arange(4, dtype=np.int32)
    rb = eng.submit(pb, 14, tenant="bg")
    out = []
    for _ in range(4):
        out += eng.step()
    rf = eng.submit((pb + 7) % 64, 4, tenant="fg")
    # Step until the preemption actually parked bg.
    for _ in range(6):
        out += eng.step()
        if eng.stats["parked"]:
            break
    assert eng.stats["parked"] == 1
    # Expire the parked request's deadline while it waits.
    eng._parked[0]["state"]["req"].deadline_s = 1e-6
    done = {c.id: c for c in out + eng.run()}
    assert done[rb].finish_reason == "deadline"
    n_partial = len(done[rb].tokens) - done[rb].prompt_len
    assert n_partial >= 1, "partial tokens must ride the typed completion"
    assert len(done[rb].token_latencies_s) == n_partial
    assert done[rb].tenant == "bg"
    assert done[rf].ok
    assert eng.stats["resumed"] == 0, "expired parked must not resume"
    # Blocks released: everything not free is held ONLY by the prefix
    # cache (evictable capacity), and no reservation lingers.
    assert eng.decode._reserved_future == 0
    assert not eng.decode._parked_held
    cache_held = {
        b for ids in eng.decode._prefix_cache.values() for b in ids
    }
    assert len(eng.decode._free) + len(cache_held) == eng.pool_blocks - 1
    eng.close()


@pytest.mark.fast
def test_disagg_deferred_head_keeps_its_turn(gpt):
    """FIFO within a class, like colocated admission: a head request
    whose launch defers (pool headroom, slot capacity) keeps its
    round-robin turn — the cursor commits only when a request actually
    launches, so a stream of small same-class peers cannot starve a
    large deferred head by jumping it on every tick."""
    model, params, _ = gpt
    eng = DisaggServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8,
        tenants=[TenantSpec("a", "standard"), TenantSpec("b", "standard")],
    )
    ra = eng.submit(np.arange(9, dtype=np.int32), 4, tenant="a")
    eng.submit(np.arange(3, dtype=np.int32), 3, tenant="b")
    # Two uncommitted picks return the SAME head — a deferral between
    # them must not rotate the cursor past tenant a.
    q1, r1, s1, rr1 = eng._next_request()
    q2, r2, s2, rr2 = eng._next_request()
    assert r1.id == ra and r2.id == ra and s1.name == "a"
    # Committing the pick rotates to tenant b, the weighted-RR behavior.
    eng._commit_rr(rr1)
    _, r3, s3, _ = eng._next_request()
    assert s3.name == "b"
    done = {c.id: c for c in eng.run()}
    assert all(c.ok for c in done.values())
    eng.close()


def test_disagg_separate_partition_prefix_transfer_is_windowed(gpt):
    """Cross-partition handoffs move the occupied WINDOW, never the
    bucket: a no-hit handoff transfers exactly its prompt's block
    window back (not the power-of-two slot bucket), and a prefix-hit
    admission transfers the seed's occupied prefix out plus only the
    private blocks back — all pinned EXACTLY against the analytic
    window bytes. (Cross-partition prefix reuse saves prefill COMPUTE;
    link bytes are symmetric — seed-out ≈ prefix-back — which these
    pins document.)"""
    model, params, _ = gpt
    cfg = model.config
    penv = build_mesh(MeshConfig(data=1), devices=[jax.devices()[1]])
    bs = 8
    eng = DisaggServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=bs,
        prefill_env=penv,
    )

    def window_bytes(tok):  # K/V fp32 payload + index rows, per transfer
        per = cfg.num_layers * 2 * tok * cfg.hidden_dim * 4
        return per + cfg.num_layers * 4 + 4  # cache_index [L,1] + pos_index

    pre = np.arange(2 * bs, dtype=np.int32) % 64
    p1 = np.concatenate([pre, np.asarray([7, 9, 1], np.int32)])  # 19 tok
    p2 = np.concatenate([pre, np.asarray([11, 3], np.int32)])  # 18 tok
    r1 = eng.submit(p1, 4)
    done1 = {c.id: c for c in eng.run()}
    m1 = eng.stats["handoff_transfer_bytes"]
    # No hit: backward only — the 3-block window (24 tok), NOT the
    # 32-token bucket the slot cache is shaped to.
    assert m1 == window_bytes(3 * bs), (m1, window_bytes(3 * bs))
    r2 = eng.submit(p2, 4)
    done2 = {c.id: c for c in eng.run()}
    m2 = eng.stats["handoff_transfer_bytes"] - m1
    assert done2[r2].prefix_cache_hit
    # Hit: the 2-block seed crosses out, ONE private block crosses back.
    assert m2 == window_bytes(2 * bs) + window_bytes(bs), m2
    for rid, p, d in ((r1, p1, done1), (r2, p2, done2)):
        ref = generate(
            model, params, jnp.asarray(p)[None], max_new_tokens=4,
            temperature=0.0,
        )
        np.testing.assert_array_equal(d[rid].tokens, np.asarray(ref)[0])
    eng.close()


def test_disagg_sequential_latency_after_preemption_no_livelock(gpt):
    """Regression (review round 5): a queued latency request and a
    parked best-effort victim must not wait on each other forever. With
    one slot, fg1 preempts bg; after fg1 completes, fg2 must take the
    free slot (the parked bg does not reserve it — it outranks only
    non-latency placements), and bg resumes once the latency stream
    drains — every request completes, token-identically."""
    model, params, _ = gpt
    eng = DisaggServingEngine(
        model, params, num_slots=1, temperature=0.0, kv_block_size=8,
        tenants=[TenantSpec("fg", "latency"),
                 TenantSpec("bg", "best_effort")],
    )
    pb = np.arange(4, dtype=np.int32)
    pf1 = (pb + 7) % 64
    pf2 = (pb + 23) % 64
    rb = eng.submit(pb, 16, tenant="bg")
    out = []
    for _ in range(4):
        out += eng.step()
    rf1 = eng.submit(pf1, 4, tenant="fg")
    # Drive until fg1 finished; THEN submit fg2 — the livelock shape:
    # free slot + parked bg + queued latency.
    for _ in range(30):
        out += eng.step()
        if any(c.id == rf1 for c in out):
            break
    assert any(c.id == rf1 for c in out), "fg1 never completed"
    rf2 = eng.submit(pf2, 4, tenant="fg")
    done = {c.id: c for c in out + eng.run(max_steps=300)}
    assert sorted(done) == [rb, rf1, rf2], (
        f"livelock: resolved only {sorted(done)}"
    )
    for rid, (p, n) in ((rb, (pb, 16)), (rf1, (pf1, 4)), (rf2, (pf2, 4))):
        ref = generate(
            model, params, jnp.asarray(p)[None], max_new_tokens=n,
            temperature=0.0,
        )
        np.testing.assert_array_equal(done[rid].tokens, np.asarray(ref)[0])
    assert eng.stats["parked"] >= 1 and eng.stats["resumed"] >= 1
    eng.close()
