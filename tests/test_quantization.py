"""Quantization primitive gates (ops/quantization.py): round-trip error
bounds per format and granularity, zero-safety, the scaled matmul's
accuracy against the full-precision dot, and the straight-through VJP
contract (backward == the plain matmul's gradients, exactly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.ops.quantization import (
    LOWP_FORMATS,
    dequantize,
    lowp_dtype,
    qmax,
    quantize,
    quantized_matmul,
)

pytestmark = pytest.mark.fast


@pytest.mark.parametrize("fmt", sorted(LOWP_FORMATS))
def test_round_trip_error_bound_per_tensor(fmt):
    """Symmetric per-tensor quantization: |x - deq(q(x))| <= half a
    quantization step for int8 (round-to-nearest) and <= one fp8 ulp of
    the scaled value for the float formats."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 32)) * 3.0, jnp.float32)
    q, scale = quantize(x, fmt)
    assert q.dtype == lowp_dtype(fmt)
    assert scale.shape == (1, 1)
    back = dequantize(q, scale)
    err = float(jnp.abs(back - x).max())
    s = float(scale[0, 0])
    if fmt == "int8":
        assert err <= 0.5 * s + 1e-7, (err, s)
    else:
        # fp8 relative step at the top of the range: 2^-mantissa_bits.
        mant = 3 if fmt == "fp8_e4m3" else 2
        assert err <= s * qmax(fmt) * 2.0 ** (-mant), (err, s)
    # The max-magnitude element is exactly representable (scale maps the
    # amax onto qmax) — symmetric quantization's anchor property.
    i = jnp.unravel_index(jnp.argmax(jnp.abs(x)), x.shape)
    np.testing.assert_allclose(float(back[i]), float(x[i]), rtol=1e-6)


def test_per_channel_beats_per_tensor_on_skewed_channels():
    """Per-channel scales exist because channels with small dynamic range
    must not inherit the largest channel's quantization step."""
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(32, 4)), jnp.float32)
    w = w * jnp.asarray([100.0, 1.0, 0.01, 0.0001])[None, :]
    q_t, s_t = quantize(w, "int8")
    q_c, s_c = quantize(w, "int8", channel_axes=(1,))
    assert s_c.shape == (1, 4)
    err_t = jnp.abs(dequantize(q_t, s_t) - w).max(axis=0)
    err_c = jnp.abs(dequantize(q_c, s_c) - w).max(axis=0)
    # The small channels are destroyed per-tensor, preserved per-channel.
    assert float(err_c[2]) < float(err_t[2])
    assert float(err_c[3]) < float(err_t[3])
    rel = err_c / jnp.abs(w).max(axis=0)
    assert float(rel.max()) <= 1.0 / 254 + 1e-6, rel


def test_all_zero_input_is_safe():
    """Zero tensors (fresh cache rows, zero-init layers) must quantize to
    zeros with a finite scale — never a divide-by-zero NaN."""
    x = jnp.zeros((8, 8), jnp.float32)
    q, s = quantize(x, "int8")
    assert np.all(np.asarray(q) == 0)
    assert np.all(np.isfinite(np.asarray(s)))
    np.testing.assert_array_equal(np.asarray(dequantize(q, s)), 0.0)


def test_unknown_format_raises_with_vocabulary():
    with pytest.raises(KeyError, match="int8"):
        lowp_dtype("int4")
    with pytest.raises(KeyError, match="fp8_e4m3"):
        quantize(jnp.ones(3), "bf8")


@pytest.mark.parametrize("fmt", ["int8", "fp8_e4m3"])
def test_quantized_matmul_tracks_plain_matmul(fmt):
    """The scaled low-precision matmul stays within the documented band
    of the fp32 product (per-tensor x scale, per-channel w scale)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 24)), jnp.float32) * 0.2
    ref = jnp.einsum("btk,km->btm", x, w)
    out = quantized_matmul(x, w, fmt)
    rel = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
    assert rel < 0.05, rel


def test_quantized_matmul_straight_through_grads_are_exact():
    """The STE contract: gradients of the quantized matmul equal the
    PLAIN matmul's gradients exactly — the quantizers differentiate as
    identity against the full-precision residuals, so master-weight
    updates see no quantization in the backward."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    ct = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)

    def qloss(x, w):
        return (quantized_matmul(x, w, "int8") * ct).sum()

    def loss(x, w):
        return ((x @ w) * ct).sum()

    gq = jax.grad(qloss, argnums=(0, 1))(x, w)
    gp = jax.grad(loss, argnums=(0, 1))(x, w)
    for a, b in zip(gq, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_int8_contraction_is_integer_exact():
    """int8 x int8 rides the integer unit: for inputs that ARE exact
    int8 grids, the quantized matmul reproduces the fp32 product bit-for
    -bit (int32 accumulation has no rounding) — the property that makes
    the MXU's 8-bit path trustworthy, not just fast."""
    rng = np.random.default_rng(4)
    xq = rng.integers(-127, 128, size=(8, 16)).astype(np.float32)
    wq = rng.integers(-127, 128, size=(16, 4)).astype(np.float32)
    x = jnp.asarray(xq * 0.5)  # exact scales: amax maps back exactly
    w = jnp.asarray(wq * 0.25)
    # Force every amax onto the grid end so quantize() reproduces the
    # grid exactly (w scales are per-channel: every column needs its
    # amax anchored, not just one).
    x = x.at[0, 0].set(127 * 0.5)
    w = w.at[0, :].set(127 * 0.25)
    ref = np.asarray(x) @ np.asarray(w)
    out = np.asarray(quantized_matmul(x, w, "int8"))
    np.testing.assert_allclose(out, ref, rtol=1e-6)
