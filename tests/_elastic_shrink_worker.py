"""Per-process supervisor half of the smaller-slice continuation test.

Launched (once per simulated host) by tests/test_elastic_multiprocess.py::
test_multiprocess_shrink_to_survivors. Host 0 (the COORDINATOR) is killed
permanently: its child hard-faults at step 9 and its supervisor has
max_restarts=0 — the moral equivalent of a host that never comes back.
Host 1 must detect the dead peer through the membership heartbeats, elect
itself rank 0 of a 1-process world, and finish the run from the last
checkpoint (Orbax resharding restore: the 4-device data sharding lands on
its 2 local devices).

Env contract: FRL_TPU_COORDINATOR, FRL_TPU_NUM_PROCESSES,
FRL_TPU_PROCESS_ID, FRL_TEST_WORKDIR; FRL_FAULT_AT_STEP on host 0 only;
FRL_TPU_INIT_TIMEOUT_S bounds the dead-coordinator rendezvous wait;
FRL_TPU_HOST_ADDRESS pins published endpoints to loopback.
"""

import os
import sys


def main() -> int:
    from frl_distributed_ml_scaffold_tpu.launcher.launch import main as launch_main

    pid = os.environ["FRL_TPU_PROCESS_ID"]
    per_host = (
        # The doomed coordinator: one fault, zero restarts. shrink_after
        # stays >0 so its supervisor joins the membership directory and
        # retires (removes its heartbeat) on the way out.
        ["elastic.max_restarts=0"]
        if pid == "0"
        else []
    )
    return launch_main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--sim-devices", "2",
            "--coordinator", os.environ["FRL_TPU_COORDINATOR"],
            "--num-processes", os.environ["FRL_TPU_NUM_PROCESSES"],
            "--process-id", pid,
            "--elastic",
            "trainer.total_steps=12",
            "trainer.log_every=4",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "data.prefetch=0",
            "model.hidden_sizes=32",
            "precision.policy=fp32",
            "checkpoint.save_every=4",
            "checkpoint.async_save=false",
            "elastic.backoff_s=0.1",
            "elastic.shrink_after=2",
            "elastic.peer_timeout_s=8",
            "workdir=" + os.environ["FRL_TEST_WORKDIR"],
        ]
        + per_host
    )


if __name__ == "__main__":
    sys.exit(main())
