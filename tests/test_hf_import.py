"""HF GPT-2 checkpoint import (tools/import_hf_gpt2.py): a randomly
initialized local HF model (no network) must produce the same logits
through the converted params as through HF's own forward."""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "tools")
)

from _jit import jit_apply

transformers = pytest.importorskip("transformers")
pytest.importorskip("torch")


@pytest.fixture(scope="module")
def hf_pair():
    import torch

    from import_hf_gpt2 import gpt_config_from_hf, hf_gpt2_to_params

    torch.manual_seed(0)
    hf_cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    return hf, hf_gpt2_to_params(hf), gpt_config_from_hf(hf_cfg)


def test_converted_params_match_model_structure(hf_pair):
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    hf, params, cfg = hf_pair
    model = GPT(cfg, get_policy("fp32"))
    tokens = np.zeros((1, 8), np.int32)
    ref = model.init({"params": jax.random.key(0)}, tokens, train=False)[
        "params"
    ]
    ref_shapes = jax.tree.map(lambda x: x.shape, ref)
    got_shapes = jax.tree.map(lambda x: x.shape, params)
    assert ref_shapes == got_shapes


def test_converted_logits_match_hf(hf_pair):
    import torch

    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    hf, params, cfg = hf_pair
    model = GPT(cfg, get_policy("fp32"))
    tokens = np.asarray(
        jax.random.randint(jax.random.key(1), (2, 12), 0, 64), np.int32
    )
    ours = jit_apply(model, train=False)({"params": params}, tokens)
    with torch.no_grad():
        theirs = hf(torch.from_numpy(tokens).long()).logits.numpy()
    # Architecturally identical (incl. LN eps 1e-5); residual diffs are
    # float summation order between XLA and torch kernels.
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-4)


def test_save_load_roundtrip(tmp_path, hf_pair):
    from import_hf_gpt2 import load_params, save_params

    _, params, _ = hf_pair
    path = str(tmp_path / "p.msgpack")
    save_params(params, path)
    restored = load_params(path)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), params, restored
    )


def test_export_roundtrip_matches(hf_pair):
    """params -> HF export -> re-import must be byte-identical, and the
    exported HF model's logits must match the original HF model's."""
    import torch

    from import_hf_gpt2 import hf_gpt2_to_params, params_to_hf_gpt2

    hf, params, cfg = hf_pair
    fresh = transformers.GPT2LMHeadModel(hf.config).eval()
    params_to_hf_gpt2(params, fresh)
    back = hf_gpt2_to_params(fresh)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), params, back
    )
    tokens = torch.arange(10).reshape(1, 10) % 64
    with torch.no_grad():
        np.testing.assert_allclose(
            fresh(tokens).logits.numpy(), hf(tokens).logits.numpy(),
            atol=1e-6, rtol=1e-6,
        )


def test_trainer_init_from_imported_params(hf_pair, tmp_path):
    """trainer.init_params_path: an imported HF checkpoint becomes the
    training starting point — params in the state equal the file's, and a
    wrong-shaped file is refused with the offending paths."""
    from import_hf_gpt2 import save_params

    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    _, params, cfg = hf_pair
    path = str(tmp_path / "hf.msgpack")
    save_params(params, path)
    overrides = [
        f"model.{k}={getattr(cfg, k)}"
        for k in ("vocab_size", "num_layers", "num_heads", "hidden_dim",
                  "seq_len")
    ] + [
        f"data.vocab_size={cfg.vocab_size}", f"data.seq_len={cfg.seq_len}",
        "data.global_batch_size=8", "precision.policy=fp32",
        "checkpoint.enabled=false", f"workdir={tmp_path}",
        f"trainer.init_params_path={path}",
    ]
    trainer = Trainer(apply_overrides(get_config("gpt2_medium_zero1"), overrides))
    state = trainer.init_state()
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-7
        ),
        jax.device_get(state.params),
        params,
    )
    # And one train step runs from the imported weights.
    s2, metrics = trainer.train_step(state, trainer.pipeline.global_batch(0))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))

    bad = apply_overrides(
        get_config("gpt2_medium_zero1"),
        overrides[:-1] + ["model.hidden_dim=48", f"trainer.init_params_path={path}"],
    )
    with pytest.raises(ValueError, match="shape mismatch"):
        Trainer(bad).init_state()


def test_init_params_path_seeds_ema_too(hf_pair, tmp_path):
    """With EMA on, the imported weights must seed ema_params as well —
    eval uses the EMA, so a random-init EMA would score garbage."""
    from import_hf_gpt2 import save_params

    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    _, params, cfg = hf_pair
    path = str(tmp_path / "hf.msgpack")
    save_params(params, path)
    trainer = Trainer(
        apply_overrides(
            get_config("gpt2_medium_zero1"),
            [
                f"model.vocab_size={cfg.vocab_size}",
                f"model.num_layers={cfg.num_layers}",
                f"model.num_heads={cfg.num_heads}",
                f"model.hidden_dim={cfg.hidden_dim}",
                f"model.seq_len={cfg.seq_len}",
                f"data.vocab_size={cfg.vocab_size}",
                f"data.seq_len={cfg.seq_len}",
                "data.global_batch_size=8", "precision.policy=fp32",
                "trainer.ema_decay=0.99", "checkpoint.enabled=false",
                f"workdir={tmp_path}",
                f"trainer.init_params_path={path}",
            ],
        )
    )
    state = trainer.init_state()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.device_get(state.ema_params),
        jax.device_get(state.params),
    )
