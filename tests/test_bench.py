"""Benchmark harness: protocol record completeness (BASELINE.md §protocol)."""

from __future__ import annotations
import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast


import json
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench

import pytest


@pytest.fixture(autouse=True)
def sandbox_last_good(tmp_path, monkeypatch):
    """Point the last-good evidence cache at a sandbox for EVERY test here.

    The round-5 self-poisoning bug: ``test_main_falls_through_candidate_
    ladder`` drives ``main()``, which calls ``_save_last_good`` — so every
    pytest run stamped the fixture value (123.0) into the committed
    ``bench_last_good.json``, and the tier-1 stale fallback could never
    re-emit real data. The env var covers subprocess reachers; the setattr
    covers the already-imported module object.
    """
    path = tmp_path / "bench_last_good.json"
    monkeypatch.setenv("FRL_BENCH_LAST_GOOD_PATH", str(path))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(path))
    yield path


def test_save_last_good_writes_sandbox_not_repo(sandbox_last_good):
    """The committed evidence cache must be untouchable from tests: writes
    land in the env-overridden sandbox and the repo copy stays
    byte-identical (it holds only real relay captures — the regenerated
    2256.04 protocol-row record, corroborable by BENCH_TABLE.jsonl)."""
    repo_cache = os.path.join(
        os.path.dirname(os.path.abspath(bench.__file__)),
        "bench_last_good.json",
    )
    before = open(repo_cache, "rb").read() if os.path.exists(repo_cache) else None
    bench._save_last_good({"metric": "m", "value": 1.0, "unit": "x",
                           "vs_baseline": 0.0})
    assert sandbox_last_good.exists()
    after = open(repo_cache, "rb").read() if os.path.exists(repo_cache) else None
    assert before == after, (
        "a test wrote the committed bench_last_good.json — the sandbox "
        "fixture is not covering some _save_last_good path"
    )
    if before is not None:
        assert json.loads(before).get("value") != 123.0, (
            "the committed cache holds the old test-fixture value again"
        )


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_session_sandbox_env_is_active():
    """conftest.py must export the session-wide cache sandbox BEFORE any
    test imports bench — the committed evidence file is then unreachable
    even from tests (and subprocesses) outside this module."""
    sandbox = os.environ.get("FRL_BENCH_LAST_GOOD_PATH")
    assert sandbox, "conftest session sandbox env var missing"
    assert os.path.abspath(sandbox) != os.path.join(
        REPO_ROOT, "bench_last_good.json"
    )
    assert not os.path.abspath(sandbox).startswith(REPO_ROOT + os.sep)


def test_committed_cache_is_corroborated(monkeypatch):
    """The acceptance gate: the committed bench_last_good.json must carry
    the real protocol-row capture (2256.04) and pass _corroborated against
    the committed BENCH_TABLE.jsonl, so the tier-1 stale fallback can
    actually fire with real data after a relay outage."""
    committed = os.path.join(REPO_ROOT, "bench_last_good.json")
    rec = json.load(open(committed))
    # _corroborated derives the table path from LAST_GOOD_PATH's dirname;
    # point it at the repo READ-ONLY (no write path runs here).
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", committed)
    assert bench._corroborated(rec), rec
    assert rec["value"] != 123.0, "test-fixture value in the committed cache"
    import re

    assert re.match(r"\d{4}-\d{2}-\d{2}T", rec.get("captured_at", "")), rec


def test_bench_table_rows_meet_protocol_schema():
    """Every committed protocol row must carry the full measurement
    context: mesh, per-sample FLOPs and MFU (BASELINE.md protocol), plus
    capture provenance — incomplete rows can't back the stale fallback.

    ``status: "queued"`` rows are one sanctioned exception: they
    record an experiment awaiting its relay window (BACKLOG R7-1 style)
    and must carry config/mesh/provenance and a note naming the queued
    A/B — but NO measurement fields, so a placeholder can never be
    mistaken for (or corroborate) a measured number.

    ``status: "stale"`` rows are the other (ISSUE 10 satellite): the
    relay-down fallback's re-emission of the last real capture
    (bench.py ``_emit_stale_or_error`` stamps them since round 13 —
    through rounds 5–9 the 2256.04 RN50 row was re-emitted as if
    fresh). A stale row carries real measured numbers, so it must keep
    the measured fields AND declare its staleness: ``stale_reason``
    plus ``captured_at`` provenance of the ORIGINAL capture — a stale
    row with no capture time is a fabrication vector, refused."""
    table = os.path.join(REPO_ROOT, "BENCH_TABLE.jsonl")
    rows = [json.loads(l) for l in open(table).read().splitlines() if l.strip()]
    assert rows, "committed BENCH_TABLE.jsonl is empty"
    assert any(
        row.get("status") not in ("queued", "stale") for row in rows
    ), (
        "BENCH_TABLE.jsonl holds only queued/stale placeholders — the "
        "stale fallback has nothing to corroborate against"
    )
    for row in rows:
        ctx = f"row for {row.get('config')}"
        if row.get("status") == "queued":
            for key in ("config", "mesh", "note"):
                assert key in row, f"queued {ctx} missing {key}"
            assert isinstance(row["mesh"], dict) and row["mesh"], ctx
            for key in ("samples_per_sec_per_chip", "step_time_median_s",
                        "mfu", "model_flops_per_sample"):
                assert key not in row, (
                    f"queued {ctx} carries measurement field {key} — "
                    "placeholders must not wear measured numbers"
                )
            assert bench._row_captured_at(row), (
                f"queued {ctx} has no provenance (stamp the queue date "
                "in source/captured_at)"
            )
            continue
        if row.get("status") == "stale":
            assert row.get("stale") is True, (
                f"stale {ctx} missing the stale flag"
            )
            assert row.get("stale_reason"), (
                f"stale {ctx} does not say WHY it is stale"
            )
            assert bench._row_captured_at(row), (
                f"stale {ctx} has no provenance of the original capture"
            )
            continue
        for key in ("config", "samples_per_sec_per_chip", "mesh",
                    "model_flops_per_sample", "mfu"):
            assert key in row, f"{ctx} missing {key}"
        assert isinstance(row["mesh"], dict) and row["mesh"], ctx
        assert row["model_flops_per_sample"] > 0, ctx
        assert 0 < row["mfu"] < 1.0, ctx
        assert bench._row_captured_at(row), f"{ctx} has no capture provenance"
        assert "stale" not in row and "stale_reason" not in row, (
            f"{ctx} carries stale markers without status:'stale' — "
            "stamp the status so consumers can filter on it"
        )


def test_stale_fallback_tier1_carries_captured_at(
    sandbox_last_good, monkeypatch, capsys
):
    """Simulated outage, tier 1 (cache present): the re-emitted record
    must carry a real captured_at, not 'unknown time'."""
    rec = {
        "metric": "rn50_imagenet_samples_per_sec_per_chip",
        "value": 2256.04, "unit": "samples/sec/chip", "vs_baseline": 0.9,
        "captured_at": "2026-07-30T00:00:00Z",
    }
    sandbox_last_good.write_text(json.dumps(rec))
    (sandbox_last_good.parent / "BENCH_TABLE.jsonl").write_text(
        json.dumps({"config": "imagenet_rn50_ddp",
                    "samples_per_sec_per_chip": 2256.04}) + "\n"
    )
    rc = bench._emit_stale_or_error("relay down (simulated)")
    assert rc == 1
    out = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    final = json.loads(out[-1])
    assert final["stale"] is True
    assert final["status"] == "stale"  # the typed stamp (ISSUE 10)
    assert final["stale_reason"].startswith("relay down")
    assert final["captured_at"] == "2026-07-30T00:00:00Z"


def test_stale_fallback_tier2_parses_captured_at_from_table_row(
    sandbox_last_good, monkeypatch, capsys
):
    """Simulated outage, tier 2 (no cache — reconstruct from the protocol
    table): captured_at must be parsed out of the row (explicit field or
    the source free text), so tier 2 no longer logs 'unknown time'."""
    assert not sandbox_last_good.exists()
    (sandbox_last_good.parent / "BENCH_TABLE.jsonl").write_text(
        json.dumps({
            "config": "imagenet_rn50_ddp",
            "samples_per_sec_per_chip": 2256.04, "mfu": 0.3233,
            "chip": "TPU v5 lite",
            "source": "evidence log, captured 2026-07-30 ~21:26 UTC",
        }) + "\n"
    )
    rc = bench._emit_stale_or_error("relay down (simulated)")
    assert rc == 1
    captured = capsys.readouterr()
    out = [l for l in captured.out.splitlines() if l.startswith("{")]
    final = json.loads(out[-1])
    assert final["stale"] is True
    assert final["status"] == "stale"  # the typed stamp (ISSUE 10)
    assert final["value"] == 2256.04
    assert final["captured_at"] == "2026-07-30T21:26:00Z"
    assert "unknown time" not in captured.err


def test_bench_config_emits_protocol_record():
    perf = bench.bench_config(
        "mnist_mlp",
        ["data.global_batch_size=64", "trainer.log_every=1000000"],
        steps=4,
        warmup=1,
    )
    rec = perf["_record"]
    for key in (
        "config", "model", "global_batch_size", "per_chip_batch_size",
        "mesh", "param_sharding", "precision", "n_chips", "chip",
        "steps_per_sec", "samples_per_sec_per_chip", "step_time_median_s",
        "step_time_p90_s",
    ):
        assert key in rec, f"protocol record missing {key}"
    assert rec["samples_per_sec_per_chip"] > 0
    assert rec["per_chip_batch_size"] * rec["n_chips"] == 64


def test_protocol_record_reports_mfu_when_peak_known(monkeypatch):
    """On chips with a known bf16 peak the record must carry model FLOPs +
    MFU (BASELINE.md protocol). CPU has no honest peak, so inject one —
    this exercises the same path the TPU jaxpr-fallback count feeds."""
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "cpu")
    monkeypatch.setitem(bench.CHIP_PEAK_FLOPS, kind, 1e12)
    # 2-step windows: the production default of 20 would run 60+ MNIST
    # steps here just to time them — irrelevant to what this test asserts.
    monkeypatch.setenv("FRL_BENCH_WINDOW", "2")
    perf = bench.bench_config(
        "mnist_mlp",
        ["data.global_batch_size=64", "trainer.log_every=1000000"],
        steps=4,
        warmup=1,
    )
    rec = perf["_record"]
    assert rec.get("model_flops_per_sample", 0) > 0
    assert 0 < rec["mfu"] < 1.0


def test_run_all_writes_jsonl(tmp_path, monkeypatch):
    monkeypatch.setenv("FRL_BENCH_WINDOW", "2")
    monkeypatch.setattr(
        bench, "ALL_CONFIGS",
        [("mnist_mlp", ["data.global_batch_size=64"], 4)],
    )
    out = tmp_path / "table.jsonl"
    assert bench.run_all(str(out)) == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 1 and lines[0]["config"] == "mnist_mlp"


def test_run_all_preserves_table_when_backend_down(tmp_path, monkeypatch):
    """A dead relay must never clobber the last good BENCH_TABLE capture
    with a one-line probe-error record."""
    import bench

    table = tmp_path / "BENCH_TABLE.jsonl"
    table.write_text('{"config": "imagenet_rn50_ddp", "good": true}\n')
    monkeypatch.setattr(
        bench, "probe_backend", lambda: (None, "backend init timeout")
    )
    rc = bench.run_all(str(table))
    assert rc == 1
    assert table.read_text() == '{"config": "imagenet_rn50_ddp", "good": true}\n'


def test_run_all_preserves_table_when_all_configs_fail(tmp_path, monkeypatch):
    """Backend dies AFTER a successful probe: all rows error out — the
    previous capture must still survive (staged-tmp-file invariant)."""
    import bench

    table = tmp_path / "BENCH_TABLE.jsonl"
    table.write_text('{"config": "imagenet_rn50_ddp", "good": true}\n')
    monkeypatch.setattr(bench, "probe_backend", lambda: ("fake-chip", None))
    def boom(*a, **k):
        raise RuntimeError("backend died mid-run")
    monkeypatch.setattr(bench, "bench_config", boom)
    rc = bench.run_all(str(table))
    assert rc == 1
    assert table.read_text() == '{"config": "imagenet_rn50_ddp", "good": true}\n'
    assert not (tmp_path / "BENCH_TABLE.jsonl.tmp").exists()


def test_run_all_preserves_table_on_partial_failure(tmp_path, monkeypatch):
    """Replacement is all-or-nothing: one config succeeding while others
    fail must not drop the failed configs' previous good rows."""
    import bench

    table = tmp_path / "BENCH_TABLE.jsonl"
    table.write_text('{"config": "old", "good": true}\n')
    monkeypatch.setattr(bench, "probe_backend", lambda: ("fake-chip", None))
    calls = []

    def flaky(name, overrides, *, steps, warmup):
        calls.append(name)
        if len(calls) > 1:
            raise RuntimeError("backend died mid-run")
        return {"_record": {"config": name, "samples_per_sec_per_chip": 1.0,
                            "step_time_median_s": 0.001, "mesh": {}}}

    monkeypatch.setattr(bench, "bench_config", flaky)
    rc = bench.run_all(str(table))
    assert rc == 1
    assert table.read_text() == '{"config": "old", "good": true}\n'
    assert not (tmp_path / "BENCH_TABLE.jsonl.tmp").exists()


def test_main_falls_through_candidate_ladder(monkeypatch, capsys):
    """If the headline candidate's child fails, main() must fall through
    to the next candidate and still print exactly one final JSON line."""
    import json as _json

    import bench

    monkeypatch.setattr(bench, "probe_backend", lambda: ("fake-chip", None))

    calls = []

    def fake_run_bounded(argv, timeout_s):
        spec = _json.loads(argv[argv.index("--child") + 1])
        calls.append(spec["config"])
        if spec["config"] == "imagenet_rn50_ddp":
            return 1, "", "simulated OOM"  # child failed
        result = {"metric": spec["metric"], "value": 123.0,
                  "unit": "samples/sec/chip", "vs_baseline": 0.5}
        return 0, "RESULT " + _json.dumps(result) + "\n", ""

    monkeypatch.setattr(bench, "_run_bounded", fake_run_bounded)
    monkeypatch.setattr("sys.argv", ["bench.py"])
    rc = bench.main()
    assert rc == 0
    assert calls == ["imagenet_rn50_ddp", "mnist_mlp"]
    final = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert len(final) == 1
    rec = _json.loads(final[0])
    assert rec["metric"] == "mnist_mlp_samples_per_sec_per_chip"
    assert rec["value"] == 123.0
