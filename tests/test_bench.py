"""Benchmark harness: protocol record completeness (BASELINE.md §protocol)."""

from __future__ import annotations
import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast


import json
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench

import pytest


@pytest.fixture(autouse=True)
def sandbox_last_good(tmp_path, monkeypatch):
    """Point the last-good evidence cache at a sandbox for EVERY test here.

    The round-5 self-poisoning bug: ``test_main_falls_through_candidate_
    ladder`` drives ``main()``, which calls ``_save_last_good`` — so every
    pytest run stamped the fixture value (123.0) into the committed
    ``bench_last_good.json``, and the tier-1 stale fallback could never
    re-emit real data. The env var covers subprocess reachers; the setattr
    covers the already-imported module object.
    """
    path = tmp_path / "bench_last_good.json"
    monkeypatch.setenv("FRL_BENCH_LAST_GOOD_PATH", str(path))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(path))
    yield path


def test_save_last_good_writes_sandbox_not_repo(sandbox_last_good):
    """The committed evidence cache must be untouchable from tests: writes
    land in the env-overridden sandbox and the repo copy stays
    byte-identical (it holds only real relay captures — the regenerated
    2256.04 protocol-row record, corroborable by BENCH_TABLE.jsonl)."""
    repo_cache = os.path.join(
        os.path.dirname(os.path.abspath(bench.__file__)),
        "bench_last_good.json",
    )
    before = open(repo_cache, "rb").read() if os.path.exists(repo_cache) else None
    bench._save_last_good({"metric": "m", "value": 1.0, "unit": "x",
                           "vs_baseline": 0.0})
    assert sandbox_last_good.exists()
    after = open(repo_cache, "rb").read() if os.path.exists(repo_cache) else None
    assert before == after, (
        "a test wrote the committed bench_last_good.json — the sandbox "
        "fixture is not covering some _save_last_good path"
    )
    if before is not None:
        assert json.loads(before).get("value") != 123.0, (
            "the committed cache holds the old test-fixture value again"
        )


def test_bench_config_emits_protocol_record():
    perf = bench.bench_config(
        "mnist_mlp",
        ["data.global_batch_size=64", "trainer.log_every=1000000"],
        steps=4,
        warmup=1,
    )
    rec = perf["_record"]
    for key in (
        "config", "model", "global_batch_size", "per_chip_batch_size",
        "mesh", "param_sharding", "precision", "n_chips", "chip",
        "steps_per_sec", "samples_per_sec_per_chip", "step_time_median_s",
        "step_time_p90_s",
    ):
        assert key in rec, f"protocol record missing {key}"
    assert rec["samples_per_sec_per_chip"] > 0
    assert rec["per_chip_batch_size"] * rec["n_chips"] == 64


def test_protocol_record_reports_mfu_when_peak_known(monkeypatch):
    """On chips with a known bf16 peak the record must carry model FLOPs +
    MFU (BASELINE.md protocol). CPU has no honest peak, so inject one —
    this exercises the same path the TPU jaxpr-fallback count feeds."""
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "cpu")
    monkeypatch.setitem(bench.CHIP_PEAK_FLOPS, kind, 1e12)
    # 2-step windows: the production default of 20 would run 60+ MNIST
    # steps here just to time them — irrelevant to what this test asserts.
    monkeypatch.setenv("FRL_BENCH_WINDOW", "2")
    perf = bench.bench_config(
        "mnist_mlp",
        ["data.global_batch_size=64", "trainer.log_every=1000000"],
        steps=4,
        warmup=1,
    )
    rec = perf["_record"]
    assert rec.get("model_flops_per_sample", 0) > 0
    assert 0 < rec["mfu"] < 1.0


def test_run_all_writes_jsonl(tmp_path, monkeypatch):
    monkeypatch.setenv("FRL_BENCH_WINDOW", "2")
    monkeypatch.setattr(
        bench, "ALL_CONFIGS",
        [("mnist_mlp", ["data.global_batch_size=64"], 4)],
    )
    out = tmp_path / "table.jsonl"
    assert bench.run_all(str(out)) == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 1 and lines[0]["config"] == "mnist_mlp"


def test_run_all_preserves_table_when_backend_down(tmp_path, monkeypatch):
    """A dead relay must never clobber the last good BENCH_TABLE capture
    with a one-line probe-error record."""
    import bench

    table = tmp_path / "BENCH_TABLE.jsonl"
    table.write_text('{"config": "imagenet_rn50_ddp", "good": true}\n')
    monkeypatch.setattr(
        bench, "probe_backend", lambda: (None, "backend init timeout")
    )
    rc = bench.run_all(str(table))
    assert rc == 1
    assert table.read_text() == '{"config": "imagenet_rn50_ddp", "good": true}\n'


def test_run_all_preserves_table_when_all_configs_fail(tmp_path, monkeypatch):
    """Backend dies AFTER a successful probe: all rows error out — the
    previous capture must still survive (staged-tmp-file invariant)."""
    import bench

    table = tmp_path / "BENCH_TABLE.jsonl"
    table.write_text('{"config": "imagenet_rn50_ddp", "good": true}\n')
    monkeypatch.setattr(bench, "probe_backend", lambda: ("fake-chip", None))
    def boom(*a, **k):
        raise RuntimeError("backend died mid-run")
    monkeypatch.setattr(bench, "bench_config", boom)
    rc = bench.run_all(str(table))
    assert rc == 1
    assert table.read_text() == '{"config": "imagenet_rn50_ddp", "good": true}\n'
    assert not (tmp_path / "BENCH_TABLE.jsonl.tmp").exists()


def test_run_all_preserves_table_on_partial_failure(tmp_path, monkeypatch):
    """Replacement is all-or-nothing: one config succeeding while others
    fail must not drop the failed configs' previous good rows."""
    import bench

    table = tmp_path / "BENCH_TABLE.jsonl"
    table.write_text('{"config": "old", "good": true}\n')
    monkeypatch.setattr(bench, "probe_backend", lambda: ("fake-chip", None))
    calls = []

    def flaky(name, overrides, *, steps, warmup):
        calls.append(name)
        if len(calls) > 1:
            raise RuntimeError("backend died mid-run")
        return {"_record": {"config": name, "samples_per_sec_per_chip": 1.0,
                            "step_time_median_s": 0.001, "mesh": {}}}

    monkeypatch.setattr(bench, "bench_config", flaky)
    rc = bench.run_all(str(table))
    assert rc == 1
    assert table.read_text() == '{"config": "old", "good": true}\n'
    assert not (tmp_path / "BENCH_TABLE.jsonl.tmp").exists()


def test_main_falls_through_candidate_ladder(monkeypatch, capsys):
    """If the headline candidate's child fails, main() must fall through
    to the next candidate and still print exactly one final JSON line."""
    import json as _json

    import bench

    monkeypatch.setattr(bench, "probe_backend", lambda: ("fake-chip", None))

    calls = []

    def fake_run_bounded(argv, timeout_s):
        spec = _json.loads(argv[argv.index("--child") + 1])
        calls.append(spec["config"])
        if spec["config"] == "imagenet_rn50_ddp":
            return 1, "", "simulated OOM"  # child failed
        result = {"metric": spec["metric"], "value": 123.0,
                  "unit": "samples/sec/chip", "vs_baseline": 0.5}
        return 0, "RESULT " + _json.dumps(result) + "\n", ""

    monkeypatch.setattr(bench, "_run_bounded", fake_run_bounded)
    monkeypatch.setattr("sys.argv", ["bench.py"])
    rc = bench.main()
    assert rc == 0
    assert calls == ["imagenet_rn50_ddp", "mnist_mlp"]
    final = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert len(final) == 1
    rec = _json.loads(final[0])
    assert rec["metric"] == "mnist_mlp_samples_per_sec_per_chip"
    assert rec["value"] == 123.0
