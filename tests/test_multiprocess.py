"""2-process ``jax.distributed`` integration (SURVEY §4 simulated-distributed
tier, call stack (a)): the ``num_processes > 1`` branches of initialize/
collectives/data-sharding actually execute — on CPU, via a real TCP
rendezvous between two subprocesses (VERDICT r1 #5)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_init_collectives_and_train(tmp_path):
    port = _free_port()
    workers = []
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env_base = {
        **os.environ,
        "FRL_TPU_COORDINATOR": f"127.0.0.1:{port}",
        "FRL_TPU_NUM_PROCESSES": "2",
        "FRL_TEST_WORKDIR": str(tmp_path),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        # Script-by-path puts tests/ on sys.path, not the repo root; keep any
        # existing entries (the axon sitecustomize lives on PYTHONPATH).
        "PYTHONPATH": repo_root
        + (os.pathsep + os.environ["PYTHONPATH"] if os.environ.get("PYTHONPATH") else ""),
    }
    script = os.path.join(os.path.dirname(__file__), "_twoproc_worker.py")
    for pid in range(2):
        env = {**env_base, "FRL_TPU_PROCESS_ID": str(pid)}
        workers.append(
            subprocess.Popen(
                [sys.executable, script],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
        )
    outputs = []
    for w in workers:
        out, _ = w.communicate(timeout=280)
        outputs.append(out)
    for w, out in zip(workers, outputs):
        assert w.returncode == 0, f"worker failed:\n{out[-3000:]}"

    checks = []
    for out in outputs:
        lines = [l for l in out.splitlines() if l.startswith("CHECK ")]
        assert lines, f"no CHECK line in worker output:\n{out[-3000:]}"
        checks.append(json.loads(lines[-1][6:]))

    by_pid = {c["pid"]: c for c in checks}
    assert set(by_pid) == {0, 1}
    for c in checks:
        assert c["process_count"] == 2
        assert c["local_devices"] == 4
        assert c["global_devices"] == 8
        assert c["broadcast"] == 41.0  # process 0's value, on both
        assert c["all_gather"] == [0, 1]
        assert c["local_batch"] == 8  # 16 global over 2 processes
    # The global loss reduction must agree across processes exactly.
    assert by_pid[0]["loss"] == by_pid[1]["loss"]
    # Hybrid ICI x DCN mesh across the real process boundary trains too.
    for c in checks:
        assert c["dcn_mesh"]["data"] == 8
        assert np.isfinite(c["dcn_loss"])
    assert by_pid[0]["dcn_loss"] == by_pid[1]["dcn_loss"]
