"""2-process ``jax.distributed`` integration (SURVEY §4 simulated-distributed
tier, call stack (a)): the ``num_processes > 1`` branches of initialize/
collectives/data-sharding actually execute — on CPU, via a real TCP
rendezvous between two subprocesses (VERDICT r1 #5)."""

import json

import numpy as np
import pytest

from _mp_harness import free_port, rendezvous_env, run_workers


def _write_index_corpus(tmp_path, n=256, size=8):
    """Shard corpus whose images CONSTANT-encode their own sample index
    (pixel value = index/255) and labels = index: pairing and per-host
    draws become assertable after gather + augment."""
    import os

    d = os.path.join(str(tmp_path), "corpus")
    os.makedirs(d, exist_ok=True)
    half = n // 2
    for shard in range(2):
        idx = np.arange(shard * half, (shard + 1) * half)
        imgs = np.broadcast_to(
            (idx / 255.0).astype(np.float32)[:, None, None, None],
            (half, size, size, 3),
        ).copy()
        np.save(os.path.join(d, f"train_images_{shard:03d}.npy"), imgs)
        np.save(os.path.join(d, f"train_labels_{shard:03d}.npy"), idx)


def test_two_process_init_collectives_and_train(tmp_path):
    _write_index_corpus(tmp_path)
    env_base = rendezvous_env(tmp_path, free_port(), device_count=4)
    envs = [
        {**env_base, "FRL_TPU_PROCESS_ID": str(pid)} for pid in range(2)
    ]
    rcs, outputs = run_workers("_twoproc_worker.py", envs, timeout=280)
    for rc, out in zip(rcs, outputs):
        assert rc == 0, f"worker failed:\n{out[-3000:]}"

    checks = []
    for out in outputs:
        lines = [l for l in out.splitlines() if l.startswith("CHECK ")]
        assert lines, f"no CHECK line in worker output:\n{out[-3000:]}"
        checks.append(json.loads(lines[-1][6:]))

    by_pid = {c["pid"]: c for c in checks}
    assert set(by_pid) == {0, 1}
    for c in checks:
        assert c["process_count"] == 2
        assert c["local_devices"] == 4
        assert c["global_devices"] == 8
        assert c["broadcast"] == 41.0  # process 0's value, on both
        assert c["all_gather"] == [0, 1]
        assert c["local_batch"] == 8  # 16 global over 2 processes
    # The global loss reduction must agree across processes exactly.
    assert by_pid[0]["loss"] == by_pid[1]["loss"]
    # Hybrid ICI x DCN mesh across the real process boundary trains too.
    for c in checks:
        assert c["dcn_mesh"]["data"] == 8
        assert np.isfinite(c["dcn_loss"])
    assert by_pid[0]["dcn_loss"] == by_pid[1]["dcn_loss"]

    # Per-host input contract over the real on-disk corpus (SURVEY C16):
    # each host drew its own samples (host_offset flows into the sampling
    # rng — identical draws would mean silent per-host duplication), the
    # image<->label pairing survived the native gather+augment path, and
    # each host's addressable slice of the GLOBAL batch is exactly its
    # local draw (make_array_from_process_local_data assembly).
    for c in checks:
        assert c["rd_pixel_decode_ok"], c
        assert c["rd_global_matches_local"], c
        assert len(c["rd_local_labels"]) == 8
    assert by_pid[0]["rd_local_labels"] != by_pid[1]["rd_local_labels"]
