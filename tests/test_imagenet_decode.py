"""JPEG → shard producer (tools/decode_imagenet.py) + loader round-trip.

The encode/decode halves run in a subprocess (TensorFlow is IO-only
tooling and must never load into the training/test process); the loader
assertions run here on the produced shards — the same contract a real
ImageNet copy would exercise (SURVEY C16).
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRODUCER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import tensorflow as tf

    raw, out = sys.argv[1], sys.argv[2]
    rng = np.random.default_rng(0)
    for ci, cls in enumerate(["n01440764", "n01443537"]):
        d = os.path.join(raw, "train", cls)
        os.makedirs(d, exist_ok=True)
        for i in range(6):
            # Distinct mean per class so labels are checkable post-decode.
            img = np.full((40 + 8 * i, 36, 3), 40 + 150 * ci, np.uint8)
            img += rng.integers(0, 20, img.shape, dtype=np.uint8)
            tf.io.write_file(
                os.path.join(d, f"img_{i}.JPEG"),
                tf.io.encode_jpeg(tf.constant(img)),
            )
    sys.argv = [
        "decode_imagenet.py", raw, out, "--split", "train",
        "--size", "32", "--shard-items", "5", "--dtype", "uint8",
    ]
    sys.path.insert(0, os.path.join(%r, "tools"))
    import decode_imagenet
    raise SystemExit(decode_imagenet.main())
    """
) % (REPO_ROOT,)


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("imagenet_jpeg")
    raw, out = str(tmp / "raw"), str(tmp / "shards")
    env = {**os.environ, "CUDA_VISIBLE_DEVICES": "-1",
           "TF_CPP_MIN_LOG_LEVEL": "2"}
    env.pop("XLA_FLAGS", None)  # keep TF from parsing jax's sim-device flag
    proc = subprocess.run(
        [sys.executable, "-c", _PRODUCER, raw, out],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    return out


def test_producer_emits_paired_shards(shard_dir):
    xs = sorted(f for f in os.listdir(shard_dir) if "images" in f)
    ys = sorted(f for f in os.listdir(shard_dir) if "labels" in f)
    assert len(xs) == len(ys) == 3  # 12 images / 5 per shard
    x0 = np.load(os.path.join(shard_dir, xs[0]))
    assert x0.shape == (5, 32, 32, 3) and x0.dtype == np.uint8
    meta = json.load(open(os.path.join(shard_dir, "train_meta.json")))
    assert meta["images"] == 12 and meta["classes"] == 2


def test_loader_round_trip_uint8_scaling(shard_dir):
    from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig

    from frl_distributed_ml_scaffold_tpu.data.imagenet import ImageNet

    cfg = DataConfig(
        name="imagenet", image_size=32, num_classes=2, data_dir=shard_dir,
        global_batch_size=8,
    )
    ds = ImageNet(cfg, split="train")
    assert not ds.is_synthetic
    batch = ds.batch(0, 8)
    x, y = batch["image"], batch["label"]
    assert x.shape == (8, 32, 32, 3) and x.dtype == np.float32
    assert set(np.unique(y)) <= {0, 1}
    # uint8 shards were rescaled to [0,1] BEFORE ImageNet normalization:
    # values land in the standardized range, not 0-255.
    assert np.abs(x).max() < 10.0
    # The two classes were encoded with far-apart pixel means; after
    # normalization their per-image means must still separate by label.
    means = x.mean(axis=(1, 2, 3))
    if (y == 0).any() and (y == 1).any():
        assert means[y == 1].min() > means[y == 0].max()
