"""Fused BatchNorm-backward kernel gates (ops/fused_bn.py).

The same contract every kernel in the repo is held to: interpreter-mode
equivalence against the autodiff reference (fwd AND bwd, fp32 stats under
the bf16 policy) at every distinct RN50 BN channel width, plus
GSPMD-compatibility — the kernel path trains under the 8-device CPU-sim
``data×fsdp`` mesh with loss parity vs the unfused path.
"""

from __future__ import annotations

import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.ops import fused_bn as fb

#: Every distinct (channels, spatial) class of RN50's BN sites, spatially
#: shrunk (the kernel tiles rows = N*H*W, so row COUNT not layout is what
#: varies): stem 64ch, stage1 64/256, stage2 128/512, stage3 256/1024,
#: stage4 512/2048. 64 and 512 also exercise sub-128-lane padding; odd
#: spatial sizes exercise row padding.
RN50_BN_SHAPES = [
    (4, 6, 6, 64),
    (2, 5, 5, 256),
    (2, 4, 4, 128),
    (2, 3, 3, 512),
    (2, 3, 3, 1024),
    (2, 2, 2, 2048),
]


def _make(shape, dtype, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=shape), dtype)
    w = jnp.asarray(rng.normal(size=shape), jnp.float32)
    return x, w


def _ref_module(dtype):
    return nn.BatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5, dtype=dtype
    )


def _fused_module(dtype, interpret):
    return fb.FusedBatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5, dtype=dtype,
        interpret=interpret,
    )


@pytest.mark.parametrize("shape", RN50_BN_SHAPES,
                         ids=[f"c{s[-1]}" for s in RN50_BN_SHAPES])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
def test_fused_bn_matches_autodiff_reference(shape, dtype):
    """Interpreter-mode kernel equivalence at every RN50 BN width: forward
    bit-equal, running stats bit-equal, dγ/dβ within fp32 tolerance, dx
    within fp32 tolerance (fp32) / one bf16 ulp (bf16 — the fused formula
    rounds once where the autodiff chain rounds per op)."""
    x, w = _make(shape, dtype, seed=shape[-1])
    ref = _ref_module(dtype)
    variables = ref.init({"params": jax.random.key(0)}, x)
    fused_vars = _fused_module(dtype, True).init({"params": jax.random.key(0)}, x)
    assert jax.tree.map(jnp.shape, variables) == jax.tree.map(
        jnp.shape, fused_vars
    ), "FusedBatchNorm must be a drop-in: identical variable tree"
    params, stats = variables["params"], variables["batch_stats"]

    def run(module, p, x_):
        y, upd = module.apply(
            {"params": p, "batch_stats": stats}, x_, mutable=["batch_stats"]
        )
        return y, upd["batch_stats"]

    def loss(module, p, x_):
        return jnp.sum(run(module, p, x_)[0].astype(jnp.float32) * w)

    fused = _fused_module(dtype, True)
    y_ref, stats_ref = jax.jit(lambda p, x_: run(ref, p, x_))(params, x)
    y_fused, stats_fused = jax.jit(lambda p, x_: run(fused, p, x_))(params, x)
    np.testing.assert_array_equal(
        np.asarray(y_ref, np.float32), np.asarray(y_fused, np.float32)
    )
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(stats_ref[k]), np.asarray(stats_fused[k]), rtol=1e-6
        )

    g_ref = jax.jit(jax.grad(lambda p: loss(ref, p, x)))(params)
    g_fused = jax.jit(jax.grad(lambda p: loss(fused, p, x)))(params)
    for k in ("scale", "bias"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_fused[k]),
            rtol=2e-4, atol=2e-4,
        )

    dx_ref = np.asarray(
        jax.jit(jax.grad(lambda x_: loss(ref, params, x_)))(x), np.float32
    )
    dx_fused = np.asarray(
        jax.jit(jax.grad(lambda x_: loss(fused, params, x_)))(x), np.float32
    )
    if dtype == jnp.float32:
        np.testing.assert_allclose(dx_ref, dx_fused, rtol=1e-5, atol=1e-5)
    else:
        atol = 2 * float(jnp.finfo(jnp.bfloat16).eps) * max(
            1.0, float(np.abs(dx_ref).max())
        )
        np.testing.assert_allclose(dx_ref, dx_fused, rtol=0.05, atol=atol)


def test_fused_bn_eval_path_matches_flax():
    """use_running_average=True (eval AND init) must be plain flax — same
    output, no custom vjp in the way."""
    x, _ = _make((4, 5, 5, 64), jnp.bfloat16)
    ref = nn.BatchNorm(use_running_average=True, momentum=0.9,
                       epsilon=1e-5, dtype=jnp.bfloat16)
    fused = fb.FusedBatchNorm(use_running_average=True, momentum=0.9,
                              epsilon=1e-5, dtype=jnp.bfloat16)
    v = ref.init({"params": jax.random.key(1)}, x)
    y_ref = ref.apply(v, x)
    y_fused = fused.apply(v, x)
    np.testing.assert_array_equal(
        np.asarray(y_ref, np.float32), np.asarray(y_fused, np.float32)
    )


def test_fused_bn_trains_under_data_fsdp_mesh(tmp_path):
    """The GSPMD gate: model.fused_bn=true RN50 smoke-train under the
    8-device CPU-sim data×fsdp mesh, KERNEL path (interpreter forced
    through the Trainer via FORCE_INTERPRET), with loss parity vs the
    unfused path — first step identical (the forward is the same
    function), trajectory within one-bf16-ulp drift."""
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    base = [
        "model.depth=10", "data.image_size=32", "data.num_classes=8",
        "model.num_classes=8", "data.global_batch_size=16",
        "optimizer.learning_rate=0.05", "optimizer.warmup_steps=0",
        "mesh.data=2", "mesh.fsdp=4",
        "parallel.param_sharding=fsdp", "parallel.fsdp_min_size=64",
        "trainer.log_every=1000", "checkpoint.enabled=false",
        f"workdir={tmp_path}",
    ]

    def run(fused: str, force_interpret: bool):
        fb.FORCE_INTERPRET = True if force_interpret else None
        try:
            cfg = apply_overrides(
                get_config("imagenet_rn50_ddp"),
                base + [f"model.fused_bn={fused}"],
            )
            trainer = Trainer(cfg)
            state = trainer.init_state()
            losses = []
            for step in range(4):
                batch = trainer.pipeline.global_batch(step)
                state, metrics = trainer.train_step(state, batch)
                losses.append(float(metrics["loss"]))
            return losses
        finally:
            fb.FORCE_INTERPRET = None

    ref = run("false", False)
    kernel = run("true", True)
    assert np.isfinite(kernel).all(), kernel
    assert kernel[-1] < kernel[0], f"no learning: {kernel}"
    # Identical forward => identical first-step loss.
    assert abs(ref[0] - kernel[0]) < 1e-4, (ref[0], kernel[0])
    # bf16-rounding drift only thereafter.
    assert abs(ref[-1] - kernel[-1]) < 5e-2 * max(1.0, abs(ref[-1])), (
        ref, kernel,
    )


def test_fused_bn_rejects_unfusable_configs_to_flax():
    """Configurations outside the kernel contract (masking, non-trailing
    feature axis, axis_name stats) must silently take the stock flax path,
    not miscompute."""
    x, _ = _make((4, 4, 4, 32), jnp.float32)
    mask = jnp.ones(x.shape, bool)
    fused = fb.FusedBatchNorm(use_running_average=False, epsilon=1e-5)
    ref = nn.BatchNorm(use_running_average=False, epsilon=1e-5)
    v = ref.init({"params": jax.random.key(0)}, x)
    y_ref, _ = ref.apply(v, x, mask=mask, mutable=["batch_stats"])
    y_fused, _ = fused.apply(v, x, mask=mask, mutable=["batch_stats"])
    np.testing.assert_array_equal(np.asarray(y_ref), np.asarray(y_fused))
