"""Logging must stay backend-free: a host-side code path that merely wants
a logger (native core loader, offline tools) must never trigger device
bring-up — on an unreachable TPU relay that blocks forever (observed)."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import os
import subprocess
import sys


def test_is_primary_process_initializes_no_backend():
    code = (
        "import sys; sys.path.insert(0, '.')\n"
        "from frl_distributed_ml_scaffold_tpu.utils.logging import (\n"
        "    get_logger, is_primary_process)\n"
        "assert is_primary_process() is True\n"
        "get_logger().info('hello')\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, xla_bridge._backends\n"
        "print('NO_BACKEND_OK')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}  # harmless if it DID init
    r = subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "NO_BACKEND_OK" in r.stdout


def test_tensorboard_sink_writes_event_file(tmp_path):
    """trainer.tensorboard=true writes TB scalar events next to the JSONL
    (lazy TF import; JSONL stays the record of truth)."""
    import glob

    import pytest

    pytest.importorskip("tensorflow")  # the sink degrades without TF
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["trainer.total_steps=4", "trainer.log_every=2",
         "trainer.tensorboard=true", "data.global_batch_size=16",
         "model.hidden_sizes=16", "checkpoint.enabled=false",
         f"workdir={tmp_path}"],
    )
    Trainer(cfg).fit()
    events = glob.glob(str(tmp_path / "mnist_mlp" / "tb" / "events.*"))
    assert events, "no TensorBoard event file written"
