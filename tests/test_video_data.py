"""Video clip-shard loader (SURVEY C16 'Ego4D clip loaders'): producer/
consumer round trip, determinism, config-shape validation, fallback."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.video import VideoClips, write_clip_shards


def make_corpus(tmp_path, n=20, t=4, s=16, c=3, classes=5, shard_size=8):
    rng = np.random.default_rng(0)
    clips = rng.standard_normal((n, t, s, s, c)).astype(np.float32)
    labels = rng.integers(0, classes, size=n)
    n_shards = write_clip_shards(
        str(tmp_path), clips, labels, shard_size=shard_size
    )
    assert n_shards == -(-n // shard_size)
    return clips, labels


def video_cfg(tmp_path, **kw):
    base = dict(
        name="video", data_dir=str(tmp_path), num_frames=4, image_size=16,
        channels=3, num_classes=5,
    )
    base.update(kw)
    return DataConfig(**base)


def test_round_trip_clips_match_source(tmp_path):
    clips, labels = make_corpus(tmp_path)
    src = VideoClips(video_cfg(tmp_path), split="train")
    assert not src.is_synthetic
    batch = src.batch(1, batch_size=6)
    assert batch["video"].shape == (6, 4, 16, 16, 3)
    flat_src = clips.reshape(len(clips), -1)
    for clip, label in zip(batch["video"], batch["label"]):
        row = clip.reshape(-1)
        matches = np.where((flat_src == row).all(axis=1))[0]
        assert len(matches) >= 1  # exact stored clip, crossing shard bounds
        assert labels[matches[0]] == label


def test_step_determinism(tmp_path):
    make_corpus(tmp_path)
    a = VideoClips(video_cfg(tmp_path), split="train").batch(7, 4)
    b = VideoClips(video_cfg(tmp_path), split="train").batch(7, 4)
    np.testing.assert_array_equal(a["video"], b["video"])
    c = VideoClips(video_cfg(tmp_path), split="train").batch(8, 4)
    assert not np.array_equal(a["video"], c["video"])


def test_config_shape_mismatch_raises(tmp_path):
    make_corpus(tmp_path, t=4, s=16)
    with pytest.raises(ValueError, match="stored clips"):
        VideoClips(video_cfg(tmp_path, num_frames=8), split="train")


def test_missing_dir_falls_back_with_warning(tmp_path):
    from conftest import capture_frl_logs

    with capture_frl_logs() as records:
        src = VideoClips(video_cfg(tmp_path / "nope"), split="train")
    assert src.is_synthetic
    assert any("SYNTHETIC" in m for m in records)
    assert src.batch(0, 2)["video"].shape == (2, 4, 16, 16, 3)


def test_unpaired_label_shard_raises(tmp_path):
    """A partially-copied corpus (missing labels shard) must fail at
    construction, never silently misalign labels (review-caught)."""
    import os

    make_corpus(tmp_path, n=20, shard_size=8)  # 3 shards
    os.remove(tmp_path / "train_labels_001.npy")
    with pytest.raises(ValueError, match="pair up"):
        VideoClips(video_cfg(tmp_path), split="train")


def test_divergent_shard_shapes_raise(tmp_path):
    make_corpus(tmp_path, n=8, t=4, shard_size=8)
    # Regenerate shard 1 with a different T.
    rng = np.random.default_rng(1)
    np.save(
        tmp_path / "train_clips_001.npy",
        rng.standard_normal((8, 8, 16, 16, 3)).astype(np.float32),
    )
    np.save(tmp_path / "train_labels_001.npy", rng.integers(0, 5, size=8))
    with pytest.raises(ValueError, match="inconsistent"):
        VideoClips(video_cfg(tmp_path), split="train")


def test_imagenet_warns_on_missing_dir(tmp_path):
    from conftest import capture_frl_logs

    from frl_distributed_ml_scaffold_tpu.data.imagenet import ImageNet

    with capture_frl_logs() as records:
        src = ImageNet(
            DataConfig(name="imagenet", data_dir=str(tmp_path / "nope")),
            split="train",
        )
    assert src.is_synthetic
    assert any("SYNTHETIC" in m for m in records)


def test_video_recipe_trains_on_real_shards(tmp_path):
    """BASELINE config 5 accepts data.name=video + data_dir."""
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    corpus = tmp_path / "clips"
    corpus.mkdir()
    make_corpus(corpus, n=32, t=4, s=32, classes=8, shard_size=16)
    cfg = apply_overrides(
        get_config("ego4d_video_elastic"),
        [
            "model.image_size=32",
            "model.num_frames=4",
            "model.tubelet_size=2,8,8",
            "model.hidden_dim=64",
            "model.num_layers=2",
            "model.num_heads=4",
            "model.num_classes=8",
            "data.name=video",
            f"data.data_dir={corpus}",
            "data.image_size=32",
            "data.num_frames=4",
            "data.num_classes=8",
            "data.global_batch_size=8",
            "data.prefetch=0",
            "precision.policy=fp32",
            "trainer.log_every=1000",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    assert not trainer.pipeline.source.is_synthetic
    state = trainer.init_state()
    for step in range(2):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
