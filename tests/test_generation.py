"""KV-cache autoregressive generation (models/generation.py): the decode
path must produce the same logits as the full causal forward, and sampling
must be a pure function of the rng key."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jit import jit_apply, jit_init

from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig, PrecisionConfig
from frl_distributed_ml_scaffold_tpu.models.generation import generate
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
from frl_distributed_ml_scaffold_tpu.precision import get_policy

FP32 = get_policy(PrecisionConfig(policy="fp32"))
TINY = dict(
    vocab_size=64, num_layers=2, num_heads=2, hidden_dim=32, seq_len=24, dropout=0.0
)


@pytest.fixture(scope="module")
def gpt():
    model = GPT(GPTConfig(**TINY), FP32)
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params, tokens


def test_prefill_matches_full_forward(gpt):
    """Decode-mode prefill (masked attention over the padded cache) must
    equal the plain causal forward at every prompt position."""
    model, params, tokens = gpt
    full = jit_apply(model, train=False)({"params": params}, tokens)
    prefill, _ = jit_apply(model, decode=True, mutable=["cache"])(
        {"params": params}, tokens
    )
    np.testing.assert_allclose(full, prefill, atol=1e-5, rtol=1e-5)


def test_stepwise_decode_matches_full_forward(gpt):
    """Feeding tokens one at a time through the cache must reproduce the
    full forward's next-token logits at every step — the KV cache is
    correct, not just self-consistent."""
    model, params, tokens = gpt
    full = jit_apply(model, train=False)({"params": params}, tokens)
    _, vars_out = jit_apply(model, decode=True, mutable=["cache"])(
        {"params": params}, tokens[:, :1]
    )
    cache = vars_out["cache"]
    # One compiled single-token step reused across the whole decode loop.
    step = jit_apply(model, decode=True, mutable=["cache"])
    for i in range(1, tokens.shape[1]):
        logits, vars_out = step(
            {"params": params, "cache": cache}, tokens[:, i : i + 1]
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            full[:, i], logits[:, 0], atol=2e-5, rtol=1e-5
        )


def test_greedy_generation_deterministic_and_bounded(gpt):
    model, params, tokens = gpt
    out1 = generate(model, params, tokens, max_new_tokens=6, temperature=0.0)
    out2 = generate(model, params, tokens, max_new_tokens=6, temperature=0.0)
    assert out1.shape == (2, 14)
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[:, :8], tokens)
    assert int(out1.max()) < 64 and int(out1.min()) >= 0


def test_sampled_generation_is_pure_function_of_rng(gpt):
    model, params, tokens = gpt
    a = generate(
        model, params, tokens, max_new_tokens=5, temperature=0.8, top_k=8,
        rng=jax.random.key(7),
    )
    b = generate(
        model, params, tokens, max_new_tokens=5, temperature=0.8, top_k=8,
        rng=jax.random.key(7),
    )
    c = generate(
        model, params, tokens, max_new_tokens=5, temperature=0.8, top_k=8,
        rng=jax.random.key(8),
    )
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)  # different key, different continuation


def test_generation_refuses_context_overflow(gpt):
    model, params, tokens = gpt
    with pytest.raises(ValueError, match="exceeds the model context"):
        generate(model, params, tokens, max_new_tokens=17)  # 8 + 17 > 24


def test_generate_bucketed_decode_never_materializes_full_context(gpt):
    """The whole generate() program — prefill + scanned decode — under a
    16-token cache bucket materializes NO array carrying the full
    ``seq_len`` (the PR 4 decode pin, now via analysis.pins and extended
    from the single decode step to the end-to-end sampling program; the
    wpe param is an invar and exempt by construction)."""
    from frl_distributed_ml_scaffold_tpu.analysis import pins

    model, params, tokens = gpt
    jaxpr = jax.make_jaxpr(
        lambda p, t: generate(
            model, p, t, max_new_tokens=6, temperature=0.0, cache_len=16
        )
    )(params, tokens)
    pins.assert_no_dim_materialized(jaxpr, model.config.seq_len)
    # And the bucket is actually in play (a cache-free rewrite would
    # also pass the negative pin).
    assert any(16 in s for s in pins.eqn_output_shapes(jaxpr))


def test_eos_padding(gpt):
    """Once eos is emitted (forced here via vocab-restricted greedy), the
    remaining positions hold eos."""
    model, params, tokens = gpt
    out = generate(
        model, params, tokens, max_new_tokens=6, temperature=0.0, eos_id=int(
            generate(model, params, tokens, max_new_tokens=1, temperature=0.0)[0, -1]
        ),
    )
    # The first generated token IS the eos id for row 0, so every later
    # position in row 0 must repeat it.
    assert np.all(np.asarray(out[0, 8:]) == out[0, 8])


def test_top_p_sampling_restricts_support(gpt):
    """Nucleus sampling with a tiny p must only ever emit the argmax when
    one token dominates the distribution — and stays a pure function of
    the rng key."""
    from frl_distributed_ml_scaffold_tpu.models.generation import _sample

    # Row 0: one dominant token; row 1: fully tied (the case where a
    # value-threshold nucleus would silently keep everything — the mask is
    # positional, so exactly ceil-to-p of the stable sort order survives).
    logits = jnp.stack(
        [
            jnp.array([10.0, 0.0, 0.0, 0.0]),
            jnp.zeros((4,)),
        ]
    )
    for seed in range(8):
        tok = _sample(
            logits, jax.random.key(seed), temperature=1.0, top_k=0,
            top_p=0.5,
        )
        assert int(tok[0]) == 0  # dominant token holds >0.99 mass
        # Uniform row: mass_before < 0.5 keeps exactly 2 of 4; the sort is
        # stable descending (argsort of -logits), so the tied survivors
        # are the LOWEST indices (0, then 1).
        assert int(tok[1]) in (0, 1)
    a = generate(
        *gpt[:2], gpt[2], max_new_tokens=4, temperature=0.9, top_p=0.8,
        rng=jax.random.key(3),
    )
    b = generate(
        *gpt[:2], gpt[2], max_new_tokens=4, temperature=0.9, top_p=0.8,
        rng=jax.random.key(3),
    )
    np.testing.assert_array_equal(a, b)


def test_beam_search_one_beam_equals_greedy(gpt):
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    model, params, tokens = gpt
    greedy = generate(model, params, tokens, max_new_tokens=6, temperature=0.0)
    beam, scores = beam_search(
        model, params, tokens, max_new_tokens=6, num_beams=1
    )
    np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))
    assert scores.shape == (2,) and np.isfinite(np.asarray(scores)).all()


def test_beam_search_beats_or_matches_greedy_logprob(gpt):
    """The whole point of beams: the returned sequence's sum log-prob must
    be >= greedy's (greedy is one path in the searched space)."""
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    model, params, tokens = gpt
    n_new = 6

    def seq_logprob(full):
        logits = jit_apply(model, train=False)({"params": params}, full[:, :-1])
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            lp[:, -n_new:], full[:, -n_new:, None].astype(jnp.int32), axis=-1
        )[..., 0]
        return picked.sum(-1)

    greedy = generate(model, params, tokens, max_new_tokens=n_new, temperature=0.0)
    beam, scores = beam_search(
        model, params, tokens, max_new_tokens=n_new, num_beams=4
    )
    g_lp = np.asarray(seq_logprob(jnp.asarray(greedy)))
    b_lp = np.asarray(seq_logprob(jnp.asarray(beam)))
    assert (b_lp >= g_lp - 1e-4).all(), (b_lp, g_lp)
    # And the search's own score agrees with the independent forward.
    np.testing.assert_allclose(np.asarray(scores), b_lp, atol=2e-3, rtol=1e-4)


def test_beam_search_eos_freezes_beams(gpt):
    """A finished beam may only repeat eos at zero extra log-prob: its
    score must freeze at the finishing step and the tail must be eos."""
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    model, params, tokens = gpt
    # Use the greedy first token of row 0 as eos: beam 0 finishes at once.
    eos = int(
        generate(model, params, tokens, max_new_tokens=1, temperature=0.0)[0, -1]
    )
    out, scores = beam_search(
        model, params, tokens, max_new_tokens=5, num_beams=3, eos_id=eos
    )
    out = np.asarray(out)
    row0_new = out[0, 8:]
    if row0_new[0] == eos:  # the eos beam won the search
        assert (row0_new == eos).all()
        # Frozen score == single-token log-prob of eos, independently
        # computed from the full forward.
        logits = jit_apply(model, train=False)({"params": params}, gpt[2])
        lp = jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
        np.testing.assert_allclose(
            float(scores[0]), float(lp[eos]), atol=2e-3
        )
    else:  # a live beam out-scored the frozen one — also legal; check it
        assert float(scores[0]) >= float(
            jax.nn.log_softmax(
                jit_apply(model, train=False)({"params": params}, gpt[2])[
                    0, -1
                ].astype(jnp.float32)
            )[eos]
        ) - 1e-4


def test_beam_search_length_penalty_reranks(gpt):
    """alpha=0 returns raw sums; alpha>0 returns sum/len**alpha. With no
    eos every beam has the same length, so the winning SEQUENCE must be
    identical and the score exactly the normalized raw score."""
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    model, params, tokens = gpt
    n_new = 5
    raw_toks, raw_scores = beam_search(
        model, params, tokens, max_new_tokens=n_new, num_beams=3
    )
    lp_toks, lp_scores = beam_search(
        model, params, tokens, max_new_tokens=n_new, num_beams=3,
        length_penalty=1.0,
    )
    np.testing.assert_array_equal(np.asarray(lp_toks), np.asarray(raw_toks))
    np.testing.assert_allclose(
        np.asarray(lp_scores), np.asarray(raw_scores) / n_new, rtol=1e-6
    )


def test_generation_works_with_moe_model():
    """The MoE GPT returns (logits, aux) tuples — prefill, cached decode,
    and beam search must all handle that shape (and the expert routing
    must run in decode mode)."""
    from frl_distributed_ml_scaffold_tpu.config.schema import MoEConfig
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    # num_groups=4 does NOT divide the decode-step token count (n = batch
    # = 2 at one token per sequence): _num_groups must gcd-snap instead of
    # raising, or grouped-MoE checkpoints could never be sampled.
    model = GPT(
        GPTConfig(
            **TINY, moe=MoEConfig(num_experts=4, top_k=2, num_groups=4)
        ),
        FP32,
    )
    tokens = jax.random.randint(jax.random.key(4), (2, 6), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    out = generate(model, params, tokens, max_new_tokens=4, temperature=0.0)
    assert out.shape == (2, 10) and int(np.asarray(out).max()) < 64
    beam, scores = beam_search(
        model, params, tokens, max_new_tokens=4, num_beams=2
    )
    assert beam.shape == (2, 10)
    assert np.isfinite(np.asarray(scores)).all()


@pytest.mark.parametrize(
    "pp_kw",
    [
        dict(pipeline_stages=2, pipeline_microbatches=2),
        dict(
            num_layers=4,
            pipeline_stages=2,
            pipeline_microbatches=2,
            pipeline_circular_repeat=2,
        ),
    ],
    ids=["gpipe", "circular"],
)
def test_generation_from_pipeline_trained_params(pp_kw):
    """A pipeline-trained checkpoint must generate without config surgery:
    generate()/beam_search restack the stage-stacked weights onto the plain
    layer stack (pure reshape). Correctness anchor: the plain model with
    restacked params reproduces the pipeline model's full-forward logits
    exactly, and decode from the PP model equals decode from that plain
    twin."""
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search
    from frl_distributed_ml_scaffold_tpu.models.gpt import (
        unstack_pipeline_params,
    )

    cfg = dataclasses.replace(GPTConfig(**TINY), **pp_kw)
    pp_model = GPT(cfg, FP32)
    tokens = jax.random.randint(jax.random.key(7), (2, 6), 0, 64)
    pp_params = jit_init(pp_model, tokens, train=False)["params"]

    plain = GPT(dataclasses.replace(cfg, pipeline_stages=1), FP32)
    restacked = unstack_pipeline_params(cfg, pp_params)
    # The restack is numerically exact: full forwards agree.
    pp_logits = jit_apply(pp_model, train=False)({"params": pp_params}, tokens)
    plain_logits = jit_apply(plain, train=False)({"params": restacked}, tokens)
    np.testing.assert_allclose(pp_logits, plain_logits, atol=1e-5, rtol=1e-5)

    # generate() accepts the PP model + PP params directly.
    out_pp = generate(pp_model, pp_params, tokens, max_new_tokens=4,
                      temperature=0.0)
    out_plain = generate(plain, restacked, tokens, max_new_tokens=4,
                         temperature=0.0)
    np.testing.assert_array_equal(out_pp, out_plain)
    assert out_pp.shape == (2, 10)

    beam, scores = beam_search(
        pp_model, pp_params, tokens, max_new_tokens=3, num_beams=2
    )
    assert beam.shape == (2, 9)
    assert np.isfinite(np.asarray(scores)).all()


def test_generation_under_tp_mesh():
    """Tensor-parallel INFERENCE: generate() with Megatron-sharded params
    on a data x model mesh must equal the unsharded decode exactly — the
    KV caches are pinned head-sharded by the decode path itself
    (models/gpt.py `_constrain_kv_cache` + the shard_map'd
    ops/decode_attention entry; the deeper gates live in
    tests/test_serving.py)."""
    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        build_mesh,
        mesh_context,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import gpt_tp_rules
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        shard_params_for_serving,
    )

    cfg = GPTConfig(**{**TINY, "num_heads": 2, "hidden_dim": 32})
    model = GPT(cfg, FP32)
    tokens = jax.random.randint(jax.random.key(9), (2, 6), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    ref = generate(model, params, tokens, max_new_tokens=5, temperature=0.0)

    env = build_mesh(MeshConfig(data=4, model=2))
    with mesh_context(env):
        sharded = shard_params_for_serving(params, env, gpt_tp_rules())
        qk = sharded["blocks"]["attn"]["query"]["kernel"]
        assert "model" in tuple(
            e for e in qk.sharding.spec if e
        ), qk.sharding.spec  # TP actually active
        out = generate(
            model, sharded, tokens, max_new_tokens=5, temperature=0.0
        )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_ragged_prompts_match_per_row_generation(gpt):
    """Left-padded prompts + a lengths array: each row of a mixed-length
    batch must generate exactly what it would generate alone (prefill must
    neither attend over nor cache the pad columns)."""
    model, params, tokens = gpt
    short = tokens[1:2, :3]  # row 1 truncated to 3 real tokens
    padded = jnp.concatenate(
        [tokens[0:1], jnp.concatenate(
            [jnp.zeros((1, 5), jnp.int32), short], axis=1
        )],
        axis=0,
    )  # [2, 8]: row 0 dense, row 1 = [pad x5 | 3 real]
    lens = jnp.asarray([8, 3], jnp.int32)
    out = generate(
        model, params, padded, max_new_tokens=5, temperature=0.0,
        prompt_lengths=lens,
    )
    ref_full = generate(
        model, params, tokens, max_new_tokens=5, temperature=0.0
    )
    ref_short = generate(
        model, params, short, max_new_tokens=5, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(out)[0], np.asarray(ref_full)[0])
    np.testing.assert_array_equal(
        np.asarray(out)[1, 8:], np.asarray(ref_short)[0, 3:]
    )
    # And the prompt region is returned as passed (pads included).
    np.testing.assert_array_equal(np.asarray(out)[:, :8], np.asarray(padded))


def test_ragged_prompts_beam_search_matches_per_row(gpt):
    """beam_search rides the same shared prefill: a left-padded row must
    return the same beam (tokens and score) as its unpadded solo run."""
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    model, params, tokens = gpt
    short = tokens[1:2, :4]
    padded = jnp.concatenate(
        [jnp.zeros((1, 4), jnp.int32), short], axis=1
    )  # [1, 8]
    lens = jnp.asarray([4], jnp.int32)
    beam_p, score_p = beam_search(
        model, params, padded, max_new_tokens=4, num_beams=3,
        prompt_lengths=lens,
    )
    beam_s, score_s = beam_search(
        model, params, short, max_new_tokens=4, num_beams=3
    )
    np.testing.assert_array_equal(
        np.asarray(beam_p)[0, 8:], np.asarray(beam_s)[0, 4:]
    )
    np.testing.assert_allclose(
        np.asarray(score_p), np.asarray(score_s), atol=1e-5, rtol=1e-6
    )


def test_eos_early_retirement_generate_and_beam(gpt):
    """Both decode consumers share one attention entry point and one eos
    discipline: after every row has emitted eos, generate() must only
    append eos (the retired rows never un-retire), and a finished beam's
    score must be IDENTICAL whether the search runs 3 or 8 steps past its
    eos (frozen beams extend at zero additional log-prob)."""
    from frl_distributed_ml_scaffold_tpu.models.generation import beam_search

    model, params, tokens = gpt
    # Greedy first tokens per row — using row 0's as eos retires row 0 at
    # step 1; row 1 retires whenever it happens to emit it.
    first = np.asarray(
        generate(model, params, tokens, max_new_tokens=1, temperature=0.0)
    )[:, -1]
    eos = int(first[0])
    out = np.asarray(
        generate(
            model, params, tokens, max_new_tokens=8, temperature=0.0,
            eos_id=eos,
        )
    )
    row0 = out[0, 8:]
    assert row0[0] == eos and (row0 == eos).all(), row0
    for r in range(out.shape[0]):
        new = out[r, 8:]
        hits = np.flatnonzero(new == eos)
        if hits.size:  # everything after the first eos is eos
            assert (new[hits[0]:] == eos).all(), new

    short, s_short = beam_search(
        model, params, tokens, max_new_tokens=3, num_beams=3, eos_id=eos
    )
    long, s_long = beam_search(
        model, params, tokens, max_new_tokens=8, num_beams=3, eos_id=eos
    )
    short, long = np.asarray(short), np.asarray(long)
    # Row 0's winning beam finished at its first token in both runs (or a
    # live beam outscored it in both — either way scores must agree when
    # the winner is the frozen one).
    if short[0, 8] == eos and long[0, 8] == eos:
        assert (long[0, 8:] == eos).all()
        np.testing.assert_allclose(
            float(s_short[0]), float(s_long[0]), atol=1e-5
        )
