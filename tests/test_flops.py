"""Jaxpr FLOP counter (utils/flops.py): exact on known shapes, consistent
with XLA's own cost analysis where that exists (CPU), wired as the bench
MFU fallback for backends without cost analysis."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import jax
import jax.numpy as jnp
import numpy as np

from frl_distributed_ml_scaffold_tpu.utils.flops import fn_flops


def test_matmul_flops_exact():
    a = jnp.zeros((64, 128))
    b = jnp.zeros((128, 32))
    assert fn_flops(lambda a, b: a @ b, a, b) == 2 * 64 * 128 * 32


def test_batched_dot_flops_exact():
    a = jnp.zeros((8, 64, 128))
    b = jnp.zeros((8, 128, 32))
    f = lambda a, b: jax.lax.batch_matmul(a, b)
    assert fn_flops(f, a, b) == 8 * 2 * 64 * 128 * 32


def test_conv_flops_exact():
    import flax.linen as nn

    x = jnp.zeros((4, 16, 16, 8))
    conv = nn.Conv(32, (3, 3), padding="SAME", use_bias=False)
    params = conv.init(jax.random.key(0), x)
    got = fn_flops(lambda p, x: conv.apply(p, x), params, x)
    assert got == 2 * 4 * 16 * 16 * 32 * 8 * 9  # out_elems * cin * k_spatial


def test_scan_multiplies_by_length():
    w = jnp.zeros((16, 16))

    def f(w, x):
        def body(c, _):
            return c @ w, None

        out, _ = jax.lax.scan(body, x, None, length=7)
        return out

    x = jnp.zeros((16, 16))
    assert fn_flops(f, w, x) == 7 * 2 * 16 * 16 * 16


def test_grad_counts_backward_too():
    a = jnp.zeros((32, 32))
    b = jnp.zeros((32, 32))
    fwd = fn_flops(lambda a, b: (a @ b).sum(), a, b)
    with_bwd = fn_flops(jax.grad(lambda a, b: (a @ b).sum(), argnums=(0, 1)), a, b)
    # d(a@b) needs two more matmuls of the same size.
    assert with_bwd == 3 * fwd


def test_pallas_call_multiplied_by_grid():
    """The pallas_call jaxpr param is ONE grid cell's kernel; the counter
    must multiply by the grid or flash-attention FLOPs undercount by the
    whole grid (review-caught bug)."""
    from frl_distributed_ml_scaffold_tpu.ops.flash_attention import flash_attention

    def mk(t):
        q = jnp.zeros((1, t, 2, 64), jnp.float32)
        return q, q, q

    f = lambda q, k, v: flash_attention(
        q, k, v, causal=False, block_q=128, block_k=128, interpret=True
    )
    f256 = fn_flops(f, *mk(256))  # grid (1, 2, 2, 2)
    f512 = fn_flops(f, *mk(512))  # grid (1, 2, 4, 4)
    # Non-causal attention FLOPs are quadratic in T: 2x T -> 4x FLOPs.
    assert f512 == 4 * f256, (f256, f512)
    # Absolute: QK^T + PV = 2 matmuls of 2*T*T*D per (b, h).
    assert f256 == 2 * (2 * 2 * 256 * 256 * 64), f256


def test_agrees_with_xla_cost_analysis_on_cpu():
    """XLA's CPU cost analysis counts elementwise FLOPs too, so the jaxpr
    count must be a large fraction of (but never exceed) XLA's."""
    import flax.linen as nn

    model = nn.Dense(256)
    x = jnp.zeros((128, 512))
    params = model.init(jax.random.key(0), x)

    def loss(p, x):
        return (model.apply(p, x) ** 2).mean()

    g = jax.grad(loss)
    lowered = jax.jit(g).lower(params, x)
    xla_flops = float(lowered.cost_analysis()["flops"])
    ours = fn_flops(g, params, x)
    assert ours <= xla_flops * 1.01
    assert ours >= 0.8 * xla_flops


def test_trainer_cost_analysis_has_flops(tmp_path):
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "data.global_batch_size=64",
            "data.prefetch=0",
            "model.hidden_sizes=32",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = trainer.pipeline.global_batch(0)
    cost = trainer.step_cost_analysis(state, batch)
    assert cost is not None and float(cost["flops"]) > 0
    # The fallback path must agree with whatever XLA said (within the
    # elementwise-op slack) so MFU doesn't jump across backends.
    from frl_distributed_ml_scaffold_tpu.utils.flops import fn_flops as ff

    jaxpr_flops = trainer._mesh_scoped(ff)(trainer._train_step_fn, state, batch)
    assert jaxpr_flops <= float(cost["flops"]) * 1.01
    assert jaxpr_flops >= 0.5 * float(cost["flops"])
