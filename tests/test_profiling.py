"""Profiling tier (SURVEY C19): trace-window capture through the trainer."""

from __future__ import annotations

import glob
import os

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
from frl_distributed_ml_scaffold_tpu.launcher.launch import hlo_dump_flags
from frl_distributed_ml_scaffold_tpu.utils.profiling import (
    WindowProfiler,
    annotate,
)

import pytest


@pytest.fixture(scope="module")
def profiled_run(tmp_path_factory):
    """One profiling-enabled trainer run shared by the trace tests."""
    workdir = tmp_path_factory.mktemp("profiled")
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=8",
            "trainer.log_every=4",
            "trainer.profile_steps=3",
            "trainer.profile_start_step=2",
            "data.global_batch_size=32",
            "checkpoint.enabled=false",
            f"workdir={workdir}",
        ],
    )
    Trainer(cfg).fit()
    return os.path.join(workdir, cfg.name, "trace")


def test_trainer_profile_window_writes_trace(profiled_run):
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the dir.
    assert glob.glob(os.path.join(profiled_run, "**", "*.xplane.pb"),
                     recursive=True), f"no trace written under {profiled_run}"


def test_window_profiler_short_run_stops_cleanly(tmp_path):
    p = WindowProfiler(str(tmp_path / "t"), start_step=0, num_steps=100)
    p.step_start(0)  # run "ends" before the window does
    p.stop()
    assert not p._active
    p.stop()  # idempotent


def test_window_profiler_disabled_is_noop(tmp_path):
    p = WindowProfiler(str(tmp_path / "t"), start_step=0, num_steps=0)
    for s in range(5):
        p.step_start(s)
    p.stop()
    assert not (tmp_path / "t").exists()


def test_annotate_and_flags():
    with annotate("phase"):
        pass
    flags = hlo_dump_flags("/tmp/dump")
    assert "--xla_dump_to=/tmp/dump" in flags


def test_trace_analyze_reports_cleanly_on_sim_trace(profiled_run):
    """tools/trace_analyze.py on a CPU-sim capture must say there is no
    TPU plane (instead of silent empty output) and exit 0; on-chip traces
    get the per-op table."""
    import subprocess
    import sys

    tool = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "tools",
        "trace_analyze.py",
    )
    r = subprocess.run(
        [sys.executable, tool, profiled_run],
        capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, r.stderr[-400:]
    assert "no /device:TPU plane" in r.stdout


def test_trace_overlap_interval_math():
    """trace_analyze's comm/compute overlap sweep (the overlap-scheduled
    FSDP evidence path): union-merge and intersection must be exact on
    touching, nested and disjoint intervals."""
    from tools.trace_analyze import COMM_OPS, _intersection_len, _merge

    assert _merge([(5, 10), (0, 3), (2, 6), (20, 25)]) == [(0, 10), (20, 25)]
    assert _intersection_len([(0, 10)], [(5, 15)]) == 5
    assert _intersection_len([(0, 2), (8, 12)], [(1, 9)]) == 2
    assert _intersection_len([(0, 2)], [(3, 4)]) == 0
    # the classifier must recognize the collectives the overlap schedule
    # emits (fusion names embed these substrings)
    assert "all-gather" in COMM_OPS and "reduce-scatter" in COMM_OPS


def test_trace_overlap_summary_output(capsys):
    """overlap_summary on a synthetic lane: 4 ms of comm, 3 ms hidden
    under compute, 1 ms exposed."""
    from tools.trace_analyze import overlap_summary

    class E:
        def __init__(self, mid, off_ms, dur_ms):
            self.metadata_id = mid
            self.offset_ps = int(off_ms * 1e9)
            self.duration_ps = int(dur_ms * 1e9)

    class Line:
        events = [
            E(1, 0.0, 5.0),   # compute [0, 5)
            E(2, 2.0, 4.0),   # all-gather [2, 6) -> 3 hidden, 1 exposed
        ]

    emeta = {1: "fusion.42", 2: "all-gather-start.3"}
    overlap_summary(Line(), emeta)
    out = capsys.readouterr().out
    assert "comm 4.00 ms total" in out
    assert "3.00 ms hidden" in out and "75.0%" in out
    assert "1.00 ms exposed" in out


def test_trace_overlap_summary_zero_duration_comm(capsys):
    """Async collective pairs can log zero-duration start/done markers; a
    lane with only those must report 'no duration', not ZeroDivisionError."""
    from tools.trace_analyze import overlap_summary

    class E:
        def __init__(self, mid, off_ps, dur_ps):
            self.metadata_id = mid
            self.offset_ps = off_ps
            self.duration_ps = dur_ps

    class Line:
        events = [E(1, 0, 5_000_000), E(2, 2_000_000, 0)]

    overlap_summary(Line(), {1: "fusion.1", 2: "all-gather-start.7"})
    out = capsys.readouterr().out
    assert "no duration" in out


def test_trace_overlap_classifies_ppermute_hidden_vs_exposed(capsys):
    """Per-class overlap classification (the tp_overlap A/B evidence path):
    a synthetic lane with one collective-permute span fully hidden under
    compute and one fully exposed must bucket 2 ms hidden / 2 ms exposed
    under the collective-permute class — and keep the fsdp classes
    (all-gather here) separately bucketed in the same capture."""
    from tools.trace_analyze import classify_overlap, overlap_summary

    ms = int(1e9)
    events = [
        ("fusion.loop_multiply.9", 0 * ms, 6 * ms),      # compute [0, 6)
        ("collective-permute-start.1", 1 * ms, 3 * ms),  # hidden  [1, 3)
        ("collective-permute-done.2", 8 * ms, 10 * ms),  # exposed [8, 10)
        ("all-gather-fusion.3", 5 * ms, 7 * ms),         # 1 hidden, 1 exposed
    ]
    stats = classify_overlap(events)
    cp = stats["collective-permute"]
    assert cp["total_ms"] == pytest.approx(4.0)
    assert cp["hidden_ms"] == pytest.approx(2.0)
    assert cp["exposed_ms"] == pytest.approx(2.0)
    ag = stats["all-gather"]
    assert ag["hidden_ms"] == pytest.approx(1.0)
    assert ag["exposed_ms"] == pytest.approx(1.0)
    assert stats["all"]["total_ms"] == pytest.approx(6.0)
    assert stats["all"]["hidden_ms"] == pytest.approx(3.0)

    # The printed summary carries the per-class lines.
    class E:
        def __init__(self, mid, start, end):
            self.metadata_id = mid
            self.offset_ps = start
            self.duration_ps = end - start

    lane_events = [E(i, a, b) for i, (_, a, b) in enumerate(events)]

    class Line:
        pass

    Line.events = lane_events
    emeta = {i: name for i, (name, _, _) in enumerate(events)}
    overlap_summary(Line(), emeta)
    out = capsys.readouterr().out
    assert "collective-permute: 4.00 ms, 2.00 hidden / 2.00 exposed" in out
    assert "all-gather: 2.00 ms, 1.00 hidden / 1.00 exposed" in out


def test_trace_decode_classifies_kernel_vs_cache_update(capsys):
    """Decode-serving classification (the serve_bench on-chip capture,
    BACKLOG R8-1): a synthetic lane with a fused decode-attention kernel
    span, per-row cache scatter spans, and surrounding projection fusions
    must split the step time into kernel / cache-update / other — and the
    printed summary must only appear when decode work is present."""
    from tools.trace_analyze import classify_decode, decode_summary

    ms = int(1e9)
    events = [
        ("fusion.matmul.3", 0 * ms, 4 * ms),
        ("custom-call.decode_kernel.1", 4 * ms, 7 * ms),
        ("dynamic-update-slice-fusion.2", 7 * ms, 8 * ms),
        ("scatter.9", 8 * ms, 10 * ms),
        ("fusion.sample.4", 10 * ms, 11 * ms),
        # A sharded decode lane's collective: "reduce-scatter" must NOT
        # substring-match the bare "scatter" cache class — comm time is
        # classify_overlap's business, here it lands in "other".
        ("reduce-scatter.5", 11 * ms, 13 * ms),
    ]
    stats = classify_decode(events)
    assert stats["decode_kernel_ms"] == pytest.approx(3.0)
    assert stats["cache_update_ms"] == pytest.approx(3.0)
    assert stats["other_ms"] == pytest.approx(7.0)

    class E:
        def __init__(self, mid, start, end):
            self.metadata_id = mid
            self.offset_ps = start
            self.duration_ps = end - start

    class Line:
        pass

    Line.events = [E(i, a, b) for i, (_, a, b) in enumerate(events)]
    emeta = {i: name for i, (name, _, _) in enumerate(events)}
    decode_summary(Line(), emeta)
    out = capsys.readouterr().out
    assert "decode: kernel 3.00 ms" in out
    assert "cache update 3.00 ms" in out

    # A training lane (no decode kernel) prints nothing.
    Line.events = Line.events[:1]
    decode_summary(Line(), {0: events[0][0]})
    assert capsys.readouterr().out == ""
