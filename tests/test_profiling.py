"""Profiling tier (SURVEY C19): trace-window capture through the trainer."""

from __future__ import annotations

import glob
import os

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
from frl_distributed_ml_scaffold_tpu.launcher.launch import hlo_dump_flags
from frl_distributed_ml_scaffold_tpu.utils.profiling import (
    WindowProfiler,
    annotate,
)


def test_trainer_profile_window_writes_trace(tmp_path):
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=8",
            "trainer.log_every=4",
            "trainer.profile_steps=3",
            "trainer.profile_start_step=2",
            "data.global_batch_size=32",
            "checkpoint.enabled=false",
            f"workdir={tmp_path}",
        ],
    )
    trainer = Trainer(cfg)
    trainer.fit()
    trace_root = os.path.join(tmp_path, cfg.name, "trace")
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the dir.
    assert glob.glob(os.path.join(trace_root, "**", "*.xplane.pb"),
                     recursive=True), f"no trace written under {trace_root}"


def test_window_profiler_short_run_stops_cleanly(tmp_path):
    p = WindowProfiler(str(tmp_path / "t"), start_step=0, num_steps=100)
    p.step_start(0)  # run "ends" before the window does
    p.stop()
    assert not p._active
    p.stop()  # idempotent


def test_window_profiler_disabled_is_noop(tmp_path):
    p = WindowProfiler(str(tmp_path / "t"), start_step=0, num_steps=0)
    for s in range(5):
        p.step_start(s)
    p.stop()
    assert not (tmp_path / "t").exists()


def test_annotate_and_flags():
    with annotate("phase"):
        pass
    flags = hlo_dump_flags("/tmp/dump")
    assert "--xla_dump_to=/tmp/dump" in flags
