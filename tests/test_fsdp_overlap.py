"""Overlap-scheduled FSDP (parallel/fsdp_overlap.py): the explicit
blockwise all-gather / reduce-scatter schedule must (i) match the plain
GSPMD FSDP path numerically on every mesh composition, (ii) gather
BLOCKWISE — one layer's slice inside the scan body, never the stacked
full-model tensor — and (iii) refuse configs it cannot honor."""

# NOT in the `fast` tier: this module is a multi-mesh numerics grid
# (~50 s warm), which the tier's selection rule keeps out by design —
# same category as the pipeline equivalence grids (COVERAGE.md).

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

GPT_TINY = [
    "model.num_layers=2", "model.num_heads=4", "model.hidden_dim=64",
    "model.seq_len=64", "model.vocab_size=256",
    "data.seq_len=64", "data.vocab_size=256",
    "data.global_batch_size=16",
    "trainer.grad_accum=1", "trainer.remat=none",
    "trainer.log_every=1000000",
    "precision.policy=fp32",
    "checkpoint.enabled=false",
    "optimizer.warmup_steps=0",
    "parallel.fsdp_min_size=16",
]

RN_TINY = [
    "model.depth=10", "model.num_classes=10",
    "data.name=synthetic_imagenet", "data.image_size=32",
    "data.num_classes=10", "data.global_batch_size=16",
    "trainer.grad_accum=1", "trainer.remat=none",
    "trainer.log_every=1000000",
    "precision.policy=fp32",
    "optimizer.name=sgd", "optimizer.learning_rate=0.01",
    "optimizer.warmup_steps=0",
    "checkpoint.enabled=false",
    "parallel.fsdp_min_size=16",
]

FSDP = ["parallel.param_sharding=fsdp", "parallel.opt_sharding=like_params"]


def make_trainer(name, base, overrides, tmp_path):
    cfg = apply_overrides(
        get_config(name), base + [f"workdir={tmp_path}"] + list(overrides)
    )
    env = build_mesh(cfg.mesh)
    return Trainer(cfg, mesh_env=env)


def run_steps(trainer, n=3):
    state = trainer.init_state()
    for step in range(n):
        state, metrics = trainer.train_step(
            state, trainer.pipeline.global_batch(step)
        )
    return jax.device_get(state), jax.device_get(metrics)


def assert_params_close(a, b, atol=2e-3):
    """Default tolerance: well inside the ISSUE's 2e-2 acceptance band.
    It can't be 1e-5-tight under adamw: parameters whose true gradient is
    ~0 (e.g. attn/key/bias — softmax is key-bias invariant) get their
    float-noise gradients amplified to lr-scale sign updates by m/sqrt(v),
    and the explicit-collective path reorders those reductions. Losses and
    grad norms stay bit-identical (asserted where compared)."""
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=atol, rtol=1e-4),
        a.params,
        b.params,
    )


def gpt_pair(tmp_path, mesh, extra=()):
    """(plain-FSDP state, overlap state) after 3 identical steps."""
    ref = make_trainer(
        "gpt2_medium_zero1", GPT_TINY, mesh + FSDP + list(extra),
        tmp_path / "ref",
    )
    ovl = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY, mesh + list(extra),
        tmp_path / "ovl",
    )
    return run_steps(ref), run_steps(ovl)


def test_overlap_matches_fsdp_only_mesh(tmp_path):
    """fsdp=8: the pure-FSDP mesh (batch sharded over fsdp too)."""
    (ref, ref_m), (ovl, ovl_m) = gpt_pair(
        tmp_path, ["mesh.data=1", "mesh.fsdp=8"]
    )
    assert_params_close(ref, ovl)
    np.testing.assert_allclose(ovl_m["loss"], ref_m["loss"], atol=1e-5)
    # The overlap config must actually shard the block params (a silently
    # replicated run would also "match").
    t = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY,
        ["mesh.data=1", "mesh.fsdp=8"], tmp_path / "shard",
    )
    state = t.init_state()
    qk = state.params["blocks"]["attn"]["query"]["kernel"]
    assert any(
        e == "fsdp" or (isinstance(e, tuple) and "fsdp" in e)
        for e in qk.sharding.spec
    ), qk.sharding.spec


def test_overlap_matches_data_x_fsdp(tmp_path):
    """data=2 x fsdp=4: the hybrid mesh of the acceptance gate."""
    (ref, _), (ovl, _) = gpt_pair(tmp_path, ["mesh.data=2", "mesh.fsdp=4"])
    assert_params_close(ref, ovl)


def test_overlap_composes_with_tp(tmp_path):
    """data=2 x fsdp=2 x model=2: gathers remove ONLY the fsdp axis; the
    Megatron column/row splits stay sharded through the block compute."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=2", "mesh.fsdp=2", "mesh.model=2"]
    )
    assert_params_close(ref, ovl)


def test_overlap_grad_accum_accumulates_sharded(tmp_path):
    """grad_accum=4: microbatch grads accumulate as SHARDS. Numerics must
    match, and the accumulated-grads constraint keeps the scan carry in
    the params' sharded layout (asserted via the compiled step running on
    the same shardings — a gathered fp32 carry would still be numerically
    right, so the layout is pinned by grad_shardings in make_train_step)."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=1", "mesh.fsdp=8"],
        extra=["trainer.grad_accum=4"],
    )
    assert_params_close(ref, ovl)


@pytest.mark.parametrize("block_remat", ["full", "save_attn"])
def test_overlap_block_remat_interaction(tmp_path, block_remat):
    """Per-block remat modes compose: the gather rides inside the remat
    region, so the backward re-gathers under every policy."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=1", "mesh.fsdp=8"],
        extra=[f"model.block_remat={block_remat}"],
    )
    assert_params_close(ref, ovl)


def test_overlap_remat_full_interaction(tmp_path):
    """trainer.remat=full (whole-loss checkpoint) around the hooked model."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=1", "mesh.fsdp=8"],
        extra=["trainer.remat=full"],
    )
    assert_params_close(ref, ovl)


# --------------------------------------------------------------- blockwise
# Jaxpr pins ride the shared analysis.pins API (docs/static_analysis.md);
# the per-test _walk_jaxpr copy this file used to carry lives in
# analysis/jaxpr_utils.py.

from frl_distributed_ml_scaffold_tpu.analysis import pins


def test_overlap_gathers_are_blockwise(tmp_path):
    """Peak gathered-param live set is ONE block, not the model: every
    explicit all_gather in the step jaxpr produces a per-layer SLICE shape
    (the stacked [L, ...] leaves never pass through a gather), and the
    gathers sit inside the scan body, where XLA's collective pipeliner can
    overlap iteration k+1's gather with iteration k's compute."""
    t = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY,
        ["mesh.data=2", "mesh.fsdp=4"], tmp_path,
    )
    state = t.init_state()
    batch = t.pipeline.global_batch(0)
    with mesh_context(t.env):
        jaxpr = jax.make_jaxpr(t._train_step_fn)(state, batch)

    pins.assert_collective_present(
        jaxpr, "all_gather", "overlap mode produced no explicit all_gather"
    )

    stacked = {
        tuple(l.shape) for l in jax.tree.leaves(state.params["blocks"])
    }
    sliced = {s[1:] for s in stacked}
    # Membership in the per-layer slice set is the whole pin: it excludes
    # the stacked [L, ...] leaves (different rank) and bounds every
    # gather's bytes at one block's worth.
    pins.assert_all_gather_outputs_within(
        jaxpr, sliced,
        "an all_gather output is not a per-block param slice "
        f"(expected one of {sorted(sliced)}) — the gather is NOT blockwise",
    )

    # The scan body must contain the gathers (that's what makes the
    # schedule per-iteration): at least one scan eqn exists whose body
    # carries all_gather eqns.
    scans = pins.scan_collective_counts(jaxpr, "all_gather")
    assert any(n > 0 for n in scans), (
        "no scan body contains the explicit gathers — they were hoisted "
        f"out of the layer loop (scan gather counts: {scans})"
    )


def test_overlap_backward_has_reduce_scatter(tmp_path):
    """The gather's transpose is an explicit reduce-scatter (psum_scatter
    binds the ``reduce_scatter`` primitive): gradients leave each block as
    shards, never as full-model tensors."""
    t = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY,
        ["mesh.data=2", "mesh.fsdp=4"], tmp_path,
    )
    state = t.init_state()
    batch = t.pipeline.global_batch(0)
    with mesh_context(t.env):
        jaxpr = jax.make_jaxpr(t._train_step_fn)(state, batch)
    pins.assert_collective_present(
        jaxpr, "reduce_scatter",
        "no explicit reduce_scatter in the overlap step jaxpr — gradients "
        "are not being scattered back into shards",
    )


# ----------------------------------------------------------------- resnet


def test_resnet_overlap_matches(tmp_path):
    """Per-block gather on the (non-scanned) ResNet stack, BatchNorm
    mutation and all, matches the GSPMD FSDP path."""
    ref = make_trainer(
        "imagenet_rn50_ddp", RN_TINY,
        ["mesh.data=2", "mesh.fsdp=4"] + FSDP, tmp_path / "ref",
    )
    ovl = make_trainer(
        "imagenet_rn50_ddp", RN_TINY,
        ["mesh.data=2", "mesh.fsdp=4"] + FSDP + ["parallel.fsdp_overlap=true"],
        tmp_path / "ovl",
    )
    (ref_s, _), (ovl_s, _) = run_steps(ref), run_steps(ovl)
    assert_params_close(ref_s, ovl_s)
    # BatchNorm running stats advance identically too.
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=1e-5, rtol=1e-5),
        ref_s.extras,
        ovl_s.extras,
    )


@pytest.mark.parametrize("prefetch", [0, 2])
def test_resnet_prefetch_window_is_numerics_neutral(tmp_path, prefetch):
    """fsdp_prefetch only reorders the schedule (optimization_barrier
    gates); any window must produce identical math."""
    ref = make_trainer(
        "imagenet_rn50_ddp", RN_TINY,
        ["mesh.data=2", "mesh.fsdp=4"] + FSDP, tmp_path / "ref",
    )
    ovl = make_trainer(
        "imagenet_rn50_ddp", RN_TINY,
        ["mesh.data=2", "mesh.fsdp=4"] + FSDP
        + ["parallel.fsdp_overlap=true", f"parallel.fsdp_prefetch={prefetch}"],
        tmp_path / "ovl",
    )
    (ref_s, _), (ovl_s, _) = run_steps(ref, n=2), run_steps(ovl, n=2)
    assert_params_close(ref_s, ovl_s)


# ------------------------------------------------------------- validation


def test_overlap_requires_fsdp_sharding(tmp_path):
    with pytest.raises(ValueError, match="param_sharding"):
        make_trainer(
            "gpt2_medium_zero1", GPT_TINY,
            ["mesh.fsdp=8", "parallel.fsdp_overlap=true"], tmp_path,
        )


def test_overlap_refuses_pipeline(tmp_path):
    with pytest.raises(ValueError, match="pipeline"):
        make_trainer(
            "gpt2_medium_fsdp_overlap", GPT_TINY,
            ["mesh.data=1", "mesh.fsdp=4", "mesh.pipe=2",
             "model.num_layers=4", "model.pipeline_stages=2"],
            tmp_path,
        )


def test_overlap_refuses_negative_prefetch(tmp_path):
    with pytest.raises(ValueError, match="fsdp_prefetch"):
        make_trainer(
            "gpt2_medium_fsdp_overlap", GPT_TINY,
            ["mesh.data=1", "mesh.fsdp=8", "parallel.fsdp_prefetch=-1"],
            tmp_path,
        )


def test_overlap_parity_dryrun_style(tmp_path):
    """dryrun_multichip-style parity: first-step loss of the composed
    data x fsdp overlap mesh agrees with the SAME config on one device
    (tol 2e-2, the driver's parity band)."""
    ovl = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY,
        ["mesh.data=2", "mesh.fsdp=4"], tmp_path / "multi",
    )
    state = ovl.init_state()
    _, m_multi = ovl.train_step(state, ovl.pipeline.global_batch(0))

    cfg1 = apply_overrides(
        get_config("gpt2_medium_zero1"),
        GPT_TINY + [f"workdir={tmp_path}/single", "mesh.data=1", "mesh.fsdp=1"],
    )
    env1 = build_mesh(cfg1.mesh, devices=jax.devices()[:1])
    single = Trainer(cfg1, mesh_env=env1)
    s1 = single.init_state()
    _, m_single = single.train_step(s1, single.pipeline.global_batch(0))
    l_multi, l_single = float(m_multi["loss"]), float(m_single["loss"])
    assert abs(l_multi - l_single) <= 2e-2 * max(1.0, abs(l_single)), (
        l_multi, l_single,
    )
