"""tools/collective_bench.py harness: every collective lowers and times on
the simulated mesh (numbers are meaningless on CPU; the lowering is what
CI asserts — a pod runs the same tool for real ICI/DCN bandwidth)."""


import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast
import json
import os
import subprocess
import sys


def test_collective_bench_runs_all_ops():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    tool = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "tools",
        "collective_bench.py",
    )
    r = subprocess.run(
        [sys.executable, tool, "--mb", "0.25", "--iters", "2"],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert r.returncode == 0, r.stderr[-500:]
    recs = [json.loads(l) for l in r.stdout.splitlines() if l.startswith("{")]
    ops = {rec["op"] for rec in recs}
    assert ops == {
        "all_reduce", "all_gather", "reduce_scatter", "permute", "all_to_all"
    }
    assert all("error" not in rec for rec in recs), recs
    assert all(rec["n"] == 8 and rec["time_us"] > 0 for rec in recs)
