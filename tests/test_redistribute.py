"""Redistribution-service tier (ISSUE 15): plan compiler, executors, and
the three wired seams.

The acceptance headline this file pins: moving state between meshes is
BIT-IDENTICAL to the replicated-staging reference while the executor's
transient stays inside the plan's scratch budget — no full replicated
copy is ever materialized (the arXiv 2112.01075 contract) — and the
three seams hold their composition contracts: elastic restore falls back
down the committed chain exactly like the direct path (the PR 9
torn-write shape, now on a reformed mesh), train→serve params serve
token-identically, and a live pool re-spread preserves decode token
identity for in-flight slots.
"""

from __future__ import annotations

import dataclasses
import os

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.redist

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from _jit import jit_init

from frl_distributed_ml_scaffold_tpu import redistribute as rd
from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.config.schema import (
    GPTConfig,
    ParallelConfig,
    PrecisionConfig,
)
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    MeshConfig,
    build_mesh,
    mesh_context,
)
from frl_distributed_ml_scaffold_tpu.models.generation import generate
from frl_distributed_ml_scaffold_tpu.models.gpt import GPT, gpt_tp_rules
from frl_distributed_ml_scaffold_tpu.parallel.partition import (
    param_specs,
    shard_params_for_serving,
    shardings_from_specs,
)
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.redistribute import executor as rd_exec
from frl_distributed_ml_scaffold_tpu.serving import ServingEngine, build_engine
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

FP32 = get_policy(PrecisionConfig(policy="fp32"))

TINY = dict(
    vocab_size=64, num_layers=2, num_heads=4, hidden_dim=64, seq_len=64,
    dropout=0.0,
)


@pytest.fixture(scope="module")
def gpt():
    model = GPT(GPTConfig(**TINY), FP32)
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params, tokens


@pytest.fixture(scope="module", autouse=True)
def _release_module_state():
    """This module builds dozens of meshes, executor programs, and
    engine jit caches; drop them when it finishes (the
    ``perf_sweep.build()`` discipline) so the modules that run next in
    the suite — the serving wall-clock pins in particular — measure
    under the same process state they saw before this tier existed."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


def _bits(x) -> bytes:
    """Bit-exact comparison handle for any dtype (fp8 included)."""
    return np.asarray(jax.device_get(x)).tobytes()


def _mesh(devices=None, **kw):
    return build_mesh(MeshConfig(**kw), devices=devices)


# ------------------------------------------------------------- plan model


@pytest.mark.fast
def test_identity_plan_is_noop():
    env = _mesh(data=2, fsdp=4)
    x = jax.device_put(
        np.arange(64.0, dtype=np.float32).reshape(8, 8),
        NamedSharding(env.mesh, P("fsdp", None)),
    )
    out, plan = rd.redistribute_tree(
        {"w": x}, {"w": NamedSharding(env.mesh, P("fsdp", None))}
    )
    assert plan.leaves[0].kind == "identity"
    assert plan.bytes_moved == 0 and plan.peak_scratch_bytes == 0
    assert out["w"] is x


@pytest.mark.fast
def test_plan_costs_moved_equals_shard_delta_floor():
    """The 2112.01075 minimality claim as a number: every plan the
    compiler emits moves exactly the bytes each destination shard lacks
    (no gather-everything round-trips hiding in the chunk lists)."""
    env = _mesh(data=1, fsdp=4, model=2)
    serve = _mesh(devices=jax.devices()[:2], data=1, model=2)
    x = jax.ShapeDtypeStruct(
        (64, 64), jnp.float32,
        sharding=NamedSharding(env.mesh, P("fsdp", "model")),
    )
    for dst in (
        NamedSharding(env.mesh, P(None, "model")),
        NamedSharding(env.mesh, P()),
        NamedSharding(serve.mesh, P("model", None)),
    ):
        plan = rd.compile_leaf_plan((64, 64), jnp.float32, x.sharding, dst)
        assert plan.bytes_moved == plan.bytes_lower_bound, (
            str(dst.spec), plan.to_dict(),
        )
    # Replication is the one destination whose per-device need IS the
    # whole leaf; a sharded destination must stay under it.
    sharded = rd.compile_leaf_plan(
        (64, 64), jnp.float32, x.sharding,
        NamedSharding(env.mesh, P(None, "model")),
    )
    assert sharded.peak_scratch_bytes < sharded.leaf_bytes


@pytest.mark.fast
def test_scratch_limit_splits_chunks():
    env = _mesh(data=1, fsdp=4, model=2)
    serve = _mesh(devices=jax.devices()[:2], data=1, model=2)
    src = NamedSharding(env.mesh, P("fsdp", None))
    dst = NamedSharding(serve.mesh, P(None, "model"))
    small = rd.compile_leaf_plan(
        (64, 64), jnp.float32, src, dst, scratch_limit_bytes=1024
    )
    big = rd.compile_leaf_plan((64, 64), jnp.float32, src, dst)
    assert len(small.chunks) > len(big.chunks)
    assert max(c.nbytes for c in small.chunks) <= 1024
    # Identical cost model either way: chunking changes granularity,
    # never WHAT moves.
    assert small.bytes_moved == big.bytes_moved


def test_restore_layout_spec_overlays_unused_axes():
    env = _mesh(data=2, fsdp=4)
    spec = rd.restore_layout_spec((64, 48), P("fsdp", None), env.mesh)
    assert spec == P("fsdp", "data")
    # Nothing to overlay -> the target spec unchanged.
    assert rd.restore_layout_spec((64,), P("fsdp"), env.mesh) == P("fsdp")
    # Indivisible dims shed axes instead of breaking the layout.
    assert rd.restore_layout_spec((7, 5), P(), env.mesh) == P(None, None)
    # The resulting transition is a clean DROP program.
    plan = rd.compile_leaf_plan(
        (64, 48), jnp.float32,
        NamedSharding(env.mesh, spec),
        NamedSharding(env.mesh, P("fsdp", None)),
    )
    assert plan.kind == "collective"
    assert not plan.transition.moves and not plan.transition.adds
    assert plan.transition.drops


# -------------------------------------------------- roundtrip identity grid

MESH_PAIRS = {
    "one_to_n": (
        lambda: (_mesh(devices=[jax.devices()[0]], data=1), P()),
        lambda: (_mesh(data=1, model=8), P("model", None)),
    ),
    "n_to_m_shrink": (
        lambda: (_mesh(devices=jax.devices()[:4], data=1, model=4),
                 P(None, "model")),
        lambda: (_mesh(devices=jax.devices()[:2], data=1, model=2),
                 P(None, "model")),
    ),
    "n_to_m_grow": (
        lambda: (_mesh(devices=jax.devices()[:2], data=1, model=2),
                 P("model", None)),
        lambda: (_mesh(data=1, model=8), P("model", None)),
    ),
    "fsdp_model_to_model_only": (
        lambda: (_mesh(data=1, fsdp=4, model=2), P("fsdp", "model")),
        lambda: (_mesh(devices=jax.devices()[:2], data=1, model=2),
                 P(None, "model")),
    ),
    "mpmd_stage_to_merged": (
        # A stage-local tree on its pipe-slice submesh re-spread onto
        # the full merged mesh (the ISSUE 14 stage layout -> plain
        # stack placement seam).
        lambda: (_mesh(devices=jax.devices()[:2], data=2), P("data", None)),
        lambda: (_mesh(data=2, fsdp=4), P(("data", "fsdp"), None)),
    ),
}

DTYPES = {
    "f32": np.float32,
    "bf16": jnp.bfloat16,
    "int8": np.int8,
    "fp8_e4m3": jnp.float8_e4m3fn,
}


@pytest.mark.parametrize("dtype_name", list(DTYPES))
@pytest.mark.parametrize("pair", list(MESH_PAIRS))
def test_roundtrip_identity_grid(pair, dtype_name):
    """Bit-exact there AND back across every mesh-pair shape the seams
    exercise, for every cache dtype class — int8/fp8 cover the
    quantized-KV scale/payload leaves (PR 6 format vocabulary)."""
    src_fn, dst_fn = MESH_PAIRS[pair]
    src_env, src_spec = src_fn()
    dst_env, dst_spec = dst_fn()
    dtype = DTYPES[dtype_name]
    rng = np.random.default_rng(7)
    x_np = rng.integers(-100, 100, size=(64, 32)).astype(np.float32)
    x_np = np.asarray(jnp.asarray(x_np).astype(dtype))
    x = jax.device_put(x_np, NamedSharding(src_env.mesh, src_spec))
    ref = _bits(x)

    out, plan = rd.redistribute_tree(
        {"w": x}, {"w": NamedSharding(dst_env.mesh, dst_spec)}
    )
    assert _bits(out["w"]) == ref
    assert plan.bytes_moved == plan.bytes_lower_bound
    assert plan.executed_scratch_bytes <= max(
        plan.peak_scratch_bytes, 1
    )
    # And back: the roundtrip is the identity.
    back, _ = rd.redistribute_tree(
        out, {"w": jax.device_put(x_np, NamedSharding(
            src_env.mesh, src_spec)).sharding}
    )
    assert _bits(back["w"]) == ref


def test_collective_executor_matches_naive_reference(monkeypatch):
    """Every same-mesh collective program class == the replicated-staging
    oracle (gather-everything-then-slice), bit for bit — the correctness
    half of the mutation gate (the lint half lives in
    tests/test_graft_lint.py)."""
    env = _mesh(data=2, fsdp=2, model=2)
    rng = np.random.default_rng(3)
    x_np = rng.standard_normal((32, 16, 8)).astype(np.float32)
    cases = [
        (P("fsdp", None, None), P(None, "fsdp", None)),   # move
        (P(("data", "fsdp"), None, None), P(None, None, None)),  # drop
        (P(None, None, None), P("model", None, None)),    # add
        (P("fsdp", "data", None), P("fsdp", None, "model")),  # drop+add
    ]
    for src_spec, dst_spec in cases:
        x = jax.device_put(x_np, NamedSharding(env.mesh, src_spec))
        plan = rd.compile_leaf_plan(
            x.shape, x.dtype, x.sharding,
            NamedSharding(env.mesh, dst_spec),
        )
        assert plan.kind == "collective", (str(src_spec), str(dst_spec))
        out = rd.execute_leaf(plan, x, donate=False)
        monkeypatch.setattr(rd_exec, "_NAIVE_GATHER_SCATTER", True)
        naive = rd.execute_leaf(plan, x, donate=False)
        monkeypatch.setattr(rd_exec, "_NAIVE_GATHER_SCATTER", False)
        assert _bits(out) == _bits(naive) == x_np.tobytes()


def test_collective_program_cache_keys_on_mesh_shape():
    """Regression (review find): two meshes with the SAME device ids but
    different axis shapes lower identical spec strings to different
    placements — the program cache must not hand the second mesh the
    first mesh's jitted program."""
    x_np = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
    for kw in (dict(data=2, model=4), dict(data=4, model=2)):
        env = _mesh(**kw)
        x = jax.device_put(
            x_np, NamedSharding(env.mesh, P("model", None))
        )
        dst = NamedSharding(env.mesh, P(None, "model"))
        plan = rd.compile_leaf_plan(x.shape, x.dtype, x.sharding, dst)
        assert plan.kind == "collective"
        out = rd.execute_leaf(plan, x, donate=False)
        ref = jax.device_put(x_np, dst)
        for a, b in zip(
            sorted(out.addressable_shards, key=lambda s: s.device.id),
            sorted(ref.addressable_shards, key=lambda s: s.device.id),
        ):
            assert a.index == b.index, (kw, a.device, a.index, b.index)
            np.testing.assert_array_equal(
                np.asarray(a.data), np.asarray(b.data)
            )


def test_executor_donates_source():
    env = _mesh(data=2, fsdp=4)
    serve = _mesh(devices=jax.devices()[:2], data=1, model=2)
    x_np = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
    # Chunked (cross-mesh) donation: source deleted once the move lands.
    x = jax.device_put(x_np, NamedSharding(env.mesh, P("fsdp", None)))
    out, _ = rd.redistribute_tree(
        {"w": x}, {"w": NamedSharding(serve.mesh, P(None, "model"))},
        donate=True,
    )
    assert x.is_deleted()
    assert _bits(out["w"]) == x_np.tobytes()
    # Collective donation rides donate_argnums inside the program.
    y = jax.device_put(x_np, NamedSharding(env.mesh, P("fsdp", None)))
    out2, _ = rd.redistribute_tree(
        {"w": y}, {"w": NamedSharding(env.mesh, P(None, "fsdp"))},
        donate=True,
    )
    assert y.is_deleted()
    assert _bits(out2["w"]) == x_np.tobytes()


# ------------------------------------------------------ seam 1: restore


def ckpt_cfg(tmp_path, extra=()):
    return apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=6",
            "trainer.log_every=3",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "model.hidden_sizes=64,32",
            "precision.policy=fp32",
            "checkpoint.enabled=true",
            "checkpoint.save_every=2",
            "checkpoint.async_save=false",
            f"workdir={tmp_path}",
        ]
        + list(extra),
    )


def _gpt_trainer_cfg(tmp_path, extra=()):
    return apply_overrides(
        get_config("gpt2_medium_zero1"),
        [
            "model.vocab_size=128", "model.num_layers=2",
            "model.num_heads=4", "model.hidden_dim=64", "model.seq_len=32",
            "data.vocab_size=128", "data.seq_len=32",
            "data.global_batch_size=16",
            "trainer.total_steps=2", "trainer.log_every=10",
            "trainer.eval_every=0", "trainer.grad_accum=1",
            "precision.policy=fp32",
            "parallel.param_sharding=fsdp", "parallel.fsdp_min_size=16",
            "checkpoint.enabled=true", "checkpoint.save_every=2",
            "checkpoint.async_save=false",
            f"workdir={tmp_path}",
        ]
        + list(extra),
    )


def _assert_state_bitexact(a, b):
    flat_a = jax.tree_util.tree_leaves(jax.device_get(a))
    flat_b = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_restore_redistributed_fsdp_model_onto_smaller_mesh(tmp_path):
    """The acceptance headline, seam 1: an fsdp×model checkpoint
    restores onto a DIFFERENT-SIZE mesh through the redistribution
    service bit-identically to the direct Orbax resharding read — and
    the executed plan's scratch stays under the full-tree staging the
    direct replicated path would need."""
    cfg = _gpt_trainer_cfg(tmp_path, ["mesh.fsdp=4", "mesh.model=2"])
    t = Trainer(cfg, mesh_env=build_mesh(cfg.mesh))
    t.fit()
    t.checkpointer.close()

    cfg_b = _gpt_trainer_cfg(
        tmp_path, ["mesh.data=1", "mesh.fsdp=2", "mesh.model=2"]
    )
    env_b = build_mesh(cfg_b.mesh, devices=jax.devices()[:4])
    ref_trainer = Trainer(cfg_b, mesh_env=env_b)
    ref = ref_trainer.checkpointer.restore_or_init(ref_trainer)
    ref_trainer.checkpointer.close()

    cfg_r = _gpt_trainer_cfg(
        tmp_path,
        ["mesh.data=1", "mesh.fsdp=2", "mesh.model=2",
         "checkpoint.restore_redistribute=true"],
    )
    t_r = Trainer(cfg_r, mesh_env=build_mesh(cfg_r.mesh,
                                             devices=jax.devices()[:4]))
    restored = t_r.checkpointer.restore_or_init(t_r)
    plan = t_r.checkpointer.last_restore_plan
    assert plan is not None
    _assert_state_bitexact(restored.params, ref.params)
    _assert_state_bitexact(restored.opt_state, ref.opt_state)
    # No replicated staging: every leaf's transient stays under the
    # whole-leaf copy a naive gather would make on every device (leaves
    # whose TARGET is replication are the allowed exception — the full
    # copy is the destination, not staging).
    from jax.sharding import PartitionSpec as PS

    for leaf in plan.leaves:
        tgt = getattr(leaf.dst_sharding, "spec", PS())
        if any(e is not None for e in tuple(tgt)):
            assert leaf.peak_scratch_bytes < max(leaf.leaf_bytes, 1), (
                leaf.to_dict()
            )
    # Placement landed in the NEW trainer's shardings: it can step.
    assert int(jax.device_get(restored.step)) == 2
    t_r.checkpointer.close()


@pytest.mark.chaos
def test_restore_redistributed_reformed_mesh_falls_back_past_torn(tmp_path):
    """The chaos row (the PR 9 torn-write shape, on a reformed mesh):
    a torn third save is skipped, and the redistribution restore on a
    4-device world lands on the last committed step with values
    bit-identical to the direct restore of that step."""
    from frl_distributed_ml_scaffold_tpu import faults
    from frl_distributed_ml_scaffold_tpu.faults import FaultPlan

    cfg = ckpt_cfg(tmp_path, ["mesh.data=8"])
    with faults.active(
        FaultPlan([dict(site="checkpoint.torn_write", at=3)])
    ):
        t = Trainer(cfg, mesh_env=build_mesh(cfg.mesh))
        t.fit()
        t.checkpointer.close()

    cfg4 = ckpt_cfg(
        tmp_path,
        ["mesh.data=4", "checkpoint.restore_redistribute=true"],
    )
    env4 = build_mesh(cfg4.mesh, devices=jax.devices()[:4])
    t4 = Trainer(cfg4, mesh_env=env4)
    ck = t4.checkpointer
    assert ck.uncommitted_steps() == [6]
    restored = ck.restore_or_init(t4)
    assert int(jax.device_get(restored.step)) == 4
    assert ck.last_restore_plan is not None

    cfg_ref = ckpt_cfg(tmp_path, ["mesh.data=4"])
    t_ref = Trainer(cfg_ref, mesh_env=env4)
    ref = t_ref.checkpointer.restore_or_init(t_ref)
    _assert_state_bitexact(restored.params, ref.params)
    t_ref.checkpointer.close()
    t4.checkpointer.close()


# ------------------------------------------------- seam 2: train→serve


def test_train_to_serve_bit_identical_and_bounded(gpt):
    """Seam 2: fsdp×model-sharded params reshard onto the serving TP
    mesh bit-identically to the replicated-staging reference, with every
    sharded leaf's transient under the full-leaf copy, and the placed
    params serve token-identically."""
    model, params, tokens = gpt
    train_env = _mesh(data=1, fsdp=4, model=2)
    specs = param_specs(
        params,
        ParallelConfig(param_sharding="fsdp", fsdp_min_size=16),
        train_env.mesh,
        gpt_tp_rules(),
    )
    train_params = jax.tree.map(
        lambda p, sh: jax.device_put(p, sh),
        params,
        shardings_from_specs(specs, train_env.mesh),
    )
    serve_env = _mesh(devices=jax.devices()[:2], data=1, model=2)
    placed, plan = rd.train_to_serve(train_params, serve_env, gpt_tp_rules())

    # Bit-identity vs the replicated-staging reference (device_get the
    # whole tree, device_put per serving spec).
    host = jax.device_get(params)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(placed),
        jax.tree_util.tree_leaves_with_path(host),
    ):
        assert _bits(a) == np.asarray(b).tobytes(), pa
    assert plan.bytes_moved == plan.bytes_lower_bound
    for leaf in plan.leaves:
        tgt = tuple(getattr(leaf.dst_sharding, "spec", P()))
        if any(e is not None for e in tgt):
            assert leaf.peak_scratch_bytes < max(leaf.leaf_bytes, 1)

    # The placed tree SERVES: engine output == replicated generate().
    prompt = np.asarray(tokens[0], np.int32)
    ref = generate(
        model, params, jnp.asarray(prompt)[None], max_new_tokens=4,
        temperature=0.0,
    )
    with mesh_context(serve_env):
        eng = ServingEngine(
            model, placed, num_slots=2, temperature=0.0, kv_block_size=8
        )
        rid = eng.submit(prompt, 4)
        done = {c.id: c for c in eng.run()}[rid]
        eng.close()
    np.testing.assert_array_equal(done.tokens, np.asarray(ref)[0])


def test_shard_params_for_serving_routes_sharded_trees(gpt, monkeypatch):
    """The adoption pin: a device-resident sharded tree goes through
    redistribute.train_to_serve (not a host round-trip); host trees keep
    the direct device_put path."""
    model, params, _ = gpt
    train_env = _mesh(data=1, fsdp=4, model=2)
    sharded = jax.tree.map(
        lambda p: jax.device_put(
            p, NamedSharding(train_env.mesh, P())
        ),
        params,
    )
    calls = []
    real = rd.train_to_serve

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    import frl_distributed_ml_scaffold_tpu.redistribute as rmod

    monkeypatch.setattr(rmod, "train_to_serve", spy)
    serve_env = _mesh(devices=jax.devices()[:2], data=1, model=2)
    with mesh_context(serve_env):
        placed = shard_params_for_serving(sharded, serve_env, gpt_tp_rules())
    assert calls, "sharded tree did not route through the service"
    _assert_state_bitexact(placed, params)
    # Host trees: unchanged direct path.
    calls.clear()
    with mesh_context(serve_env):
        placed2 = shard_params_for_serving(
            jax.device_get(params), serve_env, gpt_tp_rules()
        )
    assert not calls
    _assert_state_bitexact(placed2, params)


def test_build_engine_rules_places_and_serves(gpt):
    model, params, tokens = gpt
    prompt = np.asarray(tokens[1], np.int32)
    ref = generate(
        model, params, jnp.asarray(prompt)[None], max_new_tokens=4,
        temperature=0.0,
    )
    serve_env = _mesh(data=1, model=8)
    from frl_distributed_ml_scaffold_tpu.config.schema import ServingConfig

    with mesh_context(serve_env):
        eng = build_engine(
            model, params,
            serving=ServingConfig(kv_block_size=8),
            rules=gpt_tp_rules(), num_slots=2, temperature=0.0,
        )
        # Placement actually happened: at least one leaf is
        # model-sharded per the TP rules.
        leaves = jax.tree_util.tree_leaves_with_path(eng.params)
        assert any(
            "model" in str(getattr(l.sharding, "spec", ""))
            for _, l in leaves
        )
        rid = eng.submit(prompt, 4)
        done = {c.id: c for c in eng.run()}[rid]
        eng.close()
    np.testing.assert_array_equal(done.tokens, np.asarray(ref)[0])


# ------------------------------------------------ seam 3: respread_pool


@pytest.mark.serving
def test_respread_pool_inflight_token_identity(gpt):
    """Seam 3: a live model-axis change mid-decode — grow 2→4 and a
    fresh engine's shrink 4→2 — keeps every in-flight request
    token-identical to an uninterrupted replicated run, parks/resumes
    through the PR 12 machinery, and prices the move (bytes_moved > 0,
    counted on the telemetry counters)."""
    model, params, tokens = gpt
    prompts = [np.asarray(tokens[0], np.int32),
               np.asarray(tokens[1], np.int32)]
    ref_eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    rids = [ref_eng.submit(p, 8) for p in prompts]
    ref = {c.id: c for c in ref_eng.run()}
    ref_eng.close()

    # Lock-order sentinel (ISSUE 20): the live re-spread (park, move,
    # resume) runs under lock instrumentation — the acquisition order
    # across the engine + redistribute executor must stay acyclic.
    from frl_distributed_ml_scaffold_tpu import faults
    from frl_distributed_ml_scaffold_tpu.analysis import pins

    with faults.instrumented_locks() as locks_rec:
        env2 = _mesh(devices=jax.devices()[:2], data=1, model=2)
        with mesh_context(env2):
            sp = shard_params_for_serving(params, env2, gpt_tp_rules())
            eng = ServingEngine(
                model, sp, num_slots=2, temperature=0.0, kv_block_size=8
            )
            ids = [eng.submit(p, 8) for p in prompts]
            eng.step()
            eng.step()
        env4 = _mesh(devices=jax.devices()[:4], data=1, model=4)
        plans = eng.respread_pool(env4)
        assert eng.stats["parked"] == 2 and eng.stats["resumed"] == 2
        assert plans["cache"].bytes_moved > 0
        assert (
            plans["cache"].executed_scratch_bytes
            <= plans["cache"].peak_scratch_bytes
        )
        snap = eng.telemetry.snapshot()
        assert snap["serve_pool_respread_total"] == 1
        assert snap["serve_pool_respread_bytes_total"] > 0
        done = {c.id: c for c in eng.run()}
        eng.close()
    pins.assert_lock_order_acyclic(locks_rec)
    for rid, want in zip(ids, rids):
        np.testing.assert_array_equal(done[rid].tokens, ref[want].tokens)

    # Shrink: 4 → 2 via the int convenience form.
    env4b = _mesh(devices=jax.devices()[:4], data=1, model=4)
    with mesh_context(env4b):
        sp4 = shard_params_for_serving(params, env4b, gpt_tp_rules())
        eng2 = ServingEngine(
            model, sp4, num_slots=2, temperature=0.0, kv_block_size=8
        )
        ids2 = [eng2.submit(p, 8) for p in prompts]
        eng2.step()
    eng2.respread_pool(2)
    done2 = {c.id: c for c in eng2.run()}
    eng2.close()
    for rid, want in zip(ids2, rids):
        np.testing.assert_array_equal(done2[rid].tokens, ref[want].tokens)


@pytest.mark.fast
def test_respread_refuses_bucketed_and_indivisible(gpt):
    model, params, _ = gpt
    eng = ServingEngine(model, params, num_slots=2, temperature=0.0)
    with pytest.raises(ValueError, match="paged-engine"):
        eng.respread_pool(2)
    eng.close()
    eng2 = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    with pytest.raises(ValueError, match="num_heads"):
        eng2.respread_pool(
            _mesh(data=1, model=8)
        )  # 8 does not divide 4 heads
    eng2.close()


# ------------------------------------------------------------ quantized


def test_respread_quantized_pool_scale_leaves():
    """The int8 pool's 1-byte payloads AND bf16 scale pools re-spread
    bit-exactly (the dtypes row of the acceptance grid, on the real
    engine tree)."""
    model = GPT(GPTConfig(**dict(TINY, kv_cache_quant="int8")), FP32)
    tokens = jax.random.randint(jax.random.key(2), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    prompt = np.asarray(tokens[0], np.int32)
    ref_eng = ServingEngine(
        model, params, num_slots=2, temperature=0.0, kv_block_size=8
    )
    rid_ref = ref_eng.submit(prompt, 6)
    ref = {c.id: c for c in ref_eng.run()}[rid_ref]
    ref_eng.close()

    env2 = _mesh(devices=jax.devices()[:2], data=1, model=2)
    with mesh_context(env2):
        sp = shard_params_for_serving(params, env2, gpt_tp_rules())
        eng = ServingEngine(
            model, sp, num_slots=2, temperature=0.0, kv_block_size=8
        )
        rid = eng.submit(prompt, 6)
        eng.step()
    plans = eng.respread_pool(4)
    # Scale pools rode the plan next to the 1-byte payloads.
    paths = [l.path for l in plans["cache"].leaves]
    assert any("key_pool_scale" in p for p in paths), paths
    done = {c.id: c for c in eng.run()}[rid]
    eng.close()
    np.testing.assert_array_equal(done.tokens, ref.tokens)
