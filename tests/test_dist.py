"""Mesh + collectives tests on the simulated 8-device CPU mesh (SURVEY §4)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
from frl_distributed_ml_scaffold_tpu.dist import build_mesh, collectives, local_batch_size
from frl_distributed_ml_scaffold_tpu.dist.mesh import AXES, resolve_axis_sizes


def test_eight_sim_devices():
    assert jax.device_count() == 8


def test_resolve_axis_sizes_wildcard():
    sizes = resolve_axis_sizes(MeshConfig(data=-1, model=2), 8)
    assert sizes["data"] == 4 and sizes["model"] == 2


def test_resolve_axis_sizes_mismatch_raises():
    with pytest.raises(ValueError):
        resolve_axis_sizes(MeshConfig(data=3, model=2), 8)


def test_build_mesh_axes_and_batch_spec():
    env = build_mesh(MeshConfig(data=2, fsdp=2, model=2))
    assert env.mesh.axis_names == AXES
    assert env.num_devices == 8
    assert env.batch_axis_size == 4
    assert env.batch_spec(None) == P(("data", "fsdp"), None)


def test_hybrid_dcn_mesh_shape_and_slice_layout():
    """dcn_data>1 (SURVEY §5 multi-slice): the data axis's OUTER component
    is the DCN factor, so each contiguous device group forms one slice and
    only the data-axis collective crosses slices. CPU-sim devices carry no
    slice metadata, so this exercises the manual hybrid layout; the axis
    semantics asserted here are the ones the real create_hybrid_device_mesh
    path also guarantees."""
    env = build_mesh(MeshConfig(data=4, model=2, dcn_data=2))
    assert dict(env.mesh.shape) == {
        "pipe": 1, "data": 4, "fsdp": 1, "seq": 1, "expert": 1, "model": 2,
    }
    dev = np.asarray(env.mesh.devices)  # [pipe, data, fsdp, seq, expert, model]
    ids = np.vectorize(lambda d: d.id)(dev)[0, :, 0, 0, 0, :]  # [data, model]
    # Slice 0 = devices 0..3 <-> data rows 0..1; slice 1 = 4..7 <-> rows 2..3.
    assert set(ids[:2].ravel()) == {0, 1, 2, 3}
    assert set(ids[2:].ravel()) == {4, 5, 6, 7}
    # Within a slice, the model axis varies fastest (innermost == ICI-nearest).
    assert ids[0, 0] + 1 == ids[0, 1]


def test_hybrid_dcn_mesh_indivisible_raises():
    with pytest.raises(ValueError, match="dcn_data"):
        build_mesh(MeshConfig(data=2, model=4, dcn_data=4))


def test_mesh_layout_fallback_warns():
    """Naive row-major placement must be observable, not silent (it costs
    real ICI locality on hardware)."""
    from conftest import capture_frl_logs

    with capture_frl_logs() as records:
        build_mesh(MeshConfig(data=4, model=2, dcn_data=2))
    assert any("row-major" in m for m in records), records


def test_local_batch_size_single_process():
    env = build_mesh(MeshConfig(data=-1))
    assert local_batch_size(64, env) == 64
    with pytest.raises(ValueError):
        local_batch_size(12, env)  # not divisible by 8 batch devices


def _shmap(fn, mesh, in_specs, out_specs):
    from frl_distributed_ml_scaffold_tpu.dist.mesh import shard_map_compat

    return jax.jit(
        shard_map_compat(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_all_reduce_matches_sum():
    env = build_mesh(MeshConfig(data=-1))
    x = jnp.arange(8.0)

    f = _shmap(
        lambda a: collectives.all_reduce(a, "data"),
        env.mesh, (P("data"),), P("data"),
    )
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_mean_is_ddp_grad_average():
    env = build_mesh(MeshConfig(data=-1))
    x = jnp.arange(8.0)
    f = _shmap(
        lambda a: collectives.all_mean(a, "data"),
        env.mesh, (P("data"),), P("data"),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, x.mean()))


def test_all_gather_reduce_scatter_roundtrip():
    env = build_mesh(MeshConfig(data=-1))
    x = jnp.arange(16.0).reshape(8, 2)

    def fn(a):  # a: (1, 2) shard
        full = collectives.all_gather(a, "data")  # (8, 2)
        return collectives.reduce_scatter(full, "data")  # (1, 2), sum over 8 copies

    f = _shmap(fn, env.mesh, (P("data", None),), P("data", None))
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 8)


def test_broadcast_from_nonzero_source():
    env = build_mesh(MeshConfig(data=-1))
    x = jnp.arange(8.0)
    f = _shmap(
        lambda a: collectives.broadcast(a, "data", source=3),
        env.mesh, (P("data"),), P("data"),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 3.0))


def test_ring_shift_rotates():
    env = build_mesh(MeshConfig(data=-1))
    x = jnp.arange(8.0)
    f = _shmap(
        lambda a: collectives.ring_shift(a, "data", shift=1),
        env.mesh, (P("data"),), P("data"),
    )
    # shard i's value moves to shard i+1
    np.testing.assert_allclose(np.asarray(f(x)), np.roll(np.arange(8.0), 1))


def test_all_to_all_transposes_shards():
    env = build_mesh(MeshConfig(data=-1))
    x = jnp.arange(64.0).reshape(8, 8)

    f = _shmap(
        lambda a: collectives.all_to_all(a, "data", split_axis=1, concat_axis=0),
        env.mesh, (P("data", None),), P(None, "data"),
    )
    out = f(x)
    # all_to_all along the other axis is a block transpose of the shard grid;
    # the global result here equals the original matrix re-tiled — check shape
    # and content preservation.
    assert out.shape == (8, 8)
    assert set(np.asarray(out).ravel()) == set(np.arange(64.0))


def test_axis_index_and_size():
    env = build_mesh(MeshConfig(data=-1))

    def fn(a):
        return a + collectives.axis_index("data") * 0 + collectives.axis_size("data")

    f = _shmap(fn, env.mesh, (P("data"),), P("data"))
    np.testing.assert_allclose(np.asarray(f(jnp.zeros(8))), np.full(8, 8.0))


def test_host_tier_single_process():
    assert collectives.host_all_gather(np.array([1.0]))[0] == 1.0
    collectives.barrier("test")
