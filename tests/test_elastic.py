"""Checkpoint/resume + elastic tier (SURVEY §4 fault injection, C13/C14).

Covers call stacks (c) and (d): sharded save → restore (same and *changed*
topology), and the supervisor's full crash → restart → resume cycle with a
real hard-killed child process.
"""

import json
import os

import jax
import numpy as np
import pytest

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer


def ckpt_cfg(tmp_path, extra=()):
    return apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=6",
            "trainer.log_every=3",
            "data.global_batch_size=64",
            "model.hidden_sizes=64,32",
            "precision.policy=fp32",
            "checkpoint.enabled=true",
            "checkpoint.save_every=3",
            "checkpoint.async_save=false",
            f"workdir={tmp_path}",
        ]
        + list(extra),
    )


def assert_params_close(a, b, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=atol, rtol=1e-6),
        jax.device_get(a),
        jax.device_get(b),
    )


def test_save_restore_roundtrip(tmp_path):
    """C13: save at step 6, fresh Trainer restores the exact state."""
    cfg = ckpt_cfg(tmp_path)
    trainer = Trainer(cfg)
    final_state, _ = trainer.fit()
    trainer.checkpointer.close()

    fresh = Trainer(cfg)
    restored = fresh.checkpointer.restore_or_init(fresh)
    assert int(jax.device_get(restored.step)) == 6
    assert_params_close(restored.params, final_state.params)
    assert_params_close(restored.opt_state, final_state.opt_state)
    fresh.checkpointer.close()


def test_restore_params_only_matches_full(tmp_path):
    """Partial restore (params subtree via ocp.PLACEHOLDER) must equal the
    params of a full-state restore — it is the avg_checkpoints/offline
    path that skips reading the optimizer moments."""
    cfg = ckpt_cfg(tmp_path)
    trainer = Trainer(cfg)
    final_state, _ = trainer.fit()
    trainer.checkpointer.close()

    fresh = Trainer(cfg)
    full = fresh.checkpointer.restore_or_init(fresh)
    params_only = fresh.checkpointer.restore_params_only(
        fresh.state_shapes, fresh.state_shardings,
        fresh.checkpointer.latest_step(),
    )
    assert_params_close(params_only, full.params)
    fresh.checkpointer.close()

    # Cross-topology: the same partial restore onto a 4-device mesh (the
    # explicit ArrayRestoreArgs shardings are what makes PyTreeRestore
    # safe off the writer's topology — the tool's any-host promise).
    cfg4 = ckpt_cfg(tmp_path, ["mesh.data=4"])
    t4 = Trainer(
        cfg4, mesh_env=build_mesh(cfg4.mesh, devices=jax.devices()[:4])
    )
    p4 = t4.checkpointer.restore_params_only(
        t4.state_shapes, t4.state_shardings, t4.checkpointer.latest_step()
    )
    assert_params_close(p4, full.params)
    t4.checkpointer.close()


def test_topology_change_restore(tmp_path):
    """C13 resharding restore: write on an 8-device mesh, read on 4 devices.

    This is the elastic-shrink path of call stack (d): the restored state
    must land in the *new* trainer's shardings with identical values.
    """
    cfg8 = ckpt_cfg(tmp_path, ["mesh.data=8", "trainer.total_steps=3"])
    t8 = Trainer(cfg8, mesh_env=build_mesh(cfg8.mesh))
    state8, _ = t8.fit()
    t8.checkpointer.close()

    cfg4 = ckpt_cfg(tmp_path, ["mesh.data=4", "trainer.total_steps=3"])
    env4 = build_mesh(cfg4.mesh, devices=jax.devices()[:4])
    t4 = Trainer(cfg4, mesh_env=env4)
    restored = t4.checkpointer.restore_or_init(t4)
    assert int(jax.device_get(restored.step)) == 3
    assert_params_close(restored.params, state8.params)
    # The restored state is live on the new mesh: one more step must run.
    batch = t4.pipeline.global_batch(3)
    next_state, metrics = t4.train_step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(next_state.step)) == 4
    t4.checkpointer.close()


def test_topology_grow_and_strategy_change_restore(tmp_path):
    """Elastic-grow + strategy change: a checkpoint written by a 4-device
    replicated-params run restores into an 8-device FSDP-sharded trainer —
    values identical, placement per the NEW sharding rules."""
    cfg4 = ckpt_cfg(tmp_path, ["mesh.data=4", "trainer.total_steps=3"])
    t4 = Trainer(cfg4, mesh_env=build_mesh(cfg4.mesh, devices=jax.devices()[:4]))
    state4, _ = t4.fit()
    t4.checkpointer.close()

    cfg8 = ckpt_cfg(
        tmp_path,
        [
            "mesh.data=1",
            "mesh.fsdp=8",
            "trainer.total_steps=3",
            "parallel.param_sharding=fsdp",
            "parallel.fsdp_min_size=64",
        ],
    )
    t8 = Trainer(cfg8, mesh_env=build_mesh(cfg8.mesh))
    restored = t8.checkpointer.restore_or_init(t8)
    assert int(jax.device_get(restored.step)) == 3
    assert_params_close(restored.params, state4.params)
    # Placement follows the new trainer's FSDP specs, not the saved layout.
    big = [l for l in jax.tree.leaves(restored.params) if l.size >= 64]
    assert big and all(
        any(
            "fsdp" in (e or ()) if isinstance(e, tuple) else e == "fsdp"
            for e in l.sharding.spec
        )
        for l in big
    )
    batch = t8.pipeline.global_batch(3)
    _, metrics = t8.train_step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))
    t8.checkpointer.close()


def test_fault_hook_fires_once(tmp_path, monkeypatch):
    """The injection hook is one-shot per workdir (marker file)."""
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import fault_hook_from_env

    cfg = ckpt_cfg(tmp_path)
    monkeypatch.setenv("FRL_FAULT_AT_STEP", "4")
    hook = fault_hook_from_env(cfg)
    assert hook is not None
    hook(0, {})  # not the fault step: survives
    marker = os.path.join(cfg.workdir, cfg.name, "fault_injected")
    os.makedirs(os.path.dirname(marker), exist_ok=True)
    open(marker, "w").write("4")
    assert fault_hook_from_env(cfg) is None  # marker disarms it


def test_supervisor_kill_and_resume(tmp_path):
    """C14 end-to-end: child hard-dies mid-run, supervisor restarts it, the
    run resumes from the last checkpoint and completes.

    Proof of *resume* (not restart-from-zero): metrics.jsonl is append-only
    across child processes; steps must be [4, 8, 12] with no duplicates —
    run 1 logs 4 and 8, dies after step 9; run 2 starts at 8 and logs 12.
    """
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import supervise
    from frl_distributed_ml_scaffold_tpu.launcher.launch import _parse_args

    overrides = [
        "trainer.total_steps=12",
        "trainer.log_every=4",
        "trainer.eval_every=0",
        "data.global_batch_size=64",
        "model.hidden_sizes=32",
        "precision.policy=fp32",
        "checkpoint.save_every=4",
        "checkpoint.async_save=false",
        "elastic.backoff_s=0.1",
        f"workdir={tmp_path}",
    ]
    args = _parse_args(
        ["--config", "mnist_mlp", "--device", "cpu", "--sim-devices", "8",
         "--elastic"] + overrides
    )
    cfg = apply_overrides(get_config("mnist_mlp"), overrides)

    os.environ["FRL_FAULT_AT_STEP"] = "9"
    try:
        rc = supervise(args, cfg)
    finally:
        del os.environ["FRL_FAULT_AT_STEP"]

    assert rc == 0
    run_dir = os.path.join(str(tmp_path), cfg.name)
    assert os.path.exists(os.path.join(run_dir, "fault_injected"))
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        steps = [json.loads(line)["step"] for line in fh]
    train_steps = [s for s in steps if s in (4, 8, 12)]
    assert train_steps == [4, 8, 12], steps
    ckpt_steps = sorted(
        int(d) for d in os.listdir(os.path.join(run_dir, "ckpt")) if d.isdigit()
    )
    assert 12 in ckpt_steps
    # Supervisor tracing (ISSUE 8): the incident reads as one trace —
    # a crashed child_run (the fault's rc), a restart_wait, and the
    # clean child_run, all on the supervisor lane.
    trace = json.load(
        open(os.path.join(run_dir, "supervisor_0_trace.json"))
    )
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    child_rcs = [e["args"]["rc"] for e in spans if e["name"] == "child_run"]
    assert child_rcs == [43, 0], child_rcs  # FAULT_EXIT_CODE then clean
    assert any(e["name"] == "restart_wait" for e in spans)
    roots = [e for e in spans if e["name"] == "supervise"]
    assert len(roots) == 1
    root_id = roots[0]["args"]["span"]
    assert all(
        e["args"]["parent"] == root_id for e in spans if e is not roots[0]
    )


def test_sigterm_preempts_checkpoint_and_resume(tmp_path):
    """Graceful preemption (TPU maintenance events deliver SIGTERM): the
    fit loop must finish the in-flight step, checkpoint, and return cleanly
    — and a fresh run must resume from that checkpoint with no step
    duplicated or lost."""
    import signal

    cfg = ckpt_cfg(
        tmp_path,
        ["trainer.total_steps=10", "trainer.log_every=2",
         "checkpoint.save_every=100", "trainer.eval_every=0"],
    )
    trainer = Trainer(cfg)

    def send_sigterm_at_step_4(step, metrics):
        if step == 4:  # zero-based: the 5th step is in flight
            os.kill(os.getpid(), signal.SIGTERM)

    handler_before = signal.getsignal(signal.SIGTERM)
    state, last = trainer.fit(on_step=send_sigterm_at_step_4)
    assert int(jax.device_get(state.step)) == 5  # stopped right after step 5
    assert last.get("event") == "preempted"
    # The preemption save is the only one (save_every=100 never fires).
    assert trainer.checkpointer.latest_step() == 5
    # fit() restored the pre-existing SIGTERM disposition on exit.
    assert signal.getsignal(signal.SIGTERM) is handler_before

    resumed = Trainer(cfg)
    state2, _ = resumed.fit()  # restore_or_init picks up step 5
    assert int(jax.device_get(state2.step)) == 10
    with open(os.path.join(str(tmp_path), cfg.name, "metrics.jsonl")) as fh:
        steps = [json.loads(l)["step"] for l in fh]
    # Run 1 logs 2, 4, then the preemption record at 5; run 2 resumes from
    # 5 and logs 6, 8, 10 — contiguous, nothing duplicated.
    assert steps == [2, 4, 5, 6, 8, 10], steps
