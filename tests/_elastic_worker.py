"""Per-process supervisor half of the multi-process elastic test.

Launched (once per simulated host) by tests/test_elastic_multiprocess.py.
Each instance is exactly what a real pod host runs: the launcher CLI in
``--elastic`` mode (supervisor wrapping a multi-process training child that
rendezvouses over ``jax.distributed``). The test kills one *child* mid-run
via fault injection; this script only stands in for "one host's command
line" — all logic lives in the launcher itself.

Env contract (set by the test): FRL_TPU_COORDINATOR, FRL_TPU_NUM_PROCESSES,
FRL_TPU_PROCESS_ID, FRL_TEST_WORKDIR; FRL_FAULT_AT_STEP optionally set for
exactly one process's environment.
"""

import os
import sys


def main() -> int:
    from frl_distributed_ml_scaffold_tpu.launcher.launch import main as launch_main

    return launch_main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--sim-devices", "2",
            "--coordinator", os.environ["FRL_TPU_COORDINATOR"],
            "--num-processes", os.environ["FRL_TPU_NUM_PROCESSES"],
            "--process-id", os.environ["FRL_TPU_PROCESS_ID"],
            "--elastic",
            "trainer.total_steps=12",
            "trainer.log_every=4",
            "trainer.eval_every=0",
            "data.global_batch_size=64",
            "data.prefetch=0",
            "model.hidden_sizes=32",
            "precision.policy=fp32",
            "checkpoint.save_every=4",
            "checkpoint.async_save=false",
            "elastic.backoff_s=0.1",
            "workdir=" + os.environ["FRL_TEST_WORKDIR"],
        ]
    )


if __name__ == "__main__":
    sys.exit(main())
