"""Latency-hiding tensor parallelism (parallel/tp_overlap.py +
ops/collective_matmul.py): the collective-matmul schedule must (i) match
the plain GSPMD TP path numerically on every mesh composition, (ii) run
BLOCKWISE — ppermute-chained per-shard matmuls inside the scan body, with
no monolithic all-gather of activations anywhere in the step — and (iii)
refuse configs it cannot honor."""

# The core gates here ride the `fast` tier where marked; the extended
# mesh-x-remat equivalence matrix is `slow` (COVERAGE.md "Test tiers") —
# the representative compositions below already cover each dimension.

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh, mesh_context
from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

GPT_TINY = [
    "model.num_layers=2", "model.num_heads=4", "model.hidden_dim=64",
    "model.seq_len=64", "model.vocab_size=256",
    "data.seq_len=64", "data.vocab_size=256",
    "data.global_batch_size=16",
    "trainer.grad_accum=1", "trainer.remat=none",
    "trainer.log_every=1000000",
    "precision.policy=fp32",
    "checkpoint.enabled=false",
    "optimizer.warmup_steps=0",
]

VIT_TINY = [
    "model.image_size=32", "model.patch_size=8", "model.hidden_dim=64",
    "model.num_layers=2", "model.num_heads=4", "model.num_classes=10",
    "data.name=synthetic_imagenet", "data.image_size=32",
    "data.num_classes=10", "data.global_batch_size=16",
    "trainer.grad_accum=1", "trainer.remat=none",
    "trainer.log_every=1000000",
    "precision.policy=fp32",
    "checkpoint.enabled=false",
    "optimizer.warmup_steps=0",
]


def make_trainer(name, base, overrides, tmp_path):
    cfg = apply_overrides(
        get_config(name), base + [f"workdir={tmp_path}"] + list(overrides)
    )
    env = build_mesh(cfg.mesh)
    return Trainer(cfg, mesh_env=env)


def run_steps(trainer, n=3):
    state = trainer.init_state()
    for step in range(n):
        state, metrics = trainer.train_step(
            state, trainer.pipeline.global_batch(step)
        )
    return jax.device_get(state), jax.device_get(metrics)


def assert_params_close(a, b, atol=2e-3):
    """steps x lr tolerance (the test_fsdp_overlap.py discipline): adamw
    amplifies numerically-zero grads (e.g. attn/key/bias) into lr-scale
    sign updates from float noise, and the ring reorders those reductions
    vs GSPMD's allreduce. Losses are compared tightly where asserted."""
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=atol, rtol=1e-4),
        a.params,
        b.params,
    )


def gpt_pair(tmp_path, mesh, extra=()):
    """(GSPMD-TP state+metrics, tp_overlap state+metrics) after 3 steps."""
    ref = make_trainer(
        "gpt2_tp", GPT_TINY, mesh + list(extra), tmp_path / "ref"
    )
    ovl = make_trainer(
        "gpt2_medium_tp_overlap", GPT_TINY, mesh + list(extra),
        tmp_path / "ovl",
    )
    return run_steps(ref), run_steps(ovl)


def test_tp_overlap_matches_model_only_mesh(tmp_path):
    """model=8: the pure-TP mesh, plus a sharding sanity check — the
    overlap config must still Megatron-shard the kernels (a silently
    replicated run would also 'match')."""
    (ref, ref_m), (ovl, ovl_m) = gpt_pair(
        tmp_path, ["mesh.data=1", "mesh.model=8"]
    )
    assert_params_close(ref, ovl)
    np.testing.assert_allclose(ovl_m["loss"], ref_m["loss"], atol=1e-5)
    t = make_trainer(
        "gpt2_medium_tp_overlap", GPT_TINY,
        ["mesh.data=1", "mesh.model=8"], tmp_path / "shard",
    )
    state = t.init_state()
    qk = state.params["blocks"]["attn"]["query"]["kernel"]
    assert any(
        e == "model" or (isinstance(e, tuple) and "model" in e)
        for e in qk.sharding.spec
    ), qk.sharding.spec


def test_tp_overlap_matches_data_x_model(tmp_path):
    """data=2 x model=4: the hybrid mesh of the acceptance gate."""
    (ref, _), (ovl, _) = gpt_pair(tmp_path, ["mesh.data=2", "mesh.model=4"])
    assert_params_close(ref, ovl)


def test_tp_overlap_matches_fsdp_x_model(tmp_path):
    """data=2 x fsdp=2 x model=2 with params fsdp-sharded: the rings must
    compose with GSPMD's fsdp gathers of the weight shards."""
    extra = [
        "parallel.param_sharding=fsdp", "parallel.opt_sharding=like_params",
        "parallel.fsdp_min_size=16",
    ]
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=2", "mesh.fsdp=2", "mesh.model=2"], extra
    )
    assert_params_close(ref, ovl)


def test_tp_overlap_composes_with_fsdp_overlap(tmp_path):
    """BOTH explicit schedules at once (the composition the ISSUE names):
    fsdp_overlap's per-block param gathers + tp_overlap's collective
    matmuls, vs the all-GSPMD path on the same fsdp x model mesh."""
    mesh = ["mesh.data=1", "mesh.fsdp=4", "mesh.model=2"]
    ref = make_trainer(
        "gpt2_tp", GPT_TINY,
        mesh + ["parallel.param_sharding=fsdp",
                "parallel.opt_sharding=like_params",
                "parallel.fsdp_min_size=16"],
        tmp_path / "ref",
    )
    ovl = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY,
        mesh + ["parallel.tp_overlap=true", "parallel.fsdp_min_size=16"],
        tmp_path / "ovl",
    )
    (ref_s, _), (ovl_s, _) = run_steps(ref), run_steps(ovl)
    assert_params_close(ref_s, ovl_s)


def test_tp_overlap_grad_accum_matches(tmp_path):
    """grad_accum=4: the rings run inside the microbatch scan body."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=2", "mesh.model=4"],
        extra=["trainer.grad_accum=4"],
    )
    assert_params_close(ref, ovl)


@pytest.mark.slow
@pytest.mark.parametrize("block_remat", ["full", "save_attn"])
def test_tp_overlap_block_remat_interaction(tmp_path, block_remat):
    """Per-block remat modes: the rings sit inside the remat region, so
    the backward re-runs them instead of saving gathered activations."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=2", "mesh.model=4"],
        extra=[f"model.block_remat={block_remat}"],
    )
    assert_params_close(ref, ovl)


@pytest.mark.slow
@pytest.mark.parametrize("remat", ["full", "dots"])
def test_tp_overlap_trainer_remat_interaction(tmp_path, remat):
    """Whole-loss checkpoint modes around the hooked model."""
    (ref, _), (ovl, _) = gpt_pair(
        tmp_path, ["mesh.data=2", "mesh.model=4"],
        extra=[f"trainer.remat={remat}"],
    )
    assert_params_close(ref, ovl)


def test_vit_tp_overlap_matches(tmp_path):
    """ViT equivalents (flax MHA qkv/out dot_general injection + MlpBlock),
    batch-chunked rings: tp_overlap == GSPMD TP on data x model."""
    mesh = [
        "mesh.data=2", "mesh.model=4", "parallel.param_sharding=replicated",
    ]
    ref = make_trainer(
        "imagenet_vitb_fsdp", VIT_TINY, mesh, tmp_path / "ref"
    )
    ovl = make_trainer(
        "imagenet_vitb_fsdp", VIT_TINY,
        mesh + ["parallel.tp_overlap=true"], tmp_path / "ovl",
    )
    (ref_s, ref_m), (ovl_s, ovl_m) = run_steps(ref), run_steps(ovl)
    assert_params_close(ref_s, ovl_s)
    np.testing.assert_allclose(ovl_m["loss"], ref_m["loss"], atol=1e-5)


# --------------------------------------------------------------- blockwise
# Jaxpr pins ride the shared analysis.pins API (docs/static_analysis.md);
# the per-test _walk_jaxpr copies this file used to carry live in
# analysis/jaxpr_utils.py.

from frl_distributed_ml_scaffold_tpu.analysis import pins


def _step_jaxpr(t):
    state = t.init_state()
    batch = t.pipeline.global_batch(0)
    with mesh_context(t.env):
        return jax.make_jaxpr(t._train_step_fn)(state, batch), state


@pytest.mark.fast
@pytest.mark.parametrize("policy", ["fp32", "bf16_mixed"])
def test_tp_overlap_schedule_is_blockwise_ppermute(tmp_path, policy):
    """The jaxpr pin of the acceptance gate: the step must carry blockwise
    ppermute chains INSIDE the layer-scan body (forward and backward), and
    NO monolithic all_gather of activations — on the pure-TP config there
    is no all_gather primitive in the step at all.

    Parametrized over the precision policy because the shared-QKV ring
    cache keys on input-object identity: under bf16_mixed the fp32
    LayerNorm output is pre-cast once in the attention block precisely so
    the trio still shares ONE ring — this pin is what keeps that from
    silently regressing to three."""
    m = 4
    t = make_trainer(
        "gpt2_medium_tp_overlap", GPT_TINY,
        ["mesh.data=2", f"mesh.model={m}", f"precision.policy={policy}"],
        tmp_path,
    )
    jaxpr, _ = _step_jaxpr(t)

    pins.assert_no_collective(
        jaxpr, "all_gather",
        "tp_overlap step contains an explicit all_gather — the activation "
        "gather is supposed to be a blockwise ppermute ring",
    )
    pins.assert_collective_present(
        jaxpr, "ppermute", "tp_overlap produced no ppermute chains"
    )

    # Per layer-scan iteration: 4 rings (shared-QKV gather, fc_in gather,
    # attn-out scatter, fc_out scatter), each a bidirectional chain of
    # 2*(m-1) hops. The scan bodies must carry them — that's what makes
    # the schedule per-block; the backward scan carries its own.
    scan_counts = pins.scan_collective_counts(jaxpr, "ppermute")
    with_rings = [n for n in scan_counts if n > 0]
    assert len(with_rings) >= 2, (
        "expected ppermute chains inside both the forward and backward "
        f"layer scans (scan ppermute counts: {scan_counts})"
    )
    assert max(with_rings) >= 4 * 2 * (m - 1), scan_counts
    # The QKV trio shares ONE gather ring: 4 rings/block forward, not 6.
    assert min(with_rings) == 4 * 2 * (m - 1), (
        "forward scan ppermute count does not match the shared-QKV "
        f"4-ring schedule (scan counts: {scan_counts})"
    )


@pytest.mark.fast
def test_tp_overlap_no_activation_gather_under_fsdp(tmp_path):
    """Composed with explicit-FSDP gathers: every all_gather in the step
    must be a PARAM-slice gather (the fsdp_overlap schedule), never an
    activation — activations ride the ppermute rings."""
    t = make_trainer(
        "gpt2_medium_fsdp_overlap", GPT_TINY,
        ["mesh.data=1", "mesh.fsdp=4", "mesh.model=2",
         "parallel.tp_overlap=true", "parallel.fsdp_min_size=16"],
        tmp_path,
    )
    jaxpr, state = _step_jaxpr(t)
    pins.assert_collective_present(
        jaxpr, "all_gather",
        "fsdp_overlap composition lost its explicit param gathers",
    )
    # The param gathers run inside shard_map, so their jaxpr-level output
    # shapes are per-shard views: a per-block slice with its Megatron-split
    # dim still divided by the model axis.
    m = 2
    param_slices = set()
    for l in jax.tree.leaves(state.params["blocks"]):
        s = tuple(l.shape[1:])
        param_slices.add(s)
        for i, d in enumerate(s):
            if d % m == 0:
                param_slices.add(s[:i] + (d // m,) + s[i + 1 :])
    pins.assert_all_gather_outputs_within(
        jaxpr, param_slices,
        "an all_gather output is not a per-block param slice — an "
        "activation passed through a monolithic gather",
    )
    pins.assert_collective_present(
        jaxpr, "ppermute", "composed schedule lost its ppermute rings"
    )


# ------------------------------------------------------------- validation


@pytest.mark.fast
def test_tp_overlap_requires_model_axis(tmp_path):
    with pytest.raises(ValueError, match="mesh.model"):
        make_trainer(
            "gpt2_medium_tp_overlap", GPT_TINY,
            ["mesh.data=8", "mesh.model=1"], tmp_path,
        )


@pytest.mark.fast
def test_tp_overlap_refuses_pipeline(tmp_path):
    with pytest.raises(ValueError, match="pipeline"):
        make_trainer(
            "gpt2_medium_tp_overlap", GPT_TINY,
            ["mesh.data=2", "mesh.model=2", "mesh.pipe=2",
             "model.num_layers=4", "model.pipeline_stages=2"],
            tmp_path,
        )


@pytest.mark.fast
def test_tp_overlap_refuses_sequence_parallel(tmp_path):
    with pytest.raises(ValueError, match="sequence"):
        make_trainer(
            "gpt2_medium_tp_overlap", GPT_TINY,
            ["mesh.data=2", "mesh.model=2", "mesh.seq=2",
             "model.attention=ring", "parallel.sequence=ring"],
            tmp_path,
        )


@pytest.mark.fast
def test_tp_overlap_refuses_moe(tmp_path):
    with pytest.raises(ValueError, match="[Mm]oE"):
        make_trainer(
            "gpt2_medium_tp_overlap", GPT_TINY,
            ["mesh.data=2", "mesh.model=4", "model.moe.num_experts=4"],
            tmp_path,
        )


@pytest.mark.fast
def test_tp_overlap_refuses_indivisible_hidden_dim(tmp_path):
    """Indivisible Megatron feature dims must fail at validation (GSPMD
    pads uneven shards; the rings split exactly), not as a shard_map
    trace error."""
    with pytest.raises(ValueError, match="hidden_dim"):
        make_trainer(
            "gpt2_medium_tp_overlap", GPT_TINY,
            ["mesh.data=1", "mesh.model=8", "model.hidden_dim=60",
             "model.num_heads=4"],
            tmp_path,
        )


@pytest.mark.fast
def test_tp_overlap_refuses_indivisible_seq(tmp_path):
    with pytest.raises(ValueError, match="seq_len"):
        make_trainer(
            "gpt2_medium_tp_overlap", GPT_TINY,
            ["mesh.data=1", "mesh.model=8", "model.seq_len=60",
             "data.seq_len=60"],
            tmp_path,
        )


@pytest.mark.fast
def test_tp_overlap_refuses_unsupported_family(tmp_path):
    with pytest.raises(ValueError, match="family"):
        make_trainer(
            "imagenet_rn50_ddp",
            ["data.name=synthetic_imagenet", "data.image_size=32",
             "data.num_classes=10", "data.global_batch_size=16",
             "model.depth=10", "model.num_classes=10",
             "checkpoint.enabled=false", "trainer.log_every=1000000"],
            ["mesh.data=8", "parallel.tp_overlap=true"],
            tmp_path,
        )


def test_tp_overlap_parity_dryrun_style(tmp_path):
    """dryrun_multichip-style parity: first-step loss of the composed
    data x model overlap mesh agrees with the same config on one device
    (tol 2e-2, the driver's parity band)."""
    ovl = make_trainer(
        "gpt2_medium_tp_overlap", GPT_TINY,
        ["mesh.data=2", "mesh.model=4"], tmp_path / "multi",
    )
    state = ovl.init_state()
    _, m_multi = ovl.train_step(state, ovl.pipeline.global_batch(0))

    cfg1 = apply_overrides(
        get_config("gpt2_medium_zero1"),
        GPT_TINY + [f"workdir={tmp_path}/single", "mesh.data=1", "mesh.fsdp=1"],
    )
    env1 = build_mesh(cfg1.mesh, devices=jax.devices()[:1])
    single = Trainer(cfg1, mesh_env=env1)
    s1 = single.init_state()
    _, m_single = single.train_step(s1, single.pipeline.global_batch(0))
    l_multi, l_single = float(m_multi["loss"]), float(m_single["loss"])
    assert abs(l_multi - l_single) <= 2e-2 * max(1.0, abs(l_single)), (
        l_multi, l_single,
    )
