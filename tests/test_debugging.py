"""Sanitizer tier (SURVEY §5): the runtime checks actually fire."""

from __future__ import annotations
import pytest as _pytest_mark  # noqa: E402

# Sub-2-minute smoke tier (COVERAGE.md "Test tiers"): this module's
# measured wall time keeps `pytest -m fast` under the tier budget.
pytestmark = _pytest_mark.mark.fast


import os

import jax
import jax.numpy as jnp
import pytest

from frl_distributed_ml_scaffold_tpu.utils.debugging import (
    sanitize,
    sanitize_from_env,
    strict_donation,
)


def test_sanitize_nans_traps():
    with sanitize("nans"):
        with pytest.raises(FloatingPointError):
            jnp.zeros(4) / jnp.zeros(4)  # 0/0 -> NaN trap
    # flag restored on exit
    assert not getattr(jax.config, "jax_debug_nans")


def test_sanitize_restores_on_error():
    try:
        with sanitize("leaks"):
            assert getattr(jax.config, "jax_check_tracer_leaks")
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert not getattr(jax.config, "jax_check_tracer_leaks")


def test_sanitize_rejects_unknown_name():
    with pytest.raises(KeyError):
        with sanitize("racez"):
            pass


def test_sanitize_from_env(monkeypatch):
    monkeypatch.setenv("FRL_TPU_SANITIZE", "leaks")
    try:
        assert sanitize_from_env()
        assert getattr(jax.config, "jax_check_tracer_leaks")
    finally:
        jax.config.update("jax_check_tracer_leaks", False)
    monkeypatch.setenv("FRL_TPU_SANITIZE", "")
    assert not sanitize_from_env()


def test_strict_donation_passes_clean_code():
    with strict_donation():
        f = jax.jit(lambda x: x + 1, donate_argnums=0)
        x = jnp.ones(8)
        f(x)
