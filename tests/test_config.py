"""Config system tests (SURVEY §4 unit tier, C17)."""

import dataclasses

import pytest

from frl_distributed_ml_scaffold_tpu.config import (
    ExperimentConfig,
    MLPConfig,
    apply_overrides,
    config_from_dict,
    config_to_dict,
    get_config,
    list_configs,
)


def test_registry_has_five_baseline_recipes():
    names = list_configs()
    for required in (
        "mnist_mlp",
        "imagenet_rn50_ddp",
        "imagenet_vitb_fsdp",
        "gpt2_medium_zero1",
        "ego4d_video_elastic",
    ):
        assert required in names


def test_get_config_returns_fresh_frozen_instances():
    a = get_config("mnist_mlp")
    b = get_config("mnist_mlp")
    assert a == b
    with pytest.raises(dataclasses.FrozenInstanceError):
        a.name = "x"


def test_override_scalar_and_nested():
    cfg = get_config("mnist_mlp")
    cfg2 = apply_overrides(
        cfg, ["optimizer.learning_rate=0.5", "trainer.total_steps=7", "name=zz"]
    )
    assert cfg2.optimizer.learning_rate == 0.5
    assert cfg2.trainer.total_steps == 7
    assert cfg2.name == "zz"
    # original untouched
    assert cfg.trainer.total_steps != 7


def test_override_types():
    cfg = get_config("mnist_mlp")
    cfg2 = apply_overrides(
        cfg,
        [
            "model.hidden_sizes=128,64",
            "checkpoint.enabled=true",
            "optimizer.grad_clip_norm=none",
            "mesh.data=4",
        ],
    )
    assert cfg2.model.hidden_sizes == (128, 64)
    assert cfg2.checkpoint.enabled is True
    assert cfg2.optimizer.grad_clip_norm is None
    assert cfg2.mesh.data == 4


def test_override_unknown_field_raises():
    cfg = get_config("mnist_mlp")
    with pytest.raises(KeyError):
        apply_overrides(cfg, ["trainer.nonexistent=1"])


def test_roundtrip_dict():
    cfg = get_config("gpt2_medium_zero1")
    d = config_to_dict(cfg)
    assert d["model"]["num_layers"] == 24
    cfg2 = config_from_dict(ExperimentConfig, d)
    assert cfg2.trainer == cfg.trainer
    assert cfg2.optimizer == cfg.optimizer


def test_mlp_default():
    m = MLPConfig()
    assert m.family == "mlp"


def test_trainer_refuses_num_classes_mismatch():
    """Labels >= model.num_classes NaN the CE loss while grads stay finite
    (clamped gather) — the Trainer must refuse the config up front."""
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["model.num_classes=7", "data.global_batch_size=8"],
    )
    with pytest.raises(ValueError, match="num_classes"):
        Trainer(cfg)
