"""Tracing tier (ISSUE 8): spans across serve/train/elastic + the perf
ledger's exporters.

Layers, mirroring the subsystem:

- **Tracer**: trace/span/parent id semantics, implicit nesting, ring
  bound, enabled=False no-ops; the Chrome-trace-event export is
  golden-tested on fixed spans (tests/golden/trace_events.json).
- **Serving**: a CPU-sim serve run exports valid Chrome-trace JSON with
  ONE connected span tree per request spanning enqueue→retire, and
  tracing-on decode is token-identical to tracing-off with bounded
  step-time overhead (the PR 7 telemetry pin discipline).
- **Trainer**: fit() writes <run_dir>/trace_events.json with the
  step/load_batch/dispatch spans on the run's named lane; tracing=false
  keeps the telemetry.jsonl phase records and writes no trace file.
- **tools**: telemetry_report --diff percentile-delta table is
  golden-tested (tests/golden/telemetry_report_diff.json).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.obs

from frl_distributed_ml_scaffold_tpu.telemetry import (
    MetricsRegistry,
    Timeline,
    Tracer,
)

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ tracer


@pytest.mark.fast
def test_tracer_ids_nesting_ring_and_disabled():
    tr = Tracer(capacity=2)
    t = tr.new_trace("x")
    with tr.span("outer", trace=t) as outer:
        with tr.span("inner") as inner:  # implicit parent + trace
            assert inner.parent_id == outer.span_id
            assert inner.trace == t
    recs = tr.spans()
    assert [r["name"] for r in recs] == ["inner", "outer"]  # end order
    assert recs[0]["parent"] == outer.span_id
    assert recs[1].get("parent") is None
    # Ring bound: a third record drops the oldest, counts it.
    tr.emit("c", t0=0.0, dur_s=0.1, trace=t)
    assert len(tr) == 2 and tr.dropped == 1
    # drain() empties; a second drain is empty, not an error.
    assert len(tr.drain()) == 2
    assert tr.drain() == [] and len(tr) == 0
    # Disabled: null spans, nothing recorded, emit returns id 0.
    off = Tracer(enabled=False)
    with off.span("a") as s:
        s.end()
    assert off.begin("b").span_id == 0
    assert off.emit("c", t0=0.0, dur_s=0.0) == 0
    assert len(off) == 0


@pytest.mark.fast
def test_tracer_tees_finished_spans_into_timeline():
    """The drain-buffer contract: the Timeline keeps carrying the phase
    records (name/dur_s/attrs + span ids) for the telemetry.jsonl path
    while the tracer ring holds the tree for the Chrome export."""
    tl = Timeline()
    tr = Tracer(timeline=tl)
    t = tr.new_trace("lane")
    tr.emit("prefill", t0=0.0, dur_s=0.25, trace=t, cat="serve", slot=1)
    (rec,) = tl.drain()
    assert rec["event"] == "timeline" and rec["name"] == "prefill"
    assert rec["dur_s"] == 0.25 and rec["slot"] == 1
    assert rec["trace"] == t and rec["span"] > 0


@pytest.mark.fast
def test_trace_name_table_bounded_and_disabled_allocates_nothing():
    """A long-lived engine calls new_trace() per request forever: the
    lane-label table must stay bounded like the span ring, disabled
    tracers must not grow it at all, and the export must not emit
    metadata rows for lanes whose spans are gone (drained/evicted)."""
    off = Tracer(enabled=False)
    assert off.new_trace("request 1") == 0
    assert off._trace_names == {}
    tr = Tracer(capacity=4, origin=0.0)
    tids = [tr.new_trace(f"request {i}") for i in range(10)]
    assert len(tr._trace_names) == 4  # oldest labels evicted
    tr.emit("request", t0=0.0, dur_s=0.1, trace=tids[-1])
    events = tr.chrome_trace()["traceEvents"]
    lanes = [e for e in events if e["name"] == "thread_name"]
    assert [(e["tid"], e["args"]["name"]) for e in lanes] == [
        (tids[-1], "request 9")
    ]


@pytest.mark.fast
def test_chrome_trace_matches_golden():
    """The export acceptance golden: fixed spans → byte-stable
    Chrome-trace-event JSON (object form, "X" completes + "M" metadata,
    tid = trace lane). Regenerate deliberately if the format changes —
    this is what Perfetto/chrome://tracing parse."""
    tr = Tracer(origin=0.0)
    t = tr.new_trace("request 0")
    root = tr.emit(
        "request", t0=0.0005, dur_s=0.0125, trace=t, cat="serve",
        request=0, prompt_len=4, finish_reason="length", n_tokens=2,
    )
    tr.emit(
        "queue_wait", t0=0.0005, dur_s=0.001, trace=t, parent=root,
        cat="serve", slot=0,
    )
    tr.emit(
        "prefill", t0=0.0015, dur_s=0.004, trace=t, parent=root,
        cat="serve", slot=0, bucket=8, request=0,
    )
    tr.emit(
        "graft", t0=0.0035, dur_s=0.001, trace=t, parent=root,
        cat="serve", slot=0, bucket=16,
    )
    e = tr.new_trace("engine")
    tr.emit(
        "decode", t0=0.006, dur_s=0.003, trace=e, cat="serve",
        bucket=16, active=1,
    )
    tr.emit(
        "decode_tick", t0=0.006, dur_s=0.003, trace=t, parent=root,
        cat="serve", slot=0, token=1,
    )
    tr.emit(
        "retire", t0=0.013, dur_s=0.0, trace=t, parent=root, cat="serve",
        slot=0, request=0, reason="length", n_tokens=2,
    )
    golden = json.load(open(os.path.join(GOLDEN, "trace_events.json")))
    assert tr.chrome_trace() == golden


# ----------------------------------------------------------------- serving


@pytest.fixture(scope="module")
def gpt():
    import jax

    from _jit import jit_init
    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=4, hidden_dim=64,
            seq_len=64, dropout=0.0,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    params = jit_init(model, tokens, train=False)["params"]
    return model, params


def _workload(n=5, seed=7):
    rng = np.random.default_rng(seed)
    return [
        (
            rng.integers(0, 64, size=int(rng.integers(2, 12))).astype(np.int32),
            int(rng.integers(2, 8)),
        )
        for _ in range(n)
    ]


def _serve(model, params, workload, **kw):
    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    eng = ServingEngine(model, params, num_slots=3, temperature=0.0, **kw)
    for prompt, n_new in workload:
        eng.submit(prompt, n_new)
    done = {c.id: c for c in eng.run()}
    return eng, done


def test_serve_trace_export_is_connected_per_request(gpt, tmp_path):
    """The serve acceptance gate: the exported trace is valid
    Chrome-trace-event JSON, and every request is ONE connected span
    tree — a single parentless "request" root per trace id spanning
    enqueue→retire, with queue_wait/prefill/decode_tick/retire leaves
    all chained to it."""
    model, params = gpt
    work = _workload()
    eng, done = _serve(model, params, work)
    try:
        assert len(done) == len(work)
        path = tmp_path / "serve_trace.json"
        eng.export_trace(str(path))
        trace = json.loads(path.read_text())  # valid JSON by construction
        assert set(trace) == {"traceEvents", "displayTimeUnit"}
        events = trace["traceEvents"]
        spans = [e for e in events if e["ph"] == "X"]
        meta = [e for e in events if e["ph"] == "M"]
        assert spans and meta
        for e in spans:  # the chrome-trace-event complete-event schema
            assert {"name", "ph", "ts", "dur", "pid", "tid", "args"} <= set(e)
        # Per-request lanes: metadata names them "request <id>".
        lane_names = {
            e["tid"]: e["args"]["name"] for e in meta
            if e["name"] == "thread_name"
        }
        roots = [
            e for e in spans
            if e["name"] == "request" and "parent" not in e["args"]
        ]
        assert len(roots) == len(work)  # one root per request, each closed
        for root in roots:
            rid = root["args"]["request"]
            lane = root["tid"]
            assert lane_names[lane] == f"request {rid}"
            tree = [e for e in spans if e["tid"] == lane]
            kids = [e for e in tree if e is not root]
            # Connectedness: every other span on the lane chains to the
            # root (depth 1 by construction — assert the edge exactly).
            assert kids and all(
                e["args"].get("parent") == root["args"]["span"] for e in kids
            )
            names = {e["name"] for e in kids}
            assert {"queue_wait", "prefill", "graft", "retire"} <= names
            n_new = len(done[rid].tokens) - done[rid].prompt_len
            assert (
                len([e for e in kids if e["name"] == "decode_tick"])
                == n_new - 1
            )
            # The root spans enqueue→retire: it contains its children.
            t0, t1 = root["ts"], root["ts"] + root["dur"]
            for e in kids:
                assert t0 <= e["ts"] and e["ts"] + e["dur"] <= t1 + 1e-3
        # Engine-lane spans (decode programs, grows) ride their own lane.
        eng_lanes = [t for t, n in lane_names.items() if n == "engine"]
        assert len(eng_lanes) == 1
        assert any(
            e["name"] == "decode" and e["tid"] == eng_lanes[0] for e in spans
        )
    finally:
        eng.close()


def test_tracing_off_token_identical_with_bounded_overhead(gpt):
    """The overhead pin (same discipline as the PR 7 telemetry pin):
    tracing must never touch the jitted programs — tokens identical with
    the tracer enabled vs disabled, median per-token latency within a
    generous 3x. Telemetry stays ON in both arms so only tracing moves."""
    model, params = gpt
    work = _workload(n=5, seed=13)
    runs = {}
    for label, tracer in (
        ("on", None),  # engine default: enabled tracer
        ("off", Tracer(enabled=False)),
    ):
        eng, _ = _serve(model, params, work, tracer=tracer)  # warm pass
        eng.reset_cache()
        for prompt, n_new in work:
            eng.submit(prompt, n_new)
        done = {c.id: c for c in eng.run()}
        runs[label] = (
            {rid: c.tokens for rid, c in done.items()},
            [dt for c in done.values() for dt in c.token_latencies_s[1:]],
        )
        eng.close()
    tokens_on, lat_on = runs["on"]
    tokens_off, lat_off = runs["off"]
    assert sorted(tokens_on) == sorted(tokens_off)
    for rid in tokens_on:
        np.testing.assert_array_equal(
            tokens_on[rid], tokens_off[rid],
            err_msg=f"tracing changed request {rid}'s tokens",
        )
    med_on = float(np.median(lat_on))
    med_off = float(np.median(lat_off))
    assert med_on <= 3.0 * max(med_off, 1e-9), (med_on, med_off)


def test_engine_timeline_phases_survive_external_tracer(gpt):
    """telemetry.jsonl's phase records (PR 7 contract) must not depend on
    tracing state: with a caller-supplied DISABLED tracer the engine
    falls back to bare timeline events, and with the default tee the
    same phases arrive exactly once (no double records)."""
    model, params = gpt
    work = _workload(n=2, seed=3)
    for tracer in (None, Tracer(enabled=False)):
        eng, done = _serve(model, params, work, tracer=tracer)
        try:
            assert len(done) == len(work)
            recs = eng.timeline.drain()
            names = [r["name"] for r in recs]
            assert {"queue_wait", "prefill", "graft", "decode",
                    "retire"} <= set(names)
            # Exactly one retire phase per request in BOTH arms.
            assert names.count("retire") == len(work)
        finally:
            eng.close()


def test_reset_cache_drops_warm_pass_spans(gpt):
    """The serve_bench warm-up discipline extends to spans: after
    reset_cache the ring carries only the measured pass's trees."""
    model, params = gpt
    work = _workload(n=2, seed=5)
    eng, _ = _serve(model, params, work)
    try:
        assert len(eng.tracing) > 0
        eng.reset_cache()
        assert len(eng.tracing) == 0
        for prompt, n_new in work:
            eng.submit(prompt, n_new)
        eng.run()
        roots = [
            r for r in eng.tracing.spans() if r["name"] == "request"
        ]
        assert len(roots) == len(work)
    finally:
        eng.close()


# ----------------------------------------------------------------- trainer


def _tiny_fit(workdir, overrides=()):
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        [
            "trainer.total_steps=6",
            "trainer.log_every=3",
            "data.global_batch_size=32",
            "checkpoint.enabled=false",
            f"workdir={workdir}",
            *overrides,
        ],
    )
    Trainer(cfg).fit()
    return os.path.join(workdir, cfg.name)


def test_trainer_fit_exports_chrome_trace(tmp_path):
    """fit() writes <run_dir>/trace_events.json: the run's named lane
    carrying step → load_batch/dispatch spans for every step, children
    chained to their step span."""
    run_dir = _tiny_fit(str(tmp_path))
    trace = json.loads(
        open(os.path.join(run_dir, "trace_events.json")).read()
    )
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    lanes = {
        e["args"]["name"] for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "mnist_mlp" in lanes
    steps = [e for e in spans if e["name"] == "step"]
    assert len(steps) == 6
    by_id = {e["args"]["span"]: e for e in spans}
    for name in ("load_batch", "dispatch"):
        kids = [e for e in spans if e["name"] == name]
        assert len(kids) == 6
        for e in kids:  # nested under that step's root span
            parent = by_id[e["args"]["parent"]]
            assert parent["name"] == "step"
            assert parent["args"]["step"] == e["args"]["step"]
    # The spans also landed in telemetry.jsonl via the timeline tee.
    recs = [
        json.loads(l)
        for l in open(os.path.join(run_dir, "telemetry.jsonl"))
    ]
    phases = {r["name"] for r in recs if r["event"] == "timeline"}
    assert {"step", "load_batch", "dispatch"} <= phases


def test_trainer_tracing_off_keeps_timeline_phases(tmp_path):
    """trainer.tracing=false: no trace file, but telemetry.jsonl still
    carries the load_batch/dispatch phase records (the PR 7 contract
    must not regress when tracing is off)."""
    run_dir = _tiny_fit(str(tmp_path), ["trainer.tracing=false"])
    assert not os.path.exists(os.path.join(run_dir, "trace_events.json"))
    recs = [
        json.loads(l)
        for l in open(os.path.join(run_dir, "telemetry.jsonl"))
    ]
    phases = {r["name"] for r in recs if r["event"] == "timeline"}
    assert {"load_batch", "dispatch"} <= phases


# ------------------------------------------------------- telemetry_report


def _write_run_jsonl(path, bucket_counts, steps, extra_scalar=None):
    """A minimal telemetry.jsonl with one cumulative snapshot whose
    histogram carries serialized CUMULATIVE bucket counts."""
    metrics = {
        "lat": {
            "type": "histogram", "count": bucket_counts[-1],
            "sum": 1.0, "p50": 0.0, "p95": 0.0, "p99": 0.0,
            "buckets": {"0.001": bucket_counts[0],
                        "0.004": bucket_counts[1],
                        "0.016": bucket_counts[2],
                        "+Inf": bucket_counts[-1]},
        },
        "steps_total": float(steps),
    }
    if extra_scalar:
        metrics.update(extra_scalar)
    with open(path, "w") as fh:
        fh.write(json.dumps(
            {"event": "timeline", "name": "dispatch", "ts": 1.0,
             "dur_s": 0.01}
        ) + "\n")
        fh.write(json.dumps(
            {"event": "telemetry", "ts": 2.0, "metrics": metrics}
        ) + "\n")


@pytest.mark.fast
def test_telemetry_report_diff_matches_golden(tmp_path, capsys):
    """Satellite: --diff recomputes each side's percentiles from the raw
    buckets and renders the B-A delta table; the --json payload is
    golden-tested byte-stable."""
    import sys as _sys

    tools = os.path.join(REPO, "tools")
    if tools not in _sys.path:
        _sys.path.insert(0, tools)
    import telemetry_report

    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_run_jsonl(str(a), (4, 8, 10, 10), steps=10)
    _write_run_jsonl(
        str(b), (1, 3, 10, 12), steps=12, extra_scalar={"queue_depth": 2.0}
    )
    out = tmp_path / "diff.json"
    rc = telemetry_report.main(
        ["--diff", str(a), str(b), "--json", str(out)]
    )
    assert rc == 0
    text = capsys.readouterr().out
    assert "telemetry diff" in text and "d_p99_s" in text
    golden = json.load(
        open(os.path.join(GOLDEN, "telemetry_report_diff.json"))
    )
    assert json.loads(out.read_text()) == golden
    # Deltas tie out against the single-run reports they join.
    rep = golden["histograms"][0]
    assert rep["delta"]["count"] == rep["b"]["count"] - rep["a"]["count"]
    assert rep["delta"]["p50_s"] == pytest.approx(
        rep["b"]["p50_s"] - rep["a"]["p50_s"], abs=1e-6
    )
