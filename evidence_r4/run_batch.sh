#!/bin/bash
# Round-4 evidence batch (VERDICT r3 next-round #1): the relay answered at
# 21:06 UTC 2026-07-30 — capture every staged on-chip measurement in order,
# each stage bounded so a relay drop mid-batch cannot hang the round.
cd /root/repo
set -o pipefail  # rc must be the python/timeout status, not tee's
mkdir -p evidence_r4
stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

echo "=== evidence batch start $(stamp) ==="

echo "--- stage 1: tpu_smoke (incl. fused-AdamW first REAL Mosaic compile) ---"
timeout 1500 python tools/tpu_smoke.py 2>&1 | tee evidence_r4/smoke.log
echo "stage1 rc=$? $(stamp)"

echo "--- stage 2: bench.py headline (reproduce 2257.9 / 0.903) ---"
timeout 1500 python bench.py 2>&1 | tee evidence_r4/headline.log
echo "stage2 rc=$? $(stamp)"

echo "--- stage 3: bench.py --all (regenerate BENCH_TABLE.jsonl + gpt2_moe line) ---"
timeout 3600 python bench.py --all 2>&1 | tee evidence_r4/bench_all.log
echo "stage3 rc=$? $(stamp)"

echo "--- stage 4: perf_sweep gpt2_opt gpt2_offload rn50_fused_opt ---"
timeout 5400 python tools/perf_sweep.py gpt2_opt gpt2_offload rn50_fused_opt 2>&1 | tee evidence_r4/perf_sweep.log
echo "stage4 rc=$? $(stamp)"

echo "--- stage 5: flash_sweep ladder to 64k ---"
timeout 5400 python tools/flash_sweep.py 2>&1 | tee evidence_r4/flash_sweep.log
echo "stage5 rc=$? $(stamp)"

echo "=== evidence batch done $(stamp) ==="
