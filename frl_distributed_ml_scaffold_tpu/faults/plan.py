"""Deterministic fault injection: the ``FaultPlan`` registry (ISSUE 9).

At the north-star scale (millions of users, pod-scale training),
preemptions, torn checkpoint writes, hung steps, and poison requests are
ROUTINE operating conditions — the only way to keep the recovery paths
honest is to exercise them on demand, reproducibly, in tests and chaos
benches. This module is the one registry those drills go through:

- **Sites, not callbacks.** Every injectable failure is a NAMED site
  (``KNOWN_SITES``); the trainer, serving engine, checkpointer, data
  pipeline, and elastic membership each consult their site with a cheap
  host-side hook (``faults.fire(site)`` — a dict lookup + ``None`` check
  when unarmed). Unknown site names are refused at plan construction, so
  a typo'd chaos spec fails loudly instead of silently injecting
  nothing.
- **Deterministic.** A spec fires on the ``at``-th matching consultation
  (1-based) for ``times`` consecutive consultations (``times=0`` = every
  one from ``at``); optional probabilistic firing (``p < 1``) draws from
  a ``random.Random(seed)`` owned by the plan — same seed, same chaos.
  Wall clock never participates.
- **Counted.** Every injection increments ``fault_injected_total`` plus
  a per-site counter on the plan's registry (when given) and the plan's
  own ``injected`` tally — a chaos run's report can always say exactly
  what was injected, and the tiers separately count what they OBSERVED
  (``serve_shed_total``, ``heartbeat_write_failures_total``, ...); the
  injected-vs-observed diff is the detection gap.

The ambient plan (installed via ``faults.install`` / the ``active``
context manager, or the ``FRL_FAULT_PLAN`` env var for child processes)
lives in ``faults/__init__.py``; this module is the mechanism.
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
from typing import Any, Iterable, Optional

#: The injectable sites. One name per failure class in the fault matrix
#: (docs/operations.md "Failure semantics"); adding a site here is the
#: contract that some tier consults it and tests/test_faults.py pins
#: both its detection and its recovery.
KNOWN_SITES = frozenset(
    {
        # checkpoint/manager.py: save completes on disk but the write is
        # torn (a file truncated, no commit marker) — the crash-mid-write
        # shape restore must skip.
        "checkpoint.torn_write",
        # data/pipeline.py: the host-side batch build raises (decode
        # error, bad shard, transient FS) — retried under faults/retry.py.
        "data.loader",
        # trainer/loop.py: one step's host loop hangs for ``arg`` seconds
        # (a wedged collective / data loader) — the stall watchdog's prey.
        "trainer.hung_step",
        # trainer/loop.py: deliver SIGTERM to ourselves (a TPU maintenance
        # preemption) — drives the checkpoint-and-exit-clean path.
        "trainer.preempt",
        # launcher/elastic.py child: hard os._exit after a step (the
        # SIGKILL moral equivalent) — drives the supervisor restart path.
        "child.hard_exit",
        # serving/engine.py: a request's prefill raises (poison request).
        "serve.prefill",
        # serving/engine.py: growing the KV cache to the next bucket
        # fails (allocation failure at high occupancy).
        "serve.grow",
        # serving/engine.py: the speculative draft proposer raises
        # mid-decode (ISSUE 11) — the slot degrades to plain
        # single-token decode for the rest of its request (counted,
        # never sheds, never hangs; tokens stay identical because
        # drafting is advisory).
        "serve.draft",
        # serving/scheduler.py: the PREFILL WORKER dies mid-request
        # (ISSUE 12) — the scheduler releases the pool reservation,
        # re-queues the request at the head of its tenant queue, and
        # retries (bounded by serving.handoff_retries, then typed
        # "error"); the decode worker never notices.
        "serve.prefill_worker",
        # serving/scheduler.py: the prefill→decode HANDOFF (the
        # block-table splice) fails (ISSUE 12) — same recovery as a
        # prefill-worker death: release, re-queue, bounded retry. The
        # never-hangs contract extends across the worker boundary.
        "serve.handoff",
        # launcher/elastic.py: a membership heartbeat write raises OSError
        # (shared-FS outage) — drives the counted-retirement path.
        "elastic.heartbeat_write",
    }
)


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed injection: fire at the ``at``-th matching consultation.

    ``key`` narrows matching to consultations carrying the same key (the
    sites define what a key is — the serving engine passes the request
    id, the data pipeline the step); ``""`` matches every consultation.
    ``arg`` is the site-specific payload (hang seconds for
    ``trainer.hung_step``; unused elsewhere).
    """

    site: str
    at: int = 1
    times: int = 1  # 0 = every consultation from ``at`` on
    p: float = 1.0
    arg: float = 0.0
    key: str = ""

    def __post_init__(self):
        if self.site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (known: "
                f"{sorted(KNOWN_SITES)}) — a typo'd chaos spec would "
                "otherwise silently inject nothing"
            )
        if self.at < 1:
            raise ValueError(f"fault {self.site}: at={self.at} < 1 (1-based)")
        if self.times < 0:
            raise ValueError(f"fault {self.site}: times={self.times} < 0")
        if not 0.0 < self.p <= 1.0:
            raise ValueError(f"fault {self.site}: p={self.p} outside (0, 1]")


def _counter_name(site: str) -> str:
    return f"fault_injected_{site.replace('.', '_')}_total"


class FaultPlan:
    """A seeded set of ``FaultSpec``s consulted via ``fire``.

    Thread-safe (the engine's watchdog thread, the prefetch worker, and
    the elastic heartbeat thread all consult concurrently); cheap when a
    site has no specs (one lock-free dict lookup).
    """

    def __init__(
        self,
        specs: Iterable[FaultSpec | dict],
        *,
        seed: int = 0,
        registry: Any | None = None,
    ):
        parsed = [
            s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in specs
        ]
        self._by_site: dict[str, list[FaultSpec]] = {}
        for s in parsed:
            self._by_site.setdefault(s.site, []).append(s)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        # Per-spec count of MATCHING consultations (the occurrence index
        # ``at`` indexes into) — keyed by spec identity, not site, so two
        # specs on one site count independently.
        self._matches: dict[int, int] = {}
        #: site -> injections fired (the plan's own ledger; always kept,
        #: registry or not, so chaos tests can assert without telemetry).
        self.injected: dict[str, int] = {}
        self._registry = registry
        self._m_total = (
            registry.counter(
                "fault_injected_total",
                help="fault-plan injections fired, all sites",
            )
            if registry is not None
            else None
        )
        self._m_site: dict[str, Any] = {}
        if registry is not None:
            # Register every armed site's counter up front: the catalog
            # contract (a site that never fired scrapes as 0 — itself a
            # signal that the drill did not reach it).
            for site in self._by_site:
                self._m_site[site] = registry.counter(
                    _counter_name(site),
                    help=f"injections fired at fault site {site}",
                )

    @classmethod
    def from_env(
        cls, value: str, *, registry: Any | None = None
    ) -> "FaultPlan":
        """Parse the ``FRL_FAULT_PLAN`` JSON: either a list of spec
        objects or ``{"seed": ..., "specs": [...]}``."""
        try:
            data = json.loads(value)
        except ValueError as e:
            raise ValueError(
                f"FRL_FAULT_PLAN is not valid JSON ({e}): {value!r}"
            ) from None
        if isinstance(data, dict):
            seed = int(data.get("seed", 0))
            specs = data.get("specs", [])
        else:
            seed, specs = 0, data
        if not isinstance(specs, list):
            raise ValueError(
                f"FRL_FAULT_PLAN specs must be a list, got {type(specs).__name__}"
            )
        return cls(specs, seed=seed, registry=registry)

    @property
    def sites(self) -> list[str]:
        return sorted(self._by_site)

    def fire(self, site: str, key: str = "") -> Optional[FaultSpec]:
        """Consult ``site``; returns the firing spec (the caller applies
        its effect) or ``None``. The no-spec path is one dict lookup."""
        specs = self._by_site.get(site)
        if not specs:
            return None
        with self._lock:
            # EVERY matching spec observes this consultation (the
            # independent-counting contract above) — an early return
            # would make a stacked plan's later windows fire late.
            fired: Optional[FaultSpec] = None
            for spec in specs:
                if spec.key and spec.key != str(key):
                    continue
                sid = id(spec)
                n = self._matches.get(sid, 0) + 1
                self._matches[sid] = n
                if n < spec.at:
                    continue
                if spec.times and n >= spec.at + spec.times:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                if fired is None:
                    fired = spec
            if fired is None:
                return None
            self.injected[site] = self.injected.get(site, 0) + 1
            if self._m_total is not None:
                self._m_total.inc()
                self._m_site[site].inc()
            return fired
