"""One retry policy for every retrying tier (ISSUE 9).

Before this module the repo hand-rolled retry/backoff separately in the
elastic supervisor's restart loop (exponential, uncapped) and wherever a
transient-FS call needed retrying. One policy object replaces them so
the semantics are auditable in one place:

- **Exponential backoff with a cap**: ``delay(attempt) =
  min(backoff_s * 2**(attempt-1), max_backoff_s)`` — the supervisor's
  exact historical sequence for small attempt counts, now bounded so a
  crash-looping child cannot back off into hours.
- **Seeded jitter**: ``jitter`` spreads each delay uniformly over
  ``[d*(1-jitter), d*(1+jitter)]`` from a ``random.Random(seed)`` — the
  thundering-herd breaker for fleet-synchronized failures (every host's
  child dies at the same shared-FS outage), deterministic per seed so
  chaos tests replay exactly.
- **A budget, not a promise**: ``max_retries`` retries after the first
  try, then the last exception propagates. Step-driven retriers that
  never sleep (streaming window adoption) consume only the budget.

``call`` is the sleeping form (data-loader rebuilds, any transient-FS
work); the elastic supervisor keeps its own loop structure (restart
accounting, shrink policy) and takes just ``delay``. Interval-driven
retriers (the membership heartbeat) and step-driven budgets (streaming
window adoption) deliberately stay outside — their cadence IS the
backoff. Every adopter logs each retry — a silent retry is the failure
mode this module exists to kill.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Iterator, Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter + budget; see the module docstring."""

    max_retries: int = 3
    backoff_s: float = 1.0
    max_backoff_s: float = 60.0
    jitter: float = 0.0  # fraction of the delay, uniform, seeded
    seed: int = 0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries={self.max_retries} < 0")
        if self.backoff_s < 0 or self.max_backoff_s < 0:
            raise ValueError(
                f"negative backoff ({self.backoff_s}, {self.max_backoff_s})"
            )
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter={self.jitter} outside [0, 1)")

    def delay(
        self, attempt: int, rng: Optional[random.Random] = None
    ) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        if attempt < 1:
            raise ValueError(f"attempt={attempt} < 1 (1-based)")
        d = min(self.backoff_s * (2.0 ** (attempt - 1)), self.max_backoff_s)
        if self.jitter and d > 0:
            r = rng if rng is not None else random.Random(self.seed)
            d *= 1.0 + self.jitter * (2.0 * r.random() - 1.0)
        return d

    def delays(self) -> Iterator[float]:
        """The full budgeted delay sequence (one shared jitter stream —
        deterministic per seed)."""
        rng = random.Random(self.seed)
        for attempt in range(1, self.max_retries + 1):
            yield self.delay(attempt, rng)

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        retry_on: tuple = (Exception,),
        describe: str = "",
        logger: Any | None = None,
        counter: Any | None = None,
        on_retry: Callable[[int, BaseException], None] | None = None,
        sleep: Callable[[float], None] = time.sleep,
        **kwargs: Any,
    ) -> Any:
        """Run ``fn`` under the policy: on a ``retry_on`` exception, log
        it, count it (``counter.inc()`` when given), back off, retry; the
        budget's last exception propagates unchanged. Anything outside
        ``retry_on`` propagates immediately — a retry loop must never
        absorb KeyboardInterrupt or a programming error it wasn't told
        about."""
        rng = random.Random(self.seed)
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                # Only PERFORMED retries are counted/observed — the
                # budget-exhausting failure propagates, it is not a
                # retry, and a ledger reading max_retries+1 would show a
                # phantom attempt to chaos drills diffing injected vs
                # observed.
                if counter is not None:
                    counter.inc()
                if on_retry is not None:
                    on_retry(attempt, e)
                d = self.delay(attempt, rng)
                if logger is None:
                    from frl_distributed_ml_scaffold_tpu.utils.logging import (
                        get_logger,
                    )

                    logger = get_logger()
                logger.warning(
                    "retry %d/%d%s in %.3fs after %s: %s",
                    attempt,
                    self.max_retries,
                    f" for {describe}" if describe else "",
                    d,
                    type(e).__name__,
                    e,
                )
                if d > 0:
                    sleep(d)
