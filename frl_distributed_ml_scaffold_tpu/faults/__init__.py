"""Fault-injection harness + unified retry policy (ISSUE 9).

``plan.py`` holds the mechanism (``FaultPlan``/``FaultSpec``, the named
site registry); ``retry.py`` the one backoff policy every retrying tier
adopts. This package root holds the AMBIENT plan: the tiers consult
module-level hooks (``fire``/``maybe_raise``/``maybe_hang``) so deep call
stacks (a Checkpointer constructed inside a Trainer inside a supervised
child) need no plumbing — and the unarmed path is one ``None`` check.

Arming:

- in-process (tests, chaos benches): ``with faults.active(plan): ...``
  or ``faults.install(plan)`` / ``faults.install(None)``;
- cross-process (elastic supervision drills): the ``FRL_FAULT_PLAN`` env
  var (JSON — see ``FaultPlan.from_env``), read lazily on the first
  consultation in the child. Note the occurrence counters (``at``) are
  per-process: a restarted child re-counts from zero, so supervised
  drills that must fire exactly once still use the workdir-marker
  one-shot (``launcher/elastic.py``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Iterator, Optional

from frl_distributed_ml_scaffold_tpu.faults.locks import (
    LockOrderRecorder,
    instrumented_locks,
)
from frl_distributed_ml_scaffold_tpu.faults.plan import (
    KNOWN_SITES,
    FaultPlan,
    FaultSpec,
)
from frl_distributed_ml_scaffold_tpu.faults.retry import RetryPolicy

__all__ = [
    "KNOWN_SITES",
    "FaultPlan",
    "FaultSpec",
    "LockOrderRecorder",
    "RetryPolicy",
    "active",
    "current_plan",
    "fire",
    "install",
    "instrumented_locks",
    "maybe_hang",
    "maybe_raise",
]

_PLAN: Optional[FaultPlan] = None
_ENV_READ = False


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` as the ambient plan (``None`` disarms); returns
    the previous plan so callers can restore it."""
    global _PLAN, _ENV_READ
    prev = _PLAN
    _PLAN = plan
    # An explicit install (including disarm) overrides the env path for
    # the rest of the process — tests must never inherit a stray env plan.
    _ENV_READ = True
    return prev


@contextlib.contextmanager
def active(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Scoped arming: ``with faults.active(plan): ...``."""
    prev = install(plan)
    try:
        yield plan
    finally:
        install(prev)


def current_plan() -> Optional[FaultPlan]:
    global _ENV_READ, _PLAN
    if not _ENV_READ:
        _ENV_READ = True
        spec = os.environ.get("FRL_FAULT_PLAN")
        if spec:
            _PLAN = FaultPlan.from_env(spec)
    return _PLAN


def fire(site: str, key: Any = "") -> Optional[FaultSpec]:
    """Consult the ambient plan at ``site``; ``None`` when unarmed (the
    fast path every production step takes)."""
    plan = _PLAN if _ENV_READ else current_plan()
    if plan is None:
        return None
    return plan.fire(site, str(key))


def maybe_raise(
    site: str,
    exc: type = RuntimeError,
    *,
    key: Any = "",
    msg: str | None = None,
) -> None:
    """Raise ``exc`` when the site fires — the injection shape for sites
    whose real failure is an exception (loader errors, heartbeat OSError,
    poison prefill, grow allocation failure)."""
    spec = fire(site, key)
    if spec is not None:
        raise exc(msg or f"injected fault: {site}" + (f" key={key}" if str(key) else ""))


def maybe_hang(site: str, *, key: Any = "") -> bool:
    """Sleep ``spec.arg`` seconds when the site fires (a hung/slow step);
    returns whether it fired."""
    spec = fire(site, key)
    if spec is None:
        return False
    if spec.arg > 0:
        time.sleep(spec.arg)
    return True
