"""Runtime lock-order sentinel: ``faults.instrumented_locks()`` (ISSUE 20).

The static pass (``analysis/concurrency.py``) proves lock discipline
over the source; this module observes it on LIVE threads.  Inside the
context, ``threading.Lock``/``RLock``/``Condition`` constructed by
package code return instrumented wrappers that record, per thread:

- the acquisition-order edges (which lock was held when another was
  acquired) — the runtime twin of the static lock-order graph;
- per-creation-site acquisition counts and hold times (max + total).

At scope exit the recorder asserts the observed order graph is ACYCLIC
— so every chaos/disagg/elastic/redistribute drill that runs under it
doubles as a deadlock drill: if two threads ever took locks in opposite
orders during the drill, the test fails even though the interleaving
happened not to deadlock this time.

Only locks whose creating frame lives inside this package are wrapped
by default (jax/runtime internals construct locks constantly; their
hold times during compiles would drown the signal); ``wrap_all=True``
lifts that for synthetic unit tests.  The recorder's own bookkeeping is
guarded by an ORIGINAL (unwrapped) lock, so it never records itself.

``analysis.pins.assert_lock_order_acyclic`` /
``assert_no_blocking_under_lock`` consume the recorder mid-drill.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Any, Iterator, Optional

__all__ = ["LockOrderRecorder", "instrumented_locks"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

#: Directory of the package root (…/frl_distributed_ml_scaffold_tpu).
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG_NAME = os.path.basename(_PKG_DIR)


class LockOrderRecorder:
    """Per-thread acquisition sequences, order edges, and hold times."""

    def __init__(self) -> None:
        self._meta = _REAL_LOCK()  # guards the dicts below; never wrapped
        #: (held_site, acquired_site) -> times observed.  Sites are
        #: per-INSTANCE (creation site + serial): two locks born on the
        #: same source line are different locks, and flagging a cycle
        #: across distinct instance pairs would be a false positive
        #: (hand-over-hand per-item locks are legal).
        self.edges: dict[tuple[str, str], int] = {}
        #: site -> acquisitions
        self.acquired: dict[str, int] = {}
        #: site -> (max_hold_s, total_hold_s, thread name at max)
        self.holds: dict[str, tuple[float, float, str]] = {}
        self._tls = threading.local()
        self._serials: dict[str, int] = {}

    def instance_site(self, label: str) -> str:
        """Unique site id for a new lock born at source-site ``label``."""
        with self._meta:
            n = self._serials.get(label, 0)
            self._serials[label] = n + 1
        return label if n == 0 else f"{label}#{n}"

    # -- wrapper callbacks -------------------------------------------
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def on_acquired(self, site: str) -> None:
        held = self._held()
        t = time.monotonic()
        with self._meta:
            self.acquired[site] = self.acquired.get(site, 0) + 1
            for h_site, _ in held:
                if h_site != site:
                    key = (h_site, site)
                    self.edges[key] = self.edges.get(key, 0) + 1
        held.append((site, t))

    def on_released(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == site:
                dur = time.monotonic() - held[i][1]
                del held[i]
                # NEVER threading.current_thread() here: during thread
                # bootstrap it can allocate a _DummyThread -> Event ->
                # patched Lock(), re-entering the recorder.  get_ident
                # allocates nothing; _active is only read.
                ident = threading.get_ident()
                t = threading._active.get(ident)
                name = t.name if t is not None else f"tid{ident}"
                with self._meta:
                    mx, total, who = self.holds.get(site, (0.0, 0.0, ""))
                    if dur > mx:
                        mx, who = dur, name
                    self.holds[site] = (mx, total + dur, who)
                return

    # -- queries ------------------------------------------------------
    def order_edges(self) -> dict[tuple[str, str], int]:
        with self._meta:
            return dict(self.edges)

    def max_holds(self) -> dict[str, tuple[float, str]]:
        """site -> (max hold seconds, holding thread's name)."""
        with self._meta:
            return {s: (mx, who) for s, (mx, _, who) in self.holds.items()}

    def find_cycle(self) -> Optional[list[str]]:
        """A lock-order cycle as [site_a, site_b, ..., site_a], or None."""
        edges = self.order_edges()
        adj: dict[str, list[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        color: dict[str, int] = {}
        stack: list[str] = []
        out: list[list[str]] = []

        def dfs(u: str) -> None:
            color[u] = 1
            stack.append(u)
            for v in sorted(adj[u]):
                if out:
                    break
                if color.get(v, 0) == 0:
                    dfs(v)
                elif color.get(v) == 1:
                    out.append(stack[stack.index(v):] + [v])
            stack.pop()
            color[u] = 2

        for node in sorted(adj):
            if out:
                break
            if color.get(node, 0) == 0:
                dfs(node)
        return out[0] if out else None

    def publish(self, registry: Any) -> None:
        """Counters/gauges for a drill's report: how much locking a
        fault drill actually exercised, and the worst hold seen."""
        with self._meta:
            n_acq = sum(self.acquired.values())
            n_sites = len(self.acquired)
            n_edges = len(self.edges)
            worst = max(
                (mx for mx, _, _ in self.holds.values()), default=0.0
            )
        registry.counter(
            "lock_acquisitions_total",
            help="instrumented lock acquisitions during the drill",
        ).inc(n_acq)
        registry.gauge(
            "lock_sites", help="distinct instrumented lock creation sites"
        ).set(n_sites)
        registry.gauge(
            "lock_order_edges",
            help="observed lock-order edges (held -> acquired)",
        ).set(n_edges)
        registry.gauge(
            "lock_hold_max_seconds",
            help="longest single lock hold observed",
        ).set(worst)


class _InstrumentedLock:
    """Wraps a real Lock; reports acquire/release to the recorder."""

    def __init__(self, recorder: LockOrderRecorder, site: str, real: Any):
        self._recorder = recorder
        self._site = site
        self._real = real

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._recorder.on_acquired(self._site)
        return ok

    def release(self) -> None:
        self._recorder.on_released(self._site)
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> "_InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<instrumented {self._real!r} @ {self._site}>"


class _InstrumentedRLock(_InstrumentedLock):
    """Reentrant variant: only the OUTERMOST acquire/release records, so
    reentry neither double-counts hold time nor self-edges."""

    def __init__(self, recorder: LockOrderRecorder, site: str, real: Any):
        super().__init__(recorder, site, real)
        self._depth = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._real.acquire(blocking, timeout)
        if ok:
            d = getattr(self._depth, "n", 0)
            self._depth.n = d + 1
            if d == 0:
                self._recorder.on_acquired(self._site)
        return ok

    def release(self) -> None:
        d = getattr(self._depth, "n", 0)
        self._depth.n = max(0, d - 1)
        if d == 1:
            self._recorder.on_released(self._site)
        self._real.release()

    # Condition(wrapped_rlock) support: CPython's Condition probes these
    # and, when absent, falls back to acquire(0)-based ownership checks
    # that are WRONG for reentrant locks (acquire(0) succeeds for the
    # owner).  Delegate to the real RLock, keeping the recorder's view
    # consistent: a full release ends the hold, the restore restarts it.
    def _release_save(self) -> Any:
        if getattr(self._depth, "n", 0) > 0:
            self._recorder.on_released(self._site)
        self._depth.n = 0
        return self._real._release_save()

    def _acquire_restore(self, state: Any) -> None:
        self._real._acquire_restore(state)
        self._depth.n = 1
        self._recorder.on_acquired(self._site)

    def _is_owned(self) -> bool:
        return self._real._is_owned()


class _InstrumentedCondition:
    """A real Condition over a real (R)Lock, with enter/exit/wait
    reported to the recorder (wait releases, wake re-acquires)."""

    def __init__(
        self,
        recorder: LockOrderRecorder,
        site: str,
        lock: Any = None,
    ):
        if isinstance(lock, _InstrumentedLock):
            lock = lock._real
        self._real = _REAL_CONDITION(lock)
        self._recorder = recorder
        self._site = site

    def acquire(self, *a: Any, **kw: Any) -> bool:
        ok = self._real.acquire(*a, **kw)
        if ok:
            self._recorder.on_acquired(self._site)
        return ok

    def release(self) -> None:
        self._recorder.on_released(self._site)
        self._real.release()

    def __enter__(self) -> "_InstrumentedCondition":
        self._real.__enter__()
        self._recorder.on_acquired(self._site)
        return self

    def __exit__(self, *exc: Any) -> None:
        self._recorder.on_released(self._site)
        self._real.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._recorder.on_released(self._site)
        try:
            return self._real.wait(timeout)
        finally:
            self._recorder.on_acquired(self._site)

    def wait_for(self, predicate: Any, timeout: Optional[float] = None):
        self._recorder.on_released(self._site)
        try:
            return self._real.wait_for(predicate, timeout)
        finally:
            self._recorder.on_acquired(self._site)

    def notify(self, n: int = 1) -> None:
        self._real.notify(n)

    def notify_all(self) -> None:
        self._real.notify_all()


def _creation_site() -> tuple[str, str]:
    """(site id, origin) for the frame that called the factory; origin
    is "threading" (stdlib thread/event internals — never wrapped, they
    are bootstrap machinery and pure noise), "pkg", or "other"."""
    f = sys._getframe(2)
    fn = f.f_code.co_filename
    if fn == threading.__file__:
        return "", "threading"
    in_pkg = _PKG_DIR in os.path.abspath(fn) or (
        os.sep + _PKG_NAME + os.sep
    ) in fn
    try:
        rel = os.path.relpath(fn, _PKG_DIR)
    except ValueError:
        rel = fn
    return f"{rel}:{f.f_lineno}", "pkg" if in_pkg else "other"


@contextlib.contextmanager
def instrumented_locks(
    *, registry: Any = None, wrap_all: bool = False
) -> Iterator[LockOrderRecorder]:
    """Patch the ``threading`` lock factories package-wide for the scope.

    Yields the :class:`LockOrderRecorder`; at scope exit the factories
    are restored, telemetry is published to ``registry`` (if given), and
    a lock-order CYCLE observed at runtime raises ``AssertionError``
    (only when the body itself did not raise — a drill's own failure is
    not masked).  ``wrap_all=True`` also wraps locks created outside the
    package (synthetic unit tests).
    """
    rec = LockOrderRecorder()

    def _wrap(origin: str) -> bool:
        return origin == "pkg" or (wrap_all and origin == "other")

    def lock_factory() -> Any:
        site, origin = _creation_site()
        if not _wrap(origin):
            return _REAL_LOCK()
        return _InstrumentedLock(rec, rec.instance_site(site), _REAL_LOCK())

    def rlock_factory() -> Any:
        site, origin = _creation_site()
        if not _wrap(origin):
            return _REAL_RLOCK()
        return _InstrumentedRLock(
            rec, rec.instance_site(site), _REAL_RLOCK()
        )

    def condition_factory(lock: Any = None) -> Any:
        site, origin = _creation_site()
        if not _wrap(origin):
            return _REAL_CONDITION(lock)
        return _InstrumentedCondition(rec, rec.instance_site(site), lock)

    prev = (threading.Lock, threading.RLock, threading.Condition)
    threading.Lock = lock_factory  # type: ignore[assignment]
    threading.RLock = rlock_factory  # type: ignore[assignment]
    threading.Condition = condition_factory  # type: ignore[assignment]
    ok = False
    try:
        yield rec
        ok = True
    finally:
        threading.Lock, threading.RLock, threading.Condition = prev
        if registry is not None:
            rec.publish(registry)
    if ok:
        cycle = rec.find_cycle()
        if cycle:
            raise AssertionError(
                "lock-order-inversion (runtime): instrumented locks were "
                f"acquired in a cyclic order {' -> '.join(cycle)}; two "
                "threads interleaving these edges in opposite orders "
                f"deadlock. Edges: {rec.order_edges()}"
            )
