"""Distributed layer (SURVEY C1, C2): the TPU-native ``dist/`` equivalent.

The reference's ``dist/`` wraps NCCL/Gloo process groups and explicit
collective calls. On TPU the transport is ICI (intra-slice torus) / DCN
(cross-slice), and collectives are either compiler-inserted by GSPMD or
explicit ``lax`` primitives inside ``shard_map``. This package is the thin
façade so no user code ever touches backend specifics:

- ``initialize.py`` — process bring-up (``jax.distributed.initialize``),
  the single cross-host control point (replaces torchrun rendezvous).
- ``mesh.py``       — logical mesh construction over the physical topology,
  including hybrid ICI×DCN meshes.
- ``collectives.py``— allreduce/allgather/reduce-scatter/broadcast/barrier/
  ppermute/all_to_all wrappers usable inside jit (shard_map) and host-side.
"""

from frl_distributed_ml_scaffold_tpu.dist.initialize import (
    initialize_distributed,
    process_count,
    process_index,
)
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    MeshEnv,
    build_mesh,
    local_batch_size,
)
from frl_distributed_ml_scaffold_tpu.dist import collectives
