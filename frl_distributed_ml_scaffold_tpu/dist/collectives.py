"""Collective façade (SURVEY C2): the ``dist/`` wrapper API, TPU-native.

Two tiers, mirroring how the reference is used:

**Device tier** — inside a compiled program under ``shard_map`` over a mesh
axis. These lower to XLA collectives on ICI/DCN (the NCCL equivalents):
``all_reduce``/``all_mean`` → AllReduce, ``all_gather`` → AllGather,
``reduce_scatter`` → ReduceScatter, ``permute`` → CollectivePermute,
``all_to_all`` → AllToAll, ``broadcast`` → source-select + AllReduce.
Under plain GSPMD (no shard_map) you normally never call these — the compiler
inserts them from sharding annotations; they exist for the manual-parallelism
paths (pipeline, ring attention, MoE dispatch) and for parity with the
reference's explicit-collective API.

**Host tier** — outside jit, process-level coordination:
``host_all_gather``, ``host_broadcast``, ``barrier``.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import multihost_utils

AxisName = str | tuple[str, ...]

# ----------------------------- device tier --------------------------------


def all_reduce(x: Any, axis: AxisName) -> Any:
    """Sum-allreduce a pytree over mesh axis/axes (NCCL allreduce parity)."""
    return jax.tree.map(lambda a: lax.psum(a, axis), x)


def all_mean(x: Any, axis: AxisName) -> Any:
    """Mean-allreduce (the DDP gradient-averaging semantic)."""
    return jax.tree.map(lambda a: lax.pmean(a, axis), x)


def all_gather(x: Any, axis: AxisName, *, gather_axis: int = 0, tiled: bool = True) -> Any:
    """Gather shards along ``gather_axis`` from every member of ``axis``."""
    return jax.tree.map(
        lambda a: lax.all_gather(a, axis, axis=gather_axis, tiled=tiled), x
    )


def reduce_scatter(x: Any, axis: AxisName, *, scatter_axis: int = 0) -> Any:
    """Sum-reduce then scatter shards along ``scatter_axis``."""
    return jax.tree.map(
        lambda a: lax.psum_scatter(a, axis, scatter_dimension=scatter_axis, tiled=True),
        x,
    )


def broadcast(x: Any, axis: str, *, source: int = 0) -> Any:
    """Broadcast ``source``'s value to all members of ``axis``.

    SPMD has no asymmetric send; the idiom is mask-then-allreduce (one
    AllReduce, same cost class as NCCL broadcast on a ring).
    """
    idx = lax.axis_index(axis)

    def _bcast(a):
        masked = jnp.where(idx == source, a, jnp.zeros_like(a))
        return lax.psum(masked, axis)

    return jax.tree.map(_bcast, x)


def permute(x: Any, axis: str, perm: Sequence[tuple[int, int]]) -> Any:
    """Point-to-point shift over ``axis``: ``perm`` is (src, dst) pairs.

    The primitive under ring attention and pipeline stage hand-off.
    """
    return jax.tree.map(lambda a: lax.ppermute(a, axis, perm), x)


def ring_shift(x: Any, axis: str, *, shift: int = 1) -> Any:
    """Rotate shards around the axis ring by ``shift`` (ring-attention step)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return permute(x, axis, perm)


def all_to_all(
    x: Any, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True
) -> Any:
    """AllToAll resharding (Ulysses head<->seq exchange, MoE dispatch)."""
    return jax.tree.map(
        lambda a: lax.all_to_all(
            a, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
        ),
        x,
    )


def axis_index(axis: str):
    """This shard's coordinate along ``axis`` (reference: group rank)."""
    return lax.axis_index(axis)


def axis_size(axis: str):
    """Size of the mesh axis (reference: group world size).

    ``lax.axis_size`` only exists on newer jax; older releases statically
    fold ``psum(1, axis)`` of a Python literal to the same int — the
    classic idiom, kept as the fallback so ring/Ulysses hop counts stay
    compile-time constants on both.
    """
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


# ------------------------------ host tier ---------------------------------


def host_all_gather(x: Any) -> Any:
    """Gather per-process values to every process (outside jit)."""
    return multihost_utils.process_allgather(x)


def host_broadcast(x: Any, *, is_source: bool | None = None) -> Any:
    """Broadcast process 0's pytree to all processes (outside jit)."""
    if is_source is None:
        is_source = jax.process_index() == 0
    return multihost_utils.broadcast_one_to_all(x, is_source=is_source)


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (reference: dist.barrier)."""
    multihost_utils.sync_global_devices(name)
