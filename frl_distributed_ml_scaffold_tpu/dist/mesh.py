"""Logical device mesh over the physical TPU topology (SURVEY C2, §5).

The reference maps ranks onto NCCL communicators; TPU-native, parallelism is
one ``jax.sharding.Mesh`` whose axes are the parallelism dimensions
(data/fsdp/model/seq/expert/pipe — see MeshConfig). Axis placement determines
which transport the collectives ride: intra-slice axes use ICI (the 2D/3D
torus), and when ``dcn_data > 1`` the data axis spans DCN via a hybrid mesh —
laid out so gradient allreduce crosses DCN once while everything else stays
on ICI.

Batch semantics: FSDP *is* data parallelism with parameters sharded, so the
global batch dimension shards over ``("data", "fsdp")`` jointly; ``seq``
additionally shards the sequence dimension for long-context runs.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig

# Canonical axis order. Collective-heaviest axes go LAST so
# mesh_utils places them on the fastest (innermost) physical links:
# model/seq/expert collectives fire per-layer, data/fsdp once per step.
AXES: tuple[str, ...] = ("pipe", "data", "fsdp", "seq", "expert", "model")

# Axes that jointly shard the global batch dimension.
BATCH_AXES: tuple[str, ...] = ("data", "fsdp")


@dataclass(frozen=True)
class MeshEnv:
    """A resolved mesh + its config; the object the trainer passes around."""

    mesh: Mesh
    config: MeshConfig

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    def axis_size(self, name: str) -> int:
        return self.mesh.shape[name]

    @property
    def batch_axis_size(self) -> int:
        return self.axis_size("data") * self.axis_size("fsdp")

    def batch_spec(self, *trailing) -> P:
        """PartitionSpec for a batch-leading array: ``P(("data","fsdp"), ...)``."""
        return P(BATCH_AXES, *trailing)

    def batch_sharding(self, *trailing) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(*trailing))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)


def resolve_axis_sizes(cfg: MeshConfig, n_devices: int) -> dict[str, int]:
    """Fill the ``-1`` wildcard axis and validate the product."""
    sizes = cfg.axis_sizes()
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {fixed}"
            )
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(
            f"mesh {sizes} needs {total} devices but {n_devices} are available"
        )
    return sizes


def enable_sharding_invariant_rng() -> None:
    """Make jax.random streams independent of sharding/mesh layout.

    jax's legacy (non-partitionable) threefry lowers RNG in a way that can
    produce DIFFERENT values for the same key depending on how the output
    is sharded — measured in this container: ``jit(init,
    out_shardings=...)`` of the same seed gives different kernels on a
    data=2 x fsdp=4 mesh than on one device (while fsdp=8 happens to
    match), which silently breaks every cross-mesh equivalence guarantee
    this repo makes (tests AND real reshard-resume workflows).
    ``jax_threefry_partitionable=True`` is the upstream fix: counter-based
    bit generation, identical values under any sharding, and faster under
    SPMD. Called from ``build_mesh`` so every entry point (trainer, bench,
    tools, tests) agrees; escape hatch for bit-exact continuity of runs
    seeded under the legacy impl: FRL_TPU_LEGACY_RNG=1."""
    import os

    if os.environ.get("FRL_TPU_LEGACY_RNG"):
        return
    try:
        jax.config.update("jax_threefry_partitionable", True)
    except Exception as e:  # a jax without the flag already behaves this way
        import logging

        logging.getLogger(__name__).debug(
            "jax_threefry_partitionable unavailable (%s); this jax "
            "already defaults to the partitionable impl", e,
        )


def build_mesh(cfg: MeshConfig, devices=None) -> MeshEnv:
    """Construct the mesh with topology-aware device ordering.

    ``mesh_utils.create_device_mesh`` permutes devices so that mesh-adjacent
    devices are ICI-adjacent; ``create_hybrid_device_mesh`` additionally
    keeps DCN-crossing axes outermost for multi-slice (``dcn_data > 1``).
    """
    enable_sharding_invariant_rng()
    devices = list(jax.devices()) if devices is None else list(devices)
    sizes = resolve_axis_sizes(cfg, len(devices))
    shape = tuple(sizes[a] for a in AXES)

    if cfg.dcn_data > 1:
        if sizes["data"] % cfg.dcn_data != 0:
            raise ValueError(
                f"data axis {sizes['data']} not divisible by dcn_data={cfg.dcn_data}"
            )
        ici_shape = tuple(
            sizes[a] // cfg.dcn_data if a == "data" else sizes[a] for a in AXES
        )
        dcn_shape = tuple(cfg.dcn_data if a == "data" else 1 for a in AXES)
        # Routing: CPU simulation (incl. multi-process CPU, whose devices
        # carry a nominal slice 0) takes the manual layout below. On real
        # accelerators the slice metadata must MATCH the config — a
        # dcn_data that disagrees with the physical slice count is an
        # actionable misconfiguration and must raise, not silently degrade
        # to a hand-rolled layout that would straddle DCN.
        is_sim = all(getattr(d, "platform", None) == "cpu" for d in devices)
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        real_slices = {s for s in slice_ids if s is not None}
        if not is_sim and real_slices and len(real_slices) != cfg.dcn_data:
            raise ValueError(
                f"mesh.dcn_data={cfg.dcn_data} but the device topology "
                f"reports {len(real_slices)} slice(s)"
            )
        if not is_sim and len(real_slices) > 1:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices
            )
        else:
            # Lay the mesh out by hand with the SAME semantics — the dcn
            # factor is the OUTER component of the data axis, so consecutive
            # device groups form the "slices" and only the data-axis
            # allreduce crosses the slice boundary.
            _warn_layout_fallback("hybrid ICI x DCN", ici_shape, dcn_shape)
            arr = np.asarray(devices).reshape((cfg.dcn_data,) + ici_shape)
            # [dcn, pipe, data_ici, ...] -> [pipe, dcn, data_ici, ...]
            arr = np.moveaxis(arr, 0, 1)
            dev_array = arr.reshape(shape)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, AssertionError, NotImplementedError):
            # CPU-sim and odd topologies: plain row-major placement.
            _warn_layout_fallback("topology-aware", shape, None)
            dev_array = np.asarray(devices).reshape(shape)

    return MeshEnv(mesh=Mesh(dev_array, AXES), config=cfg)


def _warn_layout_fallback(kind: str, shape, dcn_shape) -> None:
    """Topology-aware placement silently degrading to naive device order is
    harmless in CPU simulation but costs real ICI bandwidth on hardware —
    make it observable (VERDICT r1 weak #6)."""
    from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

    extra = f" x DCN {dcn_shape}" if dcn_shape else ""
    get_logger().warning(
        "build_mesh: %s device placement unavailable for shape %s%s; using "
        "row-major order (fine in simulation; on multi-chip hardware "
        "mesh-adjacent devices may not be ICI-adjacent)",
        kind,
        shape,
        extra,
    )


# ---------------------------------------------------------------------------
# Current-mesh context: manual-collective ops (ring attention, Ulysses,
# pipeline) embed shard_map regions inside the GSPMD-jitted step and need the
# concrete Mesh at trace time. The Trainer sets this once at construction.
# ---------------------------------------------------------------------------

_CURRENT_ENV: MeshEnv | None = None


def set_current_mesh(env: MeshEnv | None) -> None:
    global _CURRENT_ENV
    _CURRENT_ENV = env


def current_mesh_env() -> MeshEnv | None:
    return _CURRENT_ENV


class mesh_context:
    """Scoped mesh context: ``with mesh_context(env): ...``.

    jit tracing is lazy, so the context must be live at *call* time of any
    function whose trace embeds shard_map regions — the Trainer wraps each
    compiled-step invocation, which keeps two coexisting Trainers with
    different meshes from poisoning each other's traces.
    """

    def __init__(self, env: MeshEnv | None):
        self.env = env
        self._prev: MeshEnv | None = None

    def __enter__(self):
        self._prev = current_mesh_env()
        set_current_mesh(self.env)
        return self.env

    def __exit__(self, *exc):
        set_current_mesh(self._prev)
        return False


def shard_map_compat(fn, *, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across the jax API move: new jax exposes
    ``jax.shard_map(..., check_vma=)``, older releases only
    ``jax.experimental.shard_map.shard_map(..., check_rep=)``. Every
    manual-collective op routes through here so the repo runs on both.
    Replication checking is disabled either way: callers' out_specs declare
    intent (psum'd outputs are replicated by construction)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def local_batch_size(global_batch_size: int, env: MeshEnv | None = None) -> int:
    """Per-host batch share (reference: per-rank batch). Validates evenness."""
    n_proc = jax.process_count()
    if global_batch_size % n_proc != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {n_proc} processes"
        )
    if env is not None and global_batch_size % env.batch_axis_size != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"batch mesh axes ({env.batch_axis_size})"
        )
    return global_batch_size // n_proc
