"""Process bring-up (SURVEY C1, call stack (a)).

Reference behavior: torchrun spawns N workers per node and each calls
``dist.init_process_group("nccl")`` with a TCP rendezvous. TPU-native: JAX is
multi-controller SPMD — ONE process per host, each owning its local chips;
``jax.distributed.initialize`` is the only cross-host control point. On a
single host (or under test) initialization is a no-op.

Environment contract (mirrors torchrun's env:// rendezvous, TPU-flavored):
``FRL_TPU_COORDINATOR`` (host:port), ``FRL_TPU_NUM_PROCESSES``,
``FRL_TPU_PROCESS_ID`` — all optional; on Cloud TPU pod slices JAX
auto-detects all three from the metadata server.
"""

from __future__ import annotations

import os

import jax

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up multi-host JAX if configured; safe to call unconditionally.

    Resolution order: explicit args > FRL_TPU_* env vars > JAX autodetection
    (Cloud TPU metadata). Single-process runs skip initialization entirely.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("FRL_TPU_COORDINATOR")
    if num_processes is None and "FRL_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["FRL_TPU_NUM_PROCESSES"])
    if process_id is None and "FRL_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["FRL_TPU_PROCESS_ID"])

    if num_processes == 1:
        # Explicit single-process topology (e.g. the elastic supervisor
        # shrinking to the last survivor): nothing to initialize, even when
        # a stale FRL_TPU_COORDINATOR is still in the environment.
        return
    if num_processes is not None and num_processes > 1:
        _enable_cpu_collectives()
        # Bounded rendezvous: when a peer host is gone for good, the default
        # 300 s initialization timeout is what the elastic supervisor's
        # shrink policy (launcher/elastic.py) waits on — let deployments
        # (and the shrink tests) tighten it.
        timeout_s = int(os.environ.get("FRL_TPU_INIT_TIMEOUT_S", "300"))
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=timeout_s,
        )
        _INITIALIZED = True
    elif coordinator_address is not None:
        # Pod-slice autodetect path: let JAX fill in counts from the platform.
        jax.distributed.initialize(coordinator_address=coordinator_address)
        _INITIALIZED = True
    # else: single process — nothing to initialize.


def _enable_cpu_collectives() -> None:
    """Multi-process compiled collectives on the CPU backend need an
    explicit cross-process implementation (jax's default is 'none', which
    raises "Multiprocess computations aren't implemented on the CPU
    backend" at the first psum). Select gloo BEFORE the backend
    initializes — this is what makes the 2-process CPU-sim tests
    (test_multiprocess / test_elastic_multiprocess) real collectives
    rather than a capability of some boxes and not others. Set
    unconditionally for multi-process topologies: it only configures the
    CPU backend's cross-process transport, so on TPU pods it is inert
    (platform sniffing here is a trap — probing the backend would
    initialize it prematurely, and the config flags differ across jax
    releases). No-op on jax builds without the knob."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # older/newer jax without the option: leave default
        import logging

        logging.getLogger(__name__).debug(
            "jax_cpu_collectives_implementation unavailable (%s); "
            "keeping the backend default", e,
        )


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def shutdown_distributed() -> None:
    global _INITIALIZED
    if _INITIALIZED:
        jax.distributed.shutdown()
        _INITIALIZED = False
