"""Latency-hiding tensor parallelism: the collective-matmul schedule.

The plain TP path (``mesh.model > 1`` + ``gpt_tp_rules``/``vit_tp_rules``)
leaves the per-layer ``model``-axis collectives to GSPMD: one monolithic
allreduce after each row-parallel matmul (attn-out, fc_out), serialized
against the matmuls on every layer's critical path. Following "Scalable
Training of Language Models using JAX pjit and TPUv4" (PAPERS.md), this
module decomposes each TP matmul into per-shard blocks chained by
``ppermute`` (ops/collective_matmul.py) so each block's communication
hides under the previous block's compute:

- the residual stream between sublayers lives *sharded over the model
  axis* (sequence-sharded for the GPT stack — Megatron sequence
  parallelism — and batch-sharded for ViT/video, whose token count is not
  divisible by the axis);
- the column-parallel projections (QKV / fc_in) consume it through a
  bidirectional all-gather-matmul ring — the gather streams in while the
  resident chunk multiplies — with the QKV trio sharing ONE ring (the
  first projection returns the assembled gather for its two siblings);
- the row-parallel projections (attn-out / fc_out) produce it through the
  transpose ring, matmul-reduce-scatter, whose rotating partial-sum
  accumulators replace the exposed allreduce.

Wiring is the ``fsdp_overlap`` hook pattern: the Trainer clones the model
with ``tp_overlap=TpHooks(...)`` for the loss path only (init/decode stay
on the plain model — the params tree is identical either way), and the
hooks ride flax's injectable ``dot_general`` so ``nn.Dense`` /
``nn.MultiHeadDotProductAttention`` param creation is untouched.

Correctness is sim-gated in tests/test_tp_overlap.py (numerics vs the
GSPMD TP path across mesh compositions, grad accumulation, remat modes;
jaxpr pins on the blockwise ppermute chains); the on-chip step-time A/B
rides ``tools/perf_sweep.py gpt2_tp_overlap`` (BACKLOG R7).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

from jax import lax
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    BATCH_AXES,
    current_mesh_env,
    shard_map_compat,
)
from frl_distributed_ml_scaffold_tpu.ops.collective_matmul import (
    all_gather_matmul,
    matmul_reduce_scatter,
)

#: Model families with collective-matmul dot_general hooks wired up.
SUPPORTED_FAMILIES = ("gpt", "vit", "video")


def _canonicalize(x, w, dimension_numbers):
    """Fold a flax Dense/DenseGeneral contraction into the canonical
    ``[batch, chunkable, K] x [K, M]`` matmul the ring ops speak.

    Returns ``(x2, w2, restore)`` where ``restore(y2)`` unfolds the result
    features back to the caller's layout, or ``None`` if the contraction
    is not the trailing-dims-of-x against leading-dims-of-w pattern every
    hooked projection uses (callers then fall back to ``lax.dot_general``).
    """
    (lhs_c, rhs_c), (lhs_b, rhs_b) = dimension_numbers
    nc = len(lhs_c)
    if (
        lhs_b
        or rhs_b
        or tuple(lhs_c) != tuple(range(x.ndim - nc, x.ndim))
        or tuple(rhs_c) != tuple(range(nc))
        or x.ndim - nc != 2  # [batch, tokens, features...]
    ):
        return None
    k = math.prod(x.shape[x.ndim - nc :])
    feats = w.shape[nc:]
    x2 = x.reshape(x.shape[: x.ndim - nc] + (k,))
    w2 = w.reshape((k, math.prod(feats) if feats else 1))

    def restore(y2):
        return y2.reshape(y2.shape[:-1] + feats)

    return x2, w2, restore


@dataclass(frozen=True)
class TpHooks:
    """Collective-matmul schedule for one model family.

    ``chunk_axis`` — which activation dim the residual stream shards over
    the model axis: 1 (tokens) for the GPT scan stack, 0 (batch) for
    ViT/video (197 tokens is prime; the batch dim divides instead).

    ``lowp`` — the low-precision fast path (``parallel.low_precision``):
    when set ("int8" | "fp8_e4m3" | "fp8_e5m2"), the rings ppermute
    quantized chunks + scales and the four hooked matmuls run as scaled
    low-precision matmuls with straight-through grads
    (ops/collective_matmul.py module docstring; ops/quantization.py).
    """

    axis: str = "model"
    chunk_axis: int = 1
    lowp: str | None = None

    # ------------------------------------------------------------- specs

    def stream_spec(self) -> P:
        """Logical spec of the sharded residual stream ([B, T, D])."""
        if self.chunk_axis == 1:
            return P(BATCH_AXES, self.axis, None)
        return P((*BATCH_AXES, self.axis), None, None)

    def _gathered_spec(self) -> P:
        return P(BATCH_AXES, None, None)

    def _split_spec(self) -> P:
        """Feature-split activation ([B, T, M_local])."""
        return P(BATCH_AXES, None, self.axis)

    # ------------------------------------------------------------ helpers

    def _env(self):
        env = current_mesh_env()
        if env is None or env.axis_size(self.axis) <= 1:
            return None
        return env

    def constrain_stream(self, x):
        """Pin the residual stream to its sharded layout between the
        collective matmuls (the adds/LayerNorms in between are per-token,
        so GSPMD keeps them local once anchored)."""
        env = self._env()
        if env is None or x.ndim != 3:
            return x
        return lax.with_sharding_constraint(x, env.sharding(self.stream_spec()))

    def _check_chunkable(self, x2, n: int) -> bool:
        dim = x2.shape[self.chunk_axis]
        if self.chunk_axis == 0:
            # The batch dim also carries the data/fsdp sharding; the ring
            # chunks what remains per batch shard.
            env = current_mesh_env()
            per = math.prod(env.axis_size(a) for a in BATCH_AXES)
            return dim % (per * n) == 0
        return dim % n == 0

    # ----------------------------------------------------- dot_general API

    def ag_dot_general(self, x, w, dimension_numbers, precision=None, **kw):
        """Column-parallel projection: bidirectional all-gather-matmul."""
        env = self._env()
        canon = _canonicalize(x, w, dimension_numbers) if env else None
        if canon is None or not self._check_chunkable(
            canon[0], env.axis_size(self.axis)
        ):
            return lax.dot_general(
                x, w, dimension_numbers, precision=precision
            )
        x2, w2, restore = canon
        inner = partial(
            all_gather_matmul,
            axis_name=self.axis,
            chunk_axis=self.chunk_axis,
            return_full=False,
            precision=precision,
            lowp=self.lowp,
        )
        y2 = shard_map_compat(
            inner,
            mesh=env.mesh,
            in_specs=(self.stream_spec(), P(None, self.axis)),
            out_specs=self._split_spec(),
        )(x2, w2)
        return restore(y2)

    def mrs_dot_general(self, x, w, dimension_numbers, precision=None, **kw):
        """Row-parallel projection: bidirectional matmul-reduce-scatter."""
        env = self._env()
        canon = _canonicalize(x, w, dimension_numbers) if env else None
        if canon is None:
            return lax.dot_general(
                x, w, dimension_numbers, precision=precision
            )
        x2, w2, restore = canon
        n = env.axis_size(self.axis)
        # The OUTPUT is what gets chunk-sharded here; its chunkable dim is
        # x2's (they share batch/token dims).
        if not self._check_chunkable(x2, n):
            return lax.dot_general(
                x, w, dimension_numbers, precision=precision
            )
        inner = partial(
            matmul_reduce_scatter,
            axis_name=self.axis,
            chunk_axis=self.chunk_axis,
            precision=precision,
            lowp=self.lowp,
        )
        z2 = shard_map_compat(
            inner,
            mesh=env.mesh,
            in_specs=(self._split_spec(), P(self.axis, None)),
            out_specs=self.stream_spec(),
        )(x2, w2)
        return restore(z2)

    def qkv_context(self) -> "_QkvContext":
        """Shared-ring context for a fused QKV (or any multi-consumer)
        projection trio: the first projection runs the gather ring and
        keeps the assembled copy; siblings on the SAME input reuse it with
        a plain local matmul — one ring, not three."""
        return _QkvContext(self)


class _QkvContext:
    """Stateful dot_general shared by the q/k/v projections of one
    attention call (state lives only for that trace)."""

    def __init__(self, hooks: TpHooks):
        self._hooks = hooks
        self._x_ref = None  # strong ref: keeps id() comparisons sound
        self._full = None

    def dot_general(self, x, w, dimension_numbers, precision=None, **kw):
        hooks = self._hooks
        env = hooks._env()
        canon = _canonicalize(x, w, dimension_numbers) if env else None
        if canon is None or not hooks._check_chunkable(
            canon[0], env.axis_size(hooks.axis)
        ):
            return lax.dot_general(
                x, w, dimension_numbers, precision=precision
            )
        x2, w2, restore = canon
        if self._x_ref is x:
            # Sibling projection of the same input: the gathered copy from
            # the first ring is replicated over the model axis, the kernel
            # is column-split — a comm-free local matmul under GSPMD
            # (quantized under the low-precision fast path, so ALL of the
            # QKV trio's matmuls run low-precision, not just the ring's).
            if hooks.lowp is not None:
                from frl_distributed_ml_scaffold_tpu.ops.quantization import (
                    quantized_matmul,
                )

                return restore(quantized_matmul(self._full, w2, hooks.lowp))
            y2 = lax.dot_general(
                self._full,
                w2,
                (((self._full.ndim - 1,), (0,)), ((), ())),
                precision=precision,
            )
            return restore(y2)
        inner = partial(
            all_gather_matmul,
            axis_name=hooks.axis,
            chunk_axis=hooks.chunk_axis,
            return_full=True,
            precision=precision,
            lowp=hooks.lowp,
        )
        y2, full = shard_map_compat(
            inner,
            mesh=env.mesh,
            in_specs=(hooks.stream_spec(), P(None, hooks.axis)),
            out_specs=(hooks._split_spec(), hooks._gathered_spec()),
        )(x2, w2)
        self._x_ref = x
        self._full = full
        return restore(y2)


# ------------------------------------------------------------- validation


def validate_ring_schedule(cfg, *, lowp: str | None = None) -> None:
    """Fail fast on configs the collective-matmul schedule cannot honor
    (a silent fallback to the GSPMD TP schedule would invalidate any A/B
    built on it) — the fsdp_overlap validation contract. Called by the
    schedule layer (parallel/schedule.py ``validate_schedule_config``)
    for every ``granularity="ring_chunk"`` gather; the legacy knob path
    reaches it through ``validate_tp_overlap_config``."""
    family = getattr(cfg.model, "family", None)
    if family not in SUPPORTED_FAMILIES:
        raise ValueError(
            f"parallel.tp_overlap=true: model family {family!r} has no "
            f"collective-matmul hooks (supported: {SUPPORTED_FAMILIES})"
        )
    if (
        getattr(cfg.model, "pipeline_stages", 1) > 1
        and getattr(cfg.model, "pipeline_impl", "spmd") != "mpmd"
    ):
        # The SPMD stage-vmap path owns its own block schedule; the MPMD
        # backend (ISSUE 14) builds the rings INSIDE each per-stage
        # program — no stage vmap to collide with.
        raise ValueError(
            "parallel.tp_overlap composes with data/fsdp/model meshes but "
            "not with the SPMD pipeline backend (the stage-vmap path owns "
            "its own block schedule); set model.pipeline_stages=1 or "
            "model.pipeline_impl='mpmd'"
        )
    if cfg.parallel.sequence != "none" or cfg.mesh.seq > 1:
        raise ValueError(
            "parallel.tp_overlap owns the token dim's model-axis sharding; "
            "it does not compose with sequence parallelism "
            "(parallel.sequence, mesh.seq)"
        )
    if getattr(cfg.model, "attention", "dense") not in ("dense", "flash"):
        raise ValueError(
            "parallel.tp_overlap requires attention='dense'|'flash' "
            f"(got {cfg.model.attention!r}: ring/ulysses reshard the token "
            "dim themselves)"
        )
    moe = getattr(cfg.model, "moe", None)
    if moe is not None and moe.num_experts > 0:
        raise ValueError(
            "parallel.tp_overlap: the MoE MLP has no collective-matmul "
            "hooks (its dispatch owns the token exchange); set "
            "model.moe.num_experts=0"
        )
    if lowp is not None:
        from frl_distributed_ml_scaffold_tpu.ops.quantization import (
            lowp_dtype,
        )

        lowp_dtype(lowp)  # KeyError (with the vocabulary) on typos


def validate_tp_overlap_config(cfg) -> None:
    """Legacy-knob adapter: validate ``parallel.tp_overlap=true`` by
    deriving its schedule declaration and running the schedule layer's
    checks (the ``low_precision`` knob becomes the ring pair's ``lowp``
    transfer attribute)."""
    from frl_distributed_ml_scaffold_tpu.ops.quantization import resolve_lowp
    from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
        OverlapSchedule,
        gather,
        scatter,
        validate_schedule_config,
    )

    lowp = resolve_lowp(getattr(cfg.parallel, "low_precision", "none"))
    sched = OverlapSchedule.build(
        gather("model", granularity="ring_chunk", lowp=lowp),
        scatter("model", lowp=lowp),
    )
    validate_schedule_config(sched, cfg)


def make_tp_hooks(cfg, env) -> TpHooks:
    """Build the hooks for a resolved mesh, validating what only the mesh
    knows (axis size, chunk divisibility). ``lowp`` comes from the
    config's RESOLVED schedule declaration (parallel/schedule.py) — low
    precision is a transfer attribute of the declared ring, whether the
    ring was requested via the legacy ``tp_overlap``/``low_precision``
    knobs or an explicit ``parallel.schedule`` string."""
    from frl_distributed_ml_scaffold_tpu.ops.quantization import resolve_lowp
    from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
        schedule_from_config,
    )

    sched = schedule_from_config(cfg)
    ring = sched.ring_gather() if sched is not None else None
    lowp = (
        ring.lowp if ring is not None
        else resolve_lowp(getattr(cfg.parallel, "low_precision", "none"))
    )
    validate_ring_schedule(cfg, lowp=lowp)
    m = env.axis_size("model")
    if m <= 1:
        raise ValueError(
            "parallel.tp_overlap=true requires mesh.model > 1 (the "
            f"resolved model axis is {m}); there is no TP communication "
            "to hide on this mesh"
        )
    family = cfg.model.family
    # The shard_map in_specs split the Megatron feature dims exactly
    # (P(None, "model") / P("model", None)): indivisible widths must fail
    # HERE, not as an obscure shard_map trace error — GSPMD pads uneven
    # shards, the explicit rings do not.
    d = cfg.model.hidden_dim
    if d % m != 0 or (d * cfg.model.mlp_ratio) % m != 0:
        raise ValueError(
            f"parallel.tp_overlap: model.hidden_dim={d} (and mlp width "
            f"{d * cfg.model.mlp_ratio}) must divide by mesh.model={m} — "
            "the collective-matmul rings split the Megatron feature dims "
            "exactly, without GSPMD's padding"
        )
    # num_heads need NOT divide by m: the attention segment between the
    # rings stays GSPMD-owned (head-split F is just a feature dim to it,
    # and it pads/reshards as it always did — equivalence is gated at
    # heads=4, model=8 in tests/test_tp_overlap.py).
    if family == "gpt":
        if cfg.model.seq_len % m != 0:
            raise ValueError(
                f"parallel.tp_overlap: model.seq_len={cfg.model.seq_len} "
                f"must divide by mesh.model={m} (the residual stream is "
                "sequence-sharded over the model axis)"
            )
        return TpHooks(axis="model", chunk_axis=1, lowp=lowp)
    # vit/video: the token count (1 + patches) is generally not divisible;
    # the batch dim carries the chunking instead.
    per_shard = (
        env.axis_size("data") * env.axis_size("fsdp") * m * cfg.trainer.grad_accum
    )
    if cfg.data.global_batch_size % per_shard != 0:
        raise ValueError(
            "parallel.tp_overlap: "
            f"data.global_batch_size={cfg.data.global_batch_size} must "
            f"divide by data*fsdp*model*grad_accum={per_shard} (the "
            f"{family} residual stream is batch-sharded over the model axis)"
        )
    return TpHooks(axis="model", chunk_axis=0, lowp=lowp)
