"""Parallelism layer (SURVEY C4–C9): strategies as sharding annotations.

The reference implements DP/FSDP/ZeRO as *wrapper modules* (DDP, FSDP) and
process-group plumbing. TPU-native, every strategy is a PartitionSpec
assignment over one mesh:

- DP      — params ``P()``, batch over ``("data","fsdp")``; GSPMD inserts the
            gradient allreduce DDP's hooks did.
- FSDP    — params sharded over ``fsdp`` (largest divisible dim); XLA
            all-gathers params per layer and reduce-scatters grads — the
            SimpleFSDP formulation (PAPERS.md).
- ZeRO-1  — params replicated, optimizer state sharded over ``fsdp``.
- TP      — Megatron column/row rules on attention/MLP weights (``model``).
- SP      — ring attention / Ulysses over ``seq`` (ops/ring_attention.py).
- EP      — MoE expert sharding over ``expert`` (models/moe.py).
- PP      — stage assignment over ``pipe`` (parallel/pipeline.py).
"""

from frl_distributed_ml_scaffold_tpu.parallel.partition import (
    PartitionRules,
    block_param_slice_shapes,
    fsdp_spec_for,
    opt_state_specs,
    param_specs,
    shardings_from_specs,
)
from frl_distributed_ml_scaffold_tpu.parallel.pipeline import SpmdPipeline
from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
    OverlapSchedule,
    ScheduleError,
    gather,
    parse_schedule,
    scatter,
    schedule_from_config,
)
