"""MPMD pipeline parallelism: per-stage programs + host-side 1F1B driver
(ISSUE 14; ROADMAP item 3 — the arXiv 2412.14374 formulation).

The SPMD backend (parallel/pipeline.py) keeps the whole pipeline timeline
inside ONE compiled program: stage weights carry a leading ``[S, ...]``
vmap dim, a rolling ``jnp.roll`` buffer is the stage-to-stage send, and
the GPipe scan holds all ``M`` microbatch activations live. This module
is the other shape the paper argues for — **multiple programs, multiple
data**:

- **Per-stage programs.** Each pipeline stage is its own jitted program
  (``models/gpt.py GptStage``) on its own ``pipe``-slice SUBMESH, with
  stage-local params (plain ``[L/S, ...]`` block slices — no vmap dim)
  and stage-local optimizer shards. FSDP/ZeRO/TP partitioning applies
  per stage over the submesh's data/fsdp/model/seq axes, and the PR 13
  overlap-schedule declarations lower per stage program (blockwise fsdp
  gathers + collective-matmul rings inside a stage compose unchanged).
- **Explicit transfers.** Inter-stage activation/gradient handoffs are
  explicit ``jax.device_put`` calls between stage submeshes (the splice/
  transfer discipline PR 12 established at the serving handoff, applied
  to the training boundary). Nothing crosses stages inside a compiled
  program — graft-lint pins every stage program free of ``pipe``-axis
  collectives (``pipeline:stage_program``).
- **1F1B schedule.** A host-side driver runs the classic
  warmup/steady/cooldown order: stage ``j`` issues ``min(S-1-j, M)``
  warmup forwards, then alternates one-forward-one-backward, then drains.
  Steady state therefore holds only ``min(S, M)`` in-flight microbatch
  boundary activations (stage 0's warmup depth) instead of GPipe's ``M``
  — the analytic model below (``peak_live_activations``) is pinned
  against the driver's measured counters in tests. The backward
  recomputes each stage forward from its saved BOUNDARY input (the
  memory profile 1F1B exists for); ``trainer.remat`` composes by
  checkpointing the recompute's own residuals.

Because per-stage programs never vmap over a stage dim, the
``vmap(spmd_axis_name="pipe")`` x sequence-parallel shard_map lowering
bug (BACKLOG R8-2) cannot occur: ring/ulysses attention open their
shard_map regions directly inside a stage program. And because each
stage is already a self-contained program with explicit boundary
transfers, stages can move to separate slices (DCN between them) without
changing shape — the training-side analogue of PR 12's worker split.

Selection: ``model.pipeline_impl="mpmd"`` behind the existing knobs
(``pipeline_stages``/``pipeline_microbatches`` keep their meaning;
``effective_microbatches`` stays the single resolution rule). Grad
accumulation folds into the same 1F1B run as extra microbatches — the
two knobs both just microbatch the global batch here.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    AXES,
    BATCH_AXES,
    MeshEnv,
    mesh_context,
)
from frl_distributed_ml_scaffold_tpu.parallel.partition import (
    opt_state_specs,
    param_specs,
    shardings_from_specs,
)
from frl_distributed_ml_scaffold_tpu.parallel.pipeline import (
    circular_repeat,
    effective_microbatches,
)
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState

#: Schedules the analytic model knows. "gpipe" is the SPMD backend's
#: all-forwards-then-all-backwards timeline; "1f1b" is this module's.
SCHEDULES = ("gpipe", "1f1b")

#: Donation seam for the stage update programs (params/opt-state/EMA are
#: donated so stepping a stage never holds two copies of its state). The
#: graft-lint mutation gate flips this to prove the donation audit bites.
_DONATE_STAGE_STATE = True

#: Donation seam for the per-microbatch transient buffers (saved boundary
#: inputs, incoming cotangents, grad accumulators). Default OFF: with it
#: on, this container's CPU jaxlib produced RARE nondeterministic grad
#: corruption (~1e-3 param drift between identical runs) when two MPMD
#: runners interleaved dispatch on overlapping submeshes — the same
#: jaxlib that miscompiles vmap(spmd_axis_name) x shard_map (BACKLOG
#: R8-2), and XLA reported most of these donations "not usable" anyway
#: (grad layouts rarely alias through the vjp). Transient donation is an
#: in-place-reuse optimization, NOT the 1F1B memory model: saved
#: boundary activations are freed when their backward pops them either
#: way. Revisit on TPU with an on-chip soak before flipping.
_DONATE_TRANSIENTS = False


def bubble_fraction(schedule: str, num_stages: int, num_microbatches: int) -> float:
    """Idle fraction of the pipeline timeline: ``(S-1)/(M+S-1)``.

    Fill/drain costs ``S-1`` microbatch-slots at both ends of the
    timeline whichever way the middle is ordered, so GPipe and 1F1B share
    the bubble FRACTION (at equal per-microbatch fwd+bwd cost); 1F1B's
    win is peak activation MEMORY (``peak_live_activations`` — S vs M),
    which is what unlocks large ``M`` and therefore small bubbles.
    """
    if schedule not in SCHEDULES:
        raise KeyError(f"unknown pipeline schedule {schedule!r} ({SCHEDULES})")
    s, m = int(num_stages), int(num_microbatches)
    if s <= 1 or m < 1:
        return 0.0
    return (s - 1) / (m + s - 1)


def peak_live_activations(
    schedule: str, num_stages: int, num_microbatches: int
) -> int:
    """Max in-flight forward boundary activations any stage holds.

    - ``gpipe``: every microbatch's activations stay live until the
      backward sweep starts → ``M``.
    - ``1f1b``: stage ``j`` warms up ``min(S-1-j, M)`` forwards and then
      retires one activation per new forward → ``min(S-j, M)``; the max
      (stage 0) is ``min(S, M)`` — ``< M`` whenever ``M > S``.
    """
    if schedule not in SCHEDULES:
        raise KeyError(f"unknown pipeline schedule {schedule!r} ({SCHEDULES})")
    s, m = int(num_stages), int(num_microbatches)
    if s <= 1:
        return 1
    if schedule == "gpipe":
        return max(m, 1)
    return max(min(s, m), 1)


def stage_peak_live(stage: int, num_stages: int, num_microbatches: int) -> int:
    """1F1B per-stage peak in-flight activations: ``min(S - j, M)``."""
    return max(min(num_stages - stage, num_microbatches), 1)


def stage_submesh(env: MeshEnv, stage: int) -> MeshEnv:
    """Stage ``stage``'s submesh: the full mesh's ``pipe`` axis sliced to
    one coordinate (kept at size 1 so every PartitionSpec that names
    ``pipe`` stays valid), all other axes intact — the device set one
    per-stage program runs on."""
    ax = AXES.index("pipe")
    devs = np.take(env.mesh.devices, [stage], axis=ax)
    return MeshEnv(
        mesh=Mesh(devs, AXES),
        config=dataclasses.replace(env.config, pipe=1),
    )


def _stage_forward(module, policy, params_c, x, rng, train: bool):
    """Apply one stage program body on compute-cast params — the single
    seam every fwd/bwd/loss program routes through (and the one the
    graft-lint cross-stage-collective mutation gate patches)."""
    del policy  # reserved for future per-stage policy overrides
    rngs = {"dropout": rng} if train else None
    return module.apply({"params": params_c}, x, train=train, rngs=rngs)


class MpmdPipelineRunner:
    """Builds the per-stage programs for one ExperimentConfig and drives
    them: ``train_step``/``eval_step`` are drop-in replacements for the
    Trainer's compiled steps (same ``(state, batch)`` contract), with the
    TrainState's ``params``/``opt_state``/``ema_params`` holding
    ``{"stage_j": ...}`` trees whose leaves live on stage ``j``'s
    submesh."""

    def __init__(self, cfg, env: MeshEnv, policy):
        self.cfg = cfg
        self.env = env
        self.policy = policy
        model_cfg = cfg.model
        if getattr(model_cfg, "family", None) != "gpt":
            raise ValueError(
                "model.pipeline_impl='mpmd': per-stage programs are wired "
                f"for the GPT stack (family {model_cfg.family!r}); use "
                "pipeline_impl='spmd'"
            )
        if cfg.data.name not in ("lm", "lm_synthetic"):
            raise ValueError(
                "model.pipeline_impl='mpmd' implements the LM task "
                f"(data.name {cfg.data.name!r})"
            )
        if model_cfg.moe.num_experts > 0:
            raise ValueError(
                "model.pipeline_impl='mpmd' does not support MoE blocks "
                "(the router aux loss needs a cross-stage reduction the "
                "explicit-transfer boundary does not carry yet); use "
                "pipeline_impl='spmd'"
            )
        if circular_repeat(model_cfg) > 1:
            raise ValueError(
                "model.pipeline_impl='mpmd' runs the 1F1B schedule; the "
                "circular (interleaved) schedule is an SPMD-backend "
                "feature — set pipeline_circular_repeat=1 or "
                "pipeline_impl='spmd'"
            )
        if cfg.trainer.offload_opt_state:
            raise ValueError(
                "model.pipeline_impl='mpmd' does not compose with "
                "trainer.offload_opt_state (per-stage programs manage "
                "their own state residency)"
            )
        s = int(model_cfg.pipeline_stages)
        if s < 2:
            raise ValueError("pipeline_impl='mpmd' needs pipeline_stages >= 2")
        if env.axis_size("pipe") != s:
            raise ValueError(
                f"pipeline_impl='mpmd' maps one stage per pipe-mesh slice: "
                f"mesh.pipe={env.axis_size('pipe')} != "
                f"pipeline_stages={s}"
            )
        if model_cfg.num_layers % s:
            raise ValueError(
                f"{model_cfg.num_layers} layers not divisible by {s} stages"
            )
        self.num_stages = s
        self.microbatches = effective_microbatches(model_cfg)
        # Grad accumulation folds into the same 1F1B run: both knobs just
        # split the global batch into per-microbatch programs here, and
        # grads are averaged over all of them — numerically the SPMD
        # path's mean-of-chunk-means at equal sizes.
        self.total_micro = self.microbatches * cfg.trainer.grad_accum
        b = cfg.data.global_batch_size
        if b % self.total_micro:
            raise ValueError(
                f"data.global_batch_size={b} not divisible by "
                f"pipeline_microbatches x grad_accum = {self.total_micro}"
            )
        self.micro_batch = b // self.total_micro
        self.subenvs = [stage_submesh(env, j) for j in range(s)]
        if self.micro_batch % self.subenvs[0].batch_axis_size:
            raise ValueError(
                f"microbatch size {self.micro_batch} not divisible by the "
                f"stage submesh batch axes "
                f"({self.subenvs[0].batch_axis_size})"
            )
        if model_cfg.lm_loss_chunk:
            from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

            get_logger().warning(
                "pipeline_impl='mpmd' computes the LM head densely per "
                "microbatch (model.lm_loss_chunk=%d ignored; microbatch "
                "logits are already 1/M of the batch tensor)",
                model_cfg.lm_loss_chunk,
            )
        # Optimizer WITHOUT the global-norm clip element: clipping needs
        # the cross-stage norm, which the driver coordinates exactly —
        # per-stage sq-norms summed on host, grads pre-scaled by
        # clip/max(norm, clip) before a clip-less tx.update (optax's
        # clip_by_global_norm is the first chain element, so pre-scaling
        # is bit-for-bit its semantics).
        from frl_distributed_ml_scaffold_tpu.trainer.optimizers import (
            make_optimizer,
        )

        self.clip_norm = cfg.optimizer.grad_clip_norm
        self.tx, self.lr_schedule = make_optimizer(
            dataclasses.replace(cfg.optimizer, grad_clip_norm=None),
            cfg.trainer,
        )
        self.has_ema = cfg.trainer.ema_decay > 0.0
        self._layers_per_stage = model_cfg.num_layers // s

        # Telemetry (attached per fit() by the Trainer).
        self._telem = None
        self._tracer = None
        self._trace = None
        self._watchdog = None
        self._g_idle = None
        self._g_bubble = None
        self._c_transfer = None
        #: Driver instrumentation from the last train_step: per-stage peak
        #: in-flight boundary activations (the 1F1B memory pin reads
        #: this) and explicit boundary-transfer bytes.
        self.last_peak_live: list[int] = [0] * s
        self.last_boundary_bytes: int = 0
        self.last_stage_idle_s: list[float] = [0.0] * s
        self._step_transfer_bytes = 0

        self._build_modules()
        self._build_specs()
        self._build_programs()
        self._logits_fn = None  # lazy (tests/export only)

    # ------------------------------------------------------------- build

    def _build_modules(self) -> None:
        from frl_distributed_ml_scaffold_tpu.models.gpt import GptStage

        s = self.num_stages
        self._modules = [
            GptStage(
                self.cfg.model,
                self.policy,
                num_layers=self._layers_per_stage,
                first=(j == 0),
                last=(j == s - 1),
            )
            for j in range(s)
        ]

    def _stage_example(self, j: int, batch: int):
        cfg = self.cfg.model
        t = cfg.seq_len
        if j == 0:
            return jnp.zeros((batch, t), jnp.int32)
        return jnp.zeros(
            (batch, t, cfg.hidden_dim), self.policy.compute_dtype
        )

    def _build_specs(self) -> None:
        """Per-stage state shapes/specs/shardings (the Trainer re-exports
        them so checkpointing sees one TrainState-shaped tree whose
        leaves carry per-submesh NamedShardings)."""
        from frl_distributed_ml_scaffold_tpu.models.gpt import gpt_tp_rules

        cfg = self.cfg
        seed_key = jax.random.key(cfg.trainer.seed)
        self._param_shapes = []
        self._param_specs = []
        self._param_shardings = []
        self._opt_shapes = []
        self._opt_specs = []
        self._opt_shardings = []
        self._grad_shardings = []
        for j, (sub, module) in enumerate(zip(self.subenvs, self._modules)):
            rng = jax.random.fold_in(seed_key, j)
            ex = self._stage_example(j, self.micro_batch)

            def init_fn(r, _m=module, _x=ex):
                return _m.init({"params": r}, _x, train=False)["params"]

            with mesh_context(sub):
                shapes = jax.eval_shape(init_fn, rng)
                opt_shapes = jax.eval_shape(self.tx.init, shapes)
            rules = (
                gpt_tp_rules() if sub.axis_size("model") > 1
                or sub.axis_size("expert") > 1 else None
            )
            p_specs = param_specs(shapes, cfg.parallel, sub.mesh, rules)
            o_specs = opt_state_specs(
                opt_shapes, shapes, p_specs, cfg.parallel, sub.mesh
            )
            self._opt_specs.append(o_specs)
            self._param_shapes.append(shapes)
            self._param_specs.append(p_specs)
            self._param_shardings.append(
                shardings_from_specs(p_specs, sub.mesh)
            )
            self._opt_shapes.append(opt_shapes)
            self._opt_shardings.append(
                shardings_from_specs(o_specs, sub.mesh)
            )
            # Grad accumulators ride the params' (possibly fsdp-sharded)
            # layout — microbatch grads accumulate as SHARDS, the SPMD
            # path's grad_shardings discipline.
            self._grad_shardings.append(
                shardings_from_specs(p_specs, sub.mesh)
            )
        s = self.num_stages
        self.state_shapes = TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            params={f"stage_{j}": self._param_shapes[j] for j in range(s)},
            opt_state={f"stage_{j}": self._opt_shapes[j] for j in range(s)},
            extras={},
            ema_params=(
                {f"stage_{j}": self._param_shapes[j] for j in range(s)}
                if self.has_ema else None
            ),
        )
        self.state_specs = TrainState(
            step=P(),
            params={f"stage_{j}": self._param_specs[j] for j in range(s)},
            opt_state={f"stage_{j}": self._opt_specs[j] for j in range(s)},
            extras={},
            ema_params=(
                {f"stage_{j}": self._param_specs[j] for j in range(s)}
                if self.has_ema else None
            ),
        )
        self.state_shardings = TrainState(
            step=NamedSharding(self.subenvs[0].mesh, P()),
            params={f"stage_{j}": self._param_shardings[j] for j in range(s)},
            opt_state={f"stage_{j}": self._opt_shardings[j] for j in range(s)},
            extras={},
            ema_params=(
                {f"stage_{j}": self._param_shardings[j] for j in range(s)}
                if self.has_ema else None
            ),
        )

        # The attached overlap schedule lowers per stage program: the
        # hook mechanisms need the stage's own param specs + submesh.
        from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
            hooked_model,
            schedule_from_config,
        )

        sched = schedule_from_config(cfg)
        self.overlap_schedule = sched
        if sched is not None:
            self._loss_modules = [
                hooked_model(
                    sched, m, cfg, self.subenvs[j], self._param_specs[j]
                )
                for j, m in enumerate(self._modules)
            ]
        else:
            self._loss_modules = list(self._modules)

        # Boundary layouts. Activations ENTERING stage j live on stage
        # j's submesh: batch-sharded over (data, fsdp); the sequence dim
        # rides the seq axis when populated, or the model axis when the
        # TP rings keep the residual stream sequence-sharded
        # (TpHooks.stream_spec) — the transfer then moves the already-
        # sharded stream, never a gathered copy.
        def boundary_spec(j):
            hooks = getattr(self._loss_modules[j], "tp_overlap", None)
            if hooks is not None:
                return hooks.stream_spec()
            sub = self.subenvs[j]
            if (
                sub.axis_size("seq") > 1
                and cfg.model.seq_len % sub.axis_size("seq") == 0
            ):
                return P(BATCH_AXES, "seq", None)
            return P(BATCH_AXES, None, None)

        self._bound_shardings = [
            NamedSharding(self.subenvs[j].mesh, boundary_spec(j))
            for j in range(s)
        ]
        self._tok_sharding0 = NamedSharding(
            self.subenvs[0].mesh, P(BATCH_AXES, None)
        )
        self._tgt_sharding_last = NamedSharding(
            self.subenvs[s - 1].mesh, P(BATCH_AXES, None)
        )
        # The tied embedding's cross-stage mirrors: the last stage reads
        # the compute-cast table for the LM head; its gradient rides the
        # reverse transfer back into stage 0's master copy.
        emb_spec = self._param_specs[0]["wte"]["embedding"]
        self._emb_sharding_last = NamedSharding(
            self.subenvs[s - 1].mesh, emb_spec
        )
        self._emb_grad_sharding0 = NamedSharding(
            self.subenvs[0].mesh, emb_spec
        )
        self._scalar_shardings = [
            NamedSharding(self.subenvs[j].mesh, P()) for j in range(s)
        ]

    def _scoped(self, j: int, fn):
        """Trace-time mesh context for stage ``j``'s programs (the
        Trainer's ``_mesh_scoped`` discipline, per submesh)."""

        def wrapped(*args, **kwargs):
            with mesh_context(self.subenvs[j]):
                return fn(*args, **kwargs)

        return wrapped

    def _maybe_remat(self, f):
        """``trainer.remat`` composes with the stage-boundary recompute:
        the bwd programs re-run the stage forward from its saved input
        either way (that IS the 1F1B memory profile); remat modes
        additionally checkpoint the recompute's own residuals."""
        remat = self.cfg.trainer.remat
        if remat == "none":
            return f
        if remat == "full":
            return jax.checkpoint(f)
        if remat == "dots":
            return jax.checkpoint(
                f, policy=jax.checkpoint_policies.checkpoint_dots
            )
        raise KeyError(f"unknown remat mode {remat!r}")

    def _build_programs(self) -> None:
        cfg, policy = self.cfg, self.policy
        s = self.num_stages
        dtype = policy.compute_dtype
        rdtype = policy.reduce_dtype
        ema_d = cfg.trainer.ema_decay
        inv = 1.0 / self.total_micro

        self._fwd_fn, self._fwd = [], []
        self._bwd_fn, self._bwd = [], []
        self._fin_fn, self._fin = [], []
        self._upd_fn, self._upd = [], []
        self._zero_grads = []
        self._eval_fwd = []

        for j in range(s):
            module = self._loss_modules[j]
            g_sh = self._grad_shardings[j]

            def fwd(params, x, rng, _m=module):
                pc = policy.cast_to_compute(params)
                return _stage_forward(_m, policy, pc, x, rng, True)

            self._fwd_fn.append(fwd)
            self._fwd.append(self._scoped(j, jax.jit(fwd)))

            if j < s - 1:

                def bwd(params, x, g_out, rng, g_acc, _m=module,
                        _j=j, _gsh=g_sh):
                    pc = policy.cast_to_compute(params)

                    def f(p, xx):
                        return _stage_forward(_m, policy, p, xx, rng, True)

                    f = self._maybe_remat(f)
                    if _j == 0:
                        # Tokens are integral — no input cotangent.
                        _, vjp = jax.vjp(lambda p: f(p, x), pc)
                        (gp,) = vjp(g_out)
                        gx = None
                    else:
                        _, vjp = jax.vjp(f, pc, x)
                        gp, gx = vjp(g_out)
                    g_acc = jax.tree.map(
                        lambda a, g: a + g.astype(rdtype), g_acc, gp
                    )
                    g_acc = jax.lax.with_sharding_constraint(g_acc, _gsh)
                    return g_acc if _j == 0 else (g_acc, gx)

                donate = (2, 4) if j == 0 else (1, 2, 4)
                if not _DONATE_TRANSIENTS:
                    donate = ()
                self._bwd_fn.append(bwd)
                self._bwd.append(
                    self._scoped(j, jax.jit(bwd, donate_argnums=donate))
                )
            else:
                # Last stage: fused fwd+bwd per microbatch — the LM head
                # (weight-tied: the transferred embedding mirror) + CE,
                # value_and_grad over (params, embedding, input) in one
                # program; its input cotangent starts the reverse
                # pipeline.
                def last(params, emb, x, targets, rng, g_acc, g_emb_acc,
                         _m=module, _gsh=g_sh):
                    pc = policy.cast_to_compute(params)

                    def f(p, e, xx):
                        feats = _stage_forward(_m, policy, p, xx, rng, True)
                        # Exactly wte.attend's math (models/gpt.py):
                        # compute-dtype matmul, fp32 softmax-CE after.
                        logits = (feats.astype(dtype) @ e.T).astype(
                            jnp.float32
                        )
                        return optax.softmax_cross_entropy_with_integer_labels(
                            logits, targets
                        ).mean()

                    f = self._maybe_remat(f)
                    ce, (gp, ge, gx) = jax.value_and_grad(
                        f, argnums=(0, 1, 2)
                    )(pc, emb, x)
                    g_acc = jax.tree.map(
                        lambda a, g: a + g.astype(rdtype), g_acc, gp
                    )
                    g_acc = jax.lax.with_sharding_constraint(g_acc, _gsh)
                    g_emb_acc = g_emb_acc + ge.astype(rdtype)
                    metrics = {"ce_loss": ce, "perplexity": jnp.exp(ce)}
                    return ce, metrics, g_acc, g_emb_acc, gx

                self._last_fn = last
                self._last = self._scoped(
                    j,
                    jax.jit(
                        last,
                        donate_argnums=(
                            (2, 5, 6) if _DONATE_TRANSIENTS else ()
                        ),
                    ),
                )

            # Grad finalize: average over all microbatches, cast to the
            # param dtype (the SPMD step's cast_to_param point), and emit
            # the stage's squared grad norm for the host-coordinated
            # global clip + grad_norm metric. Stage 0 folds the tied
            # embedding's transferred head gradient in first.
            if j == 0:

                def fin(g_acc, g_emb, _gsh=g_sh):
                    wte = dict(g_acc["wte"])
                    wte["embedding"] = wte["embedding"] + g_emb
                    g_acc = {**g_acc, "wte": wte}
                    g = jax.tree.map(lambda t: t * inv, g_acc)
                    g = policy.cast_to_param(g)
                    g = jax.lax.with_sharding_constraint(g, _gsh)
                    sq = sum(
                        jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in jax.tree.leaves(g)
                    )
                    return g, sq

                fin_donate = (0, 1)
            else:

                def fin(g_acc, _gsh=g_sh):
                    g = jax.tree.map(lambda t: t * inv, g_acc)
                    g = policy.cast_to_param(g)
                    g = jax.lax.with_sharding_constraint(g, _gsh)
                    sq = sum(
                        jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in jax.tree.leaves(g)
                    )
                    return g, sq

                fin_donate = (0,)
            if not _DONATE_TRANSIENTS:
                fin_donate = ()
            self._fin_fn.append(fin)
            self._fin.append(
                self._scoped(j, jax.jit(fin, donate_argnums=fin_donate))
            )

            # Stage update: clip factor in, new stage state out, old
            # state donated (the per-stage face of the train step's
            # donate_argnums=(0,) — audited by graft-lint's
            # pipeline:stage_program family).
            if self.has_ema:

                def upd(params, opt, ema, g, factor):
                    g = jax.tree.map(lambda t: t * factor, g)
                    updates, new_opt = self.tx.update(g, opt, params)
                    new_params = optax.apply_updates(params, updates)
                    new_ema = jax.tree.map(
                        lambda e, p: e * ema_d
                        + p.astype(e.dtype) * (1.0 - ema_d),
                        ema,
                        new_params,
                    )
                    return new_params, new_opt, new_ema

                upd_out = (
                    self._param_shardings[j],
                    self._opt_shardings[j],
                    self._param_shardings[j],
                )
                upd_donate = (0, 1, 2, 3)
            else:

                def upd(params, opt, g, factor):
                    g = jax.tree.map(lambda t: t * factor, g)
                    updates, new_opt = self.tx.update(g, opt, params)
                    new_params = optax.apply_updates(params, updates)
                    return new_params, new_opt

                upd_out = (self._param_shardings[j], self._opt_shardings[j])
                upd_donate = (0, 1, 2)
            self._upd_fn.append(upd)
            self._upd.append(
                self._scoped(
                    j,
                    jax.jit(
                        upd,
                        donate_argnums=(
                            upd_donate if _DONATE_STAGE_STATE else ()
                        ),
                        out_shardings=upd_out,
                    ),
                )
            )

            shapes = self._param_shapes[j]

            def zeros(_shapes=shapes):
                return jax.tree.map(
                    lambda l: jnp.zeros(l.shape, rdtype), _shapes
                )

            self._zero_grads.append(
                self._scoped(
                    j, jax.jit(zeros, out_shardings=self._grad_shardings[j])
                )
            )

            def efwd(params, x, _m=module):
                pc = policy.cast_to_compute(params)
                return _stage_forward(_m, policy, pc, x, None, False)

            self._eval_fwd.append(self._scoped(j, jax.jit(efwd)))

        emb_shape = self._param_shapes[0]["wte"]["embedding"]

        def zero_emb():
            return jnp.zeros(emb_shape.shape, rdtype)

        self._zero_emb = self._scoped(
            s - 1,
            jax.jit(
                zero_emb,
                out_shardings=NamedSharding(
                    self.subenvs[s - 1].mesh,
                    self._param_specs[0]["wte"]["embedding"],
                ),
            ),
        )

        # Tiny stage-0 helper for the cross-stage grad norm: the DRIVER
        # is host-side code (the hygiene pass must not read it as a
        # traced fn), so even the final sqrt runs as a compiled program.
        self._sqrt0 = self._scoped(0, jax.jit(jnp.sqrt))

        def eval_loss(params, emb, x, targets, _m=self._loss_modules[-1]):
            pc = policy.cast_to_compute(params)
            feats = _stage_forward(_m, policy, pc, x, None, False)
            logits = (feats.astype(dtype) @ emb.T).astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            ).mean()
            return ce, {"ce_loss": ce, "perplexity": jnp.exp(ce)}

        self._eval_loss = self._scoped(s - 1, jax.jit(eval_loss))

    # ----------------------------------------------------------- init

    def init_state(self) -> TrainState:
        """Per-stage sharded init (each stage's params materialize
        directly on its submesh) assembled into ONE TrainState."""
        cfg = self.cfg
        seed_key = jax.random.key(cfg.trainer.seed)
        params = {}
        opt = {}
        for j, (sub, module) in enumerate(zip(self.subenvs, self._modules)):
            rng = jax.random.fold_in(seed_key, j)
            ex = self._stage_example(j, self.micro_batch)

            def init_fn(r, _m=module, _x=ex):
                return _m.init({"params": r}, _x, train=False)["params"]

            with mesh_context(sub):
                params[f"stage_{j}"] = jax.jit(
                    init_fn, out_shardings=self._param_shardings[j]
                )(rng)
                opt[f"stage_{j}"] = jax.jit(
                    self.tx.init, out_shardings=self._opt_shardings[j]
                )(params[f"stage_{j}"])
        ema = (
            jax.tree.map(jnp.copy, params) if self.has_ema else None
        )
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt,
            extras={},
            ema_params=ema,
        )

    def place_plain_params(self, plain_host_params) -> dict:
        """Slice a PLAIN-layout (host) params tree into the per-stage
        layout and place each stage's slice on its submesh — the
        mpmd face of ``trainer.init_params_path`` and of the parity
        tests' shared-init discipline."""
        from frl_distributed_ml_scaffold_tpu.models.gpt import (
            mpmd_stage_params,
        )

        # Defensive copy BEFORE device_put: on the CPU backend,
        # jax.device_get returns numpy VIEWS of the device buffers and
        # device_put can zero-copy alias host memory — so params "placed"
        # from another trainer's device_get would silently change when
        # that trainer's donated step reuses the aliased buffers
        # (observed: a DP reference step corrupting the staged params it
        # was being compared against). A host-side copy breaks the chain.
        plain_host_params = jax.tree.map(
            lambda l: np.array(l, copy=True), plain_host_params
        )
        staged = mpmd_stage_params(
            self.cfg.model, plain_host_params, self.num_stages
        )
        return {
            f"stage_{j}": jax.device_put(
                staged[f"stage_{j}"], self._param_shardings[j]
            )
            for j in range(self.num_stages)
        }

    # ------------------------------------------------------- telemetry

    def attach_telemetry(
        self, *, registry=None, tracer=None, trace=None, watchdog=None
    ) -> None:
        """Wire the fit() loop's telemetry into the 1F1B driver: per-stage
        idle gauges + the analytic bubble gauge, boundary-transfer
        counter, stage-lane spans, and watchdog beats from inside the
        driver loop (a wedged inter-stage transfer then fires the PR 7
        stall dump instead of hanging silently)."""
        self._tracer = tracer
        self._trace = trace
        self._watchdog = watchdog
        self._telem = registry
        if registry is not None:
            self._g_idle = [
                registry.gauge(
                    f"pipeline_stage{j}_idle_s",
                    help="host-observed dispatch shadow of stage j per "
                    "step (fill/drain + starvation)",
                )
                for j in range(self.num_stages)
            ]
            self._g_bubble = registry.gauge(
                "pipeline_bubble_fraction",
                help="analytic (S-1)/(M+S-1) of the running 1F1B schedule",
            )
            self._c_transfer = registry.counter(
                "pipeline_boundary_transfer_bytes_total",
                help="explicit inter-stage activation/gradient bytes "
                "moved by the driver",
            )

    def _span(self, name: str, **fields):
        if self._tracer is not None and getattr(self._tracer, "enabled", False):
            return self._tracer.span(
                name, trace=self._trace, cat="pipeline", **fields
            )
        import contextlib

        return contextlib.nullcontext()

    # ---------------------------------------------------------- driver

    def _transfer(self, arr, sharding):
        out = jax.device_put(arr, sharding)
        self._step_transfer_bytes += int(arr.size) * arr.dtype.itemsize
        return out

    def _stage_ops(self, j: int):
        """Stage ``j``'s 1F1B op string: warmup forwards, steady 1F1B
        pairs, cooldown backwards. The last stage runs fused
        forward+backward microsteps ('X')."""
        m = self.total_micro
        if j == self.num_stages - 1:
            return ["X"] * m
        w = min(self.num_stages - 1 - j, m)
        return ["F"] * w + ["F", "B"] * (m - w) + ["B"] * w

    def train_step(self, state: TrainState, batch) -> tuple[TrainState, dict]:
        """One optimizer step: a full 1F1B pass over
        ``microbatches x grad_accum`` microbatches, explicit boundary
        transfers between stage submeshes, then per-stage updates under
        one host-coordinated global grad norm."""
        cfg, policy = self.cfg, self.policy
        s, mt, mb = self.num_stages, self.total_micro, self.micro_batch
        t_start = time.perf_counter()
        self._step_transfer_bytes = 0
        tokens = batch["tokens"]
        step_num = int(jax.device_get(state.step))
        step_key = jax.random.fold_in(
            jax.random.key(cfg.trainer.seed), state.step
        )
        stage_keys = [
            jax.device_put(step_key, self._scalar_shardings[j])
            for j in range(s)
        ]
        params = state.params
        emb = params["stage_0"]["wte"]["embedding"]
        emb_last = self._transfer(
            emb.astype(policy.compute_dtype), self._emb_sharding_last
        )

        def rng_for(j, m):
            return jax.random.fold_in(
                jax.random.fold_in(stage_keys[j], m), j
            )

        def ingest_tokens(m):
            sl = tokens[m * mb : (m + 1) * mb]
            return self._transfer(sl[:, :-1], self._tok_sharding0)

        def ingest_targets(m):
            sl = tokens[m * mb : (m + 1) * mb]
            return self._transfer(sl[:, 1:], self._tgt_sharding_last)

        g_acc = [self._zero_grads[j]() for j in range(s)]
        g_emb_acc = self._zero_emb()
        ops = [self._stage_ops(j) for j in range(s)]
        pc = [0] * s
        f_cnt = [0] * s
        b_cnt = [0] * s
        saved: list[dict] = [{} for _ in range(s)]
        ready_acts: list[dict] = [{} for _ in range(s)]
        ready_grads: list[dict] = [{} for _ in range(s)]
        peak_live = [0] * s
        first_t: list[float | None] = [None] * s
        last_t: list[float | None] = [None] * s
        losses = []
        metrics_sum = None

        def mark(j):
            now = time.perf_counter()
            if first_t[j] is None:
                first_t[j] = now
            last_t[j] = now

        while any(pc[j] < len(ops[j]) for j in range(s)):
            progressed = False
            for j in range(s):
                if pc[j] >= len(ops[j]):
                    continue
                op = ops[j][pc[j]]
                if op == "F":
                    m = f_cnt[j]
                    if j == 0:
                        x = ingest_tokens(m)
                    elif m in ready_acts[j]:
                        x = ready_acts[j].pop(m)
                    else:
                        continue
                    with self._span(f"stage{j}_fwd", step=step_num,
                                    microbatch=m):
                        y = self._fwd[j](
                            params[f"stage_{j}"], x, rng_for(j, m)
                        )
                    mark(j)
                    saved[j][m] = x
                    peak_live[j] = max(peak_live[j], len(saved[j]))
                    ready_acts[j + 1][m] = self._transfer(
                        y, self._bound_shardings[j + 1]
                    )
                    f_cnt[j] += 1
                elif op == "X":  # last stage: fused fwd+bwd
                    m = f_cnt[j]
                    if m not in ready_acts[j]:
                        continue
                    x = ready_acts[j].pop(m)
                    tgt = ingest_targets(m)
                    with self._span(f"stage{j}_fwd_bwd", step=step_num,
                                    microbatch=m):
                        ce, mtr, g_acc[j], g_emb_acc, gx = self._last(
                            params[f"stage_{j}"], emb_last, x, tgt,
                            rng_for(j, m), g_acc[j], g_emb_acc,
                        )
                    mark(j)
                    losses.append(ce)
                    metrics_sum = (
                        mtr if metrics_sum is None
                        else jax.tree.map(
                            lambda a, b: a + b, metrics_sum, mtr
                        )
                    )
                    if s > 1:
                        ready_grads[j - 1][m] = self._transfer(
                            gx, self._bound_shardings[j - 1]
                        )
                    f_cnt[j] += 1
                    b_cnt[j] += 1
                else:  # "B"
                    m = b_cnt[j]
                    if m not in ready_grads[j]:
                        continue
                    g = ready_grads[j].pop(m)
                    x = saved[j].pop(m)
                    with self._span(f"stage{j}_bwd", step=step_num,
                                    microbatch=m):
                        if j == 0:
                            g_acc[0] = self._bwd[0](
                                params["stage_0"], x, g, rng_for(0, m),
                                g_acc[0],
                            )
                        else:
                            g_acc[j], gx = self._bwd[j](
                                params[f"stage_{j}"], x, g, rng_for(j, m),
                                g_acc[j],
                            )
                            ready_grads[j - 1][m] = self._transfer(
                                gx, self._bound_shardings[j - 1]
                            )
                    mark(j)
                    b_cnt[j] += 1
                pc[j] += 1
                progressed = True
                if self._watchdog is not None:
                    # Beats from INSIDE the driver loop: a wedged
                    # transfer/dispatch silences them and fires the dump.
                    self._watchdog.beat()
            if not progressed:
                raise RuntimeError(
                    "1F1B schedule wedged: no stage op is ready "
                    f"(pc={pc}, fwd={f_cnt}, bwd={b_cnt}) — schedule "
                    "bookkeeping bug, not a device stall"
                )

        # Finalize: average + cast per stage; tied-embedding head grad
        # transfers back to stage 0; ONE global norm across stages.
        g_emb0 = self._transfer(g_emb_acc, self._emb_grad_sharding0)
        grads, sqs = [], []
        for j in range(s):
            args = (g_acc[j], g_emb0) if j == 0 else (g_acc[j],)
            g, sq = self._fin[j](*args)
            grads.append(g)
            sqs.append(sq)
        sq_total = sum(
            jax.device_put(sq, self._scalar_shardings[0]) for sq in sqs
        )
        gnorm = self._sqrt0(sq_total)
        if self.clip_norm is not None:
            # Host-coordinated exact clip_by_global_norm: factor applied
            # to the averaged param-dtype grads, clip element stripped
            # from the per-stage chain (see __init__).
            gn = float(jax.device_get(gnorm))
            factor = 1.0 if gn < self.clip_norm else self.clip_norm / gn
        else:
            factor = 1.0

        new_params, new_opt, new_ema = {}, {}, {}
        for j in range(s):
            key = f"stage_{j}"
            with self._span(f"stage{j}_update", step=step_num):
                if self.has_ema:
                    p, o, e = self._upd[j](
                        params[key], state.opt_state[key],
                        state.ema_params[key], grads[j], factor,
                    )
                    new_ema[key] = e
                else:
                    p, o = self._upd[j](
                        params[key], state.opt_state[key], grads[j], factor
                    )
                new_params[key] = p
                new_opt[key] = o
            if self._watchdog is not None:
                self._watchdog.beat()

        t_end = time.perf_counter()
        self.last_peak_live = peak_live
        self.last_boundary_bytes = self._step_transfer_bytes
        self.last_stage_idle_s = [
            (first_t[j] - t_start if first_t[j] is not None else 0.0)
            + (t_end - last_t[j] if last_t[j] is not None else 0.0)
            for j in range(s)
        ]
        if self._telem is not None:
            for j in range(s):
                self._g_idle[j].set(self.last_stage_idle_s[j])
            self._g_bubble.set(bubble_fraction("1f1b", s, mt))
            self._c_transfer.inc(self._step_transfer_bytes)

        inv_m = 1.0 / len(losses)
        metrics = {
            k: v * inv_m for k, v in (metrics_sum or {}).items()
        }
        metrics["loss"] = sum(losses) * inv_m
        metrics["grad_norm"] = gnorm
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt,
            extras={},
            ema_params=new_ema if self.has_ema else None,
        )
        return new_state, metrics

    # ------------------------------------------------------------ eval

    def _forward_features(self, params, inputs):
        """Full-batch forward through stages ``0..S-2`` (eval/export):
        returns the LAST stage's boundary input on the last submesh (the
        last stage itself runs inside the loss/logits program)."""
        x = self._transfer(inputs, self._tok_sharding0)
        for j in range(self.num_stages - 1):
            y = self._eval_fwd[j](params[f"stage_{j}"], x)
            x = self._transfer(y, self._bound_shardings[j + 1])
        return x

    def eval_step(self, state: TrainState, batch) -> dict:
        """Forward-only metrics step (the make_eval_step contract)."""
        self._step_transfer_bytes = 0
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        params = state.params
        x = self._forward_features(params, inputs)
        emb = params["stage_0"]["wte"]["embedding"]
        emb_last = self._transfer(
            emb.astype(self.policy.compute_dtype), self._emb_sharding_last
        )
        tgt = self._transfer(targets, self._tgt_sharding_last)
        ce, metrics = self._eval_loss(
            params[f"stage_{self.num_stages - 1}"], emb_last, x, tgt
        )
        out = dict(metrics)
        out["loss"] = ce
        return out

    def apply_logits(self, params, inputs):
        """Full-batch logits (tests/parity rigs): the per-stage forward
        chain + the weight-tied head, numerically the plain GPT apply."""
        s = self.num_stages
        if self._logits_fn is None:
            dtype = self.policy.compute_dtype
            policy = self.policy
            module = self._loss_modules[-1]

            def logits_fn(p_last, emb, x):
                pc = policy.cast_to_compute(p_last)
                feats = _stage_forward(module, policy, pc, x, None, False)
                return feats.astype(dtype) @ emb.T

            self._logits_fn = self._scoped(s - 1, jax.jit(logits_fn))
        x = self._forward_features(params, inputs)
        emb = params["stage_0"]["wte"]["embedding"]
        emb_last = self._transfer(
            emb.astype(self.policy.compute_dtype), self._emb_sharding_last
        )
        return self._logits_fn(params[f"stage_{s - 1}"], emb_last, x)

    # ------------------------------------------------------- analysis

    def step_cost_analysis(self) -> dict | None:
        """Analytic step FLOPs for MFU logging: per-microbatch fwd+bwd
        jaxpr FLOPs summed over stages x microbatches, plus the update
        programs (the jaxpr counter the SPMD path falls back to)."""
        try:
            from frl_distributed_ml_scaffold_tpu.utils.flops import (
                jaxpr_flops,
            )

            total = 0.0
            for art in self.lint_artifacts():
                total += jaxpr_flops(art["fwd_bwd_jaxpr"]) * self.total_micro
            return {"flops": float(total), "flops_source": "jaxpr-mpmd"}
        except Exception:
            return None

    def lint_artifacts(self) -> list[dict]:
        """ABSTRACT per-stage programs for graft-lint and the perf ledger
        (nothing runs): per stage, the microbatch fwd jaxpr, the fused
        fwd+bwd jaxpr (last stage: the loss/grad program), and the
        LOWERED update program for the donation audit — the artifacts the
        ``pipeline:stage_program`` family pins free of cross-stage
        collectives and donation regressions."""
        out = []
        s = self.num_stages
        key_aval = jax.eval_shape(lambda: jax.random.key(0))
        for j in range(s):
            sub = self.subenvs[j]
            shapes = self._param_shapes[j]
            x_aval = jax.eval_shape(
                lambda _j=j: self._stage_example(_j, self.micro_batch)
            )
            g_aval = jax.eval_shape(
                lambda: jax.tree.map(
                    lambda l: jnp.zeros(l.shape, self.policy.reduce_dtype),
                    shapes,
                )
            )
            with mesh_context(sub):
                fwd_jaxpr = jax.make_jaxpr(self._fwd_fn[j])(
                    shapes, x_aval, key_aval
                )
                if j < s - 1:
                    y_aval = jax.eval_shape(
                        self._fwd_fn[j], shapes, x_aval, key_aval
                    )
                    if j == 0:
                        fb = jax.make_jaxpr(
                            lambda p, x, g, r, ga: self._bwd_fn[j](
                                p, x, g, r, ga
                            )
                        )(shapes, x_aval, y_aval, key_aval, g_aval)
                    else:
                        fb = jax.make_jaxpr(self._bwd_fn[j])(
                            shapes, x_aval, y_aval, key_aval, g_aval
                        )
                else:
                    emb_aval = jax.eval_shape(
                        lambda: jnp.zeros(
                            self._param_shapes[0]["wte"]["embedding"].shape,
                            self.policy.compute_dtype,
                        )
                    )
                    ge_aval = jax.eval_shape(
                        lambda: jnp.zeros(
                            self._param_shapes[0]["wte"]["embedding"].shape,
                            self.policy.reduce_dtype,
                        )
                    )
                    tgt_aval = jax.ShapeDtypeStruct(
                        (self.micro_batch, self.cfg.model.seq_len), jnp.int32
                    )
                    fb = jax.make_jaxpr(self._last_fn)(
                        shapes, emb_aval, x_aval, tgt_aval, key_aval,
                        g_aval, ge_aval,
                    )
                g_param_aval = jax.eval_shape(
                    lambda: jax.tree.map(
                        lambda l: jnp.zeros(
                            l.shape, self.policy.param_dtype
                        ),
                        shapes,
                    )
                )
                upd_args = (
                    (shapes, self._opt_shapes[j], shapes, g_param_aval, 1.0)
                    if self.has_ema
                    else (shapes, self._opt_shapes[j], g_param_aval, 1.0)
                )
                upd_jit = jax.jit(
                    self._upd_fn[j],
                    donate_argnums=(
                        ((0, 1, 2, 3) if self.has_ema else (0, 1, 2))
                        if _DONATE_STAGE_STATE else ()
                    ),
                )
                update_lowered = upd_jit.lower(*upd_args)
            out.append(
                {
                    "stage": j,
                    "chips": sub.mesh.size,
                    "fwd_jaxpr": fwd_jaxpr,
                    "fwd_bwd_jaxpr": fb,
                    "update_lowered": update_lowered,
                    # Positions the donation audit must see donated:
                    # params/opt/[ema]/grads — everything but the
                    # trailing clip-factor scalar.
                    "update_donate_expected": (
                        (0, 1, 2, 3) if self.has_ema else (0, 1, 2)
                    ),
                    "params_shapes": shapes,
                    "boundary_bytes_per_microbatch": int(
                        self.micro_batch
                        * self.cfg.model.seq_len
                        * self.cfg.model.hidden_dim
                        * np.dtype(self.policy.compute_dtype).itemsize
                    ),
                }
            )
        return out
