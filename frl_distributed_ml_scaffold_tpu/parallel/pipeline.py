"""Pipeline parallelism: GPipe schedule expressed in GSPMD (SURVEY C7).

The reference stages a model across device groups with MPMD ranks and
point-to-point NCCL sends. TPU-native, the whole pipeline stays inside the
one compiled SPMD program (cf. the MPMD-PP and pjit papers in PAPERS.md):

- **Stage-stacked parameters.** The repeated block is wrapped in
  ``nn.scan`` (layers within a stage) inside ``nn.vmap`` (across stages),
  so every block parameter carries leading dims ``[S, L/S, ...]`` and the
  stage dim is sharded over the ``pipe`` mesh axis — each stage's weights
  live only on its pipeline group.
- **Rolling activation buffer.** A ``[S, microbatch, ...]`` buffer, also
  sharded over ``pipe`` on dim 0, holds the activation each stage is
  currently working on. One schedule tick = every stage applies its layers
  to its slot (the vmapped compute partitions across ``pipe``), then the
  buffer rolls by one: ``jnp.roll`` on a pipe-sharded dim compiles to the
  collective-permute that is the stage-to-stage send.
- **GPipe timeline.** ``lax.scan`` over ``M + S - 1`` ticks: stage 0
  ingests microbatch ``t`` at tick ``t``, the last stage emits microbatch
  ``t - (S-1)``; the (S-1)-tick fill/drain bubble is the standard GPipe
  cost, amortized by ``num_microbatches``. The backward pass needs no
  hand-written schedule at all — autodiff through roll/scan yields the
  reverse pipeline, and XLA's latency-hiding scheduler overlaps the
  permutes with compute.

Because nothing here leaves GSPMD-land, PP composes freely with DP/FSDP
(batch axes on the microbatch dim) and TP (``model`` axis inside each
stage's weights). The stage vmap names its mapped axis
(``spmd_axis_name="pipe"``), so the flash/ring/Ulysses attention ops —
which open their own ``shard_map`` regions — batch over the stage dim and
compose with PP as well (their in/out specs gain the leading ``pipe``
entry through vmap's batching rule).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import BATCH_AXES, current_mesh_env


def effective_microbatches(model_cfg) -> int:
    """The one resolution rule for the pipeline microbatch count: the
    configured value, defaulting to the stage count (minimum bubble-free
    fill). Single source of truth for the model, trainer init sizing, and
    bubble-fraction logging."""
    stages = getattr(model_cfg, "pipeline_stages", 1)
    if stages <= 1:
        return 1
    return getattr(model_cfg, "pipeline_microbatches", 0) or stages


def pipeline_summary(model_cfg) -> str | None:
    """One-line human summary incl. the GPipe bubble fraction, or None when
    the model isn't pipelined — the single place the formula lives."""
    stages = getattr(model_cfg, "pipeline_stages", 1)
    if stages <= 1:
        return None
    micro = effective_microbatches(model_cfg)
    bubble = (stages - 1) / (micro + stages - 1)
    return (
        f"pipeline: {stages} stages x {micro} microbatches, "
        f"bubble fraction (S-1)/(M+S-1) = {bubble:.3f}"
    )


def _constrain(x: jax.Array, *leading_axes) -> jax.Array:
    """Sharding-constrain the leading dims of ``x`` (no-op without a mesh)."""
    env = current_mesh_env()
    if env is None:
        return x
    spec = P(*leading_axes, *([None] * (x.ndim - len(leading_axes))))
    return jax.lax.with_sharding_constraint(x, NamedSharding(env.mesh, spec))


class _PipelineTick(nn.Module):
    """One schedule tick: ingest → vmapped stage compute → roll.

    Scanned over the timeline with ``variable_broadcast="params"`` so the
    stage weights are created once and reused every tick.
    """

    block_cls: Any  # scan-signature module: __call__((x, aux), _) -> ((x, aux), None)
    block_args: tuple
    num_stages: int
    layers_per_stage: int

    @nn.compact
    def __call__(self, carry, xs):
        buf, aux_acc = carry  # buf: [S, mb, ...]; aux_acc: scalar
        inp, valid = xs  # inp: [mb, ...] feed for stage 0; valid: [S] this tick
        s = self.num_stages

        # Layers within a stage run sequentially (nn.scan); stages run as one
        # batched computation over the stage dim (nn.vmap) that GSPMD
        # partitions across ``pipe`` — params get leading dims [S, L/S, ...].
        stage = nn.scan(
            self.block_cls,
            length=self.layers_per_stage,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )
        body = nn.vmap(
            stage,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=((0, 0), None),
            out_axes=((0, 0), None),
            axis_size=s,
            # The stage dim is sharded over ``pipe``; naming it lets inner
            # shard_map regions (flash/ring/ulysses attention) batch over it
            # — their collectives/kernels stay per-stage-local and the specs
            # gain a leading "pipe" entry automatically. This is what makes
            # PP compose with the custom-kernel attention modes.
            spmd_axis_name="pipe",
        )(*self.block_args, name="blocks")

        buf = buf.at[0].set(inp.astype(buf.dtype))
        buf = _constrain(buf, "pipe", BATCH_AXES)
        (out, aux_delta), _ = body((buf, jnp.zeros((s,), jnp.float32)), None)
        # Bubble ticks process garbage slots; mask their aux contribution.
        aux_acc = aux_acc + jnp.sum(aux_delta * valid.astype(jnp.float32))
        y = out[s - 1]  # last stage's emission (valid from tick S-1 on)
        buf_next = _constrain(jnp.roll(out, 1, axis=0), "pipe", BATCH_AXES)
        return (buf_next, aux_acc), y


class SpmdPipeline(nn.Module):
    """Pipeline a stack of ``num_layers`` blocks over ``num_stages`` stages.

    ``block_cls(*block_args)`` must have the scan signature
    ``((x, aux_scalar), None) -> ((x, aux_scalar), None)``. The input batch
    dim must divide into ``num_microbatches``.
    """

    block_cls: Any
    block_args: tuple
    num_layers: int
    num_stages: int
    num_microbatches: int

    @nn.compact
    def __call__(self, x: jax.Array, aux0: jax.Array):
        s, m = self.num_stages, self.num_microbatches
        if self.num_layers % s:
            raise ValueError(f"{self.num_layers} layers not divisible by {s} stages")
        if x.shape[0] % m:
            raise ValueError(f"batch {x.shape[0]} not divisible by {m} microbatches")
        mb = x.shape[0] // m
        ticks = m + s - 1

        x_mb = _constrain(
            x.reshape((m, mb) + x.shape[1:]), None, BATCH_AXES
        )
        # Stage-0 feed per tick: microbatch t while t < M, dead inputs after.
        if s > 1:
            pad = jnp.zeros((s - 1,) + x_mb.shape[1:], x_mb.dtype)
            feed = jnp.concatenate([x_mb, pad])
        else:
            feed = x_mb
        # valid[t, j] — stage j holds real data (microbatch t-j) at tick t.
        t_idx = jnp.arange(ticks)[:, None]
        s_idx = jnp.arange(s)[None, :]
        valid = (t_idx - s_idx >= 0) & (t_idx - s_idx < m)

        timeline = nn.scan(
            _PipelineTick,
            length=ticks,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
        )(
            self.block_cls,
            self.block_args,
            s,
            self.num_layers // s,
            name="ticks",
        )
        buf0 = _constrain(
            jnp.zeros((s, mb) + x.shape[1:], x.dtype), "pipe", BATCH_AXES
        )
        (_, aux_sum), ys = timeline((buf0, jnp.zeros((), jnp.float32)), (feed, valid))
        # Per-layer aux terms (e.g. the MoE router loss) are means over their
        # microbatch, so the schedule accumulates M full copies of the
        # plain-path value — average them back to batch-size-invariant form.
        aux = aux0 + aux_sum / m
        # Microbatch t emerges from the last stage at tick t + S - 1.
        out = ys[s - 1 :].reshape((m * mb,) + ys.shape[2:])
        return _constrain(out, BATCH_AXES), aux
