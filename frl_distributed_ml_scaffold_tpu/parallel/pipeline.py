"""Pipeline parallelism: GPipe schedule expressed in GSPMD (SURVEY C7).

The reference stages a model across device groups with MPMD ranks and
point-to-point NCCL sends. TPU-native, the whole pipeline stays inside the
one compiled SPMD program (cf. the MPMD-PP and pjit papers in PAPERS.md):

- **Stage-stacked parameters.** The repeated block is wrapped in
  ``nn.scan`` (layers within a stage) inside ``nn.vmap`` (across stages),
  so every block parameter carries leading dims ``[S, L/S, ...]`` and the
  stage dim is sharded over the ``pipe`` mesh axis — each stage's weights
  live only on its pipeline group.
- **Rolling activation buffer.** A ``[S, microbatch, ...]`` buffer, also
  sharded over ``pipe`` on dim 0, holds the activation each stage is
  currently working on. One schedule tick = every stage applies its layers
  to its slot (the vmapped compute partitions across ``pipe``), then the
  buffer rolls by one: ``jnp.roll`` on a pipe-sharded dim compiles to the
  collective-permute that is the stage-to-stage send.
- **GPipe timeline.** ``lax.scan`` over ``M + S - 1`` ticks: stage 0
  ingests microbatch ``t`` at tick ``t``, the last stage emits microbatch
  ``t - (S-1)``; the (S-1)-tick fill/drain bubble is the standard GPipe
  cost, amortized by ``num_microbatches``. The backward pass needs no
  hand-written schedule at all — autodiff through roll/scan yields the
  reverse pipeline, and XLA's latency-hiding scheduler overlaps the
  permutes with compute.

Because nothing here leaves GSPMD-land, PP composes freely with DP/FSDP
(batch axes on the microbatch dim) and TP (``model`` axis inside each
stage's weights). The stage vmap names its mapped axis
(``spmd_axis_name="pipe"``), so the flash/ring/Ulysses attention ops —
which open their own ``shard_map`` regions — batch over the stage dim and
compose with PP as well (their in/out specs gain the leading ``pipe``
entry through vmap's batching rule).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import BATCH_AXES, current_mesh_env


def effective_microbatches(model_cfg) -> int:
    """The one resolution rule for the pipeline microbatch count: the
    configured value, defaulting to the stage count (minimum bubble-free
    fill). Single source of truth for the model, trainer init sizing, and
    bubble-fraction logging."""
    stages = getattr(model_cfg, "pipeline_stages", 1)
    if stages <= 1:
        return 1
    return getattr(model_cfg, "pipeline_microbatches", 0) or stages


def circular_repeat(model_cfg) -> int:
    """Virtual-stage multiplier of the circular schedule (1 = plain GPipe)."""
    if getattr(model_cfg, "pipeline_stages", 1) <= 1:
        return 1
    return getattr(model_cfg, "pipeline_circular_repeat", 1) or 1


def pipeline_summary(model_cfg) -> str | None:
    """One-line human summary incl. the schedule's bubble fraction, or None
    when the model isn't pipelined — the single place the formula lives."""
    stages = getattr(model_cfg, "pipeline_stages", 1)
    if stages <= 1:
        return None
    micro = effective_microbatches(model_cfg)
    v = circular_repeat(model_cfg)
    if getattr(model_cfg, "pipeline_impl", "spmd") == "mpmd":
        # The MPMD backend (parallel/mpmd_pipeline.py): same fill/drain
        # bubble fraction, but steady state holds min(S, M) in-flight
        # microbatch activations instead of M — the number that lets M
        # grow (and the bubble shrink) without activation memory growing.
        bubble = (stages - 1) / (micro + stages - 1)
        return (
            f"pipeline: {stages} stages x {micro} microbatches "
            f"[mpmd-1f1b], bubble fraction (S-1)/(M+S-1) = {bubble:.3f}, "
            f"steady-state live microbatch activations = "
            f"{min(stages, micro)} (vs {micro} under gpipe)"
        )
    bubble = (stages - 1) / (v * micro + stages - 1)
    sched = "gpipe" if v == 1 else f"circular(x{v})"
    if getattr(model_cfg, "pipeline_stage_remat", False):
        sched += "+stage-remat"
    return (
        f"pipeline: {stages} stages x {micro} microbatches [{sched}], "
        f"bubble fraction (S-1)/(vM+S-1) = {bubble:.3f}"
    )


def _constrain(x: jax.Array, *leading_axes) -> jax.Array:
    """Sharding-constrain the leading dims of ``x`` (no-op without a mesh)."""
    env = current_mesh_env()
    if env is None:
        return x
    spec = P(*leading_axes, *([None] * (x.ndim - len(leading_axes))))
    return jax.lax.with_sharding_constraint(x, NamedSharding(env.mesh, spec))


class _PipelineTick(nn.Module):
    """One schedule tick: ingest → vmapped stage compute → roll.

    Scanned over the timeline with ``variable_broadcast="params"`` so the
    stage weights are created once and reused every tick.
    """

    block_cls: Any  # scan-signature module: __call__((x, aux), _) -> ((x, aux), None)
    block_args: tuple
    num_stages: int
    layers_per_stage: int
    stage_remat: bool = False

    @nn.compact
    def __call__(self, carry, xs):
        buf, aux_acc = carry  # buf: [S, mb, ...]; aux_acc: scalar
        inp, valid = xs  # inp: [mb, ...] feed for stage 0; valid: [S] this tick
        s = self.num_stages

        # Layers within a stage run sequentially (nn.scan); stages run as one
        # batched computation over the stage dim (nn.vmap) that GSPMD
        # partitions across ``pipe`` — params get leading dims [S, L/S, ...].
        stage = nn.scan(
            self.block_cls,
            length=self.layers_per_stage,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )
        if self.stage_remat:
            # Stage-granular rematerialization — the 1F1B memory profile
            # inside the one-program GSPMD formulation: autodiff saves only
            # each tick's stage-BOUNDARY inputs (the scan carry) and
            # recomputes stage internals in the backward, so activation
            # residency drops from O(ticks · per-stage internals) to
            # O(ticks · boundary) + one stage's internals transiently
            # (measured: tools/pp_memory_audit.py; docs/perf_playbook.md).
            # prevent_cse=False: the tick scan already blocks CSE, and the
            # guard would only inhibit XLA optimizations.
            stage = nn.remat(stage, prevent_cse=False)
        body = nn.vmap(
            stage,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=((0, 0), None),
            out_axes=((0, 0), None),
            axis_size=s,
            # The stage dim is sharded over ``pipe``; naming it lets inner
            # shard_map regions (flash/ring/ulysses attention) batch over it
            # — their collectives/kernels stay per-stage-local and the specs
            # gain a leading "pipe" entry automatically. This is what makes
            # PP compose with the custom-kernel attention modes.
            spmd_axis_name="pipe",
        )(*self.block_args, name="blocks")

        buf = buf.at[0].set(inp.astype(buf.dtype))
        buf = _constrain(buf, "pipe", BATCH_AXES)
        (out, aux_delta), _ = body((buf, jnp.zeros((s,), jnp.float32)), None)
        # Bubble ticks process garbage slots; mask their aux contribution.
        aux_acc = aux_acc + jnp.sum(aux_delta * valid.astype(jnp.float32))
        y = out[s - 1]  # last stage's emission (valid from tick S-1 on)
        buf_next = _constrain(jnp.roll(out, 1, axis=0), "pipe", BATCH_AXES)
        return (buf_next, aux_acc), y


class SpmdPipeline(nn.Module):
    """Pipeline a stack of ``num_layers`` blocks over ``num_stages`` stages.

    ``block_cls(*block_args)`` must have the scan signature
    ``((x, aux_scalar), None) -> ((x, aux_scalar), None)``. The input batch
    dim must divide into ``num_microbatches``.
    """

    block_cls: Any
    block_args: tuple
    num_layers: int
    num_stages: int
    num_microbatches: int
    stage_remat: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, aux0: jax.Array):
        s, m = self.num_stages, self.num_microbatches
        if self.num_layers % s:
            raise ValueError(f"{self.num_layers} layers not divisible by {s} stages")
        if x.shape[0] % m:
            raise ValueError(f"batch {x.shape[0]} not divisible by {m} microbatches")
        mb = x.shape[0] // m
        ticks = m + s - 1

        x_mb = _constrain(
            x.reshape((m, mb) + x.shape[1:]), None, BATCH_AXES
        )
        # Stage-0 feed per tick: microbatch t while t < M, dead inputs after.
        if s > 1:
            pad = jnp.zeros((s - 1,) + x_mb.shape[1:], x_mb.dtype)
            feed = jnp.concatenate([x_mb, pad])
        else:
            feed = x_mb
        # valid[t, j] — stage j holds real data (microbatch t-j) at tick t.
        t_idx = jnp.arange(ticks)[:, None]
        s_idx = jnp.arange(s)[None, :]
        valid = (t_idx - s_idx >= 0) & (t_idx - s_idx < m)

        timeline = nn.scan(
            _PipelineTick,
            length=ticks,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
        )(
            self.block_cls,
            self.block_args,
            s,
            self.num_layers // s,
            self.stage_remat,
            name="ticks",
        )
        buf0 = _constrain(
            jnp.zeros((s, mb) + x.shape[1:], x.dtype), "pipe", BATCH_AXES
        )
        (_, aux_sum), ys = timeline((buf0, jnp.zeros((), jnp.float32)), (feed, valid))
        # Per-layer aux terms (e.g. the MoE router loss) are means over their
        # microbatch, so the schedule accumulates M full copies of the
        # plain-path value — average them back to batch-size-invariant form.
        aux = aux0 + aux_sum / m
        # Microbatch t emerges from the last stage at tick t + S - 1.
        out = ys[s - 1 :].reshape((m * mb,) + ys.shape[2:])
        return _constrain(out, BATCH_AXES), aux


class CircularSpmdPipeline(nn.Module):
    """Circular (interleaved) pipeline schedule — GPipe's bubble cut by ``v``.

    Each physical stage ``j`` holds ``v`` non-adjacent layer groups
    ("virtual stages" ``r*S + j`` for ``r in [0, v)``), so every microbatch
    rotates through the stage ring ``v`` times. The fill/drain bubble
    amortizes over ``v*M`` busy ticks instead of ``M``:
    ``(S-1)/(v*M + S-1)`` — the same schedule praxis/Megatron call circular
    or interleaved pipelining, expressed here entirely inside one GSPMD
    program (no MPMD ranks, cf. PAPERS.md).

    Mechanics per tick ``t`` of ``v*M + S - 1``:

    - **Param selection.** Block params live as ONE pytree-valued flax param
      ``blocks`` with leading dims ``[v, S, L/(S*v), ...]`` (stage dim
      sharded over ``pipe``). Stage ``j`` is working repeat
      ``r_j = (t - j) // M``, so each tick gathers ``leaf[r_j, j]`` — a
      per-stage dynamic index on the *unsharded* ``v`` dim, which GSPMD
      partitions without touching other stages' weights.
    - **Compute.** The selected per-stage params are applied with
      ``jax.vmap(stage.apply, spmd_axis_name="pipe")`` — the same batched
      stage compute as the GPipe class, so flash/ring/ulysses attention
      (which open shard_map regions) compose identically.
    - **Rotation + parking.** The ``[S, mb, ...]`` buffer rolls by one
      (collective-permute over ``pipe``). A microbatch leaving stage S-1
      mid-run is not finished — it re-enters stage 0 for its next repeat
      after waiting ``M - S`` ticks in a parking FIFO (for ``M == S`` the
      roll wraparound IS the re-entry). External inputs feed slot 0 only
      during the first ``M`` ticks; recirculated activations after that.

    Requires ``M >= S`` (otherwise a re-entering microbatch collides with
    the injection of a fresh one) and ``num_layers % (S*v) == 0``.
    """

    block_cls: Any
    block_args: tuple
    num_layers: int
    num_stages: int
    num_microbatches: int
    repeat: int
    stage_remat: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, aux0: jax.Array):
        s, m, v = self.num_stages, self.num_microbatches, self.repeat
        if self.num_layers % (s * v):
            raise ValueError(
                f"{self.num_layers} layers not divisible by "
                f"{s} stages x {v} circular repeats"
            )
        if x.shape[0] % m:
            raise ValueError(f"batch {x.shape[0]} not divisible by {m} microbatches")
        if m < s:
            raise ValueError(
                f"circular schedule needs microbatches >= stages ({m} < {s}): "
                "a re-entering microbatch would collide with a fresh injection"
            )
        mb = x.shape[0] // m
        lg = self.num_layers // (s * v)
        ticks = v * m + s - 1
        # Parking FIFO between exit from stage S-1 (pushed at end of tick t)
        # and re-entry into stage 0 (read at start of tick t + M - S + 1,
        # i.e. after M - S intervening shifts — hence M - S + 1 slots).
        qlen = m - s + 1

        # Layers within one virtual-stage group run sequentially, exactly as
        # the GPipe class's per-stage nn.scan. The module is detached
        # (parent=None): its params are owned by THIS module as the stacked
        # ``blocks`` pytree below, and init/apply are used purely.
        stage = nn.scan(
            self.block_cls,
            length=lg,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )(*self.block_args, parent=None)

        slot_ex = (
            jnp.zeros((mb,) + x.shape[1:], x.dtype),
            jnp.zeros((), jnp.float32),
        )

        def init_stacked(rng):
            g = v * s
            rngs = jax.random.split(rng, g)
            ps = jax.vmap(
                lambda r: stage.init({"params": r, "dropout": r}, slot_ex, None)[
                    "params"
                ]
            )(rngs)
            # [g, lg, ...] -> [v, s, lg, ...]; virtual stage r*S+j -> [r, j].
            return jax.tree.map(
                lambda l: l.reshape((v, s) + l.shape[1:]), ps
            )

        stacked = self.param("blocks", init_stacked)

        has_drop = self.has_rng("dropout")
        drop_rng = self.make_rng("dropout") if has_drop else None

        def select_params(stacked_, r_vec):
            """leaf[v, s, ...] -> [s, ...] with out[j] = leaf[r_vec[j], j]."""
            env = current_mesh_env()

            def sel(leaf):
                picked = jax.vmap(
                    lambda lv, r: jax.lax.dynamic_index_in_dim(
                        lv, r, axis=0, keepdims=False
                    ),
                    in_axes=(1, 0),
                )(leaf, r_vec)
                if env is None:
                    return picked
                # Pin only the stage dim; trailing dims stay UNCONSTRAINED so
                # GSPMD keeps e.g. Megatron 'model'-sharded kernels sharded
                # (a None here would mean "replicated" and force a per-tick
                # all-gather of every TP weight).
                spec = P(
                    "pipe", *([P.UNCONSTRAINED] * (picked.ndim - 1))
                )
                return jax.lax.with_sharding_constraint(
                    picked, NamedSharding(env.mesh, spec)
                )

            return jax.tree.map(sel, stacked_)

        def apply_stage(p, slot, rng):
            rngs = {"dropout": rng} if has_drop else None
            (y, aux), _ = stage.apply(
                {"params": p}, (slot, jnp.zeros((), jnp.float32)), None, rngs=rngs
            )
            return y, aux

        def tick_compute(stacked_, r_vec, buf, rngs_t):
            params_t = select_params(stacked_, r_vec)
            return jax.vmap(apply_stage, spmd_axis_name="pipe")(
                params_t, buf, rngs_t
            )

        if self.stage_remat:
            # Same stage-granular remat as the GPipe class: save only the
            # stage-boundary carry per tick, recompute internals in bwd.
            # Param SELECTION sits inside the checkpointed region — done
            # outside, every tick's gathered per-stage params ([ticks, S,
            # ...] ~ the model over again) would be saved as residuals;
            # inside, the backward re-gathers from the resident stack.
            tick_compute = jax.checkpoint(tick_compute, prevent_cse=False)

        x_mb = _constrain(x.reshape((m, mb) + x.shape[1:]), None, BATCH_AXES)

        def tick(carry, t):
            buf, queue, aux_acc = carry
            # Injection: external feed while filling (ticks 0..M-1), parked
            # activations re-entering for their next repeat afterwards. The
            # clamped index keeps the feed at M slots (its value is ignored
            # for t >= M) instead of padding v*M+S-1 zero microbatches.
            inp = x_mb[jnp.minimum(t, m - 1)]
            recirc = queue[qlen - 1]
            buf = buf.at[0].set(
                jnp.where(t < m, inp.astype(buf.dtype), recirc)
            )
            buf = _constrain(buf, "pipe", BATCH_AXES)

            offs = t - jnp.arange(s)
            r_vec = jnp.clip(offs // m, 0, v - 1).astype(jnp.int32)
            valid = (offs >= 0) & (offs < v * m)
            if has_drop:
                rngs_t = jax.vmap(
                    lambda j: jax.random.fold_in(jax.random.fold_in(drop_rng, t), j)
                )(jnp.arange(s))
            else:
                rngs_t = jnp.zeros((s,), jnp.uint32)  # unused placeholder
            out, aux_delta = tick_compute(stacked, r_vec, buf, rngs_t)
            aux_acc = aux_acc + jnp.sum(aux_delta * valid.astype(jnp.float32))
            y = out[s - 1]
            queue = _constrain(
                jnp.roll(queue, 1, axis=0).at[0].set(y), None, BATCH_AXES
            )
            buf_next = _constrain(jnp.roll(out, 1, axis=0), "pipe", BATCH_AXES)
            return (buf_next, queue, aux_acc), y

        buf0 = _constrain(
            jnp.zeros((s, mb) + x.shape[1:], x.dtype), "pipe", BATCH_AXES
        )
        queue0 = _constrain(
            jnp.zeros((qlen, mb) + x.shape[1:], x.dtype), None, BATCH_AXES
        )
        (_, _, aux_sum), ys = jax.lax.scan(
            tick,
            (buf0, queue0, jnp.zeros((), jnp.float32)),
            jnp.arange(ticks),
        )
        # Each microbatch contributes one aux term per virtual stage pass;
        # normalize to the plain path's per-batch value (cf. SpmdPipeline).
        aux = aux0 + aux_sum / m
        # Microbatch t of the final repeat exits at tick (v-1)*M + t + S - 1.
        out = ys[(v - 1) * m + s - 1 :].reshape((m * mb,) + ys.shape[2:])
        return _constrain(out, BATCH_AXES), aux
