"""Partition-spec derivation: name rules + FSDP/ZeRO overlays (SURVEY C4–C5).

Pipeline for deciding where every array lives:

1. **Model rules** (optional): regex ``(pattern, PartitionSpec)`` pairs
   matched against the param's path name — how TP expresses Megatron
   column/row splits. First match wins; no match → replicated.
2. **FSDP overlay** (``param_sharding="fsdp"``): any dimension not already
   sharded gets the ``fsdp`` axis on the largest divisible dim. Leaves
   smaller than ``min_size`` stay replicated (collective latency >> memory
   saved).
3. **Optimizer state** mirrors param specs by path-suffix matching (optax
   states embed params-shaped subtrees, e.g. ``.../mu/<param path>``);
   ``zero1`` instead *shards* those mirrors over ``fsdp`` while params stay
   replicated.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config.schema import ParallelConfig
from frl_distributed_ml_scaffold_tpu.utils.trees import named_tree_map, tree_path_names


@dataclass(frozen=True)
class PartitionRules:
    """Ordered regex → PartitionSpec rules (first match wins)."""

    rules: tuple[tuple[str, P], ...] = ()

    def match(self, name: str) -> P | None:
        for pattern, spec in self.rules:
            if re.search(pattern, name):
                return spec
        return None


def fsdp_spec_for(
    shape: Sequence[int],
    base: P,
    *,
    axis: str = "fsdp",
    axis_size: int,
    min_size: int,
) -> P:
    """Overlay the fsdp axis onto ``base`` for an array of ``shape``.

    Picks the largest dimension that is (a) unsharded in ``base`` and
    (b) divisible by ``axis_size``. Ties break toward the *first* such dim
    (usually the input/feature dim, giving all-gather-friendly layouts).
    """
    if axis_size <= 1 or int(np.prod(shape)) < min_size:
        return base
    entries = list(base) + [None] * (len(shape) - len(base))
    # Already sharded over this axis (e.g. ZeRO-1 overlay on FSDP params):
    # nothing to add — a mesh axis can appear at most once in a spec.
    if any(axis == e or (isinstance(e, tuple) and axis in e) for e in entries):
        return P(*entries)
    candidates = [
        i
        for i, (dim, e) in enumerate(zip(shape, entries))
        if e is None and dim % axis_size == 0 and dim >= axis_size
    ]
    if not candidates:
        return base
    best = max(candidates, key=lambda i: shape[i])
    entries[best] = axis
    return P(*entries)


def param_specs(
    params: Any,
    parallel: ParallelConfig,
    mesh: Mesh,
    rules: PartitionRules | None = None,
) -> Any:
    """PartitionSpec pytree for the parameters."""
    fsdp_size = mesh.shape["fsdp"]

    def decide(name: str, leaf) -> P:
        base = (rules.match(name) if rules else None) or P()
        if parallel.param_sharding == "fsdp":
            return fsdp_spec_for(
                leaf.shape,
                base,
                axis_size=fsdp_size,
                min_size=parallel.fsdp_min_size,
            )
        if parallel.param_sharding == "replicated":
            return base
        raise ValueError(f"unknown param_sharding {parallel.param_sharding!r}")

    return named_tree_map(decide, params)


def opt_state_specs(
    opt_state_shapes: Any,
    params: Any,
    p_specs: Any,
    parallel: ParallelConfig,
    mesh: Mesh,
) -> Any:
    """PartitionSpec pytree for the optimizer state.

    ``opt_state_shapes`` should come from ``jax.eval_shape(tx.init, params)``
    so no real memory is allocated. Leaves are matched to params by path
    suffix: optax embeds params-shaped trees (``mu``, ``nu``, trace, …) whose
    key paths end with the param's own path.
    """
    param_names = tree_path_names(params)
    spec_leaves = jax.tree.leaves(p_specs, is_leaf=lambda x: isinstance(x, P))
    param_shapes = [l.shape for l in jax.tree.leaves(params)]
    # Longest path first: with nested modules, "Block_0/Dense_0/kernel" must
    # win over a sibling "Dense_0/kernel" that is also a suffix. The shape
    # check rejects any remaining same-suffix/different-array collisions.
    by_name = sorted(
        zip(param_names, spec_leaves, param_shapes), key=lambda t: -len(t[0])
    )
    fsdp_size = mesh.shape["fsdp"]
    unmatched: list[str] = []

    def decide(name: str, leaf) -> P:
        if not hasattr(leaf, "shape") or leaf.shape == ():
            return P()  # step counts etc.
        matched: P | None = None
        for pname, pspec, pshape in by_name:
            if (name.endswith("/" + pname) or name == pname) and leaf.shape == pshape:
                matched = pspec
                break
        if matched is None:
            # Suffix matching relies on optax states embedding param-shaped
            # subtrees under param-suffixed paths; optimizers that don't
            # (factored states, custom wrappers) land here and stay
            # replicated. Silent replication is a ZeRO no-op — record any
            # leaf big enough that sharding it would have mattered (only
            # when the mesh could have sharded it at all: fsdp > 1).
            if fsdp_size > 1 and int(np.prod(leaf.shape)) >= parallel.fsdp_min_size:
                unmatched.append(name)
            return P()
        if parallel.opt_sharding == "zero1":
            # ZeRO-1: shard the state mirror over fsdp even though params
            # aren't. (If params are already fsdp-sharded this is a no-op
            # overlay on top of the inherited spec.)
            return fsdp_spec_for(
                leaf.shape,
                matched,
                axis_size=fsdp_size,
                min_size=parallel.fsdp_min_size,
            )
        if parallel.opt_sharding == "like_params":
            return matched
        raise ValueError(f"unknown opt_sharding {parallel.opt_sharding!r}")

    specs = named_tree_map(decide, opt_state_shapes)
    if unmatched:
        from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

        get_logger().warning(
            "opt_state_specs: %d optimizer-state leaves >= fsdp_min_size "
            "did not suffix-match any parameter and stay REPLICATED "
            "(opt_sharding=%r is a no-op for them): %s",
            len(unmatched),
            parallel.opt_sharding,
            ", ".join(unmatched[:5]) + (", ..." if len(unmatched) > 5 else ""),
        )
    return specs


def block_param_slice_shapes(params_shapes: Any, model_axis: int) -> set[tuple]:
    """Legal all_gather output shapes for a blockwise overlap schedule:
    per-block slices of the stacked ``blocks`` params (scan-sliced —
    leading layer dim dropped), or whole leaves for non-scanned families,
    with Megatron-split dims also allowed at ``1/model_axis`` (the
    per-shard view inside a composed schedule's shard_map regions).

    This is the shape set ``analysis.pins.assert_schedule`` and the
    graft-lint runner check blockwise gathers against — kept next to the
    spec derivation so "which dims a block gather may move" has one owner.
    """
    import jax

    slices: set[tuple] = set()
    blocks = getattr(params_shapes, "get", lambda *_: None)("blocks")
    leaves = jax.tree.leaves(blocks) if blocks is not None else []
    if not leaves:  # non-scanned families: any full param leaf is a block
        leaves = jax.tree.leaves(params_shapes)
        for l in leaves:
            slices.add(tuple(l.shape))
    for l in leaves:
        s = tuple(l.shape[1:]) if blocks is not None else tuple(l.shape)
        slices.add(s)
        if model_axis > 1:
            for i, d in enumerate(s):
                if d % model_axis == 0:
                    slices.add(s[:i] + (d // model_axis,) + s[i + 1:])
    return slices


def shardings_from_specs(specs: Any, mesh: Mesh) -> Any:
    """PartitionSpec pytree → NamedSharding pytree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params_for_serving(params: Any, env: Any, rules: PartitionRules) -> Any:
    """Place a params tree onto a serving mesh per the model's TP rules
    — the one-call version of derive-specs + device_put that every
    decode consumer (serving/engine.py callers, tools/serve_bench.py,
    the sharded-decode tests) otherwise hand-rolls.

    Serving has no optimizer state and no FSDP overlay — params are
    either replicated or Megatron-sharded over ``model`` — so the overlay
    config is the default ``ParallelConfig()`` (replicated base) and only
    ``rules`` decides placement. The head-sharded KV cache then follows
    from these kernels at trace time (models/gpt.py pins the layout).

    Device-resident SHARDED trees (a live training layout — the
    train→serve handoff, ISSUE 15) route through the redistribution
    service: each leaf moves only the shard deltas the destination
    layout lacks, never a replicated host round-trip. Host (numpy)
    trees — and multi-process trees whose shards this process cannot
    address (the executor is single-controller) — keep the direct
    shard-wise ``device_put``."""
    leaves = jax.tree.leaves(params)
    if any(
        isinstance(getattr(l, "sharding", None), NamedSharding)
        for l in leaves
    ) and all(
        getattr(l, "is_fully_addressable", True) for l in leaves
    ):
        from frl_distributed_ml_scaffold_tpu.redistribute import (
            train_to_serve,
        )

        placed, _plan = train_to_serve(params, env, rules)
        return placed
    specs = param_specs(params, ParallelConfig(), env.mesh, rules)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(env.mesh, s)),
        params,
        specs,
    )
