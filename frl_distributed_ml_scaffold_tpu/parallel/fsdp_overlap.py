"""Overlap-scheduled FSDP: explicit blockwise all-gather / reduce-scatter.

The plain ``param_sharding="fsdp"`` path hands parameter gathering to GSPMD:
sharded params flow into the jit program and the partitioner inserts
all-gathers wherever it likes — in practice often hoisted to the top of the
program (full params materialized up front) and serialized against compute.
SimpleFSDP (arxiv 2411.00284) shows that making the per-block collectives
explicit recovers the hidden communication time: gather block k's shards
immediately before block k's compute, free them after use, and
reduce-scatter block k's gradients straight back into shards, so the
latency-hiding scheduler can run block k+1's gather under block k's compute.

Mechanics here (``parallel.fsdp_overlap=true``):

- ``gather_leaf`` opens a one-leaf ``shard_map`` region over the current
  mesh and calls the ``dist/collectives.py`` façade's ``all_gather`` over
  the ``fsdp`` axis — an *explicit* AllGather pinned to the consuming
  block, visible in the jaxpr (the blockwise-ness test keys on this).
  JAX's transpose of a tiled ``all_gather`` is ``psum_scatter``, so the
  backward is the matching explicit ReduceScatter for free; cross-axis
  gradient reductions (the ``data`` allreduce) stay with GSPMD, which
  already inserts them for the non-overlap path.
- Gathered leaves are tagged ``checkpoint_name(..., "fsdp_gathered")`` and
  every hooked block is wrapped in ``nn.remat`` with (by default) the
  ``save_anything_except_these_names`` policy: forward residuals are kept
  as usual but the gathered full params are NOT saved — the backward
  re-gathers (standard FSDP reshard-after-forward), which is what keeps
  peak live params at ~one block instead of the whole model.
- Models expose *blockwise apply hooks* (``param_hooks`` on GPT/ResNet):
  the scanned transformer stack applies the gather per scan iteration via
  ``nn.map_variables`` (so each layer's slice is gathered inside the loop
  body — the form XLA's collective pipeliner hoists one iteration ahead,
  the ``fsdp_prefetch=1`` schedule), and the ResNet block loop creates a
  per-block hook whose gather is tied by ``optimization_barrier`` to the
  output of block ``k - 1 - prefetch`` — a structurally enforced prefetch
  window.

Everything is correctness-gated on the CPU sim (tests/test_fsdp_overlap.py:
numerics vs the GSPMD path, jaxpr blockwise-ness, mesh compositions); the
on-chip step-time A/B rides ``tools/perf_sweep.py gpt2_fsdp_overlap``
(BACKLOG).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist import collectives
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    current_mesh_env,
    shard_map_compat,
)

#: checkpoint_name tag on gathered params; the remat policy drops exactly
#: these so the backward re-gathers instead of keeping full params alive.
GATHER_NAME = "fsdp_gathered"

#: Model families with blockwise apply hooks wired up.
SUPPORTED_FAMILIES = ("gpt", "resnet")


@dataclass(frozen=True)
class OverlapHooks:
    """What a model needs to run the overlap schedule.

    ``block_hook`` — ``nn.map_variables`` trans_in_fn for a scanned block
    stack (receives ``{"params": <sliced block params>}``); built from the
    per-block (scan-sliced) PartitionSpecs.
    ``hook_factory`` — ``factory(token) -> trans_in_fn`` for Python-loop
    block stacks (ResNet): ``token`` is the activation whose completion
    gates this block's gather (the prefetch window).
    """

    prefetch: int = 1
    block_hook: Callable[[dict], dict] | None = None
    hook_factory: Callable[[Any], Callable[[dict], dict]] | None = None


def gathered_spec(spec: P, axis: str = "fsdp") -> P:
    """``spec`` with every occurrence of ``axis`` removed (gather target)."""
    out = []
    for e in spec:
        if e == axis:
            out.append(None)
        elif isinstance(e, tuple):
            rest = tuple(a for a in e if a != axis)
            out.append(rest if rest else None)
        else:
            out.append(e)
    return P(*out)


@jax.custom_vjp
def _sched_gate(x, token):
    """Scheduling-only dependence of ``x`` on ``token``: XLA may not issue
    ``x``'s consumers before ``token`` exists, but the VALUE is just ``x``
    — so the custom VJP passes the cotangent straight through (this jax's
    ``optimization_barrier`` has no differentiation rule of its own, and
    the token's true derivative is zero anyway)."""
    x2, _ = lax.optimization_barrier((x, token))
    return x2


def _sched_gate_fwd(x, token):
    return _sched_gate(x, token), token


def _sched_gate_bwd(token, dy):
    import jax.numpy as jnp

    return dy, jnp.zeros_like(token)


_sched_gate.defvjp(_sched_gate_fwd, _sched_gate_bwd)


def _axis_dim(spec: P, axis: str) -> int | None:
    for i, e in enumerate(spec):
        if e == axis or (isinstance(e, tuple) and axis in e):
            return i
    return None


def strip_scan_dim(spec: P) -> P:
    """Spec of one scan-sliced block leaf from its stacked spec (drop the
    leading layer-dim entry). If the fsdp overlay landed on the layer dim
    itself the sliced leaf is simply unsharded — the hook passes it through
    and GSPMD keeps handling it."""
    entries = list(spec)
    return P(*entries[1:]) if entries else P()


def gather_leaf(x: jax.Array, spec: P, *, axis: str = "fsdp", token=None):
    """Explicit all-gather of one sharded leaf over ``axis``.

    Identity on leaves whose spec doesn't carry ``axis``. ``token`` (an
    activation) gates when the gather may be *issued*: an
    ``optimization_barrier`` ties the shard read to the token, which is how
    the ResNet loop enforces the ``fsdp_prefetch`` window. The gathered
    value is checkpoint_name-tagged so remat policies can refuse to save it.
    """
    dim = _axis_dim(spec, axis)
    if dim is None:
        return x
    env = current_mesh_env()
    if env is None or env.axis_size(axis) == 1:
        return x
    if token is not None:
        # The gate's only job is scheduling: the shard becomes
        # data-dependent on the token, so XLA cannot issue this gather
        # before the token's producer block has finished.
        x = _sched_gate(x, token)
    out_spec = gathered_spec(spec, axis)

    def inner(shard):
        return collectives.all_gather(shard, axis, gather_axis=dim, tiled=True)

    y = shard_map_compat(
        inner, mesh=env.mesh, in_specs=(spec,), out_specs=out_spec
    )(x)
    return checkpoint_name(y, GATHER_NAME)


def gather_tree(tree: Any, specs: Any, *, axis: str = "fsdp", token=None):
    """``gather_leaf`` over a params subtree with a matching specs subtree."""
    return jax.tree_util.tree_map(
        lambda x, s: gather_leaf(x, s, axis=axis, token=token),
        tree,
        specs,
        is_leaf=lambda t: isinstance(t, P),
    )


def make_scan_block_hook(sliced_specs: Any, *, axis: str = "fsdp"):
    """trans_in_fn for ``nn.map_variables`` around a scanned Block.

    ``sliced_specs`` must mirror one block's param subtree (the stacked
    specs with the leading layer dim stripped — ``strip_scan_dim``).
    Running inside the scan body, this gathers exactly one layer's slice
    per iteration: the blockwise schedule.
    """

    def hook(variables: dict) -> dict:
        out = dict(variables)
        out["params"] = gather_tree(variables["params"], sliced_specs, axis=axis)
        return out

    return hook


def make_shape_hook_factory(parallel, axis_size: int, *, axis: str = "fsdp"):
    """Per-block hook factory for non-scanned block stacks (ResNet).

    ResNet has no TP rules by design, so each leaf's spec is derived from
    its shape with exactly the machinery ``param_specs`` used
    (``fsdp_spec_for`` with base=P()) — the hook's view of "which dim is
    sharded" provably matches the state shardings. ``factory(token)``
    closes over the prefetch-window token for one block.
    """
    from frl_distributed_ml_scaffold_tpu.parallel.partition import fsdp_spec_for

    def leaf_spec(leaf) -> P:
        return fsdp_spec_for(
            leaf.shape,
            P(),
            axis=axis,
            axis_size=axis_size,
            min_size=parallel.fsdp_min_size,
        )

    def factory(token):
        def hook(variables: dict) -> dict:
            out = dict(variables)
            out["params"] = jax.tree_util.tree_map(
                lambda x: gather_leaf(x, leaf_spec(x), axis=axis, token=token),
                variables["params"],
            )
            return out

        return hook

    return factory


def overlap_remat_policy(block_remat: str = "none"):
    """Checkpoint policy for a hooked block: whatever the configured
    per-block remat mode saves, gathered params are never among it.

    - "none"      — save every intermediate EXCEPT the gathered params
                    (memory profile of the un-rematted block, minus the
                    full-params residency; backward re-gathers).
    - "full"      — save nothing (model.block_remat=full semantics; the
                    gathered params are recomputed along with the rest).
    - "save_attn" — save only the attention-sublayer outputs (gathers
                    excluded by construction).
    """
    if block_remat == "none":
        return jax.checkpoint_policies.save_anything_except_these_names(
            GATHER_NAME
        )
    if block_remat == "full":
        return None
    if block_remat == "save_attn":
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    raise KeyError(
        f"unknown block_remat {block_remat!r} for the overlap path "
        "(none | full | save_attn)"
    )


def validate_block_schedule(cfg, *, prefetch: int) -> None:
    """Fail fast on configs a blockwise gather rule cannot honor (a silent
    fallback to the GSPMD schedule would invalidate any A/B built on it).
    Called by the schedule layer (parallel/schedule.py
    ``validate_schedule_config``) for every ``granularity="block"``
    gather; the legacy knob path reaches it through
    ``validate_overlap_config``."""
    family = getattr(cfg.model, "family", None)
    if cfg.parallel.param_sharding != "fsdp":
        raise ValueError(
            "parallel.fsdp_overlap=true requires param_sharding='fsdp' "
            f"(got {cfg.parallel.param_sharding!r}): the overlap schedule "
            "is a rewrite of how fsdp-sharded params are gathered, not a "
            "sharding strategy of its own"
        )
    if family not in SUPPORTED_FAMILIES:
        raise ValueError(
            f"parallel.fsdp_overlap=true: model family {family!r} has no "
            f"blockwise apply hooks (supported: {SUPPORTED_FAMILIES})"
        )
    if (
        getattr(cfg.model, "pipeline_stages", 1) > 1
        and getattr(cfg.model, "pipeline_impl", "spmd") != "mpmd"
    ):
        # The SPMD stage-vmap path owns its own block schedule; the MPMD
        # backend (ISSUE 14) lowers the blockwise gathers INSIDE each
        # per-stage program, where they compose as in the plain stack.
        raise ValueError(
            "parallel.fsdp_overlap composes with dp/fsdp/tp meshes but not "
            "with the SPMD pipeline backend (the stage-vmap path owns its "
            "own block schedule); set model.pipeline_stages=1 or "
            "model.pipeline_impl='mpmd'"
        )
    if prefetch < 0:
        raise ValueError(
            f"parallel.fsdp_prefetch must be >= 0, got {prefetch}"
        )


def validate_overlap_config(cfg) -> None:
    """Legacy-knob adapter: validate ``parallel.fsdp_overlap=true`` by
    deriving its schedule declaration and running the schedule layer's
    checks (parallel/schedule.py owns the full contradiction set — e.g.
    the prefetch-vs-block-count window bound)."""
    from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
        OverlapSchedule,
        gather,
        scatter,
        validate_schedule_config,
    )

    sched = OverlapSchedule.build(
        gather("fsdp", granularity="block",
               prefetch=cfg.parallel.fsdp_prefetch),
        scatter("fsdp"),
    )
    validate_schedule_config(sched, cfg)
