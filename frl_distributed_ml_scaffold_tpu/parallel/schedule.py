"""Unified overlap-schedule layer: ONE declarative per-axis gather/scatter
schedule for FSDP x TP x low precision (ROADMAP item 2).

``parallel/fsdp_overlap.py`` and ``parallel/tp_overlap.py`` are two
hand-built instances of the same perf idea — re-express a monolithic GSPMD
collective blockwise so it hides under compute. This module folds them into
one declaration a model requests per axis, in the spirit of SimpleFSDP's
compile-driven wrapping (arXiv 2411.00284) and veScale's eager-SPMD
consistency model (arXiv 2509.07003):

    schedule = OverlapSchedule.build(
        gather("fsdp", granularity="block", prefetch=1),
        scatter("fsdp"),
        gather("model", granularity="ring_chunk", lowp="int8"),
        scatter("model", lowp="int8"),
    )

and ONE executor lowers it onto the existing machinery:

- ``granularity="block"`` — per-block explicit ``all_gather`` of the
  axis's param shards inside the consuming block's scan iteration /
  Python-loop body, with an ``optimization_barrier``-enforced ``prefetch``
  window and a remat policy that refuses to save the gathered full params
  (parallel/fsdp_overlap.py's mechanics; the backward ``scatter`` is the
  gather's transpose, an explicit ``reduce_scatter``).
- ``granularity="ring_chunk"`` — the four per-block axis matmuls become
  bidirectional ``ppermute`` collective-matmul rings with
  mutually-transposed VJPs (ops/collective_matmul.py via
  parallel/tp_overlap.py's dot_general injection), the residual stream
  staying sharded over the axis between them.
- ``lowp`` — low precision is an attribute of the TRANSFER, not a
  per-ring hook: a ring-chunk rule with ``lowp`` set streams quantized
  chunks + scalar scales (ops/quantization.py) on every hop, forward and
  backward.

The legacy knobs (``parallel.fsdp_overlap``, ``fsdp_prefetch``,
``tp_overlap``, ``low_precision``) keep their exact semantics: they are
derived into this schedule by ``schedule_from_config`` and the old modules
are thin adapters over it. A ``parallel.schedule`` string declares the
same thing directly (``parse_schedule`` grammar below) and is pinned
program-identical to the knob spelling in tests/test_schedule.py.

Contradictory declarations fail at BUILD time with a typed
``ScheduleError`` naming the offending schedule attribute (e.g. ``lowp``
without any ring axis, a prefetch window larger than the block count) —
never as a shape error deep in the scan body.

The declaration is also what the static layer verifies: ``analysis.pins
.assert_schedule`` derives the expected collective classes/counts/bytes
from the schedule itself (analysis/schedule.py), and the perf ledger's
rows carry ``describe()`` so census rows are per-schedule, not
per-recipe.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from frl_distributed_ml_scaffold_tpu.ops.quantization import resolve_lowp

#: Transfer granularities a gather/scatter rule may declare.
GRANULARITIES = ("block", "ring_chunk")

#: Reductions a scatter rule may declare (the rings/reduce-scatter are sums).
REDUCE_OPS = ("sum",)

#: Axes with a lowering: blockwise param gathers ride ``fsdp``; collective-
#: matmul rings ride ``model``. Other mesh axes have no overlap machinery.
BLOCK_AXES = ("fsdp",)
RING_AXES = ("model",)


class ScheduleError(ValueError):
    """A malformed or contradictory overlap-schedule declaration.

    ``attribute`` names the schedule attribute at fault (``axis``,
    ``granularity``, ``prefetch``, ``lowp``, ``reduce``, ``schedule`` for
    whole-declaration conflicts) so config tooling can point at the knob
    instead of the user diffing a shape error out of a scan body.
    """

    def __init__(self, attribute: str, message: str):
        self.attribute = attribute
        super().__init__(f"overlap schedule [{attribute}]: {message}")


@dataclass(frozen=True)
class GatherRule:
    """One axis's gather declaration: how this axis's sharded operands
    reach their consumers. ``prefetch`` applies to ``block`` granularity
    (how many blocks ahead a gather may be issued); ``lowp`` to
    ``ring_chunk`` (quantize every chunk transfer)."""

    axis: str
    granularity: str = "block"
    prefetch: int = 1
    lowp: str | None = None


@dataclass(frozen=True)
class ScatterRule:
    """One axis's scatter declaration: how results/gradients return to
    shards — the gather's transpose (explicit reduce_scatter for
    ``block``, the rotating matmul-reduce-scatter ring for
    ``ring_chunk``)."""

    axis: str
    reduce: str = "sum"
    lowp: str | None = None


def gather(
    axis: str,
    *,
    granularity: str = "block",
    prefetch: int = 1,
    lowp: str | None = None,
) -> GatherRule:
    """Declare one axis's gather. Structural errors (unknown granularity,
    negative prefetch, lowp on a non-ring transfer) raise ``ScheduleError``
    here, at declaration time."""
    if granularity not in GRANULARITIES:
        raise ScheduleError(
            "granularity",
            f"unknown granularity {granularity!r} for axis {axis!r} "
            f"(known: {GRANULARITIES})",
        )
    if prefetch < 0:
        raise ScheduleError(
            "prefetch",
            f"parallel.fsdp_prefetch must be >= 0, got {prefetch} "
            f"(axis {axis!r})",
        )
    if granularity != "block" and prefetch != 1:
        raise ScheduleError(
            "prefetch",
            f"prefetch={prefetch} declared on a {granularity!r} gather of "
            f"axis {axis!r}: the prefetch window is a block-granularity "
            "attribute (ring chunks stream hop by hop)",
        )
    lowp = resolve_lowp(lowp)
    if lowp is not None and granularity != "ring_chunk":
        raise ScheduleError(
            "lowp",
            f"lowp={lowp!r} declared on a {granularity!r} gather of axis "
            f"{axis!r}: low precision is a ring-chunk transfer attribute "
            "(blockwise param gathers move master-dtype shards)",
        )
    return GatherRule(axis=axis, granularity=granularity, prefetch=prefetch,
                      lowp=lowp)


def scatter(
    axis: str, *, reduce: str = "sum", lowp: str | None = None
) -> ScatterRule:
    """Declare one axis's scatter (the gather's transpose)."""
    if reduce not in REDUCE_OPS:
        raise ScheduleError(
            "reduce",
            f"unknown reduce {reduce!r} for axis {axis!r} "
            f"(known: {REDUCE_OPS})",
        )
    return ScatterRule(axis=axis, reduce=reduce, lowp=resolve_lowp(lowp))


@dataclass(frozen=True)
class OverlapSchedule:
    """The full declaration: per-axis gather/scatter rules.

    Construct via ``build`` (or ``parse_schedule``) so the cross-rule
    invariants hold; the Trainer derives one from the config
    (``schedule_from_config``) and hands it to ``hooked_model`` — the
    executor that lowers it onto the blockwise-gather and
    collective-matmul machinery.
    """

    gathers: tuple[GatherRule, ...] = ()
    scatters: tuple[ScatterRule, ...] = ()

    # ----------------------------------------------------------- builders

    @staticmethod
    def build(*rules: GatherRule | ScatterRule) -> "OverlapSchedule":
        gs: list[GatherRule] = []
        ss: list[ScatterRule] = []
        for r in rules:
            if isinstance(r, GatherRule):
                gs.append(r)
            elif isinstance(r, ScatterRule):
                ss.append(r)
            else:
                raise ScheduleError(
                    "schedule", f"not a gather/scatter rule: {r!r}"
                )
        sched = OverlapSchedule(gathers=tuple(gs), scatters=tuple(ss))
        sched._check_structure()
        return sched

    def _check_structure(self) -> None:
        for rules, kind in ((self.gathers, "gather"),
                            (self.scatters, "scatter")):
            axes = [r.axis for r in rules]
            dup = {a for a in axes if axes.count(a) > 1}
            if dup:
                raise ScheduleError(
                    "axis",
                    f"duplicate {kind} rules for axes {sorted(dup)} — one "
                    "declaration per axis",
                )
        for g in self.gathers:
            if g.granularity == "block" and g.axis not in BLOCK_AXES:
                raise ScheduleError(
                    "axis",
                    f"blockwise gathers are the param-shard schedule of "
                    f"the fsdp axis; axis {g.axis!r} has no block lowering "
                    f"(block axes: {BLOCK_AXES})",
                )
            if g.granularity == "ring_chunk" and g.axis not in RING_AXES:
                raise ScheduleError(
                    "axis",
                    f"ring-chunk gathers are the collective-matmul "
                    f"schedule of the model axis; axis {g.axis!r} has no "
                    f"ring lowering (ring axes: {RING_AXES})",
                )
        gather_axes = {g.axis for g in self.gathers}
        for s in self.scatters:
            if s.axis not in gather_axes:
                raise ScheduleError(
                    "axis",
                    f"scatter on axis {s.axis!r} without a matching gather "
                    "— a scatter is the transpose of its axis's gather",
                )
        # ``lowp`` is a property of the axis's WIRE: the forward ring and
        # its transpose quantize together (a block gather's lowp is
        # already refused in ``gather``, so a lowp scatter on a block
        # axis lands here as a mismatch).
        for g in self.gathers:
            s = self.scatter_on(g.axis)
            if s is not None and s.lowp != g.lowp:
                raise ScheduleError(
                    "lowp",
                    f"axis {g.axis!r} declares gather lowp={g.lowp!r} but "
                    f"scatter lowp={s.lowp!r} — the forward ring and its "
                    "transpose quantize the same wire",
                )

    # ------------------------------------------------------------ lookups

    def gather_on(self, axis: str) -> GatherRule | None:
        for g in self.gathers:
            if g.axis == axis:
                return g
        return None

    def scatter_on(self, axis: str) -> ScatterRule | None:
        for s in self.scatters:
            if s.axis == axis:
                return s
        return None

    def block_gather(self) -> GatherRule | None:
        """The (at most one) blockwise param-gather rule."""
        for g in self.gathers:
            if g.granularity == "block":
                return g
        return None

    def ring_gather(self) -> GatherRule | None:
        """The (at most one) ring-chunk rule."""
        for g in self.gathers:
            if g.granularity == "ring_chunk":
                return g
        return None

    # --------------------------------------------------------- rendering

    def render(self) -> str:
        """Canonical declaration string (``parse_schedule``'s inverse)."""
        parts = []
        for g in self.gathers:
            attrs = [g.axis, g.granularity]
            if g.granularity == "block":
                attrs.append(f"prefetch={g.prefetch}")
            if g.lowp is not None:
                attrs.append(f"lowp={g.lowp}")
            parts.append(f"gather({','.join(attrs)})")
        for s in self.scatters:
            attrs = [s.axis, f"reduce={s.reduce}"]
            if s.lowp is not None:
                attrs.append(f"lowp={s.lowp}")
            parts.append(f"scatter({','.join(attrs)})")
        return "+".join(parts)

    def short(self) -> str:
        """Compact per-axis summary for table columns, e.g.
        ``fsdp:block(p1)+model:ring(int8)``."""
        parts = []
        for g in self.gathers:
            if g.granularity == "block":
                parts.append(f"{g.axis}:block(p{g.prefetch})")
            else:
                parts.append(
                    f"{g.axis}:ring({g.lowp})" if g.lowp
                    else f"{g.axis}:ring"
                )
        return "+".join(parts) or "gspmd"

    def describe(self) -> dict:
        """JSON-able descriptor — the per-schedule identity the perf
        ledger's rows and graft-lint's reports carry."""
        return {
            "declared": self.render(),
            "short": self.short(),
            "gathers": [
                {"axis": g.axis, "granularity": g.granularity,
                 "prefetch": g.prefetch, "lowp": g.lowp or "off"}
                for g in self.gathers
            ],
            "scatters": [
                {"axis": s.axis, "reduce": s.reduce, "lowp": s.lowp or "off"}
                for s in self.scatters
            ],
        }


_TERM_RE = re.compile(r"^(gather|scatter)\(([^()]*)\)$")


def parse_schedule(text: str) -> OverlapSchedule:
    """Parse the declaration grammar::

        gather(AXIS[,GRANULARITY][,prefetch=N][,lowp=FMT])
        scatter(AXIS[,reduce=OP][,lowp=FMT])

    joined by ``+`` (whitespace-insensitive), e.g.
    ``gather(fsdp,block,prefetch=1)+scatter(fsdp)+
    gather(model,ring_chunk,lowp=int8)+scatter(model,lowp=int8)``.
    """
    terms = [t for t in re.sub(r"\s+", "", text).split("+") if t]
    if not terms:
        raise ScheduleError("schedule", f"empty schedule string {text!r}")
    rules: list[GatherRule | ScatterRule] = []
    for term in terms:
        m = _TERM_RE.match(term)
        if not m:
            raise ScheduleError(
                "schedule",
                f"cannot parse term {term!r} (expected "
                "gather(axis,...) or scatter(axis,...))",
            )
        kind, body = m.group(1), m.group(2)
        args = [a for a in body.split(",") if a]
        if not args:
            raise ScheduleError(
                "axis", f"{kind}() needs at least an axis name: {term!r}"
            )
        pos: list[str] = []
        kw: dict[str, str] = {}
        for a in args:
            if "=" in a:
                k, v = a.split("=", 1)
                kw[k] = v
            elif kw:
                raise ScheduleError(
                    "schedule",
                    f"positional attr after keyword attr in {term!r}",
                )
            else:
                pos.append(a)
        axis = pos[0]
        if kind == "gather":
            if len(pos) > 2:
                raise ScheduleError(
                    "schedule", f"too many positional attrs in {term!r}"
                )
            granularity = pos[1] if len(pos) > 1 else \
                kw.pop("granularity", "block")
            unknown = set(kw) - {"prefetch", "lowp"}
            if unknown:
                raise ScheduleError(
                    "schedule",
                    f"unknown gather attr(s) {sorted(unknown)} in {term!r}",
                )
            try:
                prefetch = int(kw.get("prefetch", "1"))
            except ValueError:
                raise ScheduleError(
                    "prefetch",
                    f"prefetch must be an integer: {term!r}",
                ) from None
            rules.append(gather(
                axis, granularity=granularity, prefetch=prefetch,
                lowp=kw.get("lowp"),
            ))
        else:
            if len(pos) > 1:
                raise ScheduleError(
                    "schedule", f"too many positional attrs in {term!r}"
                )
            unknown = set(kw) - {"reduce", "lowp"}
            if unknown:
                raise ScheduleError(
                    "schedule",
                    f"unknown scatter attr(s) {sorted(unknown)} in {term!r}",
                )
            rules.append(scatter(
                axis, reduce=kw.get("reduce", "sum"), lowp=kw.get("lowp"),
            ))
    return OverlapSchedule.build(*rules)


# ------------------------------------------------------- config derivation


def schedule_from_config(cfg) -> OverlapSchedule | None:
    """The config's declared schedule, or None when no overlap schedule is
    requested.

    ``parallel.schedule="auto"`` (the default) derives the schedule from
    the legacy knobs — ``fsdp_overlap``/``fsdp_prefetch`` become the
    blockwise fsdp pair, ``tp_overlap``/``low_precision`` the ring-chunk
    model pair — preserving their exact semantics through the adapters.
    An explicit declaration string replaces the derivation and must AGREE
    with any legacy knob that is also set (a contradiction is a
    ``ScheduleError``, not a silent override).

    Build-time contradiction checks live here and in
    ``validate_schedule_config`` — e.g. ``parallel.low_precision``
    without any ring axis refuses loudly instead of silently changing
    nothing.
    """
    p = cfg.parallel
    declared = getattr(p, "schedule", "auto")
    if declared in ("", "auto"):
        return _schedule_from_knobs(p)
    sched = parse_schedule(declared)
    # The declaration replaces the derivation; any legacy knob that IS
    # set must agree with it, per knob (so e.g. low_precision=int8 next
    # to a string that declares the int8 ring is consistent even with
    # tp_overlap left false).
    block, ring = sched.block_gather(), sched.ring_gather()
    if p.fsdp_overlap and (
        block is None or block.prefetch != p.fsdp_prefetch
    ):
        raise ScheduleError(
            "schedule",
            f"parallel.schedule={declared!r} contradicts "
            f"parallel.fsdp_overlap=true/fsdp_prefetch={p.fsdp_prefetch} "
            f"(the knobs derive gather(fsdp,block,prefetch="
            f"{p.fsdp_prefetch})) — declare one or the other",
        )
    if p.tp_overlap and ring is None:
        raise ScheduleError(
            "schedule",
            f"parallel.schedule={declared!r} contradicts "
            "parallel.tp_overlap=true (the knob derives "
            "gather(model,ring_chunk)) — declare one or the other",
        )
    lowp = resolve_lowp(p.low_precision)
    if lowp is not None:
        if ring is None:
            _refuse_lowp_without_rings(p)
        if ring.lowp != lowp:
            raise ScheduleError(
                "lowp",
                f"parallel.schedule={declared!r} contradicts "
                f"parallel.low_precision={p.low_precision!r}: the declared "
                f"ring carries lowp={ring.lowp!r} — declare one or the "
                "other",
            )
    return sched


def _schedule_from_knobs(p) -> OverlapSchedule | None:
    rules: list[GatherRule | ScatterRule] = []
    if p.fsdp_overlap:
        rules.append(gather("fsdp", granularity="block",
                            prefetch=p.fsdp_prefetch))
        rules.append(scatter("fsdp"))
    if p.tp_overlap:
        lowp = resolve_lowp(p.low_precision)
        rules.append(gather("model", granularity="ring_chunk", lowp=lowp))
        rules.append(scatter("model", lowp=lowp))
    elif p.low_precision != "none":
        _refuse_lowp_without_rings(p)
    if not rules:
        return None
    return OverlapSchedule.build(*rules)


def _refuse_lowp_without_rings(p) -> None:
    # Keeps the Trainer's historical phrasing: the knob quantizes the
    # rings; with no ring axis declared it would silently change nothing.
    raise ScheduleError(
        "lowp",
        f"parallel.low_precision={p.low_precision!r} requires a ring-chunk "
        "gather axis (parallel.tp_overlap=true): the low-precision fast "
        "path lives in the collective-matmul rings; there is no GSPMD "
        "low-precision schedule to fall back to",
    )


# ----------------------------------------------------- config validation


def model_block_count(model_cfg) -> int | None:
    """How many hook-able blocks the model family stacks — the bound the
    prefetch window is checked against (None: family without blockwise
    hooks; the family check itself raises elsewhere)."""
    family = getattr(model_cfg, "family", None)
    if family == "gpt":
        return int(model_cfg.num_layers)
    if family == "resnet":
        from frl_distributed_ml_scaffold_tpu.models.resnet import STAGE_SIZES

        sizes = STAGE_SIZES.get(model_cfg.depth)
        return int(sum(sizes)) if sizes else None
    return None


def validate_schedule_config(sched: OverlapSchedule, cfg) -> None:
    """Everything the schedule + config (but not the live mesh) can
    refuse: the legacy adapters' checks, centralized, plus the
    contradictions that used to surface as shape errors in the scan body.
    Mesh-dependent checks (axis sizes, chunk divisibility) stay with the
    hook builders, which see the resolved mesh."""
    block = sched.block_gather()
    ring = sched.ring_gather()
    if block is not None:
        from frl_distributed_ml_scaffold_tpu.parallel.fsdp_overlap import (
            validate_block_schedule,
        )

        validate_block_schedule(cfg, prefetch=block.prefetch)
        n_blocks = model_block_count(cfg.model)
        if n_blocks is not None and block.prefetch > n_blocks:
            raise ScheduleError(
                "prefetch",
                f"prefetch window {block.prefetch} exceeds the model's "
                f"block count {n_blocks} ({cfg.model.family}): there is "
                "nothing to issue that far ahead — shrink "
                "parallel.fsdp_prefetch",
            )
    if ring is not None:
        from frl_distributed_ml_scaffold_tpu.parallel.tp_overlap import (
            validate_ring_schedule,
        )

        validate_ring_schedule(cfg, lowp=ring.lowp)


# ------------------------------------------------------------ the executor


def block_overlap_hooks(rule: GatherRule, cfg, env, params_specs):
    """Lower a blockwise gather rule onto the explicit per-block
    all-gather machinery (parallel/fsdp_overlap.py): the ``OverlapHooks``
    the model families consume via ``nn.map_variables``. The matching
    scatter needs no lowering of its own — JAX's transpose of the tiled
    ``all_gather`` IS the explicit ``reduce_scatter``."""
    import jax
    from jax.sharding import PartitionSpec as P

    from frl_distributed_ml_scaffold_tpu.parallel.fsdp_overlap import (
        OverlapHooks,
        make_scan_block_hook,
        make_shape_hook_factory,
        strip_scan_dim,
    )

    if cfg.model.family == "gpt":
        # The scanned stack's hook gathers one layer's SLICE per scan
        # iteration; its specs are the stacked specs minus the layer dim.
        sliced = jax.tree.map(
            strip_scan_dim,
            params_specs["blocks"],
            is_leaf=lambda t: isinstance(t, P),
        )
        return OverlapHooks(
            prefetch=rule.prefetch,
            block_hook=make_scan_block_hook(sliced, axis=rule.axis),
        )
    # resnet (validate_schedule_config gates the families)
    return OverlapHooks(
        prefetch=rule.prefetch,
        hook_factory=make_shape_hook_factory(
            cfg.parallel, env.axis_size(rule.axis), axis=rule.axis
        ),
    )


def hooked_model(sched: OverlapSchedule, model, cfg, env, params_specs):
    """THE executor: clone ``model`` with every hook the schedule's rules
    lower to — the blockwise param-gather hook (``param_hooks``) and/or
    the collective-matmul dot_general hooks (``tp_overlap``), stacked so
    both schedules run in the same scan body. Apply-only (the hook
    mechanisms cannot create params); init/decode keep the plain model —
    the params tree is identical either way."""
    # Deferred module import so the low-precision mutation gate's
    # monkeypatch of tp_overlap.make_tp_hooks still intercepts the build.
    from frl_distributed_ml_scaffold_tpu.parallel import tp_overlap as _tpo

    out = model
    if sched.block_gather() is not None:
        out = out.clone(
            param_hooks=block_overlap_hooks(
                sched.block_gather(), cfg, env, params_specs
            )
        )
    if sched.ring_gather() is not None:
        out = out.clone(tp_overlap=_tpo.make_tp_hooks(cfg, env))
    return out
