// Native data-loader core (SURVEY C16; §7 hard part 5 "input pipeline
// throughput").
//
// The reference's loader tier does its heavy lifting in native code (the
// torch DataLoader worker pool: C++ decode/collate under a Python
// orchestrator). This is the TPU-side equivalent: the per-sample hot ops —
// shard gather, train-time augmentation (random crop + flip + normalize),
// synthetic batch synthesis — as a multithreaded C++ library. Python
// orchestrates (data/native.py via ctypes), C++ moves the bytes.
//
// Threading model: a fixed worker pool sized to the hardware, work split by
// sample — batches are embarrassingly parallel and each sample's work is
// tens of µs, so per-batch thread spawn would dominate; the pool is spawned
// once at first use and parks on a condition variable between calls.
//
// Build: g++ -O3 -march=native -shared -fPIC (driven by data/native.py,
// cached next to this file).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ----------------------------------------------------------- worker pool

// Each parallel_for publishes a heap-owned Task (function copied in, not
// pointed at) that workers pin via shared_ptr. Per-task atomic counters
// mean a straggler from a finished call can at worst fetch an exhausted
// index from the OLD task and immediately park — it can never steal work
// from, or run the function of, a later call (the back-to-back
// gather-then-augment pattern in imagenet.batch()).
struct Task {
  std::function<void(int64_t)> fn;
  int64_t total = 0;
  std::atomic<int64_t> next{0}, done{0};
};

class Pool {
 public:
  static Pool& instance() {
    static Pool pool;
    return pool;
  }

  // Run fn(i) for i in [0, n) across the pool; blocks until done.
  void parallel_for(int64_t n, std::function<void(int64_t)> fn) {
    if (n <= 0) return;
    if (n == 1) {
      fn(0);
      return;
    }
    auto task = std::make_shared<Task>();
    task->fn = std::move(fn);
    task->total = n;
    {
      std::lock_guard<std::mutex> lk(m_);
      current_ = task;
      epoch_++;
    }
    cv_.notify_all();
    run(*task);  // the caller participates too — no idle producer
    std::unique_lock<std::mutex> lk(m_);
    finished_cv_.wait(lk, [&] { return task->done.load() >= task->total; });
    // Another caller (prefetch worker vs. eval path) may have published its
    // own task meanwhile — only clear the slot if it is still ours, or its
    // batch would silently run single-threaded.
    if (current_ == task) current_.reset();
  }

 private:
  Pool() {
    int n = static_cast<int>(std::thread::hardware_concurrency());
    n_threads_ = n > 2 ? n - 1 : 1;  // leave a core for the dispatcher
    for (int t = 0; t < n_threads_; ++t) {
      threads_.emplace_back([this] { worker_loop(); });
    }
  }
  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(m_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  void worker_loop() {
    uint64_t seen = 0;
    for (;;) {
      std::shared_ptr<Task> task;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait(lk, [&] { return stop_ || epoch_ != seen; });
        if (stop_) return;
        seen = epoch_;
        task = current_;  // pin: stays alive even after the call returns
      }
      if (task) run(*task);
    }
  }

  void run(Task& task) {
    for (;;) {
      int64_t i = task.next.fetch_add(1, std::memory_order_relaxed);
      if (i >= task.total) break;
      task.fn(i);
      if (task.done.fetch_add(1, std::memory_order_acq_rel) + 1 >=
          task.total) {
        // Lock around the notify: the dispatcher re-checks its predicate
        // under m_, so holding m_ here means it is either already blocked
        // (and receives this notify) or will observe done==total on its
        // first predicate check — no lost-wakeup window.
        std::lock_guard<std::mutex> lk(m_);
        finished_cv_.notify_all();
      }
    }
  }

  std::vector<std::thread> threads_;
  int n_threads_;
  std::mutex m_;
  std::condition_variable cv_, finished_cv_;
  std::shared_ptr<Task> current_;
  uint64_t epoch_ = 0;
  bool stop_ = false;
};

// ------------------------------------------------------------------ rng

// splitmix64: tiny, high-quality, seedable per (seed, stream) — matches the
// Python side's contract that batches are pure functions of (seed, step).
inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

inline float uniform01(uint64_t& s) {
  return (splitmix64(s) >> 40) * (1.0f / 16777216.0f);  // 24-bit mantissa
}

}  // namespace

extern "C" {

// Threaded row gather: dst[i] = src[idx[i]] (row = row_elems floats).
// The mmap'd-shard read path — page faults happen here, in parallel.
void frl_gather_rows(const float* src, const int64_t* idx, float* dst,
                     int64_t n, int64_t row_elems) {
  Pool::instance().parallel_for(n, [&](int64_t i) {
    std::memcpy(dst + i * row_elems, src + idx[i] * row_elems,
                sizeof(float) * row_elems);
  });
}

// uint8 variant with on-the-fly f32 conversion and 1/255 scaling — uint8
// is the natural 4x-smaller storage for pre-decoded image shards.
void frl_gather_rows_u8(const uint8_t* src, const int64_t* idx, float* dst,
                        int64_t n, int64_t row_elems) {
  Pool::instance().parallel_for(n, [&](int64_t i) {
    const uint8_t* s = src + idx[i] * row_elems;
    float* d = dst + i * row_elems;
    for (int64_t e = 0; e < row_elems; ++e) {
      d[e] = s[e] * (1.0f / 255.0f);
    }
  });
}

// Windowed gather from a flat token stream (the LM corpus path): each
// output row is window tokens starting at starts[i], widened to int32.
// Arbitrary (unaligned) starts — this is the piece plain row-gather can't
// express; the per-window copy is where the token-bin mmap page faults
// happen, across the pool.
void frl_gather_windows_u16(const uint16_t* src, const int64_t* starts,
                            int32_t* dst, int64_t n, int64_t window) {
  Pool::instance().parallel_for(n, [&](int64_t i) {
    const uint16_t* s = src + starts[i];
    int32_t* d = dst + i * window;
    for (int64_t e = 0; e < window; ++e) d[e] = (int32_t)s[e];
  });
}

void frl_gather_windows_u32(const uint32_t* src, const int64_t* starts,
                            int32_t* dst, int64_t n, int64_t window) {
  Pool::instance().parallel_for(n, [&](int64_t i) {
    const uint32_t* s = src + starts[i];
    int32_t* d = dst + i * window;
    for (int64_t e = 0; e < window; ++e) d[e] = (int32_t)s[e];
  });
}

// Train-time augmentation on NHWC float32: per-sample random crop from
// (h, w) to (crop, crop) + horizontal flip (p=0.5) + per-channel
// normalize. Eval: center crop, no flip. One pass over the bytes.
void frl_augment_batch(const float* in, float* out, int64_t n, int64_t h,
                       int64_t w, int64_t c, int64_t crop, uint64_t seed,
                       int train, const float* mean, const float* stddev) {
  Pool::instance().parallel_for(n, [&](int64_t i) {
    uint64_t s = seed ^ (0x243f6a8885a308d3ULL * (uint64_t)(i + 1));
    int64_t max_y = h - crop, max_x = w - crop;
    int64_t y0, x0;
    bool flip;
    if (train) {
      y0 = max_y > 0 ? (int64_t)(uniform01(s) * (max_y + 1)) : 0;
      x0 = max_x > 0 ? (int64_t)(uniform01(s) * (max_x + 1)) : 0;
      if (y0 > max_y) y0 = max_y;
      if (x0 > max_x) x0 = max_x;
      flip = uniform01(s) < 0.5f;
    } else {
      y0 = max_y / 2;
      x0 = max_x / 2;
      flip = false;
    }
    const float* src = in + i * h * w * c;
    float* dst = out + i * crop * crop * c;
    for (int64_t y = 0; y < crop; ++y) {
      const float* row = src + ((y0 + y) * w + x0) * c;
      float* orow = dst + y * crop * c;
      for (int64_t x = 0; x < crop; ++x) {
        int64_t sx = flip ? (crop - 1 - x) : x;
        const float* px = row + sx * c;
        float* opx = orow + x * c;
        for (int64_t ch = 0; ch < c; ++ch) {
          opx[ch] = (px[ch] - mean[ch]) / stddev[ch];
        }
      }
    }
  });
}

int frl_version() { return 3; }

}  // extern "C"
