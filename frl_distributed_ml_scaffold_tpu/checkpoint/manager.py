"""Orbax-backed checkpoint manager (SURVEY C13, call stack (c)).

Crash consistency (ISSUE 9): a checkpoint only COUNTS once its commit
marker exists. ``save`` writes the Orbax step dir, waits for the bytes
(async saves commit at the next ``save``/``wait``/``close`` — the point
where ``wait_until_finished`` proves them complete), then atomically
publishes ``commits/step_<N>``. ``latest_step``/``all_steps`` judge only
committed steps, so a write torn by a crash/preemption mid-serialization
(step dir present, marker absent) is skipped instead of restored; and
``restore_or_init`` additionally survives bit-rot a marker cannot see —
a committed step that fails to restore is REPORTED (``corrupt_steps``,
directory left in place for inspection, never deleted) and the restore
falls back down the committed chain to the last good step. Directories
written before the marker protocol (no ``commits/`` dir at all) are
honored wholesale — the first new-protocol save backfills their markers
(staged + one atomic rename) so they STAY committed once ``commits/``
exists — and the exception-driven fallback is their safety net.
"""

from __future__ import annotations

import os
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import CheckpointConfig
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger


class Checkpointer:
    """Async sharded save + resharding restore for a TrainState.

    ``restore_or_init(trainer)`` is the one entry the Trainer and the elastic
    supervisor both use: if a committed checkpoint exists it restores
    **into the trainer's current shardings** (which may correspond to a
    different topology than the writer's — Orbax reshards from the abstract
    target pytree), falling back down the committed chain past torn or
    corrupt steps; otherwise it initializes fresh.
    """

    def __init__(self, directory: str, cfg: CheckpointConfig):
        self.directory = directory
        self.cfg = cfg
        self.logger = get_logger()
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.max_to_keep,
                enable_async_checkpointing=cfg.async_save,
            ),
        )
        self._commits_dir = os.path.join(directory, "commits")
        # Steps already on disk in a directory the marker protocol has
        # never touched: pre-protocol checkpoints, honored wholesale.
        # Captured NOW so the first _commit can backfill their markers —
        # without this, the first new-protocol save would flip every
        # legacy step to "uncommitted" (and a torn write from THIS
        # process, which happens after construction, stays unmarked).
        self._legacy_steps: set[int] = (
            set()
            if os.path.isdir(self._commits_dir)
            else {int(s) for s in self._mngr.all_steps()}
        )
        # Steps whose Orbax save was issued but not yet proven complete
        # (async): committed at the next save/wait/close.
        self._uncommitted: list[int] = []
        #: Committed steps that failed to restore (bit rot, truncated
        #: arrays): reported by restore_or_init, left on disk.
        self.corrupt_steps: list[int] = []
        #: The last redistribution plan a ``via_redistribution`` restore
        #: executed (ISSUE 15) — its bytes_moved / peak_scratch_bytes
        #: are the migration's cost record (None until one runs).
        self.last_restore_plan = None

    # ------------------------------------------------------ commit markers

    def _marker(self, step: int) -> str:
        return os.path.join(self._commits_dir, f"step_{int(step)}")

    def _commit(self, step: int) -> None:
        """Atomically publish the marker (write-tmp + rename: a reader
        either sees a complete marker or none). Only the primary process
        writes — the marker's absence must mean "torn", never "written
        by a rank that died first"."""
        if jax.process_index() != 0:
            return
        if not os.path.isdir(self._commits_dir):
            # First marker this directory has ever seen: backfill the
            # pre-protocol steps (committed wholesale until now — they
            # must STAY committed once commits/ exists) in a staged dir
            # published with one atomic rename, so a crash anywhere in
            # the transition leaves either no commits/ (legacy semantics
            # intact) or a complete one — never an empty commits/ that
            # orphans every existing checkpoint.
            stage = self._commits_dir + f".tmp.{os.getpid()}"
            shutil.rmtree(stage, ignore_errors=True)
            os.makedirs(stage)
            for s in self._legacy_steps | {int(step)}:
                with open(os.path.join(stage, f"step_{int(s)}"), "w") as fh:
                    fh.write(f"{int(s)}\n")
            os.rename(stage, self._commits_dir)
            return
        tmp = self._marker(step) + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(f"{int(step)}\n")
        os.replace(tmp, self._marker(step))

    def _commit_pending(self) -> None:
        """Publish markers for async saves now proven complete. The
        caller has just returned from ``wait_until_finished`` — that is
        the proof."""
        for step in self._uncommitted:
            self._commit(step)
        self._uncommitted.clear()

    def _has_commits_dir(self) -> bool:
        return os.path.isdir(self._commits_dir)

    def is_committed(self, step: int) -> bool:
        """Committed = marker present; pre-marker-protocol directories
        (no ``commits/`` dir ever created) count wholesale."""
        if not self._has_commits_dir():
            return True
        return os.path.exists(self._marker(step))

    def uncommitted_steps(self) -> list[int]:
        """On-disk Orbax steps with no commit marker — torn writes (or a
        save still in flight). Reported, never auto-deleted: operators
        decide what a torn checkpoint's remains are worth."""
        if not self._has_commits_dir():
            return []
        return [
            s for s in sorted(self._mngr.all_steps())
            if not os.path.exists(self._marker(s))
        ]

    # --------------------------------------------------------------- save

    def save(self, step: int, state: TrainState, *, force: bool = False) -> bool:
        if self._uncommitted:
            # Previous async saves: wait (Orbax serializes async saves
            # anyway, so this wait is ~free by the time the next save is
            # due) and publish their markers before starting new work.
            self._mngr.wait_until_finished()
            self._commit_pending()
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            if faults.fire("checkpoint.torn_write") is not None:
                # Injected torn write: the step dir stays visible but a
                # payload file is truncated and NO marker is published —
                # exactly what a crash mid-serialization leaves behind.
                self._mngr.wait_until_finished()
                self._tear(step)
                self.logger.warning(
                    "fault injection: torn checkpoint write at step %d "
                    "(file truncated, commit marker withheld)", step
                )
                return saved
            if self.cfg.async_save:
                self._uncommitted.append(step)
            else:
                self._commit(step)
            self.logger.info(
                "checkpoint saved at step %d -> %s", step, self.directory
            )
        return saved

    def _tear(self, step: int) -> None:
        """Truncate the largest payload file under the step dir (the
        injection shape of a mid-write crash)."""
        step_dir = os.path.join(self.directory, str(int(step)))
        victim, size = None, 0
        for root, _, files in os.walk(step_dir):
            for name in files:
                p = os.path.join(root, name)
                try:
                    sz = os.path.getsize(p)
                except OSError:
                    continue
                if sz > size:
                    victim, size = p, sz
        if victim is not None:
            with open(victim, "r+b") as fh:
                fh.truncate(size // 2)

    # ------------------------------------------------------------ queries

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self, *, include_uncommitted: bool = False) -> list[int]:
        steps = sorted(self._mngr.all_steps())
        if include_uncommitted or not self._has_commits_dir():
            return steps
        return [s for s in steps if os.path.exists(self._marker(s))]

    # ---------------------------------------------------------- restores

    def restore_params_only(
        self, state_shapes: Any, state_shardings: Any, step: int
    ):
        """Restore just the params subtree (``ocp.PLACEHOLDER`` skips the
        optimizer moments/extras on disk — ~1/3 the I/O of a full-state
        restore). Explicit per-leaf restore args carry the CALLER's
        shardings, so this reshards across topologies like ``restore``
        (PyTreeRestore would otherwise read the writer's sharding file,
        which is invalid on a different device set). Returns params.

        Compat: ``ocp.PLACEHOLDER`` only exists on newer orbax releases;
        older ones (e.g. the 0.7.x in this container) fall back to a full
        restore and take the params subtree — identical result, just
        without the skipped-moments I/O saving."""
        if not hasattr(ocp, "PLACEHOLDER"):
            return self.restore(state_shapes, state_shardings, step).params
        abstract = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state_shapes,
            state_shardings,
        )
        target = abstract.replace(
            opt_state=jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.opt_state),
            extras=jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.extras),
            ema_params=(
                jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.ema_params)
                if abstract.ema_params is not None
                else None
            ),
        )

        def _restore_arg(x):
            if x is ocp.PLACEHOLDER:
                return ocp.RestoreArgs()
            return ocp.ArrayRestoreArgs(sharding=x.sharding, dtype=x.dtype)

        restore_args = jax.tree.map(
            _restore_arg, target, is_leaf=lambda x: x is ocp.PLACEHOLDER
        )
        # A dedicated read-only manager: orbax binds one handler type per
        # item name per manager, and the main one serves StandardSave/
        # StandardRestore for the training path.
        reader = ocp.CheckpointManager(self.directory)
        try:
            restored = reader.restore(
                step,
                args=ocp.args.PyTreeRestore(
                    item=target, restore_args=restore_args
                ),
            )
        finally:
            reader.close()
        return restored.params

    def restore(
        self,
        state_shapes: Any,
        state_shardings: Any,
        step: int | None = None,
        *,
        via_redistribution: bool = False,
    ):
        """Restore into the given shardings (resharding as needed).

        ``via_redistribution`` (ISSUE 15, the elastic-restore seam):
        instead of asking Orbax for the target layout directly, restore
        each leaf at the memory-efficient EVEN layout
        (``redistribute.restore_layout_spec`` — the target spec with
        every unused mesh axis overlaid, so each device reads ~1/N of
        the leaf and no replicated copy is ever staged, even for leaves
        whose target IS replication), then run the redistribution plan
        executor on-device (donated-in-place, pure atom-drop collective
        programs by construction) to the target shardings. Bit-identical
        to the direct path; the executed plan is recorded on
        ``last_restore_plan`` for cost attribution."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if via_redistribution and jax.process_count() > 1:
            # The executor's scope is single-controller today (its
            # chunked fallback needs every shard addressable, and the
            # multi-controller collective path is unproven on this
            # backend — see docs/operations.md "State redistribution");
            # a multi-process restore takes the direct Orbax read
            # rather than risking a cross-process wedge mid-reform.
            self.logger.warning(
                "restore_redistribute requested under %d processes: "
                "falling back to the direct Orbax resharding read "
                "(the redistribution executor is single-controller)",
                jax.process_count(),
            )
            via_redistribution = False
        if not via_redistribution:
            abstract = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                state_shapes,
                state_shardings,
            )
            restored = self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
            self.logger.info(
                "restored checkpoint step %d from %s", step, self.directory
            )
            return restored
        from jax.sharding import NamedSharding

        from frl_distributed_ml_scaffold_tpu.redistribute import (
            compile_tree_plan,
            execute,
            restore_layout_spec,
        )

        even = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=NamedSharding(
                    sh.mesh, restore_layout_spec(s.shape, sh.spec, sh.mesh)
                ),
            ),
            state_shapes,
            state_shardings,
        )
        restored = self._mngr.restore(step, args=ocp.args.StandardRestore(even))
        scratch = (
            int(self.cfg.redistribute_scratch_mb * 1024 * 1024)
            if getattr(self.cfg, "redistribute_scratch_mb", 0)
            else None
        )
        plan = compile_tree_plan(
            restored, state_shardings, scratch_limit_bytes=scratch
        )
        restored = execute(plan, restored, donate=True)
        self.last_restore_plan = plan
        self.logger.info(
            "restored checkpoint step %d from %s via redistribution "
            "(%d leaves, %d bytes moved, lower bound %d, peak scratch %d)",
            step, self.directory, len(plan.leaves), plan.bytes_moved,
            plan.bytes_lower_bound, plan.peak_scratch_bytes,
        )
        return restored

    def _restore_bridging_ema(
        self, shapes: Any, shardings: Any, step: int,
        *, via_redistribution: bool = False,
    ) -> TrainState:
        """One step's restore, bridging an ema_decay toggle across the
        resume (the checkpoint has/lacks the ema_params subtree relative
        to the new run's target) — a corrupt step raises out of BOTH
        attempts and the caller falls back down the chain."""
        via = via_redistribution
        try:
            return self.restore(shapes, shardings, step, via_redistribution=via)
        except Exception:
            if shapes.ema_params is not None:
                # New run wants EMA, checkpoint predates it: restore without
                # the EMA subtree and seed it from the restored params.
                state = self.restore(
                    shapes.replace(ema_params=None),
                    shardings.replace(ema_params=None),
                    step,
                    via_redistribution=via,
                )
                self.logger.warning(
                    "checkpoint step %d has no ema_params (ema_decay was "
                    "enabled after it was written): seeding EMA from the "
                    "restored params", step,
                )
                # Real copies, not aliases: the train step donates the whole
                # state, and XLA rejects the same buffer donated twice.
                import jax.numpy as jnp

                return state.replace(
                    ema_params=jax.tree.map(jnp.copy, state.params)
                )
            # New run dropped EMA, checkpoint has it: restore it alongside
            # (same shapes/shardings as params) and discard.
            state = self.restore(
                shapes.replace(ema_params=shapes.params),
                shardings.replace(ema_params=shardings.params),
                step,
                via_redistribution=via,
            )
            self.logger.warning(
                "checkpoint step %d carries ema_params but ema_decay=0 now: "
                "discarding the EMA tree", step,
            )
            return state.replace(ema_params=None)

    def restore_or_init(self, trainer) -> TrainState:
        torn = self.uncommitted_steps()
        if torn:
            self.logger.warning(
                "checkpoint dir %s holds uncommitted step(s) %s (torn "
                "write or crash mid-save): skipping them; directories "
                "left in place for inspection", self.directory, torn,
            )
        steps = self.all_steps()
        shapes, shardings = trainer.state_shapes, trainer.state_shardings
        # ISSUE 15: a reformed (different-topology) mesh restores through
        # the redistribution service — even-layout read + on-device plan
        # execution — instead of Orbax's direct target-layout read. The
        # committed-chain fallback below is unchanged: a torn/corrupt
        # step fails out of either path identically.
        via = bool(getattr(self.cfg, "restore_redistribute", False))
        for step in reversed(steps):
            try:
                return self._restore_bridging_ema(
                    shapes, shardings, step, via_redistribution=via
                )
            except Exception as e:
                # Bit rot / truncation a commit marker cannot see: report
                # it, keep the directory for inspection, fall back to the
                # previous committed step.
                self.corrupt_steps.append(step)
                self.logger.error(
                    "checkpoint step %d is committed but unreadable "
                    "(%s: %s); falling back to the previous committed "
                    "step — directory left in place for inspection",
                    step, type(e).__name__, e,
                )
        if steps:
            self.logger.error(
                "no committed checkpoint under %s was restorable "
                "(%d tried); initializing fresh", self.directory, len(steps),
            )
        return trainer.init_state()

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        self._commit_pending()

    def close(self) -> None:
        try:
            self._mngr.wait_until_finished()
            self._commit_pending()
        finally:
            self._mngr.close()
