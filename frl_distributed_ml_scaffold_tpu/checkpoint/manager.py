"""Orbax-backed checkpoint manager (SURVEY C13, call stack (c))."""

from __future__ import annotations

from typing import Any

import jax
import orbax.checkpoint as ocp

from frl_distributed_ml_scaffold_tpu.config.schema import CheckpointConfig
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger


class Checkpointer:
    """Async sharded save + resharding restore for a TrainState.

    ``restore_or_init(trainer)`` is the one entry the Trainer and the elastic
    supervisor both use: if a checkpoint exists it restores **into the
    trainer's current shardings** (which may correspond to a different
    topology than the writer's — Orbax reshards from the abstract target
    pytree); otherwise it initializes fresh.
    """

    def __init__(self, directory: str, cfg: CheckpointConfig):
        self.directory = directory
        self.cfg = cfg
        self.logger = get_logger()
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.max_to_keep,
                enable_async_checkpointing=cfg.async_save,
            ),
        )

    def save(self, step: int, state: TrainState, *, force: bool = False) -> bool:
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            self.logger.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, state_shapes: Any, state_shardings: Any, step: int | None = None):
        """Restore into the given shardings (resharding as needed)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state_shapes,
            state_shardings,
        )
        restored = self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        self.logger.info("restored checkpoint step %d from %s", step, self.directory)
        return restored

    def restore_or_init(self, trainer) -> TrainState:
        step = self.latest_step()
        if step is not None:
            return self.restore(trainer.state_shapes, trainer.state_shardings, step)
        return trainer.init_state()

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
