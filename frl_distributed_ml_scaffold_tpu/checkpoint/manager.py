"""Orbax-backed checkpoint manager (SURVEY C13, call stack (c))."""

from __future__ import annotations

from typing import Any

import jax
import orbax.checkpoint as ocp

from frl_distributed_ml_scaffold_tpu.config.schema import CheckpointConfig
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger


class Checkpointer:
    """Async sharded save + resharding restore for a TrainState.

    ``restore_or_init(trainer)`` is the one entry the Trainer and the elastic
    supervisor both use: if a checkpoint exists it restores **into the
    trainer's current shardings** (which may correspond to a different
    topology than the writer's — Orbax reshards from the abstract target
    pytree); otherwise it initializes fresh.
    """

    def __init__(self, directory: str, cfg: CheckpointConfig):
        self.directory = directory
        self.cfg = cfg
        self.logger = get_logger()
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.max_to_keep,
                enable_async_checkpointing=cfg.async_save,
            ),
        )

    def save(self, step: int, state: TrainState, *, force: bool = False) -> bool:
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            self.logger.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def restore_params_only(
        self, state_shapes: Any, state_shardings: Any, step: int
    ):
        """Restore just the params subtree (``ocp.PLACEHOLDER`` skips the
        optimizer moments/extras on disk — ~1/3 the I/O of a full-state
        restore). Explicit per-leaf restore args carry the CALLER's
        shardings, so this reshards across topologies like ``restore``
        (PyTreeRestore would otherwise read the writer's sharding file,
        which is invalid on a different device set). Returns params.

        Compat: ``ocp.PLACEHOLDER`` only exists on newer orbax releases;
        older ones (e.g. the 0.7.x in this container) fall back to a full
        restore and take the params subtree — identical result, just
        without the skipped-moments I/O saving."""
        if not hasattr(ocp, "PLACEHOLDER"):
            return self.restore(state_shapes, state_shardings, step).params
        abstract = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state_shapes,
            state_shardings,
        )
        target = abstract.replace(
            opt_state=jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.opt_state),
            extras=jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.extras),
            ema_params=(
                jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.ema_params)
                if abstract.ema_params is not None
                else None
            ),
        )

        def _restore_arg(x):
            if x is ocp.PLACEHOLDER:
                return ocp.RestoreArgs()
            return ocp.ArrayRestoreArgs(sharding=x.sharding, dtype=x.dtype)

        restore_args = jax.tree.map(
            _restore_arg, target, is_leaf=lambda x: x is ocp.PLACEHOLDER
        )
        # A dedicated read-only manager: orbax binds one handler type per
        # item name per manager, and the main one serves StandardSave/
        # StandardRestore for the training path.
        reader = ocp.CheckpointManager(self.directory)
        try:
            restored = reader.restore(
                step,
                args=ocp.args.PyTreeRestore(
                    item=target, restore_args=restore_args
                ),
            )
        finally:
            reader.close()
        return restored.params

    def restore(self, state_shapes: Any, state_shardings: Any, step: int | None = None):
        """Restore into the given shardings (resharding as needed)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state_shapes,
            state_shardings,
        )
        restored = self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        self.logger.info("restored checkpoint step %d from %s", step, self.directory)
        return restored

    def restore_or_init(self, trainer) -> TrainState:
        step = self.latest_step()
        if step is None:
            return trainer.init_state()
        shapes, shardings = trainer.state_shapes, trainer.state_shardings
        try:
            return self.restore(shapes, shardings, step)
        except Exception:
            # Structure mismatch happens when trainer.ema_decay was toggled
            # across the resume: the checkpoint on disk has (or lacks) the
            # ema_params subtree relative to the new run's target. Bridge
            # both directions rather than aborting the resume.
            if shapes.ema_params is not None:
                # New run wants EMA, checkpoint predates it: restore without
                # the EMA subtree and seed it from the restored params.
                state = self.restore(
                    shapes.replace(ema_params=None),
                    shardings.replace(ema_params=None),
                    step,
                )
                self.logger.warning(
                    "checkpoint step %d has no ema_params (ema_decay was "
                    "enabled after it was written): seeding EMA from the "
                    "restored params", step,
                )
                # Real copies, not aliases: the train step donates the whole
                # state, and XLA rejects the same buffer donated twice.
                import jax.numpy as jnp

                return state.replace(
                    ema_params=jax.tree.map(jnp.copy, state.params)
                )
            # New run dropped EMA, checkpoint has it: restore it alongside
            # (same shapes/shardings as params) and discard.
            state = self.restore(
                shapes.replace(ema_params=shapes.params),
                shardings.replace(ema_params=shardings.params),
                step,
            )
            self.logger.warning(
                "checkpoint step %d carries ema_params but ema_decay=0 now: "
                "discarding the EMA tree", step,
            )
            return state.replace(ema_params=None)

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
