"""Sharded checkpointing (SURVEY C13): Orbax save/restore with resharding.

Reference behavior: rank-coordinated sharded state-dict files + metadata,
reload + reshard on resume. TPU-native: Orbax ``CheckpointManager`` — async
save off the training thread, restore driven by an *abstract* state pytree
carrying NamedShardings, so a checkpoint written on one topology restores
onto another (the elastic-resume path, SURVEY C14).
"""

from frl_distributed_ml_scaffold_tpu.checkpoint.manager import Checkpointer
