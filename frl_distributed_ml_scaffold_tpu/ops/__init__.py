"""TPU ops layer (SURVEY C8 + §5 long-context; Pallas kernels).

Manual-parallelism attention implementations that GSPMD cannot derive from
sharding annotations alone:

- ``ring_attention`` — KV shards rotate around the ``seq`` mesh axis via
  ``ppermute``; each hop runs the fused flash kernel with logsumexp
  merging, and a custom VJP re-rotates KV in the backward (ring/blockwise
  attention; PAPERS.md collective-redistribution lineage).
- ``ulysses_attention`` — DeepSpeed-Ulysses-style ``all_to_all`` reshard
  (seq-sharded ↔ head-sharded) around flash attention on the local
  full-length sequence.
- ``flash_attention`` — the fused Pallas TPU kernel (online-softmax fwd +
  two-kernel custom-VJP bwd); the framework's hand-written "native" tier
  and the building block of both sharded modes above. Under a
  sequence-sharded mesh it delegates to ``ring_attention``.
- ``dense_attention`` — the single-device reference all sharded paths
  reduce to; fp32 softmax, bf16-multiply/fp32-accumulate einsums.
- ``decode_attention`` — the serving-side fused split-KV single-token
  decode kernel over the KV cache (length-masked to the occupied prefix,
  head-sharded over the ``model`` axis under a mesh) with
  ``dense_decode_attention`` as its identical-numerics reference; both
  accept a quantized cache (1-byte K/V + per-position-per-head scales)
  and dequantize per chunk.

All are drop-in (B, T, H, D)-shaped attention functions used by the GPT
model's ``attention=`` config switch. ``quantize``/``dequantize``/
``quantized_matmul`` (ops/quantization.py) are the low-precision
substrate shared by the collective-matmul rings
(``parallel.low_precision``) and the quantized KV cache
(``model.kv_cache_quant``).
"""

from frl_distributed_ml_scaffold_tpu.ops.flash_attention import flash_attention
from frl_distributed_ml_scaffold_tpu.ops.fused_bn import (
    FusedBatchNorm,
    fused_bn_train,
)
from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention,
)
from frl_distributed_ml_scaffold_tpu.ops.ulysses import ulysses_attention
from frl_distributed_ml_scaffold_tpu.ops.decode_attention import (
    decode_attention,
    dense_decode_attention,
)
from frl_distributed_ml_scaffold_tpu.ops.quantization import (
    dequantize,
    quantize,
    quantized_matmul,
)
