"""Fused BatchNorm-backward Pallas TPU kernel — the priced RN50 HBM fix.

The v5e trace (docs/perf_playbook.md "Where the remaining RN50 gap lives")
pins ~150 ms of the 227 ms headline step in bandwidth-bound BN/ReLU-backward
fusions: the autodiff BN backward materializes dx̂ and the stat-gradient
intermediates, so each BN layer's activation is read and its gradient
written several times around the statistics reductions. This module replaces
ONLY the backward of train-mode BatchNorm with a two-kernel Pallas chain at
the exact-math HBM floor:

  1. **reduction pass** — one stream over (x, dy) producing the per-channel
     sums ``dβ = Σ dy`` and ``dγ = Σ dy·x̂``, with x̂ RECOMPUTED in-register
     from (x, μ, σ) rather than saved by the forward;
  2. **dx pass** — one stream over (x, dy) producing
     ``dx = (γ/σ)·(dy − dβ/M − x̂·dγ/M)`` directly, no dx̂ / no broadcasted
     stat-grad tensors ever touching HBM.

Total HBM traffic: x and dy read twice each, dx written once — the floor
for the exact (non-approximated) BN backward, since dx depends on full-batch
reductions of dy. The forward is byte-identical to ``flax.linen.BatchNorm``
(same fp32 fast-variance stats, same promote-then-cast normalize), swapped
in via ``jax.custom_vjp`` — so ``model.fused_bn=true`` changes backward
scheduling, never training math.

Sharding (the fused_adamw honesty-contract lesson, solved rather than
refused this time): a ``pallas_call`` is opaque to GSPMD, but BN backward is
**sync-BN** — the sums span the global batch. Under a mesh with a populated
batch axis the backward shard_maps over ``("data", "fsdp")``: each shard
runs the reduction kernel on its local rows, one ``lax.psum`` merges the
per-channel sums (the same collective autodiff's sync-BN backward needs),
and the dx kernel runs shard-local. Off-mesh the kernels run directly.

Non-TPU backends run the identical math as plain jnp (exact, fast) so CI
and sim meshes never touch Mosaic by default; the kernels themselves are
covered in interpreter mode (``interpret=True`` / ``FORCE_INTERPRET``),
mirroring the ``fused_adamw.py`` / ``flash_attention.py`` pattern.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_LANES = 128

#: Test hook: force the Pallas interpreter through call paths that do not
#: expose an ``interpret`` argument (the Trainer → ResNet → FusedBatchNorm
#: chain). None = route by backend (TPU: compiled kernel; else: jnp math).
FORCE_INTERPRET: bool | None = None


def _rows_per_block(c_pad: int) -> int:
    """Row-block size for a (rows, C) grid: ~1 MB of fp32 per operand block,
    power of two, sublane-aligned. At RN50's widest BN (C=2048) this is 128
    rows; at the stem (C=64 → padded 128) it is 1024."""
    target = 256 * 1024  # fp32 elements per operand block
    return int(max(8, min(1024, 2 ** int(np.log2(max(8, target // c_pad))))))


def _use_kernel(interpret: bool | None) -> tuple[bool, bool]:
    """(run_pallas, interpret_flag) — same routing contract as fused_adamw:
    TPU compiles the kernel, non-TPU defaults to the identical jnp math,
    and tests opt into the interpreter explicitly."""
    if interpret is None:
        interpret = FORCE_INTERPRET
    if interpret is None:
        on_tpu = jax.default_backend() == "tpu"
        return on_tpu, False
    return True, bool(interpret)


# ------------------------------------------------------------------ forward


def _bn_train_forward(x, scale, bias, eps, out_dtype):
    """Train-mode BN forward, mirroring flax ``_compute_stats`` (fp32
    fast-variance, clipped non-negative) + ``_normalize`` (promoted math,
    single final cast) op for op — the numerics the tests pin against
    ``nn.BatchNorm``. Returns (y, mean, var); stats are fp32 (C,)."""
    x32 = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = x32.mean(axes)
    mean2 = jnp.square(x32).mean(axes)
    var = jnp.maximum(0.0, mean2 - jnp.square(mean))
    y = x32 - mean
    mul = lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    y = y * mul + bias.astype(jnp.float32)
    return y.astype(out_dtype), mean, var


# ----------------------------------------------------------------- backward


def _fallback_bwd(x, dy, scale, mean, var, eps):
    """The backward formula as plain jnp — the identical-math non-TPU path
    (XLA fuses it fine at CI scale) and the reference the kernels mirror."""
    axes = tuple(range(x.ndim - 1))
    m = float(np.prod([x.shape[a] for a in axes]))
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    inv = lax.rsqrt(var + eps)
    xhat = (x32 - mean) * inv
    dbeta = dy32.sum(axes)
    dgamma = (dy32 * xhat).sum(axes)
    gi = scale.astype(jnp.float32) * inv
    dx = gi * (dy32 - dbeta * (1.0 / m) - xhat * (dgamma * (1.0 / m)))
    return dx.astype(x.dtype), dgamma, dbeta


def _sums_kernel(x_ref, dy_ref, mean_ref, inv_ref, db_ref, dg_ref,
                 acc_b, acc_g):
    """Pass 1: per-channel Σdy and Σdy·x̂ over the row grid. VMEM scratch
    accumulators persist across the sequential TPU grid; x̂ is recomputed
    from the resident (x, μ, 1/σ) tiles — it never exists in HBM."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_b[...] = jnp.zeros_like(acc_b)
        acc_g[...] = jnp.zeros_like(acc_g)

    dy32 = dy_ref[...].astype(jnp.float32)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    acc_b[...] += dy32.sum(axis=0, keepdims=True)
    acc_g[...] += (dy32 * xhat).sum(axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finish():
        db_ref[...] = acc_b[...]
        dg_ref[...] = acc_g[...]


def _dx_kernel(x_ref, dy_ref, mean_ref, inv_ref, gi_ref, k1_ref, k2_ref,
               dx_ref):
    """Pass 2: dx = (γ/σ)·(dy − dβ/M − x̂·dγ/M), one streamed read of
    (x, dy) and one write of dx. k1 = dβ/M, k2 = dγ/M precomputed (C,)."""
    dy32 = dy_ref[...].astype(jnp.float32)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    dx = gi_ref[...] * (dy32 - k1_ref[...] - xhat * k2_ref[...])
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _pad_2d(a2d, rows_pad, c_pad):
    r, c = a2d.shape
    return jnp.pad(a2d, ((0, rows_pad - r), (0, c_pad - c)))


def _vec(v, c_pad):
    return jnp.pad(v.astype(jnp.float32), (0, c_pad - v.shape[0])).reshape(1, -1)


def _kernel_sums(x2d, dy2d, mean, var, eps, interpret):
    """(Σdy, Σdy·x̂) over local rows via the pass-1 kernel. Row/channel
    padding is zero-filled on dy, so padded positions contribute nothing."""
    import jax.experimental.pallas as pl

    r, c = x2d.shape
    c_pad = max(_LANES, -(-c // _LANES) * _LANES)
    rb = _rows_per_block(c_pad)
    rows_pad = max(rb, -(-r // rb) * rb)
    blk = pl.BlockSpec((rb, c_pad), lambda i: (i, 0))
    vec = pl.BlockSpec((1, c_pad), lambda i: (0, 0))
    out = jax.ShapeDtypeStruct((1, c_pad), jnp.float32)
    from jax.experimental.pallas import tpu as pltpu

    inv = lax.rsqrt(var + eps)
    db, dg = pl.pallas_call(
        _sums_kernel,
        grid=(rows_pad // rb,),
        in_specs=[blk, blk, vec, vec],
        out_specs=[vec, vec],
        out_shape=[out, out],
        scratch_shapes=[
            pltpu.VMEM((1, c_pad), jnp.float32),
            pltpu.VMEM((1, c_pad), jnp.float32),
        ],
        interpret=interpret,
    )(
        _pad_2d(x2d, rows_pad, c_pad),
        _pad_2d(dy2d, rows_pad, c_pad),
        _vec(mean, c_pad),
        _vec(inv, c_pad),
    )
    return db[0, :c], dg[0, :c]


def _kernel_dx(x2d, dy2d, scale, mean, var, dgamma, dbeta, eps, m, interpret):
    """dx over local rows via the pass-2 kernel; ``m`` is the GLOBAL count."""
    import jax.experimental.pallas as pl

    r, c = x2d.shape
    c_pad = max(_LANES, -(-c // _LANES) * _LANES)
    rb = _rows_per_block(c_pad)
    rows_pad = max(rb, -(-r // rb) * rb)
    blk = pl.BlockSpec((rb, c_pad), lambda i: (i, 0))
    vec = pl.BlockSpec((1, c_pad), lambda i: (0, 0))
    inv = lax.rsqrt(var + eps)
    gi = scale.astype(jnp.float32) * inv
    dx = pl.pallas_call(
        _dx_kernel,
        grid=(rows_pad // rb,),
        in_specs=[blk, blk, vec, vec, vec, vec, vec],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((rows_pad, c_pad), x2d.dtype),
        interpret=interpret,
    )(
        _pad_2d(x2d, rows_pad, c_pad),
        _pad_2d(dy2d, rows_pad, c_pad),
        _vec(mean, c_pad),
        _vec(inv, c_pad),
        _vec(gi, c_pad),
        _vec(dbeta * (1.0 / m), c_pad),
        _vec(dgamma * (1.0 / m), c_pad),
    )
    return dx[:r, :c]


def _pallas_bwd_local(x, dy, mean, var, eps, interpret):
    """Pass-1 kernel on LOCAL rows; the caller psums the returned partial
    sums when sharded. NHWC→(rows, C) reshapes are free (row-major,
    feature axis last)."""
    c = x.shape[-1]
    x2d = x.reshape(-1, c)
    dy2d = dy.reshape(-1, c)
    dbeta, dgamma = _kernel_sums(x2d, dy2d, mean, var, eps, interpret)
    return x2d, dy2d, dgamma, dbeta


def _bn_bwd_dispatch(x, dy, scale, mean, var, eps, interpret):
    """Route the backward: jnp math off-TPU (unless interpret is forced),
    else the Pallas chain — shard_mapped over the batch axes when the
    ambient mesh shards the batch, with one psum merging the channel sums
    (sync-BN, matching the forward's global statistics)."""
    run_pallas, interp = _use_kernel(interpret)
    if not run_pallas:
        return _fallback_bwd(x, dy, scale, mean, var, eps)

    m_global = float(np.prod(x.shape[:-1]))

    def local(x_l, dy_l, scale_r, mean_r, var_r, *, axis_names):
        x2d, dy2d, dgamma, dbeta = _pallas_bwd_local(
            x_l, dy_l, mean_r, var_r, eps, interp
        )
        if axis_names:
            dgamma = lax.psum(dgamma, axis_names)
            dbeta = lax.psum(dbeta, axis_names)
        dx2d = _kernel_dx(
            x2d, dy2d, scale_r, mean_r, var_r, dgamma, dbeta, eps,
            m_global, interp,
        )
        return dx2d.reshape(x_l.shape), dgamma, dbeta

    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        BATCH_AXES,
        current_mesh_env,
        shard_map_compat,
    )

    env = current_mesh_env()
    if env is None or env.batch_axis_size <= 1:
        return local(x, dy, scale, mean, var, axis_names=())
    if x.shape[0] % env.batch_axis_size != 0:
        # shard_map needs exact divisibility; GSPMD-padded odd batches take
        # the identical-math jnp path rather than silently all-gathering
        # around an opaque kernel.
        return _fallback_bwd(x, dy, scale, mean, var, eps)
    from jax.sharding import PartitionSpec as P

    batch = P(BATCH_AXES, *([None] * (x.ndim - 1)))
    rep = P()
    return shard_map_compat(
        functools.partial(local, axis_names=BATCH_AXES),
        mesh=env.mesh,
        in_specs=(batch, batch, rep, rep, rep),
        out_specs=(batch, rep, rep),
    )(x, dy, scale, mean, var)


# ----------------------------------------------------------- custom-vjp tie


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _bn_train(eps, out_dtype, interpret, x, scale, bias):
    return _bn_train_forward(x, scale, bias, eps, out_dtype)


def _bn_train_fwd(eps, out_dtype, interpret, x, scale, bias):
    y, mean, var = _bn_train_forward(x, scale, bias, eps, out_dtype)
    # Residuals: x̂ is NOT saved — the backward recomputes it from
    # (x, mean, var), which is the whole HBM win.
    return (y, mean, var), (x, scale, bias, mean, var)


def _bn_train_bwd(eps, out_dtype, interpret, res, cts):
    x, scale, bias, mean, var = res
    dy, _, _ = cts
    # The mean/var outputs exist ONLY to feed the (non-differentiated)
    # running-average update; the module below stop_gradients them, so
    # their cotangents are structurally zero and the backward covers y
    # alone. This function is private to FusedBatchNorm for that reason.
    dx, dgamma, dbeta = _bn_bwd_dispatch(
        x, dy, scale, mean, var, eps, interpret
    )
    return dx.astype(x.dtype), dgamma.astype(scale.dtype), dbeta.astype(bias.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def fused_bn_train(x, scale, bias, *, eps=1e-5, out_dtype=None,
                   interpret: bool | None = None):
    """Train-mode BatchNorm with the fused Pallas backward.

    Returns ``(y, mean, var)``; ``mean``/``var`` are the fp32 batch stats
    for running-average updates and must not be differentiated through
    (wrap them in ``stop_gradient``, as ``FusedBatchNorm`` does). Forward
    numerics match ``nn.BatchNorm`` exactly; ``out_dtype=None`` applies the
    flax promotion rule (promote of x/scale/bias dtypes).
    """
    if out_dtype is None:
        out_dtype = jnp.promote_types(
            jnp.promote_types(x.dtype, scale.dtype), bias.dtype
        )
    return _bn_train(eps, jnp.dtype(out_dtype), interpret, x, scale, bias)


# ------------------------------------------------------------------ module


class FusedBatchNorm(nn.BatchNorm):
    """``nn.BatchNorm`` drop-in: identical params/variables/forward, the
    train-mode backward replaced by the fused kernel chain.

    Configurations outside the kernel's contract (non-trailing feature
    axis, pmap-style ``axis_name`` stats, masking, slow variance, disabled
    scale/bias) delegate wholesale to ``nn.BatchNorm`` — as does eval mode,
    whose running-stat normalize has no reduction chain to fuse.
    """

    interpret: bool | None = None

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None, *,
                 mask=None):
        use_running_average = nn.merge_param(
            "use_running_average",
            self.use_running_average,
            use_running_average,
        )
        fusable = (
            not use_running_average
            and mask is None
            and self.axis == -1
            and self.axis_name is None
            and self.axis_index_groups is None
            and self.use_fast_variance
            and self.force_float32_reductions
            and self.use_bias
            and self.use_scale
        )
        if not fusable:
            # merge_param refuses a value given both at construction and at
            # call time — forward the call-time value only when the
            # constructor left it unset.
            ura = None if self.use_running_average is not None else use_running_average
            return super().__call__(x, use_running_average=ura, mask=mask)

        feature_shape = (x.shape[-1],)
        # Same variable/param names and creation order as nn.BatchNorm —
        # checkpoints and partition rules see an identical tree.
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda s: jnp.zeros(s, jnp.float32), feature_shape,
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda s: jnp.ones(s, jnp.float32), feature_shape,
        )
        scale = self.param(
            "scale", self.scale_init, feature_shape, self.param_dtype
        )
        bias = self.param(
            "bias", self.bias_init, feature_shape, self.param_dtype
        )
        from flax.linen import dtypes as _dtypes

        out_dtype = _dtypes.canonicalize_dtype(x, scale, bias, dtype=self.dtype)
        y, mean, var = fused_bn_train(
            x, scale, bias, eps=self.epsilon, out_dtype=out_dtype,
            interpret=self.interpret,
        )
        if not self.is_initializing():
            mean = lax.stop_gradient(mean)
            var = lax.stop_gradient(var)
            ra_mean.value = (
                self.momentum * ra_mean.value + (1 - self.momentum) * mean
            )
            ra_var.value = (
                self.momentum * ra_var.value + (1 - self.momentum) * var
            )
        return y
